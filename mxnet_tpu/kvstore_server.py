"""Parameter-server process entry.

Reference: python/mxnet/kvstore_server.py — `_init_kvstore_server_module`
(:58-68) blocks server-role processes inside ``import mxnet``; the worker's
rank 0 sends a pickled optimizer which the server installs as its updater
(:36-44 command handler → pickle.loads → get_updater).

Here the transport lives in the native runtime (src/ps.cc). This module
hosts it in a Python process so the *real* optimizer (any Optimizer
subclass, custom LR schedules, pickled user classes) runs server-side, key
by key, on flat fp32 views — the reference's server also updates flattened
1-D NDArrays.
"""
from __future__ import annotations

import base64
import logging
import os
import pickle
import threading
import time

import numpy as np

# NOTE (import-lock invariant): in a server process the MAIN thread never
# leaves ``import mxnet_tpu`` (_init_kvstore_server_module serves inside
# it), so it holds the package's import lock for the process lifetime.
# Conn-handler / replication / checkpoint-writer / standby threads
# therefore must NEVER execute a package-relative import — it would block
# on that lock forever. Everything those threads need from the package is
# imported HERE, at module top, on the importing thread itself.
from . import fault, telemetry
from .analysis import witness
from ._native import COMMAND_FN, UPDATER_FN, get_lib
from .base import env_float, env_int
from .utils.atomic_file import atomic_write, read_verified

__all__ = ["KVStoreServer", "MembershipRegistry", "plan_server_groups",
           "_init_kvstore_server_module",
           "STATS_VEC_LEN", "encode_stats_vec", "decode_stats_vec",
           "encode_bytes_vec", "decode_bytes_vec"]


def plan_server_groups(num_servers, replicas):
    """Partition server ids into replicated groups of ``replicas + 1``.

    Group g serves key range g (``ikey % num_groups``); the first member is
    the boot-time primary, the rest are backups in deterministic failover
    order. ``MXNET_KV_REPLICAS=0`` (the default) degenerates to one
    singleton group per server — exactly the pre-HA ``ikey % num_servers``
    sharding, so the HA machinery stays strictly additive."""
    num_servers = int(num_servers)
    replicas = int(replicas)
    if replicas < 0:
        raise ValueError("MXNET_KV_REPLICAS must be >= 0, got %d" % replicas)
    width = replicas + 1
    if num_servers % width:
        raise ValueError(
            "MXNET_KV_REPLICAS=%d needs a server count divisible by %d, "
            "got %d server(s)" % (replicas, width, num_servers))
    return [list(range(g * width, (g + 1) * width))
            for g in range(num_servers // width)]

# Wire format of the vector a server publishes under a reserved key when a
# worker sends ``stats_to:<key>`` (kvstore.request_server_stats decodes it
# back into a dict). The transport ships float32, which stops representing
# consecutive integers past 2^24 (~16.7M updates — a few hours of real
# training), so each counter travels as two 24-bit words: exact to 2^48.
# Order is the wire contract — append fields, never reorder. The HA
# counters (_STATS_COUNTER_FIELDS_HA) were appended AFTER the original
# has_optimizer flag so the flag keeps its wire position: a pre-HA decoder
# reading its own prefix of the longer vector still parses correctly.
_STATS_COUNTER_FIELDS = ("updates_applied", "update_failures")
_STATS_COUNTER_FIELDS_HA = (
    "repl_forwards", "repl_acks", "repl_failures", "repl_lag_rounds",
    "ckpt_writes", "ckpt_restores", "ckpt_bytes")
STATS_VEC_LEN = (2 * len(_STATS_COUNTER_FIELDS) + 1  # + has_optimizer flag
                 + 2 * len(_STATS_COUNTER_FIELDS_HA))


def encode_stats_vec(stats):
    """Server side: stats dict -> float32 wire vector (lo24/hi words)."""
    vec = []
    for f in _STATS_COUNTER_FIELDS:
        v = int(stats[f])
        vec.append(float(v & 0xFFFFFF))
        vec.append(float(v >> 24))
    vec.append(1.0 if stats["has_optimizer"] else 0.0)
    for f in _STATS_COUNTER_FIELDS_HA:
        v = int(stats.get(f, 0))
        vec.append(float(v & 0xFFFFFF))
        vec.append(float(v >> 24))
    return np.array(vec, np.float32)


def decode_stats_vec(arr):
    """Worker side: float32 wire vector -> stats dict (inverse of encode)."""
    vals = [int(round(float(x))) for x in arr]
    out = {}
    for i, f in enumerate(_STATS_COUNTER_FIELDS):
        out[f] = vals[2 * i] | (vals[2 * i + 1] << 24)
    base = 2 * len(_STATS_COUNTER_FIELDS)
    out["has_optimizer"] = bool(vals[base])
    for i, f in enumerate(_STATS_COUNTER_FIELDS_HA):
        lo = base + 1 + 2 * i
        if lo + 1 >= len(vals):
            break  # vector from a pre-HA server: HA counters absent
        out[f] = vals[lo] | (vals[lo + 1] << 24)
    return out


def encode_bytes_vec(payload):
    """Arbitrary bytes -> float32 wire vector ``[len, b0, b1, ...]`` for the
    reserved-key publish channel (the membership table travels as JSON this
    way — float32 represents 0..255 and lengths to 2^24 exactly)."""
    vec = np.empty(len(payload) + 1, np.float32)
    vec[0] = len(payload)
    if payload:
        vec[1:] = np.frombuffer(payload, np.uint8)
    return vec


def decode_bytes_vec(arr):
    """Inverse of :func:`encode_bytes_vec`; tolerates a buffer longer than
    the encoded payload (pulls hand over a fixed-cap buffer)."""
    n = int(round(float(arr[0])))
    if n < 0 or n > len(arr) - 1:
        return None
    return bytes(np.asarray(np.round(arr[1:1 + n]), np.uint8))


class MembershipRegistry:
    """PS-coordinated cluster membership for elastic training — lives on
    server rank 0 (docs/distributed.md §elasticity).

    Workers register (``mb_join``), heartbeat (``mb_hb``), and read the
    table (``mb_get`` + reserved-key pull). The registry owns the
    monotonically increasing **membership epoch**: it bumps on every
    membership change after initial formation (heartbeat lapse, explicit
    leave, rejoin) and synchronously broadcasts ``mepoch:<epoch>:<workers>``
    to EVERY server before the new epoch becomes visible to workers — so by
    the time any worker adopts an epoch from the table, every server
    already rejects the previous one. Initial formation (the first
    ``num_workers`` joins) keeps epoch 0: a normal start must not churn.

    ``broadcast`` is injectable for tests; the default sends the command to
    each server on a deadline-bounded probe (a wedged sibling server costs
    one timeout, never wedges the registry).

    **Server membership** (server HA, docs/distributed.md §server-HA): the
    registry also tracks the PS tier itself. Servers heartbeat
    (``mb_srv_hb``); a lapse — or a worker's probe-confirmed
    ``mb_srv_dead`` hint — evicts the server, and if it was the primary of
    its replication group the first alive backup is promoted: the new
    key→server map (``smap``) is broadcast to every surviving server, then
    the membership epoch bumps so workers take the same
    reject→drain→adopt→continue path they take for worker loss. Server
    lapse monitoring arms itself on the FIRST server heartbeat, so
    registries in non-HA jobs (and unit tests) never see spurious server
    evictions. The registry itself fails over: it periodically replicates
    its own snapshot to the group-0 backups (``mb_sync``), and the first
    alive group-0 member resumes it when every predecessor is dead
    (deterministic failover order = group-0 member order)."""

    def __init__(self, num_workers, heartbeat_timeout_s=None,
                 broadcast=None, logger=None, num_servers=None,
                 replicas=None, probe=None, resume=None):
        # no function-level package imports: a registry failover constructs
        # this on the standby thread (see the import-lock note at the top)
        self._target = int(num_workers)
        self._timeout_s = (heartbeat_timeout_s if heartbeat_timeout_s
                           is not None
                           else env_float("MXNET_ELASTIC_HEARTBEAT_TIMEOUT_S",
                                          5.0))
        self._logger = logger or logging.getLogger(__name__)
        self._broadcast = (broadcast if broadcast is not None
                           else self._broadcast_to_servers)
        self._lock = threading.Lock()
        self._lock = witness.declare(
            "mxnet_tpu.kvstore_server.MembershipRegistry._lock", self._lock)
        self._alive = {}   # rank -> last-heartbeat monotonic time
        self._last_step = {}  # rank -> last training step it reported:
        # membership events name the step a reconfiguration landed at, so
        # a post-mortem can line the epoch bump up with the training
        # timeline (workers report it on joins/heartbeats)
        self._epoch = 0
        self._formed = False
        self._done = False
        self._pos = None   # restart position published by the coordinator
        self._bcast_clients = None  # lazy: sid -> (addr, client handle)
        # ---- server membership (guarded-by: _lock) ----------------------
        if num_servers is None:
            num_servers = int(os.environ.get("DMLC_NUM_SERVER", "1"))
        if replicas is None:
            replicas = env_int("MXNET_KV_REPLICAS", 0)
        self._groups = plan_server_groups(num_servers, replicas)
        now = time.monotonic()
        self._srv_alive = {s: now for s in range(int(num_servers))}
        self._smap = [g[0] for g in self._groups]  # group -> primary sid
        self._srv_monitoring = False  # armed by the first server heartbeat
        self._srv_probe = probe if probe is not None else self._probe_server
        self._sync_at = now  # next mb_sync replication of the registry state
        if resume:
            self._resume_from(resume)
        self._stop = threading.Event()
        self._monitor = threading.Thread(
            target=self._monitor_loop, daemon=True,
            name="mxnet-kv-membership-monitor")
        self._monitor.start()

    def _resume_from(self, snap):
        """Seed state from a predecessor's ``mb_sync`` snapshot (registry
        failover onto a group-0 backup). Heartbeat timestamps travel as
        ages so monotonic clocks never cross processes; the dead
        predecessor's stale age then lapses here within one timeout and
        the normal eviction path promotes this host's group."""
        now = time.monotonic()
        # registry failover re-runs this on a live object whose monitor
        # thread is already scanning these maps — seed under the lock
        with self._lock:
            self._epoch = int(snap.get("epoch", 0))
            self._formed = bool(snap.get("formed", False))
            self._done = bool(snap.get("done", False))
            self._pos = snap.get("pos")
            self._last_step = {int(r): int(s)
                               for r, s in (snap.get("steps") or {}).items()}
            self._alive = {int(r): now - float(age)
                           for r, age in (snap.get("workers") or {}).items()}
            srv = snap.get("servers")
            if srv is not None:
                self._srv_alive = {int(s): now - float(age)
                                   for s, age in srv.items()}
            if snap.get("smap"):
                self._smap = [int(s) if s is not None else None
                              for s in snap["smap"]]
            self._srv_monitoring = bool(snap.get("srv_monitoring", False))

    def snapshot(self):
        """JSON-able full state for ``mb_sync`` standby replication
        (inverse of :meth:`_resume_from`)."""
        with self._lock:
            now = time.monotonic()
            return {
                "epoch": self._epoch,
                "formed": self._formed,
                "done": self._done,
                "pos": self._pos,
                "steps": {str(r): s for r, s in self._last_step.items()},
                "workers": {str(r): now - t for r, t in self._alive.items()},
                "servers": {str(s): now - t
                            for s, t in self._srv_alive.items()},
                "smap": list(self._smap),
                "srv_monitoring": self._srv_monitoring,
            }

    # ---- worker-facing transitions (conn handler threads) ---------------
    def join(self, rank, step=None):
        """Register ``rank``; counts as its first heartbeat. Bumps the
        epoch whenever the cluster was already formed — including a rank
        that is still listed as alive: a rejoin of a known rank means its
        previous incarnation died (possibly faster than the heartbeat
        lapse could notice), and any round it half-pushed must be flushed
        before the replacement's traffic lands."""
        rank = int(rank)
        with self._lock:
            self._alive[rank] = time.monotonic()
            if step is not None:
                self._last_step[rank] = int(step)
            if not self._formed:
                if len(self._alive) >= self._target:
                    self._formed = True
                    self._logger.info(
                        "membership: formed with workers %s (epoch %d)",
                        sorted(self._alive), self._epoch)
                return self._epoch
            telemetry.event("worker_joined", rank=rank,
                            epoch=self._epoch + 1,
                            last_step=self._last_step.get(rank))
            self._bump_locked("worker %d joined" % rank)
            return self._epoch

    def heartbeat(self, rank, step=None):
        with self._lock:
            # only known members refresh: a heartbeat racing the lapse that
            # evicted its sender must not resurrect it without a join (the
            # eviction already reconfigured the cluster past it)
            if int(rank) in self._alive:
                self._alive[int(rank)] = time.monotonic()
                if step is not None:
                    self._last_step[int(rank)] = int(step)

    def leave(self, rank):
        """Graceful mid-training departure: same reconfiguration as a
        lapse, minus the detection latency."""
        with self._lock:
            if int(rank) in self._alive:
                del self._alive[int(rank)]
                if self._formed:
                    telemetry.event("worker_lost", rank=int(rank),
                                    reason="leave", epoch=self._epoch + 1,
                                    last_step=self._last_step.get(int(rank)))
                    self._bump_locked("worker %s left" % rank)

    def done(self, rank):
        """Training reached its end on ``rank``: removed WITHOUT an epoch
        bump (every worker finishes the same boundary; reconfiguring here
        would churn the shutdown), and the table's ``done`` flag tells any
        late-relaunched worker to exit instead of waiting to join. Lapse
        monitoring continues for the ranks that have NOT reported done —
        a worker killed between a peer's completion and its own must still
        bump the epoch, or the peer's trailing barrier would wait on it
        forever."""
        with self._lock:
            self._alive.pop(int(rank), None)
            self._done = True

    def set_pos(self, payload):
        """Record the restart position the reconfiguration coordinator
        publishes (training epoch, nbatch, iterator state, mepoch) — the
        joiner reads it from the table to enter at the same boundary."""
        with self._lock:
            self._pos = payload

    # ---- server-facing transitions (conn handler threads) ----------------
    def server_heartbeat(self, sid):
        """Refresh server ``sid``'s liveness; an unknown sid is a (re)join.

        Unlike worker heartbeats, an unknown server heartbeat ALWAYS counts
        as a join: a relaunched server slot is the same shard rejoining as
        a backup of its group — there is no half-pushed-round hazard to
        flush, so resurrecting it is always safe. The first heartbeat ever
        seen arms server-lapse monitoring (and refreshes every seed
        timestamp, so siblings that simply have not beaten yet are not
        instantly evicted)."""
        sid = int(sid)
        with self._lock:
            self._arm_srv_locked()
            if sid in self._srv_alive:
                self._srv_alive[sid] = time.monotonic()
                return
            self._srv_alive[sid] = time.monotonic()
            telemetry.event("server_rejoined", sid=sid, epoch=self._epoch)
            self._logger.warning(
                "membership: server %d rejoined as a backup of its group "
                "(smap %s)", sid, self._smap)
            # a rejoin never steals primaryship back (sticky smap: churn-
            # free, and the rejoiner's slots are stale) — but it CAN revive
            # a group that lost every member
            self._reconfigure_servers_locked(rejoined=sid)

    server_join = server_heartbeat  # mb_srv_join and mb_srv_hb are the same

    def server_suspect(self, sid):
        """A worker reported server ``sid`` dead (its client socket
        failed). Trust but verify: confirm with a deadline-bounded probe on
        a fresh socket before evicting — a worker-side network blip must
        not take down a healthy shard. Runs on a conn handler thread; the
        probe happens OUTSIDE the lock."""
        sid = int(sid)
        with self._lock:
            if sid not in self._srv_alive:
                return  # already evicted
        if self._srv_probe(sid):
            self._logger.info(
                "membership: server %d reported dead by a worker but "
                "answers probes — keeping it", sid)
            return
        with self._lock:
            if sid in self._srv_alive:
                del self._srv_alive[sid]
                telemetry.event("server_lost", sid=sid,
                                reason="worker_report", epoch=self._epoch)
                self._reconfigure_servers_locked(lost=sid)

    def table(self):
        """The membership table workers consume (JSON-able)."""
        with self._lock:
            return {
                "epoch": self._epoch,
                "workers": sorted(self._alive),
                "target": self._target,
                "formed": self._formed,
                "done": self._done,
                "pos": self._pos,
                # rank -> last training step it reported (joins/heartbeats):
                # observability only — mxtop shows where each worker is, and
                # reconfigure post-mortems line the bump up with the steps
                "steps": dict(self._last_step),
                # server HA: group -> primary sid (workers route by this)
                # and the alive server set (observability)
                "smap": list(self._smap),
                "servers": sorted(self._srv_alive),
            }

    def close(self):
        self._stop.set()
        self._monitor.join(timeout=5)

    # ---- internals -------------------------------------------------------
    def _bump_locked(self, why):
        """Caller holds ``_lock``. Bump + broadcast synchronously: the new
        epoch must be live on every server before any worker can read it."""
        self._epoch += 1
        # a position from the previous membership is stale — the coordinator
        # republishes after reconfiguring under the new epoch
        self._pos = None
        workers = len(self._alive)
        telemetry.counter("kv.membership.reconfigures").inc()
        telemetry.gauge("kv.membership.epoch").set(self._epoch)
        self._logger.warning(
            "membership: epoch %d (%s) — %d worker(s): %s",
            self._epoch, why, workers, sorted(self._alive))
        self._broadcast("mepoch:%d:%d" % (self._epoch, max(workers, 1)))

    def _arm_srv_locked(self):
        """First server heartbeat arms lapse monitoring; refresh every seed
        so a sibling that has not beaten yet gets a full timeout to."""
        if not self._srv_monitoring:
            self._srv_monitoring = True
            now = time.monotonic()
            for s in self._srv_alive:
                self._srv_alive[s] = now

    def _recompute_smap_locked(self):
        """Sticky primary recomputation: a group keeps its primary while it
        is alive; a dead primary is replaced by the first alive member in
        group order (deterministic failover). Returns ``[(group, old,
        new), ...]`` for every group whose primary changed."""
        changed = []
        for gi, members in enumerate(self._groups):
            cur = self._smap[gi]
            if cur is not None and cur in self._srv_alive:
                continue
            new = next((s for s in members if s in self._srv_alive), None)
            if new != cur:
                self._smap[gi] = new
                changed.append((gi, cur, new))
        return changed

    def _reconfigure_servers_locked(self, lost=None, rejoined=None):
        """A server left or (re)joined: recompute the map, tell every
        surviving server (they need it for replication targeting) and —
        only when a primary actually changed — bump the membership epoch so
        workers drain, adopt the new map, and re-seed the promoted
        primaries. The smap broadcast goes out BEFORE the epoch bump:
        by the time a worker reconfigures, every server already routes and
        replicates on the new map."""
        changed = self._recompute_smap_locked()
        promotions = [(gi, old, new) for gi, old, new in changed
                      if new is not None]
        import json

        self._broadcast("smap:" + json.dumps(
            {"smap": self._smap, "alive": sorted(self._srv_alive)}))
        for gi, old, new in changed:
            if new is None:
                self._logger.error(
                    "membership: server group %d lost ALL members %s — its "
                    "key range is unservable until one rejoins",
                    gi, self._groups[gi])
        if not promotions:
            if lost is not None:
                self._logger.warning(
                    "membership: backup server %d lost — no promotion "
                    "needed (smap %s)", lost, self._smap)
            return
        for gi, old, new in promotions:
            telemetry.counter("kv.replication.failovers").inc()
            telemetry.event("server_promoted", group=gi, old_primary=old,
                            new_primary=new, epoch=self._epoch + 1)
        why = ("server %s lost — promoted %s"
               % (lost, ["group %d: %s->%s" % c for c in promotions])
               if lost is not None else
               "server %s rejoined — revived %s"
               % (rejoined, ["group %d: %s->%s" % c for c in promotions]))
        self._bump_locked(why)

    def _probe_server(self, sid):
        """Fresh-socket liveness probe of server ``sid`` with a deadline
        (see mxt_ps_probe: cannot wedge on a shared client socket)."""
        lib = get_lib()
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        timeout_ms = max(min(int(self._timeout_s * 1000), 2000), 100)
        return lib.mxt_ps_probe(host.encode(), port + int(sid),
                                timeout_ms) == 0

    def _broadcast_to_servers(self, cmd):
        lib = get_lib()
        create2 = getattr(lib, "mxt_ps_client_create2", None)
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        if self._bcast_clients is None:
            self._bcast_clients = {}
            for s in range(sum(len(g) for g in self._groups)):
                # bounded connect budget: dialing a dead sibling during a
                # failover broadcast must cost seconds, not the 60s launch
                # race budget
                c = (create2(host.encode(), port + s, 30) if create2
                     else lib.mxt_ps_client_create(host.encode(), port + s))
                self._bcast_clients[s] = (("%s:%d" % (host, port + s)), c)
        timeout_ms = max(int(self._timeout_s * 1000), 1)
        # only alive servers are told: an evicted server no longer needs
        # epochs/maps (it re-learns on rejoin), and dialing it would cost a
        # timeout per broadcast. Every caller (_bump_locked,
        # _reconfigure_servers_locked, _sync_standbys) already holds _lock;
        # the analyzer cannot see through the injected self._broadcast hop.
        # fwlint: disable=unguarded-shared-write — caller holds _lock
        alive = set(self._srv_alive)
        for s, (addr, c) in self._bcast_clients.items():
            if s not in alive:
                continue
            if (not c or lib.mxt_ps_client_is_dead(c)) and create2:
                # reconnect (bounded): e.g. a relaunched server slot
                if c:
                    lib.mxt_ps_client_destroy(c)
                c = create2(host.encode(), port + s, 30)
                self._bcast_clients[s] = (addr, c)
            if not c or lib.mxt_ps_client_probe(c, cmd.encode(),
                                                timeout_ms) != 0:
                self._logger.error(
                    "membership: server %s did not acknowledge %r — a stale "
                    "epoch may briefly survive there", addr, cmd)

    def _sync_standbys(self):
        """Replicate the registry's own state to the group-0 backups
        (``mb_sync``) so a standby can resume it if this host dies. Sent
        through the normal broadcast channel: non-standby servers just
        stash the snapshot harmlessly."""
        if len(self._groups[0]) < 2:
            return  # no standbys configured
        import json

        payload = base64.b64encode(
            json.dumps(self.snapshot()).encode()).decode()
        with self._lock:
            self._broadcast("mb_sync:" + payload)

    def _monitor_loop(self):
        while not self._stop.wait(max(self._timeout_s / 4.0, 0.1)):
            now = time.monotonic()
            sync_due = False
            with self._lock:
                # server-lapse monitoring runs regardless of worker-side
                # formation (servers heartbeat from process start), but only
                # once armed by the first server heartbeat ever seen
                if self._srv_monitoring:
                    dead = [s for s, t in self._srv_alive.items()
                            if now - t > self._timeout_s]
                    for s in dead:
                        del self._srv_alive[s]
                        telemetry.event("server_lost", sid=s,
                                        reason="heartbeat_lapse",
                                        epoch=self._epoch)
                    if dead:
                        self._logger.warning(
                            "membership: server heartbeat lapse: %s",
                            sorted(dead))
                        self._reconfigure_servers_locked(lost=sorted(dead))
                    if now >= self._sync_at:
                        self._sync_at = now + self._timeout_s
                        sync_due = True
                if self._formed:
                    # done-reported ranks were removed from _alive by
                    # done(); everyone still listed is monitored even after
                    # the first mb_done (see done())
                    expired = [r for r, t in self._alive.items()
                               if now - t > self._timeout_s]
                    for r in expired:
                        del self._alive[r]
                    if expired:
                        for r in expired:
                            telemetry.event("worker_lost", rank=r,
                                            reason="heartbeat_lapse",
                                            epoch=self._epoch + 1,
                                            last_step=self._last_step.get(r))
                        self._bump_locked(
                            "heartbeat lapse: worker(s) %s" % sorted(expired))
            if sync_due:
                # outside the lock: snapshot() retakes it, and the
                # broadcast is network I/O
                try:
                    self._sync_standbys()
                except Exception:  # noqa: BLE001 — standby replication is
                    # best-effort; a failed sync costs failover freshness,
                    # never the registry itself
                    self._logger.exception("membership: mb_sync failed")


class KVStoreServer:
    """Hosts one PS shard (reference: kvstore_server.py:20 KVStoreServer)."""

    def __init__(self, port=None, num_workers=None, sync=True):
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable")
        self._lib = lib
        if port is None:
            base = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
            port = base + int(os.environ.get("DMLC_SERVER_ID", "0"))
        if num_workers is None:
            num_workers = int(os.environ.get("DMLC_NUM_WORKER", "1"))
        self._handle = lib.mxt_ps_server_create(port, num_workers, 1 if sync else 0)
        if not self._handle:
            raise RuntimeError("cannot bind PS server port %d" % port)
        self._port = port
        self._self_client = None  # lazy loopback client for stats publishing
        self._self_client_lock = threading.Lock()
        self._updater = None
        self._updater_lock = threading.Lock()
        self._states = {}
        # update-failure accounting: a raising updater must not silently
        # leave weights stale forever (the old behavior printed and kept
        # serving). Every failure is counted and logged; past the threshold
        # the server stops with an error instead of training on garbage.
        # MXNET_KV_SERVER_MAX_UPDATE_FAILURES=0 means die on the first one.
        self._stats_lock = threading.Lock()  # counters bump on conn threads
        self._stats_lock = witness.declare(
            "mxnet_tpu.kvstore_server.KVStoreServer._stats_lock",
            self._stats_lock)
        self._update_failures = 0
        self._updates_applied = 0
        self._last_update_error = None
        from .base import env_int

        self._max_update_failures = env_int(
            "MXNET_KV_SERVER_MAX_UPDATE_FAILURES", 10)

        # ---- server HA (docs/distributed.md §server-HA) ------------------
        from .base import env_bool, env_flag, env_float

        self._sid = int(os.environ.get("DMLC_SERVER_ID", "0"))
        self._num_workers = int(num_workers)
        self._elastic = env_bool("MXNET_ELASTIC")
        self._replicas = env_int("MXNET_KV_REPLICAS", 0)
        nservers = int(os.environ.get("DMLC_NUM_SERVER", "1"))
        self._groups = plan_server_groups(nservers, self._replicas)
        self._gi = next((i for i, g in enumerate(self._groups)
                         if self._sid in g), None)
        group = self._groups[self._gi] if self._gi is not None else [self._sid]
        self._backups = [s for s in group if s != self._sid]
        self._hb_timeout_s = env_float(
            "MXNET_ELASTIC_HEARTBEAT_TIMEOUT_S", 5.0)
        self._ha_lock = threading.Lock()
        self._ha_lock = witness.declare(
            "mxnet_tpu.kvstore_server.KVStoreServer._ha_lock", self._ha_lock)
        # guarded-by: _ha_lock — smap/alive view from registry broadcasts,
        # primary flag, and the standby's last mb_sync snapshot
        self._alive_sids = set(range(nservers))
        self._smap_view = [g[0] for g in self._groups]
        self._primary = bool(group) and group[0] == self._sid
        self._mb_sync = None
        self._mepoch = 0
        # guarded-by: _stats_lock — HA wire counters (stats vec fields)
        self._ha_stats = dict.fromkeys(_STATS_COUNTER_FIELDS_HA, 0)
        # optimizer objects live on the MAIN thread (exec loop); pending
        # states hold restored/replicated slots until an updater exists
        self._updater_obj = None
        self._optimizer_obj = None
        self._pending_states = None
        self._repl_recv_epoch = {}  # key -> last replication seq received
        # replication pipeline (guarded-by: _repl_cv's lock): at most one
        # in-flight round per key — offering the next round for a key waits
        # (bounded) for the previous forward to complete, which is what
        # keeps every backup at most one BSP round behind its primary
        self._repl_cv = threading.Condition()
        self._repl_inflight = set()
        self._repl_seq = 0
        self._repl_done_seq = 0
        self._repl_clients = {}  # sid -> client handle (repl thread + mepoch)
        self._reg_clients = {}   # sid -> client (heartbeat thread only)
        self._repl_wait_s = min(self._hb_timeout_s, 2.0)
        self._nservers = nservers
        self._ha_stop = threading.Event()
        self._ha_threads = []
        import queue as _queue

        self._repl_q = _queue.Queue()

        # durable optimizer slots: pickled {optimizer, states} written
        # through utils/atomic_file (tmp+fsync+rename+CRC) every
        # MXNET_KV_SERVER_CKPT_STEPS applied updates; a relaunched/promoted
        # server warm-starts from it under DMLC_PS_RECOVERY=1
        self._ckpt_steps = env_int("MXNET_KV_SERVER_CKPT_STEPS", 0)
        from .base import env_str

        ckpt_dir = env_str("MXNET_KV_SERVER_CKPT_DIR", "")
        if not ckpt_dir:
            import tempfile

            ckpt_dir = os.path.join(
                tempfile.gettempdir(),
                "mxnet-kv-server-ckpt-%d" % os.getuid())
        self._ckpt_path = os.path.join(
            ckpt_dir, "kv_server_%d.optstate" % self._sid)
        self._ckpt_count = 0  # applied rounds since start (main thread only)
        import queue

        self._ckpt_q = queue.Queue()
        if self._ckpt_steps > 0:
            os.makedirs(ckpt_dir, exist_ok=True)
        if env_flag("DMLC_PS_RECOVERY"):
            self._restore_checkpoint()

        # elastic membership: the first group-0 member hosts the registry
        # (docs/distributed.md §elasticity); its group siblings stand by to
        # resume it (deterministic failover order = group-0 member order),
        # and every elastic server heartbeats to it
        self._registry = None
        if self._elastic and self._sid == self._groups[0][0]:
            self._registry = MembershipRegistry(num_workers)

        # ALL python work (optimizer unpickle + update) runs on the server's
        # MAIN thread via this queue — the reference's single-threaded
        # Executor run-loop design (kvstore_dist_server.h:28-85), and a hard
        # requirement here: the main thread blocks inside `import mxnet_tpu`
        # holding the module import lock, so any import from a C++ conn
        # thread (e.g. unpickling mxnet_tpu.optimizer.SGD) would deadlock.
        import queue

        self._exec_q = queue.Queue()

        def _on_main(fn):
            done = threading.Event()
            box = {}

            def task():
                try:
                    fn()
                except Exception as e:  # don't wedge the run loop; the
                    box["err"] = e      # caller decides what the error means
                finally:
                    done.set()

            self._exec_q.put(task)
            done.wait()
            return box.get("err")

        self._on_main = _on_main

        def _apply(key, grad_ptr, weight_ptr, n):
            # flat fp32 views over the server's buffers; optimizer updates
            # in place (reference: DataHandle → updater_(key, merged, &stored);
            # with no optimizer installed the merged value is stored directly,
            # dist_server.h else-branch — update_on_kvstore=False pulls
            # merged grads back)
            import ctypes

            grad = np.ctypeslib.as_array(
                ctypes.cast(grad_ptr, ctypes.POINTER(ctypes.c_float)), (n,))
            weight = np.ctypeslib.as_array(
                ctypes.cast(weight_ptr, ctypes.POINTER(ctypes.c_float)), (n,))
            with self._updater_lock:
                fn = self._updater
            # fault seam (docs/fault_tolerance.md): SIGKILL this SERVER
            # after K applied updates — lands while optimizer slots and
            # replication are in flight, the worst case for promotion
            fault.kill_server(self._sid)
            # unlocked read: _primary only flips on registry smap
            # broadcasts, and a one-round-late view just costs one
            # forward/skip — never correctness (kInit carries full weights)
            repl = self._replicas > 0 and self._primary and self._backups
            if fn is None:
                weight[:] = grad
                if repl:
                    self._repl_offer(int(key), weight.copy(), None)
            else:
                box = {}

                def work():
                    fn(int(key), grad, weight)
                    if repl:
                        box["state"] = self._slot_state_blob(int(key))
                    self._ckpt_tick_main()

                err = _on_main(work)
                if err is None:
                    with self._stats_lock:
                        self._updates_applied += 1
                    telemetry.counter("kvstore_server.updates_applied").inc()
                    if repl:
                        self._repl_offer(int(key), weight.copy(),
                                         box.get("state"))
                else:
                    self._note_update_failure(int(key), err)

        def _command(cmd_ptr, n):
            import ctypes

            cmd = ctypes.string_at(cmd_ptr, n)
            if cmd.startswith(b"optim:"):
                blob = base64.b64decode(cmd[6:])
                err = _on_main(lambda: self._set_optimizer(pickle.loads(blob)))
                if err is not None:
                    import traceback

                    traceback.print_exception(err)
            elif cmd.startswith(b"mepoch:"):
                # the native layer already adopted the epoch (src/ps.cc
                # forwards membership commands after handling them); track
                # it here so replication clients stamp the CURRENT epoch —
                # a forward stamped stale would be kRejectEpoch'd by the
                # backup's own epoch gate
                try:
                    self._adopt_mepoch(int(cmd.split(b":")[1]))
                except (IndexError, ValueError):
                    logging.error("kvstore-server: malformed %r", cmd)
            elif cmd.startswith(b"smap:"):
                try:
                    self._adopt_smap(cmd[5:])
                except Exception:  # noqa: BLE001 — a malformed map must not
                    # take down the conn handler
                    logging.exception("kvstore-server: bad smap %r", cmd)
            elif cmd.startswith(b"repl:"):
                try:
                    self._handle_repl(cmd)
                except Exception:  # noqa: BLE001 — replication input is
                    # best-effort on the receiver: reject, never crash
                    logging.exception(
                        "kvstore-server: replication payload failed")
            elif cmd.strip() == b"stats":
                # operator-facing liveness/health line on the server log;
                # in-process callers use .stats() directly
                logging.warning("kvstore-server stats: %s", self.stats())
            elif cmd.startswith(b"stats_to:"):
                # log (same side-effect as plain "stats") AND publish the
                # counters under the worker-chosen reserved key, so
                # kvstore.request_server_stats can pull them as data — the
                # command response itself carries no payload (src/ps.cc)
                logging.warning("kvstore-server stats: %s", self.stats())
                try:
                    self._publish_stats(int(cmd[9:]))
                except Exception:  # noqa: BLE001 — a failed publish must not
                    # take down the conn handler; the worker sees a short
                    # pull and warns
                    logging.exception("kvstore-server: stats publish failed")
            elif cmd.startswith(b"trace_to:"):
                # per-rank RPC attribution (trace identity on the wire):
                # publish the native transport's rank table as JSON under
                # the worker-chosen reserved key
                # (kvstore.request_server_trace pulls it back)
                try:
                    import json

                    payload = json.dumps(
                        {"per_rank": self.trace_stats()}).encode()
                    self._publish_vec(int(cmd[9:]),
                                      encode_bytes_vec(payload))
                except Exception:  # noqa: BLE001 — same contract as
                    # stats_to: a failed publish degrades to a short pull
                    # on the worker, never a dead conn handler
                    logging.exception("kvstore-server: trace publish failed")
            elif cmd.startswith(b"mb_"):
                try:
                    self._handle_membership(cmd)
                except Exception:  # noqa: BLE001 — a malformed membership
                    # command must not take down the conn handler; the
                    # worker's bounded probe/fetch surfaces the silence
                    logging.exception(
                        "kvstore-server: membership command %r failed", cmd)

        self._apply_cb = UPDATER_FN(_apply)        # keep refs alive
        self._command_cb = COMMAND_FN(_command)
        import ctypes

        lib.mxt_ps_server_set_updater(
            self._handle, ctypes.cast(self._apply_cb, ctypes.c_void_p))
        lib.mxt_ps_server_set_command_handler(
            self._handle, ctypes.cast(self._command_cb, ctypes.c_void_p))
        self._start_ha_threads()

    # ---- server HA internals ---------------------------------------------
    def _start_ha_threads(self):
        def start(name, target):
            t = threading.Thread(target=target, daemon=True, name=name)
            t.start()
            self._ha_threads.append(t)

        if self._replicas > 0 and self._backups:
            start("mxnet-kv-replication", self._repl_loop)
        if self._ckpt_steps > 0:
            start("mxnet-kv-server-ckpt-writer", self._ckpt_writer_loop)
        if self._elastic and self._nservers > 1:
            # any multi-server elastic job heartbeats the PS tier; a lone
            # server has nobody to fail over to and skips the traffic
            start("mxnet-kv-server-heartbeat", self._hb_loop)
        if self._elastic and self._registry is None \
                and self._gi == 0 and self._sid != self._groups[0][0]:
            start("mxnet-kv-registry-standby", self._standby_loop)

    def _adopt_mepoch(self, epoch):
        # conn-handler thread publishes; the reconnect path reads it when
        # stamping a fresh replication client — both under _repl_cv
        with self._repl_cv:
            self._mepoch = epoch = int(epoch)
            clients = [c for c in self._repl_clients.values() if c]
        for c in clients:
            self._lib.mxt_ps_client_set_epoch(c, epoch)

    def _adopt_smap(self, payload):
        """Registry broadcast of the key→server map + alive set (conn
        handler thread): primaries use it to pick replication targets, and
        a backup learns here that it was promoted."""
        import json

        m = json.loads(payload.decode())
        with self._ha_lock:
            self._alive_sids = {int(s) for s in m.get("alive", [])}
            smap = [int(s) if s is not None else None
                    for s in m.get("smap", [])]
            if len(smap) == len(self._smap_view):
                self._smap_view = smap
            was = self._primary
            self._primary = (self._gi is not None
                             and self._smap_view[self._gi] == self._sid)
            now_primary = self._primary
        if now_primary and not was:
            logging.warning(
                "kvstore-server %d: PROMOTED to primary of group %d "
                "(smap %s)", self._sid, self._gi, smap)
        elif was and not now_primary:
            logging.warning(
                "kvstore-server %d: demoted to backup of group %d "
                "(smap %s)", self._sid, self._gi, smap)

    def _repl_targets(self):
        with self._ha_lock:
            return [s for s in self._backups if s in self._alive_sids]

    def _repl_offer(self, key, weight_np, state_blob):
        """Queue one applied round for forwarding (conn handler thread,
        AFTER the round committed locally). Blocks — bounded by
        ``_repl_wait_s`` — while the key's previous round is still being
        forwarded: this backpressure is the replication-epoch guarantee
        (backup at most one round behind). On timeout the round is queued
        anyway; kInit carries the full weight, so a skipped wait can delay
        a backup, never corrupt it."""
        with self._repl_cv:
            deadline = time.monotonic() + self._repl_wait_s
            while key in self._repl_inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._repl_cv.wait(remaining)
            self._repl_inflight.add(key)
            self._repl_seq += 1
            seq = self._repl_seq
            lag = self._repl_seq - self._repl_done_seq
        telemetry.gauge("kv.replication.lag_rounds").set(lag)
        with self._stats_lock:
            self._ha_stats["repl_lag_rounds"] = lag
        self._repl_q.put((int(key), weight_np, state_blob, seq))

    def _repl_loop(self):
        import ctypes

        lib = self._lib
        while not self._ha_stop.is_set():
            item = self._repl_q.get()
            if item is None:
                break
            key, vec, state_blob, seq = item
            forwards = acks = failures = 0
            for sid in self._repl_targets():
                forwards += 1
                ok = False
                try:
                    c = self._repl_client(sid)
                    if c is not None:
                        rc = lib.mxt_ps_client_init(
                            c, key,
                            vec.ctypes.data_as(
                                ctypes.POINTER(ctypes.c_float)), vec.size)
                        if rc == 0:
                            if state_blob is not None:
                                cmd = b"repl:%d:%d:%s" % (
                                    key, seq, base64.b64encode(state_blob))
                                ok = lib.mxt_ps_client_probe(
                                    c, cmd,
                                    int(self._repl_wait_s * 1000)) == 0
                            else:
                                ok = True
                except Exception:  # noqa: BLE001 — a sick backup must never
                    # stall the primary's data path
                    logging.exception(
                        "kvstore-server %d: replication forward to %d "
                        "failed", self._sid, sid)
                if ok:
                    acks += 1
                else:
                    failures += 1
            telemetry.counter("kv.replication.forwards").inc(forwards)
            if acks:
                telemetry.counter("kv.replication.acks").inc(acks)
            if failures:
                telemetry.counter("kv.replication.errors").inc(failures)
            with self._stats_lock:
                self._ha_stats["repl_forwards"] += forwards
                self._ha_stats["repl_acks"] += acks
                self._ha_stats["repl_failures"] += failures
            with self._repl_cv:
                self._repl_inflight.discard(key)
                self._repl_done_seq = seq
                self._repl_cv.notify_all()

    def _repl_client(self, sid):
        """Lazy per-backup client on the replication thread; rebuilt
        (bounded connect budget) after the backup restarts."""
        lib = self._lib
        create2 = getattr(lib, "mxt_ps_client_create2", None)
        with self._repl_cv:
            c = self._repl_clients.get(sid)
        if c is not None and not lib.mxt_ps_client_is_dead(c):
            return c
        if c is not None:
            lib.mxt_ps_client_destroy(c)
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        c = (create2(host.encode(), port + sid, 10) if create2
             else lib.mxt_ps_client_create(host.encode(), port + sid))
        with self._repl_cv:
            if c:
                lib.mxt_ps_client_set_epoch(c, self._mepoch)
            self._repl_clients[sid] = c
        return c

    def _handle_repl(self, cmd):
        """Backup side of a primary's forward: ``repl:<key>:<seq>:<b64
        pickled np state>`` (the weight itself arrived just before as a
        kInit on the same socket, so ordering is the transport's). Slot
        install runs on the main thread — the states dict belongs to the
        exec loop."""
        body = cmd[5:]
        key_s, _, rest = body.partition(b":")
        seq_s, _, b64 = rest.partition(b":")
        key, seq = int(key_s), int(seq_s)

        def install():
            state = pickle.loads(base64.b64decode(b64))
            u = self._updater_obj
            if u is not None:
                from .optimizer import Updater

                u.states[key] = Updater._from_np(state)
                u.states_synced[key] = False
            else:
                if self._pending_states is None:
                    self._pending_states = {}
                self._pending_states[key] = state
            self._repl_recv_epoch[key] = seq
            self._ckpt_tick_main()

        err = self._on_main(install)
        if err is not None:
            logging.error(
                "kvstore-server %d: replicated slot install failed for "
                "key %d: %r", self._sid, key, err)

    def _slot_state_blob(self, key):
        """Main thread only: the key's post-update optimizer slot as
        pickled numpy, or None when there is nothing to replicate."""
        u = self._updater_obj
        if u is None:
            return None
        state = u.states.get(key)
        if state is None:
            return None
        from .optimizer import Updater

        return pickle.dumps(Updater._to_np(state))

    # ---- durable optimizer slots -----------------------------------------
    def _ckpt_tick_main(self):
        """Main thread only: count an applied/replicated round; at the
        MXNET_KV_SERVER_CKPT_STEPS cadence snapshot the slots (cheap —
        pickling numpy) and hand the blob to the writer thread (fsync off
        the update path)."""
        if self._ckpt_steps <= 0:
            return
        self._ckpt_count += 1
        if self._ckpt_count % self._ckpt_steps:
            return
        states = None
        u = self._updater_obj
        if u is not None and u.states:
            from .optimizer import Updater

            states = {k: Updater._to_np(v) for k, v in u.states.items()}
        elif self._pending_states:
            states = dict(self._pending_states)
        if not states:
            return
        self._ckpt_q.put(pickle.dumps({
            "optimizer": self._optimizer_obj,
            "states": states,
            "updates_applied": self._ckpt_count,
        }))

    def _ckpt_writer_loop(self):
        import zlib

        while not self._ha_stop.is_set():
            blob = self._ckpt_q.get()
            if blob is None:
                break
            try:
                with atomic_write(self._ckpt_path,
                                  fault_name="server_ckpt_write") as w:
                    w.write(blob)
                telemetry.counter("kv.server_ckpt.writes").inc()
                telemetry.counter("kv.server_ckpt.bytes").inc(len(blob))
                with self._stats_lock:
                    self._ha_stats["ckpt_writes"] += 1
                    self._ha_stats["ckpt_bytes"] += len(blob)
                    first = self._ha_stats["ckpt_writes"] == 1
                # first write at warning — visible confirmation that
                # durability is live and where the file landed; the
                # periodic rewrites stay at info
                (logging.warning if first else logging.info)(
                    "kvstore-server %d: optimizer-state checkpoint "
                    "(%d bytes, states crc 0x%08x) -> %s",
                    self._sid, len(blob), zlib.crc32(blob),
                    self._ckpt_path)
            except Exception:  # noqa: BLE001 — a failed write costs
                # durability freshness, never the serving path
                telemetry.counter("kv.server_ckpt.errors").inc()
                logging.exception(
                    "kvstore-server %d: optimizer-state checkpoint write "
                    "failed", self._sid)

    def _restore_checkpoint(self):
        """Warm-start per-key optimizer slots from the last durable
        checkpoint (DMLC_PS_RECOVERY=1: this process is a relaunched or
        promoted server slot). Main thread, during __init__ — before the
        transport serves anything. A corrupt file (CRC mismatch) is
        counted and logged, and the server cold-starts; it NEVER crashes
        the slot."""
        import zlib

        if not os.path.exists(self._ckpt_path):
            return
        try:
            blob = read_verified(self._ckpt_path)
            snap = pickle.loads(blob)
            self._pending_states = dict(snap.get("states") or {})
            optim = snap.get("optimizer")
            if optim is not None:
                self._set_optimizer(optim)
            telemetry.counter("kv.server_ckpt.restores").inc()
            with self._stats_lock:
                self._ha_stats["ckpt_restores"] += 1
            logging.warning(
                "kvstore-server %d: restored optimizer state for %d "
                "key(s) from %s (%d bytes, states crc 0x%08x) — warm "
                "start", self._sid, len(snap.get("states") or {}),
                self._ckpt_path, len(blob), zlib.crc32(blob))
        except Exception:  # noqa: BLE001 — ChecksumError, torn pickle, a
            # stale incompatible snapshot: all degrade to a cold start
            telemetry.counter("kv.server_ckpt.errors").inc()
            logging.exception(
                "kvstore-server %d: optimizer-state checkpoint %s "
                "unreadable — cold start (momentum resets)",
                self._sid, self._ckpt_path)

    # ---- PS-tier heartbeats + registry failover --------------------------
    def _hb_loop(self):
        """Every elastic server heartbeats the registry so a dead server
        is noticed by lapse, exactly like a dead worker. When the registry
        is in-process (we host it) the call is direct; otherwise the beat
        walks the group-0 members in failover order until one acknowledges
        — which is also how the beat finds a resumed registry after a
        failover."""
        period = max(self._hb_timeout_s / 3.0, 0.1)
        target = [self._groups[0][0]]  # mutable current-registry memo
        while not self._ha_stop.wait(period):
            try:
                reg = self._registry
                if reg is not None:
                    reg.server_heartbeat(self._sid)
                    continue
                self._send_registry_hb(target)
            except Exception:  # noqa: BLE001 — heartbeat must never die
                logging.exception(
                    "kvstore-server %d: heartbeat failed", self._sid)

    def _send_registry_hb(self, target):
        lib = self._lib
        create2 = getattr(lib, "mxt_ps_client_create2", None)
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        cmd = b"mb_srv_hb:%d" % self._sid
        timeout_ms = max(int(self._hb_timeout_s * 500), 100)
        cands = [target[0]] + [s for s in self._groups[0]
                               if s != target[0] and s != self._sid]
        for sid in cands:
            c = self._reg_clients.get(sid)
            if c is not None and lib.mxt_ps_client_is_dead(c):
                lib.mxt_ps_client_destroy(c)
                c = None
            if c is None:
                c = (create2(host.encode(), port + sid, 10) if create2
                     else lib.mxt_ps_client_create(host.encode(),
                                                   port + sid))
                self._reg_clients[sid] = c
            if c and lib.mxt_ps_client_probe(c, cmd, timeout_ms) == 0:
                target[0] = sid
                return
        logging.warning(
            "kvstore-server %d: no registry candidate %s acknowledged a "
            "heartbeat", self._sid, cands)

    def _standby_loop(self):
        """Group-0 backup watching its predecessors: when every group-0
        member before this one (deterministic failover order) is dead —
        confirmed by consecutive fresh-socket probes, after having seen a
        predecessor alive at least once — resume the MembershipRegistry
        here from the last ``mb_sync`` snapshot."""
        lib = self._lib
        host = os.environ.get("DMLC_PS_ROOT_URI", "127.0.0.1")
        port = int(os.environ.get("DMLC_PS_ROOT_PORT", "9091"))
        my_pos = self._groups[0].index(self._sid)
        preds = self._groups[0][:my_pos]
        probe_ms = max(min(int(self._hb_timeout_s * 500), 2000), 100)
        period = max(self._hb_timeout_s / 2.0, 0.1)
        seen_alive = False
        dead_rounds = 0
        while not self._ha_stop.wait(period):
            if self._registry is not None:
                return
            alive = any(
                lib.mxt_ps_probe(host.encode(), port + s, probe_ms) == 0
                for s in preds)
            if alive:
                seen_alive = True
                dead_rounds = 0
                continue
            if not seen_alive:
                continue  # launch race: predecessors not up yet
            dead_rounds += 1
            if dead_rounds < 2:
                continue
            snap = None
            with self._ha_lock:
                raw = self._mb_sync
            if raw:
                try:
                    import json

                    snap = json.loads(base64.b64decode(raw).decode())
                except Exception:  # noqa: BLE001 — a torn snapshot is
                    # worse than none: resume cold
                    logging.exception(
                        "kvstore-server %d: mb_sync snapshot unreadable",
                        self._sid)
            telemetry.counter("kv.replication.failovers").inc()
            telemetry.event("registry_failover", sid=self._sid,
                            predecessors=preds, with_snapshot=bool(snap))
            logging.warning(
                "kvstore-server %d: registry predecessor(s) %s dead — "
                "resuming the membership registry here (%s snapshot)",
                self._sid, preds, "with" if snap else "WITHOUT")
            # race-ok: one-shot rebind by the sole standby thread (runs only
            # after every predecessor died); concurrent readers see the old
            # None or the fully constructed registry, nothing in between
            self._registry = MembershipRegistry(
                self._num_workers, resume=snap)
            return

    def _note_update_failure(self, key, err):
        """Count a failed server-side update (runs on a conn thread).

        The weight for ``key`` kept its previous value — the failed update
        was dropped, which under BSP silently biases training if it keeps
        happening. So: log loudly every time, and past
        MXNET_KV_SERVER_MAX_UPDATE_FAILURES enqueue a poison task that
        re-raises out of :meth:`run`, killing the server process (workers
        then observe a dead node via their probes instead of pulling
        quietly-stale weights forever)."""
        telemetry.counter("kvstore_server.update_failures").inc()
        with self._stats_lock:
            self._update_failures += 1
            self._last_update_error = "key %d: %r" % (key, err)
            failures = self._update_failures
        logging.error(
            "kvstore-server: updater failed for key %d (%d failure(s) so "
            "far, threshold %d): %r",
            key, failures, self._max_update_failures, err)
        if failures > self._max_update_failures:
            stats = self.stats()

            def die():
                raise RuntimeError(
                    "kvstore-server: %d optimizer updates failed (threshold "
                    "%d) — refusing to keep serving stale weights; last "
                    "error: %s; stats: %s"
                    % (stats["update_failures"], self._max_update_failures,
                       stats["last_update_error"], stats)) from err

            self._exec_q.put(die)

    def _handle_membership(self, cmd):
        """Dispatch a worker's ``mb_*`` command to the registry (conn
        handler thread). Only the registry host serves them; a sibling or
        non-elastic server ignores the traffic (the worker's bounded fetch
        times out and it retries against the registry's real address) —
        except ``mb_sync``, the registry's own state replicated TO the
        standbys."""
        if cmd.startswith(b"mb_sync:"):
            with self._ha_lock:
                self._mb_sync = cmd[8:].decode()
            return
        if self._registry is None:
            return
        name, _, arg = cmd.decode().partition(":")
        if name == "mb_join":
            # "mb_join:<rank>[:<step>]" — the optional step (elastic.py
            # appends it) timestamps membership events in training steps
            rank, _, step = arg.partition(":")
            self._registry.join(int(rank), int(step) if step else None)
        elif name == "mb_hb":
            rank, _, step = arg.partition(":")
            self._registry.heartbeat(int(rank), int(step) if step else None)
        elif name == "mb_leave":
            self._registry.leave(int(arg))
        elif name == "mb_done":
            self._registry.done(int(arg))
        elif name == "mb_pos":
            import json

            self._registry.set_pos(
                json.loads(base64.b64decode(arg).decode()))
        elif name in ("mb_srv_hb", "mb_srv_join"):
            self._registry.server_heartbeat(int(arg))
        elif name == "mb_srv_dead":
            # a worker's dead-socket hint; the registry probe-confirms
            # before evicting (this blocks the conn thread for at most one
            # probe deadline — conn handlers are per-request threads)
            self._registry.server_suspect(int(arg))
        elif name == "mb_get":
            import json

            payload = json.dumps(self._registry.table()).encode()
            self._publish_vec(int(arg), encode_bytes_vec(payload))

    def _publish_stats(self, key):
        """Push this server's counters into its OWN store under ``key``
        (runs on a conn handler thread, before the command response is sent,
        so the requesting worker's follow-up pull always finds the entry).

        The worker picks a fresh negative key per call, so this self-push
        always takes the server's first-push init path (src/ps.cc
        HandlePush) — it cannot join a BSP merge round or run the optimizer.
        Only already-imported modules are touched: a first-time import here
        would deadlock on the import lock the blocked main thread holds.

        The push happens WHILE holding ``_self_client_lock``: the shutdown
        path takes the same lock before destroying the loopback client, so
        a stats request racing a stop can never push on a freed handle —
        teardown waits for the in-flight publish (the server is still alive
        at that point, so the publish completes promptly)."""
        self._publish_vec(key, encode_stats_vec(self.stats()))

    def _publish_vec(self, key, vec):
        """Loopback self-push of ``vec`` under reserved key ``key`` (the
        payload channel for stats and the membership table — see
        :meth:`_publish_stats` for the locking contract)."""
        import ctypes

        with self._self_client_lock:
            if self._self_client is None:
                c = self._lib.mxt_ps_client_create(b"127.0.0.1", self._port)
                if not c:
                    raise RuntimeError(
                        "cannot open loopback client to own port %d"
                        % self._port)
                self._self_client = c
            rc = self._lib.mxt_ps_client_push(
                self._self_client, key,
                vec.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), vec.size)
        if rc != 0:
            raise RuntimeError("loopback publish push failed (key %d)" % key)

    def trace_stats(self):
        """Per-rank RPC attribution from the native transport (trace
        identity on the wire, docs/observability.md §cluster): ``{rank:
        {"last_step": ..., "last_mepoch": ..., "pushes": ..., "pulls": ...,
        "barriers": ..., "inits": ...}}`` — which worker step each rank's
        traffic last carried, and how much data-path handling this shard
        has done for it. Served over the command channel as
        ``trace_to:<key>``."""
        import ctypes

        cap = 7 * 256  # 256 ranks — far beyond any PS-tier deployment here
        buf = (ctypes.c_double * cap)()
        n = self._lib.mxt_ps_server_trace_stats(self._handle, buf, cap)
        out = {}
        for i in range(0, max(n, 0), 7):
            rank, step, mepoch, pushes, pulls, barriers, inits = buf[i:i + 7]
            out[int(rank)] = {
                "last_step": int(step), "last_mepoch": int(mepoch),
                "pushes": int(pushes), "pulls": int(pulls),
                "barriers": int(barriers), "inits": int(inits),
            }
        return out

    def stats(self):
        """Health counters (also printed by the ``b"stats"`` client command)."""
        with self._stats_lock:  # counters bump on conn threads; snapshot
            out = {             # must pair count with its matching error
                "updates_applied": self._updates_applied,
                "update_failures": self._update_failures,
                "last_update_error": self._last_update_error,
                "has_optimizer": self._updater is not None,
            }
            out.update(self._ha_stats)
        return out

    def _set_optimizer(self, optimizer):
        from . import fault
        from . import optimizer as opt
        from .ndarray import NDArray

        updater = opt.get_updater(optimizer)
        # server HA: an optimizer (re)install must never silently reset the
        # per-key slots — reconfigure resends the optimizer after a rescale
        # (elastic.py), and a restored/promoted server holds slots from its
        # checkpoint or from primary forwards (_pending_states)
        prev = self._updater_obj
        if prev is not None and prev.states:
            updater.states = prev.states
            updater.states_synced = dict.fromkeys(updater.states, False)
        elif self._pending_states:
            updater.states = {
                k: opt.Updater._from_np(v)
                for k, v in self._pending_states.items()}
            updater.states_synced = dict.fromkeys(updater.states, False)
            self._pending_states = None
        self._updater_obj = updater
        self._optimizer_obj = optimizer

        def apply_np(key, grad_np, weight_np):
            fault.hit("server_updater")
            g = NDArray(np.array(grad_np))
            w = NDArray(weight_np.copy())
            updater(key, g, w)
            weight_np[:] = w.asnumpy()

        with self._updater_lock:
            self._updater = apply_np

    def run(self):
        """Serve until a worker sends the stop command, executing python
        work (optimizer updates) on THIS thread (reference: KVStoreServer.run
        → single-threaded Executor loop, kvstore_dist_server.h:28-85)."""

        def waiter():
            self._lib.mxt_ps_server_wait(self._handle)
            self._exec_q.put(None)

        t = threading.Thread(target=waiter, daemon=True,
                             name="mxnet-kv-server-waiter")
        t.start()
        while True:
            task = self._exec_q.get()
            if task is None:
                break
            task()
        t.join()
        # destroy joins conn threads, whose in-flight handlers may still
        # enqueue work (e.g. an async push racing the stop) — keep executing
        # those on a drainer so their done.wait() can't wedge the join. The
        # import-lock constraint no longer applies: anything they run was
        # already imported by earlier main-thread tasks.
        import queue as _q

        stop_drain = threading.Event()

        def drainer():
            while not stop_drain.is_set():
                try:
                    task = self._exec_q.get(timeout=0.05)
                except _q.Empty:
                    continue
                if task is not None:
                    task()

        d = threading.Thread(target=drainer,
                             name="mxnet-kv-server-drainer")
        d.start()
        # stop the HA threads before tearing the transport down (they own
        # client handles into it); queue sentinels wake the blocking gets
        self._ha_stop.set()
        self._repl_q.put(None)
        self._ckpt_q.put(None)
        for t in self._ha_threads:
            t.join(timeout=2)
        if self._registry is not None:
            self._registry.close()
        with self._self_client_lock:
            if self._self_client is not None:
                self._lib.mxt_ps_client_destroy(self._self_client)
                self._self_client = None
        self._lib.mxt_ps_server_destroy(self._handle)
        stop_drain.set()
        d.join()
        # race-ok: shutdown epilogue — the waiter thread exited before the
        # destroy above, so nothing else can observe this rebind
        self._handle = None


def _init_kvstore_server_module():
    """Block server-role processes here (reference: kvstore_server.py:58-68,
    called from `import mxnet` when DMLC_ROLE=server)."""
    role = os.environ.get("DMLC_ROLE", "")
    if role == "server":
        server = KVStoreServer()
        server.run()
        import sys

        sys.exit(0)
    # the reference's scheduler role does rendezvous; our workers connect
    # directly to servers, so a scheduler process just exits cleanly
    if role == "scheduler":
        import sys

        sys.exit(0)
