"""Custom python operators — the user escape hatch.

Reference: python/mxnet/operator.py — ``CustomOp``/``CustomOpProp`` (:396,
:442) registered via ``register`` (:576, C side ``MXCustomOpRegister`` +
src/operator/custom/custom-inl.h running callbacks as kAsync engine ops),
plus the legacy ``NumpyOp``/``NDArrayOp`` (:126, :226).

TPU design: a custom op is host Python inside an XLA graph. Forward lowers
to ``jax.pure_callback`` (the XLA host-callback — the analog of the
reference's kAsync engine callback into Python) with shapes from the prop's
``infer_shape``; the gradient is a ``jax.custom_vjp`` whose backward is a
second ``pure_callback`` into ``CustomOp.backward``. Works identically under
``mx.nd.Custom`` (imperative), inside ``Symbol`` graphs, and under jit —
but, being a host round-trip, it synchronizes the device pipeline exactly
like the reference's custom ops serialized their engine stream.
"""
from __future__ import annotations

import numpy as np

from .base import MXNetError
from .ops.registry import Operator, _OP_REGISTRY

__all__ = ["CustomOp", "CustomOpProp", "register", "get_all_registered_operators",
           "NumpyOp", "NDArrayOp"]

_CUSTOM_REGISTRY = {}


class CustomOp:
    """Base class for custom operators (reference: operator.py:396)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        raise NotImplementedError

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        raise NotImplementedError

    def assign(self, dst, req, src):
        """(reference: operator.py CustomOp.assign — honor the write request)"""
        if req == "null":
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise MXNetError("unknown req %s" % req)


class CustomOpProp:
    """Operator property: shapes/types/instantiation (reference: operator.py:442)."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]] * len(self.list_outputs()), []

    def infer_type(self, in_type):
        return (in_type, [in_type[0]] * len(self.list_outputs()),
                [in_type[0]] * len(self.list_auxiliary_states()))

    def list_outputs(self):
        return ["output"]

    def list_arguments(self):
        return ["data"]

    def list_auxiliary_states(self):
        return []

    def need_top_grad(self):
        return self.need_top_grad_

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        deps = []
        if self.need_top_grad():
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()


def register(reg_name):
    """Register a CustomOpProp subclass under ``op_type`` name
    (reference: operator.py:576 register → MXCustomOpRegister)."""

    def _reg(prop_cls):
        _CUSTOM_REGISTRY[reg_name] = prop_cls
        return prop_cls

    return _reg


def get_all_registered_operators():
    return sorted(_CUSTOM_REGISTRY)


# ---------------------------------------------------------------------------
# the 'Custom' op bridging the prop/op classes into the op registry
# (reference: src/operator/custom/custom.cc registered as "Custom")
# ---------------------------------------------------------------------------

def _get_prop(attrs):
    op_type = attrs.get("op_type")
    if op_type is None:
        raise MXNetError("Custom op needs op_type attr")
    if op_type not in _CUSTOM_REGISTRY:
        raise MXNetError("Custom op type '%s' not registered" % op_type)
    kwargs = {k: v for k, v in attrs.items() if k != "op_type"}
    return _CUSTOM_REGISTRY[op_type](**kwargs)


def _custom_arg_names(attrs):
    return _get_prop(attrs).list_arguments()


def _custom_aux_names(attrs):
    return _get_prop(attrs).list_auxiliary_states()


def _custom_num_outputs(attrs):
    return len(_get_prop(attrs).list_outputs())


def _np_list(arrays):
    from .ndarray import NDArray

    return [NDArray(np.asarray(a)) for a in arrays]


def _custom_forward(octx, attrs, args, auxs):
    import jax

    prop = _get_prop(attrs)
    n_out = len(prop.list_outputs())
    in_shapes = [tuple(a.shape) for a in args]
    in_dtypes = [np.dtype(a.dtype) for a in args]
    _, out_shapes, _ = prop.infer_shape([list(s) for s in in_shapes])
    _, out_dtypes, _ = prop.infer_type(in_dtypes)
    out_struct = tuple(
        jax.ShapeDtypeStruct(tuple(s), np.dtype(d))
        for s, d in zip(out_shapes, out_dtypes))
    aux_struct = tuple(jax.ShapeDtypeStruct(tuple(a.shape), np.dtype(a.dtype))
                       for a in auxs)
    is_train = bool(octx.is_train)
    need_top = prop.need_top_grad()
    n_args = len(args)

    def host_forward(*host_args):
        a_in = _np_list(host_args[:n_args])
        a_aux = _np_list(host_args[n_args:])
        op = prop.create_operator(None, in_shapes, in_dtypes)
        outs = _np_list([np.zeros(s, d) for s, d in zip(out_shapes, out_dtypes)])
        op.forward(is_train, ["write"] * n_out, a_in, outs, a_aux)
        res = [o.asnumpy().astype(d) for o, d in zip(outs, out_dtypes)]
        res += [a.asnumpy() for a in a_aux]  # aux may be mutated in place
        return tuple(res)

    def host_backward(*host_args):
        # layout: out_grads..., in_data..., out_data..., auxs...
        i = 0
        g_out = _np_list(host_args[i:i + n_out]); i += n_out
        a_in = _np_list(host_args[i:i + n_args]); i += n_args
        a_out = _np_list(host_args[i:i + n_out]); i += n_out
        a_aux = _np_list(host_args[i:])
        op = prop.create_operator(None, in_shapes, in_dtypes)
        grads = _np_list([np.zeros(s, d) for s, d in zip(in_shapes, in_dtypes)])
        op.backward(["write"] * n_args, g_out, a_in, a_out, grads, a_aux)
        return tuple(g.asnumpy().astype(d) for g, d in zip(grads, in_dtypes))

    @jax.custom_vjp
    def run(args_t, auxs_t):
        res = jax.pure_callback(host_forward, out_struct + aux_struct,
                                *args_t, *auxs_t)
        return list(res[:n_out]), list(res[n_out:])

    def run_fwd(args_t, auxs_t):
        outs, new_auxs = run(args_t, auxs_t)
        return (outs, new_auxs), (tuple(args_t), tuple(outs), tuple(auxs_t))

    def run_bwd(res, cts):
        args_t, outs_t, auxs_t = res
        g_outs, _g_auxs = cts
        g_outs = [jax.numpy.zeros_like(o) if g is None else g
                  for g, o in zip(g_outs, outs_t)]
        in_struct = tuple(jax.ShapeDtypeStruct(s, d)
                          for s, d in zip(in_shapes, in_dtypes))
        grads = jax.pure_callback(host_backward, in_struct,
                                  *g_outs, *args_t, *outs_t, *auxs_t)
        return (list(grads), [jax.numpy.zeros_like(a) for a in auxs_t])

    run.defvjp(run_fwd, run_bwd)
    outs, new_auxs = run(list(args), list(auxs))
    return list(outs), list(new_auxs)


def _custom_infer_shape(attrs, in_shapes, aux_shapes):
    prop = _get_prop(attrs)
    ins, outs, auxs = prop.infer_shape([list(s) if s else None for s in in_shapes])
    return ([tuple(s) for s in ins], [tuple(s) for s in outs],
            [tuple(s) for s in auxs])


_OP_REGISTRY["Custom"] = Operator(
    "Custom",
    _custom_forward,
    arg_names=_custom_arg_names,
    aux_names=_custom_aux_names,
    num_outputs=_custom_num_outputs,
    infer_shape=_custom_infer_shape,
    keep_extras=True,
)
# Custom takes arbitrary string kwargs forwarded to the prop ctor; the registry
# treats unknown attrs as pass-through extras, so no Param schema is declared.


# ---------------------------------------------------------------------------
# legacy python-op APIs (reference: operator.py:126 NumpyOp, :226 NDArrayOp) —
# thin adapters onto the CustomOp machinery
# ---------------------------------------------------------------------------

class _LegacyProp(CustomOpProp):
    def __init__(self, legacy):
        super().__init__(need_top_grad=legacy.need_top_grad_)
        self._legacy = legacy

    def list_arguments(self):
        return self._legacy.list_arguments()

    def list_outputs(self):
        return self._legacy.list_outputs()

    def infer_shape(self, in_shape):
        res = self._legacy.infer_shape(in_shape)
        return (res[0], res[1], []) if len(res) == 2 else res

    def create_operator(self, ctx, in_shapes, in_dtypes):
        legacy = self._legacy

        class _Adapter(CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                xs = [a.asnumpy() for a in in_data]
                ys = [o.asnumpy() for o in out_data]
                legacy.forward(in_data=xs, out_data=ys)
                for o, y in zip(out_data, ys):
                    self.assign(o, req[0], y)

            def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
                ograd = [g.asnumpy() for g in out_grad]
                xs = [a.asnumpy() for a in in_data]
                ys = [o.asnumpy() for o in out_data]
                igrad = [g.asnumpy() for g in in_grad]
                legacy.backward(out_grad=ograd, in_data=xs, out_data=ys,
                                in_grad=igrad)
                for g, v in zip(in_grad, igrad):
                    self.assign(g, req[0], v)

        return _Adapter()


class NumpyOp:
    """Legacy numpy custom op (reference: operator.py:126). Subclass and
    implement forward/backward/list_*/infer_shape; call the instance on
    symbols: ``op = MyOp(); y = op(x, name=...)``."""

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def need_top_grad(self):
        return self.need_top_grad_

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]]

    def forward(self, in_data, out_data):
        raise NotImplementedError

    def backward(self, out_grad, in_data, out_data, in_grad):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        from . import symbol as sym_mod

        name = "numpy_op_%d" % id(self)
        if name not in _CUSTOM_REGISTRY:
            legacy = self
            _CUSTOM_REGISTRY[name] = lambda **kw: _LegacyProp(legacy)
        kwargs["op_type"] = name
        return sym_mod.Custom(*args, **kwargs)


NDArrayOp = NumpyOp  # same python-side contract in this rebuild
