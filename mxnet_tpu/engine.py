"""Engine — host-side async dependency scheduler over the native runtime.

Reference: include/mxnet/engine.h:75-250 (NewVariable/NewOperator/PushAsync/
WaitForVar/WaitForAll) with ThreadedEnginePerDevice as the default
implementation and NaiveEngine as the synchronous debug fallback, selected by
``MXNET_ENGINE_TYPE`` (src/engine/engine.cc:13-39).

On TPU the *device* stream is XLA's own async dispatch (every jitted call is
already non-blocking), so this engine schedules the HOST side of the
framework: data-pipeline stages, checkpoint/serialization work, kvstore
server handlers and custom-op callbacks — anything the reference ran on its
CPU worker pools. The dependency model is identical: ops declare const
(read) and mutable (write) vars; writes are exclusive, reads shared, FIFO
per var.

``MXNET_ENGINE_TYPE=NaiveEngine`` runs everything inline on the pushing
thread (the reference's bisection tool for scheduling bugs);
``MXNET_CPU_WORKER_NTHREADS`` sizes the pool.
"""
from __future__ import annotations

import os
import threading

from ._native import ENGINE_FN, get_lib

__all__ = ["Engine", "NaiveEngine", "ThreadedEngine", "get_engine", "Var"]


class Var:
    """Opaque dependency token (reference: engine.h VarHandle)."""

    __slots__ = ("handle",)

    def __init__(self, handle):
        self.handle = handle


class Engine:
    def new_variable(self):
        raise NotImplementedError

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        raise NotImplementedError

    def wait_for_var(self, var):
        raise NotImplementedError

    def wait_all(self):
        raise NotImplementedError

    def delete_variable(self, var):
        raise NotImplementedError


class NaiveEngine(Engine):
    """Synchronous engine: push == run (reference: src/engine/naive_engine.cc)."""

    def new_variable(self):
        return Var(None)

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        fn()

    def wait_for_var(self, var):
        pass

    def wait_all(self):
        pass

    def delete_variable(self, var):
        pass


class ThreadedEngine(Engine):
    """Native threaded dependency engine (src/engine.cc via ctypes).

    Python callables are retained until their op completes; the C++ side
    invokes them on worker threads through a single trampoline.
    """

    def __init__(self, num_workers=None):
        import ctypes

        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable (no g++?); "
                               "set MXNET_ENGINE_TYPE=NaiveEngine")
        if num_workers is None:
            num_workers = int(os.environ.get("MXNET_CPU_WORKER_NTHREADS",
                                             str(min(8, os.cpu_count() or 1))))
        self._lib = lib
        self._handle = lib.mxt_engine_create(num_workers)
        self._pending = {}
        self._pending_lock = threading.Lock()
        self._next_id = [1]
        self._ctypes = ctypes

        def _trampoline(arg):
            key = int(arg)
            with self._pending_lock:
                fn = self._pending.pop(key)
            try:
                fn()
            except Exception:  # worker threads must never die on user errors
                import traceback

                traceback.print_exc()

        self._trampoline = ENGINE_FN(_trampoline)  # keep alive

    def new_variable(self):
        return Var(self._lib.mxt_engine_new_var(self._handle))

    def _var_array(self, vars_):
        import ctypes

        arr = (ctypes.c_void_p * max(len(vars_), 1))()
        for i, v in enumerate(vars_):
            arr[i] = v.handle
        return arr

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        with self._pending_lock:
            key = self._next_id[0]
            self._next_id[0] += 1
            self._pending[key] = fn
        cv = self._var_array(const_vars)
        mv = self._var_array(mutable_vars)
        self._lib.mxt_engine_push(
            self._handle, self._ctypes.cast(self._trampoline, self._ctypes.c_void_p),
            key, cv, len(const_vars), mv, len(mutable_vars), priority)

    def wait_for_var(self, var):
        self._lib.mxt_engine_wait_for_var(self._handle, var.handle)

    def wait_all(self):
        self._lib.mxt_engine_wait_all(self._handle)

    def delete_variable(self, var):
        self._lib.mxt_engine_delete_var(self._handle, var.handle)

    def __del__(self):
        try:
            self._lib.mxt_engine_wait_all(self._handle)
            self._lib.mxt_engine_destroy(self._handle)
        except Exception:
            pass


_engine = None
_engine_lock = threading.Lock()


def get_engine():
    """Process-global engine singleton (reference: Engine::Get, engine.cc:42-50)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            kind = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEngine")
            if kind == "NaiveEngine":
                _engine = NaiveEngine()
            else:
                try:
                    _engine = ThreadedEngine()
                except RuntimeError:
                    _engine = NaiveEngine()
        return _engine
