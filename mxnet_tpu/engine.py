"""Engine — host-side async dependency scheduler over the native runtime.

Reference: include/mxnet/engine.h:75-250 (NewVariable/NewOperator/PushAsync/
WaitForVar/WaitForAll) with ThreadedEnginePerDevice as the default
implementation and NaiveEngine as the synchronous debug fallback, selected by
``MXNET_ENGINE_TYPE`` (src/engine/engine.cc:13-39).

On TPU the *device* stream is XLA's own async dispatch (every jitted call is
already non-blocking), so this engine schedules the HOST side of the
framework: data-pipeline stages, checkpoint/serialization work, kvstore
server handlers and custom-op callbacks — anything the reference ran on its
CPU worker pools. The dependency model is identical: ops declare const
(read) and mutable (write) vars; writes are exclusive, reads shared, FIFO
per var.

``MXNET_ENGINE_TYPE=NaiveEngine`` runs everything inline on the pushing
thread (the reference's bisection tool for scheduling bugs);
``MXNET_CPU_WORKER_NTHREADS`` sizes the pool.
"""
from __future__ import annotations

import os
import threading
import time

from . import base, telemetry
from ._native import ENGINE_FN, get_lib
from .analysis import sanitizer as _sanitizer

__all__ = ["Engine", "NaiveEngine", "ThreadedEngine", "get_engine", "Var"]


class Var:
    """Opaque dependency token (reference: engine.h VarHandle).

    ``deleted`` is set by ``delete_variable`` so the dependency sanitizer
    (analysis/sanitizer.py) can flag use-after-free; the scheduler itself
    never reads it.
    """

    __slots__ = ("handle", "deleted")

    def __init__(self, handle):
        self.handle = handle
        self.deleted = False


class Engine:
    """Base engine with the reference's error-propagation contract: an
    exception raised inside a pushed fn is recorded (first one wins, like the
    on_complete error path in threaded_engine.cc) and re-raised from the next
    ``wait_for_var``/``wait_all`` on the pushing thread — never printed and
    dropped. The recorded error is cleared when raised, so training loops
    that catch it can keep using the engine."""

    def __init__(self):
        self._err_lock = threading.Lock()
        self._first_error = None  # guarded-by: _err_lock

    def _record_error(self, exc):
        import logging

        # error-path counter: rare by definition, so it counts even with
        # telemetry disabled (docs/observability.md "always-on counters")
        telemetry.counter("engine.push_errors").inc()
        with self._err_lock:
            if self._first_error is None:
                self._first_error = exc
                # also log NOW: if the program never reaches another wait
                # (e.g. it exits after its last push), the re-raise path
                # never runs and this line is the only trace of the failure
                logging.getLogger(__name__).error(
                    "engine: pushed fn failed; will re-raise at the next "
                    "wait_for_var/wait_all", exc_info=exc)
                return
        # only one error can re-raise at the wait; later ones must still
        # leave a trace (the old print-and-drop behavior, kept for exactly
        # the errors the new path cannot surface)
        logging.getLogger(__name__).error(
            "engine: dropping secondary error (an earlier one is pending "
            "re-raise at the next wait)", exc_info=exc)

    def _raise_pending(self):
        with self._err_lock:
            err, self._first_error = self._first_error, None
        if err is not None:
            raise err

    def new_variable(self):
        raise NotImplementedError

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        raise NotImplementedError

    def wait_for_var(self, var):
        raise NotImplementedError

    def wait_all(self):
        raise NotImplementedError

    def delete_variable(self, var):
        raise NotImplementedError


class NaiveEngine(Engine):
    """Synchronous engine: push == run (reference: src/engine/naive_engine.cc).

    Errors still surface at the wait, not the push — matching ThreadedEngine
    so code bisected under MXNET_ENGINE_TYPE=NaiveEngine sees identical
    control flow, and so a failed push doesn't prevent later pushes."""

    def new_variable(self):
        return Var(None)

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        from . import fault

        if _sanitizer.active():
            # strict mode raises HERE on a deleted declared var (caller bug,
            # synchronous by design); in-fn checks ride inside the wrapper
            _sanitizer.check_declared(const_vars, mutable_vars)
            fn = _sanitizer.wrap_push(fn, const_vars, mutable_vars)
        tel = telemetry.enabled()
        if tel:
            telemetry.counter("engine.pushes").inc()
            t0 = time.perf_counter()
        try:
            fn()
        except (Exception, fault.InjectedCrash) as e:
            # parity with the threaded trampoline: errors (including a
            # simulated crash) surface at the wait, not the push. But this
            # runs on the PUSHING thread, so KeyboardInterrupt/SystemExit
            # must propagate immediately — deferring Ctrl-C would make the
            # process un-interruptible, which the worker-thread trampoline
            # can't cause (the interpreter delivers signals to the main
            # thread only).
            self._record_error(e)
        finally:
            if tel:
                telemetry.histogram("engine.push_latency_seconds").observe(
                    time.perf_counter() - t0)

    def wait_for_var(self, var):
        self._raise_pending()

    def wait_all(self):
        self._raise_pending()

    def delete_variable(self, var):
        var.deleted = True


class ThreadedEngine(Engine):
    """Native threaded dependency engine (src/engine.cc via ctypes).

    Python callables are retained until their op completes; the C++ side
    invokes them on worker threads through a single trampoline.
    """

    def __init__(self, num_workers=None):
        import ctypes

        super().__init__()
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native runtime unavailable (no g++?); "
                               "set MXNET_ENGINE_TYPE=NaiveEngine")
        if num_workers is None:
            num_workers = base.env_int("MXNET_CPU_WORKER_NTHREADS",
                                       min(8, os.cpu_count() or 1))
        self._lib = lib
        self._handle = lib.mxt_engine_create(num_workers)
        self._pending_lock = threading.Lock()
        self._pending = {}  # guarded-by: _pending_lock
        self._next_id = [1]  # guarded-by: _pending_lock
        self._ctypes = ctypes

        def _trampoline(arg):
            key = int(arg)
            with self._pending_lock:
                fn = self._pending.pop(key)
                depth = len(self._pending)
            tel = telemetry.enabled()
            if tel:
                telemetry.gauge("engine.queue_depth").set(depth)
                t0 = time.perf_counter()
            try:
                fn()
            except BaseException as e:  # noqa: BLE001 — a worker thread must
                # never throw into the C++ callback; record for the next wait
                self._record_error(e)
            finally:
                if tel:
                    telemetry.histogram(
                        "engine.push_latency_seconds").observe(
                            time.perf_counter() - t0)

        self._trampoline = ENGINE_FN(_trampoline)  # keep alive

    def new_variable(self):
        return Var(self._lib.mxt_engine_new_var(self._handle))

    def _var_array(self, vars_):
        import ctypes

        arr = (ctypes.c_void_p * max(len(vars_), 1))()
        for i, v in enumerate(vars_):
            arr[i] = v.handle
        return arr

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        if _sanitizer.active():
            _sanitizer.check_declared(const_vars, mutable_vars)
            fn = _sanitizer.wrap_push(fn, const_vars, mutable_vars)
        with self._pending_lock:
            key = self._next_id[0]
            self._next_id[0] += 1
            self._pending[key] = fn
            depth = len(self._pending)
        if telemetry.enabled():
            # queue depth = ops accepted but not yet started by a worker; the
            # trampoline updates it downward as it drains
            telemetry.counter("engine.pushes").inc()
            telemetry.gauge("engine.queue_depth").set(depth)
        cv = self._var_array(const_vars)
        mv = self._var_array(mutable_vars)
        try:
            self._lib.mxt_engine_push(
                self._handle, self._ctypes.cast(self._trampoline, self._ctypes.c_void_p),
                key, cv, len(const_vars), mv, len(mutable_vars), priority)
        except BaseException:
            # the native side never saw the op, so the trampoline will never
            # pop this entry — without this, every failed push leaks its fn
            # (and everything the closure captures) forever
            with self._pending_lock:
                self._pending.pop(key, None)
            raise

    def wait_for_var(self, var):
        self._lib.mxt_engine_wait_for_var(self._handle, var.handle)
        self._raise_pending()

    def wait_all(self):
        self._lib.mxt_engine_wait_all(self._handle)
        self._raise_pending()

    def delete_variable(self, var):
        var.deleted = True
        self._lib.mxt_engine_delete_var(self._handle, var.handle)

    def __del__(self):
        try:
            self._lib.mxt_engine_wait_all(self._handle)
            self._lib.mxt_engine_destroy(self._handle)
        except Exception:  # fwlint: disable=swallowed-exception — interpreter
            pass  # teardown: the lib/ctypes globals may already be gone


_engine = None
_engine_lock = threading.Lock()


def get_engine():
    """Process-global engine singleton (reference: Engine::Get, engine.cc:42-50)."""
    global _engine
    with _engine_lock:
        if _engine is None:
            kind = base.env_str("MXNET_ENGINE_TYPE", "ThreadedEngine")
            if kind == "NaiveEngine":
                _engine = NaiveEngine()
            else:
                try:
                    _engine = ThreadedEngine()
                except RuntimeError:
                    _engine = NaiveEngine()
        return _engine
