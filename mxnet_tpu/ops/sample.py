"""Sampling ops (reference: src/operator/tensor/sample_op.cc —
uniform/normal/gamma/exponential/poisson/negative_binomial/generalized_nb,
plus multinomial in sample_multinomial_op).

TPU-native randomness: each stochastic op consumes an explicit threefry key from
``OpContext.rng`` (split by the caller per invocation) instead of the reference's
per-device stateful RNG resource (src/resource.cc:158). Inside compiled graphs
the key is a real operand, so compiled training steps stay pure and replayable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import Param, register


def _shape_dtype(attrs):
    shape = attrs["shape"] or ()
    dt = attrs.get("dtype") or np.float32
    return shape, dt


def _reg_sampler(name, draw, params, aliases=()):
    @register(
        name,
        arg_names=(),
        params=dict(params, shape=Param.shape(()), dtype=Param.dtype(None)),
        stochastic=True,
        alias=aliases,
    )
    def _fwd(octx, attrs, args, auxs, _draw=draw):
        shape, dt = _shape_dtype(attrs)
        return [jax.lax.stop_gradient(_draw(octx.rng, attrs, shape, dt))], []

    return _fwd


_reg_sampler(
    "_random_uniform",
    lambda key, attrs, shape, dt: jax.random.uniform(
        key, shape, dtype=dt, minval=attrs["low"], maxval=attrs["high"]
    ),
    {"low": Param.float(0.0), "high": Param.float(1.0)},
    aliases=("random_uniform", "uniform"),
)

_reg_sampler(
    "_random_normal",
    lambda key, attrs, shape, dt: attrs["loc"]
    + attrs["scale"] * jax.random.normal(key, shape, dtype=dt),
    {"loc": Param.float(0.0), "scale": Param.float(1.0)},
    aliases=("random_normal", "normal"),
)

_reg_sampler(
    "_random_gamma",
    lambda key, attrs, shape, dt: attrs["beta"]
    * jax.random.gamma(key, attrs["alpha"], shape, dtype=dt),
    {"alpha": Param.float(1.0), "beta": Param.float(1.0)},
    aliases=("random_gamma",),
)

_reg_sampler(
    "_random_exponential",
    lambda key, attrs, shape, dt: jax.random.exponential(key, shape, dtype=dt) / attrs["lam"],
    {"lam": Param.float(1.0)},
    aliases=("random_exponential",),
)

_reg_sampler(
    "_random_poisson",
    lambda key, attrs, shape, dt: jax.random.poisson(key, attrs["lam"], shape).astype(dt),
    {"lam": Param.float(1.0)},
    aliases=("random_poisson",),
)

_reg_sampler(
    "_random_negative_binomial",
    lambda key, attrs, shape, dt: _neg_binomial(key, attrs["k"], attrs["p"], shape).astype(dt),
    {"k": Param.int(1), "p": Param.float(1.0)},
    aliases=("random_negative_binomial",),
)

_reg_sampler(
    "_random_randint",
    lambda key, attrs, shape, dt: jax.random.randint(
        key, shape, int(attrs["low"]), int(attrs["high"])
    ).astype(dt if dt is not None else np.int32),
    {"low": Param.float(0.0), "high": Param.float(1.0)},
    aliases=("random_randint",),
)


def _neg_binomial(key, k, p, shape):
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, shape) * ((1 - p) / p)
    return jax.random.poisson(k2, lam, shape)


# ------------------------------------------------------------- multisample
# One draw-set PER ROW of NDArray distribution parameters (reference:
# src/operator/tensor/multisample_op.cc — sample_uniform(low=arr, high=arr,
# shape=S) -> arr.shape + S). vmap over the parameter rows with split keys.
def _reg_multisampler(name, arg_names, draw):
    @register(
        name,
        arg_names=tuple(arg_names),
        params={"shape": Param.shape(()), "dtype": Param.dtype(None)},
        stochastic=True,
        alias=(name.lstrip("_"),),
    )
    def _fwd(octx, attrs, args, auxs, _draw=draw):
        shape, dt = _shape_dtype(attrs)
        pshape = args[0].shape
        flat = [a.reshape(-1).astype(jnp.float32) for a in args]
        keys = jax.random.split(octx.rng, flat[0].shape[0])
        out = jax.vmap(lambda k, *ps: _draw(k, ps, shape, dt))(keys, *flat)
        return [jax.lax.stop_gradient(out.reshape(pshape + tuple(shape)))], []

    def _infer(attrs, in_shapes, aux_shapes, _n=len(arg_names)):
        p = next((s for s in in_shapes if s is not None), None)
        if p is None:
            raise ValueError("%s: parameter shape required" % name)
        out = tuple(p) + tuple(attrs["shape"] or ())
        return [tuple(p)] * _n, [out], []

    from .registry import get_op

    get_op(name)._infer_shape = _infer
    return _fwd


_reg_multisampler(
    "_sample_uniform", ("low", "high"),
    lambda k, ps, s, dt: jax.random.uniform(k, s, minval=ps[0], maxval=ps[1]).astype(dt or np.float32),
)
_reg_multisampler(
    "_sample_normal", ("mu", "sigma"),
    lambda k, ps, s, dt: (ps[0] + ps[1] * jax.random.normal(k, s)).astype(dt or np.float32),
)
_reg_multisampler(
    "_sample_gamma", ("alpha", "beta"),
    lambda k, ps, s, dt: (ps[1] * jax.random.gamma(k, ps[0], s)).astype(dt or np.float32),
)
_reg_multisampler(
    "_sample_exponential", ("lam",),
    lambda k, ps, s, dt: (jax.random.exponential(k, s) / ps[0]).astype(dt or np.float32),
)
_reg_multisampler(
    "_sample_poisson", ("lam",),
    lambda k, ps, s, dt: jax.random.poisson(k, ps[0], s).astype(dt or np.float32),
)
_reg_multisampler(
    "_sample_negative_binomial", ("k", "p"),
    lambda k, ps, s, dt: _neg_binomial(k, ps[0], ps[1], s).astype(dt or np.float32),
)


@register(
    "_sample_multinomial",
    arg_names=("data",),
    params={"shape": Param.shape(()), "get_prob": Param.bool(False), "dtype": Param.dtype(None)},
    stochastic=True,
    num_outputs=lambda attrs: 2 if attrs.get("get_prob") else 1,
    alias=("sample_multinomial",),
)
def _multinomial(octx, attrs, args, auxs):
    probs = args[0]
    shape = attrs["shape"] or ()
    n = int(np.prod(shape)) if shape else 1
    logits = jnp.log(jnp.maximum(probs, 1e-37))
    if probs.ndim == 1:
        draw = jax.random.categorical(octx.rng, logits, shape=(n,)).reshape(shape or ())
    else:
        draw = jax.random.categorical(octx.rng, logits[:, None, :], axis=-1, shape=(probs.shape[0], n))
        draw = draw.reshape((probs.shape[0],) + (shape or ()))
    dt = attrs.get("dtype") or np.int32
    outs = [jax.lax.stop_gradient(draw.astype(dt))]
    if attrs["get_prob"]:
        if probs.ndim == 1:
            lp = jnp.log(jnp.maximum(probs, 1e-37))[draw]
        else:
            lp = jnp.take_along_axis(
                jnp.log(jnp.maximum(probs, 1e-37)), draw.reshape(probs.shape[0], -1), axis=1
            ).reshape(outs[0].shape)
        outs.append(lp)
    return outs, []
