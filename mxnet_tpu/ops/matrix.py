"""Matrix / shape-manipulation ops.

Reference: src/operator/tensor/matrix_op.cc + matrix_op-inl.h (the 11k-LoC family:
Reshape/Flatten/transpose/dot/batch_dot/slice/clip/repeat/tile/reverse, SURVEY §2.3)
plus the layer-style shape ops Concat (src/operator/concat.cc), SliceChannel
(slice_channel.cc), SwapAxis (swapaxis.cc), Crop (crop.cc), Pad (pad.cc).

dot/batch_dot are the MXU entry points: they lower to a single XLA dot_general
with a configurable accumulation type (fp32 accumulation for bf16 inputs —
the TPU-native version of the reference's pseudo-fp16, convolution.cu:30-45).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, parse_shape
from .registry import Param, fp32_precision, register, register_simple


# ---- reshape with MXNet's special codes (matrix_op-inl.h ReshapeParam) ------
def mx_reshape(shape, target, reverse=False):
    """Implement MXNet Reshape's 0/-1/-2/-3/-4 codes on a concrete shape."""
    src = list(shape)
    if reverse:
        src = src[::-1]
        target = tuple(reversed(target))
    out = []
    src_i = 0
    i = 0
    target = list(target)
    while i < len(target):
        t = target[i]
        if t == 0:
            out.append(src[src_i])
            src_i += 1
        elif t == -1:
            out.append(-1)
            src_i += 1
        elif t == -2:
            out.extend(src[src_i:])
            src_i = len(src)
        elif t == -3:
            out.append(src[src_i] * src[src_i + 1])
            src_i += 2
        elif t == -4:
            d1, d2 = target[i + 1], target[i + 2]
            cur = src[src_i]
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2])
            src_i += 1
            i += 2
        else:
            out.append(t)
            src_i += 1
        i += 1
    if -1 in out:
        known = int(np.prod([d for d in out if d != -1])) if len(out) > 1 else 1
        total = int(np.prod(shape)) if shape else 1
        out[out.index(-1)] = total // max(known, 1)
    if reverse:
        out = out[::-1]
    return tuple(int(d) for d in out)


def _reshape(attrs, x):
    target = attrs["shape"]
    if target is None or target == ():
        # legacy target_shape attr
        ts = attrs.get("target_shape")
        if ts:
            return jnp.reshape(x, ts)
        raise MXNetError("Reshape: shape required")
    return jnp.reshape(x, mx_reshape(x.shape, target, attrs["reverse"]))


register_simple(
    "Reshape",
    _reshape,
    arg_names=("data",),
    params={
        "shape": Param.shape(()),
        "reverse": Param.bool(False),
        "target_shape": Param.shape(()),
        "keep_highest": Param.bool(False),
    },
    alias=("reshape",),
)

register_simple(
    "Flatten",
    lambda attrs, x: jnp.reshape(x, (x.shape[0], -1)),
    arg_names=("data",),
    alias=("flatten",),
)


def _transpose(attrs, x):
    axes = attrs["axes"]
    if axes is None or axes == ():
        axes = tuple(reversed(range(x.ndim)))
    return jnp.transpose(x, axes)


register_simple(
    "transpose", _transpose, arg_names=("data",), params={"axes": Param.shape(())}
)

register_simple(
    "expand_dims",
    lambda attrs, x: jnp.expand_dims(x, attrs["axis"]),
    arg_names=("data",),
    params={"axis": Param.int()},
)


def _swapaxis(attrs, x):
    return jnp.swapaxes(x, attrs["dim1"], attrs["dim2"])


register_simple(
    "SwapAxis",
    _swapaxis,
    arg_names=("data",),
    params={"dim1": Param.int(0), "dim2": Param.int(0)},
    alias=("swapaxes",),
)


# ---- dot family (matrix_op-inl.h DotForward / BatchDotForward) -------------
def _dot(attrs, lhs, rhs):
    ta, tb = attrs["transpose_a"], attrs["transpose_b"]
    a = lhs.T if ta and lhs.ndim == 2 else (jnp.transpose(lhs) if ta else lhs)
    b = rhs.T if tb and rhs.ndim == 2 else (jnp.transpose(rhs) if tb else rhs)
    # fp32 inputs contract at HIGHEST (TPU's DEFAULT silently drops fp32
    # matmuls to bf16); low-precision inputs keep the native fast path
    prec = fp32_precision(a.dtype)
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b, precision=prec)
    return jnp.dot(a, b, precision=prec, preferred_element_type=_acc_type(a.dtype))


def _acc_type(dt):
    dt = np.dtype(dt)
    if dt in (np.dtype(np.float16), np.dtype(jnp.bfloat16)):
        return np.float32
    return None


def _batch_dot(attrs, lhs, rhs):
    ta, tb = attrs["transpose_a"], attrs["transpose_b"]
    a = jnp.swapaxes(lhs, -1, -2) if ta else lhs
    b = jnp.swapaxes(rhs, -1, -2) if tb else rhs
    return jnp.matmul(a, b, precision=fp32_precision(a.dtype),
                      preferred_element_type=_acc_type(a.dtype))


register_simple(
    "dot",
    _dot,
    arg_names=("lhs", "rhs"),
    params={"transpose_a": Param.bool(False), "transpose_b": Param.bool(False)},
)
register_simple(
    "batch_dot",
    _batch_dot,
    arg_names=("lhs", "rhs"),
    params={"transpose_a": Param.bool(False), "transpose_b": Param.bool(False)},
    alias=("linalg_gemm2",),
)


# ---- slicing (matrix_op-inl.h SliceParam / SliceAxis) ----------------------
def _slice(attrs, x):
    begin, end = attrs["begin"], attrs["end"]
    idx = []
    for i in range(x.ndim):
        b = begin[i] if i < len(begin) and begin[i] is not None else 0
        e = end[i] if i < len(end) and end[i] is not None else x.shape[i]
        idx.append(slice(b, e))
    return x[tuple(idx)]


def _parse_shape_opt(v):
    """Parse shapes that may contain None entries: (None, 2)."""
    if v is None:
        return ()
    if isinstance(v, (tuple, list)):
        return tuple(None if e is None else int(e) for e in v)
    s = str(v).strip().strip("()[]")
    if not s:
        return ()
    return tuple(None if tok.strip() == "None" else int(float(tok)) for tok in s.split(","))


register_simple(
    "slice",
    _slice,
    arg_names=("data",),
    params={"begin": Param(_parse_shape_opt), "end": Param(_parse_shape_opt)},
    alias=("crop_like_slice",),
)


def _slice_axis(attrs, x):
    ax = attrs["axis"] % x.ndim
    b = attrs["begin"]
    e = attrs["end"]
    if e is None:
        e = x.shape[ax]
    if b < 0:
        b += x.shape[ax]
    if e < 0:
        e += x.shape[ax]
    sl = [slice(None)] * x.ndim
    sl[ax] = slice(b, e)
    return x[tuple(sl)]


register_simple(
    "slice_axis",
    _slice_axis,
    arg_names=("data",),
    params={
        "axis": Param.int(),
        "begin": Param.int(0),
        "end": Param(lambda v: None if v in (None, "None", "") else int(float(v)), None),
    },
)


def _reverse(attrs, x):
    axes = attrs["axis"] if isinstance(attrs["axis"], tuple) else (attrs["axis"],)
    return jnp.flip(x, axes)


register_simple(
    "reverse", _reverse, arg_names=("data",), params={"axis": Param.shape(())}, alias=("flip",)
)


def _tile(attrs, x):
    return jnp.tile(x, attrs["reps"])


register_simple("tile", _tile, arg_names=("data",), params={"reps": Param.shape()})


def _repeat(attrs, x):
    ax = attrs["axis"]
    return jnp.repeat(x, attrs["repeats"], axis=ax)


register_simple(
    "repeat",
    _repeat,
    arg_names=("data",),
    params={
        "repeats": Param.int(),
        "axis": Param(lambda v: None if v in (None, "None", "") else int(float(v)), None),
    },
)


# ---- concat / split (concat.cc:81 MXNET_REGISTER_OP_PROPERTY(Concat);
# slice_channel.cc SliceChannel) --------------------------------------------
@register(
    "Concat",
    arg_names=lambda attrs: ["arg%d" % i for i in range(int(attrs.get("num_args", 1)))],
    params={"num_args": Param.int(1), "dim": Param.int(1)},
    key_var_num_args="num_args",
    alias=("concat",),
)
def _concat(octx, attrs, args, auxs):
    return [jnp.concatenate(args, axis=attrs["dim"])], []


@register(
    "SliceChannel",
    arg_names=("data",),
    params={"num_outputs": Param.int(), "axis": Param.int(1), "squeeze_axis": Param.bool(False)},
    num_outputs=lambda attrs: int(attrs["num_outputs"]),
    output_names=lambda attrs: ["output%d" % i for i in range(int(attrs["num_outputs"]))],
    alias=("split",),
)
def _slice_channel(octx, attrs, args, auxs):
    x = args[0]
    parts = jnp.split(x, attrs["num_outputs"], axis=attrs["axis"])
    if attrs["squeeze_axis"]:
        parts = [jnp.squeeze(p, axis=attrs["axis"]) for p in parts]
    return list(parts), []


def _stack(attrs, *args):
    return jnp.stack(args, axis=attrs["axis"])


@register(
    "stack",
    arg_names=lambda attrs: ["arg%d" % i for i in range(int(attrs.get("num_args", 1)))],
    params={"num_args": Param.int(1), "axis": Param.int(0)},
    key_var_num_args="num_args",
)
def _stack_op(octx, attrs, args, auxs):
    return [jnp.stack(args, axis=attrs["axis"])], []


# ---- Pad (pad.cc — edge/constant/reflect on 4d/5d) -------------------------
def _pad(attrs, x):
    pw = attrs["pad_width"]
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(x.ndim)]
    mode = attrs["mode"]
    if mode == "constant":
        return jnp.pad(x, pairs, mode="constant", constant_values=attrs["constant_value"])
    if mode == "edge":
        return jnp.pad(x, pairs, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pairs, mode="reflect")
    raise MXNetError("Pad: unknown mode %s" % mode)


register_simple(
    "Pad",
    _pad,
    arg_names=("data",),
    params={
        "pad_width": Param.shape(),
        "mode": Param.str("constant"),
        "constant_value": Param.float(0.0),
    },
    alias=("pad",),
)


# ---- Crop (crop.cc: crop h/w of src to match shape or ref symbol) ----------
@register(
    "Crop",
    arg_names=lambda attrs: ["arg%d" % i for i in range(int(attrs.get("num_args", 1)))],
    params={
        "num_args": Param.int(1),
        "offset": Param.shape((0, 0)),
        "h_w": Param.shape((0, 0)),
        "center_crop": Param.bool(False),
    },
    key_var_num_args="num_args",
)
def _crop(octx, attrs, args, auxs):
    x = args[0]
    if len(args) == 2:
        th, tw = args[1].shape[2], args[1].shape[3]
    else:
        th, tw = attrs["h_w"]
    if attrs["center_crop"]:
        oh = (x.shape[2] - th) // 2
        ow = (x.shape[3] - tw) // 2
    else:
        oh, ow = attrs["offset"]
    return [x[:, :, oh : oh + th, ow : ow + tw]], []


# ---- slice assignment (matrix_op.cc:258 _slice_assign / :283 _crop_assign_scalar)
def _region(attrs, shape):
    begin, end = attrs["begin"], attrs["end"]
    idx = []
    for i in range(len(shape)):
        b = begin[i] if i < len(begin) and begin[i] is not None else 0
        e = end[i] if i < len(end) and end[i] is not None else shape[i]
        idx.append(slice(b, e))
    return tuple(idx)


register_simple(
    "_slice_assign",
    lambda attrs, lhs, rhs: lhs.at[_region(attrs, lhs.shape)].set(rhs.astype(lhs.dtype)),
    arg_names=("lhs", "rhs"),
    params={"begin": Param(_parse_shape_opt), "end": Param(_parse_shape_opt)},
    alias=("_crop_assign",),
)

register_simple(
    "_crop_assign_scalar",
    lambda attrs, x: x.at[_region(attrs, x.shape)].set(np.asarray(attrs["scalar"], x.dtype)),
    arg_names=("data",),
    params={
        "begin": Param(_parse_shape_opt),
        "end": Param(_parse_shape_opt),
        "scalar": Param.float(0.0),
    },
    alias=("_slice_assign_scalar",),
)


# ---- where (control_flow.cc) ----------------------------------------------
register_simple(
    "where",
    lambda attrs, cond, x, y: jnp.where(cond.astype(bool), x, y),
    arg_names=("condition", "x", "y"),
)

# ---- diag/eye-ish helpers used by tests ------------------------------------
register_simple(
    "squeeze",
    lambda attrs, x: jnp.squeeze(x, axis=attrs["axis"] if attrs["axis"] != () else None),
    arg_names=("data",),
    params={"axis": Param.shape(())},
)
