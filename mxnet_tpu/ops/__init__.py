"""Operator library: importing this package registers every op.

The registry (registry.py) is the single registration seam — the analog of the
reference's NNVM op registry consumed by both the imperative path
(src/c_api/c_api_ndarray.cc MXImperativeInvoke) and the symbolic path
(src/executor/graph_executor.cc). ~300 names registered across the modules below.
"""
from . import elemwise  # noqa: F401
from . import reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import init_ops  # noqa: F401
from . import sample  # noqa: F401
from . import indexing  # noqa: F401
from . import ordering  # noqa: F401
from . import nn  # noqa: F401
from . import loss  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import contrib_ops  # noqa: F401
from . import spatial  # noqa: F401
from . import attention  # noqa: F401
from .registry import OpContext, Operator, get_op, list_ops, register, register_simple  # noqa: F401
