"""Neural-network layer ops.

Reference: the legacy-property layer family in src/operator/*.cc —
FullyConnected (fully_connected-inl.h:60-120), Convolution (convolution-inl.h,
im2col), Deconvolution, Pooling (nn/pool.h), Activation, LeakyReLU, BatchNorm
(batch_norm.cc/.cu), Dropout, LRN, InstanceNorm, L2Normalization, UpSampling,
SequenceLast/Mask/Reverse — each a hand-written Forward/Backward pair, with cuDNN
fast paths (src/operator/cudnn_*.h).

TPU design: every layer is one traced jax expression lowered to XLA conv/dot/
reduce-window HLOs that map directly onto the MXU (conv/dot) and VPU
(elementwise). Backward is autodiff — the hand Backward kernels and the
cuDNN-vs-mshadow dual path disappear; XLA's conv transpose IS the gradient.
Aux-state mutation (BatchNorm moving stats, FMutateInputs in the reference)
is explicit: auxs in, updated auxs out.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import OpContext, Param, fp32_precision, register, register_simple


def _conv_dims(kernel):
    return len(kernel)


# ---------------------------------------------------------------- FullyConnected
@register(
    "FullyConnected",
    arg_names=lambda attrs: ["data", "weight"] + ([] if attrs.get("no_bias") else ["bias"]),
    params={
        "num_hidden": Param.int(),
        "no_bias": Param.bool(False),
        "flatten": Param.bool(True),
    },
)
def _fully_connected(octx, attrs, args, auxs):
    data, weight = args[0], args[1]
    if attrs["flatten"]:
        x = data.reshape(data.shape[0], -1)
    else:
        x = data
    # No preferred_element_type: the MXU accumulates bf16 dots in fp32
    # natively, and this JAX version's conv/dot transpose rules reject a
    # widened cotangent dtype under vjp.
    out = jnp.dot(x, weight.T, precision=fp32_precision(x.dtype))
    if not attrs["no_bias"]:
        out = out + args[2]
    return [out], []


def _fc_infer_shape(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    if data is None:
        raise MXNetError("FullyConnected: data shape required")
    nh = attrs["num_hidden"]
    if attrs["flatten"]:
        in_dim = int(np.prod(data[1:]))
        out = (data[0], nh)
    else:
        in_dim = data[-1]
        out = tuple(data[:-1]) + (nh,)
    shapes = [tuple(data), (nh, in_dim)]
    if not attrs["no_bias"]:
        shapes.append((nh,))
    return shapes, [out], []


from .registry import get_op  # noqa: E402

get_op("FullyConnected")._infer_shape = _fc_infer_shape


# ---------------------------------------------------------------- Convolution
_CONV_PARAMS = {
    "kernel": Param.shape(),
    "stride": Param.shape(()),
    "dilate": Param.shape(()),
    "pad": Param.shape(()),
    "num_filter": Param.int(),
    "num_group": Param.int(1),
    "no_bias": Param.bool(False),
    "workspace": Param.int(1024),  # accepted+ignored: XLA owns scratch memory
    "cudnn_tune": Param.str(""),
    "cudnn_off": Param.bool(False),
    "layout": Param.str("None"),
}


def _conv_tuples(attrs, nd):
    stride = attrs["stride"] or (1,) * nd
    dilate = attrs["dilate"] or (1,) * nd
    pad = attrs["pad"] or (0,) * nd
    return stride, dilate, pad


def _conv_layout(attrs, nd):
    """Resolve the conv layout attr: channel-first reference default, or NHWC
    (2-d only; the reference exposes the same layout parameter,
    convolution-inl.h ConvolutionParam::layout)."""
    layout = attrs.get("layout") or "None"
    if layout in ("None", ""):
        return "NC" + "DHW"[3 - nd:]
    if layout == "NHWC":
        if nd != 2:
            raise MXNetError("layout=NHWC is 2-d only")
        return "NHWC"
    if layout in ("NCW", "NCHW", "NCDHW"):
        return layout
    raise MXNetError("Convolution: unsupported layout %s" % layout)


def _conv_dn(nd, layout=None):
    # channel-first (reference default, convolution-inl.h) or NHWC with OHWI
    # kernels (the reference's NHWC weight layout)
    if layout == "NHWC":
        return jax.lax.conv_dimension_numbers(
            (1,) * 4, (1,) * 4, ("NHWC", "OHWI", "NHWC")
        )
    sp = "DHW"[3 - nd :]
    return jax.lax.conv_dimension_numbers(
        (1, 1) + (1,) * nd, (1, 1) + (1,) * nd, ("NC" + sp, "OI" + sp, "NC" + sp)
    )


@register(
    "Convolution",
    arg_names=lambda attrs: ["data", "weight"] + ([] if attrs.get("no_bias") else ["bias"]),
    params=dict(_CONV_PARAMS),
    alias=("Convolution_v1",),
)
def _convolution(octx, attrs, args, auxs):
    data, weight = args[0], args[1]
    nd = _conv_dims(attrs["kernel"])
    stride, dilate, pad = _conv_tuples(attrs, nd)
    layout = _conv_layout(attrs, nd)
    out = jax.lax.conv_general_dilated(
        data,
        weight,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        rhs_dilation=dilate,
        dimension_numbers=_conv_dn(nd, layout),
        feature_group_count=attrs["num_group"],
        precision=fp32_precision(data.dtype),
    )
    if not attrs["no_bias"]:
        bias = args[2]
        bshape = ((1,) * (nd + 1) + (-1,)) if layout == "NHWC" else ((1, -1) + (1,) * nd)
        out = out + bias.reshape(bshape)
    return [out], []


def _conv_out_dim(x, k, s, p, d):
    return (x + 2 * p - (d * (k - 1) + 1)) // s + 1


def _conv_infer_shape(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    if data is None:
        raise MXNetError("Convolution: data shape required")
    nd = _conv_dims(attrs["kernel"])
    stride, dilate, pad = _conv_tuples(attrs, nd)
    nf, ng = attrs["num_filter"], attrs["num_group"]
    layout = _conv_layout(attrs, nd)
    if layout == "NHWC":
        wshape = (nf,) + tuple(attrs["kernel"]) + (data[-1] // ng,)
        spatial = tuple(
            _conv_out_dim(data[1 + i], attrs["kernel"][i], stride[i], pad[i], dilate[i])
            for i in range(nd)
        )
        out = (data[0],) + spatial + (nf,)
    else:
        wshape = (nf, data[1] // ng) + tuple(attrs["kernel"])
        spatial = tuple(
            _conv_out_dim(data[2 + i], attrs["kernel"][i], stride[i], pad[i], dilate[i])
            for i in range(nd)
        )
        out = (data[0], nf) + spatial
    shapes = [tuple(data), wshape] + ([] if attrs["no_bias"] else [(nf,)])
    return shapes, [out], []


get_op("Convolution")._infer_shape = _conv_infer_shape


# ---------------------------------------------------------------- Deconvolution
_DECONV_PARAMS = dict(_CONV_PARAMS)
_DECONV_PARAMS.update({"adj": Param.shape(()), "target_shape": Param.shape(())})


@register(
    "Deconvolution",
    arg_names=lambda attrs: ["data", "weight"] + ([] if attrs.get("no_bias") else ["bias"]),
    params=_DECONV_PARAMS,
)
def _deconvolution(octx, attrs, args, auxs):
    data, weight = args[0], args[1]
    if (attrs.get("layout") or "None") not in ("None", "", "NCW", "NCHW", "NCDHW"):
        raise MXNetError("Deconvolution: only channel-first layouts supported")
    nd = _conv_dims(attrs["kernel"])
    stride, dilate, pad = _conv_tuples(attrs, nd)
    # Gradient-of-conv semantics (the reference implements deconv as conv
    # backward-data, deconvolution-inl.h): lhs dilation by stride, flipped
    # effective padding.
    pads = [
        (dilate[i] * (attrs["kernel"][i] - 1) - pad[i], dilate[i] * (attrs["kernel"][i] - 1) - pad[i] + (attrs["adj"][i] if attrs["adj"] else 0))
        for i in range(nd)
    ]
    # MXNet deconv weight layout is (C_in, nf/ng, k...) with groups laid out
    # along C_in; XLA's feature_group_count wants rhs (I=C_in/ng, O=nf) with
    # groups along O — relayout when grouped (deconvolution-inl.h contract)
    ng = attrs["num_group"]
    if ng > 1:
        cin, nf_pg = weight.shape[0], weight.shape[1]
        w = weight.reshape((ng, cin // ng, nf_pg) + weight.shape[2:])
        w = jnp.moveaxis(w, 0, 1)  # (cin_pg, ng, nf_pg, k...)
        weight = w.reshape((cin // ng, ng * nf_pg) + weight.shape[2:])
    sp = "DHW"[3 - nd :]
    dn = jax.lax.conv_dimension_numbers(
        data.shape, weight.shape, ("NC" + sp, "IO" + sp, "NC" + sp)
    )
    out = jax.lax.conv_general_dilated(
        data,
        jnp.flip(weight, axis=tuple(range(2, 2 + nd))),
        window_strides=(1,) * nd,
        padding=pads,
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=dn,
        feature_group_count=attrs["num_group"],
        precision=fp32_precision(data.dtype),
    )
    if not attrs["no_bias"]:
        out = out + args[2].reshape((1, -1) + (1,) * nd)
    return [out], []


def _deconv_infer_shape(attrs, in_shapes, aux_shapes):
    if (attrs.get("layout") or "None") not in ("None", "", "NCW", "NCHW", "NCDHW"):
        raise MXNetError("Deconvolution: only channel-first layouts supported")
    data = in_shapes[0]
    nd = _conv_dims(attrs["kernel"])
    stride, dilate, pad = _conv_tuples(attrs, nd)
    nf, ng = attrs["num_filter"], attrs["num_group"]
    adj = attrs["adj"] or (0,) * nd
    wshape = (data[1], nf // ng) + tuple(attrs["kernel"])
    spatial = tuple(
        (data[2 + i] - 1) * stride[i] - 2 * pad[i] + (dilate[i] * (attrs["kernel"][i] - 1) + 1) + adj[i]
        for i in range(nd)
    )
    out = (data[0], nf) + spatial
    shapes = [tuple(data), wshape] + ([] if attrs["no_bias"] else [(nf,)])
    return shapes, [out], []


get_op("Deconvolution")._infer_shape = _deconv_infer_shape


# ---------------------------------------------------------------- Pooling
def _pool_layout(attrs, nd):
    """Same validation contract as _conv_layout: channel-first default,
    NHWC (2-d only), loud error on anything else."""
    layout = attrs.get("layout") or "None"
    if layout in ("None", ""):
        return "NC" + "DHW"[3 - nd:]
    if layout == "NHWC":
        if nd != 2:
            raise MXNetError("Pooling: layout=NHWC is 2-d only")
        return "NHWC"
    if layout in ("NCW", "NCHW", "NCDHW"):
        return layout
    raise MXNetError("Pooling: unsupported layout %s" % layout)


@register(
    "Pooling",
    arg_names=("data",),
    params={
        "kernel": Param.shape(()),
        "pool_type": Param.str("max"),
        "global_pool": Param.bool(False),
        "stride": Param.shape(()),
        "pad": Param.shape(()),
        "pooling_convention": Param.str("valid"),
        "cudnn_off": Param.bool(False),
        "layout": Param.str("None"),
    },
    alias=("Pooling_v1",),
)
def _pooling(octx, attrs, args, auxs):
    x = args[0]
    nd = x.ndim - 2
    nhwc = _pool_layout(attrs, nd) == "NHWC"
    sp0 = 1 if nhwc else 2  # first spatial dim index
    if attrs["global_pool"]:
        kernel = x.shape[sp0:sp0 + nd]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = attrs["kernel"]
        stride = attrs["stride"] or (1,) * nd
        pad = attrs["pad"] or (0,) * nd
    pads = []
    for i in range(nd):
        extra = 0
        if attrs["pooling_convention"] == "full" and not attrs["global_pool"]:
            h = x.shape[sp0 + i]
            out_full = -(-(h + 2 * pad[i] - kernel[i]) // stride[i]) + 1  # ceil
            extra = max(0, (out_full - 1) * stride[i] + kernel[i] - h - 2 * pad[i])
        pads.append((pad[i], pad[i] + extra))
    if nhwc:
        window = (1,) + tuple(kernel) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        padding = [(0, 0)] + pads + [(0, 0)]
    else:
        window = (1, 1) + tuple(kernel)
        strides = (1, 1) + tuple(stride)
        padding = [(0, 0), (0, 0)] + pads
    pt = attrs["pool_type"]
    # NOTE: init must be a concrete scalar (python/np), not a jnp array — the
    # monoid pattern-match that routes to the differentiable reduce_window_max/
    # sum primitives fails on tracer inits under jit.
    if pt == "max":
        init = -np.inf if jnp.issubdtype(x.dtype, jnp.floating) else np.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides, padding)
    elif pt in ("avg", "sum"):
        zero = np.array(0, x.dtype).item() if not jnp.issubdtype(x.dtype, jnp.floating) else 0.0
        s = jax.lax.reduce_window(x, zero, jax.lax.add, window, strides, padding)
        if pt == "avg":
            ones = jnp.ones(x.shape[sp0:sp0 + nd], x.dtype)
            cnt = jax.lax.reduce_window(
                ones, zero, jax.lax.add, tuple(kernel), tuple(stride), pads
            )
            s = s / (cnt[..., None] if nhwc else cnt)
        out = s
    else:
        raise MXNetError("Pooling: unknown pool_type %s" % pt)
    return [out], []


def _pool_infer_shape(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    nd = len(data) - 2
    nhwc = _pool_layout(attrs, nd) == "NHWC"
    sp0 = 1 if nhwc else 2
    if attrs["global_pool"]:
        out = ((data[0],) + (1,) * nd + (data[-1],)) if nhwc             else (tuple(data[:2]) + (1,) * nd)
        return [tuple(data)], [out], []
    kernel = attrs["kernel"]
    stride = attrs["stride"] or (1,) * nd
    pad = attrs["pad"] or (0,) * nd
    sp = []
    for i in range(nd):
        if attrs["pooling_convention"] == "full":
            o = -(-(data[sp0 + i] + 2 * pad[i] - kernel[i]) // stride[i]) + 1
        else:
            o = (data[sp0 + i] + 2 * pad[i] - kernel[i]) // stride[i] + 1
        sp.append(o)
    out = ((data[0],) + tuple(sp) + (data[-1],)) if nhwc         else (tuple(data[:2]) + tuple(sp))
    return [tuple(data)], [out], []


get_op("Pooling")._infer_shape = _pool_infer_shape


# ---------------------------------------------------------------- Activation
@register(
    "Activation",
    arg_names=("data",),
    params={"act_type": Param.str()},
)
def _activation(octx, attrs, args, auxs):
    x = args[0]
    t = attrs["act_type"]
    if t == "relu":
        out = jax.nn.relu(x)
    elif t == "sigmoid":
        out = jax.nn.sigmoid(x)
    elif t == "tanh":
        out = jnp.tanh(x)
    elif t == "softrelu":
        out = jax.nn.softplus(x)
    elif t == "softsign":
        out = jax.nn.soft_sign(x)
    else:
        raise MXNetError("Activation: unknown act_type %s" % t)
    return [out], []


# ---------------------------------------------------------------- LeakyReLU
@register(
    "LeakyReLU",
    arg_names=lambda attrs: ["data", "gamma"] if attrs.get("act_type") == "prelu" else ["data"],
    params={
        "act_type": Param.str("leaky"),
        "slope": Param.float(0.25),
        "lower_bound": Param.float(0.125),
        "upper_bound": Param.float(0.334),
    },
    stochastic=True,  # rrelu needs a key in training
)
def _leaky_relu(octx, attrs, args, auxs):
    x = args[0]
    t = attrs["act_type"]
    if t == "leaky":
        out = jnp.where(x > 0, x, attrs["slope"] * x)
    elif t == "elu":
        out = jnp.where(x > 0, x, attrs["slope"] * (jnp.exp(x) - 1))
    elif t == "prelu":
        gamma = args[1].reshape((1, -1) + (1,) * (x.ndim - 2)) if x.ndim > 1 else args[1]
        out = jnp.where(x > 0, x, gamma * x)
    elif t == "rrelu":
        if octx.is_train and octx.rng is not None:
            slope = jax.random.uniform(
                octx.rng, (x.shape[0],) + (1,) * (x.ndim - 1),
                minval=attrs["lower_bound"], maxval=attrs["upper_bound"], dtype=x.dtype,
            )
        else:
            slope = (attrs["lower_bound"] + attrs["upper_bound"]) / 2.0
        out = jnp.where(x > 0, x, slope * x)
    else:
        raise MXNetError("LeakyReLU: unknown act_type %s" % t)
    return [out], []


def _lrelu_infer_shape(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    shapes = [tuple(data)]
    if attrs.get("act_type") == "prelu":
        shapes.append((data[1],))
    return shapes, [tuple(data)], []


get_op("LeakyReLU")._infer_shape = _lrelu_infer_shape


# ---------------------------------------------------------------- BatchNorm
@register(
    "BatchNorm",
    arg_names=("data", "gamma", "beta"),
    aux_names=("moving_mean", "moving_var"),
    params={
        "eps": Param.float(1e-3),
        "momentum": Param.float(0.9),
        "fix_gamma": Param.bool(True),
        "use_global_stats": Param.bool(False),
        "output_mean_var": Param.bool(False),
        "axis": Param.int(1),
        "cudnn_off": Param.bool(False),
    },
    num_outputs=3,
    num_visible_outputs=lambda attrs: 3 if attrs.get("output_mean_var") else 1,
    output_names=("output", "mean", "var"),
    alias=("BatchNorm_v1",),
)
def _batch_norm(octx, attrs, args, auxs):
    x, gamma, beta = args
    mmean, mvar = auxs
    ax = attrs["axis"] % x.ndim
    red = tuple(i for i in range(x.ndim) if i != ax)
    bshape = tuple(x.shape[ax] if i == ax else 1 for i in range(x.ndim))
    if attrs["fix_gamma"]:
        gamma = jax.lax.stop_gradient(jnp.ones_like(gamma))
    if octx.is_train and not attrs["use_global_stats"]:
        # stats stay fp32 end to end even when the graph runs bf16. Default:
        # one-pass E[x]/E[x^2] with BOTH reductions accumulating fp32 — the
        # squares are converted to fp32 INLINE in the reduce chain (fuses, no
        # materialized fp32 copy), so cancellation only bites beyond
        # |mean|/std ~ 4000 (fp32 mantissa), far outside trained-BN ranges —
        # and x is read ONCE per stat pass instead of twice. The bf16 hazard
        # the two-pass guarded against (squaring in bf16 collapses variance
        # past |mean|/std ~ 20) does not apply with fp32 accumulation.
        # MXNET_TPU_BN_TWOPASS=1 restores the exact centered two-pass.
        from ..base import env_flag

        if env_flag("MXNET_TPU_BN_TWOPASS"):
            mean = jnp.mean(x, axis=red, dtype=jnp.float32)
            centered = x.astype(jnp.float32) - mean.reshape(bshape)
            var = jnp.mean(jnp.square(centered), axis=red)
        else:
            # converts stay INLINE in each reduce chain (single consumer ->
            # fuses; a shared astype would materialize an fp32 copy of x)
            mean = jnp.mean(x, axis=red, dtype=jnp.float32)
            ex2 = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=red)
            var = jnp.maximum(ex2 - jnp.square(mean), 0.0)
        m = attrs["momentum"]
        new_mean = mmean * m + jax.lax.stop_gradient(mean) * (1 - m)
        new_var = mvar * m + jax.lax.stop_gradient(var) * (1 - m)
    else:
        mean, var = mmean, mvar
        new_mean, new_var = mmean, mvar
    # rsqrt in fp32, then normalize in x's dtype so bf16 activations stay
    # bf16 (fp32 stats must not promote the tensor — the next conv requires
    # matching dtypes)
    inv = jax.lax.rsqrt(var.reshape(bshape).astype(jnp.float32) + attrs["eps"]).astype(x.dtype)
    out = ((x - mean.reshape(bshape).astype(x.dtype)) * inv
           * gamma.reshape(bshape).astype(x.dtype)
           + beta.reshape(bshape).astype(x.dtype))
    return [out, mean.astype(x.dtype), var.astype(x.dtype)], [new_mean, new_var]


def _bn_infer_shape(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    c = (data[attrs.get("axis", 1) % len(data)],)
    return [tuple(data), c, c], [tuple(data), c, c], [c, c]


get_op("BatchNorm")._infer_shape = _bn_infer_shape


# ---------------------------------------------------------------- InstanceNorm
@register(
    "InstanceNorm",
    arg_names=("data", "gamma", "beta"),
    params={"eps": Param.float(1e-3)},
)
def _instance_norm(octx, attrs, args, auxs):
    x, gamma, beta = args
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    out = (x - mean) * jax.lax.rsqrt(var + attrs["eps"])
    return [out * gamma.reshape(bshape) + beta.reshape(bshape)], []


def _in_infer_shape(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    c = (data[1],)
    return [tuple(data), c, c], [tuple(data)], []


get_op("InstanceNorm")._infer_shape = _in_infer_shape


# ---------------------------------------------------------------- L2Normalization
@register(
    "L2Normalization",
    arg_names=("data",),
    params={"eps": Param.float(1e-10), "mode": Param.str("instance")},
)
def _l2_normalization(octx, attrs, args, auxs):
    x = args[0]
    mode = attrs["mode"]
    if mode == "instance":
        red = tuple(range(1, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=True) + attrs["eps"])
    elif mode == "channel":
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True) + attrs["eps"])
    elif mode == "spatial":
        red = tuple(range(2, x.ndim))
        norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=red, keepdims=True) + attrs["eps"])
    else:
        raise MXNetError("L2Normalization: unknown mode %s" % mode)
    return [x / norm], []


# ---------------------------------------------------------------- LRN
@register(
    "LRN",
    arg_names=("data",),
    params={
        "alpha": Param.float(1e-4),
        "beta": Param.float(0.75),
        "knorm": Param.float(2.0),
        "nsize": Param.int(),
    },
    num_outputs=2,
    num_visible_outputs=1,
    output_names=("output", "tmp_norm"),
)
def _lrn(octx, attrs, args, auxs):
    x = args[0]
    n = attrs["nsize"]
    half = n // 2
    sq = jnp.square(x)
    ssum = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add,
        (1, n, 1, 1), (1, 1, 1, 1), [(0, 0), (half, half), (0, 0), (0, 0)],
    )
    norm = jnp.power(attrs["knorm"] + (attrs["alpha"] / n) * ssum, -attrs["beta"])
    return [x * norm, norm], []


# ---------------------------------------------------------------- Dropout
@register(
    "Dropout",
    arg_names=("data",),
    params={"p": Param.float(0.5), "mode": Param.str("training")},
    stochastic=True,
    num_outputs=2,
    num_visible_outputs=1,
    output_names=("output", "mask"),
)
def _dropout(octx, attrs, args, auxs):
    x = args[0]
    p = attrs["p"]
    apply = octx.is_train or attrs["mode"] == "always"
    if not apply or p <= 0.0 or octx.rng is None:
        return [x, jnp.ones_like(x)], []
    keep = 1.0 - p
    mask = jax.random.bernoulli(octx.rng, keep, x.shape).astype(x.dtype) / keep
    mask = jax.lax.stop_gradient(mask)
    return [x * mask, mask], []


# ---------------------------------------------------------------- softmax family
def _softmax_axis(attrs, x):
    return jax.nn.softmax(x, axis=attrs["axis"])


register_simple(
    "softmax", _softmax_axis, arg_names=("data",), params={"axis": Param.int(-1), "temperature": Param.float(1.0)}
)
register_simple(
    "log_softmax",
    lambda attrs, x: jax.nn.log_softmax(x, axis=attrs["axis"]),
    arg_names=("data",),
    params={"axis": Param.int(-1), "temperature": Param.float(1.0)},
)


@register(
    "SoftmaxActivation",
    arg_names=("data",),
    params={"mode": Param.str("instance")},
)
def _softmax_activation(octx, attrs, args, auxs):
    x = args[0]
    if attrs["mode"] == "channel":
        return [jax.nn.softmax(x, axis=1)], []
    return [jax.nn.softmax(x.reshape(x.shape[0], -1), axis=-1).reshape(x.shape)], []


# ---------------------------------------------------------------- UpSampling
@register(
    "UpSampling",
    arg_names=lambda attrs: (
        ["arg%d" % i for i in range(int(attrs.get("num_args", 1)))]
        if attrs.get("sample_type") == "nearest"
        else ["data", "weight"]
    ),
    params={
        "scale": Param.int(),
        "num_filter": Param.int(0),
        "sample_type": Param.str("nearest"),
        "multi_input_mode": Param.str("concat"),
        "num_args": Param.int(1),
        "workspace": Param.int(512),
    },
    key_var_num_args="num_args",
)
def _upsampling(octx, attrs, args, auxs):
    s = attrs["scale"]
    if attrs["sample_type"] == "nearest":
        ups = []
        target = None
        for x in args:
            u = jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3) if target is None else x
            if target is None:
                target = u.shape[2:]
            else:
                scale_i = target[0] // x.shape[2]
                u = jnp.repeat(jnp.repeat(x, scale_i, axis=2), scale_i, axis=3)
            ups.append(u)
        if len(ups) == 1:
            return [ups[0]], []
        if attrs["multi_input_mode"] == "sum":
            out = ups[0]
            for u in ups[1:]:
                out = out + u
            return [out], []
        return [jnp.concatenate(ups, axis=1)], []
    # bilinear: deconvolution with provided weight (reference wires UpSampling
    # bilinear through DeconvolutionOp, upsampling-inl.h)
    x, w = args
    k = 2 * s - s % 2
    p = (k - s) // 2  # integer pad chosen so out = in*scale
    deconv_attrs = {
        "kernel": (k, k), "stride": (s, s), "pad": (p, p), "adj": (s % 2, s % 2),
        "num_filter": attrs["num_filter"], "num_group": attrs["num_filter"],
        "no_bias": True, "dilate": (1, 1),
    }
    out, _ = _deconvolution(octx, deconv_attrs, [x, w], [])
    return out, []


def _upsampling_infer_shape(attrs, in_shapes, aux_shapes):
    s = attrs["scale"]
    data = in_shapes[0]
    if attrs["sample_type"] == "nearest":
        oh, ow = data[2] * s, data[3] * s
        if len(in_shapes) == 1:
            c = data[1]
        else:
            c = sum(sh[1] for sh in in_shapes) if attrs["multi_input_mode"] == "concat" else data[1]
        return [tuple(d) for d in in_shapes], [(data[0], c, oh, ow)], []
    k = 2 * s - s % 2
    nf = attrs["num_filter"]
    wshape = (data[1], 1, k, k)
    return [tuple(data), wshape], [(data[0], nf, data[2] * s, data[3] * s)], []


get_op("UpSampling")._infer_shape = _upsampling_infer_shape


# ---------------------------------------------------------------- Sequence ops
def _seq_mask_from_len(length, maxlen, batch, dtype):
    # (seq, batch) mask from per-batch lengths
    ar = jnp.arange(maxlen, dtype=jnp.float32)[:, None]
    return (ar < length.astype(jnp.float32)[None, :]).astype(dtype)


@register(
    "SequenceMask",
    arg_names=lambda attrs: ["data", "sequence_length"] if attrs.get("use_sequence_length") else ["data"],
    params={"use_sequence_length": Param.bool(False), "value": Param.float(0.0), "axis": Param.int(0)},
)
def _sequence_mask(octx, attrs, args, auxs):
    x = args[0]
    if not attrs["use_sequence_length"]:
        return [x], []
    length = args[1]
    ax = attrs["axis"]
    xs = jnp.swapaxes(x, 0, ax) if ax != 0 else x
    mask = _seq_mask_from_len(length, xs.shape[0], xs.shape[1], xs.dtype)
    mask = mask.reshape(mask.shape + (1,) * (xs.ndim - 2))
    out = xs * mask + attrs["value"] * (1 - mask)
    if ax != 0:
        out = jnp.swapaxes(out, 0, ax)
    return [out], []


@register(
    "SequenceLast",
    arg_names=lambda attrs: ["data", "sequence_length"] if attrs.get("use_sequence_length") else ["data"],
    params={"use_sequence_length": Param.bool(False), "axis": Param.int(0)},
)
def _sequence_last(octx, attrs, args, auxs):
    x = args[0]
    ax = attrs["axis"]
    xs = jnp.swapaxes(x, 0, ax) if ax != 0 else x
    if attrs["use_sequence_length"]:
        idx = jax.lax.stop_gradient(args[1]).astype(np.int32) - 1
        out = jnp.take_along_axis(
            xs, idx.reshape((1, -1) + (1,) * (xs.ndim - 2)).astype(np.int32), axis=0
        )[0]
    else:
        out = xs[-1]
    return [out], []


def _seqlast_infer_shape(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    ax = attrs.get("axis", 0)
    rest = tuple(d for i, d in enumerate(data) if i != ax)
    shapes = [tuple(data)]
    if attrs.get("use_sequence_length"):
        shapes.append((data[1 - ax],))
    return shapes, [rest], []


get_op("SequenceLast")._infer_shape = _seqlast_infer_shape


@register(
    "SequenceReverse",
    arg_names=lambda attrs: ["data", "sequence_length"] if attrs.get("use_sequence_length") else ["data"],
    params={"use_sequence_length": Param.bool(False), "axis": Param.int(0)},
)
def _sequence_reverse(octx, attrs, args, auxs):
    x = args[0]
    if not attrs["use_sequence_length"]:
        return [jnp.flip(x, axis=0)], []
    length = jax.lax.stop_gradient(args[1]).astype(np.int32)
    T = x.shape[0]
    ar = jnp.arange(T)[:, None]
    rev_idx = jnp.where(ar < length[None, :], length[None, :] - 1 - ar, ar)
    out = jnp.take_along_axis(x, rev_idx.reshape((T, -1) + (1,) * (x.ndim - 2)), axis=0)
    return [out], []
