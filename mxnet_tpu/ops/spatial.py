"""Spatial transform ops: ROIPooling, SpatialTransformer, GridGenerator,
BilinearSampler, Correlation.

Reference: src/operator/{roi_pooling,spatial_transformer,grid_generator,
bilinear_sampler,correlation}.{cc,cu} — each a hand Forward/Backward CUDA pair.
Here: vectorized gather/one-hot formulations whose backward is autodiff;
bilinear sampling is differentiable end-to-end (matching the reference's
hand-written BilinearSamplerBackward).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import Param, fp32_precision, get_op, register, register_simple


# ---------------------------------------------------------------- ROIPooling
@register(
    "ROIPooling",
    arg_names=("data", "rois"),
    params={"pooled_size": Param.shape(), "spatial_scale": Param.float()},
)
def _roi_pooling(octx, attrs, args, auxs):
    """Max-pool each roi into a fixed (ph, pw) grid (roi_pooling-inl.h).
    rois: (R, 5) [batch_idx, x0, y0, x1, y1] in image coords."""
    data, rois = args
    N, C, H, W = data.shape
    ph, pw = attrs["pooled_size"]
    scale = attrs["spatial_scale"]

    def one_roi(roi):
        bidx = jax.lax.stop_gradient(roi[0]).astype(jnp.int32)
        x0 = jnp.round(roi[1] * scale)
        y0 = jnp.round(roi[2] * scale)
        x1 = jnp.round(roi[3] * scale)
        y1 = jnp.round(roi[4] * scale)
        rw = jnp.maximum(x1 - x0 + 1, 1.0)
        rh = jnp.maximum(y1 - y0 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        img = data[bidx]  # (C, H, W)
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)

        def bin_val(i, j):
            hstart = jnp.floor(y0 + i * bin_h)
            hend = jnp.ceil(y0 + (i + 1) * bin_h)
            wstart = jnp.floor(x0 + j * bin_w)
            wend = jnp.ceil(x0 + (j + 1) * bin_w)
            ymask = (ys >= hstart) & (ys < hend) & (ys >= 0) & (ys < H)
            xmask = (xs >= wstart) & (xs < wend) & (xs >= 0) & (xs < W)
            m = ymask[:, None] & xmask[None, :]
            masked = jnp.where(m[None, :, :], img, -jnp.inf)
            v = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.any(m), v, 0.0)

        ii, jj = jnp.meshgrid(jnp.arange(ph), jnp.arange(pw), indexing="ij")
        vals = jax.vmap(jax.vmap(bin_val))(ii.astype(jnp.float32), jj.astype(jnp.float32))
        return jnp.transpose(vals, (2, 0, 1))  # (C, ph, pw)

    out = jax.vmap(one_roi)(rois)
    return [out], []


def _roi_infer(attrs, in_shapes, aux_shapes):
    data, rois = in_shapes
    ph, pw = attrs["pooled_size"]
    return [tuple(data), tuple(rois)], [(rois[0], data[1], ph, pw)], []


get_op("ROIPooling")._infer_shape = _roi_infer


# ---------------------------------------------------------- bilinear sampling
def _bilinear_sample(img, gx, gy):
    """Differentiable bilinear sampling of img (C,H,W) at normalized grid
    coords gx, gy in [-1, 1] (shape (Ho, Wo)). Out-of-range samples are 0
    (matching bilinear_sampler-inl.h border handling)."""
    C, H, W = img.shape
    x = (gx + 1) * (W - 1) / 2
    y = (gy + 1) * (H - 1) / 2
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    x1 = x0 + 1
    y1 = y0 + 1
    wx1 = x - x0
    wy1 = y - y0
    wx0 = 1 - wx1
    wy0 = 1 - wy1

    def gather(yy, xx):
        valid = (xx >= 0) & (xx <= W - 1) & (yy >= 0) & (yy <= H - 1)
        xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        v = img[:, yi, xi]  # (C, Ho, Wo)
        return jnp.where(valid[None], v, 0.0)

    out = (
        gather(y0, x0) * (wy0 * wx0)[None]
        + gather(y0, x1) * (wy0 * wx1)[None]
        + gather(y1, x0) * (wy1 * wx0)[None]
        + gather(y1, x1) * (wy1 * wx1)[None]
    )
    return out


@register(
    "BilinearSampler",
    arg_names=("data", "grid"),
    params={},
)
def _bilinear_sampler(octx, attrs, args, auxs):
    """(reference: bilinear_sampler.cc — grid (N, 2, Ho, Wo) of x;y in [-1,1])"""
    data, grid = args
    out = jax.vmap(lambda img, g: _bilinear_sample(img, g[0], g[1]))(data, grid)
    return [out], []


def _bs_infer(attrs, in_shapes, aux_shapes):
    data, grid = in_shapes
    return [tuple(data), tuple(grid)], [(data[0], data[1], grid[2], grid[3])], []


get_op("BilinearSampler")._infer_shape = _bs_infer


# ---------------------------------------------------------------- GridGenerator
@register(
    "GridGenerator",
    arg_names=("data",),
    params={"transform_type": Param.str(), "target_shape": Param.shape((0, 0))},
)
def _grid_generator(octx, attrs, args, auxs):
    """affine: data (N, 6) θ → sampling grid (N, 2, H, W); warp: data
    (N, 2, H, W) optical flow → grid (grid_generator.cc contract)."""
    x = args[0]
    if attrs["transform_type"] == "affine":
        H, W = attrs["target_shape"]
        theta = x.reshape(-1, 2, 3)
        ys = jnp.linspace(-1, 1, H)
        xs = jnp.linspace(-1, 1, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        coords = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, H*W)
        out = jnp.einsum("nij,jk->nik", theta, coords,
                         precision=fp32_precision(x.dtype)).reshape(-1, 2, H, W)
        return [out], []
    # warp: grid = identity + normalized flow
    N, _, H, W = x.shape
    ys = jnp.linspace(-1, 1, H)
    xs = jnp.linspace(-1, 1, W)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    flow_x = x[:, 0] * 2 / jnp.maximum(W - 1, 1)
    flow_y = x[:, 1] * 2 / jnp.maximum(H - 1, 1)
    out = jnp.stack([gx[None] + flow_x, gy[None] + flow_y], axis=1)
    return [out], []


def _gg_infer(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    if attrs["transform_type"] == "affine":
        H, W = attrs["target_shape"]
        return [tuple(data)], [(data[0], 2, H, W)], []
    return [tuple(data)], [tuple(data)], []


get_op("GridGenerator")._infer_shape = _gg_infer


# ---------------------------------------------------------- SpatialTransformer
@register(
    "SpatialTransformer",
    arg_names=("data", "loc"),
    params={
        "target_shape": Param.shape((0, 0)),
        "transform_type": Param.str("affine"),
        "sampler_type": Param.str("bilinear"),
        "cudnn_off": Param.bool(False),
    },
)
def _spatial_transformer(octx, attrs, args, auxs):
    """Affine grid + bilinear sampling (spatial_transformer.cc; the cuDNN path
    cudnn_spatial_transformer.h is the same math)."""
    data, loc = args
    H, W = attrs["target_shape"]
    theta = loc.reshape(-1, 2, 3)
    ys = jnp.linspace(-1, 1, H)
    xs = jnp.linspace(-1, 1, W)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    ones = jnp.ones_like(gx)
    coords = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)
    grid = jnp.einsum("nij,jk->nik", theta, coords,
                      precision=fp32_precision(loc.dtype)).reshape(-1, 2, H, W)
    out = jax.vmap(lambda img, g: _bilinear_sample(img, g[0], g[1]))(data, grid)
    return [out], []


def _st_infer(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    H, W = attrs["target_shape"]
    return [tuple(data), (data[0], 6)], [(data[0], data[1], H, W)], []


get_op("SpatialTransformer")._infer_shape = _st_infer


# ---------------------------------------------------------------- Correlation
@register(
    "Correlation",
    arg_names=("data1", "data2"),
    params={
        "kernel_size": Param.int(1),
        "max_displacement": Param.int(1),
        "stride1": Param.int(1),
        "stride2": Param.int(1),
        "pad_size": Param.int(0),
        "is_multiply": Param.bool(True),
    },
    num_outputs=3,
    num_visible_outputs=1,
    output_names=("output", "tmp1", "tmp2"),
)
def _correlation(octx, attrs, args, auxs):
    """FlowNet correlation layer (correlation.cc): for each displacement d in a
    (2D+1)^2 window, mean over a k×k patch of data1(x)·data2(x+d)."""
    a, b = args
    N, C, H, W = a.shape
    pad = attrs["pad_size"]
    k = attrs["kernel_size"]
    D = attrs["max_displacement"]
    s1, s2 = attrs["stride1"], attrs["stride2"]
    bk = k // 2
    ap = jnp.pad(a, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    bp = jnp.pad(b, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    Hp, Wp = H + 2 * pad, W + 2 * pad
    n_disp = 2 * (D // s2) + 1
    out_h = int(np.ceil((Hp - 2 * (bk + D)) / s1))
    out_w = int(np.ceil((Wp - 2 * (bk + D)) / s1))
    mult = attrs["is_multiply"]
    rows = []
    for dy in range(-D, D + 1, s2):
        cols = []
        for dx in range(-D, D + 1, s2):
            b_shift = jnp.roll(bp, shift=(-dy, -dx), axis=(2, 3))
            prod = ap * b_shift if mult else jnp.abs(ap - b_shift)
            # mean over channels and the k×k kernel window
            corr = jnp.mean(prod, axis=1, keepdims=False)
            if k > 1:
                corr = jax.lax.reduce_window(
                    corr, 0.0, jax.lax.add, (1, k, k), (1, 1, 1),
                    [(0, 0), (bk, bk), (bk, bk)],
                ) / (k * k)
            start = bk + D
            corr = corr[:, start : start + out_h * s1 : s1, start : start + out_w * s1 : s1]
            cols.append(corr)
        rows.extend(cols)
    out = jnp.stack(rows, axis=1)  # (N, n_disp^2, out_h, out_w)
    return [out, jnp.zeros_like(ap), jnp.zeros_like(bp)], []


def _corr_infer(attrs, in_shapes, aux_shapes):
    data1 = in_shapes[0]
    N, C, H, W = data1
    pad = attrs["pad_size"]
    k = attrs["kernel_size"]
    D = attrs["max_displacement"]
    s1, s2 = attrs["stride1"], attrs["stride2"]
    bk = k // 2
    Hp, Wp = H + 2 * pad, W + 2 * pad
    n_disp = 2 * (D // s2) + 1
    out_h = int(np.ceil((Hp - 2 * (bk + D)) / s1))
    out_w = int(np.ceil((Wp - 2 * (bk + D)) / s1))
    return (
        [tuple(data1), tuple(data1)],
        [(N, n_disp * n_disp, out_h, out_w), (N, C, Hp, Wp), (N, C, Hp, Wp)],
        [],
    )


get_op("Correlation")._infer_shape = _corr_infer


# ----------------------------------------------------- KL sparse regularization
@register(
    "IdentityAttachKLSparseReg",
    arg_names=("data",),
    aux_names=("moving_avg",),
    params={
        "sparseness_target": Param.float(0.1),
        "penalty": Param.float(0.001),
        "momentum": Param.float(0.9),
    },
    alias=("identity_attach_KL_sparse_reg",),
)
def _kl_sparse_reg(octx, attrs, args, auxs):
    """Identity forward; adds KL(ρ||ρ̂) sparsity gradient via the moving
    average of activations (identity_attach_KL_sparse_reg-inl.h)."""
    x = args[0]
    (mov,) = auxs
    rho = attrs["sparseness_target"]
    penalty = attrs["penalty"]
    mom = attrs["momentum"]
    rho_hat = jnp.mean(x, axis=0)
    new_mov = mov * mom + jax.lax.stop_gradient(rho_hat) * (1 - mom)

    @jax.custom_vjp
    def f(x):
        return x

    def f_fwd(x):
        return x, None

    def f_bwd(_, g):
        kl_grad = penalty * (-rho / jnp.maximum(new_mov, 1e-12) + (1 - rho) / jnp.maximum(1 - new_mov, 1e-12))
        return (g + kl_grad[None, :],)

    f.defvjp(f_fwd, f_bwd)
    return [f(x)], [new_mov]


def _kl_infer(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    return [tuple(data)], [tuple(data)], [(data[1],)]


get_op("IdentityAttachKLSparseReg")._infer_shape = _kl_infer


# --------------------------------------------------- uint8-wire input decode
def _parse_rgb(v):
    """Optional per-channel float tuple: None / '' / 'None' stay None."""
    if v is None or (isinstance(v, str) and v in ("None", "")):
        return None
    if isinstance(v, str):
        v = v.strip("()[] ").split(",")
        v = [x for x in (s.strip() for s in v) if x]
    try:
        return tuple(float(x) for x in v)
    except TypeError:
        return (float(v),)


@register(
    "_image_wire_normalize",
    params={
        "mean": Param(_parse_rgb, None, kind="float tuple or None"),
        "std": Param(_parse_rgb, None, kind="float tuple or None"),
        "layout": Param.str("NHWC"),
    },
    infer_type=lambda attrs, dts: (
        [dts[0] if dts[0] is not None else np.uint8], [np.float32], []),
)
def _image_wire_normalize(octx, attrs, args, auxs):
    """Decode a wire-format image batch on device: cast to fp32, subtract
    per-channel mean / divide by std, and transpose NHWC -> NCHW.

    The host side of this contract is ``io.WireSpec`` (docs/perf.md
    §pipeline): iterators ship batches as uint8 HWC — a 4x wire-size cut
    vs fp32 — and this single fused XLA program restores the compute
    layout at the device boundary. Channel stats apply along the last
    axis of ``layout`` (the reference normalizes in HWC before its own
    transpose, image_aug_default.cc)."""
    x = args[0]
    y = x.astype(jnp.float32)
    if attrs["mean"] is not None:
        y = y - jnp.asarray(attrs["mean"], jnp.float32)
    if attrs["std"] is not None:
        y = y / jnp.asarray(attrs["std"], jnp.float32)
    if attrs["layout"] == "NHWC" and y.ndim == 4:
        y = jnp.transpose(y, (0, 3, 1, 2))
    # differentiable (g/std, transposed back) so inputs_need_grad works
    # through the wire decode; integer wire inputs have no grad anyway
    return [y], []
