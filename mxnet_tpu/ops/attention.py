"""Attention ops — flash (memory-efficient) multi-head attention.

The reference (MXNet v0.10.1) predates attention entirely — its long-sequence
story is bucketing + fused cuDNN RNNs (SURVEY §5 "Long-context"). This module is
the green-field TPU-first design that gives the framework a modern long-context
path while staying inside the op-registry contract (ops/registry.py).

Design:

* ``flash_attention(q, k, v)`` operates on ``(batch, heads, seq, head_dim)``.
  Forward and backward are the FlashAttention online-softmax algorithm expressed
  as ``lax.scan`` over key/value blocks — O(seq) memory instead of O(seq^2),
  static shapes, MXU-sized matmul blocks. ``jax.custom_vjp`` saves only
  ``(q, k, v, out, lse)`` residuals; the backward pass is the standard
  dq/dk/dv block recurrence (recompute-based, no S matrix ever materialised).
* On TPU the forward uses a Pallas kernel (``_pallas_forward``) with 512×1024
  q/kv blocks (measured 12.7 TFLOP/s at seq 4096 on v5e — 1.9x XLA's scan
  lowering and 1.8x the jax library flash kernel; tiny blocks starve the MXU);
  everywhere else (CPU tests, odd shapes) the pure-XLA scan path runs. Both
  produce identical (out, lse) residuals so the backward is shared.
* The op is registered as ``_contrib_FlashAttention`` so it is reachable from
  both ``mx.nd.contrib.FlashAttention`` and ``mx.sym.contrib.FlashAttention``
  (the escape-hatch naming the reference uses for new ops, SURVEY §2.3 contrib).
* Ring/Ulysses sequence parallelism (parallel/ring.py) reuses the same block
  kernel: a ring step is one ``_block_update`` against a remote KV shard.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .registry import Param, fp32_precision, register

__all__ = ["flash_attention", "attention_reference", "paged_attention",
           "paged_attention_reference", "paged_attention_multi",
           "paged_attention_multi_reference"]

_NEG_INF = -1e30


def _scale(sm_scale, d):
    return 1.0 / np.sqrt(d) if sm_scale is None else sm_scale


def _tpu_in_process():
    """Whether a TPU backend exists in this process. Gates the Pallas
    branch at TRACE time: ``lax.platform_dependent`` still picks the
    platform at LOWERING time, but on this jax version it lowers every
    offered branch — offering the Pallas kernel to a CPU-only process
    fails its lowering outright ("Only interpret mode is supported on CPU
    backend"), so a process without a TPU must not offer it at all."""
    import jax

    return jax.default_backend() == "tpu"


def attention_reference(q, k, v, causal=False, sm_scale=None):
    """Naive softmax attention — the numeric oracle for tests (O(S^2) memory)."""
    sm_scale = _scale(sm_scale, q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32),
                   precision=lax.Precision.HIGHEST) * sm_scale
    if causal:
        qi = jnp.arange(q.shape[2])[:, None]
        ki = jnp.arange(k.shape[2])[None, :]
        s = jnp.where(qi >= ki, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32),
                      precision=lax.Precision.HIGHEST).astype(q.dtype)


# ------------------------------------------------------------------ block math
def _block_update(q, k_blk, v_blk, m, l, acc, sm_scale, mask=None,
                  precision=None):
    """One online-softmax update of (m, l, acc) with a KV block.

    q: (B,H,Sq,D) f32; k_blk/v_blk: (B,H,Bk,D); m,l: (B,H,Sq); acc: (B,H,Sq,D).
    mask: optional (Sq, Bk) bool — True = attend. precision: MXU precision
    chosen from the ORIGINAL (pre-cast) input dtype, see fp32_precision().
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk, preferred_element_type=jnp.float32,
                   precision=precision) * sm_scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m_blk = jnp.max(s, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(s - m_new[..., None])
    scale = jnp.exp(m - m_new)
    l_new = l * scale + jnp.sum(p, axis=-1)
    acc_new = acc * scale[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v_blk, preferred_element_type=jnp.float32,
        precision=precision
    )
    return m_new, l_new, acc_new


def _scan_forward(q, k, v, causal, sm_scale, block_k):
    """Pure-XLA flash forward: lax.scan over KV blocks. Returns (out, lse) f32."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_k = min(block_k, sk)
    n_blk = -(-sk // block_k)
    pad = n_blk * block_k - sk
    prec = fp32_precision(q.dtype)
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    if pad:
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad), (0, 0)))
    # (n_blk, B, H, block_k, D) scan-major layout
    kb = jnp.moveaxis(kf.reshape(b, h, n_blk, block_k, d), 2, 0)
    vb = jnp.moveaxis(vf.reshape(b, h, n_blk, block_k, d), 2, 0)
    qi = jnp.arange(sq)

    def step(carry, xs):
        m, l, acc = carry
        k_blk, v_blk, blk_idx = xs
        ki = blk_idx * block_k + jnp.arange(block_k)
        mask = ki[None, :] < sk  # (1, Bk) padding mask
        if causal:
            mask = mask & (qi[:, None] >= ki[None, :])
        else:
            mask = jnp.broadcast_to(mask, (sq, block_k))
        m, l, acc = _block_update(qf, k_blk, v_blk, m, l, acc, sm_scale, mask,
                                  precision=prec)
        return (m, l, acc), None

    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    acc0 = jnp.zeros((b, h, sq, d), jnp.float32)
    (m, l, acc), _ = lax.scan(step, (m0, l0, acc0), (kb, vb, jnp.arange(n_blk)))
    l = jnp.maximum(l, 1e-30)
    out = acc / l[..., None]
    lse = m + jnp.log(l)
    return out, lse


def _pallas_forward(q, k, v, causal, sm_scale, block_q=512, block_k=1024, interpret=False):
    """Pallas TPU flash-attention forward.

    Grid (batch*heads, q_blocks, kv_blocks) with the KV axis innermost: TPU
    executes the grid sequentially along the last axis, so (m, l, acc) live in
    VMEM scratch carried across KV steps — per-core VMEM is O(block_q·d +
    block_k·d), independent of sequence length. Output is written on the last
    KV step. Returns (out, lse) float32, identical residuals to
    ``_scan_forward``.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    n_q = -(-sq // block_q)  # ragged tails are masked inside the kernel
    n_k = -(-sk // block_k)

    def kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref, acc_ref):
        qi_blk = pl.program_id(1)
        kj = pl.program_id(2)

        @pl.when(kj == 0)
        def _init():
            m_ref[:] = jnp.full((block_q,), _NEG_INF, jnp.float32)
            l_ref[:] = jnp.zeros((block_q,), jnp.float32)
            acc_ref[:] = jnp.zeros((block_q, d), jnp.float32)

        # causal: skip blocks strictly above the diagonal
        first_q_pos = qi_blk * block_q + block_q - 1  # last row of the q block
        run = (kj * block_k <= first_q_pos) if causal else True

        @pl.when(run)
        def _step():
            qv = q_ref[0].astype(jnp.float32)
            kv = k_ref[0].astype(jnp.float32)
            vv = v_ref[0].astype(jnp.float32)
            s = jnp.dot(qv, kv.T, preferred_element_type=jnp.float32) * sm_scale
            q_pos = qi_blk * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
            k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
            mask = k_pos < sk
            if causal:
                mask = mask & (q_pos >= k_pos)
            s = jnp.where(mask, s, _NEG_INF)
            m = m_ref[:]
            m_blk = jnp.max(s, axis=-1)
            m_new = jnp.maximum(m, m_blk)
            p = jnp.exp(s - m_new[:, None])
            scale = jnp.exp(m - m_new)
            m_ref[:] = m_new
            l_ref[:] = l_ref[:] * scale + jnp.sum(p, axis=-1)
            acc_ref[:] = acc_ref[:] * scale[:, None] + jnp.dot(
                p, vv, preferred_element_type=jnp.float32
            )

        @pl.when(kj == n_k - 1)
        def _finish():
            l = jnp.maximum(l_ref[:], 1e-30)
            o_ref[0] = acc_ref[:] / l[:, None]
            lse_ref[0] = (m_ref[:] + jnp.log(l))[None, :]

    bh = b * h
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)
    pad_q = n_q * block_q - sq
    pad_k = n_k * block_k - sk
    if pad_q:
        qr = jnp.pad(qr, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        kr = jnp.pad(kr, ((0, 0), (0, pad_k), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, pad_k), (0, 0)))
    grid = (bh, n_q, n_k)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, 1, block_q), lambda i, j, kk: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n_q * block_q, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, 1, n_q * block_q), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q,), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr)
    out = out[:, :sq].reshape(b, h, sq, d)
    lse = lse[:, 0, :sq].reshape(b, h, sq)
    return out, lse


def _pallas_shapes_ok(q, k):
    """Shapes the Pallas kernel handles; platform choice happens separately
    at lowering time (lax.platform_dependent in _forward_impl). Ragged block
    tails are masked inside the kernel, but hardware Mosaic wants the
    second-minor tile aligned — require sequence multiples of 128 on the
    Pallas path; anything else takes the scan lowering."""
    d = q.shape[-1]
    # Mosaic pads the lane dim, so any multiple of 8 works; 64 is the common
    # head_dim and must not fall back to the scan path
    return (d % 8 == 0 and q.shape[2] % 128 == 0 and k.shape[2] % 128 == 0
            and q.shape[2] >= 128 and k.shape[2] >= 128)


def _pallas_backward(q, k, v, out, lse, g, causal, sm_scale,
                     block_q=512, block_k=512, interpret=False):
    """Pallas TPU flash-attention backward — two kernels, each recomputing P
    from the saved lse (no S matrix materialised, same residuals as the scan
    path): dk/dv iterate q-blocks innermost with the (block_k, d) accumulators
    in VMEM; dq iterates kv-blocks innermost. delta = rowsum(dout*out) is
    precomputed in XLA."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    n_q = -(-sq // block_q)
    n_k = -(-sk // block_k)
    bh = b * h

    delta = jnp.sum(out.astype(jnp.float32) * g.astype(jnp.float32), axis=-1)

    def prep(x, s, pad_to):
        x = x.reshape(bh, s, -1)
        pad = pad_to - s
        return jnp.pad(x, ((0, 0), (0, pad), (0, 0))) if pad else x

    qr = prep(q, sq, n_q * block_q)
    gr = prep(g, sq, n_q * block_q)
    kr = prep(k, sk, n_k * block_k)
    vr = prep(v, sk, n_k * block_k)
    lse_r = prep(lse[..., None], sq, n_q * block_q)[..., 0].reshape(bh, 1, -1)
    delta_r = prep(delta[..., None], sq, n_q * block_q)[..., 0].reshape(bh, 1, -1)

    def recompute(qv, gv, kv, vv, lse_row, delta_row, qi_blk, kj):
        s = jnp.dot(qv, kv.T, preferred_element_type=jnp.float32) * sm_scale
        q_pos = qi_blk * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        mask = k_pos < sk
        if causal:
            mask = mask & (q_pos >= k_pos)
        s = jnp.where(mask, s, _NEG_INF)
        p = jnp.exp(s - lse_row[:, None])  # (bq, bk); 0 where masked
        dp = jnp.dot(gv, vv.T, preferred_element_type=jnp.float32)
        ds = p * (dp - delta_row[:, None]) * sm_scale
        return p, ds

    def kernel_dkv(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, dk_acc, dv_acc):
        kj = pl.program_id(1)
        qi_blk = pl.program_id(2)

        @pl.when(qi_blk == 0)
        def _init():
            dk_acc[:] = jnp.zeros((block_k, d), jnp.float32)
            dv_acc[:] = jnp.zeros((block_k, d), jnp.float32)

        run = (qi_blk * block_q + block_q - 1 >= kj * block_k) if causal else True

        @pl.when(run)
        def _step():
            qv = q_ref[0].astype(jnp.float32)
            gv = g_ref[0].astype(jnp.float32)
            kv = k_ref[0].astype(jnp.float32)
            vv = v_ref[0].astype(jnp.float32)
            p, ds = recompute(qv, gv, kv, vv, lse_ref[0, 0], delta_ref[0, 0],
                              qi_blk, kj)
            dv_acc[:] += jnp.dot(p.T, gv, preferred_element_type=jnp.float32)
            dk_acc[:] += jnp.dot(ds.T, qv, preferred_element_type=jnp.float32)

        @pl.when(qi_blk == n_q - 1)
        def _finish():
            dk_ref[0] = dk_acc[:]
            dv_ref[0] = dv_acc[:]

    def kernel_dq(q_ref, g_ref, k_ref, v_ref, lse_ref, delta_ref,
                  dq_ref, dq_acc):
        qi_blk = pl.program_id(1)
        kj = pl.program_id(2)

        @pl.when(kj == 0)
        def _init():
            dq_acc[:] = jnp.zeros((block_q, d), jnp.float32)

        run = (kj * block_k <= qi_blk * block_q + block_q - 1) if causal else True

        @pl.when(run)
        def _step():
            qv = q_ref[0].astype(jnp.float32)
            gv = g_ref[0].astype(jnp.float32)
            kv = k_ref[0].astype(jnp.float32)
            vv = v_ref[0].astype(jnp.float32)
            _, ds = recompute(qv, gv, kv, vv, lse_ref[0, 0], delta_ref[0, 0],
                              qi_blk, kj)
            dq_acc[:] += jnp.dot(ds, kv, preferred_element_type=jnp.float32)

        @pl.when(kj == n_k - 1)
        def _finish():
            dq_ref[0] = dq_acc[:]

    q_spec = pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, kk, 0))
    kv_spec_outer = pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q), lambda i, j, kk: (i, 0, kk))
    dk, dv = pl.pallas_call(
        kernel_dkv,
        grid=(bh, n_k, n_q),
        in_specs=[q_spec, q_spec, kv_spec_outer, kv_spec_outer, row_spec, row_spec],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n_k * block_k, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, n_k * block_k, d), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qr, gr, kr, vr, lse_r, delta_r)

    q_spec2 = pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0))
    kv_spec2 = pl.BlockSpec((1, block_k, d), lambda i, j, kk: (i, kk, 0))
    row_spec2 = pl.BlockSpec((1, 1, block_q), lambda i, j, kk: (i, 0, j))
    (dq,) = pl.pallas_call(
        kernel_dq,
        grid=(bh, n_q, n_k),
        in_specs=[q_spec2, q_spec2, kv_spec2, kv_spec2, row_spec2, row_spec2],
        out_specs=[pl.BlockSpec((1, block_q, d), lambda i, j, kk: (i, j, 0))],
        out_shape=[jax.ShapeDtypeStruct((bh, n_q * block_q, d), jnp.float32)],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qr, gr, kr, vr, lse_r, delta_r)

    dq = dq[:, :sq].reshape(b, h, sq, d).astype(q.dtype)
    dk = dk[:, :sk].reshape(b, h, sk, d).astype(k.dtype)
    dv = dv[:, :sk].reshape(b, h, sk, d).astype(v.dtype)
    return dq, dk, dv


def _scan_backward(q, k, v, out, lse, g, causal, sm_scale, block_k):
    """Flash backward: recompute P per block from saved lse; accumulate dq/dk/dv."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_k = min(block_k, sk)
    n_blk = -(-sk // block_k)
    pad = n_blk * block_k - sk
    prec = fp32_precision(q.dtype)
    qf, kf, vf = (x.astype(jnp.float32) for x in (q, k, v))
    gf = g.astype(jnp.float32)
    of = out.astype(jnp.float32)
    if pad:
        kf = jnp.pad(kf, ((0, 0), (0, 0), (0, pad), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = jnp.moveaxis(kf.reshape(b, h, n_blk, block_k, d), 2, 0)
    vb = jnp.moveaxis(vf.reshape(b, h, n_blk, block_k, d), 2, 0)
    delta = jnp.sum(of * gf, axis=-1)  # (B,H,Sq)
    qi = jnp.arange(sq)

    def step(dq, xs):
        k_blk, v_blk, blk_idx = xs
        ki = blk_idx * block_k + jnp.arange(block_k)
        mask = ki[None, :] < sk
        if causal:
            mask = mask & (qi[:, None] >= ki[None, :])
        else:
            mask = jnp.broadcast_to(mask, (sq, block_k))
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, k_blk, preferred_element_type=jnp.float32,
                       precision=prec) * sm_scale
        s = jnp.where(mask[None, None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])  # (B,H,Sq,Bk)
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p, gf, preferred_element_type=jnp.float32,
                            precision=prec)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, v_blk, preferred_element_type=jnp.float32,
                        precision=prec)
        ds = p * (dp - delta[..., None]) * sm_scale
        dq = dq + jnp.einsum("bhqk,bhkd->bhqd", ds, k_blk, preferred_element_type=jnp.float32,
                             precision=prec)
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, qf, preferred_element_type=jnp.float32,
                            precision=prec)
        return dq, (dk_blk, dv_blk)

    dq0 = jnp.zeros((b, h, sq, d), jnp.float32)
    dq, (dk_b, dv_b) = lax.scan(step, dq0, (kb, vb, jnp.arange(n_blk)))
    dk = jnp.moveaxis(dk_b, 0, 2).reshape(b, h, n_blk * block_k, d)[:, :, :sk]
    dv = jnp.moveaxis(dv_b, 0, 2).reshape(b, h, n_blk * block_k, d)[:, :, :sk]
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal=False, sm_scale=None, block_k=256):
    """Memory-efficient attention over (batch, heads, seq, head_dim)."""
    out, _ = _forward_impl(q, k, v, causal, sm_scale, block_k)
    return out


def _forward_impl(q, k, v, causal, sm_scale, block_k):
    sm_scale = _scale(sm_scale, q.shape[-1])
    if _pallas_shapes_ok(q, k) and _tpu_in_process():
        # platform selected at LOWERING time, not trace time: the same traced
        # function may compile for the TPU (Pallas kernel) or for CPU (scan) —
        # an array's placement isn't knowable from a tracer
        out, lse = lax.platform_dependent(
            q, k, v,
            tpu=functools.partial(_pallas_forward, causal=causal, sm_scale=sm_scale),
            default=functools.partial(_scan_forward, causal=causal,
                                      sm_scale=sm_scale, block_k=block_k),
        )
    else:
        out, lse = _scan_forward(q, k, v, causal, sm_scale, block_k)
    return out.astype(q.dtype), lse


def _fa_fwd(q, k, v, causal, sm_scale, block_k):
    out, lse = _forward_impl(q, k, v, causal, sm_scale, block_k)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, sm_scale, block_k, res, g):
    q, k, v, out, lse = res
    scale = _scale(sm_scale, q.shape[-1])
    if _pallas_shapes_ok(q, k) and _tpu_in_process():
        return lax.platform_dependent(
            q, k, v, out, lse, g,
            tpu=functools.partial(_pallas_backward, causal=causal, sm_scale=scale),
            default=functools.partial(_scan_backward, causal=causal,
                                      sm_scale=scale, block_k=block_k),
        )
    return _scan_backward(q, k, v, out, lse, g, causal, scale, block_k)


flash_attention.defvjp(_fa_fwd, _fa_bwd)


# ------------------------------------------------------------- registered ops
@register(
    "_contrib_FlashAttention",
    arg_names=("query", "key", "value"),
    params={
        "causal": Param.bool(False),
        "sm_scale": Param.float(-1.0),
    },
)
def _flash_attention_op(octx, attrs, args, auxs):
    q, k, v = args
    scale = attrs["sm_scale"]
    out = flash_attention(q, k, v, attrs["causal"], None if scale <= 0 else scale)
    return [out], []


@register(
    "_contrib_MultiHeadAttention",
    arg_names=("data", "in_weight", "out_weight"),
    params={
        "num_heads": Param.int(),
        "causal": Param.bool(True),
    },
)
def _mha_op(octx, attrs, args, auxs):
    """Self-attention block over (batch, seq, model): fused qkv projection +
    flash attention + output projection. in_weight: (3*model, model),
    out_weight: (model, model) — weights laid out like FullyConnected (out, in)."""
    x, w_in, w_out = args
    bsz, seq, model = x.shape
    heads = attrs["num_heads"]
    hd = model // heads
    prec = fp32_precision(x.dtype)
    qkv = jnp.einsum("bsm,nm->bsn", x, w_in, precision=prec)  # (B,S,3*model)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def split_heads(t):
        return t.reshape(bsz, seq, heads, hd).transpose(0, 2, 1, 3)

    out = flash_attention(split_heads(q), split_heads(k), split_heads(v), attrs["causal"])
    out = out.transpose(0, 2, 1, 3).reshape(bsz, seq, model)
    return [jnp.einsum("bsm,nm->bsn", out, w_out, precision=prec)], []


def _mha_infer_shape(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    if data is None:
        raise ValueError("MultiHeadAttention: data shape required")
    model = data[2]
    if in_shapes[1] is None:
        in_shapes[1] = (3 * model, model)
    if in_shapes[2] is None:
        in_shapes[2] = (model, model)
    return in_shapes, [tuple(data)], []


from .registry import get_op  # noqa: E402

get_op("_contrib_MultiHeadAttention")._infer_shape = _mha_infer_shape


# ---------------------------------------------------- incremental decoding
@register(
    "_contrib_CachedMultiHeadAttention",
    arg_names=("data", "in_weight", "out_weight", "position"),
    aux_names=("cache_k", "cache_v"),
    params={
        "num_heads": Param.int(),
        "max_len": Param.int(),
    },
)
def _cached_mha_op(octx, attrs, args, auxs):
    """One autoregressive decode step with static-shape KV caches.

    Not in the reference (its era predates attention serving); this is the
    TPU-idiomatic incremental decoder: caches are AUX STATES of fixed shape
    (batch, heads, max_len, head_dim) mutated in place each step (the same
    FMutateInputs mechanism BatchNorm's moving stats use), so every step
    compiles once and replays — no per-length recompilation, the KV-cache
    analog of the paged-attention serving pattern.

    data: (B, 1, model) — the current token's hidden state;
    position: (1,) float — the step index t (tokens 0..t-1 already cached).
    Returns (B, 1, model); writes the step's k/v into the caches at t.

    Graph-level overflow contract: a position >= max_len can NEVER corrupt
    the cache — the write is dropped (both caches pass through unchanged)
    and the op's output is poisoned to NaN so the overflow fails loudly at
    the consumer instead of silently rereading a clobbered slot. (XLA admits
    no data-dependent errors, so in-graph the hazard lowers to
    drop-write + poison; ``transformer_lm.decode_step`` still raises
    host-side before dispatch.)
    """
    x, w_in, w_out, position = args
    cache_k, cache_v = auxs
    bsz, one, model = x.shape
    heads = attrs["num_heads"]
    max_len = attrs["max_len"]
    hd = model // heads
    pos_raw = position.reshape(()).astype(jnp.int32)
    in_range = (pos_raw >= 0) & (pos_raw < max_len)
    pos = jnp.clip(pos_raw, 0, max_len - 1)  # safe index for the dropped write

    prec = fp32_precision(x.dtype)
    qkv = jnp.einsum("bsm,nm->bsn", x, w_in, precision=prec)  # (B, 1, 3*model)
    q, k_new, v_new = jnp.split(qkv, 3, axis=-1)

    def heads_first(t):
        return t.reshape(bsz, 1, heads, hd).transpose(0, 2, 1, 3)  # (B,H,1,hd)

    q, k_new, v_new = heads_first(q), heads_first(k_new), heads_first(v_new)
    new_k = jax.lax.dynamic_update_slice(cache_k, k_new.astype(cache_k.dtype),
                                         (0, 0, pos, 0))
    new_v = jax.lax.dynamic_update_slice(cache_v, v_new.astype(cache_v.dtype),
                                         (0, 0, pos, 0))
    # overflow contract: out-of-range positions drop the write entirely
    new_k = jnp.where(in_range, new_k, cache_k)
    new_v = jnp.where(in_range, new_v, cache_v)
    # attend q over positions <= t
    s = jnp.einsum("bhqd,bhkd->bhqk", q, new_k,
                   preferred_element_type=jnp.float32,
                   precision=prec) / np.sqrt(hd)
    valid = jnp.arange(max_len) <= pos
    s = jnp.where(valid[None, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1).astype(new_v.dtype)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, new_v, precision=prec)  # (B,H,1,hd)
    out = out.transpose(0, 2, 1, 3).reshape(bsz, 1, model)
    out = jnp.einsum("bsm,nm->bsn", out, w_out, precision=prec)
    # overflow contract: poison the output so an out-of-range step fails
    # loudly downstream instead of returning stale-slot attention
    out = jnp.where(in_range, out, jnp.asarray(np.nan, out.dtype))
    return [out], [new_k, new_v]


def _cached_mha_infer(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    if data is None:
        raise ValueError("CachedMultiHeadAttention: data shape required")
    b, one, model = data
    heads = attrs["num_heads"]
    max_len = attrs["max_len"]
    hd = model // heads
    if in_shapes[1] is None:
        in_shapes[1] = (3 * model, model)
    if in_shapes[2] is None:
        in_shapes[2] = (model, model)
    if in_shapes[3] is None:
        in_shapes[3] = (1,)
    cache = (b, heads, max_len, hd)
    return in_shapes, [tuple(data)], [cache, cache]


get_op("_contrib_CachedMultiHeadAttention")._infer_shape = _cached_mha_infer


# ------------------------------------------------------- paged (ragged) decode
def paged_attention_reference(q, k_pages, v_pages, block_tables, context_lens,
                              sm_scale=None):
    """Pure-XLA paged decode attention — the numeric oracle and the CPU/CI
    lowering of the Pallas kernel below.

    One query token per sequence attends over a block-paged ragged KV cache
    (the "Ragged Paged Attention" serving layout, PAPERS.md): sequences own
    fixed-size blocks of a shared pool, named by a per-sequence block table.

    q:            (B, H, D)        — this step's query, one token per stream
    k_pages:      (N, bs, H, D)    — the shared K pool: N blocks of bs slots
    v_pages:      (N, bs, H, D)    — the shared V pool
    block_tables: (B, nb) int32    — block ids per sequence, in position
                                     order; unused tail entries may point at
                                     any block (masked by context_lens)
    context_lens: (B,) int32       — valid tokens per sequence (<= nb*bs)

    Returns (B, H, D) in q.dtype. Positions >= context_len contribute
    EXACTLY zero: their scores are pinned to -1e30, which underflows to
    p = 0.0 in float32 — garbage in masked slots cannot leak in. A row
    with context_len == 0 returns all zeros (softmax over an all-masked
    row would otherwise go uniform and average the garbage), matching
    the Pallas kernel's empty-stream output.
    """
    sm_scale = _scale(sm_scale, q.shape[-1])
    b, h, d = q.shape
    bs = k_pages.shape[1]
    nb = block_tables.shape[1]
    t = nb * bs
    k = jnp.take(k_pages, block_tables, axis=0)  # (B, nb, bs, H, D)
    v = jnp.take(v_pages, block_tables, axis=0)
    k = k.reshape(b, t, h, d).astype(jnp.float32)
    v = v.reshape(b, t, h, d).astype(jnp.float32)
    s = jnp.einsum("bhd,bthd->bht", q.astype(jnp.float32), k,
                   precision=lax.Precision.HIGHEST) * sm_scale
    valid = jnp.arange(t)[None, :] < context_lens[:, None]  # (B, T)
    s = jnp.where(valid[:, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # an all-masked row (context_len == 0) softmaxes to uniform 1/T and
    # would average the gathered garbage — pin the whole row to zero, the
    # kernel's empty-stream output
    p = jnp.where((context_lens > 0)[:, None, None], p, 0.0)
    out = jnp.einsum("bht,bthd->bhd", p, v, precision=lax.Precision.HIGHEST)
    return out.astype(q.dtype)


def _paged_pallas(q, k_pages, v_pages, block_tables, context_lens, sm_scale,
                  interpret=False):
    """Pallas TPU ragged-paged-attention decode kernel.

    Grid (B, nb) with the block axis innermost; the block TABLE and context
    lengths ride in as scalar-prefetch args (``PrefetchScalarGridSpec``) so
    the index_map can steer each step's K/V DMA straight at the sequence's
    i-th pool block — the gather never materialises per-sequence contiguous
    KV. Online-softmax state (m, l, acc) lives in VMEM scratch carried
    across block steps; blocks wholly past context_len skip compute via
    ``pl.when`` (ragged early-out). VMEM per core is O(bs·H·D), independent
    of both sequence length and pool size.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, d = q.shape
    bs = k_pages.shape[1]
    nb = block_tables.shape[1]

    def kernel(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
               m_ref, l_ref, acc_ref):
        i = pl.program_id(0)  # sequence
        j = pl.program_id(1)  # block-table slot (innermost)

        @pl.when(j == 0)
        def _init():
            m_ref[:] = jnp.full((h,), _NEG_INF, jnp.float32)
            l_ref[:] = jnp.zeros((h,), jnp.float32)
            acc_ref[:] = jnp.zeros((h, d), jnp.float32)

        ctx = cl_ref[i]

        @pl.when(j * bs < ctx)  # ragged early-out past the context
        def _step():
            qv = q_ref[0].astype(jnp.float32)   # (H, D)
            kv = k_ref[0].astype(jnp.float32)   # (bs, H, D)
            vv = v_ref[0].astype(jnp.float32)
            s = jnp.sum(qv[None] * kv, axis=-1) * sm_scale  # (bs, H)
            pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (bs, h), 0)
            s = jnp.where(pos < ctx, s, _NEG_INF)
            m = m_ref[:]
            m_new = jnp.maximum(m, jnp.max(s, axis=0))
            p = jnp.exp(s - m_new[None, :])
            scale = jnp.exp(m - m_new)
            m_ref[:] = m_new
            l_ref[:] = l_ref[:] * scale + jnp.sum(p, axis=0)
            acc_ref[:] = (acc_ref[:] * scale[:, None]
                          + jnp.sum(p[:, :, None] * vv, axis=0))

        @pl.when(j == nb - 1)
        def _finish():
            l = jnp.maximum(l_ref[:], 1e-30)
            o_ref[0] = (acc_ref[:] / l[:, None]).astype(o_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda i, j, bt, cl: (i, 0, 0)),
            pl.BlockSpec((1, bs, h, d), lambda i, j, bt, cl: (bt[i, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, h, d), lambda i, j, bt, cl: (bt[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda i, j, bt, cl: (i, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h,), jnp.float32),
            pltpu.VMEM((h,), jnp.float32),
            pltpu.VMEM((h, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32),
      q, k_pages, v_pages)


def _paged_shapes_ok(q, k_pages):
    # Mosaic pads sublanes/lanes of the trailing (H, D) tile; keep D
    # lane-aligned. bs and nb are free (ragged tails are masked in-kernel).
    return q.shape[-1] % 8 == 0 and q.shape[-1] >= 8


def paged_attention(q, k_pages, v_pages, block_tables, context_lens,
                    sm_scale=None):
    """Paged ragged decode attention over a shared KV block pool.

    Platform selected at LOWERING time (like :func:`flash_attention`): the
    Pallas kernel on TPU, the pure-XLA gather reference everywhere else —
    identical outputs, so a CPU CI run proves the math the TPU kernel runs.
    Serving-only (no vjp): the decode path never differentiates.
    """
    sm_scale = _scale(sm_scale, q.shape[-1])
    if _paged_shapes_ok(q, k_pages) and _tpu_in_process():
        return lax.platform_dependent(
            q, k_pages, v_pages, block_tables, context_lens,
            tpu=functools.partial(_paged_pallas, sm_scale=sm_scale),
            default=functools.partial(paged_attention_reference,
                                      sm_scale=sm_scale),
        )
    return paged_attention_reference(q, k_pages, v_pages, block_tables,
                                     context_lens, sm_scale=sm_scale)


# --------------------------------------------- paged multi-query (verify)
def paged_attention_multi_reference(q, k_pages, v_pages, block_tables,
                                    context_lens, sm_scale=None):
    """Pure-XLA multi-query paged attention — q-length > 1 per sequence
    with PER-LANE context lengths. The speculative-decoding verify pass
    and the CPU/CI lowering of the Pallas kernel below.

    Each sequence carries T query lanes (this step's speculative window);
    lane t's K/V has already been scattered into the pool at its position,
    so causality within the window reduces to per-lane masking: lane t may
    only read pool positions < context_lens[b, t].

    q:            (B, T, H, D)     — T query tokens per stream
    k_pages:      (N, bs, H, D)    — the shared K pool
    v_pages:      (N, bs, H, D)    — the shared V pool
    block_tables: (B, nb) int32    — ONE table per sequence (lanes share it)
    context_lens: (B, T) int32     — valid pool positions PER LANE
                                     (monotone over t for a causal window)

    Returns (B, T, H, D) in q.dtype. T == 1 with context_lens (B, 1)
    reproduces :func:`paged_attention_reference` exactly. A lane with
    context_len == 0 returns all zeros, like the single-query oracle.
    """
    sm_scale = _scale(sm_scale, q.shape[-1])
    b, tq, h, d = q.shape
    bs = k_pages.shape[1]
    nb = block_tables.shape[1]
    t = nb * bs
    k = jnp.take(k_pages, block_tables, axis=0)  # (B, nb, bs, H, D)
    v = jnp.take(v_pages, block_tables, axis=0)
    k = k.reshape(b, t, h, d).astype(jnp.float32)
    v = v.reshape(b, t, h, d).astype(jnp.float32)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k,
                   precision=lax.Precision.HIGHEST) * sm_scale
    valid = jnp.arange(t)[None, None, :] < context_lens[:, :, None]  # (B,T,K)
    s = jnp.where(valid[:, None, :, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # all-masked lanes (context_len == 0) softmax to uniform and would
    # average gathered garbage — pin them to zero like the 1-query oracle
    p = jnp.where((context_lens > 0)[:, None, :, None], p, 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v,
                     precision=lax.Precision.HIGHEST)
    return out.astype(q.dtype)


def _paged_pallas_multi(q, k_pages, v_pages, block_tables, context_lens,
                        sm_scale, interpret=False):
    """Pallas TPU multi-query ragged-paged-attention kernel.

    The decode kernel generalized to T query lanes per sequence: the same
    (B, nb) grid and scalar-prefetch-steered K/V DMA, but the online-
    softmax state (m, l, acc) carries a T axis and masking is per lane
    (``context_lens`` is (B, T)). One extra row of VMEM scratch per lane —
    still O(T·H·D), independent of pool size and sequence length. Blocks
    wholly past the LONGEST lane's context skip compute (``pl.when``);
    shorter lanes mask the tail of shared blocks with -1e30 like the
    single-query kernel masks ragged block tails.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, tq, h, d = q.shape
    bs = k_pages.shape[1]
    nb = block_tables.shape[1]

    def kernel(bt_ref, cl_ref, q_ref, k_ref, v_ref, o_ref,
               m_ref, l_ref, acc_ref):
        i = pl.program_id(0)  # sequence
        j = pl.program_id(1)  # block-table slot (innermost)

        @pl.when(j == 0)
        def _init():
            m_ref[:] = jnp.full((tq, h), _NEG_INF, jnp.float32)
            l_ref[:] = jnp.zeros((tq, h), jnp.float32)
            acc_ref[:] = jnp.zeros((tq, h, d), jnp.float32)

        ctx = cl_ref[i]                       # (T,) per-lane context
        ctx_max = jnp.max(ctx)

        @pl.when(j * bs < ctx_max)  # ragged early-out past every lane
        def _step():
            qv = q_ref[0].astype(jnp.float32)   # (T, H, D)
            kv = k_ref[0].astype(jnp.float32)   # (bs, H, D)
            vv = v_ref[0].astype(jnp.float32)
            s = jnp.einsum("thd,shd->tsh", qv, kv) * sm_scale  # (T, bs, H)
            pos = j * bs + jax.lax.broadcasted_iota(jnp.int32, (tq, bs, h), 1)
            s = jnp.where(pos < ctx[:, None, None], s, _NEG_INF)
            m = m_ref[:]                                       # (T, H)
            m_new = jnp.maximum(m, jnp.max(s, axis=1))
            p = jnp.exp(s - m_new[:, None, :])
            scale = jnp.exp(m - m_new)
            m_ref[:] = m_new
            l_ref[:] = l_ref[:] * scale + jnp.sum(p, axis=1)
            acc_ref[:] = (acc_ref[:] * scale[:, :, None]
                          + jnp.einsum("tsh,shd->thd", p, vv))

        @pl.when(j == nb - 1)
        def _finish():
            l = jnp.maximum(l_ref[:], 1e-30)
            out = acc_ref[:] / l[:, :, None]
            # a lane that never saw a valid position accumulated
            # exp(-1e30 - -1e30) = 1 weights over garbage — pin it to the
            # oracle's empty-lane zero
            out = jnp.where((ctx > 0)[:, None, None], out, 0.0)
            o_ref[0] = out.astype(o_ref.dtype)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nb),
        in_specs=[
            pl.BlockSpec((1, tq, h, d), lambda i, j, bt, cl: (i, 0, 0, 0)),
            pl.BlockSpec((1, bs, h, d), lambda i, j, bt, cl: (bt[i, j], 0, 0, 0)),
            pl.BlockSpec((1, bs, h, d), lambda i, j, bt, cl: (bt[i, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tq, h, d),
                               lambda i, j, bt, cl: (i, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tq, h), jnp.float32),
            pltpu.VMEM((tq, h), jnp.float32),
            pltpu.VMEM((tq, h, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, tq, h, d), q.dtype),
        interpret=interpret,
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32),
      q, k_pages, v_pages)


def paged_attention_multi(q, k_pages, v_pages, block_tables, context_lens,
                          sm_scale=None):
    """Multi-query paged attention over a shared KV block pool: q is
    (B, T, H, D), context_lens (B, T) per lane — the speculative-decoding
    verify pass scores all T = k+1 window positions in this ONE dispatch.

    Platform selected at LOWERING time like :func:`paged_attention`: the
    Pallas kernel on TPU, the pure-XLA gather reference everywhere else.
    Serving-only (no vjp).
    """
    sm_scale = _scale(sm_scale, q.shape[-1])
    if _paged_shapes_ok(q, k_pages) and _tpu_in_process():
        return lax.platform_dependent(
            q, k_pages, v_pages, block_tables, context_lens,
            tpu=functools.partial(_paged_pallas_multi, sm_scale=sm_scale),
            default=functools.partial(paged_attention_multi_reference,
                                      sm_scale=sm_scale),
        )
    return paged_attention_multi_reference(q, k_pages, v_pages, block_tables,
                                           context_lens, sm_scale=sm_scale)


@register(
    "_contrib_PagedAttention",
    arg_names=("query", "key_pages", "value_pages", "block_table",
               "context_len"),
    params={
        "sm_scale": Param.float(-1.0),
    },
)
def _paged_attention_op(octx, attrs, args, auxs):
    """Paged decode attention (serving): one query token per sequence over a
    block-paged shared KV pool. query: (B, heads, head_dim); key_pages/
    value_pages: (num_blocks, block_size, heads, head_dim); block_table:
    (B, nb); context_len: (B,). The serving engine drives the jax-level
    :func:`paged_attention` directly; this registration keeps the kernel
    reachable from nd/sym like every other op."""
    q, kp, vp, bt, cl = args
    scale = attrs["sm_scale"]
    out = paged_attention(q, kp, vp, bt.astype(jnp.int32),
                          cl.astype(jnp.int32),
                          None if scale <= 0 else scale)
    return [out], []


def _paged_infer_shape(attrs, in_shapes, aux_shapes):
    qs = in_shapes[0]
    if qs is None:
        raise ValueError("PagedAttention: query shape required")
    return in_shapes, [tuple(qs)], []


get_op("_contrib_PagedAttention")._infer_shape = _paged_infer_shape
