"""Array-creation ops (reference: src/operator/tensor/init_op.cc —
_zeros/_ones/_full/_arange) and shape-like creation (zeros_like/ones_like)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .registry import Param, register_simple


def _dtype_or(attrs, default=np.float32):
    dt = attrs.get("dtype")
    return default if dt is None else dt


def _shape_0to1(shape):
    """MXNet uses 0 as the 'unknown batch' wildcard in creation shapes (e.g.
    RNN begin_state uses sym.zeros((0, H)), rnn_cell.py state_info). The
    reference's nnvm inference resolves 0 bidirectionally; the XLA-friendly
    equivalent is dim 1 + broadcasting — downstream elemwise ops expand it to
    the real batch, with identical numerics and gradients."""
    return tuple(1 if s == 0 else s for s in shape)


register_simple(
    "_zeros",
    lambda attrs: jnp.zeros(_shape_0to1(attrs["shape"]), _dtype_or(attrs)),
    arg_names=(),
    params={"shape": Param.shape(()), "dtype": Param.dtype(None)},
)
register_simple(
    "_ones",
    lambda attrs: jnp.ones(_shape_0to1(attrs["shape"]), _dtype_or(attrs)),
    arg_names=(),
    params={"shape": Param.shape(()), "dtype": Param.dtype(None)},
)
register_simple(
    "_full",
    lambda attrs: jnp.full(_shape_0to1(attrs["shape"]), attrs["value"], _dtype_or(attrs)),
    arg_names=(),
    params={"shape": Param.shape(()), "value": Param.float(0.0), "dtype": Param.dtype(None)},
)


def _arange(attrs):
    start, stop, step = attrs["start"], attrs["stop"], attrs["step"]
    if stop is None:
        start, stop = 0.0, start
    out = jnp.arange(start, stop, step, dtype=_dtype_or(attrs))
    if attrs["repeat"] > 1:
        out = jnp.repeat(out, attrs["repeat"])
    return out


register_simple(
    "_arange",
    _arange,
    arg_names=(),
    params={
        "start": Param.float(0.0),
        "stop": Param(lambda v: None if v in (None, "None", "") else float(v), None),
        "step": Param.float(1.0),
        "repeat": Param.int(1),
        "dtype": Param.dtype(None),
    },
)

register_simple("zeros_like", lambda attrs, x: jnp.zeros_like(x), arg_names=("data",))
register_simple("ones_like", lambda attrs, x: jnp.ones_like(x), arg_names=("data",))
