"""Contrib ops: SSD MultiBox family, Proposal, CTCLoss, FFT, count_sketch,
quantize (reference: src/operator/contrib/* — multibox_prior.cc:78,
multibox_target.cc:285, multibox_detection.cc:175, proposal.cc:450,
ctc_loss.cc:52, fft.cc:28, count_sketch.cc:26).

TPU design: the reference's hand CUDA kernels (anchor matching loops, greedy
NMS, warp-ctc) become masked fixed-shape jnp computations + ``lax.fori_loop``
where iteration is inherent (greedy NMS suppression, CTC time recursion via
``lax.scan``). Everything is jit-compatible and differentiable where the
reference's op is (CTC; the detection ops are zero-grad, matching the
reference's Backward = 0).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError, parse_shape
from .registry import Param, get_op, register, register_simple


def _tuple_f(default):
    def _parse(v):
        if isinstance(v, (tuple, list)):
            return tuple(float(x) for x in v)
        s = str(v).strip().strip("()[]")
        if not s:
            return ()
        return tuple(float(t) for t in s.split(",") if t.strip())

    return Param(_parse, default)


# ---------------------------------------------------------------- MultiBoxPrior
@register(
    "_contrib_MultiBoxPrior",
    arg_names=("data",),
    params={
        "sizes": _tuple_f((1.0,)),
        "ratios": _tuple_f((1.0,)),
        "clip": Param.bool(False),
        "steps": _tuple_f((-1.0, -1.0)),
        "offsets": _tuple_f((0.5, 0.5)),
    },
    alias=("MultiBoxPrior",),
)
def _multibox_prior(octx, attrs, args, auxs):
    """Anchor generation: per cell, one box per size at ratio[0], plus one box
    per extra ratio at sizes[0] (behavioral contract of multibox_prior.cc:12-52:
    w=h=size/2 for the size set; w=s0*sqrt(r)/2, h=s0/(2*sqrt(r)) for ratios)."""
    x = args[0]
    H, W = x.shape[2], x.shape[3]
    if H < 1 or W < 1:
        raise MXNetError(
            "MultiBoxPrior: input feature map has zero spatial size %dx%d — "
            "the input image is too small for this network's downsampling "
            "(SSD-300 needs ~300px inputs)" % (H, W))
    sizes = jnp.asarray(attrs["sizes"], jnp.float32)
    ratios = jnp.asarray(attrs["ratios"], jnp.float32)
    step_y, step_x = attrs["steps"]
    if step_y <= 0 or step_x <= 0:
        step_y, step_x = 1.0 / H, 1.0 / W
    off_y, off_x = attrs["offsets"]
    cy = (jnp.arange(H, dtype=jnp.float32) + off_y) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + off_x) * step_x
    # half-extents for the anchor set at one cell: (num_sizes + num_ratios - 1, 2)
    hw_sizes = jnp.stack([sizes / 2, sizes / 2], axis=1)  # ratio = 1 branch
    r = jnp.sqrt(ratios[1:])
    hw_ratios = jnp.stack([sizes[0] * r / 2, sizes[0] / r / 2], axis=1)
    half = jnp.concatenate([hw_sizes, hw_ratios], axis=0)  # (K, 2) [w, h]
    K = half.shape[0]
    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")  # (H, W)
    centers = jnp.stack([cxg, cyg], axis=-1).reshape(H * W, 1, 2)  # [cx, cy]
    mins = centers - half[None, :, :]
    maxs = centers + half[None, :, :]
    boxes = jnp.concatenate([mins, maxs], axis=-1).reshape(1, H * W * K, 4)
    if attrs["clip"]:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return [jax.lax.stop_gradient(boxes)], []


def _mbp_infer(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    K = len(attrs["sizes"]) + len(attrs["ratios"]) - 1
    return [tuple(data)], [(1, data[2] * data[3] * K, 4)], []


get_op("_contrib_MultiBoxPrior")._infer_shape = _mbp_infer


# ------------------------------------------------------------- box utilities
def _iou_corner(a, b):
    """IoU between (..., 4) corner boxes a and b (broadcasting)."""
    ix0 = jnp.maximum(a[..., 0], b[..., 0])
    iy0 = jnp.maximum(a[..., 1], b[..., 1])
    ix1 = jnp.minimum(a[..., 2], b[..., 2])
    iy1 = jnp.minimum(a[..., 3], b[..., 3])
    iw = jnp.maximum(ix1 - ix0, 0.0)
    ih = jnp.maximum(iy1 - iy0, 0.0)
    inter = iw * ih
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * jnp.maximum(a[..., 3] - a[..., 1], 0.0)
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * jnp.maximum(b[..., 3] - b[..., 1], 0.0)
    union = area_a + area_b - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-12), 0.0)


def _encode_loc(anchors, gt, variances):
    """Center-form offset encoding (SSD standard, multibox_target contract)."""
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    acx = (anchors[..., 0] + anchors[..., 2]) / 2
    acy = (anchors[..., 1] + anchors[..., 3]) / 2
    gw = jnp.maximum(gt[..., 2] - gt[..., 0], 1e-12)
    gh = jnp.maximum(gt[..., 3] - gt[..., 1], 1e-12)
    gcx = (gt[..., 0] + gt[..., 2]) / 2
    gcy = (gt[..., 1] + gt[..., 3]) / 2
    v0, v1, v2, v3 = variances
    return jnp.stack(
        [
            (gcx - acx) / jnp.maximum(aw, 1e-12) / v0,
            (gcy - acy) / jnp.maximum(ah, 1e-12) / v1,
            jnp.log(gw / jnp.maximum(aw, 1e-12)) / v2,
            jnp.log(gh / jnp.maximum(ah, 1e-12)) / v3,
        ],
        axis=-1,
    )


def _decode_loc(anchors, pred, variances, clip):
    aw = anchors[..., 2] - anchors[..., 0]
    ah = anchors[..., 3] - anchors[..., 1]
    acx = (anchors[..., 0] + anchors[..., 2]) / 2
    acy = (anchors[..., 1] + anchors[..., 3]) / 2
    v0, v1, v2, v3 = variances
    cx = pred[..., 0] * v0 * aw + acx
    cy = pred[..., 1] * v1 * ah + acy
    w = jnp.exp(pred[..., 2] * v2) * aw / 2
    h = jnp.exp(pred[..., 3] * v3) * ah / 2
    out = jnp.stack([cx - w, cy - h, cx + w, cy + h], axis=-1)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


# ---------------------------------------------------------------- MultiBoxTarget
@register(
    "_contrib_MultiBoxTarget",
    arg_names=("anchor", "label", "cls_pred"),
    params={
        "overlap_threshold": Param.float(0.5),
        "ignore_label": Param.float(-1.0),
        "negative_mining_ratio": Param.float(-1.0),
        "negative_mining_thresh": Param.float(0.5),
        "minimum_negative_samples": Param.int(0),
        "variances": _tuple_f((0.1, 0.1, 0.2, 0.2)),
    },
    num_outputs=3,
    output_names=("loc_target", "loc_mask", "cls_target"),
    alias=("MultiBoxTarget",),
)
def _multibox_target(octx, attrs, args, auxs):
    """Anchor-GT matching + target encoding (multibox_target-inl.h contract):
    bipartite best-anchor-per-gt match first, then IoU>threshold matches;
    matched anchors get class gt+1 and encoded loc offsets; unmatched get
    background 0 (or ignore_label when hard-negative mining samples them out).
    """
    anchors = args[0].reshape(-1, 4)  # (A, 4)
    labels = args[1]  # (N, L, 5) [cls, x0, y0, x1, y1], cls<0 = pad
    cls_preds = args[2]  # (N, C, A)
    A = anchors.shape[0]
    N, L, _ = labels.shape
    variances = attrs["variances"]

    def per_batch(lab, cp):
        valid = lab[:, 0] >= 0  # (L,)
        gt_boxes = lab[:, 1:5]
        ious = _iou_corner(anchors[:, None, :], gt_boxes[None, :, :])  # (A, L)
        ious = jnp.where(valid[None, :], ious, -1.0)
        # 1) bipartite: each valid gt claims its best anchor
        best_anchor_per_gt = jnp.argmax(ious, axis=0)  # (L,)
        forced = jnp.zeros((A,), jnp.int32) - 1
        forced = forced.at[best_anchor_per_gt].set(
            jnp.where(valid, jnp.arange(L), -1).astype(jnp.int32)
        )
        # 2) threshold matching for the rest
        best_gt = jnp.argmax(ious, axis=1).astype(jnp.int32)  # (A,)
        best_iou = jnp.max(ious, axis=1)
        matched_gt = jnp.where(
            forced >= 0, forced,
            jnp.where(best_iou > attrs["overlap_threshold"], best_gt, -1),
        )
        is_pos = matched_gt >= 0
        safe_gt = jnp.maximum(matched_gt, 0)
        cls_t = jnp.where(is_pos, lab[safe_gt, 0] + 1.0, 0.0)
        loc_t = _encode_loc(anchors, gt_boxes[safe_gt], variances)
        loc_t = jnp.where(is_pos[:, None], loc_t, 0.0)
        mask = jnp.where(is_pos[:, None], 1.0, 0.0) * jnp.ones((A, 4))
        # hard negative mining: rank negatives by background-class confidence
        # deficit (max non-bg prob), keep ratio*num_pos
        if attrs["negative_mining_ratio"] > 0:
            num_pos = jnp.sum(is_pos)
            max_neg = jnp.maximum(
                (attrs["negative_mining_ratio"] * num_pos).astype(jnp.int32),
                attrs["minimum_negative_samples"],
            )
            neg_ok = (~is_pos) & (best_iou < attrs["negative_mining_thresh"])
            neg_score = jnp.where(neg_ok, jnp.max(cp[1:, :], axis=0), -jnp.inf)
            order = jnp.argsort(-neg_score)
            rank = jnp.zeros((A,), jnp.int32).at[order].set(jnp.arange(A, dtype=jnp.int32))
            keep_neg = neg_ok & (rank < max_neg)
            cls_t = jnp.where(is_pos, cls_t, jnp.where(keep_neg, 0.0, attrs["ignore_label"]))
        return loc_t.reshape(-1), mask.reshape(-1), cls_t

    loc_target, loc_mask, cls_target = jax.vmap(per_batch)(labels, cls_preds)
    stop = jax.lax.stop_gradient
    return [stop(loc_target), stop(loc_mask), stop(cls_target)], []


def _mbt_infer(attrs, in_shapes, aux_shapes):
    anchor, label, cls_pred = in_shapes
    A = anchor[1]
    N = label[0]
    return (
        [tuple(anchor), tuple(label), tuple(cls_pred)],
        [(N, A * 4), (N, A * 4), (N, A)],
        [],
    )


get_op("_contrib_MultiBoxTarget")._infer_shape = _mbt_infer


# Bounded NMS vectorization width shared by the detection ops below:
# batch-wide vmapped NMS fused with its decode stage crashes the v5e TPU
# worker ("kernel fault") at detection scale from N=16 up — deterministic,
# N<=8 clean — and chunking also bounds the loop body's working set for any
# batch size. Width 4 measured equal to the batch-wide vmap's steady rate
# (docs/perf.md section ssd).
_NMS_CHUNK = 4


# ------------------------------------------------------------ MultiBoxDetection
def _nms_loop(boxes, scores, cls_ids, nms_threshold, force_suppress, topk):
    """Greedy NMS over score-sorted boxes: a fori_loop where step i suppresses
    all lower-ranked boxes overlapping box i (class-aware unless force)."""
    A = boxes.shape[0]
    order = jnp.argsort(-scores)
    b = boxes[order]
    s = scores[order]
    c = cls_ids[order]
    n_iter = A if topk is None or topk <= 0 else min(topk, A)
    keep = s > -jnp.inf  # all True; invalid already have -inf score

    def body(i, keep):
        ious = _iou_corner(b[i][None, :], b)
        same_cls = jnp.ones((A,), bool) if force_suppress else (c == c[i])
        later = jnp.arange(A) > i
        suppress = (ious > nms_threshold) & same_cls & later & keep[i]
        return keep & ~suppress

    keep = jax.lax.fori_loop(0, n_iter, body, keep)
    return b, s, c, keep


@register(
    "_contrib_MultiBoxDetection",
    arg_names=("cls_prob", "loc_pred", "anchor"),
    params={
        "clip": Param.bool(True),
        "threshold": Param.float(0.01),
        "background_id": Param.int(0),
        "nms_threshold": Param.float(0.5),
        "force_suppress": Param.bool(False),
        "variances": _tuple_f((0.1, 0.1, 0.2, 0.2)),
        "nms_topk": Param.int(-1),
    },
    alias=("MultiBoxDetection",),
)
def _multibox_detection(octx, attrs, args, auxs):
    """Decode + per-class greedy NMS → (N, A, 6) rows
    [class_id, score, x0, y0, x1, y1], -1-filled for suppressed slots
    (multibox_detection-inl.h contract)."""
    cls_prob, loc_pred, anchors = args
    N, C, A = cls_prob.shape
    anchors = anchors.reshape(-1, 4)
    bg = attrs["background_id"]

    def per_batch_pre(cp, lp):
        # class with max prob excluding background
        cls_only = jnp.concatenate([cp[:bg], cp[bg + 1 :]], axis=0) if C > 1 else cp
        ids = jnp.argmax(cls_only, axis=0)
        ids = jnp.where(ids >= bg, ids + 1, ids) if C > 1 else ids  # skip bg slot
        score = jnp.max(cls_only, axis=0)
        valid = score > attrs["threshold"]
        boxes = _decode_loc(anchors, lp.reshape(-1, 4), attrs["variances"], attrs["clip"])
        score = jnp.where(valid, score, -jnp.inf)
        return boxes, score, ids

    def per_batch_nms(args3):
        boxes, score, ids = args3
        b, s, c, keep = _nms_loop(
            boxes, score, ids, attrs["nms_threshold"], attrs["force_suppress"], attrs["nms_topk"]
        )
        ok = keep & (s > -jnp.inf)
        row = jnp.concatenate(
            [
                jnp.where(ok, (c - (1 if C > 1 else 0)).astype(jnp.float32), -1.0)[:, None],
                jnp.where(ok, s, -1.0)[:, None],
                jnp.where(ok[:, None], b, -1.0),
            ],
            axis=1,
        )
        return row

    # decode/argmax vectorize over the batch; the sequential NMS stage
    # runs in bounded-width chunks instead of one batch-wide vmap (the TPU
    # fault guard — see _NMS_CHUNK above)
    pre = jax.vmap(per_batch_pre)(cls_prob, loc_pred.reshape(N, -1))
    out = jax.lax.map(per_batch_nms, pre, batch_size=min(_NMS_CHUNK, N))
    return [jax.lax.stop_gradient(out)], []


def _mbd_infer(attrs, in_shapes, aux_shapes):
    cls_prob = in_shapes[0]
    N, C, A = cls_prob
    return [tuple(s) for s in in_shapes], [(N, A, 6)], []


get_op("_contrib_MultiBoxDetection")._infer_shape = _mbd_infer


# ---------------------------------------------------------------- Proposal
@register(
    "_contrib_Proposal",
    arg_names=("cls_prob", "bbox_pred", "im_info"),
    params={
        "rpn_pre_nms_top_n": Param.int(6000),
        "rpn_post_nms_top_n": Param.int(300),
        "threshold": Param.float(0.7),
        "rpn_min_size": Param.int(16),
        "scales": _tuple_f((4.0, 8.0, 16.0, 32.0)),
        "ratios": _tuple_f((0.5, 1.0, 2.0)),
        "feature_stride": Param.int(16),
        "output_score": Param.bool(False),
        "iou_loss": Param.bool(False),
    },
    num_outputs=lambda attrs: 2 if attrs.get("output_score") else 1,
    output_names=lambda attrs: ["output", "score"] if attrs.get("output_score") else ["output"],
)
def _proposal(octx, attrs, args, auxs):
    """RPN proposal layer (proposal.cc contract): generate scale×ratio anchors
    on the feature grid, apply bbox deltas, clip to image, filter small boxes,
    take pre-NMS topk by fg score, greedy NMS, emit post-NMS topk rois
    (batch_idx, x0, y0, x1, y1)."""
    cls_prob, bbox_pred, im_info = args  # (N, 2K, H, W), (N, 4K, H, W), (N, 3)
    N, twoK, H, W = cls_prob.shape
    K = twoK // 2
    stride = attrs["feature_stride"]
    scales = jnp.asarray(attrs["scales"], jnp.float32)
    ratios = jnp.asarray(attrs["ratios"], jnp.float32)
    # base anchors centered at (stride-1)/2, standard Faster-RCNN enumeration
    base = (stride - 1) / 2.0
    ws = []
    size = stride * stride
    for r in attrs["ratios"]:
        size_r = size / r
        w0 = np.round(np.sqrt(size_r))
        h0 = np.round(w0 * r)
        for s in attrs["scales"]:
            ws.append((w0 * s, h0 * s))
    half = jnp.asarray(ws, jnp.float32) / 2.0  # (K, 2)
    sy = jnp.arange(H, dtype=jnp.float32) * stride + base
    sx = jnp.arange(W, dtype=jnp.float32) * stride + base
    cyg, cxg = jnp.meshgrid(sy, sx, indexing="ij")
    centers = jnp.stack([cxg, cyg], -1).reshape(-1, 1, 2)  # (HW, 1, 2)
    anchors = jnp.concatenate(
        [centers - half[None], centers + half[None]], axis=-1
    ).reshape(-1, 4)  # (HW*K, 4) — order (h, w, k)

    def per_batch(cp, bp, info):
        im_h, im_w = info[0], info[1]
        fg = cp[K:].transpose(1, 2, 0).reshape(-1)  # (H*W*K,) foreground scores
        deltas = bp.reshape(K, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        # decode (unnormalized variances=1, pixel coords)
        aw = anchors[:, 2] - anchors[:, 0] + 1
        ah = anchors[:, 3] - anchors[:, 1] + 1
        acx = anchors[:, 0] + aw / 2
        acy = anchors[:, 1] + ah / 2
        cx = deltas[:, 0] * aw + acx
        cy = deltas[:, 1] * ah + acy
        w = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * aw
        h = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * ah
        boxes = jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], -1)
        boxes = jnp.stack(
            [
                jnp.clip(boxes[:, 0], 0, im_w - 1),
                jnp.clip(boxes[:, 1], 0, im_h - 1),
                jnp.clip(boxes[:, 2], 0, im_w - 1),
                jnp.clip(boxes[:, 3], 0, im_h - 1),
            ],
            -1,
        )
        min_size = attrs["rpn_min_size"] * info[2]
        keep_size = ((boxes[:, 2] - boxes[:, 0] + 1) >= min_size) & (
            (boxes[:, 3] - boxes[:, 1] + 1) >= min_size
        )
        fg = jnp.where(keep_size, fg, -jnp.inf)
        pre_n = min(attrs["rpn_pre_nms_top_n"], fg.shape[0])
        top_s, top_i = jax.lax.top_k(fg, pre_n)
        top_b = boxes[top_i]
        return top_b, top_s

    def per_batch_nms(args2):
        top_b, top_s = args2
        pre_n = top_s.shape[0]
        b, s, _, keep = _nms_loop(
            top_b, top_s, jnp.zeros(pre_n, jnp.int32), attrs["threshold"], True,
            attrs["rpn_post_nms_top_n"] * 4,
        )
        post_n = attrs["rpn_post_nms_top_n"]
        s_kept = jnp.where(keep, s, -jnp.inf)
        sel_s, sel_i = jax.lax.top_k(s_kept, min(post_n, pre_n))
        rois = b[sel_i]
        pad = post_n - rois.shape[0]
        if pad > 0:
            rois = jnp.concatenate([rois, jnp.zeros((pad, 4))], 0)
            sel_s = jnp.concatenate([sel_s, jnp.full((pad,), -jnp.inf)], 0)
        return rois, sel_s

    # same TPU-fault guard as MultiBoxDetection: anchor decode + top_k
    # vectorize over the batch, the sequential NMS stage runs in bounded
    # lax.map chunks (see _NMS_CHUNK above)
    pre = jax.vmap(per_batch)(cls_prob, bbox_pred, im_info)
    rois, scores = jax.lax.map(per_batch_nms, pre,
                               batch_size=min(_NMS_CHUNK, N))
    batch_idx = jnp.repeat(
        jnp.arange(N, dtype=jnp.float32)[:, None], rois.shape[1], axis=1
    )[..., None]
    out = jnp.concatenate([batch_idx, rois], axis=-1).reshape(-1, 5)
    outs = [jax.lax.stop_gradient(out)]
    if attrs["output_score"]:
        outs.append(jax.lax.stop_gradient(scores.reshape(-1, 1)))
    return outs, []


def _proposal_infer(attrs, in_shapes, aux_shapes):
    cls_prob = in_shapes[0]
    N = cls_prob[0]
    post = attrs["rpn_post_nms_top_n"]
    outs = [(N * post, 5)]
    if attrs.get("output_score"):
        outs.append((N * post, 1))
    return [tuple(s) for s in in_shapes], outs, []


get_op("_contrib_Proposal")._infer_shape = _proposal_infer


# ---------------------------------------------------------------- CTCLoss
@register(
    "_contrib_CTCLoss",
    arg_names=("data", "label"),
    params={},
    num_outputs=2,
    num_visible_outputs=1,
    output_names=("output", "grad"),
    alias=("CTCLoss", "_contrib_ctc_loss", "WarpCTC"),
)
def _ctc_loss(octx, attrs, args, auxs):
    """CTC negative log-likelihood via the alpha (forward) recursion in log
    space, scanned over time (reference wraps warp-ctc, ctc_loss.cc; blank=0,
    labels 0-padded). Fully differentiable through lax.scan — the backward is
    autodiff instead of warp-ctc's hand beta recursion."""
    data, label = args  # (T, N, C), (N, L)
    T, N, C = data.shape
    L = label.shape[1]
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = label.astype(jnp.int32)  # 0 = padding (and 0 = blank in alphabet)
    S = 2 * L + 1
    # extended label sequence: blank, l1, blank, l2, ... blank
    ext = jnp.zeros((N, S), jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    valid_lab = lab > 0
    lab_len = jnp.sum(valid_lab, axis=1)  # (N,)
    ext_len = 2 * lab_len + 1
    neg_inf = -1e30
    # allowed skip: s-2 -> s if ext[s] != 0 and ext[s] != ext[s-2]
    can_skip = jnp.zeros((N, S), bool)
    can_skip = can_skip.at[:, 2:].set(
        (ext[:, 2:] != 0) & (ext[:, 2:] != ext[:, :-2])
    )
    alpha0 = jnp.full((N, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[0, :, 0])
    alpha0 = alpha0.at[:, 1].set(
        jnp.where(lab_len > 0, jnp.take_along_axis(logp[0], ext[:, 1:2], axis=1)[:, 0], neg_inf)
    )

    def step(alpha, logp_t):
        a_prev = alpha
        a_shift1 = jnp.concatenate([jnp.full((N, 1), neg_inf), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate([jnp.full((N, 2), neg_inf), alpha[:, :-2]], axis=1)
        a_shift2 = jnp.where(can_skip, a_shift2, neg_inf)
        merged = jnp.logaddexp(jnp.logaddexp(a_prev, a_shift1), a_shift2)
        emit = jnp.take_along_axis(logp_t, ext, axis=1)  # (N, S)
        return merged + emit, None

    alpha, _ = jax.lax.scan(step, alpha0, logp[1:])
    # mask timesteps beyond ext_len positions: gather final two states
    idx_last = jnp.maximum(ext_len - 1, 0)
    idx_prev = jnp.maximum(ext_len - 2, 0)
    a_last = jnp.take_along_axis(alpha, idx_last[:, None], axis=1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, idx_prev[:, None], axis=1)[:, 0]
    loss = -jnp.logaddexp(a_last, a_prev)
    grad_placeholder = jnp.zeros_like(data)
    return [loss, grad_placeholder], []


def _ctc_infer(attrs, in_shapes, aux_shapes):
    data, label = in_shapes
    return [tuple(data), tuple(label)], [(data[1],), tuple(data)], []


get_op("_contrib_CTCLoss")._infer_shape = _ctc_infer
get_op("_contrib_CTCLoss").is_loss = True


# ---------------------------------------------------------------- FFT / IFFT
def _fft(attrs, x):
    """(reference: fft.cc — cuFFT; output interleaves re/im on last dim)"""
    f = jnp.fft.fft(x.astype(jnp.complex64), axis=-1)
    out = jnp.stack([f.real, f.imag], axis=-1).reshape(x.shape[:-1] + (2 * x.shape[-1],))
    return out.astype(jnp.float32)


def _ifft(attrs, x):
    n = x.shape[-1] // 2
    pairs = x.reshape(x.shape[:-1] + (n, 2))
    c = pairs[..., 0] + 1j * pairs[..., 1]
    out = jnp.fft.ifft(c, axis=-1).real * n  # reference scales by n (cuFFT unnormalized)
    return out.astype(jnp.float32)


register_simple(
    "_contrib_fft", _fft, arg_names=("data",),
    params={"compute_size": Param.int(128)}, alias=("fft",),
)
register_simple(
    "_contrib_ifft", _ifft, arg_names=("data",),
    params={"compute_size": Param.int(128)}, alias=("ifft",),
)


# ---------------------------------------------------------------- count_sketch
@register(
    "_contrib_count_sketch",
    arg_names=("data", "h", "s"),
    params={"out_dim": Param.int(), "processing_batch_size": Param.int(32)},
    alias=("count_sketch",),
)
def _count_sketch(octx, attrs, args, auxs):
    """Count-sketch projection (count_sketch.cc): out[:, h[i]] += s[i]*x[:, i]."""
    x, h, s = args
    out_dim = attrs["out_dim"]
    hi = jax.lax.stop_gradient(h).astype(jnp.int32).reshape(-1)
    si = jax.lax.stop_gradient(s).reshape(-1)
    out = jnp.zeros(x.shape[:-1] + (out_dim,), x.dtype)
    return [out.at[..., hi].add(x * si)], []


def _cs_infer(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    return [tuple(s) for s in in_shapes], [tuple(data[:-1]) + (attrs["out_dim"],)], []


get_op("_contrib_count_sketch")._infer_shape = _cs_infer


# ---------------------------------------------------------------- quantize
@register(
    "_contrib_quantize",
    arg_names=("data", "min_range", "max_range"),
    params={"out_type": Param.str("uint8")},
    num_outputs=3,
    output_names=("output", "min_range", "max_range"),
    alias=("quantize",),
)
def _quantize(octx, attrs, args, auxs):
    x, mn, mx = args
    qmax = 255.0 if attrs["out_type"] == "uint8" else 127.0
    scale = qmax / jnp.maximum(mx - mn, 1e-12)
    q = jnp.clip(jnp.round((x - mn) * scale), 0, qmax)
    dt = jnp.uint8 if attrs["out_type"] == "uint8" else jnp.int8
    return [jax.lax.stop_gradient(q.astype(dt)), mn, mx], []


@register(
    "_contrib_dequantize",
    arg_names=("data", "min_range", "max_range"),
    params={"out_type": Param.str("float32")},
    alias=("dequantize",),
)
def _dequantize(octx, attrs, args, auxs):
    q, mn, mx = args
    qmax = 255.0 if q.dtype == jnp.uint8 else 127.0
    return [q.astype(jnp.float32) * (mx - mn) / qmax + mn], []
