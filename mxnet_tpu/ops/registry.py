"""Operator registry — the TPU-native analog of the reference's dual registration
systems (legacy ``OperatorProperty``/``MXNET_REGISTER_OP_PROPERTY``,
include/mxnet/operator.h:166, and NNVM ``FCompute``/``NNVM_REGISTER_OP``,
include/mxnet/op_attr_types.h:59-63 — 298 registrations total, SURVEY §2.3).

Design differences, deliberate and TPU-first:

* One registration system, not two. Every op is a **pure jax function**
  ``forward(opctx, attrs, args, auxs) -> (outputs, new_auxs)``. There are no
  hand-written Backward kernels: gradients come from jax autodiff over the same
  forward (the reference's per-op ``Backward``/``FGradient`` pairs collapse into
  ``jax.vjp``). Ops that need a non-mathematical gradient (SoftmaxOutput writes
  ``p - onehot(label)`` directly, src/operator/softmax_output-inl.h) express it
  with ``jax.custom_vjp`` inside their forward.
* Aux state (BatchNorm moving stats — ``FMutateInputs`` in the reference) is
  functional: auxs go in, updated auxs come out, and the executor writes them
  back. This is the jit-compatible form of the engine's mutable write-vars.
* Shape/type inference (``FInferShape``/``FInferType``) defaults to
  ``jax.eval_shape`` over the forward — the compiler is the shape oracle — with
  per-op overrides only where inference must fill in *unknown parameter shapes*
  from data shapes (FullyConnected weight, Convolution kernel, ...), which
  abstract evaluation cannot do backwards.
* Randomness (Dropout, samplers) is explicit: ops declaring ``stochastic=True``
  receive a threefry key via ``opctx.rng`` instead of the reference's hidden
  per-device RNG resource (src/resource.cc:158).
"""
from __future__ import annotations

import functools

from ..base import MXNetError, parse_bool, parse_shape

__all__ = ["OpContext", "Operator", "register", "register_simple", "get_op", "list_ops", "Param"]

_OP_REGISTRY = {}


class OpContext:
    """Per-invocation execution context handed to op forwards.

    Replaces the reference's ``OpContext`` (include/mxnet/op_attr_types.h:35-50:
    is_train, RunContext, requested resources): here it carries the training flag
    and an explicit PRNG key (None for deterministic ops).
    """

    __slots__ = ("is_train", "rng")

    def __init__(self, is_train=False, rng=None):
        self.is_train = is_train
        self.rng = rng


class Param:
    """Attr schema entry — the analog of a dmlc::Parameter field (DMLC_DECLARE_FIELD):
    a parser (from the JSON string form or a python value), a default, and a
    required flag. Gives every op keyword validation + canonicalization so attrs
    round-trip through Symbol JSON identically to the reference."""

    __slots__ = ("parse", "default", "required", "kind")

    _REQUIRED = object()

    def __init__(self, parse, default=_REQUIRED, kind=None):
        self.parse = parse
        self.default = default
        self.required = default is Param._REQUIRED
        # human-readable type name for generated docs (op_doc.py)
        self.kind = kind or getattr(parse, "__name__", "value").replace("parse_", "")

    @staticmethod
    def shape(default=_REQUIRED):
        return Param(parse_shape, default, kind="shape")

    @staticmethod
    def int(default=_REQUIRED):
        return Param(lambda v: int(float(v)), default, kind="int")

    @staticmethod
    def float(default=_REQUIRED):
        return Param(float, default, kind="float")

    @staticmethod
    def bool(default=_REQUIRED):
        return Param(parse_bool, default, kind="boolean")

    @staticmethod
    def str(default=_REQUIRED):
        return Param(lambda v: str(v), default, kind="string")

    @staticmethod
    def dtype(default=_REQUIRED):
        import numpy as np

        def _parse(v):
            if v is None or (isinstance(v, str) and v in ("None", "")):
                return None
            if v == "bfloat16":
                import jax.numpy as jnp

                return np.dtype(jnp.bfloat16)
            return np.dtype(v)

        return Param(_parse, default, kind="dtype")


class Operator:
    """A registered operator definition."""

    def __init__(
        self,
        name,
        forward,
        arg_names=("data",),
        aux_names=(),
        num_outputs=1,
        output_names=None,
        params=None,
        infer_shape=None,
        infer_type=None,
        stochastic=False,
        key_var_num_args=None,
        num_visible_outputs=None,
        alias=(),
        keep_extras=False,
    ):
        self.name = name
        self.forward = forward
        self._arg_names = arg_names
        self._aux_names = aux_names
        self._num_outputs = num_outputs
        self._output_names = output_names
        self.params = params or {}
        self._infer_shape = infer_shape
        self._infer_type = infer_type
        self.stochastic = stochastic
        # name of the attr carrying the variadic input count (nnvm key_var_num_args,
        # e.g. Concat's num_args / add_n's num_args)
        self.key_var_num_args = key_var_num_args
        self._num_visible_outputs = num_visible_outputs
        self.alias = alias
        # ops with open-ended kwargs (Custom forwards them to the user prop
        # ctor) keep unknown attrs in the canonical dict instead of the
        # node-attr side channel
        self.keep_extras = keep_extras

    # ---- introspection ---------------------------------------------------
    def arg_names(self, attrs):
        a = self._arg_names
        return list(a(attrs)) if callable(a) else list(a)

    def aux_names(self, attrs):
        a = self._aux_names
        return list(a(attrs)) if callable(a) else list(a)

    def num_outputs(self, attrs):
        n = self._num_outputs
        return n(attrs) if callable(n) else n

    def num_visible_outputs(self, attrs):
        n = self._num_visible_outputs
        if n is None:
            return self.num_outputs(attrs)
        return n(attrs) if callable(n) else n

    def output_names(self, attrs):
        o = self._output_names
        if o is None:
            n = self.num_outputs(attrs)
            return ["output"] if n == 1 else ["output%d" % i for i in range(n)]
        return list(o(attrs)) if callable(o) else list(o)

    # ---- attrs -----------------------------------------------------------
    def canonicalize_attrs(self, raw):
        """Parse raw attrs (strings from JSON or python values) against the schema.

        Unknown keys that look like user attrs (``__key__``/``ctx_group``-style
        graph attributes) are passed through untouched — the reference stores
        those on the node, not the op param struct.
        """
        out = {}
        extra = {}
        for k, v in (raw or {}).items():
            if k in self.params:
                try:
                    out[k] = self.params[k].parse(v)
                except Exception as e:  # noqa: BLE001
                    raise MXNetError(
                        "op %s: cannot parse attr %s=%r: %s" % (self.name, k, v, e)
                    ) from e
            else:
                extra[k] = v
        for k, p in self.params.items():
            if k not in out:
                if p.required:
                    raise MXNetError("op %s: required attr '%s' missing" % (self.name, k))
                out[k] = p.default
        if self.keep_extras:
            # graph-attr style keys (__key__, ctx_group) still go on the node
            node_attrs = {k: v for k, v in extra.items() if k.startswith("__") or k == "ctx_group"}
            out.update({k: v for k, v in extra.items() if k not in node_attrs})
            return out, node_attrs
        return out, extra

    # ---- inference -------------------------------------------------------
    def infer_shape(self, attrs, in_shapes, aux_shapes=None):
        """Return (in_shapes, out_shapes, aux_shapes); fills unknown (None) entries.

        Reference semantics: nnvm InferShape pass (consumed at
        src/executor/graph_executor.cc:428). Default: require all inputs known,
        abstract-eval the forward.
        """
        if self._infer_shape is not None:
            return self._infer_shape(attrs, list(in_shapes), list(aux_shapes or []))
        if any(s is None for s in in_shapes):
            raise MXNetError(
                "op %s: cannot infer shapes with unknown inputs %s" % (self.name, in_shapes)
            )
        import numpy as np

        out_shapes, out_dtypes, aux_s, _ = self.abstract_eval(
            attrs, list(in_shapes), [np.float32] * len(in_shapes), list(aux_shapes or []), None
        )
        return list(in_shapes), out_shapes, aux_s

    def infer_type(self, attrs, in_dtypes):
        """Return (in_dtypes, out_dtypes, aux_dtypes) with Nones filled by
        propagating the first known dtype (the reference's elemwise type rule,
        src/operator/elemwise_op_common.h)."""
        import numpy as np

        if self._infer_type is not None:
            return self._infer_type(attrs, list(in_dtypes))
        known = [d for d in in_dtypes if d is not None]
        fill = known[0] if known else np.float32
        in_dtypes = [d if d is not None else fill for d in in_dtypes]
        n_out = self.num_outputs(attrs)
        out_dt = in_dtypes[0] if in_dtypes else np.float32
        return in_dtypes, [out_dt] * n_out, []

    def abstract_eval(self, attrs, in_shapes, in_dtypes, aux_shapes, aux_dtypes):
        """jax.eval_shape over the forward: returns (out_shapes, out_dtypes,
        new_aux_shapes, new_aux_dtypes)."""
        import jax
        import numpy as np

        if aux_dtypes is None:
            aux_dtypes = [np.float32] * len(aux_shapes)
        args = [jax.ShapeDtypeStruct(tuple(s), d) for s, d in zip(in_shapes, in_dtypes)]
        auxs = [jax.ShapeDtypeStruct(tuple(s), d) for s, d in zip(aux_shapes, aux_dtypes)]
        octx = OpContext(is_train=True, rng=jax.ShapeDtypeStruct((2,), np.uint32) if self.stochastic else None)

        def f(args, auxs, rng):
            octx2 = OpContext(is_train=True, rng=rng)
            return self.forward(octx2, attrs, args, auxs)

        rng_arg = jax.ShapeDtypeStruct((2,), np.uint32) if self.stochastic else None
        outs, new_auxs = jax.eval_shape(f, args, auxs, rng_arg)
        return (
            [tuple(o.shape) for o in outs],
            [np.dtype(o.dtype) for o in outs],
            [tuple(a.shape) for a in new_auxs],
            [np.dtype(a.dtype) for a in new_auxs],
        )


def register(name, **kwargs):
    """Register operator ``name`` with forward function decorated.

    ::

        @register("exp", arg_names=("data",))
        def _exp(octx, attrs, args, auxs):
            return [jnp.exp(args[0])], []
    """

    def _reg(fn):
        op = Operator(name, fn, **kwargs)
        _OP_REGISTRY[name] = op
        for a in op.alias:
            _OP_REGISTRY[a] = op
        return fn

    return _reg


def register_simple(name, fn, arg_names=("data",), params=None, **kwargs):
    """Register a stateless op from ``fn(attrs, *arrays) -> array-or-list``."""

    @functools.wraps(fn)
    def _fwd(octx, attrs, args, auxs):
        out = fn(attrs, *args)
        if not isinstance(out, (list, tuple)):
            out = [out]
        return list(out), []

    op = Operator(name, _fwd, arg_names=arg_names, params=params, **kwargs)
    _OP_REGISTRY[name] = op
    for a in op.alias:
        _OP_REGISTRY[a] = op
    return op


def get_op(name):
    try:
        return _OP_REGISTRY[name]
    except KeyError:
        raise MXNetError("Operator '%s' is not registered" % name) from None


def has_op(name):
    return name in _OP_REGISTRY


def list_ops():
    return sorted(_OP_REGISTRY.keys())


def fp32_precision(dt):
    """Matmul/conv precision for a given input dtype: float32 means FLOAT32.

    On TPU, jax's DEFAULT precision computes fp32 contractions in bf16 on the
    MXU — silently ~3 decimal digits. The reference's fp32 semantics (and any
    CPU-vs-TPU consistency check) require true fp32, so fp32/fp64 inputs get
    HIGHEST; bf16 inputs keep DEFAULT (bf16 with fp32 accumulation is the
    native fast path users opt into via compute_dtype).
    """
    import jax
    import numpy as np

    if np.dtype(dt) in (np.dtype("float32"), np.dtype("float64")):
        return jax.lax.Precision.HIGHEST
    return jax.lax.Precision.DEFAULT
