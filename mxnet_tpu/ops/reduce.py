"""Reduction and broadcasting-shape ops.

Reference: src/operator/tensor/broadcast_reduce_op_value.cc / _index.cc and the
hand-tiled kernels in broadcast_reduce-inl.{h,cuh}. On TPU these are single XLA
reduce HLOs — the MXU/VPU tiling the reference hand-writes is the compiler's job.

MXNet axis semantics preserved: ``axis=()`` or unset means reduce-all;
``keepdims`` keeps singleton axes; ``exclude`` reduces over the complement.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import parse_bool, parse_shape
from .registry import Param, register_simple


def _axis_param(default=None):
    def _parse(v):
        if v is None or (isinstance(v, str) and v.strip() in ("None", "")):
            return None
        if isinstance(v, (int, np.integer)):
            return (int(v),)
        return parse_shape(v)

    return Param(_parse, default)


def _norm_axes(axis, ndim, exclude=False):
    if axis is None or axis == ():
        axes = tuple(range(ndim))
        return tuple(range(ndim)) if not exclude else ()
    axes = tuple(sorted(a % ndim for a in axis))
    if exclude:
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


def _make_reduce(fn):
    def _impl(attrs, x):
        axes = _norm_axes(attrs["axis"], x.ndim, attrs["exclude"])
        return fn(x, axis=axes if axes else None, keepdims=attrs["keepdims"])

    return _impl


_REDUCE_PARAMS = {
    "axis": _axis_param(None),
    "keepdims": Param.bool(False),
    "exclude": Param.bool(False),
}

for _name, _fn, _aliases in [
    ("sum", jnp.sum, ("sum_axis",)),
    ("mean", jnp.mean, ()),
    ("prod", jnp.prod, ()),
    ("max", jnp.max, ("max_axis",)),
    ("min", jnp.min, ("min_axis",)),
    ("nansum", jnp.nansum, ()),
    ("nanprod", jnp.nanprod, ()),
]:
    register_simple(
        _name,
        (lambda fn: _make_reduce(fn))(_fn),
        arg_names=("data",),
        params=dict(_REDUCE_PARAMS),
        alias=_aliases,
    )


# argmax/argmin (reference: broadcast_reduce_op_index.cc) — axis is a single int
# or None (flatten); output dtype matches input (mxnet returns float indices)
def _make_argreduce(fn):
    def _impl(attrs, x):
        ax = attrs["axis"]
        ax = None if ax is None else int(ax[0]) if isinstance(ax, tuple) else int(ax)
        out = fn(x, axis=ax)
        if attrs["keepdims"] and ax is not None:
            out = jnp.expand_dims(out, ax)
        return jax.lax.stop_gradient(out.astype(x.dtype))

    return _impl


for _name, _fn in [("argmax", jnp.argmax), ("argmin", jnp.argmin)]:
    register_simple(
        _name,
        (lambda fn: _make_argreduce(fn))(_fn),
        arg_names=("data",),
        params={"axis": _axis_param(None), "keepdims": Param.bool(False)},
    )

register_simple(
    "argmax_channel",
    lambda attrs, x: jax.lax.stop_gradient(jnp.argmax(x, axis=1).astype(x.dtype)),
    arg_names=("data",),
)


def _norm(attrs, x):
    ord_ = attrs.get("ord", 2)
    axes = _norm_axes(attrs.get("axis"), x.ndim, False) if attrs.get("axis") is not None else None
    if ord_ == 1:
        r = jnp.sum(jnp.abs(x), axis=axes, keepdims=attrs.get("keepdims", False))
    else:
        r = jnp.sqrt(jnp.sum(jnp.square(x), axis=axes, keepdims=attrs.get("keepdims", False)))
    return r


register_simple(
    "norm",
    _norm,
    arg_names=("data",),
    params={"ord": Param.int(2), "axis": _axis_param(None), "keepdims": Param.bool(False)},
)

# ---- broadcasting shape ops (reference: broadcast_reduce_op_value.cc) ------
register_simple(
    "broadcast_to",
    lambda attrs, x: jnp.broadcast_to(
        x, tuple(t if t != 0 else s for t, s in zip(attrs["shape"], x.shape))
    ),
    arg_names=("data",),
    params={"shape": Param.shape(())},
)


def _broadcast_axis(attrs, x):
    axes = attrs["axis"] if isinstance(attrs["axis"], tuple) else (attrs["axis"],)
    sizes = attrs["size"] if isinstance(attrs["size"], tuple) else (attrs["size"],)
    target = list(x.shape)
    for a, s in zip(axes, sizes):
        target[a % x.ndim] = int(s)
    return jnp.broadcast_to(x, tuple(target))


register_simple(
    "broadcast_axis",
    _broadcast_axis,
    arg_names=("data",),
    params={"axis": _axis_param(()), "size": Param.shape(())},
    alias=("broadcast_axes",),
)
