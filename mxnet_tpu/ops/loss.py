"""Output/loss ops — the heads that drive training.

Reference: SoftmaxOutput (src/operator/softmax_output-inl.h), regression outputs
(src/operator/regression_output-inl.h), SVMOutput (svm_output-inl.h), MakeLoss
(make_loss-inl.h), softmax_cross_entropy (loss_binary_op.cc).

These ops have *declared* gradients rather than mathematical ones: SoftmaxOutput's
backward writes ``(p - onehot(label)) * grad_scale`` directly, ignoring any head
gradient. We express that with ``jax.custom_vjp`` so the semantics survive inside
a whole-graph jit — the executor seeds ones into loss outputs (the reference
seeds no head grad at all and lets the op's Backward fire; same effect).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import Param, get_op, register


def _mark_loss(name):
    get_op(name).is_loss = True


# ---------------------------------------------------------------- SoftmaxOutput
def _softmax_fwd(data, attrs):
    if attrs["multi_output"]:
        return jax.nn.softmax(data, axis=1)
    if attrs["preserve_shape"]:
        return jax.nn.softmax(data, axis=-1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


def _softmax_grad(p, label, attrs):
    scale = attrs["grad_scale"]
    norm = attrs["normalization"]
    use_ignore = attrs["use_ignore"]
    ignore = attrs["ignore_label"]
    smooth = attrs.get("smooth_alpha", 0.0) or 0.0
    if attrs["multi_output"]:
        nclass = p.shape[1]
        lab = label.astype(np.int32)
        oh = jnp.moveaxis(jax.nn.one_hot(lab, nclass, dtype=p.dtype), -1, 1)
        grad = p - oh
        valid_mask = (lab != int(ignore)).astype(p.dtype) if use_ignore else jnp.ones(lab.shape, p.dtype)
        grad = grad * valid_mask[:, None]
        nvalid = jnp.maximum(jnp.sum(valid_mask), 1.0)
        denom = {"batch": float(p.shape[0]), "null": 1.0}.get(norm, None)
        grad = grad / (nvalid if denom is None else denom)
        if norm == "null":
            pass
    else:
        flat = p.reshape(p.shape[0], -1)
        nclass = flat.shape[1]
        lab = label.reshape(-1).astype(np.int32)
        oh = jax.nn.one_hot(lab, nclass, dtype=p.dtype)
        if smooth:
            oh = oh * (1 - smooth) + smooth / nclass
        grad = flat - oh
        valid_mask = (lab != int(ignore)).astype(p.dtype) if use_ignore else jnp.ones(lab.shape, p.dtype)
        grad = grad * valid_mask[:, None]
        if norm == "batch":
            grad = grad / float(p.shape[0])
        elif norm == "valid":
            grad = grad / jnp.maximum(jnp.sum(valid_mask), 1.0)
        grad = grad.reshape(p.shape)
    return grad * scale


_SOFTMAX_PARAMS = {
    "grad_scale": Param.float(1.0),
    "ignore_label": Param.float(-1.0),
    "multi_output": Param.bool(False),
    "use_ignore": Param.bool(False),
    "preserve_shape": Param.bool(False),
    "normalization": Param.str("null"),
    "out_grad": Param.bool(False),
    "smooth_alpha": Param.float(0.0),
}


@register(
    "SoftmaxOutput",
    arg_names=("data", "label"),
    params=dict(_SOFTMAX_PARAMS),
    alias=("Softmax",),
)
def _softmax_output(octx, attrs, args, auxs):
    frozen = tuple(sorted(attrs.items()))

    @jax.custom_vjp
    def f(data, label):
        return _softmax_fwd(data, dict(frozen))

    def f_fwd(data, label):
        p = _softmax_fwd(data, dict(frozen))
        return p, (p, label)

    def f_bwd(res, g):
        p, label = res
        a = dict(frozen)
        grad = _softmax_grad(p, label, a)
        if a["out_grad"]:
            grad = grad * g
        return grad, jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return [f(args[0], args[1])], []


def _softmax_output_infer_shape(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    if attrs.get("multi_output"):
        label = (data[0],) + tuple(data[2:])
    else:
        label = (data[0],)
    if in_shapes[1] is not None:
        label = tuple(in_shapes[1])
    return [tuple(data), label], [tuple(data)], []


get_op("SoftmaxOutput")._infer_shape = _softmax_output_infer_shape
_mark_loss("SoftmaxOutput")


# ---------------------------------------------------------------- regression heads
def _reg_output(name, link, grad_fn):
    @register(
        name,
        arg_names=("data", "label"),
        params={"grad_scale": Param.float(1.0)},
    )
    def _fwd(octx, attrs, args, auxs):
        scale = attrs["grad_scale"]

        @jax.custom_vjp
        def f(data, label):
            return link(data)

        def f_fwd(data, label):
            out = link(data)
            return out, (out, label)

        def f_bwd(res, g):
            out, label = res
            grad = grad_fn(out, label.reshape(out.shape)) * scale
            return grad, jnp.zeros_like(label)

        f.defvjp(f_fwd, f_bwd)
        return [f(args[0], args[1])], []

    def _infer(attrs, in_shapes, aux_shapes):
        data = in_shapes[0]
        label = tuple(in_shapes[1]) if in_shapes[1] is not None else tuple(data)
        return [tuple(data), label], [tuple(data)], []

    get_op(name)._infer_shape = _infer
    _mark_loss(name)


_reg_output("LinearRegressionOutput", lambda x: x, lambda o, l: o - l)
_reg_output("MAERegressionOutput", lambda x: x, lambda o, l: jnp.sign(o - l))
_reg_output("LogisticRegressionOutput", jax.nn.sigmoid, lambda o, l: o - l)


# ---------------------------------------------------------------- SVMOutput
@register(
    "SVMOutput",
    arg_names=("data", "label"),
    params={
        "margin": Param.float(1.0),
        "regularization_coefficient": Param.float(1.0),
        "use_linear": Param.bool(False),
    },
)
def _svm_output(octx, attrs, args, auxs):
    margin = attrs["margin"]
    reg = attrs["regularization_coefficient"]
    linear = attrs["use_linear"]

    @jax.custom_vjp
    def f(data, label):
        return data

    def f_fwd(data, label):
        return data, (data, label)

    def f_bwd(res, g):
        x, label = res
        lab = label.astype(np.int32)
        oh = jax.nn.one_hot(lab, x.shape[1], dtype=x.dtype)
        sgn = 2 * oh - 1  # +1 at true class, -1 elsewhere
        viol = (margin - sgn * x) > 0
        if linear:
            grad = jnp.where(viol, -sgn * reg, 0.0)
        else:
            grad = jnp.where(viol, -2 * (margin - sgn * x) * sgn * reg, 0.0)
        return grad.astype(x.dtype), jnp.zeros_like(label)

    f.defvjp(f_fwd, f_bwd)
    return [f(args[0], args[1])], []


def _svm_infer(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    return [tuple(data), (data[0],)], [tuple(data)], []


get_op("SVMOutput")._infer_shape = _svm_infer
_mark_loss("SVMOutput")


# ---------------------------------------------------------------- MakeLoss
@register(
    "MakeLoss",
    arg_names=("data",),
    params={
        "grad_scale": Param.float(1.0),
        "valid_thresh": Param.float(0.0),
        "normalization": Param.str("null"),
    },
    alias=("make_loss",),
)
def _make_loss(octx, attrs, args, auxs):
    scale = attrs["grad_scale"]
    norm = attrs["normalization"]
    thresh = attrs["valid_thresh"]

    @jax.custom_vjp
    def f(x):
        return x

    def f_fwd(x):
        return x, x

    def f_bwd(x, g):
        grad = jnp.full(x.shape, scale, x.dtype)
        if norm == "batch":
            grad = grad / x.shape[0]
        elif norm == "valid":
            nvalid = jnp.maximum(jnp.sum((x > thresh).astype(x.dtype)), 1.0)
            grad = grad / nvalid
        return (grad,)

    f.defvjp(f_fwd, f_bwd)
    return [f(args[0])], []


_mark_loss("MakeLoss")


# ---------------------------------------------------------------- cross entropy
@register(
    "softmax_cross_entropy",
    arg_names=("data", "label"),
)
def _softmax_cross_entropy(octx, attrs, args, auxs):
    data, label = args
    logp = jax.nn.log_softmax(data, axis=-1)
    lab = jax.lax.stop_gradient(label).astype(np.int32)
    nll = -jnp.take_along_axis(logp, lab[:, None], axis=1)[:, 0]
    return [jnp.sum(nll)], []


def _sce_infer(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    return [tuple(data), (data[0],)], [()], []


get_op("softmax_cross_entropy")._infer_shape = _sce_infer
