"""Ordering ops: topk / sort / argsort.

Reference: src/operator/tensor/ordering_op.cc (+sort_op-inl.cuh, cub/thrust
device sorts). XLA provides sort/top_k HLOs natively on TPU — no hand kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import Param, register, register_simple


def _axis_or_none(v):
    if v in (None, "None", ""):
        return None
    return int(float(v))


@register(
    "topk",
    arg_names=("data",),
    params={
        "axis": Param(_axis_or_none, -1),
        "k": Param.int(1),
        "ret_typ": Param.str("indices"),
        "is_ascend": Param.bool(False),
        "dtype": Param.dtype(None),
    },
    num_outputs=lambda attrs: 2 if attrs.get("ret_typ") == "both" else 1,
)
def _topk(octx, attrs, args, auxs):
    x = args[0]
    ax = attrs["axis"]
    k = attrs["k"] if attrs["k"] > 0 else (x.size if ax is None else x.shape[ax])
    if ax is None:
        flat = x.reshape(-1)
        vals, idx = _topk1d(flat, k, attrs["is_ascend"])
    else:
        ax = ax % x.ndim
        moved = jnp.moveaxis(x, ax, -1)
        vals, idx = _topk1d(moved, k, attrs["is_ascend"])
        vals = jnp.moveaxis(vals, -1, ax)
        idx = jnp.moveaxis(idx, -1, ax)
    idx = jax.lax.stop_gradient(idx)
    rt = attrs["ret_typ"]
    if rt == "value":
        return [vals], []
    if rt == "both":
        return [vals, idx.astype(x.dtype)], []
    if rt == "mask":
        oh = jnp.sum(jax.nn.one_hot(idx, x.shape[ax if ax is not None else -1], dtype=x.dtype), axis=-2)
        return [jax.lax.stop_gradient(oh)], []
    return [jax.lax.stop_gradient(idx.astype(x.dtype))], []


def _topk1d(x, k, is_ascend):
    if is_ascend:
        vals, idx = jax.lax.top_k(-x, k)
        return -vals, idx
    return jax.lax.top_k(x, k)


def _sort(attrs, x):
    ax = attrs["axis"]
    ax = None if ax is None else ax
    out = jnp.sort(x, axis=ax)
    if not attrs["is_ascend"]:
        out = jnp.flip(out, axis=-1 if ax is None else ax)
    return out


register_simple(
    "sort",
    _sort,
    arg_names=("data",),
    params={"axis": Param(_axis_or_none, -1), "is_ascend": Param.bool(True)},
)


def _argsort(attrs, x):
    ax = attrs["axis"]
    idx = jnp.argsort(x, axis=ax)
    if not attrs["is_ascend"]:
        idx = jnp.flip(idx, axis=-1 if ax is None else ax)
    return jax.lax.stop_gradient(idx.astype(x.dtype))


register_simple(
    "argsort",
    _argsort,
    arg_names=("data",),
    params={"axis": Param(_axis_or_none, -1), "is_ascend": Param.bool(True), "dtype": Param.dtype(None)},
)
