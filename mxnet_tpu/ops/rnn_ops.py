"""Fused multi-layer RNN op — the TPU replacement for the cuDNN-only RNN.

Reference: the `RNN` op is GPU-only there (src/operator/cudnn_rnn-inl.h:22,
cudnnRNNForwardTraining :127; the CPU path is an empty TODO, src/operator/rnn.cc:14
`LOG(FATAL) "RNN is only available for gpu"`). Here the fused RNN is a
``jax.lax.scan`` over time — XLA compiles the whole unrolled recurrence into one
executable with the gate matmuls batched onto the MXU, which is exactly what
cudnnRNN does on GPU. Works on every backend.

Parameter packing (documented contract, used by rnn.FusedRNNCell.unfuse too):
for layer l in 0..L-1, for direction d (fwd, bwd):
    i2h_weight (G*H, I_l), h2h_weight (G*H, H), i2h_bias (G*H,), h2h_bias (G*H,)
flattened in that order and concatenated. Gate order: LSTM [i, f, c, o]
(python/mxnet/rnn/rnn_cell.py LSTMCell order), GRU [r, z, n].
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import Param, fp32_precision, get_op, register


def _gates(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    g = _gates(mode)
    d = 2 if bidirectional else 1
    total = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * d
        total += d * (g * state_size * (isz + state_size) + 2 * g * state_size)
    return total


def _unpack_params(params, num_layers, input_size, state_size, bidirectional, mode):
    g = _gates(mode)
    d = 2 if bidirectional else 1
    off = 0
    layers = []
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * d
        dirs = []
        for _ in range(d):
            n_i2h = g * state_size * isz
            w_i2h = params[off : off + n_i2h].reshape(g * state_size, isz)
            off += n_i2h
            n_h2h = g * state_size * state_size
            w_h2h = params[off : off + n_h2h].reshape(g * state_size, state_size)
            off += n_h2h
            b_i2h = params[off : off + g * state_size]
            off += g * state_size
            b_h2h = params[off : off + g * state_size]
            off += g * state_size
            dirs.append((w_i2h, w_h2h, b_i2h, b_h2h))
        layers.append(dirs)
    return layers


def _cell_step(mode, state_size):
    H = state_size

    if mode == "lstm":

        def step(carry, xw, w_h2h, b_h2h):
            h, c = carry
            gates = xw + jnp.dot(h, w_h2h.T, precision=fp32_precision(h.dtype)) + b_h2h
            i, f, g_, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g_ = jnp.tanh(g_)
            c2 = f * c + i * g_
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2

    elif mode == "gru":

        def step(carry, xw, w_h2h, b_h2h):
            (h,) = carry
            hw = jnp.dot(h, w_h2h.T, precision=fp32_precision(h.dtype)) + b_h2h
            xr, xz, xn = jnp.split(xw, 3, axis=-1)
            hr, hz, hn = jnp.split(hw, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h2 = (1 - z) * n + z * h
            return (h2,), h2

    else:
        act = jax.nn.relu if mode == "rnn_relu" else jnp.tanh

        def step(carry, xw, w_h2h, b_h2h):
            (h,) = carry
            h2 = act(xw + jnp.dot(h, w_h2h.T, precision=fp32_precision(h.dtype)) + b_h2h)
            return (h2,), h2

    return step


def _run_layer(x, wp, init, mode, state_size, reverse=False):
    """x: (T, N, I); returns (out (T,N,H), final_carry)."""
    w_i2h, w_h2h, b_i2h, b_h2h = wp
    # hoist the input projection out of the scan: one big MXU matmul over T*N
    xw = jnp.einsum("tni,hi->tnh", x, w_i2h,
                    precision=fp32_precision(x.dtype)) + b_i2h
    step = _cell_step(mode, state_size)

    def body(carry, xw_t):
        return step(carry, xw_t, w_h2h, b_h2h)

    carry, out = jax.lax.scan(body, init, xw, reverse=reverse)
    return out, carry


@register(
    "RNN",
    arg_names=lambda attrs: ["data", "parameters", "state"]
    + (["state_cell"] if attrs.get("mode") == "lstm" else []),
    params={
        "state_size": Param.int(),
        "num_layers": Param.int(),
        "bidirectional": Param.bool(False),
        "mode": Param.str(),
        "p": Param.float(0.0),
        "state_outputs": Param.bool(False),
        "pkeep_": Param.float(1.0),
        "lstm_q_": Param.bool(False),
    },
    stochastic=True,
    num_outputs=lambda attrs: 1
    + (
        (2 if attrs.get("mode") == "lstm" else 1)
        if attrs.get("state_outputs")
        else 0
    ),
    output_names=lambda attrs: ["output"]
    + (
        (["state_output", "statecell_output"] if attrs.get("mode") == "lstm" else ["state_output"])
        if attrs.get("state_outputs")
        else []
    ),
)
def _rnn(octx, attrs, args, auxs):
    mode = attrs["mode"]
    H = attrs["state_size"]
    L = attrs["num_layers"]
    bidir = attrs["bidirectional"]
    d = 2 if bidir else 1
    x = args[0]
    params = args[1]
    h0 = args[2]  # (L*d, N, H)
    c0 = args[3] if mode == "lstm" else None
    T, N, I = x.shape
    layers = _unpack_params(params, L, I, H, bidir, mode)
    inp = x
    h_finals, c_finals = [], []
    key = octx.rng
    for li, dirs in enumerate(layers):
        outs = []
        for di, wp in enumerate(dirs):
            sidx = li * d + di
            # broadcast initial state up to the real batch (begin_state may be
            # batch-1 from the 0-dim wildcard convention, init_ops._shape_0to1)
            h_init = jnp.broadcast_to(h0[sidx], (N, H)).astype(x.dtype)
            if mode == "lstm":
                init = (h_init, jnp.broadcast_to(c0[sidx], (N, H)).astype(x.dtype))
            else:
                init = (h_init,)
            out, carry = _run_layer(inp, wp, init, mode, H, reverse=(di == 1))
            outs.append(out)
            h_finals.append(carry[0])
            if mode == "lstm":
                c_finals.append(carry[1])
        inp = outs[0] if d == 1 else jnp.concatenate(outs, axis=-1)
        if attrs["p"] > 0 and octx.is_train and key is not None and li < L - 1:
            key, sub = jax.random.split(key)
            keep = 1.0 - attrs["p"]
            mask = jax.random.bernoulli(sub, keep, inp.shape).astype(inp.dtype) / keep
            inp = inp * jax.lax.stop_gradient(mask)
    outputs = [inp]
    if attrs["state_outputs"]:
        outputs.append(jnp.stack(h_finals, axis=0))
        if mode == "lstm":
            outputs.append(jnp.stack(c_finals, axis=0))
    return outputs, []


def _rnn_infer_shape(attrs, in_shapes, aux_shapes):
    data = in_shapes[0]
    if data is None:
        raise MXNetError("RNN: data shape required")
    T, N, I = data
    H, L = attrs["state_size"], attrs["num_layers"]
    d = 2 if attrs["bidirectional"] else 1
    psize = rnn_param_size(L, I, H, attrs["bidirectional"], attrs["mode"])
    shapes = [tuple(data), (psize,), (L * d, N, H)]
    if attrs["mode"] == "lstm":
        shapes.append((L * d, N, H))
    outs = [(T, N, H * d)]
    if attrs["state_outputs"]:
        outs.append((L * d, N, H))
        if attrs["mode"] == "lstm":
            outs.append((L * d, N, H))
    return shapes, outs, []


get_op("RNN")._infer_shape = _rnn_infer_shape
