"""Elementwise binary/unary/scalar/logic op families.

Reference: src/operator/tensor/elemwise_binary_op_basic.cc,
elemwise_binary_broadcast_op_*.cc, elemwise_unary_op.cc, elemwise_binary_scalar_op_*.cc,
and the scalar-functor math table src/operator/mshadow_op.h (892 LoC).

TPU design: every functor is a one-line jnp expression; XLA fuses chains of these
into single HBM-bandwidth-bound kernels, which is exactly the fusion the reference
had to approximate with its `Kernel<OP,xpu>::Launch` per-op launches
(src/operator/mxnet_op.h:219). Backward comes from autodiff — the reference's
paired `_backward_*` registrations are unnecessary.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import Param, register, register_simple

_f = Param.float


def _same_dtype(a, b):
    # mxnet semantics: binary elemwise keeps lhs dtype; jnp promotion is fine for
    # matching dtypes which is what the reference requires anyway.
    return a, b


# ---- binary elementwise (reference: elemwise_binary_op_basic.cc:11-31) -----
_BINARY = {
    "elemwise_add": (lambda x, y: x + y, ("_plus", "_Plus")),
    "elemwise_sub": (lambda x, y: x - y, ("_minus", "_Minus", "_sub")),
    "elemwise_mul": (lambda x, y: x * y, ("_mul", "_Mul")),
    "elemwise_div": (lambda x, y: x / y, ("_div", "_Div")),
    "_power": (lambda x, y: jnp.power(x, y), ("_Power",)),
    "_maximum": (jnp.maximum, ("_Maximum",)),
    "_minimum": (jnp.minimum, ("_Minimum",)),
    "_hypot": (jnp.hypot, ()),
    "_mod": (jnp.mod, ()),
}
for _name, (_fn, _aliases) in _BINARY.items():
    register_simple(
        _name,
        (lambda fn: lambda attrs, x, y: fn(x, y))(_fn),
        arg_names=("lhs", "rhs"),
        alias=_aliases,
    )

# comparison ops return same-dtype 0/1 arrays like the reference
# (elemwise_binary_op_logic.cc)
_LOGIC = {
    "_equal": lambda x, y: (x == y),
    "_not_equal": lambda x, y: (x != y),
    "_greater": lambda x, y: (x > y),
    "_greater_equal": lambda x, y: (x >= y),
    "_lesser": lambda x, y: (x < y),
    "_lesser_equal": lambda x, y: (x <= y),
}
for _name, _fn in _LOGIC.items():
    register_simple(
        _name,
        (lambda fn: lambda attrs, x, y: jax.lax.stop_gradient(fn(x, y).astype(x.dtype)))(_fn),
        arg_names=("lhs", "rhs"),
    )

# ---- broadcast binary (reference: elemwise_binary_broadcast_op_*.cc) -------
for _name, _fn in {
    "broadcast_add": lambda x, y: x + y,
    "broadcast_sub": lambda x, y: x - y,
    "broadcast_minus": lambda x, y: x - y,
    "broadcast_plus": lambda x, y: x + y,
    "broadcast_mul": lambda x, y: x * y,
    "broadcast_div": lambda x, y: x / y,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
}.items():
    register_simple(_name, (lambda fn: lambda attrs, x, y: fn(x, y))(_fn), arg_names=("lhs", "rhs"))

for _name, _fn in {
    "broadcast_equal": lambda x, y: x == y,
    "broadcast_not_equal": lambda x, y: x != y,
    "broadcast_greater": lambda x, y: x > y,
    "broadcast_greater_equal": lambda x, y: x >= y,
    "broadcast_lesser": lambda x, y: x < y,
    "broadcast_lesser_equal": lambda x, y: x <= y,
}.items():
    register_simple(
        _name,
        (lambda fn: lambda attrs, x, y: jax.lax.stop_gradient(fn(x, y).astype(x.dtype)))(_fn),
        arg_names=("lhs", "rhs"),
    )

# ---- scalar ops (reference: elemwise_binary_scalar_op_basic.cc) ------------
_SCALAR = {
    "_plus_scalar": (lambda x, s: x + s, ("_PlusScalar",)),
    "_minus_scalar": (lambda x, s: x - s, ("_MinusScalar",)),
    "_rminus_scalar": (lambda x, s: s - x, ("_RMinusScalar",)),
    "_mul_scalar": (lambda x, s: x * s, ("_MulScalar",)),
    "_div_scalar": (lambda x, s: x / s, ("_DivScalar",)),
    "_rdiv_scalar": (lambda x, s: s / x, ("_RDivScalar",)),
    "_power_scalar": (lambda x, s: jnp.power(x, s), ("_PowerScalar",)),
    "_rpower_scalar": (lambda x, s: jnp.power(s, x), ("_RPowerScalar",)),
    "_maximum_scalar": (lambda x, s: jnp.maximum(x, s), ("_MaximumScalar",)),
    "_minimum_scalar": (lambda x, s: jnp.minimum(x, s), ("_MinimumScalar",)),
    "_mod_scalar": (lambda x, s: jnp.mod(x, s), ()),
    "_rmod_scalar": (lambda x, s: jnp.mod(s, x), ()),
    "_hypot_scalar": (lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)), ()),
}
for _name, (_fn, _aliases) in _SCALAR.items():
    register_simple(
        _name,
        (lambda fn: lambda attrs, x: fn(x, np.asarray(attrs["scalar"], dtype=x.dtype)))(_fn),
        arg_names=("data",),
        params={"scalar": _f()},
        alias=_aliases,
    )

for _name, _fn in {
    "_equal_scalar": lambda x, s: x == s,
    "_not_equal_scalar": lambda x, s: x != s,
    "_greater_scalar": lambda x, s: x > s,
    "_greater_equal_scalar": lambda x, s: x >= s,
    "_lesser_scalar": lambda x, s: x < s,
    "_lesser_equal_scalar": lambda x, s: x <= s,
}.items():
    register_simple(
        _name,
        (lambda fn: lambda attrs, x: jax.lax.stop_gradient(fn(x, attrs["scalar"]).astype(x.dtype)))(_fn),
        arg_names=("data",),
        params={"scalar": _f()},
    )

# ---- unary math table (reference: mshadow_op.h + elemwise_unary_op.cc) -----
_UNARY = {
    "negative": lambda x: -x,
    "abs": jnp.abs,
    "sign": jnp.sign,
    "round": jnp.round,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.fix,
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": lambda x: jax.scipy.special.gammaln(x),
    "sigmoid": jax.nn.sigmoid,
    "relu": jax.nn.relu,
    "softsign": jax.nn.soft_sign,
    "reciprocal": lambda x: 1.0 / x,
    "erf": jax.scipy.special.erf,
    "logical_not": lambda x: jax.lax.stop_gradient((x == 0).astype(x.dtype)),
}
for _name, _fn in _UNARY.items():
    register_simple(_name, (lambda fn: lambda attrs, x: fn(x))(_fn), arg_names=("data",))

# identity / gradient-control ops (reference: elemwise_unary_op.cc _copy/BlockGrad)
register_simple("_copy", lambda attrs, x: x + jnp.zeros((), x.dtype), arg_names=("data",), alias=("identity",))
# device-placement copy node (reference: PlaceDevice pass inserts _CrossDeviceCopy,
# graph_executor.cc:321; on TPU placement is SPMD-sharded so this is identity —
# XLA inserts the actual transfers)
register_simple("_CrossDeviceCopy", lambda attrs, x: x + jnp.zeros((), x.dtype), arg_names=("data",))
register_simple("BlockGrad", lambda attrs, x: jax.lax.stop_gradient(x), arg_names=("data",), alias=("stop_gradient",))
register_simple(
    "Cast",
    lambda attrs, x: x.astype(attrs["dtype"]),
    arg_names=("data",),
    params={"dtype": Param.dtype()},
    alias=("cast",),
)
register_simple(
    "clip",
    lambda attrs, x: jnp.clip(x, attrs["a_min"], attrs["a_max"]),
    arg_names=("data",),
    params={"a_min": _f(), "a_max": _f()},
)


# variadic sum (reference: elemwise_sum.cc ElementWiseSum / add_n; used by
# gradient aggregation, src/executor/graph_executor.cc:90-163)
@register(
    "add_n",
    arg_names=lambda attrs: ["arg%d" % i for i in range(int(attrs.get("num_args", 1)))],
    params={"num_args": Param.int(1)},
    key_var_num_args="num_args",
    alias=("ElementWiseSum", "_sum"),
)
def _add_n(octx, attrs, args, auxs):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return [out], []


# scatter-style grad accumulation helper (reference: _grad_add chained adds)
register_simple("_grad_add", lambda attrs, x, y: x + y, arg_names=("lhs", "rhs"))


def _smooth_l1(attrs, x):
    # reference: elemwise_binary_scalar_op_extended.cc:62 (mshadow_op::smooth_l1_loss):
    # f(x) = 0.5*(sigma*x)^2 if |x| < 1/sigma^2 else |x| - 0.5/sigma^2
    sigma = np.asarray(attrs["scalar"], dtype=x.dtype)
    sigma2 = sigma * sigma
    return jnp.where(
        jnp.abs(x) < 1.0 / sigma2,
        0.5 * jnp.square(sigma * x),
        jnp.abs(x) - 0.5 / sigma2,
    )


register_simple(
    "smooth_l1",
    _smooth_l1,
    arg_names=("data",),
    params={"scalar": _f(1.0)},
)

# identity over lhs whose shape/dtype attrs come from rhs; grad flows to lhs only
# (reference: elemwise_unary_op.cc:114 _identity_with_attr_like_rhs — used by
# slice-assign gradients)
register_simple(
    "_identity_with_attr_like_rhs",
    lambda attrs, lhs, rhs: lhs + jnp.zeros((), lhs.dtype),
    arg_names=("lhs", "rhs"),
)

# gradient placeholder node (reference: nnvm no_gradient op): a zero scalar that
# blocks gradient flow; appears in graphs where an input has no defined gradient
register_simple(
    "_NoGradient",
    lambda attrs: jax.lax.stop_gradient(jnp.zeros(())),
    arg_names=(),
)
