"""Fused optimizer update ops.

Reference: src/operator/optimizer_op.cc:18+ / optimizer_op-inl.h (SGDUpdate,
SGDMomUpdate :136, AdamParam :156, rmsprop/rmspropalex) — single fused kernels
called from python/mxnet/optimizer.py so the update never materializes
intermediates. Here each is one jitted jax expression; XLA fuses the whole
update into a single HBM pass, and inside a compiled training step the update
fuses with the gradient computation itself (something the reference cannot do).

Semantics note: these ops *mutate* their weight/state inputs in the reference
(FMutateInputs). Imperatively we return the new values and the NDArray layer
writes them back into the same buffers; inside compiled train steps the executor
threads them functionally.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import Param, register

_COMMON = {
    "lr": Param.float(),
    "wd": Param.float(0.0),
    "rescale_grad": Param.float(1.0),
    "clip_gradient": Param.float(-1.0),
}


def _prep_grad(grad, weight, attrs):
    g = grad * attrs["rescale_grad"]
    if attrs["clip_gradient"] > 0:
        g = jnp.clip(g, -attrs["clip_gradient"], attrs["clip_gradient"])
    return g + attrs["wd"] * weight


@register("sgd_update", arg_names=("weight", "grad"), params=dict(_COMMON))
def _sgd_update(octx, attrs, args, auxs):
    weight, grad = args
    g = _prep_grad(grad, weight, attrs)
    return [weight - attrs["lr"] * g], []


@register(
    "sgd_mom_update",
    arg_names=("weight", "grad", "mom"),
    params=dict(_COMMON, momentum=Param.float(0.0)),
    num_outputs=2,
    num_visible_outputs=1,
)
def _sgd_mom_update(octx, attrs, args, auxs):
    weight, grad, mom = args
    g = _prep_grad(grad, weight, attrs)
    new_mom = attrs["momentum"] * mom - attrs["lr"] * g
    return [weight + new_mom, new_mom], []


@register(
    "adam_update",
    arg_names=("weight", "grad", "mean", "var"),
    params=dict(
        _COMMON,
        beta1=Param.float(0.9),
        beta2=Param.float(0.999),
        epsilon=Param.float(1e-8),
    ),
    num_outputs=3,
    num_visible_outputs=1,
)
def _adam_update(octx, attrs, args, auxs):
    weight, grad, mean, var = args
    g = _prep_grad(grad, weight, attrs)
    b1, b2 = attrs["beta1"], attrs["beta2"]
    new_mean = b1 * mean + (1 - b1) * g
    new_var = b2 * var + (1 - b2) * jnp.square(g)
    new_w = weight - attrs["lr"] * new_mean / (jnp.sqrt(new_var) + attrs["epsilon"])
    return [new_w, new_mean, new_var], []


@register(
    "rmsprop_update",
    arg_names=("weight", "grad", "n"),
    params=dict(_COMMON, gamma1=Param.float(0.95), epsilon=Param.float(1e-8)),
    num_outputs=2,
    num_visible_outputs=1,
)
def _rmsprop_update(octx, attrs, args, auxs):
    weight, grad, n = args
    g = _prep_grad(grad, weight, attrs)
    g1 = attrs["gamma1"]
    new_n = (1 - g1) * jnp.square(g) + g1 * n
    new_w = weight - attrs["lr"] * g / jnp.sqrt(new_n + attrs["epsilon"])
    return [new_w, new_n], []


@register(
    "rmspropalex_update",
    arg_names=("weight", "grad", "n", "g", "delta"),
    params=dict(
        _COMMON, gamma1=Param.float(0.95), gamma2=Param.float(0.9), epsilon=Param.float(1e-8)
    ),
    num_outputs=4,
    num_visible_outputs=1,
)
def _rmspropalex_update(octx, attrs, args, auxs):
    weight, grad, n, gbar, delta = args
    g = _prep_grad(grad, weight, attrs)
    g1, g2 = attrs["gamma1"], attrs["gamma2"]
    new_n = (1 - g1) * jnp.square(g) + g1 * n
    new_g = (1 - g1) * g + g1 * gbar
    new_delta = g2 * delta - attrs["lr"] * g / jnp.sqrt(new_n - jnp.square(new_g) + attrs["epsilon"])
    return [weight + new_delta, new_n, new_g, new_delta], []
