"""Indexing ops: Embedding / take / batch_take / one_hot / pick / gather-scatter.

Reference: src/operator/tensor/indexing_op.{cc,cu,h} (Embedding forward =
row gather, backward = scatter-add — here the scatter-add backward falls out of
jax autodiff on ``take``, which XLA lowers to an efficient sorted-segment-sum on
TPU rather than the reference's atomic-add CUDA kernel).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import Param, register, register_simple


@register(
    "Embedding",
    arg_names=("data", "weight"),
    params={
        "input_dim": Param.int(),
        "output_dim": Param.int(),
        "dtype": Param.dtype(None),
    },
)
def _embedding(octx, attrs, args, auxs):
    idx, weight = args
    out = jnp.take(weight, jax.lax.stop_gradient(idx).astype(np.int32), axis=0)
    return [out], []


def _infer_embedding_shape(attrs, in_shapes, aux_shapes):
    data, weight = in_shapes
    w = (int(attrs["input_dim"]), int(attrs["output_dim"]))
    if weight is None:
        weight = w
    if data is None:
        raise ValueError("Embedding: data shape required")
    return [data, weight], [tuple(data) + (w[1],)], []


from .registry import get_op  # noqa: E402

get_op("Embedding")._infer_shape = _infer_embedding_shape


def _take(attrs, a, indices):
    mode = attrs.get("mode", "clip")
    idx = jax.lax.stop_gradient(indices).astype(np.int32)
    return jnp.take(a, idx, axis=attrs.get("axis", 0), mode="clip" if mode == "clip" else "wrap")


register_simple(
    "take",
    _take,
    arg_names=("a", "indices"),
    params={"axis": Param.int(0), "mode": Param.str("clip")},
)


def _batch_take(attrs, a, indices):
    idx = jax.lax.stop_gradient(indices).astype(np.int32)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


register_simple("batch_take", _batch_take, arg_names=("a", "indices"))


def _one_hot(attrs, indices):
    idx = jax.lax.stop_gradient(indices).astype(np.int32)
    dt = attrs.get("dtype") or np.float32
    on, off = attrs["on_value"], attrs["off_value"]
    oh = jax.nn.one_hot(idx, attrs["depth"], dtype=np.float32)
    return jax.lax.stop_gradient((oh * (on - off) + off).astype(dt))


register_simple(
    "one_hot",
    _one_hot,
    arg_names=("indices",),
    params={
        "depth": Param.int(),
        "on_value": Param.float(1.0),
        "off_value": Param.float(0.0),
        "dtype": Param.dtype(None),
    },
)


def _pick(attrs, data, index):
    ax = attrs["axis"]
    ax = data.ndim - 1 if ax is None else ax % data.ndim
    idx = jax.lax.stop_gradient(index).astype(np.int32)
    idxe = jnp.expand_dims(idx, ax) if idx.ndim < data.ndim else idx
    out = jnp.take_along_axis(data, idxe.astype(np.int32), axis=ax)
    if not attrs["keepdims"]:
        out = jnp.squeeze(out, axis=ax)
    return out


register_simple(
    "pick",
    _pick,
    arg_names=("data", "index"),
    params={
        "axis": Param(lambda v: None if v in (None, "None", "") else int(float(v)), -1),
        "keepdims": Param.bool(False),
    },
    alias=("choose_element_0index",),
)


def _fill_element_0index(attrs, lhs, mhs, rhs):
    idx = jax.lax.stop_gradient(rhs).astype(np.int32)
    return lhs.at[jnp.arange(lhs.shape[0]), idx].set(mhs)


register_simple(
    "fill_element_0index", _fill_element_0index, arg_names=("lhs", "mhs", "rhs")
)


def _gather_nd(attrs, data, indices):
    idx = jax.lax.stop_gradient(indices).astype(np.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


register_simple("gather_nd", _gather_nd, arg_names=("data", "indices"))


def _scatter_nd(attrs, data, indices):
    idx = jax.lax.stop_gradient(indices).astype(np.int32)
    shape = attrs["shape"]
    out = jnp.zeros(shape, data.dtype)
    m = idx.shape[0]
    return out.at[tuple(idx[i] for i in range(m))].add(data)


register_simple(
    "scatter_nd", _scatter_nd, arg_names=("data", "indices"), params={"shape": Param.shape()}
)
