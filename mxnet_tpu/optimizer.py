"""Optimizers (reference: python/mxnet/optimizer.py — registry :10/create :99,
SGD :307 calling fused sgd_update/sgd_mom_update ops :351-355, NAG, SGLD, DCASGD,
Adam :485, AdaGrad :538, RMSProp :575, AdaDelta :651, Ftrl :700, Test :753, and
the Updater :769 with state checkpointing).

The fused-update-op pattern survives: SGD/Adam/RMSProp call the registered
optimizer ops (ops/optimizer_ops.py), each one jitted XLA program per
shape — and when driven through a compiled train step the update fuses with the
backward pass entirely.
"""
from __future__ import annotations

import logging
import math
import pickle

import numpy as np

from . import ndarray as nd
from .ndarray import NDArray, zeros
from .base import MXNetError

__all__ = [
    "Optimizer", "SGD", "NAG", "SGLD", "DCASGD", "ccSGD", "Adam", "AdaGrad",
    "RMSProp", "AdaDelta", "Ftrl", "Test", "Updater", "get_updater", "create", "register",
]


class Optimizer:
    """Base optimizer with lr/wd multiplier resolution and the op registry
    (reference: optimizer.py:10-300)."""

    opt_registry = {}

    @staticmethod
    def register(klass):
        name = klass.__name__.lower()
        if name in Optimizer.opt_registry:
            logging.warning("WARNING: New optimizer %s is overriding existing optimizer", name)
        Optimizer.opt_registry[name] = klass
        return klass

    @staticmethod
    def create_optimizer(name, **kwargs):
        if name.lower() in Optimizer.opt_registry:
            return Optimizer.opt_registry[name.lower()](**kwargs)
        raise ValueError("Cannot find optimizer %s" % name)

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None, sym=None,
                 begin_num_update=0):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        if not isinstance(param_idx2name, dict):
            raise MXNetError("param_idx2name should be a dict of param indexes to names.")
        self.idx2name = param_idx2name.copy()
        self.sym = sym
        self.set_lr_mult({})
        self.set_wd_mult({})

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def set_lr_scale(self, args_lrscale):
        raise DeprecationWarning

    def set_lr_mult(self, args_lr_mult):
        """(reference: optimizer.py set_lr_mult — pulls __lr_mult__ attrs from sym)"""
        self.lr_mult = {}
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        """Defaults: no wd on bias/gamma/beta (reference: optimizer.py set_wd_mult)."""
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym is not None:
            attr = self.sym.attr_dict()
            for name in self.sym.list_arguments():
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd


register = Optimizer.register
create = Optimizer.create_optimizer


def _clipped(grad_np, rescale, clip):
    g = grad_np * rescale
    if clip is not None:
        g = np.clip(g, -clip, clip)
    return g


@register
class SGD(Optimizer):
    """SGD with momentum via the fused ops (reference: optimizer.py:307-355)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        assert isinstance(weight, NDArray)
        assert isinstance(grad, NDArray)
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        kwargs = {
            "lr": lr, "wd": wd, "rescale_grad": self.rescale_grad,
            "clip_gradient": self.clip_gradient if self.clip_gradient is not None else -1.0,
        }
        if state is not None:
            res_w, res_m = _invoke_all(
                "sgd_mom_update", [weight, grad, state], dict(kwargs, momentum=self.momentum)
            )
            weight._set_data(res_w)
            state._set_data(res_m)
        else:
            res_w, = _invoke_all("sgd_update", [weight, grad], kwargs)
            weight._set_data(res_w)


def _invoke_all(op_name, ndargs, attrs):
    """Run a registered op returning ALL outputs (including hidden state
    outputs) as raw jax arrays — used by optimizers to write back mutated
    weights/states (FMutateInputs semantics)."""
    from .ops.registry import get_op
    from .ndarray import _get_jitted

    op = get_op(op_name)
    cattrs, _ = op.canonicalize_attrs(attrs)
    args = [a.data for a in ndargs]
    fn = _get_jitted(op, cattrs, len(args), 0, False)
    outs, _ = fn(args, [], None)
    return outs


@register
class NAG(SGD):
    """Nesterov accelerated SGD (reference: optimizer.py NAG)."""

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        if state is not None:
            mom = state
            mom *= self.momentum
            g += wd * weight
            mom += g
            g += self.momentum * mom
            weight += -lr * g
        else:
            weight += -lr * (g + wd * weight)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference: optimizer.py SGLD)."""

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        noise = nd.random_normal(loc=0.0, scale=math.sqrt(lr), shape=weight.shape, ctx=weight.context)
        weight += -lr / 2 * (g + wd * weight) + noise


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference: optimizer.py DCASGD)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (zeros(weight.shape, weight.context, dtype=weight.dtype), weight.copy())

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        mon, previous_weight = state
        if mon:
            mon *= self.momentum
            mon += -lr * (g + wd * weight + self.lamda * g * g * (weight - previous_weight))
        else:
            assert self.momentum == 0.0
            mon = -lr * (g + wd * weight + self.lamda * g * g * (weight - previous_weight))
        previous_weight[:] = weight
        weight += mon


@register
class ccSGD(SGD):
    """Alias of SGD in this build (reference keeps it for compat)."""


@register
class Adam(Optimizer):
    """Adam via fused op (reference: optimizer.py:485)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, weight.context, dtype=weight.dtype),  # mean
            zeros(weight.shape, weight.context, dtype=weight.dtype),  # variance
        )

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        t = self._index_update_count[index]
        mean, var = state
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        lr_t = lr * math.sqrt(coef2) / coef1
        res_w, res_m, res_v = _invoke_all(
            "adam_update",
            [weight, grad, mean, var],
            {
                "lr": lr_t, "wd": wd, "rescale_grad": self.rescale_grad,
                "clip_gradient": self.clip_gradient if self.clip_gradient is not None else -1.0,
                "beta1": self.beta1, "beta2": self.beta2, "epsilon": self.epsilon,
            },
        )
        weight._set_data(res_w)
        mean._set_data(res_m)
        var._set_data(res_v)


@register
class AdaGrad(Optimizer):
    """(reference: optimizer.py:538)"""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        history = state
        history += g * g
        weight += -lr * (g / nd.sqrt(history + self.float_stable_eps) + wd * weight)


@register
class RMSProp(Optimizer):
    """RMSProp(+Alex Graves variant) via fused ops (reference: optimizer.py:575)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        if self.centered:
            return (
                zeros(weight.shape, weight.context),  # n
                zeros(weight.shape, weight.context),  # g
                zeros(weight.shape, weight.context),  # delta
            )
        return (zeros(weight.shape, weight.context),)  # n

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        kwargs = {
            "lr": lr, "wd": wd, "rescale_grad": self.rescale_grad,
            "clip_gradient": self.clip_gradient if self.clip_gradient is not None else -1.0,
            "gamma1": self.gamma1, "epsilon": self.epsilon,
        }
        if not self.centered:
            (n,) = state
            res_w, res_n = _invoke_all("rmsprop_update", [weight, grad, n], kwargs)
            weight._set_data(res_w)
            n._set_data(res_n)
        else:
            n, g, delta = state
            kwargs["gamma2"] = self.gamma2
            res_w, res_n, res_g, res_d = _invoke_all(
                "rmspropalex_update", [weight, grad, n, g, delta], kwargs
            )
            weight._set_data(res_w)
            n._set_data(res_n)
            g._set_data(res_g)
            delta._set_data(res_d)
        if self.clip_weights:
            weight._set_data(
                nd.clip(weight, a_min=-self.clip_weights, a_max=self.clip_weights).data
            )


@register
class AdaDelta(Optimizer):
    """(reference: optimizer.py:651)"""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, weight.context),  # accumulated g
            zeros(weight.shape, weight.context),  # accumulated delta
        )

    def update(self, index, weight, grad, state):
        wd = self._get_wd(index)
        self._update_count(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        acc_g, acc_delta = state
        acc_g *= self.rho
        acc_g += (1.0 - self.rho) * g * g
        current_delta = nd.sqrt(acc_delta + self.epsilon) / nd.sqrt(acc_g + self.epsilon) * g
        acc_delta *= self.rho
        acc_delta += (1.0 - self.rho) * current_delta * current_delta
        weight[:] = (weight - current_delta - wd * weight).data


@register
class Ftrl(Optimizer):
    """(reference: optimizer.py:700)"""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (
            zeros(weight.shape, weight.context),  # z
            zeros(weight.shape, weight.context),  # n
        )

    def update(self, index, weight, grad, state):
        lr = self._get_lr(index)
        wd = self._get_wd(index)
        self._update_count(index)
        g = grad * self.rescale_grad
        if self.clip_gradient is not None:
            g = nd.clip(g, a_min=-self.clip_gradient, a_max=self.clip_gradient)
        z, n = state
        z += g - (nd.sqrt(n + g * g) - nd.sqrt(n)) / lr * weight
        n += g * g
        w_np = (
            (nd.sign(z) * self.lamda1 - z)
            / ((self.beta + nd.sqrt(n)) / lr + wd)
            * (nd.abs(z) > self.lamda1)
        )
        weight[:] = w_np.data


@register
class Test(Optimizer):
    """Trivial updater used by kvstore tests (reference: optimizer.py:753)."""

    def create_state(self, index, weight):
        return zeros(weight.shape, weight.context)

    def update(self, index, weight, grad, state):
        weight[:] = (weight + grad * self.rescale_grad).data
        state[:] = weight


class Updater:
    """Weight updater with per-index state (reference: optimizer.py:769;
    get_states/set_states power optimizer-state checkpointing, module.py:134)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
            self.states_synced[index] = True
        elif not self.states_synced[index]:
            self.states[index] = self.sync_state_context(self.states[index], weight.context)
            self.states_synced[index] = True
        self.optimizer.update(index, weight, grad, self.states[index])

    def sync_state_context(self, state, context):
        if isinstance(state, NDArray):
            return state.as_in_context(context)
        if isinstance(state, (tuple, list)):
            return type(state)(self.sync_state_context(i, context) for i in state)
        return state

    def set_states(self, states):
        states = pickle.loads(states)
        self.states = {
            k: self._from_np(v) for k, v in states.items()
        }
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self):
        return pickle.dumps({k: self._to_np(v) for k, v in self.states.items()})

    def check_state_shapes(self, shapes_by_index, source=None):
        """Validate restored states against the weight shapes they will
        update (every state leaf of the built-in optimizers is
        weight-shaped). A ``.states`` file from a DIFFERENT model used to
        pickle-load silently and explode later inside the first
        ``optimizer.update`` — this surfaces the mismatch at load time and
        leaves the updater empty (a clean warm start) instead of armed with
        garbage."""
        def _leaf_shapes(state):
            if isinstance(state, NDArray):
                return [tuple(state.shape)]
            if isinstance(state, (tuple, list)):
                return [s for part in state for s in _leaf_shapes(part)]
            return []

        bad = []
        for idx, state in self.states.items():
            expected = shapes_by_index.get(idx)
            if expected is None:
                bad.append("index %s not among the %d bound parameters"
                           % (idx, len(shapes_by_index)))
                continue
            for shape in _leaf_shapes(state):
                if shape != tuple(expected):
                    bad.append("index %s: state shape %s != weight shape %s"
                               % (idx, shape, tuple(expected)))
        if bad:
            self.states = {}
            self.states_synced = {}
            raise MXNetError(
                "optimizer states%s do not match this model (%s) — "
                "was the model edited between runs? Discarding them for a "
                "warm start." % (
                    " from %r" % source if source else "",
                    "; ".join(bad[:4]) + ("; ..." if len(bad) > 4 else "")))

    @staticmethod
    def _to_np(state):
        if isinstance(state, NDArray):
            return state.asnumpy()
        if isinstance(state, (tuple, list)):
            return type(state)(Updater._to_np(i) for i in state)
        return state

    @staticmethod
    def _from_np(state):
        if isinstance(state, np.ndarray):
            return nd.array(state)
        if isinstance(state, (tuple, list)):
            return type(state)(Updater._from_np(i) for i in state)
        return state


# get_updater is defined after FusedUpdater at the bottom of this module


# ---- fused whole-model update ----------------------------------------------
# The per-parameter update loop (reference: model.py:99 _update_params) costs
# one dispatch per parameter per step — ~160 round trips for ResNet-50, which
# dominates the Module path on high-latency transports. FusedUpdater._builder
# maps supported optimizers (exactly SGD and Adam; subclasses like NAG/ccSGD
# deliberately fall back, their math differs) to a tree-update function that
# batches ALL parameters into one jitted XLA call with math identical to the
# per-index ``update``. lr/wd/t enter as dynamic scalars so schedulers don't
# retrace.


def _sgd_tree(momentum, rescale, clip):
    import jax.numpy as jnp

    def step(ws, gs, ss, lrs, wds):
        new_w, new_s = [], []
        for w, g, s, lr, wd in zip(ws, gs, ss, lrs, wds):
            g = g.astype(w.dtype) * rescale
            if clip is not None:
                g = jnp.clip(g, -clip, clip)
            g = g + wd * w
            if momentum:
                m = momentum * s - lr * g
                new_s.append(m)
                new_w.append(w + m)
            else:
                new_s.append(s)
                new_w.append(w - lr * g)
        return new_w, new_s

    return step


def _rmsprop_tree(gamma1, eps, rescale, clip):
    import jax.numpy as jnp

    def step(ws, gs, ss, lrs, wds):
        new_w, new_s = [], []
        for w, g, (n,), lr, wd in zip(ws, gs, ss, lrs, wds):
            g = g.astype(w.dtype) * rescale
            if clip is not None:
                g = jnp.clip(g, -clip, clip)
            g = g + wd * w
            n = (1 - gamma1) * jnp.square(g) + gamma1 * n
            new_w.append(w - lr * g / jnp.sqrt(n + eps))
            new_s.append((n,))
        return new_w, new_s

    return step


def _adam_tree(beta1, beta2, eps, rescale, clip):
    import jax.numpy as jnp

    def step(ws, gs, ss, lrs, wds):
        new_w, new_s = [], []
        for w, g, (mean, var), lr_t, wd in zip(ws, gs, ss, lrs, wds):
            g = g.astype(w.dtype) * rescale
            if clip is not None:
                g = jnp.clip(g, -clip, clip)
            g = g + wd * w
            mean = beta1 * mean + (1 - beta1) * g
            var = beta2 * var + (1 - beta2) * g * g
            new_w.append(w - lr_t * mean / (jnp.sqrt(var) + eps))
            new_s.append((mean, var))
        return new_w, new_s

    return step


class FusedUpdater(Updater):
    """Updater that applies one jitted program across all parameters when the
    optimizer supports it (SGD/Adam); falls back to per-index updates
    otherwise. State layout and get_states/set_states stay identical to
    Updater, so checkpoints interchange."""

    def __init__(self, optimizer):
        super().__init__(optimizer)
        self._jitted = None  # jax.jit handles per-shape caching internally

    def _builder(self):
        opt = self.optimizer
        clip = opt.clip_gradient
        if type(opt) is SGD:
            return _sgd_tree(opt.momentum, opt.rescale_grad, clip)
        if type(opt) is Adam:
            return _adam_tree(opt.beta1, opt.beta2, opt.epsilon, opt.rescale_grad, clip)
        if type(opt) is RMSProp and not opt.centered and opt.clip_weights is None:
            return _rmsprop_tree(opt.gamma1, opt.epsilon, opt.rescale_grad, clip)
        return None

    def update_all(self, pairs):
        """pairs: list of (index, grad NDArray, weight NDArray)."""
        builder = self._builder()
        if builder is None:
            for index, g, w in pairs:
                self(index, g, w)
            return
        # one jit call per DEVICE: arrays are device-committed, and a single
        # call over replicas on different devices would be rejected by jax
        by_dev = {}
        for p in pairs:
            key = (p[2].context.device_typeid, p[2].context.device_id)
            by_dev.setdefault(key, []).append(p)
        if self._jitted is None:
            from . import compileobs

            # wrapper-scoped (no graph_key): per-device call groups of one
            # updater legitimately hold one signature each
            self._jitted = compileobs.jit(
                builder, "optimizer.fused_update",
                site="mxnet_tpu/optimizer.py:FusedUpdater.update_all")
        for dev_pairs in by_dev.values():
            self._update_one_device(dev_pairs)

    def _update_one_device(self, pairs):
        opt = self.optimizer
        ws, gs, ss, lrs, wds = [], [], [], [], []
        momentum_sgd = type(opt) is SGD and opt.momentum
        for index, g, w in pairs:
            if index not in self.states:
                self.states[index] = opt.create_state(index, w)
                self.states_synced[index] = True
            elif not self.states_synced[index]:
                # restored states (set_states) live on the default context
                self.states[index] = self.sync_state_context(self.states[index], w.context)
                self.states_synced[index] = True
            lr = opt._get_lr(index)
            wd = opt._get_wd(index)
            opt._update_count(index)
            if type(opt) is Adam:
                t = opt._index_update_count[index]
                lr = lr * math.sqrt(1 - opt.beta2 ** t) / (1 - opt.beta1 ** t)
                mean, var = self.states[index]
                ss.append((mean.data, var.data))
            elif type(opt) is RMSProp:
                ss.append((self.states[index][0].data,))
            elif momentum_sgd:
                ss.append(self.states[index].data)
            else:
                ss.append(np.zeros((), np.float32))  # placeholder leaf
            ws.append(w.data)
            gs.append(g.data)
            lrs.append(np.float32(lr))
            wds.append(np.float32(wd))
        new_w, new_s = self._jitted(ws, gs, ss, lrs, wds)
        for (index, g, w), nw, ns in zip(pairs, new_w, new_s):
            w._set_data(nw)
            if type(opt) is Adam:
                self.states[index][0]._set_data(ns[0])
                self.states[index][1]._set_data(ns[1])
            elif type(opt) is RMSProp:
                self.states[index][0]._set_data(ns[0])
            elif momentum_sgd:
                self.states[index]._set_data(ns)


def get_updater(optimizer):
    """(reference: optimizer.py get_updater) — fused when possible."""
    return FusedUpdater(optimizer)
