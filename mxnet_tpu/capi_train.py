"""Python side of the TRAINING C API slice (src/c_api_train.cc).

The reference exposes ~120 C functions over its C++ engine
(include/mxnet/c_api.h); the predict subset already ships in
libmxtpu_predict.so. This module backs the training subset — Symbol from
JSON, simple_bind, forward/backward, argument/gradient/output access, and a
fused SGD update — so a pure C/C++ client can run a whole training loop
(compiled client test: tests/test_c_train.py).

Every ``_c_*`` function takes/returns only C-friendly types (str, bytes,
int, float, opaque PyObject handles) — the C shim marshals nothing else.
"""
from __future__ import annotations

import numpy as np

__all__ = []


def _c_symbol_from_json(json_str):
    from .symbol import load_json

    return load_json(json_str)


def _c_symbol_to_json(sym):
    return sym.tojson()


def _c_symbol_arguments(sym):
    return list(sym.list_arguments())


def _c_symbol_outputs(sym):
    return list(sym.list_outputs())


def _c_symbol_aux_states(sym):
    return list(sym.list_auxiliary_states())


def _c_variable(name):
    from . import symbol

    return symbol.Variable(name)


def _c_create_symbol(op_name, name, param_keys, param_vals,
                     input_keys, input_syms):
    """Atomic-symbol creation + composition in one call (the reference splits
    this into MXSymbolCreateAtomicSymbol + MXSymbolCompose; the cpp-package's
    Operator::CreateSymbol always performs both back-to-back, so the C slice
    exposes the fused form). All params arrive as strings — the op's
    Parameter schema parses them, exactly as the JSON loader does."""
    from . import symbol
    from .base import MXNetError
    from .ops.registry import list_ops

    if op_name not in list_ops():
        raise MXNetError("no operator named %r" % (op_name,))
    fn = getattr(symbol, op_name)
    kwargs = dict(zip(param_keys, param_vals))
    if name:
        kwargs["name"] = name
    args = []
    for k, s in zip(input_keys, input_syms):
        if k:
            kwargs[k] = s
        else:
            args.append(s)
    return fn(*args, **kwargs)


class _CExecutor:
    """Bound training executor + the host-side mirrors the C client reads."""

    def __init__(self, sym, dev_type, dev_id, shapes, grad_req):
        from . import context

        ctx = context.Context(dev_type, dev_id)
        self.executor = sym.simple_bind(ctx, grad_req=grad_req, **shapes)
        # the names the C client binds shapes for ARE its data/label inputs:
        # updates must never touch them
        self.input_names = frozenset(shapes)
        self.outputs = []

    def arg(self, name):
        arr = self.executor.arg_dict.get(name)
        if arr is None:
            raise KeyError("no argument named %r" % (name,))
        return arr


def _c_simple_bind(sym, dev_type, dev_id, shape_keys, shape_data, grad_req):
    """shape_keys: list of names; shape_data: flat list-of-lists of ints."""
    shapes = {k: tuple(int(d) for d in s)
              for k, s in zip(shape_keys, shape_data)}
    return _CExecutor(sym, dev_type, int(dev_id), shapes, grad_req)


def _c_set_arg(cexec, name, data_bytes):
    arr = cexec.arg(name)
    flat = np.frombuffer(data_bytes, dtype=np.float32)
    if flat.size != int(np.prod(arr.shape)):
        raise ValueError(
            "size mismatch for %s: got %d floats, need %d"
            % (name, flat.size, int(np.prod(arr.shape))))
    arr[:] = flat.reshape(arr.shape).astype(arr.dtype)


def _c_set_aux(cexec, name, data_bytes):
    """(reference: aux states are set through MXExecutor's aux dict —
    base_module.set_params writes both arg and aux)."""
    arr = cexec.executor.aux_dict.get(name)
    if arr is None:
        raise ValueError("no auxiliary state named %s" % name)
    flat = np.frombuffer(data_bytes, dtype=np.float32)
    if flat.size != int(np.prod(arr.shape)):
        raise ValueError(
            "size mismatch for aux %s: got %d floats, need %d"
            % (name, flat.size, int(np.prod(arr.shape))))
    arr[:] = flat.reshape(arr.shape).astype(arr.dtype)


def _c_get_array(cexec, which, name_or_index):
    """bytes of (arg|grad|output|aux) as float32."""
    if which == "arg":
        arr = cexec.arg(name_or_index)
    elif which == "grad":
        arr = cexec.executor.grad_dict.get(name_or_index)
        if arr is None:
            raise KeyError("no gradient for %r" % (name_or_index,))
    elif which == "aux":
        arr = cexec.executor.aux_dict[name_or_index]
    else:
        arr = cexec.outputs[int(name_or_index)]
    return np.ascontiguousarray(
        arr.asnumpy().astype(np.float32)).tobytes()


def _c_get_shape(cexec, which, name_or_index):
    if which == "output":
        return list(cexec.outputs[int(name_or_index)].shape)
    if which == "grad":
        return list(cexec.executor.grad_dict[name_or_index].shape)
    return list(cexec.arg(name_or_index).shape)


def _c_num_outputs(cexec):
    return len(cexec.executor._symbol.list_outputs())


def _c_forward(cexec, is_train):
    cexec.outputs = cexec.executor.forward(is_train=bool(is_train))


def _c_backward(cexec):
    cexec.executor.backward()


def _c_momentum_update(cexec, lr, wd, momentum, rescale=1.0):
    """SGD with momentum over every parameter with a gradient (velocity
    state lives on the executor, device-resident): v = mom*v -
    lr*(rescale*grad + wd*w); w += v — the reference's sgd_mom_update rule
    (src/operator/optimizer_op-inl.h SGDMomUpdate). ``rescale`` is the
    reference optimizer's rescale_grad — loss-output gradients are
    batch-summed, so pass 1/batch_size for batch-mean training."""
    ex = cexec.executor
    if not hasattr(cexec, "mom"):
        cexec.mom = {}
    for name, grad in ex.grad_dict.items():
        if grad is None or name in cexec.input_names:
            continue
        w = ex.arg_dict[name]
        v = cexec.mom.get(name)
        if v is None:
            from . import ndarray as nd

            v = nd.zeros(w.shape, ctx=w.context, dtype=w.dtype)
            cexec.mom[name] = v
        v[:] = momentum * v - lr * (rescale * grad + wd * w)
        w[:] = w + v


def _c_save_params(cexec, path):
    """Write the executor's parameters (+aux) in the reference checkpoint
    format — `arg:`/`aux:` prefixed NDArray dict (model.py save_checkpoint),
    so C-trained weights load directly into Python Module/FeedForward and
    the reference itself."""
    from . import ndarray as nd

    ex = cexec.executor
    save_dict = {
        "arg:%s" % k: v for k, v in ex.arg_dict.items()
        if k not in cexec.input_names
    }
    save_dict.update({"aux:%s" % k: v for k, v in ex.aux_dict.items()})
    nd.save(path, save_dict)


def _c_load_params(cexec, path):
    from . import ndarray as nd

    ex = cexec.executor
    loaded = nd.load(path)
    n = 0
    for k, v in loaded.items():
        tag, _, name = k.partition(":")
        if tag == "arg" and name in ex.arg_dict \
                and name not in cexec.input_names:
            ex.arg_dict[name][:] = v
            n += 1
        elif tag == "aux" and name in ex.aux_dict:
            ex.aux_dict[name][:] = v
            n += 1
    return n


def _c_sgd_update(cexec, lr, wd, rescale=1.0):
    """w -= lr * (rescale*grad + wd*w) over every PARAMETER with a gradient
    — the minimal in-framework update so a C client need not round-trip
    params. The client's bound inputs (data/labels) also carry gradients
    under grad_req='write' but must never be updated. ``rescale`` is the
    reference optimizer's rescale_grad (pass 1/batch_size for batch-mean
    training; loss gradients are batch-summed). (Full optimizers remain the
    Python/Module surface's job.)"""
    ex = cexec.executor
    for name, grad in ex.grad_dict.items():
        if grad is None or name in cexec.input_names:
            continue
        w = ex.arg_dict[name]
        w[:] = w - lr * (rescale * grad + wd * w)


# ---- Profiler (reference: c_api.h MXSetProfilerConfig/MXSetProfilerState/
# MXDumpProfile) -------------------------------------------------------------

def _c_profiler_set_config(mode, filename):
    from . import profiler

    profiler.profiler_set_config(mode=mode, filename=filename)


def _c_profiler_set_state(state):
    from . import profiler

    # the reference's C form takes 0/1; accept both that and the strings
    if state in (0, 1):
        state = "run" if state else "stop"
    profiler.profiler_set_state(state)


def _c_dump_profile():
    from . import profiler

    profiler.dump_profile()


# ---- Rtc (reference: c_api.h MXRtcCreate/MXRtcPush/MXRtcFree) --------------

def _c_rtc_create(name, input_names, output_names, kernel):
    from .rtc import Rtc

    # the C boundary carries names only; arrays bind at push time
    return Rtc(name, [(n, None) for n in input_names],
               [(n, None) for n in output_names], kernel)


def _c_rtc_push(rtc, input_blobs, input_shapes, output_shapes):
    """inputs as float32 bytes + shapes; returns list of output bytes."""
    from . import ndarray as nd

    ins = []
    for blob, shape in zip(input_blobs, input_shapes):
        flat = np.frombuffer(blob, dtype=np.float32)
        ins.append(nd.array(flat.reshape([int(d) for d in shape])))
    outs = [nd.zeros(tuple(int(d) for d in s)) for s in output_shapes]
    rtc.push(ins, outs)
    return [np.ascontiguousarray(o.asnumpy().astype(np.float32)).tobytes()
            for o in outs]


# ---- DataIter (reference: c_api.h MXListDataIters/MXDataIterCreateIter/
# Next/GetData/GetLabel/GetPadNum family) ------------------------------------

_C_ITER_NAMES = ("MNISTIter", "CSVIter", "ImageRecordIter",
                 "ImageDetRecordIter")


def _c_iter_list():
    return list(_C_ITER_NAMES)


def _parse_iter_param(v):
    """C clients pass every param as a string (the reference's convention);
    parse shapes/numbers/bools, fall back to the raw string. A value naming
    an existing path stays a string even if it LOOKS like a literal (a CSV
    file named '123' must not become the int 123)."""
    import ast
    import os

    s = v.strip()
    if os.path.exists(s):
        return s
    low = s.lower()
    if low in ("true", "false"):
        return low == "true"
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


class _CDataIter:
    def __init__(self, name, params):
        from . import io, io_image

        if name not in _C_ITER_NAMES:
            raise KeyError(
                "no data iterator named %r (have: %s)"
                % (name, ", ".join(_C_ITER_NAMES)))
        cls = getattr(io, name, None) or getattr(io_image, name)
        self.it = cls(**{k: _parse_iter_param(v) for k, v in params.items()})
        self.batch = None

    def next(self):
        try:
            self.batch = self.it.next()
            return True
        except StopIteration:
            self.batch = None
            return False

    def _current(self, which):
        if self.batch is None:
            raise RuntimeError("no current batch (call Next first)")
        arrs = self.batch.data if which == "data" else self.batch.label
        return arrs[0]

    def _array(self, which):
        return np.ascontiguousarray(
            self._current(which).asnumpy().astype(np.float32))


def _c_iter_create(name, param_keys, param_vals):
    return _CDataIter(name, dict(zip(param_keys, param_vals)))


def _c_iter_next(cit):
    return 1 if cit.next() else 0


def _c_iter_reset(cit):
    cit.it.reset()
    cit.batch = None


def _c_iter_get(cit, which):
    return cit._array(which).tobytes()


def _c_iter_shape(cit, which):
    # shape only — no batch materialization/host copy
    return [int(d) for d in cit._current(which).shape]


def _c_iter_pad(cit):
    if cit.batch is None:
        raise RuntimeError("no current batch (call Next first)")
    return int(cit.batch.pad or 0)


# ---- KVStore (reference: c_api.h MXKVStoreCreate/Init/Push/Pull family) ----

class _CKVStore:
    """KVStore handle + the host mirrors the C client reads. Values cross
    the boundary as float32 blobs; device placement/aggregation is the
    Python KVStore's job (same compute path as the Python surface)."""

    def __init__(self, kv_type):
        from .kvstore import create

        self.kv = create(kv_type)
        self.shapes = {}


def _c_kv_create(kv_type):
    return _CKVStore(kv_type)


def _c_kv_type(ckv):
    return ckv.kv.type


def _c_kv_rank(ckv):
    return int(ckv.kv.rank)


def _c_kv_num_workers(ckv):
    return int(ckv.kv.num_workers)


def _kv_array(ckv, key, data_bytes, shape):
    from . import ndarray as nd

    flat = np.frombuffer(data_bytes, dtype=np.float32)
    shape = tuple(int(d) for d in shape)
    if flat.size != int(np.prod(shape)):
        raise ValueError("key %s: got %d floats for shape %s"
                         % (key, flat.size, shape))
    ckv.shapes[int(key)] = shape
    return nd.array(flat.reshape(shape))


def _c_kv_init(ckv, key, data_bytes, shape):
    ckv.kv.init(int(key), _kv_array(ckv, key, data_bytes, shape))


def _c_kv_push(ckv, key, data_bytes, shape):
    ckv.kv.push(int(key), _kv_array(ckv, key, data_bytes, shape))


def _c_kv_pull(ckv, key):
    from . import ndarray as nd

    shape = ckv.shapes.get(int(key))
    if shape is None:
        raise KeyError("key %s was never initialized through this handle"
                       % (key,))
    out = nd.zeros(shape)
    ckv.kv.pull(int(key), out=out)
    return np.ascontiguousarray(
        out.asnumpy().astype(np.float32)).tobytes()


def _c_init_xavier(cexec, seed):
    """Xavier-initialize every weight, zero biases — convenience so the C
    client does not need an RNG."""
    from . import initializer as init_mod
    from . import random as rnd

    rnd.seed(int(seed))
    init = init_mod.Xavier()
    for name, arr in cexec.executor.arg_dict.items():
        if name.endswith(("_weight", "_bias", "_gamma", "_beta")):
            init(name, arr)


# ---- round 4: C API long tail (reference c_api.h:518 MXImperativeInvoke,
# :854 MXSymbolInferShape, :1087 MXExecutorSetMonitorCallback + op listing
# for MXSymbolListAtomicSymbolCreators) -------------------------------------

def _c_list_all_ops():
    """Registered op names (reference: MXListAllOpNames / the creator list
    behind MXSymbolListAtomicSymbolCreators)."""
    from .ops.registry import list_ops

    return sorted(list_ops())


def _c_imperative_invoke(op_name, blobs, shapes, dtypes, param_keys,
                         param_vals, in_ids=None):
    """Run one op imperatively on host blobs (reference: MXImperativeInvoke,
    c_api_ndarray.cc:324). Returns (out_blobs, out_shapes, out_dtypes).

    ``in_ids`` carries the C handle ids: inputs known to the autograd
    session (marked variables, adopted outputs) are fed as their LIVE
    python arrays so the tape stays connected — marked variables get their
    value re-synced from the C bytes first (the C side may have written the
    handle since mark time). When recording, outputs are stashed for
    _c_autograd_adopt."""
    from . import ndarray as nd
    from .base import _DTYPE_MX_TO_NP, _DTYPE_NP_TO_MX
    from .contrib import autograd

    global _AUTOGRAD_PENDING
    # a failed previous invoke (error after the python call) must not leave
    # its outputs around for THIS invoke's adoption
    _AUTOGRAD_PENDING = []
    if in_ids is None:
        in_ids = [0] * len(blobs)
    arrs = []
    for b, s, t, hid in zip(blobs, shapes, dtypes, in_ids):
        live = _AUTOGRAD_ARRAYS.get(int(hid))
        if live is not None:
            if int(hid) in _AUTOGRAD_MARKED:
                dt = np.dtype(_DTYPE_MX_TO_NP[int(t)])
                cur = np.frombuffer(bytes(b), dtype=dt).reshape(
                    [int(x) for x in s])
                if cur.shape == tuple(live.shape):
                    live._set_data(np.asarray(cur, dtype=dt))
            arrs.append(live)
            continue
        if len(b) == 0 and any(int(x) for x in s):
            # the C side skipped the bytes expecting a live tape array we
            # no longer hold — fail loudly rather than compute on garbage
            from .base import MXNetError
            raise MXNetError("stale autograd handle fed to %s" % op_name)
        arrs.append(_from_blob(b, s, t))
    attrs = {k: v for k, v in zip(param_keys, param_vals)}
    res = nd.imperative_invoke(op_name, arrs, attrs)
    if not isinstance(res, (list, tuple)):
        res = [res]
    if autograd.is_recording():
        _AUTOGRAD_PENDING = list(res)
    out_blobs, out_shapes, out_dtypes = [], [], []
    for r in res:
        a = r.asnumpy()
        out_blobs.append(np.ascontiguousarray(a).tobytes())
        out_shapes.append([int(x) for x in a.shape])
        out_dtypes.append(int(_DTYPE_NP_TO_MX[np.dtype(a.dtype)]))
    return out_blobs, out_shapes, out_dtypes


def _c_infer_shape(sym, keys, shape_data, partial):
    """(reference: MXSymbolInferShape / MXSymbolInferShapePartial,
    c_api.h:854). ``keys`` empty -> positional over list_arguments order.
    Returns (arg_shapes, out_shapes, aux_shapes, complete); unknown shapes
    come back as []."""
    arg_names = list(sym.list_arguments())
    if not keys:
        keys = arg_names[:len(shape_data)]
    kwargs = {k: tuple(int(x) for x in s)
              for k, s in zip(keys, shape_data) if len(s)}
    if partial:
        arg_shapes, out_shapes, aux_shapes = sym.infer_shape_partial(**kwargs)
    else:
        arg_shapes, out_shapes, aux_shapes = sym.infer_shape(**kwargs)

    def clean(lst):
        return [[int(x) for x in s] if s is not None else []
                for s in (lst or [])]

    def known(lst):
        return lst is not None and all(
            s is not None and 0 not in tuple(s) for s in lst)

    complete = int(known(arg_shapes) and known(out_shapes)
                   and (aux_shapes is None or known(aux_shapes)))
    return clean(arg_shapes), clean(out_shapes), clean(aux_shapes), complete


def _c_forward_monitored(cexec, is_train):
    """Forward with the per-node monitor active (reference:
    MXExecutorSetMonitorCallback -> GraphExecutor::ExecuteMonCallback,
    graph_executor.cc:761-781). Returns [(name, f32_bytes, shape), ...] in
    execution order; the C shim replays them into the client's callback."""
    ex = cexec.executor
    collected = []

    def cb(name, arr):
        a = np.ascontiguousarray(arr.asnumpy().astype(np.float32))
        collected.append((name, a.tobytes(), [int(x) for x in a.shape]))

    prev_cb = ex.monitor_callback
    prev_active = ex._monitor_active
    ex.set_monitor_callback(cb)
    try:
        cexec.outputs = ex.forward(is_train=bool(is_train))
    finally:
        ex.monitor_callback = prev_cb
        ex._monitor_active = prev_active
    return collected


def _c_random_seed(seed):
    from . import random as rnd

    rnd.seed(int(seed))


def _c_symbol_from_file(path):
    from .symbol import load

    return load(path)


def _c_symbol_save_file(sym, path):
    sym.save(path)


def _c_symbol_copy(sym):
    from .symbol import load_json

    return load_json(sym.tojson())


def _c_symbol_name(sym):
    return sym.name or ""


def _c_symbol_print(sym):
    return sym.debug_str()


def _c_symbol_group(syms):
    from .symbol import Group

    return Group(list(syms))


def _c_symbol_internals(sym):
    return sym.get_internals()


def _c_symbol_get_output(sym, index):
    return sym[int(index)]


def _c_symbol_attr(sym, key):
    v = sym.attr(key)
    return ("", 0) if v is None else (str(v), 1)


def _c_symbol_set_attr(sym, key, value):
    sym._set_attr(**{key: value})


def _c_symbol_list_attr(sym, recursive):
    d = sym.list_attr(recursive=bool(recursive)) if recursive in (0, 1) \
        else sym.list_attr()
    keys, vals = [], []
    for k, v in sorted(d.items()):
        keys.append(str(k))
        vals.append(str(v))
    return keys, vals


def _c_infer_type(sym, keys, dtypes):
    """(reference: MXSymbolInferType c_api.h:888) — int mshadow flags."""
    from .base import _DTYPE_MX_TO_NP, _DTYPE_NP_TO_MX

    kwargs = {k: np.dtype(_DTYPE_MX_TO_NP[int(t)])
              for k, t in zip(keys, dtypes)}
    arg_types, out_types, aux_types = sym.infer_type(**kwargs)

    def flags(lst):
        return [int(_DTYPE_NP_TO_MX[np.dtype(t)]) if t is not None else -1
                for t in (lst or [])]

    complete = int(all(t is not None for t in (arg_types or [])))
    return flags(arg_types), flags(out_types), flags(aux_types), complete


def _c_atomic_symbol_info(op_name):
    """(reference: MXSymbolGetAtomicSymbolInfo c_api.h:644) — name, doc,
    arg names/types/descriptions from the op registry's Param schema."""
    from .ops.registry import get_op

    op = get_op(op_name)
    doc = getattr(op, "doc", "") or ""
    keys, types, descs = [], [], []
    params = getattr(op, "params", None) or {}
    for k, spec in sorted(params.items()):
        keys.append(str(k))
        kind = getattr(spec, "kind", "value")
        if getattr(spec, "required", False):
            types.append("%s, required" % kind)
        else:
            types.append("%s, optional, default=%r"
                         % (kind, getattr(spec, "default", None)))
        descs.append("")
    return str(doc), keys, types, descs


def _c_kv_barrier(ckv):
    ckv.kv.barrier()




def _c_symbol_children(sym):
    from .base import MXNetError

    c = sym.get_children()
    if c is None:
        raise MXNetError("symbol has no children (a Variable)")
    return c


def _c_kv_send_command(ckv, head, body):
    ckv.kv._send_command_to_servers(int(head), body)


def _c_kv_num_dead_node(ckv, node_id):
    return int(ckv.kv.get_num_dead_node(int(node_id)))


def _c_exec_outputs(cexec):
    """All output blobs at once (reference: MXExecutorOutputs c_api.h:1010)
    -> [(f32_bytes, shape), ...]."""
    outs = cexec.executor.outputs
    ret = []
    for o in outs:
        a = np.ascontiguousarray(o.asnumpy().astype(np.float32))
        ret.append((a.tobytes(), [int(x) for x in a.shape]))
    return ret


# ---- imperative autograd session (reference: MXAutogradSetIsTraining /
# MXAutogradMarkVariables / MXAutogradComputeGradient, c_api.h:549-601 over
# src/ndarray/autograd.cc). The C boundary marshals host blobs, so the
# session keeps the LIVE python NDArray for every C handle the tape must
# see: marked variables (value re-synced from the C bytes at each invoke)
# and recorded op outputs (adopted under their C handle ids right after
# MXImperativeInvoke creates the handles).

_AUTOGRAD_ARRAYS = {}   # C handle id -> live python NDArray on the tape
_AUTOGRAD_MARKED = {}   # C var handle id -> (var, grad, grad handle id, req)
_AUTOGRAD_PENDING = []  # outputs of the last recorded invoke, pre-adoption


def _from_blob(blob, shape, dtype):
    from . import ndarray as nd
    from .base import _DTYPE_MX_TO_NP

    dt = np.dtype(_DTYPE_MX_TO_NP[int(dtype)])
    a = np.frombuffer(bytes(blob), dtype=dt).reshape([int(x) for x in shape])
    return nd.array(a, dtype=dt)


def _c_autograd_set_is_training(flag):
    from .contrib import autograd

    return 1 if autograd.set_is_training(bool(flag)) else 0


def _c_autograd_mark_variables(var_ids, blobs, shapes, dtypes, reqs,
                               grad_ids, grad_blobs):
    """reqs use the reference OpReqType enum: 0 null / 1 write /
    2 write-inplace (treated as write) / 3 add."""
    from .contrib import autograd

    req_name = {0: "null", 1: "write", 2: "write", 3: "add"}
    variables, gradients, grad_reqs = [], [], []
    for vid, b, s, t, r, gid, gb in zip(var_ids, blobs, shapes, dtypes,
                                        reqs, grad_ids, grad_blobs):
        var = _from_blob(b, s, t)
        grad = _from_blob(gb, s, t)  # grads share the variable's shape/dtype
        req = req_name[int(r)]
        _AUTOGRAD_ARRAYS[int(vid)] = var
        _AUTOGRAD_MARKED[int(vid)] = (var, grad, int(gid), req)
        variables.append(var)
        gradients.append(grad)
        grad_reqs.append(req)
    autograd.mark_variables(variables, gradients, grad_reqs)


def _c_autograd_adopt(out_ids):
    """Bind the C handle ids MXImperativeInvoke just created to the python
    outputs of the recorded invoke (same order). Returns how many were
    adopted (0 when the invoke was not recorded)."""
    global _AUTOGRAD_PENDING
    n = 0
    for hid, arr in zip(out_ids, _AUTOGRAD_PENDING):
        _AUTOGRAD_ARRAYS[int(hid)] = arr
        n += 1
    _AUTOGRAD_PENDING = []
    return n


def _c_autograd_compute_gradient(head_ids):
    """Replay the tape, then return the marked gradients as
    [(grad C handle id, f-contiguous bytes, shape, mx dtype), ...] for the
    C side to write back into the caller's grad handles."""
    from .base import _DTYPE_NP_TO_MX, MXNetError
    from .contrib import autograd

    heads = []
    for hid in head_ids:
        arr = _AUTOGRAD_ARRAYS.get(int(hid))
        if arr is None:
            raise MXNetError(
                "MXAutogradComputeGradient: output handle was not produced "
                "by a recorded MXImperativeInvoke (is training on?)")
        heads.append(arr)
    autograd.compute_gradient(heads)
    ret = []
    for vid, (var, grad, gid, req) in _AUTOGRAD_MARKED.items():
        if req == "null":  # OpReqType null: never write the caller's handle
            continue
        g = grad.asnumpy()
        ret.append((gid, np.ascontiguousarray(g).tobytes(),
                    [int(x) for x in g.shape],
                    int(_DTYPE_NP_TO_MX[np.dtype(g.dtype)])))
    # drop adopted intermediates (their tape is consumed); keep marked vars
    # live so another recorded forward can run against them
    _AUTOGRAD_ARRAYS.clear()
    _AUTOGRAD_ARRAYS.update(
        {vid: e[0] for vid, e in _AUTOGRAD_MARKED.items()})
    return ret


def _c_autograd_forget(hid):
    """MXNDArrayFree purge: a freed handle's id must not resurrect a stale
    array when the allocator recycles the address. Dropping a marked
    variable's var OR grad handle unmarks it."""
    from .contrib import autograd

    hid = int(hid)
    _AUTOGRAD_ARRAYS.pop(hid, None)
    entry = _AUTOGRAD_MARKED.pop(hid, None)
    if entry is not None:
        autograd._MARKED.pop(id(entry[0]), None)
        return
    for vid, (var, _grad, gid, _req) in list(_AUTOGRAD_MARKED.items()):
        if gid == hid:
            del _AUTOGRAD_MARKED[vid]
            autograd._MARKED.pop(id(var), None)
