"""Python side of the TRAINING C API slice (src/c_api_train.cc).

The reference exposes ~120 C functions over its C++ engine
(include/mxnet/c_api.h); the predict subset already ships in
libmxtpu_predict.so. This module backs the training subset — Symbol from
JSON, simple_bind, forward/backward, argument/gradient/output access, and a
fused SGD update — so a pure C/C++ client can run a whole training loop
(compiled client test: tests/test_c_train.py).

Every ``_c_*`` function takes/returns only C-friendly types (str, bytes,
int, float, opaque PyObject handles) — the C shim marshals nothing else.
"""
from __future__ import annotations

import numpy as np

__all__ = []


def _c_symbol_from_json(json_str):
    from .symbol import load_json

    return load_json(json_str)


def _c_symbol_to_json(sym):
    return sym.tojson()


def _c_symbol_arguments(sym):
    return list(sym.list_arguments())


class _CExecutor:
    """Bound training executor + the host-side mirrors the C client reads."""

    def __init__(self, sym, dev_type, dev_id, shapes, grad_req):
        from . import context

        ctx = context.Context(dev_type, dev_id)
        self.executor = sym.simple_bind(ctx, grad_req=grad_req, **shapes)
        # the names the C client binds shapes for ARE its data/label inputs:
        # updates must never touch them
        self.input_names = frozenset(shapes)
        self.outputs = []

    def arg(self, name):
        arr = self.executor.arg_dict.get(name)
        if arr is None:
            raise KeyError("no argument named %r" % (name,))
        return arr


def _c_simple_bind(sym, dev_type, dev_id, shape_keys, shape_data, grad_req):
    """shape_keys: list of names; shape_data: flat list-of-lists of ints."""
    shapes = {k: tuple(int(d) for d in s)
              for k, s in zip(shape_keys, shape_data)}
    return _CExecutor(sym, dev_type, int(dev_id), shapes, grad_req)


def _c_set_arg(cexec, name, data_bytes):
    arr = cexec.arg(name)
    flat = np.frombuffer(data_bytes, dtype=np.float32)
    if flat.size != int(np.prod(arr.shape)):
        raise ValueError(
            "size mismatch for %s: got %d floats, need %d"
            % (name, flat.size, int(np.prod(arr.shape))))
    arr[:] = flat.reshape(arr.shape).astype(arr.dtype)


def _c_get_array(cexec, which, name_or_index):
    """bytes of (arg|grad|output|aux) as float32."""
    if which == "arg":
        arr = cexec.arg(name_or_index)
    elif which == "grad":
        arr = cexec.executor.grad_dict.get(name_or_index)
        if arr is None:
            raise KeyError("no gradient for %r" % (name_or_index,))
    elif which == "aux":
        arr = cexec.executor.aux_dict[name_or_index]
    else:
        arr = cexec.outputs[int(name_or_index)]
    return np.ascontiguousarray(
        arr.asnumpy().astype(np.float32)).tobytes()


def _c_get_shape(cexec, which, name_or_index):
    if which == "output":
        return list(cexec.outputs[int(name_or_index)].shape)
    if which == "grad":
        return list(cexec.executor.grad_dict[name_or_index].shape)
    return list(cexec.arg(name_or_index).shape)


def _c_num_outputs(cexec):
    return len(cexec.executor._symbol.list_outputs())


def _c_forward(cexec, is_train):
    cexec.outputs = cexec.executor.forward(is_train=bool(is_train))


def _c_backward(cexec):
    cexec.executor.backward()


def _c_sgd_update(cexec, lr, wd):
    """w -= lr * (grad + wd * w) over every PARAMETER with a gradient — the
    minimal in-framework update so a C client need not round-trip params.
    The client's bound inputs (data/labels) also carry gradients under
    grad_req='write' but must never be updated. (Full optimizers remain the
    Python/Module surface's job.)"""
    ex = cexec.executor
    for name, grad in ex.grad_dict.items():
        if grad is None or name in cexec.input_names:
            continue
        w = ex.arg_dict[name]
        w[:] = w - lr * (grad + wd * w)


def _c_init_xavier(cexec, seed):
    """Xavier-initialize every weight, zero biases — convenience so the C
    client does not need an RNG."""
    from . import initializer as init_mod
    from . import random as rnd

    rnd.seed(int(seed))
    init = init_mod.Xavier()
    for name, arr in cexec.executor.arg_dict.items():
        if name.endswith(("_weight", "_bias", "_gamma", "_beta")):
            init(name, arr)
