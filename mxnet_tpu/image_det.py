"""Box-aware detection augmenters — the SSD training pipeline
(reference: src/io/image_det_aug_default.cc DefaultImageDetAugmenter:
crop samplers with IoU/coverage constraints :460-477 + TryCrop :287-352,
pad :480-489 + TryPad :356-363, mirror :366-371, force/shrink/fit final
resize :615-660; param table :95-165).

Everything is numpy (host-side, per-image) and plugs into
``ImageDetRecordIter``'s decode workers the same way ``Augmenter.apply_np``
does for classification — except det augmenters transform ``(image,
boxes)`` together.

Boxes are float32 rows ``[id, x0, y0, x1, y1, *extra]`` with corner
coordinates normalized to [0, 1]; rows with ``id < 0`` are padding and are
never produced here (padding happens at batch assembly).
"""
from __future__ import annotations

import random as pyrandom

import numpy as np

from .base import MXNetError
from .image import imresize_np

__all__ = [
    "DetAugmenter", "DetBorrowAug", "DetHorizontalFlipAug",
    "DetRandomPadAug", "DetRandomCropAug", "DetForceResizeAug",
    "DetResizeShorterAug", "CreateDetAugmenter",
]


class DetAugmenter:
    """Base: ``apply_np(image_hwc, boxes, rng=random) -> (image_hwc,
    boxes)``. ``rng`` is a ``random.Random``-like source; the record-iter
    workers pass per-thread instances seeded from the iterator's ``seed``
    so single-threaded decode is fully reproducible (with >1 thread the
    per-thread streams are deterministic but record→thread assignment is
    not — same property as the reference's OMP decode pool)."""

    def apply_np(self, arr, boxes, rng=pyrandom):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a geometry-free classification augmenter (color jitter,
    normalize, cast): the image transforms, the boxes pass through
    (reference: the HSL/contrast block of Process, :517-548)."""

    def __init__(self, aug):
        self.aug = aug

    def apply_np(self, arr, boxes, rng=pyrandom):
        return self.aug.apply_np(arr), boxes


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and boxes together (reference: TryMirror :366-371)."""

    def __init__(self, p=0.5):
        self.p = p

    def apply_np(self, arr, boxes, rng=pyrandom):
        if rng.random() < self.p:
            arr = arr[:, ::-1]
            if boxes.shape[0]:
                boxes = boxes.copy()
                x0 = boxes[:, 1].copy()
                boxes[:, 1] = 1.0 - boxes[:, 3]
                boxes[:, 3] = 1.0 - x0
        return arr, boxes


def _project(boxes, rect):
    """Re-express boxes in the coordinate frame of ``rect`` = (x, y, w, h)
    (normalized), clipping to [0, 1] (reference: ImageDetObject.Project)."""
    x, y, w, h = rect
    out = boxes.copy()
    out[:, 1] = np.maximum(0.0, (boxes[:, 1] - x) / w)
    out[:, 2] = np.maximum(0.0, (boxes[:, 2] - y) / h)
    out[:, 3] = np.minimum(1.0, (boxes[:, 3] - x) / w)
    out[:, 4] = np.minimum(1.0, (boxes[:, 4] - y) / h)
    return out


def _intersect_area(rect, boxes):
    x, y, w, h = rect
    ix = (np.minimum(x + w, boxes[:, 3]) - np.maximum(x, boxes[:, 1]))
    iy = (np.minimum(y + h, boxes[:, 4]) - np.maximum(y, boxes[:, 2]))
    return np.maximum(ix, 0.0) * np.maximum(iy, 0.0)


class DetRandomPadAug(DetAugmenter):
    """Expand the canvas by up to ``max_pad_scale`` with ``fill_value``
    and shift the boxes in (reference: GeneratePadBox :480-489 + the pad
    block of Process :560-576; the reference skips scales < 1.05)."""

    def __init__(self, p, max_pad_scale, fill_value=127, skip_thresh=1.05):
        self.p = p
        self.max_pad_scale = float(max_pad_scale)
        self.fill_value = fill_value
        self.skip_thresh = skip_thresh

    def apply_np(self, arr, boxes, rng=pyrandom):
        if self.max_pad_scale <= 1.0 or rng.random() >= self.p:
            return arr, boxes
        scale = rng.uniform(1.0, self.max_pad_scale)
        if scale < self.skip_thresh:
            return arr, boxes
        x0 = rng.uniform(0.0, scale - 1.0)
        y0 = rng.uniform(0.0, scale - 1.0)
        h, w = arr.shape[:2]
        top = int(y0 * h)
        left = int(x0 * w)
        nh, nw = int(scale * h), int(scale * w)
        canvas = np.full((nh, nw, arr.shape[2]), self.fill_value,
                         dtype=arr.dtype)
        canvas[top : top + h, left : left + w] = arr
        if boxes.shape[0]:
            boxes = _project(boxes, (-x0, -y0, scale, scale))
        return canvas, boxes


class DetRandomCropAug(DetAugmenter):
    """SSD-style constrained random crop: per-image, shuffle the samplers,
    draw crop boxes until one satisfies the sampler's IoU / sample-coverage
    / object-coverage constraints against at least one ground-truth box,
    then keep the objects the emit mode retains (``center``: centroid
    inside the crop; ``overlap``: gt coverage > ``emit_overlap_thresh``)
    and re-project them (reference: GenerateCropBox :460-477, TryCrop
    :287-352, sampler loop :579-612).

    Deviation from the reference, documented: the reference's TryCrop only
    enforces the constraints when *every* min is > 0 AND every max is < 1
    simultaneously (:303-306) — with the stock SSD sampler settings
    (max_* left at 1.0) that makes every crop box valid and only the emit
    mode filters. Here each constraint is enforced independently whenever
    it is restrictive (min > 0 or max < 1), which is the SSD paper's
    sampler and what the reference's parameter docs describe.
    """

    def __init__(self, p, min_scales, max_scales, min_aspect_ratios,
                 max_aspect_ratios, min_overlaps, max_overlaps,
                 min_sample_coverages, max_sample_coverages,
                 min_object_coverages, max_object_coverages,
                 max_trials, emit_mode="center", emit_overlap_thresh=0.3):
        n = len(min_scales)
        for name, t in [("max_crop_scales", max_scales),
                        ("min_crop_aspect_ratios", min_aspect_ratios),
                        ("max_crop_aspect_ratios", max_aspect_ratios),
                        ("min_crop_overlaps", min_overlaps),
                        ("max_crop_overlaps", max_overlaps),
                        ("min_crop_sample_coverages", min_sample_coverages),
                        ("max_crop_sample_coverages", max_sample_coverages),
                        ("min_crop_object_coverages", min_object_coverages),
                        ("max_crop_object_coverages", max_object_coverages),
                        ("max_crop_trials", max_trials)]:
            if len(t) != n:
                raise MXNetError(
                    "DetRandomCropAug: %s has %d entries, expected %d "
                    "(one per sampler)" % (name, len(t), n))
        if emit_mode not in ("center", "overlap"):
            raise MXNetError("crop_emit_mode must be 'center' or 'overlap'")
        self.p = p
        self.samplers = list(zip(min_scales, max_scales, min_aspect_ratios,
                                 max_aspect_ratios, min_overlaps,
                                 max_overlaps, min_sample_coverages,
                                 max_sample_coverages, min_object_coverages,
                                 max_object_coverages, max_trials))
        self.emit_mode = emit_mode
        self.emit_overlap_thresh = emit_overlap_thresh

    def _gen_crop_box(self, smin, smax, armin, armax, img_ar, rng):
        # reference GenerateCropBox: scale then aspect ratio bounded by
        # [scale^2, 1/scale^2] and the image's own aspect ratio
        scale = rng.uniform(smin, smax) + 1e-12
        min_ratio = max(armin / img_ar, scale * scale)
        max_ratio = min(armax / img_ar, 1.0 / (scale * scale))
        if min_ratio > max_ratio:
            return None
        ratio = np.sqrt(rng.uniform(min_ratio, max_ratio))
        w = min(1.0, scale * ratio)
        h = min(1.0, scale / ratio)
        return (rng.uniform(0.0, 1.0 - w),
                rng.uniform(0.0, 1.0 - h), w, h)

    def _try_crop(self, rect, boxes, sampler):
        (_, _, _, _, omin, omax, scmin, scmax, ocmin, ocmax, _) = sampler
        if boxes.shape[0] == 0:
            return boxes  # no objects: any crop is fine (reference :296)
        x, y, w, h = rect
        inter = _intersect_area(rect, boxes)
        gt_area = ((boxes[:, 3] - boxes[:, 1]) * (boxes[:, 4] - boxes[:, 2]))
        ok = np.ones(boxes.shape[0], bool)
        # ratios are semantically <= 1; clip so float64 rect x float32 box
        # arithmetic (e.g. 1.0000001 coverage) can't fail a max-bound of 1.0
        if omin > 0.0 or omax < 1.0:
            iou = np.minimum(inter / (w * h + gt_area - inter + 1e-12), 1.0)
            ok &= (iou >= omin) & (iou <= omax)
        if scmin > 0.0 or scmax < 1.0:
            cov = np.minimum(inter / (w * h), 1.0)
            ok &= (cov >= scmin) & (cov <= scmax)
        if ocmin > 0.0 or ocmax < 1.0:
            cov = np.minimum(inter / (gt_area + 1e-12), 1.0)
            ok &= (cov >= ocmin) & (cov <= ocmax)
        if not ok.any():
            return None
        # emit: which objects survive the crop
        if self.emit_mode == "center":
            cx = (boxes[:, 1] + boxes[:, 3]) * 0.5
            cy = (boxes[:, 2] + boxes[:, 4]) * 0.5
            keep = (cx >= x) & (cx <= x + w) & (cy >= y) & (cy <= y + h)
        else:
            keep = (inter / (gt_area + 1e-12)) > self.emit_overlap_thresh
        if not keep.any():
            return None
        return _project(boxes[keep], rect)

    def apply_np(self, arr, boxes, rng=pyrandom):
        if rng.random() >= self.p:
            return arr, boxes
        h, w = arr.shape[:2]
        order = list(range(len(self.samplers)))
        rng.shuffle(order)
        for idx in order:
            sampler = self.samplers[idx]
            for _ in range(int(sampler[-1])):
                rect = self._gen_crop_box(sampler[0], sampler[1], sampler[2],
                                          sampler[3], w / float(h), rng)
                if rect is None:
                    continue
                new_boxes = self._try_crop(rect, boxes, sampler)
                if new_boxes is None:
                    continue
                x, y, cw, ch = rect
                left, top = int(x * w), int(y * h)
                # >=1 px: a near-zero scale draw must not produce an empty
                # crop (the force-resize would raise and the worker would
                # drop the record as corrupt)
                cw_px = max(1, int(cw * w))
                ch_px = max(1, int(ch * h))
                return (arr[top : top + ch_px, left : left + cw_px],
                        new_boxes)
        return arr, boxes  # every sampler failed: keep the original


class DetForceResizeAug(DetAugmenter):
    """Final resize to exactly (w, h) — boxes are normalized, unaffected
    (reference: resize_mode 'force' :615-623)."""

    def __init__(self, size, interp=1):
        self.size, self.interp = size, interp

    def apply_np(self, arr, boxes, rng=pyrandom):
        if arr.shape[1] != self.size[0] or arr.shape[0] != self.size[1]:
            arr = imresize_np(arr, self.size[0], self.size[1], self.interp)
        return arr, boxes


class DetResizeShorterAug(DetAugmenter):
    """Scale the shorter edge to ``size`` before other augmenters
    (reference: the resize prologue of Process :495-509)."""

    def __init__(self, size, interp=1):
        self.size, self.interp = size, interp

    def apply_np(self, arr, boxes, rng=pyrandom):
        h, w = arr.shape[:2]
        if h > w:
            nw, nh = self.size, self.size * h // w
        else:
            nw, nh = self.size * w // h, self.size
        return imresize_np(arr, nw, nh, self.interp), boxes


def CreateDetAugmenter(data_shape, resize=0, rand_crop_prob=0.0,
                       min_crop_scales=(0.0,), max_crop_scales=(1.0,),
                       min_crop_aspect_ratios=(1.0,),
                       max_crop_aspect_ratios=(1.0,),
                       min_crop_overlaps=(0.0,), max_crop_overlaps=(1.0,),
                       min_crop_sample_coverages=(0.0,),
                       max_crop_sample_coverages=(1.0,),
                       min_crop_object_coverages=(0.0,),
                       max_crop_object_coverages=(1.0,),
                       num_crop_sampler=1, crop_emit_mode="center",
                       emit_overlap_thresh=0.3, max_crop_trials=(25,),
                       rand_pad_prob=0.0, max_pad_scale=1.0,
                       rand_mirror_prob=0.0, fill_value=127, inter_method=1,
                       brightness=0.0, contrast=0.0, saturation=0.0,
                       mean=None, std=None):
    """Build the detection augmenter list (reference: param table
    image_det_aug_default.cc:95-165 — same names and defaults; processing
    order matches Process: resize → color → mirror → pad → crop → final
    force-resize → normalize)."""
    from . import image as _img

    def _tup(v, name):
        t = [float(x) for x in (v if isinstance(v, (tuple, list)) else [v])]
        if len(t) == 1 and num_crop_sampler > 1:
            t = t * num_crop_sampler  # reference ValidateCropParameters
        if len(t) != num_crop_sampler:
            raise MXNetError("%s: %d entries for %d crop samplers"
                             % (name, len(t), num_crop_sampler))
        return t

    auglist = []
    if resize and resize > 0:
        auglist.append(DetResizeShorterAug(resize, inter_method))
    if brightness:
        auglist.append(DetBorrowAug(_img.BrightnessJitterAug(brightness)))
    if contrast:
        auglist.append(DetBorrowAug(_img.ContrastJitterAug(contrast)))
    if saturation:
        auglist.append(DetBorrowAug(_img.SaturationJitterAug(saturation)))
    if rand_mirror_prob > 0:
        auglist.append(DetHorizontalFlipAug(rand_mirror_prob))
    if rand_pad_prob > 0 and max_pad_scale > 1.0:
        auglist.append(DetRandomPadAug(rand_pad_prob, max_pad_scale,
                                       fill_value))
    if rand_crop_prob > 0 and num_crop_sampler > 0:
        auglist.append(DetRandomCropAug(
            rand_crop_prob,
            _tup(min_crop_scales, "min_crop_scales"),
            _tup(max_crop_scales, "max_crop_scales"),
            _tup(min_crop_aspect_ratios, "min_crop_aspect_ratios"),
            _tup(max_crop_aspect_ratios, "max_crop_aspect_ratios"),
            _tup(min_crop_overlaps, "min_crop_overlaps"),
            _tup(max_crop_overlaps, "max_crop_overlaps"),
            _tup(min_crop_sample_coverages, "min_crop_sample_coverages"),
            _tup(max_crop_sample_coverages, "max_crop_sample_coverages"),
            _tup(min_crop_object_coverages, "min_crop_object_coverages"),
            _tup(max_crop_object_coverages, "max_crop_object_coverages"),
            [int(x) for x in _tup(max_crop_trials, "max_crop_trials")],
            crop_emit_mode, emit_overlap_thresh))
    auglist.append(DetForceResizeAug((data_shape[2], data_shape[1]),
                                     inter_method))
    auglist.append(DetBorrowAug(_img.CastAug()))
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(_img.ColorNormalizeAug(mean, std)))
    return auglist
