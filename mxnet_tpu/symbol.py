"""Symbol — the declarative graph API.

Reference: python/mxnet/symbol.py (Symbol :41, compose, infer_shape :815,
infer_type :718, simple_bind :1157, bind :1256, tojson :1064) over the nnvm
Symbol/Graph C++ core. Here the graph is a lightweight Python DAG; its only
consumer is the Executor, which traces it straight into one jax function and
jit-compiles the whole thing — the TPU analog of GraphExecutor::Init running
nnvm passes then caching engine ops (src/executor/graph_executor.cc:336-449).
Shape/type inference runs the registry's per-op inference in topological order
(the InferShape/InferType passes, graph_executor.cc:428-429).

JSON layout matches the nnvm serialization the reference emits (nodes /
arg_nodes / heads with string attrs) so graphs round-trip between frameworks.
"""
from __future__ import annotations

import ast
import builtins
import json
import sys

import numpy as np

from .attribute import AttrScope
from .base import MXNetError, attr_str
from .context import current_context
from .name import NameManager
from .ops.registry import get_op, list_ops

__all__ = ["Symbol", "Variable", "var", "Group", "load", "load_json", "zeros", "ones", "arange"]


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "_extra_attrs")

    def __init__(self, op, name, attrs, inputs, extra_attrs=None):
        self.op = op  # op name string, or None for a variable
        self.name = name
        self.attrs = attrs or {}  # canonicalized op params
        self.inputs = inputs or []  # list of (_Node, int output index)
        self._extra_attrs = extra_attrs or {}  # user attrs (ctx_group, lr_mult, ...)

    @property
    def is_variable(self):
        return self.op is None

    def list_attr(self):
        d = {k: attr_str(v) for k, v in self.attrs.items()}
        d.update({k: attr_str(v) for k, v in self._extra_attrs.items()})
        return d


def _topo_order(root_entries):
    """Post-order DFS over the DAG; returns list of unique nodes."""
    seen = {}
    order = []
    stack = [(n, False) for n, _ in reversed(root_entries)]
    while stack:
        node, done = stack.pop()
        if done:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen[id(node)] = node
        stack.append((node, True))
        for inp, _ in reversed(node.inputs):
            if id(inp) not in seen:
                stack.append((inp, False))
    return order


class Symbol:
    """Symbol is a multi-output handle onto graph nodes: a list of
    (node, output_index) entries (nnvm's NodeEntry)."""

    __slots__ = ("_entries",)

    def __init__(self, entries):
        self._entries = list(entries)

    # ---- composition ----------------------------------------------------
    def __call__(self, *args, **kwargs):
        """Compose: replace this symbol's free variables with other symbols
        (reference: symbol.py Symbol.__call__/_compose)."""
        s = self.__copy__()
        s._compose(*args, **kwargs)
        return s

    def __copy__(self):
        # deep-copy the reachable subgraph so composition doesn't mutate shared nodes
        mapping = {}
        order = _topo_order(self._entries)
        for node in order:
            mapping[id(node)] = _Node(
                node.op,
                node.name,
                dict(node.attrs),
                [(mapping[id(i)], k) for i, k in node.inputs],
                dict(node._extra_attrs),
            )
        return Symbol([(mapping[id(n)], k) for n, k in self._entries])

    def _compose(self, *args, **kwargs):
        kwargs = {k: v for k, v in kwargs.items()}
        if args and kwargs:
            raise MXNetError("compose only accept input Symbols either as positional or keyword arguments")
        arg_names = self.list_arguments()
        if args:
            kwargs = dict(zip(arg_names, args))
        order = _topo_order(self._entries)
        var_map = {}
        for node in order:
            if node.is_variable and node.name in kwargs:
                var_map[id(node)] = kwargs[node.name]._entries[0]
        for node in order:
            node.inputs = [
                (var_map[id(i)][0], var_map[id(i)][1]) if id(i) in var_map else (i, k)
                for i, k in node.inputs
            ]

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("Cannot find output %s" % index)
            index = names.index(index)
        return Symbol([self._entries[index]])

    def __len__(self):
        return len(self._entries)

    def __iter__(self):
        return (self[i] for i in range(len(self)))

    # ---- arithmetic builds graph nodes ----------------------------------
    def _binary(self, other, op, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create(op, [a, b], {})
        if isinstance(other, (int, float, np.generic)):
            return _create(scalar_op, [self], {"scalar": float(other)})
        raise TypeError("type %s not supported" % str(type(other)))

    def __add__(self, o):
        return self._binary(o, "elemwise_add" if isinstance(o, Symbol) else None, "_plus_scalar") \
            if not isinstance(o, Symbol) else _create("elemwise_add", [self, o], {})

    __radd__ = __add__

    def __sub__(self, o):
        if isinstance(o, Symbol):
            return _create("elemwise_sub", [self, o], {})
        return _create("_minus_scalar", [self], {"scalar": float(o)})

    def __rsub__(self, o):
        return _create("_rminus_scalar", [self], {"scalar": float(o)})

    def __mul__(self, o):
        if isinstance(o, Symbol):
            return _create("elemwise_mul", [self, o], {})
        return _create("_mul_scalar", [self], {"scalar": float(o)})

    __rmul__ = __mul__

    def __div__(self, o):
        if isinstance(o, Symbol):
            return _create("elemwise_div", [self, o], {})
        return _create("_div_scalar", [self], {"scalar": float(o)})

    __truediv__ = __div__

    def __rdiv__(self, o):
        return _create("_rdiv_scalar", [self], {"scalar": float(o)})

    __rtruediv__ = __rdiv__

    def __pow__(self, o):
        if isinstance(o, Symbol):
            return _create("_power", [self, o], {})
        return _create("_power_scalar", [self], {"scalar": float(o)})

    def __neg__(self):
        return _create("negative", [self], {})

    # ---- introspection --------------------------------------------------
    @property
    def name(self):
        if len(self._entries) == 1:
            return self._entries[0][0].name
        return None

    def attr(self, key):
        node = self._entries[0][0]
        v = node._extra_attrs.get(key)
        if v is None and key in node.attrs:
            v = attr_str(node.attrs[key])
        return v

    def list_attr(self, recursive=False):
        if recursive:
            ret = {}
            for node in _topo_order(self._entries):
                for k, v in node.list_attr().items():
                    ret["%s_%s" % (node.name, k)] = v
            return ret
        return self._entries[0][0].list_attr()

    def attr_dict(self):
        ret = {}
        for node in _topo_order(self._entries):
            d = node.list_attr()
            if d:
                ret[node.name] = d
        return ret

    def _set_attr(self, **kwargs):
        self._entries[0][0]._extra_attrs.update(kwargs)

    def _arg_aux_split(self):
        """Walk the graph; classify variable nodes into args vs aux states.

        A variable is auxiliary if it feeds only aux-slots of ops (the
        reference tracks this via each op's ListAuxiliaryStates, operator.h).
        """
        aux_vars = set()
        arg_vars = set()
        for node in _topo_order(self._entries):
            if node.is_variable:
                continue
            op = get_op(node.op)
            n_args = len(op.arg_names(node.attrs))
            for i, (inp, _) in enumerate(node.inputs):
                if inp.is_variable:
                    if i >= n_args:
                        aux_vars.add(id(inp))
                    else:
                        arg_vars.add(id(inp))
        return arg_vars, aux_vars

    def list_arguments(self):
        arg_vars, aux_vars = self._arg_aux_split()
        out = []
        for node in _topo_order(self._entries):
            if node.is_variable and id(node) not in aux_vars:
                out.append(node.name)
        return out

    def list_auxiliary_states(self):
        arg_vars, aux_vars = self._arg_aux_split()
        out = []
        for node in _topo_order(self._entries):
            if node.is_variable and id(node) in aux_vars:
                out.append(node.name)
        return out

    def list_outputs(self):
        names = []
        for node, idx in self._entries:
            if node.is_variable:
                names.append(node.name)
            else:
                op = get_op(node.op)
                onames = op.output_names(node.attrs)
                if op.num_outputs(node.attrs) == 1:
                    names.append(node.name + "_" + onames[0])
                else:
                    names.append(node.name + "_" + onames[idx])
        return names

    def list_inputs(self):
        return self.list_arguments() + self.list_auxiliary_states()

    def get_internals(self):
        """All internal outputs, one entry per node output
        (reference: symbol.py get_internals)."""
        entries = []
        for node in _topo_order(self._entries):
            if node.is_variable:
                entries.append((node, 0))
            else:
                op = get_op(node.op)
                for i in range(op.num_visible_outputs(node.attrs)):
                    entries.append((node, i))
        return Symbol(entries)

    def get_children(self):
        node = self._entries[0][0]
        if not node.inputs:
            return None
        return Symbol([(n, i) for n, i in node.inputs])

    # ---- inference ------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        try:
            return self._infer_shape_impl(False, *args, **kwargs)
        except MXNetError:
            raise

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)

    def _infer_shape_impl(self, partial, *args, **kwargs):
        arg_names = self.list_arguments()
        provided = {}
        if args:
            for name, shape in zip(arg_names, args):
                if shape is not None:
                    provided[name] = tuple(shape)
        provided.update({k: tuple(v) for k, v in kwargs.items() if v is not None})
        shapes, out_shapes, aux_shapes = _infer(self, provided, "shape", partial)
        return shapes, out_shapes, aux_shapes

    def infer_type(self, *args, **kwargs):
        arg_names = self.list_arguments()
        provided = {}
        if args:
            for name, dt in zip(arg_names, args):
                if dt is not None:
                    provided[name] = np.dtype(dt)
        provided.update({k: np.dtype(v) for k, v in kwargs.items() if v is not None})
        return _infer(self, provided, "type", False)

    # ---- serialization --------------------------------------------------
    def tojson(self):
        order = _topo_order(self._entries)
        node_ids = {id(n): i for i, n in enumerate(order)}
        nodes = []
        arg_nodes = []
        for i, node in enumerate(order):
            if node.is_variable:
                arg_nodes.append(i)
                nodes.append({"op": "null", "name": node.name, "inputs": []})
                attrs = node.list_attr()
                if attrs:
                    nodes[-1]["attrs"] = attrs
            else:
                entry = {
                    "op": node.op,
                    "name": node.name,
                    "inputs": [[node_ids[id(n)], k, 0] for n, k in node.inputs],
                }
                attrs = node.list_attr()
                if attrs:
                    entry["attrs"] = attrs
                nodes.append(entry)
        heads = [[node_ids[id(n)], k, 0] for n, k in self._entries]
        return json.dumps(
            {
                "nodes": nodes,
                "arg_nodes": arg_nodes,
                "node_row_ptr": list(range(len(order) + 1)),
                "heads": heads,
                "attrs": {"mxnet_version": ["int", 1000]},
            },
            indent=2,
        )

    def save(self, fname):
        # crash-safe: a died-mid-write process must not leave a torn json at
        # the final name (checkpoint auto-resume parses this file)
        from .utils.atomic_file import atomic_write

        with atomic_write(fname, checksum=False) as f:
            f.write(self.tojson())

    # ---- binding --------------------------------------------------------
    def simple_bind(self, ctx=None, grad_req="write", type_dict=None, group2ctx=None,
                    shared_arg_names=None, shared_exec=None, shared_buffer=None,
                    compute_dtype=None, cast_exempt=(), **kwargs):
        """Shape-inferred allocation + bind (reference: symbol.py:1157).

        kwargs are input shapes. Allocates arg/grad/aux NDArrays and returns a
        bound Executor.
        """
        from . import ndarray as nd
        from .executor import Executor

        ctx = ctx or current_context()
        arg_shapes, out_shapes, aux_shapes = self.infer_shape(**kwargs)
        if arg_shapes is None:
            raise MXNetError("cannot infer shapes from %s" % kwargs)
        type_dict = type_dict or {}
        arg_names = self.list_arguments()
        arg_types, _, aux_types = self.infer_type(**{k: v for k, v in type_dict.items() if k in arg_names})
        args = [nd.zeros(s, ctx=ctx, dtype=t) for s, t in zip(arg_shapes, arg_types)]
        aux_states = [nd.zeros(s, ctx=ctx, dtype=t) for s, t in zip(aux_shapes, aux_types)]
        if grad_req == "null":
            args_grad = None
        else:
            args_grad = [nd.zeros(s, ctx=ctx, dtype=t) for s, t in zip(arg_shapes, arg_types)]
        return self.bind(ctx, args, args_grad=args_grad, grad_req=grad_req,
                         aux_states=aux_states, group2ctx=group2ctx, shared_exec=shared_exec,
                         compute_dtype=compute_dtype, cast_exempt=cast_exempt)

    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None, compute_dtype=None, cast_exempt=()):
        """Bind symbol to arrays, return Executor (reference: symbol.py:1256 →
        Executor::Bind, src/executor/graph_executor.cc:915)."""
        from .executor import Executor

        return Executor(self, ctx, args, args_grad, grad_req, aux_states,
                        group2ctx=group2ctx, shared_exec=shared_exec,
                        compute_dtype=compute_dtype, cast_exempt=cast_exempt)

    # ---- eval convenience ----------------------------------------------
    def eval(self, ctx=None, **kwargs):
        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        ex.forward()
        return ex.outputs

    def grad(self, wrt):
        raise MXNetError("Symbol.grad is deprecated; use bind with args_grad")

    def debug_str(self):
        lines = []
        for node in _topo_order(self._entries):
            if node.is_variable:
                lines.append("Variable:%s" % node.name)
            else:
                lines.append(
                    "Op:%s, Name=%s\nInputs:\n\t%s"
                    % (node.op, node.name, "\n\t".join(n.name for n, _ in node.inputs))
                )
        return "\n".join(lines)

    def __repr__(self):
        name = self.name
        return "<Symbol %s>" % (name if name else "Grouped")


def _infer(sym, provided, kind, partial):
    """Run shape or type inference over the graph in topo order."""
    order = _topo_order(sym._entries)
    known = {}  # id(node) -> list of per-output values
    for node in order:
        if node.is_variable:
            val = provided.get(node.name)
            if val is None:
                # fall back to attrs declared on the Variable itself
                # (reference: symbol.py Variable(shape=...) → __shape__ attr)
                if kind == "shape" and node._extra_attrs.get("__shape__"):
                    val = tuple(ast.literal_eval(node._extra_attrs["__shape__"]))
                elif kind != "shape" and node._extra_attrs.get("__dtype__"):
                    val = np.dtype(node._extra_attrs["__dtype__"])
            known[id(node)] = [val]
    changed = True
    for node in order:
        if node.is_variable:
            continue
        op = get_op(node.op)
        in_vals = []
        for inp, k in node.inputs:
            vals = known.get(id(inp))
            in_vals.append(None if vals is None else vals[k])
        n_args = len(op.arg_names(node.attrs))
        arg_vals, aux_vals = in_vals[:n_args], in_vals[n_args:]
        try:
            if kind == "shape":
                new_args, outs, new_aux = op.infer_shape(node.attrs, arg_vals, aux_vals)
            else:
                new_args, outs, new_aux = op.infer_type(node.attrs, arg_vals)
                new_aux = aux_vals
                if not new_aux:
                    new_aux = []
                # aux types default to arg dtype
                aux_names = op.aux_names(node.attrs)
                if aux_names and not new_aux:
                    new_aux = [new_args[0]] * len(aux_names)
                elif aux_names:
                    new_aux = [v if v is not None else new_args[0] for v in aux_vals]
        except Exception as e:  # noqa: BLE001
            if partial:
                known[id(node)] = [None] * op.num_outputs(node.attrs)
                continue
            raise MXNetError(
                "%s inference failed at node %s(%s): %s" % (kind, node.op, node.name, e)
            ) from e
        # write back filled input values onto variables
        filled = list(new_args) + list(new_aux)
        for (inp, k), v in zip(node.inputs, filled):
            if inp.is_variable and v is not None:
                prev = known[id(inp)][0]
                if kind == "shape" and prev is not None and tuple(prev) != tuple(v):
                    raise MXNetError(
                        "shape mismatch for %s: %s vs %s" % (inp.name, prev, v)
                    )
                known[id(inp)] = [v]
        known[id(node)] = list(outs)
    # collect
    arg_vars, aux_vars = sym._arg_aux_split()
    args, auxs = [], []
    for node in order:
        if node.is_variable:
            v = known[id(node)][0]
            if id(node) in aux_vars:
                auxs.append(v)
            else:
                args.append(v)
    outs = []
    for node, k in sym._entries:
        vals = known.get(id(node))
        outs.append(None if vals is None else vals[k])
    if not partial and any(v is None for v in args + outs + auxs):
        if kind == "shape":
            return None, None, None
    return args, outs, auxs


# ---- symbol creation ----------------------------------------------------
def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None, dtype=None, init=None, **kwargs):
    """Create a variable symbol (reference: symbol.py Variable)."""
    if not isinstance(name, str):
        raise TypeError("Expect a string for variable name")
    extra = AttrScope.current().get(attr or {})
    if shape is not None:
        extra["__shape__"] = str(tuple(shape))
    if lr_mult is not None:
        extra["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        extra["__wd_mult__"] = str(wd_mult)
    if dtype is not None:
        extra["__dtype__"] = str(np.dtype(dtype))
    if init is not None:
        # store the initializer spec so Module.init_params dispatches to it
        # (reference: symbol.py Variable stores init.dumps() as __init__)
        extra["__init__"] = init.dumps() if hasattr(init, "dumps") else str(init)
    extra.update({k: str(v) for k, v in kwargs.items()})
    node = _Node(None, name, {}, [], extra)
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    """Group symbols into one multi-output symbol (reference: symbol.py Group)."""
    entries = []
    for s in symbols:
        entries.extend(s._entries)
    return Symbol(entries)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


def load_json(json_str):
    """Rebuild a Symbol from nnvm-format JSON."""
    data = json.loads(json_str)
    nodes_meta = data["nodes"]
    built = []
    for meta in nodes_meta:
        attrs = meta.get("attrs", meta.get("param", {})) or {}
        # pre-NNVM files carry user attrs (ctx_group, lr_mult, ...) in a
        # separate "attr" dict (reference: legacy_json_util.cc upgrade)
        user_attrs = dict(meta.get("attr", {}) or {})
        if meta["op"] == "null":
            merged = dict(attrs)
            merged.update(user_attrs)
            node = _Node(None, meta["name"], {}, [], merged)
        else:
            op = get_op(meta["op"])
            cattrs, extra = op.canonicalize_attrs(attrs)
            extra.update(user_attrs)
            inputs = [(built[i], k) for i, k, *_ in meta["inputs"]]
            node = _Node(meta["op"], meta["name"], cattrs, inputs, extra)
        built.append(node)
    heads = data.get("heads", [[len(built) - 1, 0, 0]])
    return Symbol([(built[i], k) for i, k, *_ in heads])


# ---- generated op constructors (reference: _init_symbol_module,
# python/mxnet/symbol.py:1655) ---------------------------------------------
def _create(op_name, sym_args, attrs, name=None, extra_attrs=None):
    op = get_op(op_name)
    cattrs, extra = op.canonicalize_attrs(attrs)
    extra.update(extra_attrs or {})
    extra = AttrScope.current().get(extra)
    hint = op_name.lower().lstrip("_")
    name = NameManager.current().get(name, hint)
    arg_names = op.arg_names(cattrs)
    aux_names = op.aux_names(cattrs)
    inputs = []
    for i, aname in enumerate(list(arg_names) + list(aux_names)):
        if i < len(sym_args) and sym_args[i] is not None:
            s = sym_args[i]
            if not isinstance(s, Symbol):
                raise TypeError("op %s input %d must be Symbol, got %s" % (op_name, i, type(s)))
            inputs.append(s._entries[0])
        else:
            vnode = _Node(None, "%s_%s" % (name, aname), {}, [])
            inputs.append((vnode, 0))
    node = _Node(op_name, name, cattrs, inputs, extra)
    return Symbol([(node, i) for i in range(op.num_visible_outputs(cattrs))][: builtins.max(1, op.num_visible_outputs(cattrs))]) \
        if op.num_visible_outputs(cattrs) > 1 else Symbol([(node, 0)])


def _make_symbol_function(op_name):
    op = get_op(op_name)

    def fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        sym_args = list(args)
        attrs = {}
        arg_names_static = None
        # split kwargs into symbol inputs vs attrs
        sym_kwargs = {}
        for k, v in list(kwargs.items()):
            if isinstance(v, Symbol):
                sym_kwargs[k] = v
            else:
                attrs[k] = v
        if op.key_var_num_args and op.key_var_num_args not in attrs:
            attrs[op.key_var_num_args] = builtins.max(len(sym_args) + len(sym_kwargs), 1)
        cattrs, _ = op.canonicalize_attrs(attrs)
        names = list(op.arg_names(cattrs)) + list(op.aux_names(cattrs))
        ordered = list(sym_args) + [None] * (len(names) - len(sym_args))
        for k, v in sym_kwargs.items():
            if k in names:
                ordered[names.index(k)] = v
            else:
                raise MXNetError("op %s: unknown input '%s' (expects %s)" % (op_name, k, names))
        return _create(op_name, ordered, attrs, name=name, extra_attrs=attr)

    fn.__name__ = op_name
    fn.__doc__ = "Symbolic form of operator ``%s``." % op_name
    return fn


_cur_module = sys.modules[__name__]
for _name in list_ops():
    setattr(_cur_module, _name, _make_symbol_function(_name))
# rich generated docstrings (reference: symbol_doc.py attachment)
from . import op_doc as _op_doc  # noqa: E402

_op_doc.attach_docs(_cur_module, list_ops(), "symbolic")


def __getattr__(name):
    # ops registered after import resolve lazily (see ndarray.__getattr__)
    from .ops.registry import has_op

    if not name.startswith("__") and has_op(name):
        fn = _make_symbol_function(name)
        setattr(_cur_module, name, fn)
        _op_doc.attach_docs(_cur_module, [name], "symbolic")
        return fn
    raise AttributeError("module %r has no attribute %r" % (__name__, name))


def _module_binary(lhs, rhs, op, scalar_op, rscalar_op=None):
    """(reference: symbol.py's pow/maximum/minimum/hypot module functions —
    Symbol|scalar on either side)"""
    if isinstance(lhs, Symbol):
        if isinstance(rhs, Symbol):
            return _create(op, [lhs, rhs], {})
        return _create(scalar_op, [lhs], {"scalar": float(rhs)})
    if isinstance(rhs, Symbol):
        return _create(rscalar_op or scalar_op, [rhs], {"scalar": float(lhs)})
    raise TypeError("at least one operand must be a Symbol")


def pow(lhs, rhs):
    return _module_binary(lhs, rhs, "_power", "_power_scalar", "_rpower_scalar")


def maximum(lhs, rhs):
    return _module_binary(lhs, rhs, "_maximum", "_maximum_scalar")


def minimum(lhs, rhs):
    return _module_binary(lhs, rhs, "_minimum", "_minimum_scalar")


def hypot(lhs, rhs):
    return _module_binary(lhs, rhs, "_hypot", "_hypot_scalar")


def zeros(shape, dtype=None, **kwargs):
    return getattr(_cur_module, "_zeros")(shape=shape, dtype=dtype, **kwargs)


def ones(shape, dtype=None, **kwargs):
    return getattr(_cur_module, "_ones")(shape=shape, dtype=dtype, **kwargs)


def arange(start, stop=None, step=1.0, repeat=1, name=None, dtype=None):
    return getattr(_cur_module, "_arange")(
        start=start, stop=stop, step=step, repeat=repeat, name=name, dtype=dtype
    )
