"""Compile & device-memory observability: the program registry.

The runtime compiles ~a dozen logical XLA programs per training job — the
fused SPMD step, the executor's forward / fwd+bwd pair, the on-device wire
decode (``_image_wire_normalize``), the guard sentinel, the fused optimizer
update, deferred metric counts, export artifacts — and before this module
each called ``jax.jit`` independently with no shared accounting. Nobody
could say how many programs a fit compiled, which call site retraced on a
shape change, or where compile wall time went; ROADMAP #3's compile cache
has nothing to be judged against.

Every jit site now routes through :func:`jit` (the ``untracked-jit`` fwlint
rule keeps it that way) and the registry records, per logical program:

* a stable **graph digest** (``symbol_digest`` for graph programs, the
  op+attrs key for imperative kernels);
* the **input signature** (per-leaf shape/dtype) of every compilation, so a
  recompile is *attributed*: the ``compile.recompile`` event names the axis
  that changed (batch, seq_len, axis-k), the dtype flip, or the structural
  change, and the call site that paid for it;
* **compile wall seconds** (always-on ``compile.count`` /
  ``compile.seconds{program}`` metrics + a ``compile`` lane span on the
  chrome-trace timeline) vs **cumulative run seconds** — the
  compile-vs-steady-state split ``tools/compile_report.py`` renders offline;
* the program's **input footprint** (``arg_bytes``) and, where the backend
  exposes live stats, the device **peak watermark** observed right after the
  compile landed.

Detection is zero-copy on the hot path: a call is classified as a compile
when the underlying jit cache GREW during it (``_cache_size`` delta — jax's
own executable cache is the source of truth, so our view can never drift
from what XLA actually compiled); signatures are only computed on compile
events, never per step.

Device-memory accounting rides along: per-device live/peak byte gauges
(``jax Device.memory_stats`` where the backend exposes it, the NDArray
allocation registry as the fallback on backends that don't), and an OOM
forensics hook — :func:`oom_guard` wraps the executor boundary, catches
``RESOURCE_EXHAUSTED``, and dumps the top live allocations plus the program
table before re-raising, so the post-mortem names WHAT held the memory and
WHICH programs were resident. ``fault.py`` point ``oom`` injects the
failure for tests (``MXNET_FAULT_SPEC="oom:"``).

Always on: the accounting is a handful of counters and one cache-size read
per dispatch — the cost class of the fit loop's existing per-batch checks —
and a compile event is so expensive (seconds) that its bookkeeping is free.
"""
from __future__ import annotations

import hashlib
import logging
import threading
import time

from . import telemetry
from .base import MXNetError, env_int as _env_int

__all__ = [
    "jit", "raw_jit", "record_compile", "oom_guard", "symbol_digest",
    "program_table", "summary", "last_recompile", "reset",
    "device_memory_stats", "live_ndarray_report", "update_memory_gauges",
]

_log = logging.getLogger(__name__)

_lock = threading.RLock()
# race-ok: the one unlocked read is _rec()'s identity probe — a stale
# record is detected and re-resolved under _lock on the next line
_programs = {}  # program name -> _ProgramRecord
_recompiles = []  # chronological recompile attributions (bounded)
_MAX_RECOMPILE_LOG = 256

# chrome-trace lane for compile spans: a fixed synthetic tid so every
# compile lands on ONE dedicated row of the timeline instead of scattering
# across the worker threads that happened to trigger them
COMPILE_TRACE_TID = 59999

_lane_lock = threading.Lock()
_lane_last_end = 0.0

# sentinel: the AOT lane declined this call — take the normal jit path
_AOT_FELL_BACK = object()


def _emit_compile_span(name, wall0, dur, args):
    """One span on the compile lane. Placement is serialized: two threads
    compiling concurrently would partially overlap on the shared tid, which
    the trace-schema checker (trace_merge.validate_trace span nesting)
    rightly rejects — the later span is shifted to start after the earlier
    one ends (duration preserved, so total compile wall stays truthful)."""
    global _lane_last_end

    from . import profiler

    with _lane_lock:
        start = max(wall0, _lane_last_end)
        _lane_last_end = start + dur
    profiler.emit_span(name, "compile", start, dur, args=args,
                       tid=COMPILE_TRACE_TID)


class _ProgramRecord:
    """Registry row for one logical program (all wrappers sharing a name)."""

    __slots__ = ("name", "site", "digest", "compile_count", "compile_seconds",
                 "run_count", "run_seconds", "recompile_count", "arg_bytes",
                 "peak_bytes", "first_compile_ts", "last_compile_ts",
                 "signatures", "lock")

    def __init__(self, name, site, digest):
        self.name = name
        self.site = site
        self.digest = digest
        self.compile_count = 0
        self.compile_seconds = 0.0
        self.run_count = 0
        self.run_seconds = 0.0
        self.recompile_count = 0
        self.arg_bytes = 0
        self.peak_bytes = None  # backend peak right after last compile
        self.first_compile_ts = None
        self.last_compile_ts = None
        # graph_key -> last compiled signature (cross-wrapper recompile
        # attribution: a rebind/reshape builds a NEW wrapper for the SAME
        # logical graph, and its first compile must still diff against what
        # that graph compiled at before)
        self.signatures = {}
        self.lock = threading.Lock()

    def as_dict(self):
        with self.lock:
            return {
                "program": self.name,
                "site": self.site,
                "digest": self.digest,
                "compile_count": self.compile_count,
                "compile_seconds": round(self.compile_seconds, 6),
                "run_count": self.run_count,
                "run_seconds": round(self.run_seconds, 6),
                "recompile_count": self.recompile_count,
                "arg_bytes": self.arg_bytes,
                "peak_bytes": self.peak_bytes,
                "first_compile_ts": self.first_compile_ts,
                "last_compile_ts": self.last_compile_ts,
            }


def _record(name, site=None, digest=None):
    with _lock:
        rec = _programs.get(name)
        if rec is None:
            rec = _ProgramRecord(name, site or "", digest or "")
            _programs[name] = rec
        else:
            if site and not rec.site:
                rec.site = site
            if digest and not rec.digest:
                rec.digest = digest
        return rec


def reset():
    """Drop every program record (test isolation). The telemetry-side
    counters live in the telemetry registry and reset with it."""
    with _lock:
        _programs.clear()
        del _recompiles[:]


# ---------------------------------------------------------------------------
# graph digests & input signatures
# ---------------------------------------------------------------------------


def symbol_digest(symbol):
    """Stable digest of a Symbol's computation graph: the topo-ordered op
    sequence with attrs AND the full edge wiring (which node output feeds
    which input slot), independent of bind shapes and of node identity/
    names. Two Executors bound over the same graph share it, so a
    reshape/rebind's first compile is correctly attributed as a RECOMPILE
    of that graph rather than a fresh program — and, run after the
    graphpass canonicalize pass, digest-equal means structurally-equal:
    the property the persistent compile cache keys on.

    Variables hash by ROLE AND SLOT (``a<i>`` = i-th argument, ``x<j>`` =
    j-th aux state, in this symbol's own ordering), never by name: names
    are cosmetic, but WHICH slot feeds which input is semantics —
    ``(a+b)-a`` and ``(a+p)-p`` are different positional functions and
    must never share a digest (a shared persistent-cache key would serve
    one of them the other's executable)."""
    from .symbol import _topo_order

    order = _topo_order(symbol._entries)
    idx = {id(n): i for i, n in enumerate(order)}
    _, aux_vars = symbol._arg_aux_split()
    h = hashlib.sha1()
    n_arg = n_aux = 0
    for node in order:
        if node.is_variable:
            if id(node) in aux_vars:
                h.update(("var:x%d|" % n_aux).encode())
                n_aux += 1
            else:
                h.update(("var:a%d|" % n_arg).encode())
                n_arg += 1
            continue
        h.update(node.op.encode())
        for k, v in sorted(node.attrs.items()):
            h.update(("|%s=%s" % (k, v)).encode())
        for inp, k in node.inputs:
            h.update(("|@%d.%d" % (idx[id(inp)], k)).encode())
        h.update(b";")
    h.update(("out:" + ",".join(
        "%d.%d" % (idx[id(n)], k) for n, k in symbol._entries)).encode())
    return h.hexdigest()[:16]


def _leaf_desc(leaf):
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None:
        return ("py:%s" % type(leaf).__name__, (), "")
    return ("", tuple(int(d) for d in shape), str(dtype))


def _signature(args):
    """Per-leaf (kind, shape, dtype) tuple of a call's inputs, with jax
    keypath names so a diff can say WHICH argument changed. Computed only on
    compile events — never on the steady-state dispatch path."""
    import jax

    leaves_kp, _ = jax.tree_util.tree_flatten_with_path(args)
    sig = []
    for kp, leaf in leaves_kp:
        kind, shape, dtype = _leaf_desc(leaf)
        sig.append((jax.tree_util.keystr(kp), kind, shape, dtype))
    return tuple(sig)


def _axis_name(axis, rank):
    if axis == 0:
        return "batch"
    # "seq_len" only for token-shaped inputs (B,T) / (B,T,D): axis 1 of a
    # rank-4 image tensor is channels or height, not sequence length
    if axis == 1 and rank in (2, 3):
        return "seq_len"
    return "axis%d" % axis


def diff_signatures(old, new):
    """Attribute what changed between two compiled signatures of the same
    program: ``(cause, detail)`` where cause is one of ``batch`` /
    ``seq_len`` / ``axis<k>`` / ``dtype`` / ``rank`` / ``structure`` /
    ``placement`` (same shapes — the device/sharding moved, which our
    shape-level signature cannot see)."""
    if old == new:
        return "placement", {"note": "identical shapes: device/sharding or "
                                     "static-config change"}
    if len(old) != len(new) or \
            [e[0] for e in old] != [e[0] for e in new]:
        return "structure", {"old_leaves": len(old), "new_leaves": len(new)}
    changed = []
    for (name, okind, oshape, odt), (_, nkind, nshape, ndt) in zip(old, new):
        if okind != nkind or oshape != nshape or odt != ndt:
            changed.append((name, oshape, odt, nshape, ndt))
    if not changed:
        return "placement", {}
    name, oshape, odt, nshape, ndt = changed[0]
    detail = {"arg": name, "old_shape": list(oshape),
              "new_shape": list(nshape), "n_changed": len(changed)}
    if odt != ndt:
        detail["old_dtype"], detail["new_dtype"] = odt, ndt
        if oshape == nshape:
            return "dtype", detail
    if len(oshape) != len(nshape):
        return "rank", detail
    axes = [i for i, (a, b) in enumerate(zip(oshape, nshape)) if a != b]
    if not axes:
        return "dtype", detail
    detail["axis"] = axes[0]
    return _axis_name(axes[0], len(oshape)), detail


def _arg_nbytes(sig):
    import numpy as np

    total = 0
    for _, kind, shape, dtype in sig:
        if kind or not dtype:
            continue
        try:
            n = int(np.dtype(dtype).itemsize)
        except TypeError:
            continue
        for d in shape:
            n *= int(d)
        total += n
    return total


# ---------------------------------------------------------------------------
# the observed jit wrapper
# ---------------------------------------------------------------------------


class ObservedJit:
    """``jax.jit`` with compile accounting and an optional persistent-cache
    fast lane.

    Dispatch is jax's own (placement, retracing, donation — untouched); this
    wrapper only watches the executable-cache size across each call. Growth
    means THIS call traced+compiled: the call's wall time is recorded as
    compile seconds (trace + XLA compile + the first dispatch), a span lands
    on the chrome-trace compile lane, and — when the program's graph was
    compiled before — the old/new input signatures are diffed into a
    ``compile.recompile`` attribution. When the persistent compile cache is
    enabled (``mxnet_tpu/compile_cache.py``), every compile event is also
    classified cold-vs-disk-hit (``compile.cache_misses`` vs
    ``compile.cache_hits``) via the cache's marker index.

    ``aot=True`` marks a **single-signature** site (each executor instance,
    each serving shape bucket): with the cache enabled, the first dispatch
    resolves the call's key and either loads the serialized executable from
    disk (no trace, no compile) or AOT-compiles via ``lower().compile()``
    and serializes it for the next process; every later call dispatches the
    executable directly. A call whose signature drifts raises inside the
    executable's argument check and falls back to normal jit dispatch —
    never wrong numerics, at worst the seed's compile behavior.
    """

    __slots__ = ("_jitted", "_record", "_graph_key", "_cache_seen",
                 "_own_sigs", "_acct_lock", "_aot_mode", "_aot_state",
                 "_aot_exe", "_aot_drifts", "_cache_identity")

    def __init__(self, fn, program, site=None, graph_key=None, digest=None,
                 aot=False, cache_key=None, **jit_kwargs):
        import jax

        self._jitted = jax.jit(fn, **jit_kwargs)  # fwlint: disable=untracked-jit — the registry wrapper itself
        if digest is None:
            digest = (graph_key if isinstance(graph_key, str) else None)
        self._record = _record(program, site=site, digest=digest)
        # graph identity for cross-wrapper recompile attribution; None means
        # wrapper-scoped (per-instance programs like the fused updater whose
        # per-device call groups legitimately hold several signatures)
        self._graph_key = graph_key if graph_key is not None else id(self)
        # disk-cache identity: must be stable ACROSS processes (a bare
        # graph_key qualifies when the caller passed one — process-local
        # id(self) defaults never do). None → no hit/miss classification
        # and no AOT lane for this wrapper (jax's persistent cache still
        # serves it transparently underneath).
        if cache_key is not None:
            self._cache_identity = cache_key
        elif graph_key is not None:
            self._cache_identity = graph_key
        else:
            self._cache_identity = None
        self._aot_mode = bool(aot)
        self._aot_state = "init"  # init -> on|off (decided at first call)
        self._aot_exe = None
        self._aot_drifts = 0
        self._cache_seen = self._cache_size()
        self._own_sigs = None  # fallback signature cache when _cache_size
        # is unavailable (counts first compiles per signature, like jit)
        # serializes the classify-and-resync step only (dispatch itself is
        # unlocked): shared wrappers (op._JIT_CACHE kernels) are dispatched
        # from engine/pipeline threads concurrently, and without this both
        # the compiler and a blocked waiter would observe the cache delta
        # and double-count the compile
        self._acct_lock = threading.Lock()

    # -- introspection pass-throughs -----------------------------------
    def _cache_size(self):
        try:
            return self._jitted._cache_size()
        except (AttributeError, TypeError):
            return None

    def lower(self, *args, **kwargs):
        """AOT lowering pass-through (``Executor.memory_analysis``)."""
        return self._jitted.lower(*args, **kwargs)

    def _rec(self):
        """The live registry record — re-registered if :func:`reset` ran
        since this wrapper was built (long-lived wrappers like the
        imperative-op cache survive registry resets)."""
        rec = self._record
        if _programs.get(rec.name) is not rec:
            rec = _record(rec.name, rec.site, rec.digest)
            # race-ok: reference rebind to an equivalent record; racing
            # threads re-register the same (name, site, digest) idempotently
            self._record = rec
        return rec

    @property
    def program(self):
        return self._rec().name

    def compile_totals(self):
        """This wrapper's program-record compile tallies
        ``(compile_count, compile_seconds)`` — a cheap two-field read
        under the record lock. The serving engine samples it around each
        bucket dispatch to attribute compile-stall wall to the requests
        blocked behind a cold bucket (serving/obs.py). The record is
        shared per PROGRAM name, so concurrent compiles of sibling
        buckets land in the same tallies — callers diffing around a
        dispatch own the only driver thread in every shipped engine."""
        rec = self._rec()
        with rec.lock:
            return rec.compile_count, rec.compile_seconds

    @property
    def __wrapped__(self):
        return self._jitted

    # -- dispatch -------------------------------------------------------
    def __call__(self, *args, **kwargs):
        exe = self._aot_exe
        if exe is not None:
            out = self._aot_dispatch(exe, args, kwargs)
            if out is not _AOT_FELL_BACK:
                return out
        elif self._aot_state == "init":
            from . import compile_cache as _cc

            self._aot_state = (
                "on" if (self._aot_mode and self._cache_identity is not None
                         and _cc.aot_enabled())
                else "off")
        if self._aot_state == "on" and self._aot_exe is None:
            out = self._aot_first_call(args, kwargs)
            if out is not _AOT_FELL_BACK:
                return out
        t0 = time.perf_counter()
        try:
            out = self._jitted(*args, **kwargs)
        except Exception as exc:
            # resync so a successful trace+compile behind a failed dispatch
            # is not charged to the NEXT (cached, cheap) call
            self._resync_cache()
            if is_oom_error(exc):
                dump_oom_report(self._rec().name, exc)
            raise
        # keyword leaves ride the signature as one trailing dict group
        return self._account(args + (kwargs,) if kwargs else args, out, t0)

    # -- the AOT persistent-cache lane ----------------------------------
    def _aot_dispatch(self, exe, args, kwargs):
        """Steady-state dispatch through the resident executable. A
        signature drift (rebound shapes) raises inside the executable's
        argument check — fall back to jit dispatch; after two drifts the
        lane shuts off for good (an alternating-shape site belongs on
        jax's multi-signature cache, not here)."""
        t0 = time.perf_counter()
        try:
            out = exe(*args, **kwargs)
        except Exception as exc:
            if is_oom_error(exc):
                dump_oom_report(self._rec().name, exc)
                raise
            with self._acct_lock:
                self._aot_exe = None
                self._aot_drifts += 1
                if self._aot_drifts >= 2:
                    self._aot_state = "off"
            _log.warning(
                "compile cache: program %r AOT executable rejected a "
                "dispatch (%s: %s) — falling back to jit dispatch",
                self._rec().name, type(exc).__name__, str(exc)[:200])
            return _AOT_FELL_BACK
        dt = time.perf_counter() - t0
        rec = self._rec()
        with rec.lock:
            rec.run_count += 1
            rec.run_seconds += dt
        return out

    def _aot_first_call(self, args, kwargs):
        """Resolve this site's cache key from the first call's signature,
        then load-or-compile the executable. Any cache-layer failure falls
        back to plain jit dispatch (``compile.cache_errors`` counts it) —
        the lane is an optimization, never a correctness dependency."""
        from . import compile_cache as _cc

        rec = self._rec()
        t0 = time.perf_counter()
        wall0 = time.time()
        sig_args = args + (kwargs,) if kwargs else args
        try:
            sig = _signature(sig_args)
            key = _cc.make_key(rec.name, self._cache_identity, sig)
        except Exception:
            telemetry.counter("compile.cache_errors").inc()
            _log.warning("compile cache: could not key program %r — AOT "
                         "lane off", rec.name, exc_info=True)
            with self._acct_lock:
                self._aot_state = "off"
            return _AOT_FELL_BACK
        exe = _cc.load_executable(key, rec.name)
        if exe is not None:
            try:
                out = exe(*args, **kwargs)
            except Exception as exc:
                if is_oom_error(exc):
                    dump_oom_report(rec.name, exc)
                    raise
                # loads but won't run here (e.g. topology drift the
                # fingerprint missed): treat as corrupt, compile cold
                telemetry.counter("compile.cache_errors").inc()
                _log.warning(
                    "compile cache: loaded AOT executable for %r failed "
                    "to dispatch (%s) — compiling cold", rec.name,
                    type(exc).__name__)
                exe = None
        if exe is None:
            try:
                compiled = self._jitted.lower(*args, **kwargs).compile()
                out = compiled(*args, **kwargs)
            except Exception as exc:
                if is_oom_error(exc):
                    dump_oom_report(rec.name, exc)
                    raise
                # AOT compilation path unsupported here: shut the lane off
                # and let the normal jit dispatch (re)do the work
                telemetry.counter("compile.cache_errors").inc()
                _log.warning("compile cache: AOT lower/compile failed for "
                             "%r — falling back to jit dispatch", rec.name,
                             exc_info=True)
                with self._acct_lock:
                    self._aot_state = "off"
                return _AOT_FELL_BACK
            _cc.save_executable(key, compiled, rec.name)
            exe = compiled
        with self._acct_lock:
            self._aot_exe = exe
        # the whole resolve wall (deserialize on a hit, trace+XLA cold) is
        # a compile event; classification below splits hit from miss
        self._note_compile(sig_args, time.perf_counter() - t0, wall0,
                           sig=sig, cache_key=key)
        return out

    def _resync_cache(self):
        n = self._cache_size()
        if n is not None:
            with self._acct_lock:
                self._cache_seen = n

    def _account(self, args, out, t0):
        dt = time.perf_counter() - t0
        compiled = False
        with self._acct_lock:
            n = self._cache_size()
            if n is not None:
                # growth = this call (or one it blocked on) compiled; a
                # SHRINK (jax.clear_caches()/eviction) is not a compile —
                # resync either way so the next delta is measured from here
                if n > self._cache_seen:
                    compiled = True
                self._cache_seen = n
            else:  # degraded mode: track signatures ourselves
                if self._own_sigs is None:
                    self._own_sigs = set()
                sig = _signature(args)
                if sig not in self._own_sigs:
                    self._own_sigs.add(sig)
                    compiled = True
        if compiled:
            self._note_compile(args, dt, time.time() - dt)
        else:
            rec = self._rec()
            with rec.lock:
                rec.run_count += 1
                rec.run_seconds += dt
        return out

    def _note_compile(self, args, dt, wall0, sig=None, cache_key=None):
        rec = self._rec()
        if sig is None:
            try:
                sig = _signature(args)
            except Exception:  # never let accounting break dispatch
                sig = ()
        nbytes = _arg_nbytes(sig)
        prev = None
        with rec.lock:
            rec.compile_count += 1
            rec.compile_seconds += dt
            rec.arg_bytes = max(rec.arg_bytes, nbytes)
            now = wall0 + dt
            if rec.first_compile_ts is None:
                rec.first_compile_ts = now
            rec.last_compile_ts = now
            prev = rec.signatures.get(self._graph_key)
            rec.signatures[self._graph_key] = sig
        # persistent-cache classification: was this "compile" wall a cold
        # XLA compile or a disk hit underneath? (compile.cache_hits vs
        # compile.cache_misses — what tools/compile_report.py's warm-vs-
        # cold comparison and the "zero cold compiles" gate read)
        cached = None
        cls = None
        if self._cache_identity is not None:
            from . import compile_cache as _cc

            if _cc.enabled():
                try:
                    if cache_key is None:
                        cache_key = _cc.make_key(rec.name,
                                                 self._cache_identity, sig)
                    cls = _cc.classify_compile(rec.name, cache_key, dt)
                except Exception:
                    telemetry.counter("compile.cache_errors").inc()
                if cls is not None:
                    cached = (cls == "hit")
        # always-on metrics + the chrome-trace compile lane
        telemetry.counter("compile.count", program=rec.name).inc()
        telemetry.histogram("compile.seconds", program=rec.name).observe(dt)
        _emit_compile_span("compile[%s]" % rec.name, wall0, dt,
                           {"program": rec.name, "site": rec.site})
        ev = {"program": rec.name, "site": rec.site,
              "seconds": round(dt, 6), "count": rec.compile_count,
              "arg_bytes": nbytes}
        if cached is not None:
            ev["cached"] = cached
        telemetry.event("compile", **ev)
        peak = _backend_peak_bytes()
        if peak is not None:
            with rec.lock:
                rec.peak_bytes = peak
        if prev is not None:
            # ANY compile after the graph's first is a recompile — including
            # prev == sig, where the shapes are identical and what moved is
            # the placement (device/sharding), the one axis a shape-level
            # signature cannot see (diff_signatures labels it `placement`)
            self._note_recompile(prev, sig, dt)

    def _note_recompile(self, prev, sig, dt):
        rec = self._rec()
        cause, detail = diff_signatures(prev, sig)
        with rec.lock:
            rec.recompile_count += 1
        telemetry.counter("compile.recompile", program=rec.name,
                          cause=cause).inc()
        entry = {"ts": time.time(), "program": rec.name, "site": rec.site,
                 "cause": cause, "seconds": round(dt, 6)}
        entry.update(detail)
        with _lock:
            _recompiles.append(entry)
            if len(_recompiles) > _MAX_RECOMPILE_LOG:
                del _recompiles[:len(_recompiles) - _MAX_RECOMPILE_LOG]
        telemetry.event("compile.recompile", **entry)
        # imperative op kernels retrace at every new shape by design —
        # routine, so keep them off the warning stream; a STEP program
        # recompiling is the thing this module exists to make loud
        _log.log(
            logging.DEBUG if rec.name.startswith("op.")
            else logging.WARNING,
            "compile: program %r recompiled (%s%s) at %s — %.2fs",
            rec.name, cause,
            ", arg %s %s->%s" % (detail.get("arg"), detail.get("old_shape"),
                                 detail.get("new_shape"))
            if detail.get("arg") else "",
            rec.site or "<unknown site>", dt)


def jit(fn, program, site=None, graph_key=None, aot=False, cache_key=None,
        **jit_kwargs):
    """The registry's ``jax.jit``: every runtime jit site routes through
    here (enforced by the ``untracked-jit`` fwlint rule).

    ``program`` names the logical program (low-cardinality — it labels the
    always-on ``compile.*`` metrics); ``site`` is the defining call site for
    attribution messages; ``graph_key`` (hashable) identifies the traced
    GRAPH across wrapper rebuilds — pass :func:`symbol_digest` output for
    symbol-derived programs so rebind/reshape compiles diff against the
    graph's previous signature. ``aot=True`` opts a single-signature site
    into the persistent cache's AOT executable lane; ``cache_key``
    overrides the cross-process disk-cache identity when ``graph_key``
    carries process-local parts (e.g. a per-engine nonce) — it must encode
    EVERYTHING that shapes the traced program beyond the input signature.
    Remaining kwargs go to ``jax.jit``.
    """
    return ObservedJit(fn, program, site=site, graph_key=graph_key,
                       aot=aot, cache_key=cache_key, **jit_kwargs)


def raw_jit(fn, program, site=None, **jit_kwargs):
    """A bare ``jax.jit`` object, registered but unwatched — for
    export/AOT-style consumers (``jax.export.export``) that need the
    PjitFunction itself and never dispatch through it. Pair with
    :func:`record_compile` around the export/lower call so the compile wall
    still lands in the registry."""
    import jax

    _record(program, site=site)
    return jax.jit(fn, **jit_kwargs)  # fwlint: disable=untracked-jit — the registry wrapper itself


class record_compile:
    """Context manager charging a block's wall time to ``program`` as a
    compile (export lowering, AOT warmup): counts/seconds/span, no
    signature tracking."""

    def __init__(self, program, site=None):
        self._rec = _record(program, site=site)

    def __enter__(self):
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dt = time.perf_counter() - self._t0
        rec = self._rec
        if exc_type is None:
            with rec.lock:
                rec.compile_count += 1
                rec.compile_seconds += dt
                now = self._wall0 + dt
                if rec.first_compile_ts is None:
                    rec.first_compile_ts = now
                rec.last_compile_ts = now
            telemetry.counter("compile.count", program=rec.name).inc()
            telemetry.histogram("compile.seconds",
                                program=rec.name).observe(dt)
            _emit_compile_span("compile[%s]" % rec.name, self._wall0, dt,
                               {"program": rec.name, "site": rec.site})
            telemetry.event("compile", program=rec.name, site=rec.site,
                            seconds=round(dt, 6), count=rec.compile_count)
        return False


# ---------------------------------------------------------------------------
# registry views
# ---------------------------------------------------------------------------


def program_table():
    """Every program's registry row (list of dicts, most compile-expensive
    first) — what the OOM dump, cluster snapshots, and
    ``tools/compile_report.py`` render."""
    with _lock:
        recs = list(_programs.values())
    rows = [r.as_dict() for r in recs]
    rows.sort(key=lambda r: -r["compile_seconds"])
    return rows


def recompile_log():
    """Chronological recompile attributions (bounded to the last 256 —
    ``_MAX_RECOMPILE_LOG``)."""
    with _lock:
        return list(_recompiles)


def summary(include_recompiles=True):
    """Compact compile summary: program count, total compile count/seconds,
    total run seconds, and recompile attributions — embedded in bench.py's
    BENCH json and in cluster-stats snapshots. ``include_recompiles=False``
    skips copying the bounded recompile log (periodic publishers that only
    want the counts pair it with :func:`last_recompile`)."""
    rows = program_table()
    out = {
        "programs": len(rows),
        "compile_count": sum(r["compile_count"] for r in rows),
        "compile_seconds": round(
            sum(r["compile_seconds"] for r in rows), 6),
        "run_seconds": round(sum(r["run_seconds"] for r in rows), 6),
        "recompile_count": sum(r["recompile_count"] for r in rows),
    }
    from . import compile_cache as _cc

    if _cc.enabled():
        out["cache_hits"] = telemetry.totals("compile.cache_hits")[1]
        out["cache_misses"] = telemetry.totals("compile.cache_misses")[1]
        out["cache_errors"] = telemetry.totals("compile.cache_errors")[1]
    if include_recompiles:
        out["recompiles"] = recompile_log()
    return out


def last_recompile():
    """The most recent recompile attribution, or None — the cheap read the
    per-interval cluster-stats publisher wants (no full-log copy)."""
    with _lock:
        return dict(_recompiles[-1]) if _recompiles else None


# ---------------------------------------------------------------------------
# device-memory accounting
# ---------------------------------------------------------------------------


def _backend_peak_bytes():
    """Max ``peak_bytes_in_use`` across local devices, or None when the
    backend exposes no stats (CPU)."""
    stats = _jax_memory_stats()
    peaks = [s.get("peak_bytes_in_use") for s in stats.values()
             if s.get("peak_bytes_in_use") is not None]
    return max(peaks) if peaks else None


def _jax_memory_stats():
    """{device_str: raw Device.memory_stats dict} for devices that expose
    one (TPU/GPU backends; CPU returns none). Never INITIALIZES jax: this
    runs inside every telemetry read (dump/scrape/stall dump), and a
    host-only process — a PS server with a telemetry sink — must not pay
    backend init (or grab a process-exclusive TPU) for a scrape."""
    import sys

    if "jax" not in sys.modules:
        return {}
    # "jax imported" is NOT the real gate — mxnet_tpu itself imports jax at
    # package import, so that check alone is vacuous. What must not happen
    # is backend INIT: jax.local_devices() on a never-initialized process
    # pays full init and, on a TPU host, grabs the process-exclusive chip.
    # Peek at jax's backend cache instead; if the private API is gone,
    # accept the init cost rather than losing memory stats forever.
    try:
        from jax._src import xla_bridge
        if not xla_bridge._backends:
            return {}
    except Exception:  # fwlint: disable=swallowed-exception — private-API probe: unknown jax internals degrade to the permissive path
        pass
    out = {}
    try:
        import jax

        for d in jax.local_devices():
            try:
                st = d.memory_stats()
            except Exception:
                st = None
            if st:
                out[str(d)] = dict(st)
    except Exception:  # fwlint: disable=swallowed-exception — stats probe: no backend / no devices means "no stats", the fallback accounting takes over
        pass
    return out


def live_ndarray_report(top=None):
    """The NDArray allocation registry's view of live device memory:
    ``{"by_device": {ctx: {"bytes": n, "arrays": k}}, "top": [...]}`` with
    the ``top`` largest live buffers (shape/dtype/context/bytes). Views are
    skipped — their base carries the buffer. This is the accounting
    fallback where the backend exposes no memory stats, and the "top live
    allocations" section of the OOM dump."""
    from . import ndarray as nd

    if top is None:
        top = _env_int("MXNET_OOM_DUMP_TOP", 10)
    by_dev = {}
    entries = []
    for arr in nd.live_arrays():
        try:
            nbytes = int(arr.data.nbytes)
            ctx = str(arr.context)
            shape = tuple(arr.shape)
            dtype = str(arr.dtype)
        except Exception:  # fwlint: disable=swallowed-exception — a buffer deleted/donated mid-walk has no bytes to report; skipping it is the report
            continue
        slot = by_dev.setdefault(ctx, {"bytes": 0, "arrays": 0})
        slot["bytes"] += nbytes
        slot["arrays"] += 1
        entries.append((nbytes, shape, dtype, ctx))
    entries.sort(key=lambda e: -e[0])
    return {
        "by_device": by_dev,
        "top": [{"bytes": n, "shape": list(s), "dtype": d, "context": c}
                for n, s, d, c in entries[:max(int(top), 0)]],
    }


def device_memory_stats():
    """Per-device live/peak bytes: ``{device: {"bytes_in_use", "peak_bytes",
    "source"}}`` — jax backend stats where available, NDArray-allocation
    accounting (live bytes only) as the fallback."""
    stats = _jax_memory_stats()
    if stats:
        return {
            dev: {"bytes_in_use": s.get("bytes_in_use"),
                  "peak_bytes": s.get("peak_bytes_in_use"),
                  "source": "jax"}
            for dev, s in stats.items()
        }
    rep = live_ndarray_report(top=0)
    return {
        dev: {"bytes_in_use": slot["bytes"], "peak_bytes": None,
              "source": "ndarray"}
        for dev, slot in rep["by_device"].items()
    }


def update_memory_gauges():
    """Refresh the ``device.bytes_in_use`` / ``device.peak_bytes`` gauges
    from the current accounting. Registered as a telemetry collector, so
    every ``dump()`` / Prometheus scrape / guard stall dump reads fresh
    values; cheap enough for on-demand use too."""
    for dev, s in device_memory_stats().items():
        if s["bytes_in_use"] is not None:
            telemetry.gauge("device.bytes_in_use", device=dev).set(
                s["bytes_in_use"])
        if s["peak_bytes"] is not None:
            telemetry.gauge("device.peak_bytes", device=dev).set(
                s["peak_bytes"])
    # cumulative run seconds per program, refreshed registry-side (the hot
    # path only bumps the plain record fields; gauges render at read time)
    for row in program_table():
        telemetry.gauge("compile.run_seconds",
                        program=row["program"]).set(row["run_seconds"])


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------

_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM ")


def is_oom_error(exc):
    """Whether ``exc`` is a device out-of-memory failure (XLA surfaces these
    as RESOURCE_EXHAUSTED ``XlaRuntimeError``s; the fault injector's
    synthetic OOM carries the same marker)."""
    msg = str(exc)
    return any(m in msg for m in _OOM_MARKERS)


def dump_oom_report(program, exc, logger=None):
    """The OOM post-mortem, logged BEFORE the error propagates: per-device
    memory stats, the top live NDArray allocations, and the program table —
    what was resident and who compiled it. Counted always-on
    (``device.oom_events``) and mirrored as a structured ``oom`` event."""
    logger = logger or _log
    if getattr(exc, "_mxt_oom_dumped", False):
        return  # already dumped at an inner boundary (ObservedJit catch)
    try:
        exc._mxt_oom_dumped = True
    except AttributeError:
        pass  # slotted/frozen exception: worst case is a duplicate dump
    telemetry.counter("device.oom_events", program=program).inc()
    try:
        mem = device_memory_stats()
        live = live_ndarray_report()
        table = program_table()
        logger.error(
            "OOM at program %r: %s\n"
            "device memory: %s\n"
            "top live allocations: %s\n"
            "program table (by compile seconds): %s",
            program, exc, mem, live["top"],
            [{k: r[k] for k in ("program", "compile_count",
                                "compile_seconds", "run_seconds",
                                "arg_bytes")} for r in table])
        telemetry.event("oom", program=program, error=str(exc)[:500],
                        device_memory=mem, top_allocations=live["top"],
                        programs=[{k: r[k] for k in
                                   ("program", "compile_count", "arg_bytes")}
                                  for r in table])
    except Exception:
        logger.exception("OOM forensics dump itself failed (the original "
                         "RESOURCE_EXHAUSTED error still propagates)")


class oom_guard:
    """Executor-boundary guard: runs the block, and if it dies of
    RESOURCE_EXHAUSTED, dumps the forensics report before re-raising.
    Also hosts the ``oom`` fault-injection point (``MXNET_FAULT_SPEC=
    "oom:"``) so the dump path is testable without a real device OOM."""

    __slots__ = ("_program",)

    def __init__(self, program):
        self._program = program

    def __enter__(self):
        from . import fault

        if fault.hit("oom") is not None:
            exc = MXNetError(
                "RESOURCE_EXHAUSTED: injected device out-of-memory "
                "(fault.py point 'oom') at program %r" % self._program)
            # the injected failure takes the same forensics path a real
            # RESOURCE_EXHAUSTED from the block would: dump, then raise
            dump_oom_report(self._program, exc)
            raise exc
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc is not None and is_oom_error(exc):
            dump_oom_report(self._program, exc)
        return False


telemetry.register_collector(update_memory_gauges)
