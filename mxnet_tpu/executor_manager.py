"""Pre-Module data-parallel training helper.

Reference: python/mxnet/executor_manager.py — `_split_input_slice` :14
and `DataParallelExecutorManager` :303, the machinery `FeedForward` used
before the Module API existed.

Here the manager is an adapter over the same
`DataParallelExecutorGroup` the Module layer uses (module/executor_group.py),
so the pre-Module workflow — bind per device, scatter batches, run
`forward/backward`, read `param_arrays`/`grad_arrays`, apply an updater —
drives the identical TPU executors.
"""
from __future__ import annotations

import logging

from .base import MXNetError
from .module.executor_group import DataParallelExecutorGroup, _split_input_slice

__all__ = ["DataParallelExecutorManager", "_split_input_slice"]


class DataParallelExecutorManager:
    """Helper for data-parallel training on explicit contexts.

    Reference: executor_manager.py:303 — same surface: install_monitor /
    set_params / copy_to / param_arrays / grad_arrays / aux_arrays /
    load_data_batch / forward / backward / update_metric.

    ``sym_gen`` bucketing is the BucketingModule's job in this rebuild and
    is rejected loudly, like the reference's monitor path did.
    """

    def __init__(self, symbol, ctx, train_data, arg_names=None,
                 param_names=None, aux_names=None, work_load_list=None,
                 logger=None, sym_gen=None):
        if sym_gen is not None:
            raise MXNetError(
                "sym_gen bucketing lives in BucketingModule now; "
                "DataParallelExecutorManager handles a single symbol")
        self.logger = logger or logging
        num_device = len(ctx)
        self.logger.info("Start training with %s", str(ctx))
        if work_load_list is None:
            work_load_list = [1] * num_device
        if len(work_load_list) != num_device:
            raise MXNetError("Invalid settings for work load.")
        # slice validity (incl. uneven workloads) is _split_input_slice's
        # job — it raises on empty slices

        self.symbol = symbol
        self._batch = None
        self.ctx = ctx
        self.arg_names = arg_names or symbol.list_arguments()
        self.aux_names = aux_names or symbol.list_auxiliary_states()
        input_names = [d.name for d in train_data.provide_data] + [
            l.name for l in (train_data.provide_label or [])]
        self.param_names = param_names or [
            n for n in self.arg_names if n not in input_names]
        self.slices = _split_input_slice(train_data.batch_size,
                                         work_load_list)
        self.execgrp = DataParallelExecutorGroup(
            symbol, ctx, work_load_list,
            data_shapes=train_data.provide_data,
            label_shapes=train_data.provide_label,
            param_names=self.param_names,
            for_training=True, inputs_need_grad=False,
            logger=self.logger)

    def install_monitor(self, monitor):
        """Install monitor on all executors."""
        self.execgrp.install_monitor(monitor)

    def set_params(self, arg_params, aux_params):
        """Load parameter/aux dicts into every device executor."""
        self.execgrp.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        """Gather (device-averaged) parameters back into the given dicts."""
        self.execgrp.get_params(arg_params, aux_params)

    @property
    def param_arrays(self):
        """Per-parameter lists of per-device weight arrays."""
        return self.execgrp.param_arrays

    @property
    def grad_arrays(self):
        """Per-parameter lists of per-device gradient arrays."""
        return self.execgrp.grad_arrays

    @property
    def aux_arrays(self):
        """Per-aux lists of per-device state arrays."""
        return self.execgrp.aux_arrays

    def load_data_batch(self, data_batch):
        """Stage a batch: slices scatter to the devices on forward.

        uint8-wire batches (io.WireSpec) decode eagerly here, so repeated
        ``forward`` calls on one staged batch pay the decode once (target
        device policy in io.wire_decode_ctx)."""
        from . import io as io_mod

        self._batch = io_mod.apply_wire(
            data_batch, ctx=io_mod.wire_decode_ctx(self.ctx))

    def forward(self, is_train=False):
        if self._batch is None:
            raise MXNetError("call load_data_batch(batch) before forward()")
        self.execgrp.forward(self._batch, is_train=is_train)

    def backward(self):
        self.execgrp.backward()

    def update_metric(self, metric, labels):
        self.execgrp.update_metric(metric, labels)
