"""Symbol operator documentation (reference: python/mxnet/symbol_doc.py —
extended docstrings attached to generated symbol functions; here generation
lives in op_doc.py, re-exported under the reference's module name)."""
from .op_doc import attach_docs, build_doc  # noqa: F401
