"""graphpass — the graph-level optimization pipeline over Symbol.

The executor lowers Symbol traces essentially 1:1 and leans on XLA for
everything else. That is fine for per-op numerics but wrong for two things
XLA cannot see from a single trace (the TVM/Relay argument — do graph-level
optimization at your own IR):

* **identity**: two structurally-equal graphs built in different orders
  (operand order of commutative ops, construction order of towers) must
  hash to the same digest, or the persistent compile cache
  (``mxnet_tpu/compile_cache.py``) misses on every cosmetic difference and
  compileobs misattributes rebinds as fresh programs;
* **redundancy**: duplicate subexpressions (shared towers re-built per
  branch), constant subgraphs, and no-op scalar chains all inflate trace
  time and program size before XLA ever runs.

Every pass is a pure ``Symbol -> Symbol`` function registered in
:data:`PASS_REGISTRY`; the default pipeline is
``canonicalize -> fold_constants -> eliminate_common_subexpr ->
fuse_elemwise``. ``MXNET_GRAPH_PASSES`` is the opt-out ladder:

* unset / ``default`` — the default pipeline;
* ``none`` / ``off`` / ``0`` — passes disabled (the seed's 1:1 lowering);
* a comma list (``canonicalize,cse``) — exactly those passes, in order;
* ``default,-cse`` — the default pipeline minus the named passes;
* ``default,bucket_shapes`` — the default plus opt-in passes
  (``bucket_shapes`` changes declared bind shapes, so it never runs
  unless asked for — see docs/compiler.md).

The pipeline is contract-checked: a pass must preserve the argument /
auxiliary-state name sets and the output arity (the binding surface
Module and Executor key on). If any pass breaks the contract or raises,
:func:`optimize` falls back to the unoptimized graph and counts
``graphpass.fallbacks`` — graph optimization must never take down a fit.

Telemetry (docs/observability.md §compiler): ``graphpass.pass_seconds``
per pass (gated on :func:`telemetry.enabled`), always-on
``graphpass.nodes_eliminated`` / ``graphpass.nodes_fused`` /
``graphpass.errors`` / ``graphpass.fallbacks`` counters.
"""
from __future__ import annotations

import logging
import time

from .. import telemetry
from ..base import env_str as _env_str

__all__ = [
    "PASS_REGISTRY", "DEFAULT_PIPELINE", "register_pass", "list_passes",
    "active_passes", "run_pass", "optimize", "structural_hash",
]

_log = logging.getLogger(__name__)

PASS_REGISTRY = {}  # name -> pure Symbol -> Symbol function

# passes outside DEFAULT_PIPELINE (bucket_shapes) are opt-in: they change
# observable behavior (declared bind shapes) rather than just the lowering
DEFAULT_PIPELINE = ("canonicalize", "fold_constants",
                    "eliminate_common_subexpr", "fuse_elemwise")

_PASS_ALIASES = {"cse": "eliminate_common_subexpr"}


def register_pass(name):
    """Decorator: register a pure ``Symbol -> Symbol`` pass under ``name``."""
    def _reg(fn):
        PASS_REGISTRY[name] = fn
        return fn
    return _reg


def list_passes():
    """Registered pass names (registry order)."""
    return list(PASS_REGISTRY)


def active_passes():
    """The pass list selected by ``MXNET_GRAPH_PASSES`` (see module doc)."""
    spec = _env_str("MXNET_GRAPH_PASSES", "default")
    if spec.strip().lower() in ("none", "off", "0", ""):
        return ()
    names = []
    removed = set()
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok.startswith("-"):
            removed.add(_PASS_ALIASES.get(tok[1:].strip(),
                                          tok[1:].strip()))
            continue
        if tok.lower() in ("default", "all"):
            names.extend(n for n in DEFAULT_PIPELINE if n not in names)
            continue
        tok = _PASS_ALIASES.get(tok, tok)
        if tok not in PASS_REGISTRY:
            _log.warning("MXNET_GRAPH_PASSES: unknown pass %r (have %s) — "
                         "skipped", tok, ",".join(PASS_REGISTRY))
            continue
        if tok not in names:
            names.append(tok)
    return tuple(n for n in names if n not in removed)


def run_pass(name, symbol):
    """Run one registered pass; returns the transformed Symbol (the input
    Symbol is never mutated — passes copy first)."""
    return PASS_REGISTRY[name](symbol)


def _binding_surface(symbol):
    """The contract every pass must preserve: arg/aux name SETS (order is
    re-imposed by the executor's name-keyed binding) + output arity."""
    return (frozenset(symbol.list_arguments()),
            frozenset(symbol.list_auxiliary_states()),
            len(symbol._entries))


def optimize(symbol, passes=None):
    """Run the active pass pipeline over ``symbol``; returns the optimized
    Symbol, or ``symbol`` itself when passes are disabled, a pass fails,
    or the pipeline breaks the binding surface (counted
    ``graphpass.fallbacks`` — never raises into the bind path)."""
    names = tuple(passes) if passes is not None else active_passes()
    if not names:
        return symbol
    try:
        surface = _binding_surface(symbol)
    except Exception:
        # a graph the introspection walk cannot classify is a graph the
        # passes have no business rewriting
        telemetry.counter("graphpass.fallbacks").inc()
        return symbol
    g = symbol
    timed = telemetry.enabled()
    for name in names:
        fn = PASS_REGISTRY.get(name)
        if fn is None:
            _log.warning("graphpass: unknown pass %r skipped", name)
            continue
        t0 = time.perf_counter() if timed else 0.0
        try:
            g = fn(g)
        except Exception:
            telemetry.counter("graphpass.errors", **{"pass": name}).inc()
            _log.exception("graphpass: pass %r failed — graph left as it "
                           "was before the pass", name)
            continue
        if timed:
            telemetry.histogram("graphpass.pass_seconds",
                                **{"pass": name}).observe(
                time.perf_counter() - t0)
    try:
        ok = _binding_surface(g) == surface
    except Exception:
        ok = False
    if not ok:
        telemetry.counter("graphpass.fallbacks").inc()
        _log.warning("graphpass: pipeline %s changed the binding surface — "
                     "falling back to the unoptimized graph", list(names))
        return symbol
    return g


# importing the pass implementations registers them
from . import passes as _passes  # noqa: E402,F401
from .passes import structural_hash  # noqa: E402
