"""The pass implementations (see the package doc for the pipeline
contract). Every pass deep-copies the incoming Symbol (``__copy__``) and
mutates the copy — callers never observe a half-rewritten graph.

Ground rules shared by every pass:

* **output nodes are pinned**: a node referenced by ``sym._entries`` is
  never replaced or renamed — ``list_outputs()`` strings are part of the
  Module/metric binding surface;
* **variables are never created or destroyed**: the arg/aux name sets are
  the executor's binding contract (checked again by ``optimize``);
* **numerics-preserving**: rewrites are exact (identity elimination,
  commutative operand swap, CSE of deterministic stateless ops) or
  reassociations of scalar constants whose error is bounded well inside
  the 1e-5 golden-test tolerance (scalar-chain folding);
* **stochastic and stateful ops are opaque**: Dropout draws per-node rng
  streams and BatchNorm mutates aux state — neither is merged, moved, or
  folded.
"""
from __future__ import annotations

import ast
import hashlib

from .. import telemetry
from ..base import attr_str
from ..ops.registry import get_op
from ..symbol import Symbol, _topo_order

from . import register_pass

# binary elementwise ops where operand order is numerically irrelevant
# (IEEE add/mul/max/min commute exactly; n-ary add_n is excluded — sorting
# its operands reorders the float summation)
_COMMUTATIVE = frozenset((
    "elemwise_add", "elemwise_mul", "_maximum", "_minimum",
    "broadcast_add", "broadcast_plus", "broadcast_mul",
    "broadcast_maximum", "broadcast_minimum",
))

# pointwise ops an XLA loop fusion would merge: the fuse_elemwise pass
# groups chains of these for attribution (the annotation changes no
# numerics — XLA does the actual fusing; the group tells US it happened)
_ELEMWISE = frozenset((
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
    "_div_scalar", "_rdiv_scalar", "_power_scalar", "_rpower_scalar",
    "_maximum", "_minimum", "_maximum_scalar", "_minimum_scalar",
    "Activation", "relu", "sigmoid", "tanh", "softsign", "negative",
    "abs", "exp", "expm1", "log", "log1p", "log2", "log10", "sqrt",
    "rsqrt", "square", "cbrt", "rcbrt", "reciprocal", "erf", "sign",
    "floor", "ceil", "round", "rint", "fix", "trunc", "clip",
    "degrees", "radians", "sin", "cos", "tan", "sinh", "cosh",
    "arcsin", "arccos", "arctan", "arcsinh", "arccosh", "arctanh",
    "smooth_l1", "_copy", "identity",
))

# scalar-op identities: applying the op with this scalar is a no-op
_IDENTITY_SCALAR = {
    "_mul_scalar": 1.0,
    "_div_scalar": 1.0,
    "_plus_scalar": 0.0,
    "_minus_scalar": 0.0,
    "_power_scalar": 1.0,
}

# init ops producing a uniform constant tensor, and the value they hold
_INIT_VALUE = {
    "_zeros": lambda attrs: 0.0,
    "_ones": lambda attrs: 1.0,
    "_full": lambda attrs: float(attrs.get("value", 0.0)),
}

# scalar ops foldable onto a uniform constant: value' = f(value, scalar)
_SCALAR_EVAL = {
    "_mul_scalar": lambda v, s: v * s,
    "_plus_scalar": lambda v, s: v + s,
    "_minus_scalar": lambda v, s: v - s,
    "_rminus_scalar": lambda v, s: s - v,
    "_div_scalar": lambda v, s: v / s,
    "_rdiv_scalar": lambda v, s: s / v if v != 0.0 else None,
    "_power_scalar": lambda v, s: v ** s,
}


def _pinned(sym):
    return {id(n) for n, _ in sym._entries}


def _count_nodes(sym):
    return len(_topo_order(sym._entries))


def structural_hash(sym_or_node, _memo=None):
    """Content hash of a node's subtree (or a Symbol's whole graph):
    op + canonical attrs + recursively-hashed inputs. Variables hash by
    name. Used as the deterministic sort key for commutative-operand
    canonicalization and as the CSE value number."""
    if isinstance(sym_or_node, Symbol):
        memo = {}
        parts = ["%s#%d" % (_node_hash(n, memo), k)
                 for n, k in sym_or_node._entries]
        return hashlib.sha1("|".join(parts).encode()).hexdigest()
    return _node_hash(sym_or_node, _memo if _memo is not None else {})


def _node_hash(node, memo):
    h = memo.get(id(node))
    if h is not None:
        return h
    # iterative post-order: zoo graphs (inception_resnet_v2 ~1500 nodes)
    # would blow the recursion limit
    stack = [node]
    while stack:
        n = stack[-1]
        if id(n) in memo:
            stack.pop()
            continue
        missing = [i for i, _ in n.inputs if id(i) not in memo
                   and not i.is_variable]
        if missing:
            stack.extend(missing)
            continue
        stack.pop()
        if n.is_variable:
            memo[id(n)] = hashlib.sha1(
                ("var:%s" % n.name).encode()).hexdigest()[:16]
            continue
        parts = [n.op]
        parts.extend("%s=%s" % (k, attr_str(v))
                     for k, v in sorted(n.attrs.items()))
        for inp, k in n.inputs:
            ih = memo.get(id(inp)) if not inp.is_variable else \
                hashlib.sha1(("var:%s" % inp.name).encode()).hexdigest()[:16]
            memo.setdefault(id(inp), ih)
            parts.append("%s#%d" % (ih, k))
        memo[id(n)] = hashlib.sha1("|".join(parts).encode()).hexdigest()[:16]
    return memo[id(node)]


# ---------------------------------------------------------------------------
# canonicalize
# ---------------------------------------------------------------------------


@register_pass("canonicalize")
def canonicalize(sym):
    """Stable structural form: operands of commutative binary ops are
    sorted by subtree content hash, so ``a + b`` and ``b + a`` — and any
    construction-order difference upstream of them — produce the same
    post-pass digest. This is what makes digest-equal mean
    structurally-equal for the compile-cache key."""
    g = sym.__copy__()
    memo = {}
    for node in _topo_order(g._entries):
        if node.is_variable or node.op not in _COMMUTATIVE:
            continue
        op = get_op(node.op)
        if len(node.inputs) != 2 or op.aux_names(node.attrs):
            continue
        keyed = [(_node_hash(i, memo), k, (i, k)) for i, k in node.inputs]
        node.inputs = [e for _, _, e in sorted(keyed, key=lambda t: t[:2])]
        # ancestors hash over the sorted form
        memo.pop(id(node), None)
    return g


# ---------------------------------------------------------------------------
# fold_constants
# ---------------------------------------------------------------------------


@register_pass("fold_constants")
def fold_constants(sym):
    """Identity elimination (``x*1``, ``x+0``, ``x**1`` — bit-exact),
    scalar-chain folding (``(x*a)*b -> x*(a*b)``,
    ``(x+a)-b -> x+(a-b)``), and constant folding of scalar ops applied
    to uniform init tensors (``_ones(s)*2 -> _full(s, 2)``). Dead nodes
    fall out of the graph by unreachability."""
    g = sym.__copy__()
    pinned = _pinned(g)
    before = _count_nodes(g)
    # entry-level replacement: id(eliminated node) -> the (node, k) entry
    # its consumers should read instead
    repl = {}

    def _resolve(entry):
        node, k = entry
        while id(node) in repl:
            node, k = repl[id(node)]
        return node, k

    for node in _topo_order(g._entries):
        if node.is_variable:
            continue
        node.inputs = [_resolve(e) for e in node.inputs]
        if id(node) in pinned or len(node.inputs) != 1:
            continue
        scalar = node.attrs.get("scalar")
        inp, k = node.inputs[0]
        # 1) identity scalar op: drop the node entirely
        if node.op in _IDENTITY_SCALAR and \
                scalar == _IDENTITY_SCALAR[node.op]:
            repl[id(node)] = (inp, k)
            continue
        if inp.is_variable or id(inp) in pinned:
            continue
        # 2) same-family scalar chains collapse onto this node
        if scalar is not None and len(inp.inputs) == 1:
            in_scalar = inp.attrs.get("scalar")
            if in_scalar is not None:
                if node.op == "_mul_scalar" and inp.op == "_mul_scalar":
                    node.attrs = dict(node.attrs,
                                      scalar=float(in_scalar) * float(scalar))
                    node.inputs = [inp.inputs[0]]
                    continue
                addish = {"_plus_scalar": 1.0, "_minus_scalar": -1.0}
                if node.op in addish and inp.op in addish:
                    net = addish[inp.op] * float(in_scalar) \
                        + addish[node.op] * float(scalar)
                    node.op = "_plus_scalar"
                    node.attrs = get_op("_plus_scalar").canonicalize_attrs(
                        {"scalar": net})[0]
                    node.inputs = [inp.inputs[0]]
                    continue
        # 3) scalar op over a uniform init tensor folds to _full
        if node.op in _SCALAR_EVAL and inp.op in _INIT_VALUE \
                and not inp.inputs:
            new_val = _SCALAR_EVAL[node.op](_INIT_VALUE[inp.op](inp.attrs),
                                            float(scalar))
            if new_val is None:
                continue
            attrs = {"shape": inp.attrs.get("shape", ()),
                     "value": new_val}
            if inp.attrs.get("dtype") is not None:
                attrs["dtype"] = inp.attrs["dtype"]
            node.op = "_full"
            node.attrs = get_op("_full").canonicalize_attrs(attrs)[0]
            node.inputs = []
    g._entries = [_resolve(e) for e in g._entries]
    eliminated = before - _count_nodes(g)
    if eliminated:
        telemetry.counter("graphpass.nodes_eliminated",
                          **{"pass": "fold_constants"}).inc(eliminated)
    return g


# ---------------------------------------------------------------------------
# eliminate_common_subexpr (CSE)
# ---------------------------------------------------------------------------


@register_pass("eliminate_common_subexpr")
def eliminate_common_subexpr(sym):
    """Merge structurally identical deterministic nodes: same op, same
    canonical attrs, same input entries. Stochastic ops (per-node rng
    streams) and aux-mutating ops (BatchNorm) are never merged; output
    nodes are pinned (their names are the output surface)."""
    g = sym.__copy__()
    pinned = _pinned(g)
    before = _count_nodes(g)
    repl = {}   # id(duplicate node) -> surviving node
    table = {}  # value number -> surviving node
    for node in _topo_order(g._entries):
        if node.is_variable:
            continue
        node.inputs = [(repl.get(id(i), i), k) for i, k in node.inputs]
        op = get_op(node.op)
        if op.stochastic or op.aux_names(node.attrs):
            continue
        key = (node.op,
               tuple(sorted((k, attr_str(v))
                            for k, v in node.attrs.items())),
               tuple((id(i), k) for i, k in node.inputs))
        prev = table.get(key)
        if prev is None:
            table[key] = node
        elif id(node) not in pinned:
            repl[id(node)] = prev
    g._entries = [(repl.get(id(n), n), k) for n, k in g._entries]
    eliminated = before - _count_nodes(g)
    if eliminated:
        telemetry.counter("graphpass.nodes_eliminated",
                          **{"pass": "eliminate_common_subexpr"}).inc(
            eliminated)
    return g


# ---------------------------------------------------------------------------
# fuse_elemwise
# ---------------------------------------------------------------------------


@register_pass("fuse_elemwise")
def fuse_elemwise(sym):
    """Group chains of pointwise ops under a shared ``__fuse_group__``
    attribute. Purely annotational — the executor jits the whole graph
    and XLA performs the actual loop fusion; the groups give telemetry
    (and a future segment-lowering pass) the fusion structure at OUR IR.
    A producer joins its consumer's group only when the consumer is its
    sole reader (the XLA fusion-legality condition for avoiding
    recompute)."""
    g = sym.__copy__()
    order = _topo_order(g._entries)
    consumers = {}
    for node in order:
        for inp, _ in node.inputs:
            consumers[id(inp)] = consumers.get(id(inp), 0) + 1
    for node, _ in g._entries:
        consumers[id(node)] = consumers.get(id(node), 0) + 1

    parent = {}

    def find(i):
        while parent.get(i, i) != i:
            parent[i] = parent.get(parent[i], parent[i])
            i = parent[i]
        return i

    def union(a, b):
        parent.setdefault(a, a)
        parent.setdefault(b, b)
        parent[find(a)] = find(b)

    for node in order:
        if node.is_variable or node.op not in _ELEMWISE:
            continue
        for inp, _ in node.inputs:
            if not inp.is_variable and inp.op in _ELEMWISE \
                    and consumers.get(id(inp), 0) == 1:
                union(id(inp), id(node))
    groups = {}
    for node in order:
        if node.is_variable or id(node) not in parent:
            continue
        groups.setdefault(find(id(node)), []).append(node)
    fused = 0
    gid = 0
    for node in order:  # stable numbering: by first member's topo index
        root = find(id(node)) if id(node) in parent else None
        members = groups.pop(root, None) if root is not None else None
        if not members or len(members) < 2:
            continue
        for m in members:
            m._extra_attrs["__fuse_group__"] = "g%d" % gid
        fused += len(members)
        gid += 1
    if fused:
        telemetry.counter("graphpass.nodes_fused").inc(fused)
    return g


# ---------------------------------------------------------------------------
# bucket_shapes (opt-in: changes declared bind shapes)
# ---------------------------------------------------------------------------

_BUCKET_LADDER = (1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024)


def _bucket(n):
    for b in _BUCKET_LADDER:
        if n <= b:
            return b
    return n


@register_pass("bucket_shapes")
def bucket_shapes(sym):
    """Round every Variable-declared batch dimension (``__shape__`` dim 0)
    up to the next bucket so nearby batch sizes share one compiled
    program. OPT-IN ONLY (``MXNET_GRAPH_PASSES=default,bucket_shapes``):
    consumers must pad their batches to the bucketed shape — this pass
    changes what ``simple_bind`` allocates, not just how it lowers
    (docs/compiler.md §shape-bucketing)."""
    g = sym.__copy__()
    changed = 0
    for node in _topo_order(g._entries):
        if not node.is_variable:
            continue
        raw = node._extra_attrs.get("__shape__")
        if not raw:
            continue
        shape = tuple(ast.literal_eval(raw))
        if not shape or not isinstance(shape[0], int) or shape[0] <= 0:
            continue
        b = _bucket(shape[0])
        if b != shape[0]:
            node._extra_attrs["__shape__"] = str((b,) + tuple(shape[1:]))
            changed += 1
    if changed:
        telemetry.counter("graphpass.shapes_bucketed").inc(changed)
    return g
