"""Automatic symbol naming (reference: python/mxnet/name.py).

``NameManager`` hands out ``op_name + counter`` names for anonymous symbols;
``Prefix`` prepends a scope prefix — identical user-visible behavior so symbol
JSON produced here names nodes the same way the reference does.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]


class NameManager:
    """Manages automatic naming of symbols; with-scope stacked."""

    _current = threading.local()

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        """Return ``name`` if given, else generate ``hint%d``."""
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = "%s%d" % (hint, self._counter[hint])
        self._counter[hint] += 1
        return name

    def __enter__(self):
        self._old_manager = getattr(NameManager._current, "value", None)
        NameManager._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_manager
        NameManager._current.value = self._old_manager

    @staticmethod
    def current():
        v = getattr(NameManager._current, "value", None)
        if v is None:
            v = NameManager()
            NameManager._current.value = v
        return v


class Prefix(NameManager):
    """Name manager that always attaches a prefix to all names."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name


NameManager._current.value = NameManager()
