"""Elastic multi-host training — worker-side membership session.

The PS tier owns cluster membership (kvstore_server.MembershipRegistry on
server rank 0): a monotonically increasing **membership epoch** is stamped
on every push/pull/barrier (src/ps.cc MsgHeader.mepoch) once a job runs
elastic, and any request from a departed membership view is rejected with a
classified :class:`~mxnet_tpu.kvstore.KVMembershipError` — no gradient from
a dead or stale worker can land.

This module is the worker half (docs/distributed.md §elasticity):

* :class:`ElasticSession` registers the worker with the registry
  (``mb_join``), heartbeats it on a background thread, and owns the two
  recovery transitions the fit loop drives:

  - :meth:`ElasticSession.reconfigure` — a *survivor* hit a
    ``KVMembershipError`` (a peer was lost, or a replacement joined). It
    drains the engine under the old epoch, adopts the registry's current
    epoch, **deterministically reshards** the data (``num_workers``/``rank``
    become epoch-scoped through ``DataIter.set_partition`` + the
    ``state_dict()`` position protocol), rolls back through the PR-4 guard
    snapshot to the last consistent step, and — on the lowest surviving
    rank — re-seeds the server weights from that snapshot (kInit bypasses
    merge + optimizer) and publishes the restart position for joiners.

  - :meth:`ElasticSession.join` — a relaunched worker
    (``DMLC_PS_RECOVERY=1``, set by ``tools/launch.py --elastic``) waits for
    the coordinator's published position, adopts epoch + shard, pulls the
    current parameters, and enters the training loop at the same boundary
    the survivors rolled back to.

Knobs (docs/env_var.md): ``MXNET_ELASTIC`` switches the whole path on,
``MXNET_ELASTIC_HEARTBEAT_S`` / ``MXNET_ELASTIC_HEARTBEAT_TIMEOUT_S`` pace
failure detection, ``MXNET_ELASTIC_JOIN_TIMEOUT_S`` bounds a joiner's wait,
``MXNET_ELASTIC_MAX_RESTARTS`` caps relaunches (enforced by the launcher).
"""
from __future__ import annotations

import json
import logging
import threading
import time

from . import telemetry
from .base import (MXNetError, env_bool as _env_bool,
                   env_float as _env_float)

__all__ = ["ElasticSession", "enabled", "prepare"]


def enabled():
    """Whether this process runs elastic (``MXNET_ELASTIC``, set for the
    whole tree by ``tools/launch.py --elastic``)."""
    return _env_bool("MXNET_ELASTIC")


def prepare(kvstore, logger=None):
    """Resolve fit's ``kvstore`` argument for an elastic job: returns
    ``(kvstore, session_or_None)``. A ``dist_*`` type string is resolved to
    the real store here (the session must exist — and flip the servers into
    elastic mode — before ``init_optimizer`` touches the PS); anything that
    is not a distributed PS-backed store trains as usual with no session.
    """
    logger = logger or logging.getLogger(__name__)
    from . import kvstore as kvs

    if isinstance(kvstore, str) and "dist" in kvstore:
        kvstore = kvs.create(kvstore)
    if isinstance(kvstore, kvs.KVStoreDist):
        session = ElasticSession(kvstore, logger=logger)
        session.start()
        return kvstore, session
    logger.warning(
        "MXNET_ELASTIC is set but kvstore %r is not a distributed PS "
        "store — training continues without elasticity", kvstore)
    return kvstore, None


class ElasticSession:
    """One worker's membership session (see module docstring)."""

    def __init__(self, kv, logger=None):
        self._kv = kv
        self.rank = kv.rank
        self.logger = logger or logging.getLogger(__name__)
        self._hb_interval = _env_float("MXNET_ELASTIC_HEARTBEAT_S", 1.0)
        self._join_timeout = _env_float("MXNET_ELASTIC_JOIN_TIMEOUT_S", 300.0)
        self.joining = bool(kv.is_recovery)
        # effective (num_workers, rank) under the current membership —
        # epoch-scoped: reconfigure()/join() move it, the data partition
        # follows it
        # race-ok: atomic tuple rebind on the restart path; stats readers
        # tolerate sampling the previous membership for one tick
        self.effective = (kv.num_workers, kv.rank)
        self._stop = threading.Event()
        self._hb_thread = None
        self._closed = False

    # ---- lifecycle -------------------------------------------------------
    def start(self):
        """Flip the servers into elastic mode, register with the registry,
        and start heartbeating. Idempotent per process."""
        if self._hb_thread is not None:
            return
        self._kv.elastic_enable()
        if not self._kv.registry_command(
                "mb_join:%d:%d" % (self.rank, self._kv.step_id)):
            raise MXNetError(
                "elastic: membership registry (server 0) did not "
                "acknowledge the join — is the cluster up?")
        self._hb_thread = threading.Thread(
            target=self._hb_loop, daemon=True,
            name="mxnet-elastic-heartbeat")
        self._hb_thread.start()

    def _hb_loop(self):
        while not self._stop.wait(self._hb_interval):
            # the heartbeat carries this worker's current step (trace
            # identity): registry-side membership events can then name the
            # training step a lapse/reconfiguration landed at
            if not self._kv.registry_command(
                    "mb_hb:%d:%d" % (self.rank, self._kv.step_id)):
                # bounded probe already timed out; count it (always-on) so a
                # flapping registry is visible — the registry treats the
                # missing beat as lapse evidence, which is the correct
                # failure semantics for an unreachable worker anyway
                telemetry.counter("kv.membership.heartbeat_failures").inc()

    def close(self, done=True):
        """Stop heartbeating; ``done=True`` additionally reports graceful
        end-of-training (the registry stops lapse-monitoring and tells any
        late-relaunched worker to exit instead of waiting to join)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        if done:
            self._kv.registry_command("mb_done:%d" % self.rank)
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)

    # ---- registry views --------------------------------------------------
    def sync(self, timeout_s=None):
        """Fetch the membership table, retrying within ``timeout_s``."""
        deadline = time.monotonic() + (timeout_s if timeout_s is not None
                                       else self._hb_interval * 10)
        while True:
            raw = self._kv.registry_fetch("mb_get")
            if raw:
                try:
                    return json.loads(raw.decode())
                except ValueError:
                    pass  # torn publish: retry below
            if time.monotonic() > deadline:
                raise MXNetError(
                    "elastic: membership registry unreachable (no table "
                    "within the deadline)")
            time.sleep(min(self._hb_interval / 2.0, 0.2))

    def _shard_of(self, table):
        workers = table["workers"]
        if self.rank not in workers:
            return None
        return (len(workers), workers.index(self.rank))

    # ---- survivor path ---------------------------------------------------
    def reconfigure(self, module, train_data, guard):
        """Recover from a ``KVMembershipError``: adopt the new membership,
        reshard, roll back to the guard's last snapshot, and (on the
        coordinator — the lowest surviving rank) re-seed the servers and
        publish the restart position. Returns ``(epoch, nbatch,
        iter_restored)`` exactly like ``guard.rollback`` — fit resumes its
        inner loop there."""
        kv = self._kv
        if guard is None or guard.last_snapshot is None:
            raise MXNetError(
                "elastic: membership changed but no guard snapshot exists "
                "to roll back to (fit enables a rollback guard "
                "automatically in elastic mode — was the guard disabled?)")
        guard.suspend_watchdog()
        # 1. drain the engine UNDER THE OLD EPOCH: every in-flight async
        # push either completed in the old membership or was rejected; run
        # it twice so an error recorded during the first wait's own drain
        # cannot survive into the post-reconfiguration stream
        from .kvstore import KVMembershipError

        for _ in range(2):
            try:
                kv._engine.wait_all()
            except KVMembershipError:
                pass  # expected: that is the event being recovered from
        # 2. adopt the registry's current membership (rejoin if the
        # registry presumed US dead — e.g. a long stall outlived the
        # heartbeat timeout while the process stayed alive). When WE
        # detected a dead SERVER (consume_server_loss), the registry may
        # not have noticed yet: wait for a table whose epoch moved PAST
        # ours — resuming on the old epoch would re-route keys to the
        # corpse and reject again (docs/distributed.md §server-HA)
        server_loss = kv.consume_server_loss()
        hb_timeout = _env_float("MXNET_ELASTIC_HEARTBEAT_TIMEOUT_S", 5.0)
        srv_deadline = time.monotonic() + max(30.0, hb_timeout * 6)
        rejoins = 0
        while True:
            table = self.sync()
            shard = self._shard_of(table)
            if shard is None:
                rejoins += 1
                if rejoins > 10:
                    raise MXNetError(
                        "elastic: could not rejoin the membership after "
                        "eviction")
                self.logger.warning(
                    "elastic: registry evicted this worker (rank %d) — "
                    "rejoining", self.rank)
                kv.registry_command(
                    "mb_join:%d:%d" % (self.rank, kv.step_id))
                continue
            if server_loss and int(table["epoch"]) <= kv.membership_epoch:
                if time.monotonic() > srv_deadline:
                    raise MXNetError(
                        "elastic: a server is unreachable but the registry "
                        "never promoted a backup (no epoch bump within the "
                        "deadline) — is the whole group down?")
                time.sleep(min(self._hb_interval / 2.0, 0.2))
                continue
            break
        epoch = int(table["epoch"])
        # server map BEFORE epoch, matching the registry's own broadcast
        # order: traffic stamped with the new epoch must already route to
        # the promoted primaries
        kv.adopt_server_map(table.get("smap") or [])
        kv.set_membership_epoch(epoch)
        new_nw, new_rank = shard
        old_nw, old_rank = self.effective
        # 3. epoch-scoped reshard: the survivors repartition the data over
        # the new membership; the guard rollback below repositions the
        # resharded stream to the snapshot's batch via the iterator
        # position protocol (state_dict/load_state)
        if (new_nw, new_rank) != (old_nw, old_rank):
            set_part = getattr(train_data, "set_partition", None)
            if set_part is not None:
                set_part(new_nw, new_rank)
                telemetry.event("reshard", num_workers=new_nw,
                                rank=new_rank, epoch=epoch)
            else:
                self.logger.warning(
                    "elastic: %s has no set_partition — continuing on the "
                    "old shard (duplicate/missing samples until the next "
                    "restart)", type(train_data).__name__)
        self.effective = (new_nw, new_rank)
        # 4. roll back params/optimizer-counts/RNG/iterator to the last
        # consistent step (every survivor holds the SAME snapshot: BSP
        # lockstep + a shared snapshot cadence)
        r_epoch, r_nbatch, iter_restored = guard.rollback(module, train_data)
        # 5. BSP arithmetic follows the membership: grads are summed over
        # new_nw workers now, so the effective batch changed by
        # old_nw/new_nw — keep the update scale invariant
        self._rescale_optimizer(module, old_nw, new_nw,
                                resend=new_rank == 0)
        # 6. the coordinator makes the server tier consistent with the
        # snapshot (a half-merged round was flushed server-side; some keys
        # may have committed a round the survivors rolled back past) and
        # publishes where training restarts so a joiner can enter
        if new_rank == 0:
            self._reinit_server_params(module)
            self._publish_pos(epoch, r_epoch, r_nbatch,
                              guard.last_snapshot.iter_state)
        telemetry.event(
            "elastic_reconfigured", epoch=epoch, num_workers=new_nw,
            rank=new_rank, resume_epoch=r_epoch, resume_nbatch=r_nbatch,
            step_id=kv.step_id)
        self.logger.warning(
            "elastic: reconfigured to membership epoch %d (%d worker(s), "
            "this rank shard %d/%d) — resuming at epoch %d batch %d",
            epoch, new_nw, new_rank, new_nw, r_epoch, r_nbatch)
        return r_epoch, r_nbatch, iter_restored

    def _rescale_optimizer(self, module, old_nw, new_nw, resend):
        opt = getattr(module, "_optimizer", None)
        if opt is None or old_nw == new_nw or not old_nw or not new_nw:
            return
        opt.rescale_grad = opt.rescale_grad * float(old_nw) / float(new_nw)
        if resend and getattr(module, "_update_on_kvstore", False):
            import pickle

            # replaces the server-side updater; per-key slots (momentum,
            # Adam moments) are CARRIED OVER across the swap by the server
            # (kvstore_server._set_optimizer), so no silent momentum reset
            self._kv._send_command_to_servers(0, pickle.dumps(opt))
            self.logger.warning(
                "elastic: optimizer rescaled for %d->%d workers and "
                "re-sent to the servers (server-side per-key slots are "
                "preserved across the resend)", old_nw, new_nw)

    def _reinit_server_params(self, module):
        """kInit every param key from the (post-rollback) module params —
        direct overwrite, never a merge or an optimizer step."""
        kv = self._kv
        names = module._exec_group.param_names
        arg, _ = module.get_params()
        for idx, name in enumerate(names):
            kv._zinit(idx, arg[name].asnumpy())
        self.logger.info(
            "elastic: re-seeded %d server keys from the rollback snapshot",
            len(names))

    def _publish_pos(self, mepoch, epoch, nbatch, iter_state):
        import base64

        payload = json.dumps({
            "mepoch": mepoch,   # joiners ignore a pos from an older epoch
            "epoch": epoch,
            "nbatch": nbatch,
            "iter_state": iter_state,
        }).encode()
        self._kv.registry_command(
            b"mb_pos:" + base64.b64encode(payload))

    # ---- joiner path -----------------------------------------------------
    def join(self, module, train_data):
        """Relaunched-worker entry: wait for the coordinator's published
        restart position, adopt epoch + shard, pull the current parameters,
        and return ``(begin_epoch, resume_state)`` for fit's resume
        machinery — or ``None`` when the registry reports training already
        finished (the process should exit cleanly instead of waiting for a
        rendezvous that will never come)."""
        kv = self._kv
        deadline = time.monotonic() + self._join_timeout
        while True:
            table = self.sync()
            if table.get("done"):
                self.logger.info(
                    "elastic: training already finished — nothing to rejoin")
                return None
            pos = table.get("pos")
            shard = self._shard_of(table)
            if pos is not None and shard is not None and \
                    int(pos.get("mepoch", -1)) == int(table["epoch"]):
                break
            if time.monotonic() > deadline:
                raise MXNetError(
                    "elastic: join timed out waiting for the survivors' "
                    "restart position (MXNET_ELASTIC_JOIN_TIMEOUT_S)")
            time.sleep(min(self._hb_interval / 2.0, 0.2))
        epoch = int(table["epoch"])
        # server map before epoch (same ordering as reconfigure): the
        # parameter pull below must route to the promoted primaries
        kv.adopt_server_map(table.get("smap") or [])
        kv.set_membership_epoch(epoch)
        new_nw, new_rank = shard
        old_nw = self.effective[0]
        self.effective = (new_nw, new_rank)
        set_part = getattr(train_data, "set_partition", None)
        if set_part is not None:
            set_part(new_nw, new_rank)
            telemetry.event("reshard", num_workers=new_nw, rank=new_rank,
                            epoch=epoch)
        # current params: the coordinator re-seeded the servers from its
        # snapshot before publishing pos, so this pull IS the snapshot
        self._pull_params(module)
        self._rescale_optimizer(module, old_nw, new_nw, resend=False)
        telemetry.event(
            "worker_rejoined", epoch=epoch, num_workers=new_nw,
            rank=new_rank, resume_epoch=pos["epoch"],
            resume_nbatch=pos["nbatch"], step_id=kv.step_id)
        self.logger.warning(
            "elastic: joined membership epoch %d as shard %d/%d — entering "
            "at epoch %d batch %d", epoch, new_rank, new_nw,
            pos["epoch"], pos["nbatch"])
        return int(pos["epoch"]), {"nbatch": int(pos["nbatch"]),
                                   "iter_state": pos.get("iter_state")}

    def _pull_params(self, module):
        kv = self._kv
        group = module._exec_group
        for idx, arrs in enumerate(group.param_arrays):
            kv.pull(idx, arrs, priority=-idx)
        # refresh the host dicts so checkpoints/fused uploads see the
        # pulled weights, not this process's fresh random init
        group.get_params(module._arg_params, module._aux_params)
