// Native parameter-server transport — rebuild of the reference's distributed
// KVStore backbone (reference: ps-lite ZPush/ZPull consumed by
// src/kvstore/kvstore_dist.h:88-133; server aggregation logic
// src/kvstore/kvstore_dist_server.h:136-219 — sync mode merges pushes from
// all workers then applies the updater, async applies per push; barrier via
// ps::Postoffice, kvstore_dist.h:144-146).
//
// TPU-native role: the *synchronous* data-parallel fast path on a pod uses
// XLA collectives over ICI/DCN (parallel/spmd.py), not this. This server
// exists for the reference's other semantics that collectives cannot
// express: `dist_async` (per-push updates, no lockstep), server-side
// optimizer state, and elastic worker membership — and as the host-side
// coordination plane (barriers, key init) for `dist_sync` when the trainer
// is not jit-fused.
//
// Transport: plain TCP, one connection per worker, blocking RPCs framed as
//   [uint32 type][int32 key][uint64 nbytes][payload]
// float32 payloads (the reference also ships flattened fp32 buffers,
// kvstore_dist.h:95). Multi-server sharding is done caller-side: the Python
// KVStore assigns key -> server by hash, one RecClient per server.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <fcntl.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <ctime>
#include <memory>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace mxt {

enum MsgType : uint32_t {
  kPush = 1,
  kPull = 2,
  kResp = 3,
  kBarrier = 4,
  kCommand = 6,
  kStop = 7,
  kPushPull = 8,
  kInit = 9,         // direct weight overwrite: no merge, no optimizer —
                     // elastic reconfiguration re-seeds server state from the
                     // survivors' rollback snapshot through this
  kRejectEpoch = 10, // response: request carried a stale membership epoch
};

// Reserved-negative-key split (cluster observability plane): keys in
// (kPersistentKeyMax, 0) are single-shot diagnostic slots erased after one
// pull (stats/membership publishes); keys <= kPersistentKeyMax are
// persistent per-rank telemetry slots overwritten in place and pulled by
// any number of observers. Mirrored by kvstore.py TELEMETRY_KEY_BASE.
constexpr int kPersistentKeyMax = -(1 << 20);

#pragma pack(push, 1)
struct MsgHeader {
  uint32_t type;
  int32_t key;
  uint64_t req_id;  // echoed in the response: one connection carries many
                    // outstanding RPCs (ps-lite is an async message stream;
                    // blocking per-connection RPCs head-of-line-deadlock BSP
                    // rounds across keys)
  uint64_t nbytes;
  int64_t mepoch;   // membership epoch (elastic training): the client stamps
                    // its current epoch on every request; once the server is
                    // in elastic mode a mismatch is answered kRejectEpoch so
                    // no traffic from a departed membership view can land.
                    // 0 always matches a non-elastic server.
  // Trace identity (cluster observability plane): every request carries the
  // sending worker's rank and its training step at send time, so server-side
  // per-key push/pull handling can be attributed to the worker step that
  // caused it. rank -1 = unidentified (loopback publishers, probes, the
  // registry's broadcast clients) — never recorded. Trailing fields:
  // aggregate inits that stop at mepoch zero rank/step_id, and a zero rank
  // would masquerade as worker 0, so every raw header build must set rank
  // explicitly (Send() stamps it; mxt_ps_probe sets -1).
  int32_t rank;
  int64_t step_id;
};
#pragma pack(pop)

static bool ReadAll(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t k = ::read(fd, p, n);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

static bool WriteAll(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t k = ::write(fd, p, n);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

// Server-side updater callback: (key, grad, weight, n) — mutates weight in
// place. Registered from the hosting process (Python server runs the real
// pickled optimizer through this hook, reference kvstore_server.py:36-44).
typedef void (*UpdaterFn)(int key, const float* grad, float* weight,
                          uint64_t n);
// Command callback: arbitrary control strings from workers (reference:
// KVStoreDistServer::CommandHandle, kvstore_dist_server.h:121-134 — carries
// the pickled optimizer and sync-mode switches).
typedef void (*CommandFn)(const char* cmd, uint64_t len);

class PSServer {
 public:
  PSServer(int port, int num_workers, bool sync)
      : num_workers_(num_workers), sync_(sync) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
            0 ||
        listen(listen_fd_, 128) != 0) {
      failed_ = true;
      return;
    }
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~PSServer() { Stop(); }

  void SetUpdater(UpdaterFn fn) { updater_ = fn; }
  void SetCommandHandler(CommandFn fn) { cmd_handler_ = fn; }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    // wake every blocked conn thread (sync-push/pull/barrier waits check
    // stopping_ in their predicates but need the notify to re-evaluate)
    {
      std::unique_lock<std::mutex> lk(mu_);
      for (auto& kv : entries_) kv.second->cv.notify_all();
    }
    {
      std::unique_lock<std::mutex> lk(barrier_mu_);
      barrier_cv_.notify_all();
    }
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    if (accept_thread_.joinable()) accept_thread_.join();
    std::vector<std::thread> conns;
    {
      std::unique_lock<std::mutex> lk(mu_);
      conns.swap(conn_threads_);
    }
    for (auto& t : conns) t.join();
  }

  // Block until a worker sends kStop (reference: KVStoreDistServer::Run
  // blocks in Executor::Start, kvstore_dist_server.h:33).
  void WaitStopped() {
    std::unique_lock<std::mutex> lk(stop_mu_);
    stop_cv_.wait(lk, [&] { return stop_requested_; });
  }

  bool failed() const { return failed_; }

  // Per-rank trace attribution snapshot, serialized as flat doubles
  // (exact to 2^53 — a direct C call, not the float32 wire):
  //   [rank, last_step, last_mepoch, pushes, pulls, barriers, inits] x N
  // Returns the number of doubles written (<= cap; ranks past the cap are
  // dropped — pass 7 * max_expected_ranks).
  int TraceStats(double* out, int cap) {
    std::unique_lock<std::mutex> lk(tmu_);
    int n = 0;
    for (auto& kv : trace_) {
      if (n + 7 > cap) break;
      const RankTrace& t = kv.second;
      out[n++] = static_cast<double>(kv.first);
      out[n++] = static_cast<double>(t.last_step);
      out[n++] = static_cast<double>(t.last_mepoch);
      out[n++] = static_cast<double>(t.pushes);
      out[n++] = static_cast<double>(t.pulls);
      out[n++] = static_cast<double>(t.barriers);
      out[n++] = static_cast<double>(t.inits);
    }
    return n;
  }

 private:
  struct RankTrace {
    int64_t last_step = 0;
    int64_t last_mepoch = 0;
    uint64_t pushes = 0;
    uint64_t pulls = 0;
    uint64_t barriers = 0;
    uint64_t inits = 0;
  };

  struct Entry {
    std::mutex mu;
    std::condition_variable cv;
    std::vector<float> weight;
    std::vector<float> merged;
    int pending = 0;    // pushes merged so far this round
    int64_t version = 0;  // bumped when a sync round commits
    bool inited = false;
  };

  void AcceptLoop() {
    for (;;) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return;
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::unique_lock<std::mutex> lk(mu_);
      if (stopping_) {
        ::close(fd);
        return;
      }
      conn_threads_.emplace_back([this, fd] { ConnLoop(fd); });
    }
  }

  Entry* GetEntry(int key) {
    std::unique_lock<std::mutex> lk(mu_);
    auto& e = entries_[key];
    if (!e) e.reset(new Entry());
    return e.get();
  }

  // First push for a key initializes the weight (reference: kv.init goes
  // through the same DataHandle path, kvstore_dist_server.h:149-160).
  // Returns false when the push's membership epoch is stale, or when a
  // membership reconfiguration flushed the partial BSP round this push was
  // merged into: the contribution was discarded, and the caller answers
  // kRejectEpoch so the worker rolls back instead of believing its
  // gradient landed.
  bool HandlePush(int key, Entry* e, const float* data, uint64_t n,
                  int64_t mepoch) {
    std::unique_lock<std::mutex> lk(e->mu);
    // flush_gen_ is captured FIRST, before the epoch gate: a Reconfigure()
    // racing this push (it stores epoch_/flush_gen_ under mu_, not e->mu)
    // either bumps flush_gen_ before this read — then the wait below (or
    // the gate) rejects — or after it, in which case the wait's
    // flush_gen_ != fg comparison still rejects. Capturing it after the
    // merge would let a flushed-and-discarded push be confirmed once the
    // NEW membership's round commits.
    int64_t fg = flush_gen_;
    // re-check under the entry lock: the dispatch-time gate in Handle()
    // and this merge are not atomic — Reconfigure() stores epoch_ before
    // it flushes entries, so a stale push that slipped past the gate while
    // a reconfiguration ran must be rejected HERE, or an old-membership
    // gradient could join the fresh round
    if (elastic_ && key >= 0 && mepoch != epoch_) return false;
    if (!e->inited || key < 0) {
      // first push initializes; negative (diagnostic) keys ALWAYS take
      // this overwrite path — BSP merge semantics never apply to reserved
      // slots, so a reused or stale diagnostic key can neither join a
      // merge round nor block its publisher waiting for num_workers_
      // pushes (reserved-key sequences wrap, kvstore.py/mxtop.py)
      e->weight.assign(data, data + n);
      e->inited = true;
      e->version++;
      e->cv.notify_all();
      return true;
    }
    if (e->weight.size() != n) e->weight.resize(n, 0.f);
    if (!sync_) {  // async: apply immediately (dist_server.h:199-207)
      ApplyLocked(key, e, data, n);
      return true;
    }
    // sync: merge; the worker completing the round applies + commits
    if (e->merged.size() != n) e->merged.assign(n, 0.f);
    for (uint64_t i = 0; i < n; ++i) e->merged[i] += data[i];
    e->pending++;
    if (e->pending >= num_workers_) {
      ApplyLocked(key, e, e->merged.data(), n);
      e->merged.assign(n, 0.f);
      e->pending = 0;
      e->version++;
      e->cv.notify_all();
      return true;
    }
    int64_t v = e->version;
    e->cv.wait(lk, [&] {
      return e->version != v || flush_gen_ != fg || stopping_;
    });
    return flush_gen_ == fg;
  }

  // Elastic membership reconfiguration (command "mepoch:<epoch>:<workers>",
  // sent by the membership registry to every server): adopt the new epoch +
  // worker count, discard every partially merged BSP round, and wake blocked
  // pushers/barrier-waiters with a rejection — the survivors roll back to a
  // consistent step and re-push, so a half-merged round from the old
  // membership must never commit.
  void Reconfigure(int64_t epoch, int workers) {
    {
      std::unique_lock<std::mutex> lk(mu_);
      epoch_ = epoch;
      if (workers > 0) num_workers_ = workers;
      flush_gen_++;
      for (auto& kv : entries_) {
        Entry* e = kv.second.get();
        std::unique_lock<std::mutex> elk(e->mu);
        e->merged.assign(e->merged.size(), 0.f);
        e->pending = 0;
        e->cv.notify_all();
      }
    }
    {
      std::unique_lock<std::mutex> lk(barrier_mu_);
      barrier_count_ = 0;
      barrier_flush_++;
      barrier_cv_.notify_all();
    }
  }

  void ApplyLocked(int key, Entry* e, const float* grad, uint64_t n) {
    if (updater_) {
      updater_(key, grad, e->weight.data(), n);
    } else {
      // no updater: store the merged value (dist_server.h else-branch —
      // update_on_kvstore=False workers pull merged grads back)
      memcpy(e->weight.data(), grad, n * sizeof(float));
    }
  }

  // One reader per connection; each request dispatches to its own handler
  // thread so a BSP-blocked push never blocks later requests on the same
  // connection (ps-lite's async stream semantics). Responses serialize on a
  // per-connection write mutex and carry the request id.
  struct Conn {
    int fd;
    std::mutex wmu;
    std::mutex hmu;
    std::condition_variable hcv;
    int inflight = 0;
  };

  void Respond(Conn* c, const MsgHeader& h, const void* payload) {
    std::unique_lock<std::mutex> lk(c->wmu);
    WriteAll(c->fd, &h, sizeof(h));
    if (h.nbytes && payload) WriteAll(c->fd, payload, h.nbytes);
  }

  // Trace identity: per-rank attribution of data-path handling. Recorded
  // BEFORE the epoch gate so a rejected request still updates the rank's
  // last-seen step — the whole point is knowing where a worker WAS when
  // its traffic stopped landing. Diagnostic traffic (negative keys) is not
  // counted: a stats poll must not read as training progress.
  void RecordTrace(const MsgHeader& h) {
    if (h.rank < 0) return;
    bool data_key = h.key >= 0;
    std::unique_lock<std::mutex> lk(tmu_);
    RankTrace& t = trace_[h.rank];
    t.last_step = h.step_id;
    t.last_mepoch = h.mepoch;
    switch (h.type) {
      case kPush:
        if (data_key) t.pushes++;
        break;
      case kPull:
        if (data_key) t.pulls++;
        break;
      case kPushPull:
        if (data_key) {
          t.pushes++;
          t.pulls++;
        }
        break;
      case kInit:
        if (data_key) t.inits++;
        break;
      case kBarrier:
        t.barriers++;
        break;
      default:
        break;
    }
  }

  void Handle(Conn* c, MsgHeader h, std::vector<float> buf, std::string cmd) {
    if (h.type == kPush || h.type == kPull || h.type == kPushPull ||
        h.type == kBarrier || h.type == kInit) {
      RecordTrace(h);
    }
    // membership-epoch gate (elastic mode only; negative keys are the
    // reserved diagnostic slots — stats/membership self-publish — and stay
    // reachable from any epoch, or a stale worker could never resync)
    if (elastic_ && h.key >= 0 &&
        (h.type == kPush || h.type == kPull || h.type == kPushPull ||
         h.type == kBarrier || h.type == kInit) &&
        h.mepoch != epoch_) {
      Respond(c, MsgHeader{kRejectEpoch, h.key, h.req_id, 0, epoch_, -1, 0},
              nullptr);
      std::unique_lock<std::mutex> lk(c->hmu);
      if (--c->inflight == 0) c->hcv.notify_all();
      return;
    }
    switch (h.type) {
      case kPush: {
        Entry* e = GetEntry(h.key);
        bool ok = HandlePush(h.key, e, buf.data(), buf.size(), h.mepoch);
        Respond(c, MsgHeader{ok ? kResp : kRejectEpoch, h.key, h.req_id, 0,
                             epoch_, -1, 0},
                nullptr);
        break;
      }
      case kInit: {
        // direct overwrite: no merge/optimizer and — deliberately — no
        // version bump or notify. A pending partial round keeps waiting:
        // the elastic protocol sends kInit after the reconfigure flush and
        // before the coordinator's first push, so waking merged-but-blocked
        // pushers here would return their pushes before a round committed.
        Entry* e = GetEntry(h.key);
        std::unique_lock<std::mutex> lk(e->mu);
        if (elastic_ && h.key >= 0 && h.mepoch != epoch_) {
          // same lock-held re-check as HandlePush: an overwrite from a
          // membership that ended mid-dispatch must not land
          lk.unlock();
          Respond(c, MsgHeader{kRejectEpoch, h.key, h.req_id, 0, epoch_, -1, 0},
                  nullptr);
          break;
        }
        e->weight.assign(buf.data(), buf.data() + buf.size());
        e->inited = true;
        lk.unlock();
        Respond(c, MsgHeader{kResp, h.key, h.req_id, 0, epoch_, -1, 0}, nullptr);
        break;
      }
      case kPull: {
        // no blocking on un-inited keys: init is barriered by the caller
        // (kvstore.py init), so an empty entry is a user error — a 0-byte
        // response lets the client raise instead of wedging
        Entry* e = GetEntry(h.key);
        std::unique_lock<std::mutex> lk(e->mu);
        std::vector<float> w = e->weight;  // copy under lock, send outside
        lk.unlock();
        Respond(c, MsgHeader{kResp, h.key, h.req_id,
                             static_cast<uint64_t>(w.size() * sizeof(float)),
                             0, -1, 0},
                w.data());
        if (h.key < 0 && h.key > kPersistentKeyMax) {
          // negative keys are reserved single-shot diagnostic slots (the
          // stats_to self-publish, kvstore_server.py): exactly one reader
          // pulls each once, so erase after serving — without this every
          // stats poll would permanently leak one Entry per server.
          // Keys at or below kPersistentKeyMax are PERSISTENT telemetry
          // slots (one per worker rank — bounded by cluster size, kvstore.py
          // TELEMETRY_KEY_BASE): each worker kInit-overwrites its own slot
          // periodically and any number of observers (cluster_stats,
          // tools/mxtop.py) pull it repeatedly, so these survive the pull.
          std::unique_lock<std::mutex> mlk(mu_);
          entries_.erase(h.key);
        }
        break;
      }
      case kPushPull: {
        Entry* e = GetEntry(h.key);
        if (!HandlePush(h.key, e, buf.data(), buf.size(), h.mepoch)) {
          Respond(c, MsgHeader{kRejectEpoch, h.key, h.req_id, 0, epoch_, -1, 0},
                  nullptr);
          break;
        }
        std::unique_lock<std::mutex> lk(e->mu);
        std::vector<float> w = e->weight;
        lk.unlock();
        Respond(c, MsgHeader{kResp, h.key, h.req_id,
                             static_cast<uint64_t>(w.size() * sizeof(float)),
                             0, -1, 0},
                w.data());
        break;
      }
      case kBarrier: {
        std::unique_lock<std::mutex> lk(barrier_mu_);
        // lock-held epoch re-check (see HandlePush): a stale arrival after
        // Reconfigure() reset barrier_count_ must not count toward — or
        // prematurely release — the new membership's smaller rendezvous
        if (elastic_ && h.mepoch != epoch_) {
          lk.unlock();
          Respond(c, MsgHeader{kRejectEpoch, 0, h.req_id, 0, epoch_, -1, 0},
                  nullptr);
          break;
        }
        int64_t gen = barrier_gen_;
        int64_t bfg = barrier_flush_;
        bool ok = true;
        if (++barrier_count_ >= num_workers_) {
          barrier_count_ = 0;
          barrier_gen_++;
          barrier_cv_.notify_all();
        } else {
          barrier_cv_.wait(lk, [&] {
            return barrier_gen_ != gen || barrier_flush_ != bfg || stopping_;
          });
          // a reconfiguration flushed this rendezvous: the membership the
          // waiter was synchronizing with no longer exists
          ok = barrier_flush_ == bfg;
        }
        lk.unlock();
        Respond(c, MsgHeader{ok ? kResp : kRejectEpoch, 0, h.req_id, 0,
                             epoch_, -1, 0},
                nullptr);
        break;
      }
      case kCommand: {
        if (cmd.rfind("sync:", 0) == 0) sync_ = cmd[5] == '1';
        if (cmd.rfind("elastic:", 0) == 0) elastic_ = cmd[8] == '1';
        if (cmd.rfind("mepoch:", 0) == 0) {
          long long e = 0;
          int w = 0;
          if (sscanf(cmd.c_str() + 7, "%lld:%d", &e, &w) == 2)
            Reconfigure(e, w);
        }
        if (cmd_handler_) cmd_handler_(cmd.data(), cmd.size());
        Respond(c, MsgHeader{kResp, 0, h.req_id, 0, 0, -1, 0}, nullptr);
        break;
      }
      default:
        break;
    }
    std::unique_lock<std::mutex> lk(c->hmu);
    if (--c->inflight == 0) c->hcv.notify_all();
  }

  void ConnLoop(int fd) {
    Conn conn;
    conn.fd = fd;
    for (;;) {
      MsgHeader h;
      if (!ReadAll(fd, &h, sizeof(h))) break;
      if (h.type == kStop) {
        Respond(&conn, MsgHeader{kResp, 0, h.req_id, 0, 0, -1, 0}, nullptr);
        std::unique_lock<std::mutex> lk(stop_mu_);
        stop_requested_ = true;
        stop_cv_.notify_all();
        break;
      }
      std::vector<float> buf;
      std::string cmd;
      if (h.type == kPush || h.type == kPushPull || h.type == kInit) {
        buf.resize(h.nbytes / sizeof(float));
        if (h.nbytes && !ReadAll(fd, buf.data(), h.nbytes)) break;
      } else if (h.type == kCommand) {
        cmd.resize(h.nbytes);
        if (h.nbytes && !ReadAll(fd, &cmd[0], h.nbytes)) break;
      }
      {
        std::unique_lock<std::mutex> lk(conn.hmu);
        conn.inflight++;
      }
      // detached: a long-lived worker connection makes millions of RPCs, so
      // retaining joinable threads until teardown would accumulate without
      // bound; the inflight counter below is the (only) join point, and it
      // is reached before `conn` goes out of scope.
      std::thread(&PSServer::Handle, this, &conn, h, std::move(buf),
                  std::move(cmd))
          .detach();
    }
    {  // drain outstanding handlers before closing the socket
      std::unique_lock<std::mutex> lk(conn.hmu);
      conn.hcv.wait(lk, [&] { return conn.inflight == 0; });
    }
    ::close(fd);
  }

  int listen_fd_ = -1;
  std::atomic<int> num_workers_;
  std::atomic<bool> sync_{true};
  std::atomic<bool> stopping_{false};
  // elastic membership: epoch checked on data-path requests once elastic_
  // is switched on; flush_gen_/barrier_flush_ invalidate in-flight BSP
  // rounds and barriers across a reconfiguration
  std::atomic<bool> elastic_{false};
  std::atomic<int64_t> epoch_{0};
  std::atomic<int64_t> flush_gen_{0};
  int64_t barrier_flush_ = 0;  // guarded by barrier_mu_
  bool failed_ = false;
  std::thread accept_thread_;
  std::mutex mu_;
  std::map<int, std::unique_ptr<Entry>> entries_;
  std::mutex tmu_;  // guards trace_ (bumped on conn handler threads)
  std::map<int, RankTrace> trace_;
  std::vector<std::thread> conn_threads_;
  std::mutex barrier_mu_;
  std::condition_variable barrier_cv_;
  int barrier_count_ = 0;
  int64_t barrier_gen_ = 0;
  std::mutex stop_mu_;
  std::condition_variable stop_cv_;
  bool stop_requested_ = false;
  UpdaterFn updater_ = nullptr;
  CommandFn cmd_handler_ = nullptr;

  // PSServer is non-copyable
  PSServer(const PSServer&) = delete;
  PSServer& operator=(const PSServer&) = delete;
};

class PSClient {
 public:
  // attempts × 100ms bounds the connect retry: the default (600 = 60s)
  // covers the worker-before-server launch race; replication / failover
  // reconnect paths pass a small budget so a dead peer costs a bounded
  // wait, not a minute per round.
  PSClient(const char* host, int port, int attempts = 600) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    inet_pton(AF_INET, host, &addr.sin_addr);
    // retry: workers may start before the server (launch.py races too)
    for (int attempt = 0; attempt < attempts; ++attempt) {
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) ==
          0) {
        int one = 1;
        setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        reader_ = std::thread([this] { ReaderLoop(); });
        return;
      }
      ::close(fd_);
      struct timespec ts = {0, 100 * 1000 * 1000};
      nanosleep(&ts, nullptr);
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    }
    ::close(fd_);
    fd_ = -1;
  }

  ~PSClient() {
    if (fd_ >= 0) {
      ::shutdown(fd_, SHUT_RDWR);
      if (reader_.joinable()) reader_.join();
      ::close(fd_);
    }
  }

  bool ok() const { return fd_ >= 0; }

  // True once the reader observed a socket failure: every outstanding and
  // future RPC on this handle fails. The HA tier uses this to decide which
  // client handles to rebuild after adopting a new key→server map.
  bool IsDead() {
    std::unique_lock<std::mutex> lk(pmu_);
    return dead_;
  }

  // Membership epoch stamped on every subsequent request (elastic mode);
  // adopted by the Python tier after a registry sync.
  void SetEpoch(int64_t e) { epoch_ = e; }
  int64_t GetEpoch() const { return epoch_; }

  // Trace identity stamped on every subsequent request: the worker's rank
  // (set once at store construction; stays -1 = unidentified on loopback/
  // observer clients so they never pollute per-rank attribution) and its
  // current training step (the fit loop bumps it each batch).
  void SetIdentity(int rank) { rank_ = rank; }
  void SetStep(int64_t s) { step_ = s; }

  // 0 ok, -1 transport failure, -2 stale membership epoch
  int Push(int key, const float* data, uint64_t n) {
    Pending p;
    if (!Send(kPush, key, &p, data, n * sizeof(float))) return -1;
    int64_t r = Await(&p);
    return r >= 0 ? 0 : static_cast<int>(r);
  }

  // Direct weight overwrite (kInit): bypasses merge + optimizer. Same
  // result convention as Push.
  int Init(int key, const float* data, uint64_t n) {
    Pending p;
    if (!Send(kInit, key, &p, data, n * sizeof(float))) return -1;
    int64_t r = Await(&p);
    return r >= 0 ? 0 : static_cast<int>(r);
  }

  // Pull into caller buffer of capacity cap floats; returns #floats or -1.
  int64_t Pull(int key, float* out, uint64_t cap) {
    Pending p;
    p.out = out;
    p.cap = cap;
    if (!Send(kPull, key, &p, nullptr, 0)) return -1;
    return Await(&p);
  }

  int64_t PushPull(int key, const float* data, uint64_t n, float* out,
                   uint64_t cap) {
    Pending p;
    p.out = out;
    p.cap = cap;
    if (!Send(kPushPull, key, &p, data, n * sizeof(float))) return -1;
    return Await(&p);
  }

  // 0 ok, -1 transport failure, -2 membership reconfiguration flushed it
  int Barrier() {
    Pending p;
    if (!Send(kBarrier, 0, &p, nullptr, 0)) return -1;
    int64_t r = Await(&p);
    return r >= 0 ? 0 : static_cast<int>(r);
  }

  bool Command(const char* cmd) {
    Pending p;
    if (!Send(kCommand, 0, &p, cmd, strlen(cmd))) return false;
    return Await(&p) >= 0;
  }

  // Liveness probe: a command round-trip with a deadline. A wedged server
  // (socket open, not responding) must yield false, not a hang — the one
  // case get_num_dead_node exists for (reference: ps-lite heartbeats).
  bool CommandTimeout(const char* cmd, int timeout_ms) {
    Pending p;
    uint64_t id = 0;
    if (!Send(kCommand, 0, &p, cmd, strlen(cmd), &id)) return false;
    std::unique_lock<std::mutex> lk(p.mu);
    if (p.cv.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                      [&] { return p.done; }))
      return p.result >= 0;
    lk.unlock();
    bool removed;
    {
      std::unique_lock<std::mutex> plk(pmu_);
      removed = pending_.erase(id) > 0;
    }
    lk.lock();
    if (removed) return false;  // reader never saw a response; p is ours again
    // the reader popped p and is mid-fill: the response arrived, wait for the
    // signal (prompt — payload for command responses is empty)
    p.cv.wait(lk, [&] { return p.done; });
    return p.result >= 0;
  }

  bool Stop() {
    Pending p;
    if (!Send(kStop, 0, &p, nullptr, 0)) return false;
    return Await(&p) >= 0;
  }

 private:
  // One outstanding RPC registration: the reader thread fills result/copies
  // payload and signals. Many may be in flight on the single socket.
  struct Pending {
    std::mutex mu;
    std::condition_variable cv;
    bool done = false;
    int64_t result = -1;  // #floats (or 0) on success, -1 on failure
    float* out = nullptr;
    uint64_t cap = 0;
  };

  bool Send(uint32_t type, int key, Pending* p, const void* payload,
            uint64_t nbytes, uint64_t* out_id = nullptr) {
    if (fd_ < 0) return false;
    uint64_t id;
    {
      std::unique_lock<std::mutex> lk(pmu_);
      if (dead_) return false;
      id = next_id_++;
      pending_[id] = p;
    }
    if (out_id) *out_id = id;
    MsgHeader h{type, key, id, nbytes, epoch_.load(), rank_.load(),
                step_.load()};
    std::unique_lock<std::mutex> lk(wmu_);
    if (!WriteAll(fd_, &h, sizeof(h)) ||
        (nbytes && !WriteAll(fd_, payload, nbytes))) {
      lk.unlock();
      std::unique_lock<std::mutex> plk(pmu_);
      pending_.erase(id);
      return false;
    }
    return true;
  }

  int64_t Await(Pending* p) {
    std::unique_lock<std::mutex> lk(p->mu);
    p->cv.wait(lk, [&] { return p->done; });
    return p->result;
  }

  void ReaderLoop() {
    std::vector<float> scratch;
    for (;;) {
      MsgHeader h;
      if (!ReadAll(fd_, &h, sizeof(h))) break;
      Pending* p = nullptr;
      {
        std::unique_lock<std::mutex> lk(pmu_);
        auto it = pending_.find(h.req_id);
        if (it != pending_.end()) {
          p = it->second;
          pending_.erase(it);
        }
      }
      uint64_t n = h.nbytes / sizeof(float);
      // kRejectEpoch carries no payload: -2 distinguishes a membership
      // rejection (deterministic, never retried) from a transport -1
      int64_t result =
          h.type == kRejectEpoch ? -2 : static_cast<int64_t>(n);
      bool read_ok = true;
      if (p && p->out && n) {
        if (n <= p->cap) {
          read_ok = ReadAll(fd_, p->out, h.nbytes);
        } else {  // drain oversized payload, report true size
          scratch.resize(n);
          read_ok = ReadAll(fd_, scratch.data(), h.nbytes);
          if (read_ok) memcpy(p->out, scratch.data(), p->cap * sizeof(float));
        }
      } else if (n) {
        scratch.resize(n);
        read_ok = ReadAll(fd_, scratch.data(), h.nbytes);
      }
      if (p) {
        // p was already popped from pending_, so the failure sweep below
        // cannot see it — signal (with -1 on a failed payload read) here
        std::unique_lock<std::mutex> lk(p->mu);
        p->done = true;
        p->result = read_ok ? result : -1;
        p->cv.notify_all();
      }
      if (!read_ok) break;
    }
    // socket failed/closed: fail every outstanding + future RPC
    std::unique_lock<std::mutex> lk(pmu_);
    dead_ = true;
    for (auto& kv : pending_) {
      std::unique_lock<std::mutex> plk(kv.second->mu);
      kv.second->done = true;
      kv.second->result = -1;
      kv.second->cv.notify_all();
    }
    pending_.clear();
  }

  int fd_ = -1;
  std::atomic<int64_t> epoch_{0};
  std::atomic<int> rank_{-1};
  std::atomic<int64_t> step_{0};
  std::thread reader_;
  std::mutex wmu_;   // serializes frame writes
  std::mutex pmu_;   // guards pending_/next_id_/dead_
  std::map<uint64_t, Pending*> pending_;
  uint64_t next_id_ = 1;
  bool dead_ = false;
};

}  // namespace mxt

extern "C" {

void* mxt_ps_server_create(int port, int num_workers, int sync) {
  auto* s = new mxt::PSServer(port, num_workers, sync != 0);
  if (s->failed()) {
    delete s;
    return nullptr;
  }
  return s;
}
void mxt_ps_server_set_updater(void* h, mxt::UpdaterFn fn) {
  static_cast<mxt::PSServer*>(h)->SetUpdater(fn);
}
void mxt_ps_server_set_command_handler(void* h, mxt::CommandFn fn) {
  static_cast<mxt::PSServer*>(h)->SetCommandHandler(fn);
}
void mxt_ps_server_wait(void* h) {
  static_cast<mxt::PSServer*>(h)->WaitStopped();
}
// Per-rank trace attribution (cluster observability): flat doubles
// [rank, last_step, last_mepoch, pushes, pulls, barriers, inits] x N;
// returns the number of doubles written.
int mxt_ps_server_trace_stats(void* h, double* out, int cap) {
  return static_cast<mxt::PSServer*>(h)->TraceStats(out, cap);
}
void mxt_ps_server_destroy(void* h) { delete static_cast<mxt::PSServer*>(h); }

void* mxt_ps_client_create(const char* host, int port) {
  auto* c = new mxt::PSClient(host, port);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}
// HA reconnect path: bounded connect budget (attempts × 100ms) so dialing
// a still-dead server costs a deterministic wait, not the 60s launch-race
// budget of mxt_ps_client_create.
void* mxt_ps_client_create2(const char* host, int port, int attempts) {
  auto* c = new mxt::PSClient(host, port, attempts);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}
int mxt_ps_client_is_dead(void* h) {
  return static_cast<mxt::PSClient*>(h)->IsDead() ? 1 : 0;
}
int mxt_ps_client_push(void* h, int key, const float* data,
                       unsigned long long n) {
  return static_cast<mxt::PSClient*>(h)->Push(key, data, n);
}
// Elastic membership surface: direct weight overwrite (reconfiguration
// re-seed), and the epoch stamped on every request from this client.
int mxt_ps_client_init(void* h, int key, const float* data,
                       unsigned long long n) {
  return static_cast<mxt::PSClient*>(h)->Init(key, data, n);
}
void mxt_ps_client_set_epoch(void* h, long long epoch) {
  static_cast<mxt::PSClient*>(h)->SetEpoch(epoch);
}
// Trace identity (cluster observability): rank set once per worker store,
// step bumped by the fit loop each batch.
void mxt_ps_client_set_identity(void* h, int rank) {
  static_cast<mxt::PSClient*>(h)->SetIdentity(rank);
}
void mxt_ps_client_set_step(void* h, long long step) {
  static_cast<mxt::PSClient*>(h)->SetStep(step);
}
long long mxt_ps_client_get_epoch(void* h) {
  return static_cast<mxt::PSClient*>(h)->GetEpoch();
}
long long mxt_ps_client_pull(void* h, int key, float* out,
                             unsigned long long cap) {
  return static_cast<mxt::PSClient*>(h)->Pull(key, out, cap);
}
long long mxt_ps_client_pushpull(void* h, int key, const float* data,
                                 unsigned long long n, float* out,
                                 unsigned long long cap) {
  return static_cast<mxt::PSClient*>(h)->PushPull(key, data, n, out, cap);
}
int mxt_ps_client_barrier(void* h) {
  return static_cast<mxt::PSClient*>(h)->Barrier();
}
int mxt_ps_client_command(void* h, const char* cmd) {
  return static_cast<mxt::PSClient*>(h)->Command(cmd) ? 0 : -1;
}
int mxt_ps_client_probe(void* h, const char* cmd, int timeout_ms) {
  return static_cast<mxt::PSClient*>(h)->CommandTimeout(cmd, timeout_ms) ? 0 : -1;
}

// Standalone liveness probe on a FRESH connection with a deadline on every
// phase (connect, send, receive). Unlike client_probe it cannot block on the
// shared client socket's write mutex when a bulk Push has wedged — the
// failure mode a liveness check exists to detect.
int mxt_ps_probe(const char* host, int port, int timeout_ms) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  fcntl(fd, F_SETFL, fcntl(fd, F_GETFL, 0) | O_NONBLOCK);
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  inet_pton(AF_INET, host, &addr.sin_addr);
  int rc = ::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
  if (rc != 0 && errno != EINPROGRESS) {
    ::close(fd);
    return -1;
  }
  auto wait_io = [&](short events) {
    pollfd p{fd, events, 0};
    return ::poll(&p, 1, timeout_ms) == 1 && !(p.revents & (POLLERR | POLLHUP));
  };
  if (rc != 0) {
    if (!wait_io(POLLOUT)) {
      ::close(fd);
      return -1;
    }
    int err = 0;
    socklen_t len = sizeof(err);
    if (getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len) != 0 || err != 0) {
      ::close(fd);
      return -1;
    }
  }
  const char ping[] = "ping";
  mxt::MsgHeader h{mxt::kCommand, 0, 1, sizeof(ping) - 1, 0, -1, 0};
  char buf[sizeof(h) + sizeof(ping) - 1];
  memcpy(buf, &h, sizeof(h));
  memcpy(buf + sizeof(h), ping, sizeof(ping) - 1);
  size_t sent = 0;
  while (sent < sizeof(buf)) {
    if (!wait_io(POLLOUT)) {
      ::close(fd);
      return -1;
    }
    ssize_t n = ::send(fd, buf + sent, sizeof(buf) - sent, MSG_NOSIGNAL);
    if (n <= 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
      ::close(fd);
      return -1;
    }
    if (n > 0) sent += static_cast<size_t>(n);
  }
  mxt::MsgHeader resp;
  size_t got = 0;
  while (got < sizeof(resp)) {
    if (!wait_io(POLLIN)) {
      ::close(fd);
      return -1;
    }
    ssize_t n = ::recv(fd, reinterpret_cast<char*>(&resp) + got,
                       sizeof(resp) - got, 0);
    if (n == 0 || (n < 0 && errno != EAGAIN && errno != EWOULDBLOCK)) {
      ::close(fd);  // n == 0: peer closed before responding
      return -1;
    }
    if (n > 0) got += static_cast<size_t>(n);
  }
  ::close(fd);
  return resp.type == mxt::kResp ? 0 : -1;
}
int mxt_ps_client_stop(void* h) {
  return static_cast<mxt::PSClient*>(h)->Stop() ? 0 : -1;
}
void mxt_ps_client_destroy(void* h) { delete static_cast<mxt::PSClient*>(h); }

}  // extern "C"
