// Native parameter-server transport — rebuild of the reference's distributed
// KVStore backbone (reference: ps-lite ZPush/ZPull consumed by
// src/kvstore/kvstore_dist.h:88-133; server aggregation logic
// src/kvstore/kvstore_dist_server.h:136-219 — sync mode merges pushes from
// all workers then applies the updater, async applies per push; barrier via
// ps::Postoffice, kvstore_dist.h:144-146).
//
// TPU-native role: the *synchronous* data-parallel fast path on a pod uses
// XLA collectives over ICI/DCN (parallel/spmd.py), not this. This server
// exists for the reference's other semantics that collectives cannot
// express: `dist_async` (per-push updates, no lockstep), server-side
// optimizer state, and elastic worker membership — and as the host-side
// coordination plane (barriers, key init) for `dist_sync` when the trainer
// is not jit-fused.
//
// Transport: plain TCP, one connection per worker, blocking RPCs framed as
//   [uint32 type][int32 key][uint64 nbytes][payload]
// float32 payloads (the reference also ships flattened fp32 buffers,
// kvstore_dist.h:95). Multi-server sharding is done caller-side: the Python
// KVStore assigns key -> server by hash, one RecClient per server.

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <memory>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace mxt {

enum MsgType : uint32_t {
  kPush = 1,
  kPull = 2,
  kResp = 3,
  kBarrier = 4,
  kCommand = 6,
  kStop = 7,
  kPushPull = 8,
};

#pragma pack(push, 1)
struct MsgHeader {
  uint32_t type;
  int32_t key;
  uint64_t nbytes;
};
#pragma pack(pop)

static bool ReadAll(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t k = ::read(fd, p, n);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

static bool WriteAll(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t k = ::write(fd, p, n);
    if (k <= 0) return false;
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

// Server-side updater callback: (key, grad, weight, n) — mutates weight in
// place. Registered from the hosting process (Python server runs the real
// pickled optimizer through this hook, reference kvstore_server.py:36-44).
typedef void (*UpdaterFn)(int key, const float* grad, float* weight,
                          uint64_t n);

class PSServer {
 public:
  PSServer(int port, int num_workers, bool sync)
      : num_workers_(num_workers), sync_(sync) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    int one = 1;
    setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_ANY);
    addr.sin_port = htons(static_cast<uint16_t>(port));
    if (bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) !=
            0 ||
        listen(listen_fd_, 128) != 0) {
      failed_ = true;
      return;
    }
    accept_thread_ = std::thread([this] { AcceptLoop(); });
  }

  ~PSServer() { Stop(); }

  void SetUpdater(UpdaterFn fn) { updater_ = fn; }

  void Stop() {
    bool expected = false;
    if (!stopping_.compare_exchange_strong(expected, true)) return;
    // wake every blocked conn thread (sync-push/pull/barrier waits check
    // stopping_ in their predicates but need the notify to re-evaluate)
    {
      std::unique_lock<std::mutex> lk(mu_);
      for (auto& kv : entries_) kv.second->cv.notify_all();
    }
    {
      std::unique_lock<std::mutex> lk(barrier_mu_);
      barrier_cv_.notify_all();
    }
    ::shutdown(listen_fd_, SHUT_RDWR);
    ::close(listen_fd_);
    if (accept_thread_.joinable()) accept_thread_.join();
    std::vector<std::thread> conns;
    {
      std::unique_lock<std::mutex> lk(mu_);
      conns.swap(conn_threads_);
    }
    for (auto& t : conns) t.join();
  }

  // Block until a worker sends kStop (reference: KVStoreDistServer::Run
  // blocks in Executor::Start, kvstore_dist_server.h:33).
  void WaitStopped() {
    std::unique_lock<std::mutex> lk(stop_mu_);
    stop_cv_.wait(lk, [&] { return stop_requested_; });
  }

  bool failed() const { return failed_; }

 private:
  struct Entry {
    std::mutex mu;
    std::condition_variable cv;
    std::vector<float> weight;
    std::vector<float> merged;
    int pending = 0;    // pushes merged so far this round
    int64_t version = 0;  // bumped when a sync round commits
    bool inited = false;
  };

  void AcceptLoop() {
    for (;;) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return;
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      std::unique_lock<std::mutex> lk(mu_);
      if (stopping_) {
        ::close(fd);
        return;
      }
      conn_threads_.emplace_back([this, fd] { ConnLoop(fd); });
    }
  }

  Entry* GetEntry(int key) {
    std::unique_lock<std::mutex> lk(mu_);
    auto& e = entries_[key];
    if (!e) e.reset(new Entry());
    return e.get();
  }

  // First push for a key initializes the weight (reference: kv.init goes
  // through the same DataHandle path, kvstore_dist_server.h:149-160).
  void HandlePush(int key, Entry* e, const float* data, uint64_t n) {
    std::unique_lock<std::mutex> lk(e->mu);
    if (!e->inited) {
      e->weight.assign(data, data + n);
      e->inited = true;
      e->version++;
      e->cv.notify_all();
      return;
    }
    if (e->weight.size() != n) e->weight.resize(n, 0.f);
    if (!sync_) {  // async: apply immediately (dist_server.h:199-207)
      ApplyLocked(key, e, data, n);
      return;
    }
    // sync: merge; the worker completing the round applies + commits
    if (e->merged.size() != n) e->merged.assign(n, 0.f);
    for (uint64_t i = 0; i < n; ++i) e->merged[i] += data[i];
    e->pending++;
    if (e->pending >= num_workers_) {
      ApplyLocked(key, e, e->merged.data(), n);
      e->merged.assign(n, 0.f);
      e->pending = 0;
      e->version++;
      e->cv.notify_all();
    } else {
      int64_t v = e->version;
      e->cv.wait(lk, [&] { return e->version != v || stopping_; });
    }
  }

  void ApplyLocked(int key, Entry* e, const float* grad, uint64_t n) {
    if (updater_) {
      updater_(key, grad, e->weight.data(), n);
    } else {
      // no updater: store the merged value (dist_server.h else-branch —
      // update_on_kvstore=False workers pull merged grads back)
      memcpy(e->weight.data(), grad, n * sizeof(float));
    }
  }

  void ConnLoop(int fd) {
    std::vector<float> buf;
    for (;;) {
      MsgHeader h;
      if (!ReadAll(fd, &h, sizeof(h))) break;
      if (h.type == kStop) {
        MsgHeader r{kResp, 0, 0};
        WriteAll(fd, &r, sizeof(r));
        std::unique_lock<std::mutex> lk(stop_mu_);
        stop_requested_ = true;
        stop_cv_.notify_all();
        break;
      }
      switch (h.type) {
        case kPush: {
          uint64_t n = h.nbytes / sizeof(float);
          buf.resize(n);
          if (!ReadAll(fd, buf.data(), h.nbytes)) return CloseFd(fd);
          Entry* e = GetEntry(h.key);
          HandlePush(h.key, e, buf.data(), n);
          MsgHeader r{kResp, h.key, 0};
          if (!WriteAll(fd, &r, sizeof(r))) return CloseFd(fd);
          break;
        }
        case kPull: {
          Entry* e = GetEntry(h.key);
          std::unique_lock<std::mutex> lk(e->mu);
          e->cv.wait(lk, [&] { return e->inited || stopping_; });
          MsgHeader r{kResp, h.key,
                      static_cast<uint64_t>(e->weight.size() * sizeof(float))};
          if (!WriteAll(fd, &r, sizeof(r))) return CloseFd(fd);
          if (!WriteAll(fd, e->weight.data(), r.nbytes)) return CloseFd(fd);
          break;
        }
        case kPushPull: {  // fused push+pull round trip (saves one RTT)
          uint64_t n = h.nbytes / sizeof(float);
          buf.resize(n);
          if (!ReadAll(fd, buf.data(), h.nbytes)) return CloseFd(fd);
          Entry* e = GetEntry(h.key);
          HandlePush(h.key, e, buf.data(), n);
          std::unique_lock<std::mutex> lk(e->mu);
          MsgHeader r{kResp, h.key,
                      static_cast<uint64_t>(e->weight.size() * sizeof(float))};
          if (!WriteAll(fd, &r, sizeof(r))) return CloseFd(fd);
          if (!WriteAll(fd, e->weight.data(), r.nbytes)) return CloseFd(fd);
          break;
        }
        case kBarrier: {
          std::unique_lock<std::mutex> lk(barrier_mu_);
          int64_t gen = barrier_gen_;
          if (++barrier_count_ >= num_workers_) {
            barrier_count_ = 0;
            barrier_gen_++;
            barrier_cv_.notify_all();
          } else {
            barrier_cv_.wait(
                lk, [&] { return barrier_gen_ != gen || stopping_; });
          }
          MsgHeader r{kResp, 0, 0};
          if (!WriteAll(fd, &r, sizeof(r))) return CloseFd(fd);
          break;
        }
        case kCommand: {
          std::string cmd(h.nbytes, '\0');
          if (h.nbytes && !ReadAll(fd, &cmd[0], h.nbytes)) return CloseFd(fd);
          if (cmd.rfind("sync:", 0) == 0) sync_ = cmd[5] == '1';
          MsgHeader r{kResp, 0, 0};
          if (!WriteAll(fd, &r, sizeof(r))) return CloseFd(fd);
          break;
        }
        default:
          return CloseFd(fd);
      }
    }
    ::close(fd);
  }

  static void CloseFd(int fd) { ::close(fd); }

  int listen_fd_ = -1;
  int num_workers_;
  std::atomic<bool> sync_{true};
  std::atomic<bool> stopping_{false};
  bool failed_ = false;
  std::thread accept_thread_;
  std::mutex mu_;
  std::map<int, std::unique_ptr<Entry>> entries_;
  std::vector<std::thread> conn_threads_;
  std::mutex barrier_mu_;
  std::condition_variable barrier_cv_;
  int barrier_count_ = 0;
  int64_t barrier_gen_ = 0;
  std::mutex stop_mu_;
  std::condition_variable stop_cv_;
  bool stop_requested_ = false;
  UpdaterFn updater_ = nullptr;

  // PSServer is non-copyable
  PSServer(const PSServer&) = delete;
  PSServer& operator=(const PSServer&) = delete;
};

class PSClient {
 public:
  PSClient(const char* host, int port) {
    fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    inet_pton(AF_INET, host, &addr.sin_addr);
    // retry: workers may start before the server (launch.py races too)
    for (int attempt = 0; attempt < 600; ++attempt) {
      if (::connect(fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) ==
          0) {
        int one = 1;
        setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return;
      }
      ::close(fd_);
      struct timespec ts = {0, 100 * 1000 * 1000};
      nanosleep(&ts, nullptr);
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    }
    ::close(fd_);
    fd_ = -1;
  }

  ~PSClient() {
    if (fd_ >= 0) ::close(fd_);
  }

  bool ok() const { return fd_ >= 0; }

  bool Push(int key, const float* data, uint64_t n) {
    std::unique_lock<std::mutex> lk(mu_);
    MsgHeader h{kPush, key, n * sizeof(float)};
    if (!WriteAll(fd_, &h, sizeof(h)) ||
        !WriteAll(fd_, data, h.nbytes))
      return false;
    MsgHeader r;
    return ReadAll(fd_, &r, sizeof(r));
  }

  // Pull into caller buffer of capacity cap floats; returns #floats or -1.
  int64_t Pull(int key, float* out, uint64_t cap) {
    std::unique_lock<std::mutex> lk(mu_);
    MsgHeader h{kPull, key, 0};
    if (!WriteAll(fd_, &h, sizeof(h))) return -1;
    return ReadResp(out, cap);
  }

  int64_t PushPull(int key, const float* data, uint64_t n, float* out,
                   uint64_t cap) {
    std::unique_lock<std::mutex> lk(mu_);
    MsgHeader h{kPushPull, key, n * sizeof(float)};
    if (!WriteAll(fd_, &h, sizeof(h)) || !WriteAll(fd_, data, h.nbytes))
      return -1;
    return ReadResp(out, cap);
  }

  bool Barrier() {
    std::unique_lock<std::mutex> lk(mu_);
    MsgHeader h{kBarrier, 0, 0};
    if (!WriteAll(fd_, &h, sizeof(h))) return false;
    MsgHeader r;
    return ReadAll(fd_, &r, sizeof(r));
  }

  bool Command(const char* cmd) {
    std::unique_lock<std::mutex> lk(mu_);
    uint64_t n = strlen(cmd);
    MsgHeader h{kCommand, 0, n};
    if (!WriteAll(fd_, &h, sizeof(h)) || !WriteAll(fd_, cmd, n)) return false;
    MsgHeader r;
    return ReadAll(fd_, &r, sizeof(r));
  }

  bool Stop() {
    std::unique_lock<std::mutex> lk(mu_);
    MsgHeader h{kStop, 0, 0};
    if (!WriteAll(fd_, &h, sizeof(h))) return false;
    MsgHeader r;
    return ReadAll(fd_, &r, sizeof(r));
  }

 private:
  int64_t ReadResp(float* out, uint64_t cap) {
    MsgHeader r;
    if (!ReadAll(fd_, &r, sizeof(r))) return -1;
    uint64_t n = r.nbytes / sizeof(float);
    if (n > cap) {  // drain to keep the stream consistent
      std::vector<float> tmp(n);
      ReadAll(fd_, tmp.data(), r.nbytes);
      memcpy(out, tmp.data(), cap * sizeof(float));
      return static_cast<int64_t>(n);
    }
    if (n && !ReadAll(fd_, out, r.nbytes)) return -1;
    return static_cast<int64_t>(n);
  }

  int fd_ = -1;
  std::mutex mu_;
};

}  // namespace mxt

extern "C" {

void* mxt_ps_server_create(int port, int num_workers, int sync) {
  auto* s = new mxt::PSServer(port, num_workers, sync != 0);
  if (s->failed()) {
    delete s;
    return nullptr;
  }
  return s;
}
void mxt_ps_server_set_updater(void* h, mxt::UpdaterFn fn) {
  static_cast<mxt::PSServer*>(h)->SetUpdater(fn);
}
void mxt_ps_server_wait(void* h) {
  static_cast<mxt::PSServer*>(h)->WaitStopped();
}
void mxt_ps_server_destroy(void* h) { delete static_cast<mxt::PSServer*>(h); }

void* mxt_ps_client_create(const char* host, int port) {
  auto* c = new mxt::PSClient(host, port);
  if (!c->ok()) {
    delete c;
    return nullptr;
  }
  return c;
}
int mxt_ps_client_push(void* h, int key, const float* data,
                       unsigned long long n) {
  return static_cast<mxt::PSClient*>(h)->Push(key, data, n) ? 0 : -1;
}
long long mxt_ps_client_pull(void* h, int key, float* out,
                             unsigned long long cap) {
  return static_cast<mxt::PSClient*>(h)->Pull(key, out, cap);
}
long long mxt_ps_client_pushpull(void* h, int key, const float* data,
                                 unsigned long long n, float* out,
                                 unsigned long long cap) {
  return static_cast<mxt::PSClient*>(h)->PushPull(key, data, n, out, cap);
}
int mxt_ps_client_barrier(void* h) {
  return static_cast<mxt::PSClient*>(h)->Barrier() ? 0 : -1;
}
int mxt_ps_client_command(void* h, const char* cmd) {
  return static_cast<mxt::PSClient*>(h)->Command(cmd) ? 0 : -1;
}
int mxt_ps_client_stop(void* h) {
  return static_cast<mxt::PSClient*>(h)->Stop() ? 0 : -1;
}
void mxt_ps_client_destroy(void* h) { delete static_cast<mxt::PSClient*>(h); }

}  // extern "C"
