// Training-side C API slice (reference: include/mxnet/c_api.h — the Symbol /
// Executor function families: MXSymbolCreateFromJSON, MXExecutorForward,
// MXExecutorBackward, ...). The predict subset lives in c_predict_api.cc;
// this file adds enough surface for a pure C/C++ client to run a full
// training loop: symbol-from-JSON -> simple_bind -> set args -> forward ->
// backward -> read grads/outputs -> in-framework SGD update.
//
// Same embedding design as the predict shim: CPython is initialized lazily,
// every entry point holds the GIL, and the heavy lifting happens in
// mxnet_tpu.capi_train (whose executor is the XLA-compiled one — the compute
// path is identical to the Python surface's). Compiled client test:
// tests/test_c_train.py.
#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

// the public header declares every exported signature — including it makes
// the compiler verify each MXNET_DLL definition against its declaration
#include "include/c_train_api.h"

#define MXNET_DLL extern "C" __attribute__((visibility("default")))

// GIL/env scaffolding shared with the predict shim (defined there when both
// files link into one library).
extern thread_local std::string g_last_error_train;
thread_local std::string g_last_error_train;

void mxtpu_promote_libpython();  // c_predict_api.cc (libpython RTLD_GLOBAL)

// pure-C++ API files (c_api_recordio.cc) report through the train-error
// channel this header documents, without touching Python
void mxtpu_set_train_error(const std::string& msg) {
  g_last_error_train = msg;
}

namespace {

struct GilT {
  GilT() {
    if (!Py_IsInitialized()) {
      mxtpu_promote_libpython();
      Py_InitializeEx(0);
#if PY_VERSION_HEX < 0x03090000
      PyEval_InitThreads();
#endif
      PyEval_SaveThread();
    }
    st = PyGILState_Ensure();
  }
  ~GilT() { PyGILState_Release(st); }
  PyGILState_STATE st;
};

void set_err() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error_train = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* msg = PyUnicode_AsUTF8(s);
      if (msg) g_last_error_train = msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* train_module() {
  static PyObject* mod = nullptr;  // borrowed forever once imported
  if (!mod) {
    mod = PyImport_ImportModule("mxnet_tpu.capi_train");
    if (!mod) set_err();
  }
  return mod;
}

struct CSym {
  PyObject* obj;
};
struct CExec {
  PyObject* obj;
  // stable storage for string lists returned to C
  std::vector<std::string> names;
  std::vector<const char*> name_ptrs;
  std::vector<mx_uint> shape;
  std::vector<char> blob;
};

int fail() { return -1; }

// marshal a python list-of-str result into thread-local C string tables
int list_strings(PyObject* res, mx_uint* out_size, const char*** out_array) {
  if (!res) {
    set_err();
    return fail();
  }
  thread_local std::vector<std::string> names;
  thread_local std::vector<const char*> ptrs;
  names.clear();
  ptrs.clear();
  if (!PyList_Check(res)) {
    Py_DECREF(res);
    mxtpu_set_train_error("list_strings: helper did not return a list");
    return fail();
  }
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(res, i));
    if (!s) {
      Py_DECREF(res);
      set_err();
      return fail();
    }
    names.emplace_back(s);
  }
  Py_DECREF(res);
  for (auto& n : names) ptrs.push_back(n.c_str());
  *out_size = static_cast<mx_uint>(names.size());
  *out_array = ptrs.data();
  return 0;
}

// unpack a python bytes result into `blob` and expose it as a float32 view
int bytes_to_floats(PyObject* res, std::vector<char>* blob, const float** out,
                    mx_uint* out_size) {
  if (!res) {
    set_err();
    return fail();
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    Py_DECREF(res);
    set_err();
    return fail();
  }
  blob->assign(buf, buf + len);
  Py_DECREF(res);
  *out = reinterpret_cast<const float*>(blob->data());
  *out_size = static_cast<mx_uint>(len / sizeof(float));
  return 0;
}

}  // namespace

MXNET_DLL const char* MXTrainGetLastError() {
  return g_last_error_train.c_str();
}

MXNET_DLL int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* res = PyObject_CallMethod(mod, "_c_symbol_from_json", "s", json);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CSym{res};
  return 0;
}

MXNET_DLL int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_symbol_to_json", "O", s->obj);
  if (!res) {
    set_err();
    return fail();
  }
  thread_local std::string json;
  json = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out_json = json.c_str();
  return 0;
}

MXNET_DLL int MXSymbolFree(SymbolHandle sym) {
  if (!sym) return 0;
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  Py_XDECREF(s->obj);
  delete s;
  return 0;
}

// simple_bind: shapes as CSR (keys + flat dims + row offsets), the
// reference's shape-argument convention (c_api.h MXExecutorSimpleBind).
MXNET_DLL int MXExecutorSimpleBindLite(SymbolHandle sym, const char* dev_type,
                                       int dev_id, mx_uint num_args,
                                       const char** keys,
                                       const mx_uint* arg_shape_data,
                                       const mx_uint* arg_shape_idx,
                                       const char* grad_req,
                                       ExecutorHandle* out) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* key_list = PyList_New(num_args);
  PyObject* shape_list = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SetItem(key_list, i, PyUnicode_FromString(keys[i]));
    mx_uint lo = arg_shape_idx[i], hi = arg_shape_idx[i + 1];
    PyObject* dims = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(dims, j - lo, PyLong_FromUnsignedLong(arg_shape_data[j]));
    PyList_SetItem(shape_list, i, dims);
  }
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_simple_bind", "OsiOOs", s->obj,
                          dev_type, dev_id, key_list, shape_list, grad_req);
  Py_DECREF(key_list);
  Py_DECREF(shape_list);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CExec{res, {}, {}, {}, {}};
  return 0;
}

MXNET_DLL int MXExecutorFree(ExecutorHandle h) {
  if (!h) return 0;
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  Py_XDECREF(e->obj);
  delete e;
  return 0;
}

MXNET_DLL int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                                    const char*** out_array) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return list_strings(
      PyObject_CallMethod(train_module(), "_c_symbol_arguments", "O", s->obj),
      out_size, out_array);
}

MXNET_DLL int MXExecutorSetArg(ExecutorHandle h, const char* name,
                               const float* data, mx_uint size) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* blob = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), size * sizeof(float));
  PyObject* res = PyObject_CallMethod(train_module(), "_c_set_arg", "OsO",
                                      e->obj, name, blob);
  Py_DECREF(blob);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

namespace {

int get_array(CExec* e, const char* which, PyObject* key, const float** out,
              mx_uint* out_size) {
  PyObject* res = PyObject_CallMethod(train_module(), "_c_get_array", "OsO",
                                      e->obj, which, key);
  Py_DECREF(key);
  return bytes_to_floats(res, &e->blob, out, out_size);
}

}  // namespace

MXNET_DLL int MXExecutorGetArg(ExecutorHandle h, const char* name,
                               const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "arg", PyUnicode_FromString(name),
                   out, out_size);
}

MXNET_DLL int MXExecutorGetGrad(ExecutorHandle h, const char* name,
                                const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "grad", PyUnicode_FromString(name),
                   out, out_size);
}

MXNET_DLL int MXExecutorGetOutput(ExecutorHandle h, mx_uint index,
                                  const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "output", PyLong_FromLong(index),
                   out, out_size);
}

MXNET_DLL int MXExecutorOutputShape(ExecutorHandle h, mx_uint index,
                                    const mx_uint** out_shape,
                                    mx_uint* out_dim) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_get_shape", "OsI",
                                      e->obj, "output", index);
  if (!res) {
    set_err();
    return fail();
  }
  e->shape.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i)
    e->shape.push_back(
        static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(res, i))));
  Py_DECREF(res);
  *out_shape = e->shape.data();
  *out_dim = static_cast<mx_uint>(e->shape.size());
  return 0;
}

MXNET_DLL int MXExecutorForward(ExecutorHandle h, int is_train) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_forward", "Oi",
                                      e->obj, is_train);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorBackward(ExecutorHandle h, mx_uint, void**) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_backward", "O", e->obj);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorSGDUpdate(ExecutorHandle h, float lr, float wd,
                                  float rescale_grad) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_sgd_update", "Offf",
                                      e->obj, static_cast<double>(lr),
                                      static_cast<double>(wd),
                                      static_cast<double>(rescale_grad));
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

// ---- symbol construction (cpp-package surface) ---------------------------
// The reference separates MXSymbolCreateAtomicSymbol + MXSymbolCompose;
// cpp-package's Operator::CreateSymbol always runs both back-to-back, so
// this slice exposes the fused form. Params are strings (the op's Parameter
// schema parses them — same as the reference's C convention).

MXNET_DLL int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* res = PyObject_CallMethod(mod, "_c_variable", "s", name);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CSym{res};
  return 0;
}

MXNET_DLL int MXSymbolCreateFromOperator(
    const char* op_name, const char* name, mx_uint num_param,
    const char** param_keys, const char** param_vals, mx_uint num_inputs,
    const char** input_keys /* "" = positional */, SymbolHandle* inputs,
    SymbolHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* pkeys = PyList_New(num_param);
  PyObject* pvals = PyList_New(num_param);
  for (mx_uint i = 0; i < num_param; ++i) {
    PyList_SetItem(pkeys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(pvals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* ikeys = PyList_New(num_inputs);
  PyObject* isyms = PyList_New(num_inputs);
  for (mx_uint i = 0; i < num_inputs; ++i) {
    PyList_SetItem(ikeys, i, PyUnicode_FromString(
        input_keys ? input_keys[i] : ""));
    PyObject* o = static_cast<CSym*>(inputs[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(isyms, i, o);
  }
  PyObject* res = PyObject_CallMethod(mod, "_c_create_symbol", "ssOOOO",
                                      op_name, name ? name : "", pkeys, pvals,
                                      ikeys, isyms);
  Py_DECREF(pkeys);
  Py_DECREF(pvals);
  Py_DECREF(ikeys);
  Py_DECREF(isyms);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CSym{res};
  return 0;
}

MXNET_DLL int MXSymbolListOutputs(SymbolHandle sym, mx_uint* out_size,
                                  const char*** out_array) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return list_strings(
      PyObject_CallMethod(train_module(), "_c_symbol_outputs", "O", s->obj),
      out_size, out_array);
}

MXNET_DLL int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint* out_size,
                                          const char*** out_array) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return list_strings(
      PyObject_CallMethod(train_module(), "_c_symbol_aux_states", "O",
                          s->obj),
      out_size, out_array);
}

MXNET_DLL int MXExecutorNumOutputs(ExecutorHandle h, mx_uint* out) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_num_outputs", "O", e->obj);
  if (!res) {
    set_err();
    return fail();
  }
  *out = static_cast<mx_uint>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorGetAux(ExecutorHandle h, const char* name,
                               const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "aux", PyUnicode_FromString(name),
                   out, out_size);
}

MXNET_DLL int MXExecutorMomentumUpdate(ExecutorHandle h, float lr, float wd,
                                       float momentum, float rescale_grad) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(
      train_module(), "_c_momentum_update", "Offff", e->obj,
      static_cast<double>(lr), static_cast<double>(wd),
      static_cast<double>(momentum), static_cast<double>(rescale_grad));
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorSaveParams(ExecutorHandle h, const char* path) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_save_params", "Os",
                                      e->obj, path);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorLoadParams(ExecutorHandle h, const char* path,
                                   mx_uint* out_num_loaded) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_load_params", "Os",
                                      e->obj, path);
  if (!res) {
    set_err();
    return fail();
  }
  if (out_num_loaded)
    *out_num_loaded = static_cast<mx_uint>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

// ---- Profiler (reference: c_api.h MXSetProfilerConfig/State/MXDumpProfile)

MXNET_DLL int MXSetProfilerConfig(const char* mode, const char* filename) {
  GilT gil;
  PyObject* res = PyObject_CallMethod(
      train_module(), "_c_profiler_set_config", "ss", mode, filename);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXSetProfilerState(int state) {
  GilT gil;
  PyObject* res = PyObject_CallMethod(train_module(), "_c_profiler_set_state",
                                      "i", state);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXDumpProfile() {
  GilT gil;
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_dump_profile", NULL);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

// ---- Rtc (reference: c_api.h MXRtcCreate/MXRtcPush/MXRtcFree) ------------

struct CRtc {
  PyObject* obj;
  std::vector<std::vector<char>> out_blobs;
};

MXNET_DLL int MXRtcCreate(const char* name, mx_uint num_input,
                          mx_uint num_output, const char** input_names,
                          const char** output_names, const char* kernel,
                          RtcHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* ins = PyList_New(num_input);
  PyObject* outs = PyList_New(num_output);
  for (mx_uint i = 0; i < num_input; ++i)
    PyList_SetItem(ins, i, PyUnicode_FromString(input_names[i]));
  for (mx_uint i = 0; i < num_output; ++i)
    PyList_SetItem(outs, i, PyUnicode_FromString(output_names[i]));
  PyObject* res = PyObject_CallMethod(mod, "_c_rtc_create", "sOOs", name,
                                      ins, outs, kernel);
  Py_DECREF(ins);
  Py_DECREF(outs);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CRtc{res, {}};
  return 0;
}

MXNET_DLL int MXRtcFree(RtcHandle h) {
  if (!h) return 0;
  GilT gil;
  auto* r = static_cast<CRtc*>(h);
  Py_XDECREF(r->obj);
  delete r;
  return 0;
}

// inputs/outputs as float32 buffers with CSR-packed shapes (the
// simple_bind convention); output buffers are returned through out_blobs
// and stay valid until the next push on the same handle
MXNET_DLL int MXRtcPush(RtcHandle h, mx_uint num_input,
                        const float** input_data,
                        const mx_uint* input_shape_data,
                        const mx_uint* input_shape_idx, mx_uint num_output,
                        const mx_uint* output_shape_data,
                        const mx_uint* output_shape_idx,
                        const float** out_data, mx_uint* out_sizes) {
  GilT gil;
  auto* r = static_cast<CRtc*>(h);
  PyObject* blobs = PyList_New(num_input);
  PyObject* ishapes = PyList_New(num_input);
  for (mx_uint i = 0; i < num_input; ++i) {
    mx_uint lo = input_shape_idx[i], hi = input_shape_idx[i + 1];
    size_t n = 1;
    PyObject* dims = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j) {
      n *= input_shape_data[j];
      PyList_SetItem(dims, j - lo,
                     PyLong_FromUnsignedLong(input_shape_data[j]));
    }
    PyList_SetItem(ishapes, i, dims);
    PyList_SetItem(blobs, i,
                   PyBytes_FromStringAndSize(
                       reinterpret_cast<const char*>(input_data[i]),
                       n * sizeof(float)));
  }
  PyObject* oshapes = PyList_New(num_output);
  for (mx_uint i = 0; i < num_output; ++i) {
    mx_uint lo = output_shape_idx[i], hi = output_shape_idx[i + 1];
    PyObject* dims = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(dims, j - lo,
                     PyLong_FromUnsignedLong(output_shape_data[j]));
    PyList_SetItem(oshapes, i, dims);
  }
  PyObject* res = PyObject_CallMethod(train_module(), "_c_rtc_push", "OOOO",
                                      r->obj, blobs, ishapes, oshapes);
  Py_DECREF(blobs);
  Py_DECREF(ishapes);
  Py_DECREF(oshapes);
  if (!res) {
    set_err();
    return fail();
  }
  r->out_blobs.clear();
  if (!PyList_Check(res) ||
      PyList_Size(res) != static_cast<Py_ssize_t>(num_output)) {
    Py_DECREF(res);
    mxtpu_set_train_error(
        "MXRtcPush: kernel returned wrong number of output blobs");
    return fail();
  }
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i) {
    char* buf = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(PyList_GetItem(res, i), &buf, &len) != 0) {
      Py_DECREF(res);
      set_err();
      return fail();
    }
    size_t expect = sizeof(float);
    for (mx_uint j = output_shape_idx[i]; j < output_shape_idx[i + 1]; ++j)
      expect *= output_shape_data[j];
    if (static_cast<size_t>(len) != expect) {
      Py_DECREF(res);
      mxtpu_set_train_error(
          "MXRtcPush: output blob byte length does not match its declared "
          "shape");
      return fail();
    }
    r->out_blobs.emplace_back(buf, buf + len);
  }
  Py_DECREF(res);
  for (mx_uint i = 0; i < num_output; ++i) {
    out_data[i] = reinterpret_cast<const float*>(r->out_blobs[i].data());
    out_sizes[i] =
        static_cast<mx_uint>(r->out_blobs[i].size() / sizeof(float));
  }
  return 0;
}

// ---- DataIter (reference: c_api.h MXListDataIters/MXDataIterCreateIter/
// MXDataIterNext/GetData/GetLabel/GetPadNum) -------------------------------

struct CIter {
  PyObject* obj;
  std::vector<char> blob;
  std::vector<mx_uint> shape;
};

MXNET_DLL int MXListDataIters(mx_uint* out_size, const char*** out_array) {
  GilT gil;
  return list_strings(
      PyObject_CallMethod(train_module(), "_c_iter_list", NULL), out_size,
      out_array);
}

MXNET_DLL int MXDataIterCreate(const char* name, mx_uint num_param,
                               const char** keys, const char** vals,
                               DataIterHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* pkeys = PyList_New(num_param);
  PyObject* pvals = PyList_New(num_param);
  for (mx_uint i = 0; i < num_param; ++i) {
    PyList_SetItem(pkeys, i, PyUnicode_FromString(keys[i]));
    PyList_SetItem(pvals, i, PyUnicode_FromString(vals[i]));
  }
  PyObject* res = PyObject_CallMethod(mod, "_c_iter_create", "sOO", name,
                                      pkeys, pvals);
  Py_DECREF(pkeys);
  Py_DECREF(pvals);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CIter{res, {}, {}};
  return 0;
}

MXNET_DLL int MXDataIterFree(DataIterHandle h) {
  if (!h) return 0;
  GilT gil;
  auto* it = static_cast<CIter*>(h);
  Py_XDECREF(it->obj);
  delete it;
  return 0;
}

MXNET_DLL int MXDataIterNext(DataIterHandle h, int* out) {
  GilT gil;
  auto* it = static_cast<CIter*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_iter_next", "O", it->obj);
  if (!res) {
    set_err();
    return fail();
  }
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXDataIterBeforeFirst(DataIterHandle h) {
  GilT gil;
  auto* it = static_cast<CIter*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_iter_reset", "O", it->obj);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

namespace {

int iter_fetch(CIter* it, const char* which, const float** out,
               mx_uint* out_size) {
  PyObject* res = PyObject_CallMethod(train_module(), "_c_iter_get", "Os",
                                      it->obj, which);
  return bytes_to_floats(res, &it->blob, out, out_size);
}

int iter_shape(CIter* it, const char* which, const mx_uint** out_shape,
               mx_uint* out_dim) {
  PyObject* res = PyObject_CallMethod(train_module(), "_c_iter_shape", "Os",
                                      it->obj, which);
  if (!res) {
    set_err();
    return fail();
  }
  it->shape.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i)
    it->shape.push_back(
        static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(res, i))));
  Py_DECREF(res);
  *out_shape = it->shape.data();
  *out_dim = static_cast<mx_uint>(it->shape.size());
  return 0;
}

}  // namespace

MXNET_DLL int MXDataIterGetData(DataIterHandle h, const float** out,
                                mx_uint* out_size) {
  GilT gil;
  return iter_fetch(static_cast<CIter*>(h), "data", out, out_size);
}

MXNET_DLL int MXDataIterGetLabel(DataIterHandle h, const float** out,
                                 mx_uint* out_size) {
  GilT gil;
  return iter_fetch(static_cast<CIter*>(h), "label", out, out_size);
}

MXNET_DLL int MXDataIterGetDataShape(DataIterHandle h,
                                     const mx_uint** out_shape,
                                     mx_uint* out_dim) {
  GilT gil;
  return iter_shape(static_cast<CIter*>(h), "data", out_shape, out_dim);
}

MXNET_DLL int MXDataIterGetPadNum(DataIterHandle h, int* out) {
  GilT gil;
  auto* it = static_cast<CIter*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_iter_pad", "O", it->obj);
  if (!res) {
    set_err();
    return fail();
  }
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

// ---- KVStore (reference: c_api.h MXKVStoreCreate/Init/Push/Pull family) --

struct CKV {
  PyObject* obj;
  std::vector<char> blob;
};

MXNET_DLL int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* res = PyObject_CallMethod(mod, "_c_kv_create", "s", type);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CKV{res, {}};
  return 0;
}

MXNET_DLL int MXKVStoreFree(KVStoreHandle h) {
  if (!h) return 0;
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  Py_XDECREF(kv->obj);
  delete kv;
  return 0;
}

MXNET_DLL int MXKVStoreGetRank(KVStoreHandle h, int* out) {
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_kv_rank", "O", kv->obj);
  if (!res) {
    set_err();
    return fail();
  }
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXKVStoreGetGroupSize(KVStoreHandle h, int* out) {
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_kv_num_workers", "O", kv->obj);
  if (!res) {
    set_err();
    return fail();
  }
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

namespace {

int kv_send(CKV* kv, const char* method, int key, const float* data,
            const mx_uint* shape, mx_uint ndim) {
  size_t n = 1;
  PyObject* dims = PyList_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i) {
    n *= shape[i];
    PyList_SetItem(dims, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject* blob = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), n * sizeof(float));
  PyObject* res = PyObject_CallMethod(train_module(), method, "OiOO", kv->obj,
                                      key, blob, dims);
  Py_DECREF(blob);
  Py_DECREF(dims);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

}  // namespace

MXNET_DLL int MXKVStoreInit(KVStoreHandle h, int key, const float* data,
                            const mx_uint* shape, mx_uint ndim) {
  GilT gil;
  return kv_send(static_cast<CKV*>(h), "_c_kv_init", key, data, shape, ndim);
}

MXNET_DLL int MXKVStorePush(KVStoreHandle h, int key, const float* data,
                            const mx_uint* shape, mx_uint ndim) {
  GilT gil;
  return kv_send(static_cast<CKV*>(h), "_c_kv_push", key, data, shape, ndim);
}

MXNET_DLL int MXKVStorePull(KVStoreHandle h, int key, const float** out,
                            mx_uint* out_size) {
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  return bytes_to_floats(
      PyObject_CallMethod(train_module(), "_c_kv_pull", "Oi", kv->obj, key),
      &kv->blob, out, out_size);
}

MXNET_DLL int MXExecutorInitXavier(ExecutorHandle h, int seed) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_init_xavier", "Oi",
                                      e->obj, seed);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}
