// Training-side C API slice (reference: include/mxnet/c_api.h — the Symbol /
// Executor function families: MXSymbolCreateFromJSON, MXExecutorForward,
// MXExecutorBackward, ...). The predict subset lives in c_predict_api.cc;
// this file adds enough surface for a pure C/C++ client to run a full
// training loop: symbol-from-JSON -> simple_bind -> set args -> forward ->
// backward -> read grads/outputs -> in-framework SGD update.
//
// Same embedding design as the predict shim: CPython is initialized lazily,
// every entry point holds the GIL, and the heavy lifting happens in
// mxnet_tpu.capi_train (whose executor is the XLA-compiled one — the compute
// path is identical to the Python surface's). Compiled client test:
// tests/test_c_train.py.
#include <Python.h>

#include <atomic>
#include <cstring>
#include <string>
#include <unordered_set>
#include <vector>

// the public header declares every exported signature — including it makes
// the compiler verify each MXNET_DLL definition against its declaration
#include "include/c_array.h"
#include "include/c_train_api.h"

#define MXNET_DLL extern "C" __attribute__((visibility("default")))

// GIL/env scaffolding shared with the predict shim (defined there when both
// files link into one library).
extern thread_local std::string g_last_error_train;
thread_local std::string g_last_error_train;

void mxtpu_promote_libpython();  // c_predict_api.cc (libpython RTLD_GLOBAL)

// c_api_ndarray.cc invokes this (when set) for every MXNDArrayFree; the
// autograd session installs its purge callback into it
extern void (*mxtpu_ndarray_free_hook)(void*);

// pure-C++ API files (c_api_recordio.cc) report through the train-error
// channel this header documents, without touching Python
void mxtpu_set_train_error(const std::string& msg) {
  g_last_error_train = msg;
}

namespace {

struct GilT {
  GilT() {
    if (!Py_IsInitialized()) {
      mxtpu_promote_libpython();
      Py_InitializeEx(0);
#if PY_VERSION_HEX < 0x03090000
      PyEval_InitThreads();
#endif
      PyEval_SaveThread();
    }
    st = PyGILState_Ensure();
  }
  ~GilT() { PyGILState_Release(st); }
  PyGILState_STATE st;
};

void set_err() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error_train = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* msg = PyUnicode_AsUTF8(s);
      if (msg) g_last_error_train = msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* train_module() {
  static PyObject* mod = nullptr;  // borrowed forever once imported
  if (!mod) {
    mod = PyImport_ImportModule("mxnet_tpu.capi_train");
    if (!mod) set_err();
  }
  return mod;
}

struct CSym {
  PyObject* obj;
};
struct CExec {
  PyObject* obj = nullptr;
  // stable storage for string lists returned to C
  std::vector<std::string> names;
  std::vector<const char*> name_ptrs;
  std::vector<mx_uint> shape;
  std::vector<char> blob;
  // per-node monitor (MXExecutorSetMonitorCallback): replayed after each
  // monitored forward; mon_arrays hold the handles until the next forward
  ExecutorMonitorCallback mon_cb = nullptr;
  void* mon_ctx = nullptr;
  std::vector<void*> mon_arrays;
};

int fail() { return -1; }

// marshal a python list-of-str result into thread-local C string tables
int list_strings(PyObject* res, mx_uint* out_size, const char*** out_array) {
  if (!res) {
    set_err();
    return fail();
  }
  thread_local std::vector<std::string> names;
  thread_local std::vector<const char*> ptrs;
  names.clear();
  ptrs.clear();
  if (!PyList_Check(res)) {
    Py_DECREF(res);
    mxtpu_set_train_error("list_strings: helper did not return a list");
    return fail();
  }
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i) {
    const char* s = PyUnicode_AsUTF8(PyList_GetItem(res, i));
    if (!s) {
      Py_DECREF(res);
      set_err();
      return fail();
    }
    names.emplace_back(s);
  }
  Py_DECREF(res);
  for (auto& n : names) ptrs.push_back(n.c_str());
  *out_size = static_cast<mx_uint>(names.size());
  *out_array = ptrs.data();
  return 0;
}

// unpack a python bytes result into `blob` and expose it as a float32 view
int bytes_to_floats(PyObject* res, std::vector<char>* blob, const float** out,
                    mx_uint* out_size) {
  if (!res) {
    set_err();
    return fail();
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    Py_DECREF(res);
    set_err();
    return fail();
  }
  blob->assign(buf, buf + len);
  Py_DECREF(res);
  *out = reinterpret_cast<const float*>(blob->data());
  *out_size = static_cast<mx_uint>(len / sizeof(float));
  return 0;
}

}  // namespace

MXNET_DLL const char* MXTrainGetLastError() {
  return g_last_error_train.c_str();
}

MXNET_DLL int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* res = PyObject_CallMethod(mod, "_c_symbol_from_json", "s", json);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CSym{res};
  return 0;
}

MXNET_DLL int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_symbol_to_json", "O", s->obj);
  if (!res) {
    set_err();
    return fail();
  }
  thread_local std::string json;
  json = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out_json = json.c_str();
  return 0;
}

MXNET_DLL int MXSymbolFree(SymbolHandle sym) {
  if (!sym) return 0;
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  Py_XDECREF(s->obj);
  delete s;
  return 0;
}

// simple_bind: shapes as CSR (keys + flat dims + row offsets), the
// reference's shape-argument convention (c_api.h MXExecutorSimpleBind).
MXNET_DLL int MXExecutorSimpleBindLite(SymbolHandle sym, const char* dev_type,
                                       int dev_id, mx_uint num_args,
                                       const char** keys,
                                       const mx_uint* arg_shape_data,
                                       const mx_uint* arg_shape_idx,
                                       const char* grad_req,
                                       ExecutorHandle* out) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* key_list = PyList_New(num_args);
  PyObject* shape_list = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SetItem(key_list, i, PyUnicode_FromString(keys[i]));
    mx_uint lo = arg_shape_idx[i], hi = arg_shape_idx[i + 1];
    PyObject* dims = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(dims, j - lo, PyLong_FromUnsignedLong(arg_shape_data[j]));
    PyList_SetItem(shape_list, i, dims);
  }
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_simple_bind", "OsiOOs", s->obj,
                          dev_type, dev_id, key_list, shape_list, grad_req);
  Py_DECREF(key_list);
  Py_DECREF(shape_list);
  if (!res) {
    set_err();
    return fail();
  }
  auto* ce = new CExec();
  ce->obj = res;
  *out = ce;
  return 0;
}

MXNET_DLL int MXExecutorFree(ExecutorHandle h) {
  if (!h) return 0;
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  for (void* a : e->mon_arrays) delete static_cast<CArray*>(a);
  Py_XDECREF(e->obj);
  delete e;
  return 0;
}

MXNET_DLL int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                                    const char*** out_array) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return list_strings(
      PyObject_CallMethod(train_module(), "_c_symbol_arguments", "O", s->obj),
      out_size, out_array);
}

MXNET_DLL int MXExecutorSetAux(ExecutorHandle h, const char* name,
                               const float* data, mx_uint size) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* blob = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), size * sizeof(float));
  PyObject* res = PyObject_CallMethod(train_module(), "_c_set_aux", "OsO",
                                      e->obj, name, blob);
  Py_DECREF(blob);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorSetArg(ExecutorHandle h, const char* name,
                               const float* data, mx_uint size) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* blob = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), size * sizeof(float));
  PyObject* res = PyObject_CallMethod(train_module(), "_c_set_arg", "OsO",
                                      e->obj, name, blob);
  Py_DECREF(blob);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

namespace {

int get_array(CExec* e, const char* which, PyObject* key, const float** out,
              mx_uint* out_size) {
  PyObject* res = PyObject_CallMethod(train_module(), "_c_get_array", "OsO",
                                      e->obj, which, key);
  Py_DECREF(key);
  return bytes_to_floats(res, &e->blob, out, out_size);
}

}  // namespace

MXNET_DLL int MXExecutorGetArg(ExecutorHandle h, const char* name,
                               const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "arg", PyUnicode_FromString(name),
                   out, out_size);
}

MXNET_DLL int MXExecutorGetGrad(ExecutorHandle h, const char* name,
                                const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "grad", PyUnicode_FromString(name),
                   out, out_size);
}

MXNET_DLL int MXExecutorGetOutput(ExecutorHandle h, mx_uint index,
                                  const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "output", PyLong_FromLong(index),
                   out, out_size);
}

MXNET_DLL int MXExecutorOutputShape(ExecutorHandle h, mx_uint index,
                                    const mx_uint** out_shape,
                                    mx_uint* out_dim) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_get_shape", "OsI",
                                      e->obj, "output", index);
  if (!res) {
    set_err();
    return fail();
  }
  e->shape.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i)
    e->shape.push_back(
        static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(res, i))));
  Py_DECREF(res);
  *out_shape = e->shape.data();
  *out_dim = static_cast<mx_uint>(e->shape.size());
  return 0;
}

MXNET_DLL int MXExecutorForward(ExecutorHandle h, int is_train) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  if (e->mon_cb) {
    // monitored pass (reference ExecuteMonCallback): collect per-node
    // outputs python-side, then replay into the client's callback
    PyObject* res = PyObject_CallMethod(
        train_module(), "_c_forward_monitored", "Oi", e->obj, is_train);
    if (!res) {
      set_err();
      return fail();
    }
    for (void* a : e->mon_arrays) delete static_cast<CArray*>(a);
    e->mon_arrays.clear();
    if (!PyList_Check(res)) {
      Py_DECREF(res);
      mxtpu_set_train_error("_c_forward_monitored: expected a list");
      return fail();
    }
    struct Entry {
      std::string name;
      CArray* arr;
    };
    std::vector<Entry> entries;
    for (Py_ssize_t i = 0; i < PyList_Size(res); ++i) {
      PyObject* tup = PyList_GetItem(res, i);
      const char* nm = nullptr;
      PyObject* blob = nullptr;
      PyObject* shp = nullptr;
      if (!PyArg_ParseTuple(tup, "sSO", &nm, &blob, &shp)) {
        Py_DECREF(res);
        set_err();
        return fail();
      }
      auto* arr = new CArray();
      arr->dtype = 0;
      for (Py_ssize_t j = 0; j < PyList_Size(shp); ++j)
        arr->shape.push_back(static_cast<mx_uint>(
            PyLong_AsUnsignedLong(PyList_GetItem(shp, j))));
      char* buf = nullptr;
      Py_ssize_t len = 0;
      PyBytes_AsStringAndSize(blob, &buf, &len);
      arr->data.assign(buf, buf + len);
      e->mon_arrays.push_back(arr);
      entries.push_back({nm, arr});
    }
    Py_DECREF(res);
    for (const auto& en : entries) e->mon_cb(en.name.c_str(), en.arr, e->mon_ctx);
    return 0;
  }
  PyObject* res = PyObject_CallMethod(train_module(), "_c_forward", "Oi",
                                      e->obj, is_train);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorSetMonitorCallback(ExecutorHandle h,
                                           ExecutorMonitorCallback callback,
                                           void* callback_handle) {
  auto* e = static_cast<CExec*>(h);
  if (!e) {
    mxtpu_set_train_error("null executor handle");
    return fail();
  }
  e->mon_cb = callback;
  e->mon_ctx = callback_handle;
  return 0;
}

MXNET_DLL int MXExecutorBackward(ExecutorHandle h, mx_uint, void**) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_backward", "O", e->obj);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorSGDUpdate(ExecutorHandle h, float lr, float wd,
                                  float rescale_grad) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_sgd_update", "Offf",
                                      e->obj, static_cast<double>(lr),
                                      static_cast<double>(wd),
                                      static_cast<double>(rescale_grad));
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

// ---- symbol construction (cpp-package surface) ---------------------------
// The reference separates MXSymbolCreateAtomicSymbol + MXSymbolCompose;
// cpp-package's Operator::CreateSymbol always runs both back-to-back, so
// this slice exposes the fused form. Params are strings (the op's Parameter
// schema parses them — same as the reference's C convention).

MXNET_DLL int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* res = PyObject_CallMethod(mod, "_c_variable", "s", name);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CSym{res};
  return 0;
}

MXNET_DLL int MXSymbolCreateFromOperator(
    const char* op_name, const char* name, mx_uint num_param,
    const char** param_keys, const char** param_vals, mx_uint num_inputs,
    const char** input_keys /* "" = positional */, SymbolHandle* inputs,
    SymbolHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* pkeys = PyList_New(num_param);
  PyObject* pvals = PyList_New(num_param);
  for (mx_uint i = 0; i < num_param; ++i) {
    PyList_SetItem(pkeys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(pvals, i, PyUnicode_FromString(param_vals[i]));
  }
  PyObject* ikeys = PyList_New(num_inputs);
  PyObject* isyms = PyList_New(num_inputs);
  for (mx_uint i = 0; i < num_inputs; ++i) {
    PyList_SetItem(ikeys, i, PyUnicode_FromString(
        input_keys ? input_keys[i] : ""));
    PyObject* o = static_cast<CSym*>(inputs[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(isyms, i, o);
  }
  PyObject* res = PyObject_CallMethod(mod, "_c_create_symbol", "ssOOOO",
                                      op_name, name ? name : "", pkeys, pvals,
                                      ikeys, isyms);
  Py_DECREF(pkeys);
  Py_DECREF(pvals);
  Py_DECREF(ikeys);
  Py_DECREF(isyms);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CSym{res};
  return 0;
}

MXNET_DLL int MXSymbolListOutputs(SymbolHandle sym, mx_uint* out_size,
                                  const char*** out_array) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return list_strings(
      PyObject_CallMethod(train_module(), "_c_symbol_outputs", "O", s->obj),
      out_size, out_array);
}

MXNET_DLL int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint* out_size,
                                          const char*** out_array) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return list_strings(
      PyObject_CallMethod(train_module(), "_c_symbol_aux_states", "O",
                          s->obj),
      out_size, out_array);
}

MXNET_DLL int MXExecutorNumOutputs(ExecutorHandle h, mx_uint* out) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_num_outputs", "O", e->obj);
  if (!res) {
    set_err();
    return fail();
  }
  *out = static_cast<mx_uint>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorGetAux(ExecutorHandle h, const char* name,
                               const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "aux", PyUnicode_FromString(name),
                   out, out_size);
}

MXNET_DLL int MXExecutorMomentumUpdate(ExecutorHandle h, float lr, float wd,
                                       float momentum, float rescale_grad) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(
      train_module(), "_c_momentum_update", "Offff", e->obj,
      static_cast<double>(lr), static_cast<double>(wd),
      static_cast<double>(momentum), static_cast<double>(rescale_grad));
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorSaveParams(ExecutorHandle h, const char* path) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_save_params", "Os",
                                      e->obj, path);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorLoadParams(ExecutorHandle h, const char* path,
                                   mx_uint* out_num_loaded) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_load_params", "Os",
                                      e->obj, path);
  if (!res) {
    set_err();
    return fail();
  }
  if (out_num_loaded)
    *out_num_loaded = static_cast<mx_uint>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

// ---- Profiler (reference: c_api.h MXSetProfilerConfig/State/MXDumpProfile)

MXNET_DLL int MXSetProfilerConfig(const char* mode, const char* filename) {
  GilT gil;
  PyObject* res = PyObject_CallMethod(
      train_module(), "_c_profiler_set_config", "ss", mode, filename);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXSetProfilerState(int state) {
  GilT gil;
  PyObject* res = PyObject_CallMethod(train_module(), "_c_profiler_set_state",
                                      "i", state);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXDumpProfile() {
  GilT gil;
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_dump_profile", NULL);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

// ---- Rtc (reference: c_api.h MXRtcCreate/MXRtcPush/MXRtcFree) ------------

struct CRtc {
  PyObject* obj;
  std::vector<std::vector<char>> out_blobs;
};

MXNET_DLL int MXRtcCreate(const char* name, mx_uint num_input,
                          mx_uint num_output, const char** input_names,
                          const char** output_names, const char* kernel,
                          RtcHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* ins = PyList_New(num_input);
  PyObject* outs = PyList_New(num_output);
  for (mx_uint i = 0; i < num_input; ++i)
    PyList_SetItem(ins, i, PyUnicode_FromString(input_names[i]));
  for (mx_uint i = 0; i < num_output; ++i)
    PyList_SetItem(outs, i, PyUnicode_FromString(output_names[i]));
  PyObject* res = PyObject_CallMethod(mod, "_c_rtc_create", "sOOs", name,
                                      ins, outs, kernel);
  Py_DECREF(ins);
  Py_DECREF(outs);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CRtc{res, {}};
  return 0;
}

MXNET_DLL int MXRtcFree(RtcHandle h) {
  if (!h) return 0;
  GilT gil;
  auto* r = static_cast<CRtc*>(h);
  Py_XDECREF(r->obj);
  delete r;
  return 0;
}

// inputs/outputs as float32 buffers with CSR-packed shapes (the
// simple_bind convention); output buffers are returned through out_blobs
// and stay valid until the next push on the same handle
MXNET_DLL int MXRtcPush(RtcHandle h, mx_uint num_input,
                        const float** input_data,
                        const mx_uint* input_shape_data,
                        const mx_uint* input_shape_idx, mx_uint num_output,
                        const mx_uint* output_shape_data,
                        const mx_uint* output_shape_idx,
                        const float** out_data, mx_uint* out_sizes) {
  GilT gil;
  auto* r = static_cast<CRtc*>(h);
  PyObject* blobs = PyList_New(num_input);
  PyObject* ishapes = PyList_New(num_input);
  for (mx_uint i = 0; i < num_input; ++i) {
    mx_uint lo = input_shape_idx[i], hi = input_shape_idx[i + 1];
    size_t n = 1;
    PyObject* dims = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j) {
      n *= input_shape_data[j];
      PyList_SetItem(dims, j - lo,
                     PyLong_FromUnsignedLong(input_shape_data[j]));
    }
    PyList_SetItem(ishapes, i, dims);
    PyList_SetItem(blobs, i,
                   PyBytes_FromStringAndSize(
                       reinterpret_cast<const char*>(input_data[i]),
                       n * sizeof(float)));
  }
  PyObject* oshapes = PyList_New(num_output);
  for (mx_uint i = 0; i < num_output; ++i) {
    mx_uint lo = output_shape_idx[i], hi = output_shape_idx[i + 1];
    PyObject* dims = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(dims, j - lo,
                     PyLong_FromUnsignedLong(output_shape_data[j]));
    PyList_SetItem(oshapes, i, dims);
  }
  PyObject* res = PyObject_CallMethod(train_module(), "_c_rtc_push", "OOOO",
                                      r->obj, blobs, ishapes, oshapes);
  Py_DECREF(blobs);
  Py_DECREF(ishapes);
  Py_DECREF(oshapes);
  if (!res) {
    set_err();
    return fail();
  }
  r->out_blobs.clear();
  if (!PyList_Check(res) ||
      PyList_Size(res) != static_cast<Py_ssize_t>(num_output)) {
    Py_DECREF(res);
    mxtpu_set_train_error(
        "MXRtcPush: kernel returned wrong number of output blobs");
    return fail();
  }
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i) {
    char* buf = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(PyList_GetItem(res, i), &buf, &len) != 0) {
      Py_DECREF(res);
      set_err();
      return fail();
    }
    size_t expect = sizeof(float);
    for (mx_uint j = output_shape_idx[i]; j < output_shape_idx[i + 1]; ++j)
      expect *= output_shape_data[j];
    if (static_cast<size_t>(len) != expect) {
      Py_DECREF(res);
      mxtpu_set_train_error(
          "MXRtcPush: output blob byte length does not match its declared "
          "shape");
      return fail();
    }
    r->out_blobs.emplace_back(buf, buf + len);
  }
  Py_DECREF(res);
  for (mx_uint i = 0; i < num_output; ++i) {
    out_data[i] = reinterpret_cast<const float*>(r->out_blobs[i].data());
    out_sizes[i] =
        static_cast<mx_uint>(r->out_blobs[i].size() / sizeof(float));
  }
  return 0;
}

// ---- DataIter (reference: c_api.h MXListDataIters/MXDataIterCreateIter/
// MXDataIterNext/GetData/GetLabel/GetPadNum) -------------------------------

struct CIter {
  PyObject* obj;
  std::vector<char> blob;
  std::vector<mx_uint> shape;
};

MXNET_DLL int MXListDataIters(mx_uint* out_size, const char*** out_array) {
  GilT gil;
  return list_strings(
      PyObject_CallMethod(train_module(), "_c_iter_list", NULL), out_size,
      out_array);
}

MXNET_DLL int MXDataIterCreate(const char* name, mx_uint num_param,
                               const char** keys, const char** vals,
                               DataIterHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* pkeys = PyList_New(num_param);
  PyObject* pvals = PyList_New(num_param);
  for (mx_uint i = 0; i < num_param; ++i) {
    PyList_SetItem(pkeys, i, PyUnicode_FromString(keys[i]));
    PyList_SetItem(pvals, i, PyUnicode_FromString(vals[i]));
  }
  PyObject* res = PyObject_CallMethod(mod, "_c_iter_create", "sOO", name,
                                      pkeys, pvals);
  Py_DECREF(pkeys);
  Py_DECREF(pvals);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CIter{res, {}, {}};
  return 0;
}

MXNET_DLL int MXDataIterFree(DataIterHandle h) {
  if (!h) return 0;
  GilT gil;
  auto* it = static_cast<CIter*>(h);
  Py_XDECREF(it->obj);
  delete it;
  return 0;
}

MXNET_DLL int MXDataIterNext(DataIterHandle h, int* out) {
  GilT gil;
  auto* it = static_cast<CIter*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_iter_next", "O", it->obj);
  if (!res) {
    set_err();
    return fail();
  }
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXDataIterBeforeFirst(DataIterHandle h) {
  GilT gil;
  auto* it = static_cast<CIter*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_iter_reset", "O", it->obj);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

namespace {

int iter_fetch(CIter* it, const char* which, const float** out,
               mx_uint* out_size) {
  PyObject* res = PyObject_CallMethod(train_module(), "_c_iter_get", "Os",
                                      it->obj, which);
  return bytes_to_floats(res, &it->blob, out, out_size);
}

int iter_shape(CIter* it, const char* which, const mx_uint** out_shape,
               mx_uint* out_dim) {
  PyObject* res = PyObject_CallMethod(train_module(), "_c_iter_shape", "Os",
                                      it->obj, which);
  if (!res) {
    set_err();
    return fail();
  }
  it->shape.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i)
    it->shape.push_back(
        static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(res, i))));
  Py_DECREF(res);
  *out_shape = it->shape.data();
  *out_dim = static_cast<mx_uint>(it->shape.size());
  return 0;
}

}  // namespace

MXNET_DLL int MXDataIterGetData(DataIterHandle h, const float** out,
                                mx_uint* out_size) {
  GilT gil;
  return iter_fetch(static_cast<CIter*>(h), "data", out, out_size);
}

MXNET_DLL int MXDataIterGetLabel(DataIterHandle h, const float** out,
                                 mx_uint* out_size) {
  GilT gil;
  return iter_fetch(static_cast<CIter*>(h), "label", out, out_size);
}

MXNET_DLL int MXDataIterGetDataShape(DataIterHandle h,
                                     const mx_uint** out_shape,
                                     mx_uint* out_dim) {
  GilT gil;
  return iter_shape(static_cast<CIter*>(h), "data", out_shape, out_dim);
}

MXNET_DLL int MXDataIterGetPadNum(DataIterHandle h, int* out) {
  GilT gil;
  auto* it = static_cast<CIter*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_iter_pad", "O", it->obj);
  if (!res) {
    set_err();
    return fail();
  }
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

// ---- KVStore (reference: c_api.h MXKVStoreCreate/Init/Push/Pull family) --

struct CKV {
  PyObject* obj;
  std::vector<char> blob;
};

MXNET_DLL int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* res = PyObject_CallMethod(mod, "_c_kv_create", "s", type);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CKV{res, {}};
  return 0;
}

MXNET_DLL int MXKVStoreFree(KVStoreHandle h) {
  if (!h) return 0;
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  Py_XDECREF(kv->obj);
  delete kv;
  return 0;
}

MXNET_DLL int MXKVStoreGetRank(KVStoreHandle h, int* out) {
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_kv_rank", "O", kv->obj);
  if (!res) {
    set_err();
    return fail();
  }
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXKVStoreGetGroupSize(KVStoreHandle h, int* out) {
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_kv_num_workers", "O", kv->obj);
  if (!res) {
    set_err();
    return fail();
  }
  *out = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

namespace {

int kv_send(CKV* kv, const char* method, int key, const float* data,
            const mx_uint* shape, mx_uint ndim) {
  size_t n = 1;
  PyObject* dims = PyList_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i) {
    n *= shape[i];
    PyList_SetItem(dims, i, PyLong_FromUnsignedLong(shape[i]));
  }
  PyObject* blob = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), n * sizeof(float));
  PyObject* res = PyObject_CallMethod(train_module(), method, "OiOO", kv->obj,
                                      key, blob, dims);
  Py_DECREF(blob);
  Py_DECREF(dims);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

}  // namespace

MXNET_DLL int MXKVStoreInit(KVStoreHandle h, int key, const float* data,
                            const mx_uint* shape, mx_uint ndim) {
  GilT gil;
  return kv_send(static_cast<CKV*>(h), "_c_kv_init", key, data, shape, ndim);
}

MXNET_DLL int MXKVStorePush(KVStoreHandle h, int key, const float* data,
                            const mx_uint* shape, mx_uint ndim) {
  GilT gil;
  return kv_send(static_cast<CKV*>(h), "_c_kv_push", key, data, shape, ndim);
}

MXNET_DLL int MXKVStorePull(KVStoreHandle h, int key, const float** out,
                            mx_uint* out_size) {
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  return bytes_to_floats(
      PyObject_CallMethod(train_module(), "_c_kv_pull", "Oi", kv->obj, key),
      &kv->blob, out, out_size);
}

MXNET_DLL int MXExecutorInitXavier(ExecutorHandle h, int seed) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_init_xavier", "Oi",
                                      e->obj, seed);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

// ---- Imperative invoke + introspection (reference: c_api.h
// MXImperativeInvoke :518, MXListAllOpNames :594,
// MXSymbolListAtomicSymbolCreators :604, MXSymbolInferShape :854) ----------

namespace {

// creator handles are stable pointers into a process-wide op-name table
// (the reference's AtomicSymbolCreator is likewise an opaque registry entry)
std::vector<std::string>& op_name_table() {
  static std::vector<std::string>* t = nullptr;
  if (!t) {
    t = new std::vector<std::string>();
    PyObject* res =
        PyObject_CallMethod(train_module(), "_c_list_all_ops", NULL);
    if (res && PyList_Check(res)) {
      for (Py_ssize_t i = 0; i < PyList_Size(res); ++i) {
        const char* s = PyUnicode_AsUTF8(PyList_GetItem(res, i));
        if (s) t->push_back(s);
      }
    }
    Py_XDECREF(res);
    if (!res) PyErr_Clear();
  }
  return *t;
}

}  // namespace

MXNET_DLL int MXListAllOpNames(mx_uint* out_size, const char*** out_array) {
  GilT gil;
  auto& tbl = op_name_table();
  thread_local std::vector<const char*> ptrs;
  ptrs.clear();
  for (const auto& s : tbl) ptrs.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(ptrs.size());
  *out_array = ptrs.data();
  return 0;
}

MXNET_DLL int MXSymbolListAtomicSymbolCreators(mx_uint* out_size,
                                               AtomicSymbolCreator** out_array) {
  GilT gil;
  auto& tbl = op_name_table();
  thread_local std::vector<AtomicSymbolCreator> creators;
  creators.clear();
  for (auto& s : tbl)
    creators.push_back(const_cast<std::string*>(&s));
  *out_size = static_cast<mx_uint>(creators.size());
  *out_array = creators.data();
  return 0;
}

MXNET_DLL int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                          const char** name) {
  if (!creator) {
    mxtpu_set_train_error("null creator");
    return fail();
  }
  *name = static_cast<std::string*>(creator)->c_str();
  return 0;
}

namespace {

// C-side mirror of the autograd session's handle ids (python: capi_train's
// _AUTOGRAD_* maps). All access is under the GIL (every entry point takes
// GilT), which serializes it.
std::unordered_set<void*>& autograd_adopted() {
  static std::unordered_set<void*> s;
  return s;
}
std::unordered_set<void*>& autograd_marked() {
  static std::unordered_set<void*> s;
  return s;
}
std::atomic<bool> g_autograd_used{false};

// purge a freed handle from the session (installed as the NDArrayFree hook
// below): a recycled heap address must not resurrect a stale tape array
void autograd_on_free(void* handle) {
  if (!g_autograd_used.load(std::memory_order_acquire)) return;
  GilT gil;
  autograd_adopted().erase(handle);
  autograd_marked().erase(handle);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_autograd_forget",
                                      "O&", PyLong_FromVoidPtr, handle);
  if (!res)
    PyErr_Clear();  // teardown path: never surface errors from Free
  else
    Py_DECREF(res);
}

struct InstallFreeHook {
  InstallFreeHook() { mxtpu_ndarray_free_hook = autograd_on_free; }
} g_install_free_hook;

}  // namespace

MXNET_DLL int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                                 NDArrayHandle* inputs, int* num_outputs,
                                 NDArrayHandle** outputs, int num_params,
                                 const char** param_keys,
                                 const char** param_vals) {
  GilT gil;
  if (!creator) {
    mxtpu_set_train_error("null creator");
    return fail();
  }
  const std::string& op_name = *static_cast<std::string*>(creator);
  PyObject* blobs = PyList_New(num_inputs);
  PyObject* shapes = PyList_New(num_inputs);
  PyObject* dtypes = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    auto* a = static_cast<CArray*>(inputs[i]);
    // adopted (non-marked) handles are fed as their live python tape
    // arrays — the bytes would be discarded, so skip the copy entirely.
    // Marked variables DO marshal: their current bytes re-sync the value.
    bool skip_bytes = autograd_adopted().count(inputs[i]) &&
                      !autograd_marked().count(inputs[i]);
    PyList_SetItem(blobs, i,
                   skip_bytes
                       ? PyBytes_FromStringAndSize(nullptr, 0)
                       : PyBytes_FromStringAndSize(
                             reinterpret_cast<const char*>(a->data.data()),
                             a->data.size()));
    PyObject* dims = PyList_New(a->shape.size());
    for (size_t j = 0; j < a->shape.size(); ++j)
      PyList_SetItem(dims, j, PyLong_FromUnsignedLong(a->shape[j]));
    PyList_SetItem(shapes, i, dims);
    PyList_SetItem(dtypes, i, PyLong_FromLong(a->dtype));
  }
  PyObject* pkeys = PyList_New(num_params);
  PyObject* pvals = PyList_New(num_params);
  for (int i = 0; i < num_params; ++i) {
    PyList_SetItem(pkeys, i, PyUnicode_FromString(param_keys[i]));
    PyList_SetItem(pvals, i, PyUnicode_FromString(param_vals[i]));
  }
  // handle ids let the autograd session substitute live tape arrays for
  // marked/recorded inputs (see capi_train._c_imperative_invoke)
  PyObject* in_ids = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i)
    PyList_SetItem(in_ids, i, PyLong_FromVoidPtr(inputs[i]));
  PyObject* res = PyObject_CallMethod(
      train_module(), "_c_imperative_invoke", "sOOOOOO", op_name.c_str(),
      blobs, shapes, dtypes, pkeys, pvals, in_ids);
  Py_DECREF(blobs);
  Py_DECREF(shapes);
  Py_DECREF(dtypes);
  Py_DECREF(pkeys);
  Py_DECREF(pvals);
  Py_DECREF(in_ids);
  if (!res) {
    set_err();
    return fail();
  }
  PyObject *oblobs = nullptr, *oshapes = nullptr, *odtypes = nullptr;
  if (!PyArg_ParseTuple(res, "OOO", &oblobs, &oshapes, &odtypes)) {
    Py_DECREF(res);
    set_err();
    return fail();
  }
  Py_ssize_t n_out = PyList_Size(oblobs);
  bool caller_provided = (*num_outputs > 0 && *outputs != nullptr);
  if (caller_provided && *num_outputs != static_cast<int>(n_out)) {
    Py_DECREF(res);
    mxtpu_set_train_error("MXImperativeInvoke: wrong number of provided "
                          "output handles");
    return fail();
  }
  thread_local std::vector<NDArrayHandle> out_handles;
  if (!caller_provided) out_handles.clear();
  auto drop_allocated = [&]() {
    if (caller_provided) return;
    for (NDArrayHandle h2 : out_handles) delete static_cast<CArray*>(h2);
    out_handles.clear();
  };
  for (Py_ssize_t i = 0; i < n_out; ++i) {
    char* buf = nullptr;
    Py_ssize_t len = 0;
    if (PyBytes_AsStringAndSize(PyList_GetItem(oblobs, i), &buf, &len) != 0) {
      Py_DECREF(res);
      drop_allocated();
      set_err();
      return fail();
    }
    CArray* arr = caller_provided
                      ? static_cast<CArray*>((*outputs)[i])
                      : new CArray();
    arr->shape.clear();
    PyObject* shp = PyList_GetItem(oshapes, i);
    for (Py_ssize_t j = 0; j < PyList_Size(shp); ++j)
      arr->shape.push_back(static_cast<mx_uint>(
          PyLong_AsUnsignedLong(PyList_GetItem(shp, j))));
    arr->dtype =
        static_cast<int>(PyLong_AsLong(PyList_GetItem(odtypes, i)));
    arr->data.assign(buf, buf + len);
    arr->none = false;
    if (!caller_provided) out_handles.push_back(arr);
  }
  Py_DECREF(res);
  if (!caller_provided) {
    *num_outputs = static_cast<int>(n_out);
    *outputs = out_handles.data();
  }
  // bind the (now known) output handle ids to the recorded python outputs;
  // a no-op unless this invoke was recorded by the autograd session
  PyObject* oids = PyList_New(n_out);
  for (Py_ssize_t i = 0; i < n_out; ++i)
    PyList_SetItem(
        oids, i,
        PyLong_FromVoidPtr(caller_provided ? (*outputs)[i]
                                           : out_handles[i]));
  PyObject* ares =
      PyObject_CallMethod(train_module(), "_c_autograd_adopt", "O", oids);
  Py_DECREF(oids);
  if (!ares) {
    set_err();
    return fail();
  }
  // helper returns how many it adopted (0 when not recording): mirror the
  // now-live ids so later invokes skip marshaling their bytes
  if (PyLong_AsLong(ares) == n_out && n_out > 0)
    for (Py_ssize_t i = 0; i < n_out; ++i)
      autograd_adopted().insert(caller_provided ? (*outputs)[i]
                                                : out_handles[i]);
  Py_DECREF(ares);
  return 0;
}

// ---- imperative autograd (reference: c_api.h:549-601 MXAutogradSetIsTraining
// / MarkVariables / ComputeGradient over src/ndarray/autograd.cc; here the
// tape + jax.vjp replay in mxnet_tpu.contrib.autograd) ----------------------

MXNET_DLL int MXAutogradSetIsTraining(int is_training, int* prev) {
  GilT gil;
  if (is_training) g_autograd_used.store(true, std::memory_order_release);
  PyObject* res = PyObject_CallMethod(
      train_module(), "_c_autograd_set_is_training", "i", is_training);
  if (!res) {
    set_err();
    return fail();
  }
  if (prev) *prev = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXAutogradMarkVariables(mx_uint num_var,
                                      NDArrayHandle* var_handles,
                                      mx_uint* reqs_array,
                                      NDArrayHandle* grad_handles) {
  GilT gil;
  g_autograd_used.store(true, std::memory_order_release);
  for (mx_uint i = 0; i < num_var; ++i)
    autograd_marked().insert(var_handles[i]);
  PyObject* ids = PyList_New(num_var);
  PyObject* blobs = PyList_New(num_var);
  PyObject* shapes = PyList_New(num_var);
  PyObject* dtypes = PyList_New(num_var);
  PyObject* reqs = PyList_New(num_var);
  PyObject* gids = PyList_New(num_var);
  PyObject* gblobs = PyList_New(num_var);
  for (mx_uint i = 0; i < num_var; ++i) {
    auto* v = static_cast<CArray*>(var_handles[i]);
    auto* g = static_cast<CArray*>(grad_handles[i]);
    PyList_SetItem(ids, i, PyLong_FromVoidPtr(var_handles[i]));
    PyList_SetItem(blobs, i,
                   PyBytes_FromStringAndSize(
                       reinterpret_cast<const char*>(v->data.data()),
                       v->data.size()));
    PyObject* dims = PyList_New(v->shape.size());
    for (size_t j = 0; j < v->shape.size(); ++j)
      PyList_SetItem(dims, j, PyLong_FromUnsignedLong(v->shape[j]));
    PyList_SetItem(shapes, i, dims);
    PyList_SetItem(dtypes, i, PyLong_FromLong(v->dtype));
    PyList_SetItem(reqs, i, PyLong_FromUnsignedLong(reqs_array[i]));
    PyList_SetItem(gids, i, PyLong_FromVoidPtr(grad_handles[i]));
    PyList_SetItem(gblobs, i,
                   PyBytes_FromStringAndSize(
                       reinterpret_cast<const char*>(g->data.data()),
                       g->data.size()));
  }
  PyObject* res = PyObject_CallMethod(
      train_module(), "_c_autograd_mark_variables", "OOOOOOO", ids, blobs,
      shapes, dtypes, reqs, gids, gblobs);
  Py_DECREF(ids);
  Py_DECREF(blobs);
  Py_DECREF(shapes);
  Py_DECREF(dtypes);
  Py_DECREF(reqs);
  Py_DECREF(gids);
  Py_DECREF(gblobs);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXAutogradComputeGradient(mx_uint num_output,
                                        NDArrayHandle* output_handles) {
  GilT gil;
  // the python session drops adopted intermediates after backward (marked
  // variables stay live) — mirror that here
  autograd_adopted().clear();
  PyObject* heads = PyList_New(num_output);
  for (mx_uint i = 0; i < num_output; ++i)
    PyList_SetItem(heads, i, PyLong_FromVoidPtr(output_handles[i]));
  PyObject* res = PyObject_CallMethod(
      train_module(), "_c_autograd_compute_gradient", "O", heads);
  Py_DECREF(heads);
  if (!res) {
    set_err();
    return fail();
  }
  // [(grad handle id, bytes, shape, dtype), ...] -> write into the grad
  // handles the caller registered via MXAutogradMarkVariables
  if (!PyList_Check(res)) {
    Py_DECREF(res);
    mxtpu_set_train_error("autograd: helper did not return a list");
    return fail();
  }
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i) {
    PyObject* row = PyList_GetItem(res, i);
    PyObject *gid = nullptr, *blob = nullptr, *shp = nullptr, *dt = nullptr;
    if (!PyArg_ParseTuple(row, "OOOO", &gid, &blob, &shp, &dt)) {
      Py_DECREF(res);
      set_err();
      return fail();
    }
    auto* g = static_cast<CArray*>(PyLong_AsVoidPtr(gid));
    char* buf = nullptr;
    Py_ssize_t len = 0;
    if (!g || PyBytes_AsStringAndSize(blob, &buf, &len) != 0) {
      Py_DECREF(res);
      set_err();
      return fail();
    }
    g->shape.clear();
    for (Py_ssize_t j = 0; j < PyList_Size(shp); ++j)
      g->shape.push_back(static_cast<mx_uint>(
          PyLong_AsUnsignedLong(PyList_GetItem(shp, j))));
    g->dtype = static_cast<int>(PyLong_AsLong(dt));
    g->data.assign(buf, buf + len);
    g->none = false;
  }
  Py_DECREF(res);
  return 0;
}

namespace {

// thread-local result tables for the three InferShape shape lists
struct ShapeTable {
  std::vector<std::vector<mx_uint>> shapes;
  std::vector<mx_uint> ndims;
  std::vector<const mx_uint*> ptrs;
  void load(PyObject* list) {
    shapes.clear();
    ndims.clear();
    ptrs.clear();
    for (Py_ssize_t i = 0; i < PyList_Size(list); ++i) {
      PyObject* s = PyList_GetItem(list, i);
      std::vector<mx_uint> dims;
      for (Py_ssize_t j = 0; j < PyList_Size(s); ++j)
        dims.push_back(static_cast<mx_uint>(
            PyLong_AsUnsignedLong(PyList_GetItem(s, j))));
      shapes.push_back(std::move(dims));
    }
    for (auto& s : shapes) {
      ndims.push_back(static_cast<mx_uint>(s.size()));
      ptrs.push_back(s.data());
    }
  }
};

int infer_shape_impl(SymbolHandle sym, mx_uint num_args, const char** keys,
                     const mx_uint* arg_ind_ptr,
                     const mx_uint* arg_shape_data, mx_uint* in_shape_size,
                     const mx_uint** in_shape_ndim,
                     const mx_uint*** in_shape_data, mx_uint* out_shape_size,
                     const mx_uint** out_shape_ndim,
                     const mx_uint*** out_shape_data, mx_uint* aux_shape_size,
                     const mx_uint** aux_shape_ndim,
                     const mx_uint*** aux_shape_data, int* complete,
                     int partial) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* key_list = PyList_New(num_args);
  PyObject* shape_list = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SetItem(key_list, i,
                   PyUnicode_FromString(keys ? keys[i] : ""));
    mx_uint lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject* dims = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(dims, j - lo, PyLong_FromUnsignedLong(arg_shape_data[j]));
    PyList_SetItem(shape_list, i, dims);
  }
  if (!keys) {
    // positional form: helper maps onto list_arguments order
    Py_DECREF(key_list);
    key_list = PyList_New(0);
  }
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_infer_shape", "OOOi", s->obj,
                          key_list, shape_list, partial);
  Py_DECREF(key_list);
  Py_DECREF(shape_list);
  if (!res) {
    set_err();
    return fail();
  }
  PyObject *in_l = nullptr, *out_l = nullptr, *aux_l = nullptr;
  int comp = 0;
  if (!PyArg_ParseTuple(res, "OOOi", &in_l, &out_l, &aux_l, &comp)) {
    Py_DECREF(res);
    set_err();
    return fail();
  }
  thread_local ShapeTable t_in, t_out, t_aux;
  t_in.load(in_l);
  t_out.load(out_l);
  t_aux.load(aux_l);
  Py_DECREF(res);
  *in_shape_size = static_cast<mx_uint>(t_in.shapes.size());
  *in_shape_ndim = t_in.ndims.data();
  *in_shape_data = t_in.ptrs.data();
  *out_shape_size = static_cast<mx_uint>(t_out.shapes.size());
  *out_shape_ndim = t_out.ndims.data();
  *out_shape_data = t_out.ptrs.data();
  *aux_shape_size = static_cast<mx_uint>(t_aux.shapes.size());
  *aux_shape_ndim = t_aux.ndims.data();
  *aux_shape_data = t_aux.ptrs.data();
  *complete = comp;
  return 0;
}

}  // namespace

MXNET_DLL int MXSymbolInferShape(
    SymbolHandle sym, mx_uint num_args, const char** keys,
    const mx_uint* arg_ind_ptr, const mx_uint* arg_shape_data,
    mx_uint* in_shape_size, const mx_uint** in_shape_ndim,
    const mx_uint*** in_shape_data, mx_uint* out_shape_size,
    const mx_uint** out_shape_ndim, const mx_uint*** out_shape_data,
    mx_uint* aux_shape_size, const mx_uint** aux_shape_ndim,
    const mx_uint*** aux_shape_data, int* complete) {
  return infer_shape_impl(sym, num_args, keys, arg_ind_ptr, arg_shape_data,
                          in_shape_size, in_shape_ndim, in_shape_data,
                          out_shape_size, out_shape_ndim, out_shape_data,
                          aux_shape_size, aux_shape_ndim, aux_shape_data,
                          complete, 0);
}

MXNET_DLL int MXSymbolInferShapePartial(
    SymbolHandle sym, mx_uint num_args, const char** keys,
    const mx_uint* arg_ind_ptr, const mx_uint* arg_shape_data,
    mx_uint* in_shape_size, const mx_uint** in_shape_ndim,
    const mx_uint*** in_shape_data, mx_uint* out_shape_size,
    const mx_uint** out_shape_ndim, const mx_uint*** out_shape_data,
    mx_uint* aux_shape_size, const mx_uint** aux_shape_ndim,
    const mx_uint*** aux_shape_data, int* complete) {
  return infer_shape_impl(sym, num_args, keys, arg_ind_ptr, arg_shape_data,
                          in_shape_size, in_shape_ndim, in_shape_data,
                          out_shape_size, out_shape_ndim, out_shape_data,
                          aux_shape_size, aux_shape_ndim, aux_shape_data,
                          complete, 1);
}

MXNET_DLL int MXRandomSeed(int seed) {
  GilT gil;
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_random_seed", "i", seed);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXNotifyShutdown(void) {
  // engine drain point in the reference; XLA dispatch is synchronized per
  // call here, so nothing is pending
  return 0;
}

// ---- Symbol long tail (reference c_api.h: CreateFromFile :722, SaveToFile
// :745, Copy :760, Print :768, GetName :776, CreateGroup :713, GetInternals
// :795, GetOutput :811, GetAttr :784, SetAttr :800, ListAttr :816,
// GetAtomicSymbolInfo :644, InferType :888) --------------------------------

namespace {

int sym_from_call(PyObject* res, SymbolHandle* out) {
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CSym{res};
  return 0;
}

int str_from_call(PyObject* res, const char** out) {
  if (!res) {
    set_err();
    return fail();
  }
  thread_local std::string ret;
  const char* s = PyUnicode_AsUTF8(res);
  if (!s) {
    Py_DECREF(res);
    set_err();
    return fail();
  }
  ret = s;
  Py_DECREF(res);
  *out = ret.c_str();
  return 0;
}

}  // namespace

MXNET_DLL int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out) {
  GilT gil;
  return sym_from_call(
      PyObject_CallMethod(train_module(), "_c_symbol_from_file", "s", fname),
      out);
}

MXNET_DLL int MXSymbolSaveToFile(SymbolHandle sym, const char* fname) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_symbol_save_file",
                                      "Os", s->obj, fname);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXSymbolCopy(SymbolHandle sym, SymbolHandle* out) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return sym_from_call(
      PyObject_CallMethod(train_module(), "_c_symbol_copy", "O", s->obj), out);
}

MXNET_DLL int MXSymbolPrint(SymbolHandle sym, const char** out_str) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return str_from_call(
      PyObject_CallMethod(train_module(), "_c_symbol_print", "O", s->obj),
      out_str);
}

MXNET_DLL int MXSymbolGetName(SymbolHandle sym, const char** out,
                              int* success) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  int rc = str_from_call(
      PyObject_CallMethod(train_module(), "_c_symbol_name", "O", s->obj), out);
  if (rc == 0 && success) *success = (**out != '\0');
  return rc;
}

MXNET_DLL int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle* symbols,
                                  SymbolHandle* out) {
  GilT gil;
  PyObject* lst = PyList_New(num_symbols);
  for (mx_uint i = 0; i < num_symbols; ++i) {
    PyObject* o = static_cast<CSym*>(symbols[i])->obj;
    Py_INCREF(o);
    PyList_SetItem(lst, i, o);
  }
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_symbol_group", "O", lst);
  Py_DECREF(lst);
  return sym_from_call(res, out);
}

MXNET_DLL int MXSymbolGetInternals(SymbolHandle sym, SymbolHandle* out) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return sym_from_call(
      PyObject_CallMethod(train_module(), "_c_symbol_internals", "O", s->obj),
      out);
}

MXNET_DLL int MXSymbolGetOutput(SymbolHandle sym, mx_uint index,
                                SymbolHandle* out) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return sym_from_call(
      PyObject_CallMethod(train_module(), "_c_symbol_get_output", "OI",
                          s->obj, index),
      out);
}

MXNET_DLL int MXSymbolGetAttr(SymbolHandle sym, const char* key,
                              const char** out, int* success) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_symbol_attr", "Os",
                                      s->obj, key);
  if (!res) {
    set_err();
    return fail();
  }
  const char* val = nullptr;
  int found = 0;
  if (!PyArg_ParseTuple(res, "si", &val, &found)) {
    Py_DECREF(res);
    set_err();
    return fail();
  }
  thread_local std::string ret;
  ret = val;
  Py_DECREF(res);
  *out = ret.c_str();
  *success = found;
  return 0;
}

MXNET_DLL int MXSymbolSetAttr(SymbolHandle sym, const char* key,
                              const char* value) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_symbol_set_attr",
                                      "Oss", s->obj, key, value);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

namespace {

int list_attr_impl(SymbolHandle sym, int recursive, mx_uint* out_size,
                   const char*** out) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* res = PyObject_CallMethod(
      train_module(), "_c_symbol_list_attr", "Oi", s->obj, recursive);
  if (!res) {
    set_err();
    return fail();
  }
  PyObject *keys = nullptr, *vals = nullptr;
  if (!PyArg_ParseTuple(res, "OO", &keys, &vals)) {
    Py_DECREF(res);
    set_err();
    return fail();
  }
  // reference layout: flat [key0, val0, key1, val1, ...]
  thread_local std::vector<std::string> kv;
  thread_local std::vector<const char*> ptrs;
  kv.clear();
  ptrs.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(keys); ++i) {
    const char* k = PyUnicode_AsUTF8(PyList_GetItem(keys, i));
    const char* v = PyUnicode_AsUTF8(PyList_GetItem(vals, i));
    if (!k || !v) {
      Py_DECREF(res);
      set_err();
      return fail();
    }
    kv.emplace_back(k);
    kv.emplace_back(v);
  }
  Py_DECREF(res);
  for (auto& x : kv) ptrs.push_back(x.c_str());
  *out_size = static_cast<mx_uint>(kv.size() / 2);
  *out = ptrs.data();
  return 0;
}

}  // namespace

MXNET_DLL int MXSymbolListAttr(SymbolHandle sym, mx_uint* out_size,
                               const char*** out) {
  return list_attr_impl(sym, 1, out_size, out);
}

MXNET_DLL int MXSymbolListAttrShallow(SymbolHandle sym, mx_uint* out_size,
                                      const char*** out) {
  return list_attr_impl(sym, 0, out_size, out);
}

MXNET_DLL int MXSymbolGetAtomicSymbolInfo(
    AtomicSymbolCreator creator, const char** name, const char** description,
    mx_uint* num_args, const char*** arg_names, const char*** arg_type_infos,
    const char*** arg_descriptions, const char** key_var_num_args) {
  GilT gil;
  if (!creator) {
    mxtpu_set_train_error("null creator");
    return fail();
  }
  const std::string& op = *static_cast<std::string*>(creator);
  PyObject* res = PyObject_CallMethod(
      train_module(), "_c_atomic_symbol_info", "s", op.c_str());
  if (!res) {
    set_err();
    return fail();
  }
  PyObject *doc = nullptr, *keys = nullptr, *types = nullptr, *descs = nullptr;
  if (!PyArg_ParseTuple(res, "OOOO", &doc, &keys, &types, &descs)) {
    Py_DECREF(res);
    set_err();
    return fail();
  }
  thread_local std::string t_name, t_doc, t_kvna;
  thread_local std::vector<std::string> t_strs;
  thread_local std::vector<const char*> t_keys, t_types, t_descs;
  t_name = op;
  t_doc = PyUnicode_AsUTF8(doc) ? PyUnicode_AsUTF8(doc) : "";
  t_kvna = "";
  t_strs.clear();
  t_keys.clear();
  t_types.clear();
  t_descs.clear();
  Py_ssize_t n = PyList_Size(keys);
  // reserve so c_str() pointers stay stable while filling
  t_strs.reserve(3 * n);
  for (Py_ssize_t i = 0; i < n; ++i) {
    t_strs.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(keys, i)));
    t_keys.push_back(t_strs.back().c_str());
    t_strs.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(types, i)));
    t_types.push_back(t_strs.back().c_str());
    t_strs.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(descs, i)));
    t_descs.push_back(t_strs.back().c_str());
  }
  Py_DECREF(res);
  *name = t_name.c_str();
  *description = t_doc.c_str();
  *num_args = static_cast<mx_uint>(n);
  *arg_names = t_keys.data();
  *arg_type_infos = t_types.data();
  *arg_descriptions = t_descs.data();
  if (key_var_num_args) *key_var_num_args = t_kvna.c_str();
  return 0;
}

MXNET_DLL int MXSymbolInferType(SymbolHandle sym, mx_uint num_args,
                                const char** keys, const int* arg_type_data,
                                mx_uint* in_type_size, const int** in_type_data,
                                mx_uint* out_type_size,
                                const int** out_type_data,
                                mx_uint* aux_type_size,
                                const int** aux_type_data, int* complete) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* key_list = PyList_New(num_args);
  PyObject* type_list = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SetItem(key_list, i, PyUnicode_FromString(keys[i]));
    PyList_SetItem(type_list, i, PyLong_FromLong(arg_type_data[i]));
  }
  PyObject* res = PyObject_CallMethod(train_module(), "_c_infer_type", "OOO",
                                      s->obj, key_list, type_list);
  Py_DECREF(key_list);
  Py_DECREF(type_list);
  if (!res) {
    set_err();
    return fail();
  }
  PyObject *in_l = nullptr, *out_l = nullptr, *aux_l = nullptr;
  int comp = 0;
  if (!PyArg_ParseTuple(res, "OOOi", &in_l, &out_l, &aux_l, &comp)) {
    Py_DECREF(res);
    set_err();
    return fail();
  }
  thread_local std::vector<int> t_in, t_out, t_aux;
  auto load = [](PyObject* l, std::vector<int>* v) {
    v->clear();
    for (Py_ssize_t i = 0; i < PyList_Size(l); ++i)
      v->push_back(static_cast<int>(PyLong_AsLong(PyList_GetItem(l, i))));
  };
  load(in_l, &t_in);
  load(out_l, &t_out);
  load(aux_l, &t_aux);
  Py_DECREF(res);
  *in_type_size = static_cast<mx_uint>(t_in.size());
  *in_type_data = t_in.data();
  *out_type_size = static_cast<mx_uint>(t_out.size());
  *out_type_data = t_out.data();
  *aux_type_size = static_cast<mx_uint>(t_aux.size());
  *aux_type_data = t_aux.data();
  *complete = comp;
  return 0;
}

MXNET_DLL int MXExecutorPrint(ExecutorHandle h, const char** out_str) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* sym = PyObject_GetAttrString(e->obj, "executor");
  PyObject* res = nullptr;
  if (sym) {
    PyObject* dbg = PyObject_CallMethod(sym, "debug_str", NULL);
    Py_DECREF(sym);
    res = dbg;
  }
  return str_from_call(res, out_str);
}

// ---- KVStore long tail (reference c_api.h: GetType :1239, role predicates
// :1288-1304, Barrier :1312) -----------------------------------------------

MXNET_DLL int MXKVStoreGetType(KVStoreHandle h, const char** out) {
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  return str_from_call(
      PyObject_CallMethod(train_module(), "_c_kv_type", "O", kv->obj), out);
}

MXNET_DLL int MXKVStoreIsWorkerNode(int* ret) {
  const char* role = getenv("DMLC_ROLE");
  *ret = (!role || strcmp(role, "worker") == 0) ? 1 : 0;
  return 0;
}

MXNET_DLL int MXKVStoreIsServerNode(int* ret) {
  const char* role = getenv("DMLC_ROLE");
  *ret = (role && strcmp(role, "server") == 0) ? 1 : 0;
  return 0;
}

MXNET_DLL int MXKVStoreIsSchedulerNode(int* ret) {
  const char* role = getenv("DMLC_ROLE");
  *ret = (role && strcmp(role, "scheduler") == 0) ? 1 : 0;
  return 0;
}

MXNET_DLL int MXKVStoreBarrier(KVStoreHandle h) {
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_kv_barrier", "O", kv->obj);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

// ---- final long-tail wrappers (reference c_api.h: GetChildren :803,
// ExecutorOutputs :1010, DataIterCreateIter :1120, InitPSEnv :1227,
// SendCommmandToServers :1341, GetNumDeadNode :1354) -----------------------

MXNET_DLL int MXSymbolGetChildren(SymbolHandle sym, SymbolHandle* out) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  return sym_from_call(
      PyObject_CallMethod(train_module(), "_c_symbol_children", "O", s->obj),
      out);
}

// exact reference name for the iterator factory (this library's
// MXDataIterCreate is the same function with the same signature)
MXNET_DLL int MXDataIterCreateIter(const char* handle, mx_uint num_param,
                                   const char** keys, const char** vals,
                                   DataIterHandle* out) {
  return MXDataIterCreate(handle, num_param, keys, vals, out);
}

MXNET_DLL int MXExecutorOutputs(ExecutorHandle h, mx_uint* out_size,
                                NDArrayHandle** out) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_exec_outputs", "O", e->obj);
  if (!res) {
    set_err();
    return fail();
  }
  if (!PyList_Check(res)) {
    Py_DECREF(res);
    mxtpu_set_train_error("_c_exec_outputs: expected a list");
    return fail();
  }
  thread_local std::vector<NDArrayHandle> handles;
  // handles returned here are caller-freed (MXNDArrayFree), matching
  // MXImperativeInvoke's allocation contract
  handles.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i) {
    PyObject* tup = PyList_GetItem(res, i);
    PyObject* blob = nullptr;
    PyObject* shp = nullptr;
    if (!PyArg_ParseTuple(tup, "SO", &blob, &shp)) {
      Py_DECREF(res);
      set_err();
      return fail();
    }
    auto* arr = new CArray();
    arr->dtype = 0;
    for (Py_ssize_t j = 0; j < PyList_Size(shp); ++j)
      arr->shape.push_back(static_cast<mx_uint>(
          PyLong_AsUnsignedLong(PyList_GetItem(shp, j))));
    char* buf = nullptr;
    Py_ssize_t len = 0;
    PyBytes_AsStringAndSize(blob, &buf, &len);
    arr->data.assign(buf, buf + len);
    handles.push_back(arr);
  }
  Py_DECREF(res);
  *out_size = static_cast<mx_uint>(handles.size());
  *out = handles.data();
  return 0;
}

MXNET_DLL int MXInitPSEnv(mx_uint num_vars, const char** keys,
                          const char** vals) {
  for (mx_uint i = 0; i < num_vars; ++i) setenv(keys[i], vals[i], 1);
  return 0;
}

MXNET_DLL int MXKVStoreSendCommmandToServers(KVStoreHandle h, int cmd_head,
                                             const char* cmd_body) {
  GilT gil;
  auto* kv = static_cast<CKV*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_kv_send_command",
                                      "Ois", kv->obj, cmd_head, cmd_body);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXKVStoreGetNumDeadNode(KVStoreHandle h, int node_id,
                                      int* number, int timeout_sec) {
  GilT gil;
  (void)timeout_sec;
  auto* kv = static_cast<CKV*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_kv_num_dead_node",
                                      "Oi", kv->obj, node_id);
  if (!res) {
    set_err();
    return fail();
  }
  *number = static_cast<int>(PyLong_AsLong(res));
  Py_DECREF(res);
  return 0;
}
