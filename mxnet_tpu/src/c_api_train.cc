// Training-side C API slice (reference: include/mxnet/c_api.h — the Symbol /
// Executor function families: MXSymbolCreateFromJSON, MXExecutorForward,
// MXExecutorBackward, ...). The predict subset lives in c_predict_api.cc;
// this file adds enough surface for a pure C/C++ client to run a full
// training loop: symbol-from-JSON -> simple_bind -> set args -> forward ->
// backward -> read grads/outputs -> in-framework SGD update.
//
// Same embedding design as the predict shim: CPython is initialized lazily,
// every entry point holds the GIL, and the heavy lifting happens in
// mxnet_tpu.capi_train (whose executor is the XLA-compiled one — the compute
// path is identical to the Python surface's). Compiled client test:
// tests/test_c_train.py.
#include <Python.h>

#include <cstring>
#include <string>
#include <vector>

#define MXNET_DLL extern "C" __attribute__((visibility("default")))

typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef unsigned int mx_uint;

// GIL/env scaffolding shared with the predict shim (defined there when both
// files link into one library).
extern thread_local std::string g_last_error_train;
thread_local std::string g_last_error_train;

namespace {

struct GilT {
  GilT() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
#if PY_VERSION_HEX < 0x03090000
      PyEval_InitThreads();
#endif
      PyEval_SaveThread();
    }
    st = PyGILState_Ensure();
  }
  ~GilT() { PyGILState_Release(st); }
  PyGILState_STATE st;
};

void set_err() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error_train = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* msg = PyUnicode_AsUTF8(s);
      if (msg) g_last_error_train = msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* train_module() {
  static PyObject* mod = nullptr;  // borrowed forever once imported
  if (!mod) {
    mod = PyImport_ImportModule("mxnet_tpu.capi_train");
    if (!mod) set_err();
  }
  return mod;
}

struct CSym {
  PyObject* obj;
};
struct CExec {
  PyObject* obj;
  // stable storage for string lists returned to C
  std::vector<std::string> names;
  std::vector<const char*> name_ptrs;
  std::vector<mx_uint> shape;
  std::vector<char> blob;
};

int fail() { return -1; }

}  // namespace

MXNET_DLL const char* MXTrainGetLastError() {
  return g_last_error_train.c_str();
}

MXNET_DLL int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  GilT gil;
  PyObject* mod = train_module();
  if (!mod) return fail();
  PyObject* res = PyObject_CallMethod(mod, "_c_symbol_from_json", "s", json);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CSym{res};
  return 0;
}

MXNET_DLL int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_symbol_to_json", "O", s->obj);
  if (!res) {
    set_err();
    return fail();
  }
  thread_local std::string json;
  json = PyUnicode_AsUTF8(res);
  Py_DECREF(res);
  *out_json = json.c_str();
  return 0;
}

MXNET_DLL int MXSymbolFree(SymbolHandle sym) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  Py_XDECREF(s->obj);
  delete s;
  return 0;
}

// simple_bind: shapes as CSR (keys + flat dims + row offsets), the
// reference's shape-argument convention (c_api.h MXExecutorSimpleBind).
MXNET_DLL int MXExecutorSimpleBindLite(SymbolHandle sym, const char* dev_type,
                                       int dev_id, mx_uint num_args,
                                       const char** keys,
                                       const mx_uint* arg_shape_data,
                                       const mx_uint* arg_shape_idx,
                                       const char* grad_req,
                                       ExecutorHandle* out) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* key_list = PyList_New(num_args);
  PyObject* shape_list = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    PyList_SetItem(key_list, i, PyUnicode_FromString(keys[i]));
    mx_uint lo = arg_shape_idx[i], hi = arg_shape_idx[i + 1];
    PyObject* dims = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(dims, j - lo, PyLong_FromUnsignedLong(arg_shape_data[j]));
    PyList_SetItem(shape_list, i, dims);
  }
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_simple_bind", "OsiOOs", s->obj,
                          dev_type, dev_id, key_list, shape_list, grad_req);
  Py_DECREF(key_list);
  Py_DECREF(shape_list);
  if (!res) {
    set_err();
    return fail();
  }
  *out = new CExec{res, {}, {}, {}, {}};
  return 0;
}

MXNET_DLL int MXExecutorFree(ExecutorHandle h) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  Py_XDECREF(e->obj);
  delete e;
  return 0;
}

MXNET_DLL int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                                    const char*** out_array) {
  GilT gil;
  auto* s = static_cast<CSym*>(sym);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_symbol_arguments", "O", s->obj);
  if (!res) {
    set_err();
    return fail();
  }
  thread_local std::vector<std::string> names;
  thread_local std::vector<const char*> ptrs;
  names.clear();
  ptrs.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i)
    names.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(res, i)));
  Py_DECREF(res);
  for (auto& n : names) ptrs.push_back(n.c_str());
  *out_size = static_cast<mx_uint>(names.size());
  *out_array = ptrs.data();
  return 0;
}

MXNET_DLL int MXExecutorSetArg(ExecutorHandle h, const char* name,
                               const float* data, mx_uint size) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* blob = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), size * sizeof(float));
  PyObject* res = PyObject_CallMethod(train_module(), "_c_set_arg", "OsO",
                                      e->obj, name, blob);
  Py_DECREF(blob);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

namespace {

int get_array(CExec* e, const char* which, PyObject* key, const float** out,
              mx_uint* out_size) {
  PyObject* res = PyObject_CallMethod(train_module(), "_c_get_array", "OsO",
                                      e->obj, which, key);
  Py_DECREF(key);
  if (!res) {
    set_err();
    return fail();
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0) {
    Py_DECREF(res);
    set_err();
    return fail();
  }
  e->blob.assign(buf, buf + len);
  Py_DECREF(res);
  *out = reinterpret_cast<const float*>(e->blob.data());
  *out_size = static_cast<mx_uint>(len / sizeof(float));
  return 0;
}

}  // namespace

MXNET_DLL int MXExecutorGetArg(ExecutorHandle h, const char* name,
                               const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "arg", PyUnicode_FromString(name),
                   out, out_size);
}

MXNET_DLL int MXExecutorGetGrad(ExecutorHandle h, const char* name,
                                const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "grad", PyUnicode_FromString(name),
                   out, out_size);
}

MXNET_DLL int MXExecutorGetOutput(ExecutorHandle h, mx_uint index,
                                  const float** out, mx_uint* out_size) {
  GilT gil;
  return get_array(static_cast<CExec*>(h), "output", PyLong_FromLong(index),
                   out, out_size);
}

MXNET_DLL int MXExecutorOutputShape(ExecutorHandle h, mx_uint index,
                                    const mx_uint** out_shape,
                                    mx_uint* out_dim) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_get_shape", "OsI",
                                      e->obj, "output", index);
  if (!res) {
    set_err();
    return fail();
  }
  e->shape.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i)
    e->shape.push_back(
        static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(res, i))));
  Py_DECREF(res);
  *out_shape = e->shape.data();
  *out_dim = static_cast<mx_uint>(e->shape.size());
  return 0;
}

MXNET_DLL int MXExecutorForward(ExecutorHandle h, int is_train) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_forward", "Oi",
                                      e->obj, is_train);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorBackward(ExecutorHandle h, mx_uint, void**) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res =
      PyObject_CallMethod(train_module(), "_c_backward", "O", e->obj);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorSGDUpdate(ExecutorHandle h, float lr, float wd) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_sgd_update", "Off",
                                      e->obj, static_cast<double>(lr),
                                      static_cast<double>(wd));
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXExecutorInitXavier(ExecutorHandle h, int seed) {
  GilT gil;
  auto* e = static_cast<CExec*>(h);
  PyObject* res = PyObject_CallMethod(train_module(), "_c_init_xavier", "Oi",
                                      e->obj, seed);
  if (!res) {
    set_err();
    return fail();
  }
  Py_DECREF(res);
  return 0;
}
