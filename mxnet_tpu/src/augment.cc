// Native classification augmenters for the decode stage (pipe.cc):
// resize-shortest-edge, center/random crop, horizontal flip — the subset of
// image.py's CreateAugmenter list that ImageRecordIter(backend='native')
// accepts (reference: src/io/image_aug_default.cc DefaultImageAugmenter,
// python mirror image.py resize_short/scale_down/fixed_crop).
//
// The resampler reproduces Pillow's Resample.c 8bpc path exactly — triangle
// filter, two passes (horizontal then vertical), fixed-point coefficients at
// PRECISION_BITS with per-pass rounding to uint8 — because the PIL path in
// image.py is the correctness oracle: a "close enough" float bilinear would
// put every resized pixel ±1 off the oracle and drown real bugs in the
// parity test's tolerance.

#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

#include "include/pipe_api.h"

namespace mxt_aug {

// ---- Pillow-parity bilinear resample --------------------------------------

// Pillow src/libImaging/Resample.c: 8 bits for result, 2 for intermediate
// rounding headroom.
constexpr int kPrecisionBits = 32 - 8 - 2;

inline uint8_t clip8(int32_t v) {
  if (v >= (1 << kPrecisionBits) << 8) return 255;
  if (v <= 0) return 0;
  return static_cast<uint8_t>(v >> kPrecisionBits);
}

inline double triangle_filter(double x) {
  if (x < 0.0) x = -x;
  return x < 1.0 ? 1.0 - x : 0.0;
}

struct Coeffs {
  int ksize = 0;
  std::vector<int> bounds;   // per output index: (first input index, count)
  std::vector<int32_t> kk;   // fixed-point weights, ksize per output index
};

// Pillow precompute_coeffs + normalize_coeffs_8bpc for the full-image box.
static Coeffs precompute(int in_size, int out_size) {
  double scale = static_cast<double>(in_size) / out_size;
  double filterscale = scale < 1.0 ? 1.0 : scale;
  double support = filterscale;  // triangle filter support = 1.0
  int ksize = static_cast<int>(std::ceil(support)) * 2 + 1;
  Coeffs co;
  co.ksize = ksize;
  co.bounds.resize(static_cast<size_t>(out_size) * 2);
  std::vector<double> prekk(static_cast<size_t>(out_size) * ksize, 0.0);
  for (int xx = 0; xx < out_size; ++xx) {
    double center = (xx + 0.5) * scale;
    double ss = 1.0 / filterscale;
    int xmin = static_cast<int>(center - support + 0.5);
    if (xmin < 0) xmin = 0;
    int xmax = static_cast<int>(center + support + 0.5);
    if (xmax > in_size) xmax = in_size;
    xmax -= xmin;
    double* k = &prekk[static_cast<size_t>(xx) * ksize];
    double ww = 0.0;
    int x = 0;
    for (; x < xmax; ++x) {
      double w = triangle_filter((x + xmin - center + 0.5) * ss) * ss;
      k[x] = w;
      ww += w;
    }
    for (x = 0; x < xmax; ++x) {
      if (ww != 0.0) k[x] /= ww;
    }
    co.bounds[xx * 2 + 0] = xmin;
    co.bounds[xx * 2 + 1] = xmax;
  }
  co.kk.resize(prekk.size());
  for (size_t i = 0; i < prekk.size(); ++i) {
    double v = prekk[i] * (1 << kPrecisionBits);
    co.kk[i] = static_cast<int32_t>(v < 0 ? v - 0.5 : v + 0.5);
  }
  return co;
}

// horizontal pass: (h, sw, c) -> (h, dw, c)
static void resample_h(const uint8_t* src, int h, int sw, int c,
                       uint8_t* dst, int dw, const Coeffs& co) {
  for (int y = 0; y < h; ++y) {
    const uint8_t* in_row = src + static_cast<size_t>(y) * sw * c;
    uint8_t* out_row = dst + static_cast<size_t>(y) * dw * c;
    for (int xx = 0; xx < dw; ++xx) {
      int xmin = co.bounds[xx * 2 + 0];
      int xmax = co.bounds[xx * 2 + 1];
      const int32_t* k = &co.kk[static_cast<size_t>(xx) * co.ksize];
      for (int b = 0; b < c; ++b) {
        int32_t ss = 1 << (kPrecisionBits - 1);
        for (int x = 0; x < xmax; ++x)
          ss += in_row[(xmin + x) * c + b] * k[x];
        out_row[xx * c + b] = clip8(ss);
      }
    }
  }
}

// vertical pass: (sh, w, c) -> (dh, w, c)
static void resample_v(const uint8_t* src, int w, int c,
                       uint8_t* dst, int dh, const Coeffs& co) {
  for (int yy = 0; yy < dh; ++yy) {
    int ymin = co.bounds[yy * 2 + 0];
    int ymax = co.bounds[yy * 2 + 1];
    const int32_t* k = &co.kk[static_cast<size_t>(yy) * co.ksize];
    uint8_t* out_row = dst + static_cast<size_t>(yy) * w * c;
    for (int x = 0; x < w * c; ++x) {
      int32_t ss = 1 << (kPrecisionBits - 1);
      for (int y = 0; y < ymax; ++y)
        ss += src[static_cast<size_t>(ymin + y) * w * c + x] * k[y];
      out_row[x] = clip8(ss);
    }
  }
}

void resize_bilinear(const uint8_t* src, int sh, int sw, int c,
                     uint8_t* dst, int dh, int dw) {
  if (dh == sh && dw == sw) {  // Pillow skips no-op passes
    std::memcpy(dst, src, static_cast<size_t>(sh) * sw * c);
    return;
  }
  if (dw == sw) {
    resample_v(src, sw, c, dst, dh, precompute(sh, dh));
    return;
  }
  if (dh == sh) {
    resample_h(src, sh, sw, c, dst, dw, precompute(sw, dw));
    return;
  }
  // horizontal first, then vertical — Pillow's pass order, and the
  // intermediate rounds to uint8 exactly like Pillow's temp image
  std::vector<uint8_t> tmp(static_cast<size_t>(sh) * dw * c);
  resample_h(src, sh, sw, c, tmp.data(), dw, precompute(sw, dw));
  resample_v(tmp.data(), dw, c, dst, dh, precompute(sh, dh));
}

// ---- augmenter chain ------------------------------------------------------

// image.py scale_down: shrink the target rect to fit inside (sw, sh),
// preserving aspect, with the same float->int truncation.
void scale_down(int sw, int sh, int* w, int* h) {
  double tw = *w, th = *h;
  if (sh < th) {
    tw = tw * sh / th;
    th = sh;
  }
  if (sw < tw) {
    th = th * sw / tw;
    tw = sw;
  }
  *w = static_cast<int>(tw);
  *h = static_cast<int>(th);
}

// image.py resize_short_np: shorter edge -> size, integer-floor long edge.
void resize_short_dims(int w, int h, int size, int* nw, int* nh) {
  if (h > w) {
    *nw = size;
    *nh = static_cast<int>(static_cast<int64_t>(size) * h / w);
  } else {
    *nw = static_cast<int>(static_cast<int64_t>(size) * w / h);
    *nh = size;
  }
}

}  // namespace mxt_aug

extern "C" void mxt_resize_bilinear(const uint8_t* src, int sh, int sw, int c,
                                    uint8_t* dst, int dh, int dw) {
  mxt_aug::resize_bilinear(src, sh, sw, c, dst, dh, dw);
}
