// C NDArray API — host-array subset of the reference's c_api.h
// (include/mxnet/c_api.h: MXNDArrayCreate :244, CreateEx, CreateNone :236,
// Free, SyncCopyFromCPU/ToCPU :320-339, WaitToRead/All, GetShape :430,
// GetData :441, GetDType :450, GetContext :459, Save :301, Load :282).
//
// Pure C++ — no embedded Python: these arrays are host-side containers whose
// job is FFI data interchange and .params/.nd file IO in the reference's
// exact binary format (u64 0x112 list magic + u32 0xF993FAC8 per-array magic,
// src/ndarray/ndarray.cc:618-717 — byte-identical to mxnet_tpu/ndarray.py's
// writer, so files round-trip between C, Python, and the reference). Device
// placement is the Python/XLA layer's concern; dev_type is recorded for
// API fidelity but all storage is host memory (the predict API's Python
// bridge is the compute path for C clients).
//
// Build: part of libmxtpu_predict.so (`make c_predict`); a pure-C client
// exercises the surface in tests/test_c_predict.py.
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "include/c_array.h"
#include "include/ndarray_wire.h"

#define MXNET_DLL extern "C" __attribute__((visibility("default")))

typedef void* NDArrayHandle;
typedef unsigned int mx_uint;

// route errors into the predict shim's MXGetLastError (the one accessor
// c_api.h documents); defined in c_predict_api.cc, same .so
void mxtpu_set_last_error(const std::string& msg);

namespace {

constexpr uint64_t kListMagic = 0x112;
constexpr uint32_t kNDArrayMagic = 0xF993FAC8;

// type flag -> element size (reference mshadow type flags 0..6)
const int kDTypeSize[] = {4 /*f32*/, 8 /*f64*/, 2 /*f16*/, 1 /*u8*/,
                          4 /*i32*/, 1 /*i8*/, 8 /*i64*/};
constexpr int kNumDTypes = 7;

// per-process storage for Load's returned name/handle tables (the reference
// keeps equivalent ret_ vectors in its thread-local API registry)
struct LoadResult {
  std::vector<NDArrayHandle> handles;
  std::vector<std::string> names;
  std::vector<const char*> name_ptrs;
};
thread_local LoadResult g_load_result;

// overflow-checked element count: 0 on wrap (callers reject), mirroring
// the Python reader's exact-int product guard (ndarray.py:665-673)
size_t nelem_checked(const std::vector<mx_uint>& shape, bool* ok) {
  size_t n = 1;
  *ok = true;
  for (mx_uint s : shape) {
    if (s != 0 && n > SIZE_MAX / s) { *ok = false; return 0; }
    n *= s;
  }
  return n;
}

size_t nelem(const std::vector<mx_uint>& shape) {
  bool ok;
  return nelem_checked(shape, &ok);
}

int fail(const std::string& msg) {
  mxtpu_set_last_error(msg);
  return -1;
}

bool write_one(FILE* f, const CArray& a) {
  uint32_t ndim = a.none ? 0 : static_cast<uint32_t>(a.shape.size());
  if (fwrite(&kNDArrayMagic, 4, 1, f) != 1) return false;
  if (fwrite(&ndim, 4, 1, f) != 1) return false;
  if (ndim == 0) return true;  // none: readers stop at the shape (ndarray.py:663)
  for (mx_uint s : a.shape) {
    uint32_t v = s;
    if (fwrite(&v, 4, 1, f) != 1) return false;
  }
  int32_t ctx[2] = {1, 0};  // saved as cpu, like the reference
  if (fwrite(ctx, 4, 2, f) != 2) return false;
  int32_t flag = a.dtype;
  if (fwrite(&flag, 4, 1, f) != 1) return false;
  return fwrite(a.data.data(), 1, a.data.size(), f) == a.data.size();
}

bool read_exact(FILE* f, void* dst, size_t n) {
  return fread(dst, 1, n, f) == n;
}

CArray* read_one(FILE* f, std::string* err) {
  // shared wire-format reader (include/ndarray_wire.h); this API speaks the
  // strict reference format, so TPU-extension dtype flags are rejected
  mxt_ndwire::NdRecord rec;
  auto rd = [f](void* dst, size_t n) { return read_exact(f, dst, n); };
  if (!mxt_ndwire::read_ndarray_record(rd, &rec, err, kNumDTypes))
    return nullptr;
  auto arr = new CArray();
  arr->none = rec.none;
  arr->dtype = rec.dtype;
  arr->shape.assign(rec.shape.begin(), rec.shape.end());
  arr->data = std::move(rec.data);
  return arr;
}

}  // namespace

MXNET_DLL int MXNDArrayCreateNone(NDArrayHandle* out) {
  auto a = new CArray();
  a->none = true;
  *out = a;
  return 0;
}

MXNET_DLL int MXNDArrayCreateEx(const mx_uint* shape, mx_uint ndim,
                                int dev_type, int dev_id, int delay_alloc,
                                int dtype, NDArrayHandle* out) {
  if (dtype < 0 || dtype >= kNumDTypes) return fail("unknown dtype flag");
  auto a = new CArray();
  a->shape.assign(shape, shape + ndim);
  a->dtype = dtype;
  a->dev_type = dev_type;
  a->dev_id = dev_id;
  if (!delay_alloc) a->data.assign(nelem(a->shape) * kDTypeSize[dtype], 0);
  *out = a;
  return 0;
}

MXNET_DLL int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                              int dev_id, int delay_alloc, NDArrayHandle* out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc, 0, out);
}

// optional observer for handle teardown: the autograd session in
// c_api_train.cc installs itself here so freed handles are purged from its
// id->array maps (a recycled heap address must not resurrect a stale tape
// entry). Null when that family is unused or not linked in.
void (*mxtpu_ndarray_free_hook)(void*) = nullptr;

MXNET_DLL int MXNDArrayFree(NDArrayHandle handle) {
  if (mxtpu_ndarray_free_hook) mxtpu_ndarray_free_hook(handle);
  delete static_cast<CArray*>(handle);
  return 0;
}

MXNET_DLL int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                                       size_t size) {
  auto a = static_cast<CArray*>(handle);
  size_t bytes = size * kDTypeSize[a->dtype];
  if (size != nelem(a->shape)) return fail("size mismatch in SyncCopyFromCPU");
  a->data.resize(bytes);
  std::memcpy(a->data.data(), data, bytes);
  a->none = false;
  return 0;
}

MXNET_DLL int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data,
                                     size_t size) {
  auto a = static_cast<CArray*>(handle);
  size_t bytes = size * kDTypeSize[a->dtype];
  if (size != nelem(a->shape) || bytes > a->data.size())
    return fail("size mismatch in SyncCopyToCPU");
  std::memcpy(data, a->data.data(), bytes);
  return 0;
}

// host arrays are always materialized: waits are immediate (the async story
// lives in the Python/XLA layer)
MXNET_DLL int MXNDArrayWaitToRead(NDArrayHandle) { return 0; }
MXNET_DLL int MXNDArrayWaitToWrite(NDArrayHandle) { return 0; }
MXNET_DLL int MXNDArrayWaitAll() { return 0; }

MXNET_DLL int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                                const mx_uint** out_pdata) {
  auto a = static_cast<CArray*>(handle);
  *out_dim = static_cast<mx_uint>(a->shape.size());
  *out_pdata = a->shape.data();
  return 0;
}

MXNET_DLL int MXNDArrayGetData(NDArrayHandle handle, void** out_pdata) {
  auto a = static_cast<CArray*>(handle);
  *out_pdata = a->data.data();
  return 0;
}

MXNET_DLL int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  *out_dtype = static_cast<CArray*>(handle)->dtype;
  return 0;
}

MXNET_DLL int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                                  int* out_dev_id) {
  auto a = static_cast<CArray*>(handle);
  *out_dev_type = a->dev_type;
  *out_dev_id = a->dev_id;
  return 0;
}

MXNET_DLL int MXNDArraySave(const char* fname, mx_uint num_args,
                            NDArrayHandle* args, const char** keys) {
  FILE* f = std::fopen(fname, "wb");
  if (!f) return fail(std::string("cannot open ") + fname);
  // refuse to write a header whose data bytes cannot follow (delay_alloc
  // arrays never filled): a short blob would desync every later record
  for (mx_uint i = 0; i < num_args; ++i) {
    auto* a = static_cast<CArray*>(args[i]);
    if (!a->none && a->data.size() != nelem(a->shape) * kDTypeSize[a->dtype]) {
      std::fclose(f);
      return fail("array has no materialized data (delay_alloc unfilled)");
    }
  }
  bool ok = true;
  uint64_t header[3] = {kListMagic, 0, num_args};
  ok = fwrite(header, 8, 3, f) == 3;
  for (mx_uint i = 0; ok && i < num_args; ++i)
    ok = write_one(f, *static_cast<CArray*>(args[i]));
  uint64_t n_names = keys ? num_args : 0;
  ok = ok && fwrite(&n_names, 8, 1, f) == 1;
  for (mx_uint i = 0; ok && keys && i < num_args; ++i) {
    uint64_t len = std::strlen(keys[i]);
    ok = fwrite(&len, 8, 1, f) == 1 &&
         fwrite(keys[i], 1, len, f) == len;
  }
  std::fclose(f);
  return ok ? 0 : fail("short write");
}

MXNET_DLL int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                            NDArrayHandle** out_arr, mx_uint* out_name_size,
                            const char*** out_names) {
  FILE* f = std::fopen(fname, "rb");
  if (!f) return fail(std::string("cannot open ") + fname);
  uint64_t magic = 0, reserved = 0, count = 0;
  if (!read_exact(f, &magic, 8) || magic != kListMagic ||
      !read_exact(f, &reserved, 8) || !read_exact(f, &count, 8)) {
    std::fclose(f);
    return fail("invalid NDArray list file");
  }
  LoadResult res;
  std::string err;
  for (uint64_t i = 0; i < count; ++i) {
    CArray* a = read_one(f, &err);
    if (!a) {
      for (auto h : res.handles) delete static_cast<CArray*>(h);
      std::fclose(f);
      return fail(err);
    }
    res.handles.push_back(a);
  }
  uint64_t n_names = 0;
  if (read_exact(f, &n_names, 8) && n_names == count) {
    for (uint64_t i = 0; i < n_names; ++i) {
      uint64_t len;
      if (!read_exact(f, &len, 8) || len > (1u << 20)) {
        n_names = 0;
        res.names.clear();  // all-or-nothing: partial tables mis-associate
        break;
      }
      std::string name(len, '\0');
      if (!read_exact(f, name.data(), len)) {
        n_names = 0;
        res.names.clear();
        break;
      }
      res.names.push_back(std::move(name));
    }
  } else {
    n_names = 0;
  }
  std::fclose(f);
  for (auto& n : res.names) res.name_ptrs.push_back(n.c_str());
  g_load_result = std::move(res);
  *out_size = static_cast<mx_uint>(g_load_result.handles.size());
  *out_arr = g_load_result.handles.data();
  *out_name_size = static_cast<mx_uint>(g_load_result.names.size());
  *out_names = g_load_result.name_ptrs.data();
  return 0;
}

// ---- views + raw-bytes serialization (reference c_api.h: MXNDArraySlice
// :395, MXNDArrayAt :407, MXNDArrayReshape :418, MXNDArraySaveRawBytes
// :291, MXNDArrayLoadFromRawBytes :271). Host arrays: views are copies
// (the reference's chunk-sharing is a device-memory concern; the C-client
// contract — shapes and values — is identical). -----------------------------

MXNET_DLL int MXNDArraySlice(NDArrayHandle handle, mx_uint slice_begin,
                             mx_uint slice_end, NDArrayHandle* out) {
  auto* a = static_cast<CArray*>(handle);
  if (a->shape.empty()) return fail("cannot slice a scalar");
  if (slice_begin > slice_end || slice_end > a->shape[0])
    return fail("invalid slice range");
  if (a->data.size() != nelem(a->shape) * kDTypeSize[a->dtype])
    return fail("cannot slice an unmaterialized (delay_alloc) array");
  size_t row = kDTypeSize[a->dtype];
  for (size_t i = 1; i < a->shape.size(); ++i) row *= a->shape[i];
  auto* r = new CArray();
  r->dtype = a->dtype;
  r->dev_type = a->dev_type;
  r->dev_id = a->dev_id;
  r->shape = a->shape;
  r->shape[0] = slice_end - slice_begin;
  r->data.assign(a->data.begin() + slice_begin * row,
                 a->data.begin() + slice_end * row);
  *out = r;
  return 0;
}

MXNET_DLL int MXNDArrayAt(NDArrayHandle handle, mx_uint idx,
                          NDArrayHandle* out) {
  auto* a = static_cast<CArray*>(handle);
  if (a->shape.empty() || idx >= a->shape[0]) return fail("index out of range");
  NDArrayHandle sliced = nullptr;
  int rc = MXNDArraySlice(handle, idx, idx + 1, &sliced);
  if (rc != 0) return rc;
  auto* r = static_cast<CArray*>(sliced);
  r->shape.erase(r->shape.begin());  // drop the leading dim
  *out = r;
  return 0;
}

MXNET_DLL int MXNDArrayReshape(NDArrayHandle handle, int ndim, int* dims,
                               NDArrayHandle* out) {
  auto* a = static_cast<CArray*>(handle);
  std::vector<mx_uint> shape;
  long known = 1;
  int infer = -1;
  for (int i = 0; i < ndim; ++i) {
    if (dims[i] == -1) {
      if (infer >= 0) return fail("at most one -1 dim in reshape");
      infer = i;
      shape.push_back(0);
    } else {
      shape.push_back(static_cast<mx_uint>(dims[i]));
      known *= dims[i];
    }
  }
  long total = static_cast<long>(nelem(a->shape));
  if (infer >= 0) {
    if (known == 0 || total % known != 0)
      return fail("cannot infer -1 dim in reshape");
    shape[infer] = static_cast<mx_uint>(total / known);
    known *= shape[infer];
  }
  if (known != total) return fail("reshape changes element count");
  auto* r = new CArray();
  r->dtype = a->dtype;
  r->dev_type = a->dev_type;
  r->dev_id = a->dev_id;
  r->shape = shape;
  r->data = a->data;
  *out = r;
  return 0;
}

MXNET_DLL int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t* out_size,
                                    const char** out_buf) {
  auto* a = static_cast<CArray*>(handle);
  thread_local std::vector<char> buf;
  buf.clear();
  auto put = [&](const void* p, size_t n) {
    const char* c = static_cast<const char*>(p);
    buf.insert(buf.end(), c, c + n);
  };
  uint32_t ndim = a->none ? 0 : static_cast<uint32_t>(a->shape.size());
  put(&kNDArrayMagic, 4);
  put(&ndim, 4);
  if (ndim) {
    for (mx_uint s : a->shape) {
      uint32_t v = s;
      put(&v, 4);
    }
    int32_t ctx[2] = {1, 0};
    put(ctx, 8);
    int32_t flag = a->dtype;
    put(&flag, 4);
    put(a->data.data(), a->data.size());
  }
  *out_size = buf.size();
  *out_buf = buf.data();
  return 0;
}

MXNET_DLL int MXNDArrayLoadFromRawBytes(const void* buf, size_t size,
                                        NDArrayHandle* out) {
  const char* p = static_cast<const char*>(buf);
  const char* end = p + size;
  auto rd = [&p, end](void* dst, size_t n) {
    if (static_cast<size_t>(end - p) < n) return false;
    std::memcpy(dst, p, n);
    p += n;
    return true;
  };
  mxt_ndwire::NdRecord rec;
  std::string err;
  if (!mxt_ndwire::read_ndarray_record(rd, &rec, &err, kNumDTypes))
    return fail("LoadFromRawBytes: " + err);
  auto* r = new CArray();
  r->none = rec.none;
  r->dtype = rec.dtype;
  r->shape.assign(rec.shape.begin(), rec.shape.end());
  r->data = std::move(rec.data);
  *out = r;
  return 0;
}
