// RecordIO C API — pure C++ (no embedded Python), the reference's
// MXRecordIO* family (include/mxnet/c_api.h: MXRecordIOWriterCreate :~960,
// MXRecordIOReaderCreate, WriteRecord/ReadRecord/Tell/Seek/Free).
//
// Framing is the reference's recordio wire format (dmlc-core recordio,
// python mirror mxnet_tpu/recordio.py, native sharded reader
// src/recordio.cc): [u32 magic 0xced7230a][u32 lrec][payload][pad to 4B],
// lrec>>29 = continuation flag, lrec&((1<<29)-1) = chunk length. The writer
// splits over-long records into first/middle/last chunks exactly like the
// reference so files byte-interchange with recordio.py and the reference
// itself; the reader reassembles them.
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>

// public declarations — including them compile-checks every signature
#include "include/c_train_api.h"
#include "include/recordio_wire.h"

#define MXNET_DLL extern "C" __attribute__((visibility("default")))

void mxtpu_set_last_error(const std::string& msg);   // c_predict_api.cc
void mxtpu_set_train_error(const std::string& msg);  // c_api_train.cc

namespace {

using mxt_wire::kMagic;
using mxt_wire::kMaxChunk;

struct RecIO {
  FILE* f;
  bool writer;
  std::string buf;  // reader: last record, stable until next read
};

int fail(const char* msg) {
  // both error channels: the header documents MXTrainGetLastError, and the
  // predict shim's MXGetLastError is the reference's canonical accessor
  mxtpu_set_last_error(msg);
  mxtpu_set_train_error(msg);
  return -1;
}

}  // namespace

MXNET_DLL int MXRecordIOWriterCreate(const char* uri, RecordIOHandle* out) {
  FILE* f = std::fopen(uri, "wb");
  if (!f) return fail("cannot open for write");
  *out = new RecIO{f, true, {}};
  return 0;
}

MXNET_DLL int MXRecordIOReaderCreate(const char* uri, RecordIOHandle* out) {
  FILE* f = std::fopen(uri, "rb");
  if (!f) return fail("cannot open for read");
  *out = new RecIO{f, false, {}};
  return 0;
}

MXNET_DLL int MXRecordIOWriterFree(RecordIOHandle h) {
  auto* r = static_cast<RecIO*>(h);
  if (!r) return 0;
  // fclose performs the final flush — a full disk (ENOSPC) surfaces HERE,
  // not in the buffered writes, so its result must be checked
  int rc = r->f ? std::fclose(r->f) : 0;
  delete r;
  return rc == 0 ? 0 : fail("close/flush failed (disk full?)");
}

MXNET_DLL int MXRecordIOReaderFree(RecordIOHandle h) {
  return MXRecordIOWriterFree(h);
}

MXNET_DLL int MXRecordIOWriterWriteRecord(RecordIOHandle h, const char* buf,
                                          size_t size) {
  auto* r = static_cast<RecIO*>(h);
  if (!r->writer) return fail("handle is a reader");
  size_t off = 0;
  bool first = true;
  do {
    size_t chunk = size - off < kMaxChunk ? size - off : kMaxChunk;
    bool last = off + chunk == size;
    // cflag: 0 whole, 1 first, 2 last, 3 middle (reference recordio)
    uint32_t cflag = first ? (last ? 0u : 1u) : (last ? 2u : 3u);
    uint32_t hdr[2] = {kMagic, mxt_wire::lrec_of(
                                   cflag, static_cast<uint32_t>(chunk))};
    if (std::fwrite(hdr, 4, 2, r->f) != 2) return fail("short write");
    if (chunk && std::fwrite(buf + off, 1, chunk, r->f) != chunk)
      return fail("short write");
    static const char zeros[4] = {0, 0, 0, 0};
    size_t pad = mxt_wire::pad_of(chunk);
    if (pad && std::fwrite(zeros, 1, pad, r->f) != pad)
      return fail("short write");
    off += chunk;
    first = false;
  } while (off < size);
  return 0;
}

MXNET_DLL int MXRecordIOWriterTell(RecordIOHandle h, size_t* pos) {
  auto* r = static_cast<RecIO*>(h);
  long p = std::ftell(r->f);
  if (p < 0) return fail("tell failed");
  *pos = static_cast<size_t>(p);
  return 0;
}

/* Returns 0 with *out_buf=NULL at end-of-file (the reference's convention:
 * read past the end yields an empty record). The returned pointer stays
 * valid until the next read on the same handle. */
MXNET_DLL int MXRecordIOReaderReadRecord(RecordIOHandle h,
                                         const char** out_buf,
                                         size_t* out_size) {
  auto* r = static_cast<RecIO*>(h);
  if (r->writer) return fail("handle is a writer");
  r->buf.clear();
  bool mid_record = false;
  for (;;) {
    uint32_t hdr[2];
    // byte-granular read so a 1-7-byte trailing fragment is distinguishable
    // from a cleanly absent header
    size_t got = std::fread(hdr, 1, 8, r->f);
    if (got != 8) {
      // clean EOF only at a record boundary with a fully-absent header;
      // a partial header or EOF between chunks is data loss, not EOF
      if (got == 0 && !mid_record && std::feof(r->f)) {
        *out_buf = nullptr;
        *out_size = 0;
        return 0;
      }
      return fail(mid_record ? "file truncated mid-record"
                             : "file truncated mid-header");
    }
    if (hdr[0] != kMagic) return fail("bad record magic");
    uint32_t cflag = mxt_wire::cflag_of(hdr[1]);
    uint32_t len = mxt_wire::len_of(hdr[1]);
    size_t off = r->buf.size();
    r->buf.resize(off + len);
    if (len && std::fread(&r->buf[off], 1, len, r->f) != len)
      return fail("truncated record");
    size_t pad = mxt_wire::pad_of(len);
    if (pad) std::fseek(r->f, static_cast<long>(pad), SEEK_CUR);
    if (cflag == 0 || cflag == 2) break;  // whole or last chunk
    mid_record = true;
  }
  *out_buf = r->buf.data();
  *out_size = r->buf.size();
  return 0;
}

MXNET_DLL int MXRecordIOReaderSeek(RecordIOHandle h, size_t pos) {
  auto* r = static_cast<RecIO*>(h);
  if (std::fseek(r->f, static_cast<long>(pos), SEEK_SET) != 0)
    return fail("seek failed");
  return 0;
}
