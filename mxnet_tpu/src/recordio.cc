// Native RecordIO reader/writer + threaded sharded reader — rebuild of the
// reference's data-ingest bottom layer (reference: dmlc-core recordio framing
// consumed by src/io/iter_image_recordio_2.cc:28-80 — InputSplit chunk
// reading with part_index/num_parts sharding, feeding a background parser;
// python mirror python/mxnet/recordio.py).
//
// Wire format (identical to the reference so .rec files interchange):
//   [uint32 magic 0xced7230a][uint32 lrec][payload][pad to 4B]
//   lrec>>29 = continuation flag (0 whole, 1 first, 2 last, 3 middle),
//   lrec&((1<<29)-1) = payload length.
//
// The threaded reader owns a byte-range shard of the file: it starts at the
// first magic-aligned record at/after its range start (the reference's
// InputSplit alignment trick) and stops once a record *starts* at/after the
// range end. Records are produced into a bounded ring consumed from Python
// (or any C caller) one record at a time.

#include <cstdint>

#include "include/recordio_wire.h"
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* mxt_alloc(size_t nbytes);
void mxt_free(void* p, size_t nbytes);
}

namespace mxt {

using mxt_wire::kMagic;

struct Record {
  char* data;
  size_t len;
};

class RecReader {
 public:
  RecReader(const char* path, int part_index, int num_parts, int queue_size)
      : queue_cap_(queue_size < 1 ? 1 : queue_size) {
    f_ = fopen(path, "rb");
    if (!f_) {
      failed_ = true;
      done_ = true;
      return;
    }
    fseek(f_, 0, SEEK_END);
    int64_t size = ftell(f_);
    if (num_parts < 1) num_parts = 1;
    begin_ = size * part_index / num_parts;
    end_ = size * (part_index + 1) / num_parts;
    thread_ = std::thread([this] { ProducerLoop(); });
  }

  ~RecReader() {
    {
      std::unique_lock<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_space_.notify_all();
    if (thread_.joinable()) thread_.join();
    std::unique_lock<std::mutex> lk(mu_);
    for (auto& r : queue_) mxt_free(r.data, r.len);
    queue_.clear();
    if (f_) fclose(f_);
  }

  // Pop next record. Returns 1 and fills (*data,*len) — caller must
  // mxt_rec_free() it — or 0 at end-of-shard / error.
  int Next(char** data, size_t* len) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_data_.wait(lk, [&] { return !queue_.empty() || done_; });
    if (queue_.empty()) return 0;
    Record r = queue_.front();
    queue_.pop_front();
    lk.unlock();
    cv_space_.notify_one();
    *data = r.data;
    *len = r.len;
    return 1;
  }

  bool failed() const { return failed_; }

 private:
  // Scan forward from `begin_` to the first well-formed record header whose
  // continuation flag is 0 or 1 (a record START, not a middle chunk).
  bool SeekFirstRecord() {
    int64_t pos = (begin_ + 3) & ~int64_t(3);
    // bound is pos < end_, not pos+8 <= end_: a record may START in the last
    // <8 bytes of the shard range (the header itself extends past end_ into
    // the next shard's bytes, which is fine — ownership is by start offset).
    for (; pos < end_; pos += 4) {
      if (fseek(f_, pos, SEEK_SET) != 0) return false;
      uint32_t hdr[2];
      if (fread(hdr, 4, 2, f_) != 2) return false;
      uint32_t cflag = hdr[1] >> 29;
      if (hdr[0] == kMagic && (cflag == 0 || cflag == 1)) {
        fseek(f_, pos, SEEK_SET);
        return true;
      }
    }
    return false;
  }

  // Read one full (possibly multi-chunk) record into a pooled buffer.
  bool ReadRecord(std::string* out) {
    out->clear();
    for (;;) {
      uint32_t hdr[2];
      if (fread(hdr, 4, 2, f_) != 2) return false;
      if (hdr[0] != kMagic) return false;
      uint32_t cflag = hdr[1] >> 29;
      uint32_t len = hdr[1] & ((1u << 29) - 1);
      size_t off = out->size();
      out->resize(off + len);
      if (len && fread(&(*out)[off], 1, len, f_) != len) return false;
      size_t pad = mxt_wire::pad_of(len);
      if (pad) fseek(f_, pad, SEEK_CUR);
      if (cflag == 0 || cflag == 2) return true;
    }
  }

  void ProducerLoop() {
    if (!SeekFirstRecord()) {
      std::unique_lock<std::mutex> lk(mu_);
      done_ = true;
      cv_data_.notify_all();
      return;
    }
    std::string buf;
    for (;;) {
      int64_t start = ftell(f_);
      if (start >= end_) break;  // record starting past shard end: next part's
      if (!ReadRecord(&buf)) break;
      char* mem = static_cast<char*>(mxt_alloc(buf.size()));
      if (!mem) break;  // allocation failure ends the shard, not the process
      memcpy(mem, buf.data(), buf.size());
      std::unique_lock<std::mutex> lk(mu_);
      cv_space_.wait(lk, [&] { return queue_.size() < queue_cap_ || stop_; });
      if (stop_) {
        mxt_free(mem, buf.size());
        break;
      }
      queue_.push_back({mem, buf.size()});
      cv_data_.notify_one();
    }
    std::unique_lock<std::mutex> lk(mu_);
    done_ = true;
    cv_data_.notify_all();
  }

  FILE* f_ = nullptr;
  int64_t begin_ = 0, end_ = 0;
  size_t queue_cap_;
  std::thread thread_;
  std::mutex mu_;
  std::condition_variable cv_data_, cv_space_;
  std::deque<Record> queue_;
  bool done_ = false, stop_ = false, failed_ = false;
};

}  // namespace mxt

extern "C" {

void* mxt_rec_reader_open(const char* path, int part_index, int num_parts,
                          int queue_size) {
  auto* r = new mxt::RecReader(path, part_index, num_parts, queue_size);
  if (r->failed()) {
    delete r;
    return nullptr;
  }
  return r;
}

int mxt_rec_reader_next(void* h, char** data, size_t* len) {
  return static_cast<mxt::RecReader*>(h)->Next(data, len);
}

void mxt_rec_free(char* data, size_t len) { mxt_free(data, len); }

void mxt_rec_reader_close(void* h) { delete static_cast<mxt::RecReader*>(h); }

}  // extern "C"
