// Native JPEG decode for the input-pipeline stage (reference:
// iter_image_recordio_2.cc decodes with cv::imdecode inside the OMP pool;
// here the backend is libjpeg bound at build time — the Makefile probes for
// a linkable -ljpeg and compiles this with MXT_HAS_LIBJPEG when found, so a
// bare container still builds the rest of the runtime and python's PIL path
// stays the fallback and correctness oracle).
//
// Output contract matches image.py imdecode_np's PIL branch: RGB, HWC,
// uint8; grayscale sources expand to RGB (PIL's convert("RGB")). Exotic
// color spaces libjpeg cannot convert to RGB (e.g. CMYK from Adobe
// markers) fail with -1 and are quarantined by the caller like any other
// corrupt record.

#include <cstddef>
#include <cstdint>

#include "include/pipe_api.h"

extern "C" {
void* mxt_alloc(size_t nbytes);
void mxt_free(void* p, size_t nbytes);
}

#ifdef MXT_HAS_LIBJPEG

#include <csetjmp>
#include <cstdio>
#include <cstring>

#include <jpeglib.h>

namespace {

struct ErrorMgr {
  jpeg_error_mgr pub;
  jmp_buf setjmp_buffer;
};

void on_error_exit(j_common_ptr cinfo) {
  // corrupt records are expected input here: recover via longjmp instead of
  // libjpeg's default exit()
  longjmp(reinterpret_cast<ErrorMgr*>(cinfo->err)->setjmp_buffer, 1);
}

void on_output_message(j_common_ptr) {}  // keep warnings off stderr

// Version-independent memory source (jpeg_mem_src is libjpeg8+/turbo-only;
// the 62 ABI needs a hand-rolled source manager).
struct MemSrc {
  jpeg_source_mgr pub;
  const uint8_t* data;
  size_t len;
};

void src_init(j_decompress_ptr) {}

boolean src_fill(j_decompress_ptr cinfo) {
  // past the end of the buffer: feed a fake EOI so truncated files error
  // out through the normal header/marker checks instead of hanging
  static const JOCTET kEoi[2] = {0xFF, JPEG_EOI};
  cinfo->src->next_input_byte = kEoi;
  cinfo->src->bytes_in_buffer = 2;
  return TRUE;
}

void src_skip(j_decompress_ptr cinfo, long n) {
  if (n <= 0) return;
  jpeg_source_mgr* src = cinfo->src;
  while (static_cast<size_t>(n) > src->bytes_in_buffer) {
    n -= static_cast<long>(src->bytes_in_buffer);
    src_fill(cinfo);
  }
  src->next_input_byte += n;
  src->bytes_in_buffer -= n;
}

void src_term(j_decompress_ptr) {}

void set_mem_src(j_decompress_ptr cinfo, MemSrc* src, const uint8_t* buf,
                 size_t len) {
  src->pub.init_source = src_init;
  src->pub.fill_input_buffer = src_fill;
  src->pub.skip_input_data = src_skip;
  src->pub.resync_to_restart = jpeg_resync_to_restart;
  src->pub.term_source = src_term;
  src->pub.next_input_byte = buf;
  src->pub.bytes_in_buffer = len;
  src->data = buf;
  src->len = len;
  cinfo->src = &src->pub;
}

}  // namespace

extern "C" int mxt_decode_jpeg(const uint8_t* buf, size_t len, uint8_t** out,
                               int* h, int* w) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  MemSrc src;
  // volatile: both are written after setjmp and read in the longjmp error
  // path — without it the compiler may keep them in registers and the
  // handler would free a stale pointer (or leak) on every corrupt record
  uint8_t* volatile mem = nullptr;
  volatile size_t nbytes = 0;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = on_error_exit;
  jerr.pub.output_message = on_output_message;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    if (mem) mxt_free(mem, nbytes);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  set_mem_src(&cinfo, &src, buf, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = JCS_RGB;  // YCbCr + grayscale both convert
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != 3) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *h = static_cast<int>(cinfo.output_height);
  *w = static_cast<int>(cinfo.output_width);
  nbytes = static_cast<size_t>(*h) * *w * 3;
  mem = static_cast<uint8_t*>(mxt_alloc(nbytes));
  if (!mem) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  size_t stride = static_cast<size_t>(*w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = mem + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  *out = mem;
  return 0;
}

/* Decode directly into a caller buffer when the source dimensions equal
 * (h, w) exactly — the packed-dataset fast path: no intermediate image,
 * no copy. Returns 1 = decoded into dst, 0 = dimensions differ (caller
 * takes the resize path), -1 = corrupt. */
extern "C" int mxt_decode_jpeg_direct(const uint8_t* buf, size_t len,
                                      uint8_t* dst, int h, int w) {
  jpeg_decompress_struct cinfo;
  ErrorMgr jerr;
  MemSrc src;
  cinfo.err = jpeg_std_error(&jerr.pub);
  jerr.pub.error_exit = on_error_exit;
  jerr.pub.output_message = on_output_message;
  if (setjmp(jerr.setjmp_buffer)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  set_mem_src(&cinfo, &src, buf, len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  if (static_cast<int>(cinfo.image_height) != h ||
      static_cast<int>(cinfo.image_width) != w) {
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if (cinfo.output_components != 3 ||
      static_cast<int>(cinfo.output_height) != h ||
      static_cast<int>(cinfo.output_width) != w) {
    jpeg_destroy_decompress(&cinfo);
    return 0;
  }
  size_t stride = static_cast<size_t>(w) * 3;
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = dst + cinfo.output_scanline * stride;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 1;
}

extern "C" int mxt_pipe_decode_available(void) { return 1; }

#else  // !MXT_HAS_LIBJPEG

extern "C" int mxt_decode_jpeg(const uint8_t*, size_t, uint8_t**, int*,
                               int*) {
  return -2;
}

extern "C" int mxt_decode_jpeg_direct(const uint8_t*, size_t, uint8_t*, int,
                                      int) {
  return -1;
}

extern "C" int mxt_pipe_decode_available(void) { return 0; }

#endif  // MXT_HAS_LIBJPEG
