// NDArray save/load wire format shared by the C API (src/c_api_ndarray.cc)
// and the Python-free predict runtime (src/c_predict_pjrt.cc); the Python
// mirror is mxnet_tpu/ndarray.py save/load. Format (reference
// src/ndarray/ndarray.cc:618-717): per array [u32 0xF993FAC8 magic,
// u32 ndim, ndim*u32 dims, i32 dev_type, i32 dev_id, i32 dtype flag, raw
// data]; ndim==0 is the "none" record and stops right after the shape.
// Legacy pre-V1 blobs omit the magic (first word is ndim). A dict file is
// [u64 0x112, u64 reserved, u64 count, records..., u64 n_names, names...].
#ifndef MXTPU_NDARRAY_WIRE_H_
#define MXTPU_NDARRAY_WIRE_H_

#include <cstddef>
#include <cstdint>
#include <string>
#include <vector>

namespace mxt_ndwire {

constexpr uint32_t kNDArrayMagic = 0xF993FAC8u;
constexpr uint64_t kListMagic = 0x112;

// mshadow dtype flags 0..6 (reference) + TPU-build extensions 7..8
// (bfloat16/bool; flags the reference loader rejects, ndarray.py:630)
constexpr int kDTypeSizeTable[] = {4 /*f32*/, 8 /*f64*/, 2 /*f16*/,
                                   1 /*u8*/,  4 /*i32*/, 1 /*i8*/,
                                   8 /*i64*/, 2 /*bf16*/, 1 /*bool*/};
constexpr int kNumWireDTypes =
    static_cast<int>(sizeof(kDTypeSizeTable) / sizeof(int));

struct NdRecord {
  bool none = false;
  int dtype = 0;
  int dev_type = 1;
  int dev_id = 0;
  std::vector<uint32_t> shape;
  std::vector<uint8_t> data;
};

// Reads one record through `rd` (callable: bool(void* dst, size_t n),
// false on short read). `max_dtype` lets the strict-reference caller
// reject the TPU-extension flags. Guards mirror ndarray.py _read_ndarray:
// ndim <= 64, each dim <= 2^31, total bytes <= 2^40 — a corrupt header
// must fail cleanly, never drive a huge allocation or desynchronize.
template <typename ReadFn>
bool read_ndarray_record(ReadFn&& rd, NdRecord* out, std::string* err,
                         int max_dtype = kNumWireDTypes) {
  uint32_t magic = 0, ndim = 0;
  if (!rd(&magic, 4)) { *err = "truncated NDArray blob"; return false; }
  if (magic == kNDArrayMagic) {
    if (!rd(&ndim, 4)) { *err = "truncated NDArray blob"; return false; }
  } else {
    ndim = magic;  // legacy pre-V1 layout: first word is ndim
  }
  if (ndim > 64) { *err = "implausible ndim"; return false; }
  out->shape.resize(ndim);
  for (uint32_t i = 0; i < ndim; ++i) {
    uint32_t s = 0;
    if (!rd(&s, 4)) { *err = "truncated shape"; return false; }
    if (s > (1u << 31)) { *err = "implausible shape"; return false; }
    out->shape[i] = s;
  }
  if (ndim == 0) {  // "none" record: nothing follows the shape
    out->none = true;
    return true;
  }
  int32_t devctx[2] = {1, 0};
  int32_t flag = 0;
  if (!rd(devctx, 8) || !rd(&flag, 4)) {
    *err = "truncated header";
    return false;
  }
  if (flag < 0 || flag >= max_dtype) {
    *err = "unknown dtype flag";
    return false;
  }
  out->dev_type = devctx[0];
  out->dev_id = devctx[1];
  out->dtype = flag;
  size_t n = 1;
  for (uint32_t s : out->shape) {
    if (s != 0 && n > SIZE_MAX / s) { *err = "implausible size"; return false; }
    n *= s;
  }
  size_t bytes = n * kDTypeSizeTable[flag];
  if (bytes > (size_t(1) << 40)) { *err = "implausible size"; return false; }
  out->data.resize(bytes);
  if (!rd(out->data.data(), bytes)) {
    *err = "truncated data";
    return false;
  }
  return true;
}

}  // namespace mxt_ndwire

#endif  // MXTPU_NDARRAY_WIRE_H_
