// The host-side NDArray the C API hands out as NDArrayHandle
// (reference: include/mxnet/ndarray.h NDArray behind c_api.h handles).
// Shared between the NDArray C API (src/c_api_ndarray.cc) and the training
// C API (src/c_api_train.cc: MXImperativeInvoke outputs, monitor-callback
// arrays) so a handle created by one family is readable by the other —
// mirroring the reference where every family shares one NDArray type.
#ifndef MXTPU_C_ARRAY_H_
#define MXTPU_C_ARRAY_H_

#include <cstdint>
#include <vector>

#include "ndarray_wire.h"

typedef unsigned int mx_uint;

struct CArray {
  std::vector<mx_uint> shape;
  std::vector<uint8_t> data;
  int dtype = 0;     // mshadow flag (size table: ndarray_wire.h)
  int dev_type = 1;  // cpu
  int dev_id = 0;
  bool none = false;  // MXNDArrayCreateNone / delay_alloc placeholder
};

#endif  // MXTPU_C_ARRAY_H_
