// C API for the native decode->augment->batch input-pipeline stage
// (src/decode.cc + augment.cc + pipe.cc; python driver io_image.py
// ImageRecordIter(backend='native')). The reference's bottom data-ingest
// layer is iter_image_recordio_2.cc: an OMP pool JPEG-decoding records from
// the InputSplit chunk reader into InstVector batches — this is the same
// design with explicit worker threads over the sharded RecReader ring
// (src/recordio.cc) producing uint8-HWC wire batches.
#ifndef MXTPU_PIPE_API_H_
#define MXTPU_PIPE_API_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct MXTPipeConfig {
  const char* path;  /* .rec file */
  int part_index;
  int num_parts;
  int num_threads;  /* decode workers */
  int batch_size;
  int out_h, out_w, out_c; /* target image shape (HWC; out_c must be 3) */
  int label_width;
  long long seed;
  long long epoch;
  int resize;         /* resize shortest edge to this first (0 = off) */
  int crop;           /* 0 = center crop, 1 = random crop */
  double mirror_prob; /* horizontal flip probability (0 = off) */
  long long max_bad;  /* quarantine budget; -1 = unlimited (legacy skip) */
  int prefetch;       /* output ring depth, in batches */
} MXTPipeConfig;

/* NULL on immediate failure (unreadable file / no JPEG backend compiled). */
void* mxt_pipe_create(const MXTPipeConfig* cfg);

/* Blocking pop of the next assembled batch into caller-owned buffers:
 * data is batch*out_h*out_w*out_c uint8 (HWC, record order), label is
 * batch*label_width float32, *pad is the final-batch pad count.
 * Returns 1 = batch filled, 0 = end of shard, -1 = error (mxt_pipe_error;
 * the quarantine budget overflowing surfaces here, after any batches
 * assembled before the overflow). */
int mxt_pipe_next(void* h, uint8_t* data, float* label, int* pad);

/* Zero-copy variant: on 1, *data and *label point at the pipeline's own
 * batch buffers (same layout as mxt_pipe_next) and stay valid until
 * mxt_pipe_release — the python driver defers the release to the next pop,
 * so the host->device upload reads the stage's memory directly instead of
 * staging one more 4.8 MB copy per 32x224^2 uint8 batch. */
int mxt_pipe_pop(void* h, uint8_t** data, float** label, int* pad);
void mxt_pipe_release(void* h, uint8_t* data, float* label);

const char* mxt_pipe_error(void* h);

/* Monotonic counters since create:
 * out[0] bad records quarantined   out[1] decode seconds (summed)
 * out[2] augment seconds (summed)  out[3] assemble seconds (summed)
 * out[4] records decoded           out[5] batches emitted */
void mxt_pipe_stats(void* h, double* out, int n);

void mxt_pipe_close(void* h);

/* 1 when a JPEG decode backend was compiled in (libjpeg), else 0 —
 * python falls back to the PIL path and counts the fallback. */
int mxt_pipe_decode_available(void);

/* --- parity-test surface (tests_tpu/test_native_decode.py) ------------- */

/* Decode a JPEG byte buffer to RGB-HWC uint8 (grayscale sources are
 * expanded to RGB, like PIL's convert("RGB")). *out is mxt_alloc'd
 * (*h * *w * 3 bytes) — free with mxt_rec_free. Returns 0 ok, -1 corrupt/
 * unsupported, -2 no backend compiled in. */
int mxt_decode_jpeg(const uint8_t* buf, size_t len, uint8_t** out,
                    int* h, int* w);

/* Decode straight into dst iff the source is exactly (h, w): 1 decoded,
 * 0 dimensions differ (fall back to mxt_decode_jpeg), -1 corrupt. */
int mxt_decode_jpeg_direct(const uint8_t* buf, size_t len, uint8_t* dst,
                           int h, int w);

/* Pillow-parity two-pass fixed-point bilinear resample (uint8, c channels,
 * interleaved). Bit-identical to PIL.Image.resize(..., BILINEAR). */
void mxt_resize_bilinear(const uint8_t* src, int sh, int sw, int c,
                         uint8_t* dst, int dh, int dw);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_PIPE_API_H_ */
