/* Training-side C API slice (src/c_api_train.cc) — the Symbol/Executor
 * function families from the reference's include/mxnet/c_api.h, enough for a
 * pure C/C++ client to run a complete training loop against the XLA-compiled
 * executor. Exported by libmxtpu_predict.so (build: make c_predict).
 *
 * All float buffers are float32, row-major, caller-owned. Pointers returned
 * through out-params stay valid until the next call on the same handle
 * (thread-local for the Symbol string lists). On error every function
 * returns -1; MXTrainGetLastError() describes the failure.
 */
#ifndef MXTPU_C_TRAIN_API_H_
#define MXTPU_C_TRAIN_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef unsigned int mx_uint;

const char* MXTrainGetLastError(void);

/* ---- Symbol ---- */
int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out);
int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json);
int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                          const char*** out_array);
int MXSymbolFree(SymbolHandle sym);

/* ---- Executor ----
 * Shapes are CSR-packed like the reference's simple_bind: keys[i] names an
 * input whose dims are arg_shape_data[arg_shape_idx[i] .. arg_shape_idx[i+1]).
 * grad_req: "write" | "add" | "null". dev_type: "cpu" | "tpu" | "gpu". */
int MXExecutorSimpleBindLite(SymbolHandle sym, const char* dev_type,
                             int dev_id, mx_uint num_args, const char** keys,
                             const mx_uint* arg_shape_data,
                             const mx_uint* arg_shape_idx,
                             const char* grad_req, ExecutorHandle* out);
int MXExecutorInitXavier(ExecutorHandle exec, int seed);
int MXExecutorSetArg(ExecutorHandle exec, const char* name, const float* data,
                     mx_uint size);
int MXExecutorGetArg(ExecutorHandle exec, const char* name, const float** out,
                     mx_uint* out_size);
int MXExecutorGetGrad(ExecutorHandle exec, const char* name,
                      const float** out, mx_uint* out_size);
int MXExecutorGetOutput(ExecutorHandle exec, mx_uint index, const float** out,
                        mx_uint* out_size);
int MXExecutorOutputShape(ExecutorHandle exec, mx_uint index,
                          const mx_uint** out_shape, mx_uint* out_dim);
int MXExecutorForward(ExecutorHandle exec, int is_train);
/* head_grads unsupported in the slice: pass (0, NULL); loss outputs seed 1 */
int MXExecutorBackward(ExecutorHandle exec, mx_uint num_head_grads,
                       void** head_grads);
/* w -= lr * (grad + wd * w) for every argument with a gradient */
int MXExecutorSGDUpdate(ExecutorHandle exec, float lr, float wd);
int MXExecutorFree(ExecutorHandle exec);

#ifdef __cplusplus
}
#endif
#endif /* MXTPU_C_TRAIN_API_H_ */
