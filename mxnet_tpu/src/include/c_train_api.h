/* Training-side C API slice (src/c_api_train.cc) — the Symbol/Executor
 * function families from the reference's include/mxnet/c_api.h, enough for a
 * pure C/C++ client to run a complete training loop against the XLA-compiled
 * executor. Exported by libmxtpu_predict.so (build: make c_predict).
 *
 * All float buffers are float32, row-major, caller-owned. Pointers returned
 * through out-params stay valid until the next call on the same handle
 * (thread-local for the Symbol string lists). On error every function
 * returns -1; MXTrainGetLastError() describes the failure.
 */
#ifndef MXTPU_C_TRAIN_API_H_
#define MXTPU_C_TRAIN_API_H_

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* KVStoreHandle;
typedef unsigned int mx_uint;

const char* MXTrainGetLastError(void);

/* ---- Symbol ---- */
int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out);
int MXSymbolSaveToJSON(SymbolHandle sym, const char** out_json);
int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                          const char*** out_array);
int MXSymbolListOutputs(SymbolHandle sym, mx_uint* out_size,
                        const char*** out_array);
int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint* out_size,
                                const char*** out_array);
int MXSymbolFree(SymbolHandle sym);

/* Symbol construction from C (the cpp-package surface). The reference
 * splits atomic-symbol creation and composition (MXSymbolCreateAtomicSymbol
 * + MXSymbolCompose); cpp-package's Operator::CreateSymbol always runs both
 * back-to-back, so this slice exposes the fused form. Every operator
 * parameter is passed as a string and parsed by the op's schema. input_keys
 * entries may be "" (positional input) or the operator's input name; name
 * may be NULL/"" for an auto-generated node name. */
int MXSymbolCreateVariable(const char* name, SymbolHandle* out);
int MXSymbolCreateFromOperator(const char* op_name, const char* name,
                               mx_uint num_param, const char** param_keys,
                               const char** param_vals, mx_uint num_inputs,
                               const char** input_keys, SymbolHandle* inputs,
                               SymbolHandle* out);

/* ---- Executor ----
 * Shapes are CSR-packed like the reference's simple_bind: keys[i] names an
 * input whose dims are arg_shape_data[arg_shape_idx[i] .. arg_shape_idx[i+1]).
 * grad_req: "write" | "add" | "null". dev_type: "cpu" | "tpu" | "gpu". */
int MXExecutorSimpleBindLite(SymbolHandle sym, const char* dev_type,
                             int dev_id, mx_uint num_args, const char** keys,
                             const mx_uint* arg_shape_data,
                             const mx_uint* arg_shape_idx,
                             const char* grad_req, ExecutorHandle* out);
int MXExecutorInitXavier(ExecutorHandle exec, int seed);
int MXExecutorSetArg(ExecutorHandle exec, const char* name, const float* data,
                     mx_uint size);
int MXExecutorSetAux(ExecutorHandle exec, const char* name, const float* data,
                     mx_uint size);
int MXExecutorGetArg(ExecutorHandle exec, const char* name, const float** out,
                     mx_uint* out_size);
int MXExecutorGetGrad(ExecutorHandle exec, const char* name,
                      const float** out, mx_uint* out_size);
int MXExecutorGetOutput(ExecutorHandle exec, mx_uint index, const float** out,
                        mx_uint* out_size);
int MXExecutorOutputShape(ExecutorHandle exec, mx_uint index,
                          const mx_uint** out_shape, mx_uint* out_dim);
int MXExecutorForward(ExecutorHandle exec, int is_train);
/* head_grads unsupported in the slice: pass (0, NULL); loss outputs seed 1 */
int MXExecutorBackward(ExecutorHandle exec, mx_uint num_head_grads,
                       void** head_grads);
/* w -= lr * (rescale_grad*grad + wd*w) for every argument with a gradient.
 * Loss-output gradients are batch-SUMMED (reference semantics); pass
 * rescale_grad = 1/batch_size for batch-mean training, 1.0 for raw sums. */
int MXExecutorSGDUpdate(ExecutorHandle exec, float lr, float wd,
                        float rescale_grad);
/* v = momentum*v - lr*(rescale_grad*grad + wd*w); w += v */
int MXExecutorMomentumUpdate(ExecutorHandle exec, float lr, float wd,
                             float momentum, float rescale_grad);
int MXExecutorNumOutputs(ExecutorHandle exec, mx_uint* out);
int MXExecutorGetAux(ExecutorHandle exec, const char* name, const float** out,
                     mx_uint* out_size);
/* Reference checkpoint format (`arg:`/`aux:`-prefixed NDArray dict) — files
 * interchange with Python Module/FeedForward and the reference itself. */
int MXExecutorSaveParams(ExecutorHandle exec, const char* path);
int MXExecutorLoadParams(ExecutorHandle exec, const char* path,
                         mx_uint* out_num_loaded);
int MXExecutorFree(ExecutorHandle exec);

/* ---- Imperative + introspection (reference: c_api.h MXImperativeInvoke
 * :518, MXListAllOpNames :594, MXSymbolListAtomicSymbolCreators :604,
 * MXSymbolInferShape :854). NDArrayHandle is the host-array handle from
 * c_predict_api.h's NDList family (same CArray type across the .so). ---- */
typedef void* NDArrayHandle;
typedef void* AtomicSymbolCreator;

/* ---- NDArray host-array family (implemented in pure C++ by
 * c_api_ndarray.cc; reference: c_api.h MXNDArrayCreate :139 and friends).
 * Data is dtype-sized host bytes; sizes are in ELEMENTS. ---- */
int MXNDArrayCreateNone(NDArrayHandle* out);
int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle* out);
int MXNDArrayCreateEx(const mx_uint* shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out);
int MXNDArrayFree(NDArrayHandle handle);
int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size);
int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size);
int MXNDArrayWaitToRead(NDArrayHandle handle);
int MXNDArrayWaitToWrite(NDArrayHandle handle);
int MXNDArrayWaitAll(void);
int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata);
int MXNDArrayGetData(NDArrayHandle handle, void** out_pdata);
int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype);
int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                        int* out_dev_id);
int MXNDArraySave(const char* fname, mx_uint num_args, NDArrayHandle* args,
                  const char** keys);
int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names);
int MXNDArraySlice(NDArrayHandle handle, mx_uint begin, mx_uint end,
                   NDArrayHandle* out);
int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle* out);
int MXNDArrayReshape(NDArrayHandle handle, int ndim, int* dims,
                     NDArrayHandle* out);

int MXListAllOpNames(mx_uint* out_size, const char*** out_array);
int MXSymbolListAtomicSymbolCreators(mx_uint* out_size,
                                     AtomicSymbolCreator** out_array);
int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char** name);
/* Run one op on host arrays. Inputs are NDArrayHandles (MXNDArrayCreateEx +
 * SyncCopyFromCPU). On entry *num_outputs==0 and *outputs==NULL: the
 * library allocates output handles (caller frees each via MXNDArrayFree;
 * the handle array itself is thread-local). With caller-provided outputs,
 * results are copied into them (shapes must match). */
int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals);
/* ---- Imperative autograd (reference: c_api.h MXAutogradSetIsTraining
 * :549, MXAutogradMarkVariables :558, MXAutogradComputeGradient :570 over
 * src/ndarray/autograd.cc; here over mxnet_tpu.contrib.autograd's tape —
 * the replay differentiates as ONE jitted XLA program). Flow: set training
 * on, mark variable handles with grad handles (reqs use the OpReqType
 * enum: 0 null / 1 write / 3 add), run ops through MXImperativeInvoke,
 * then ComputeGradient on the loss handle writes into the grad handles.
 * A marked variable's CURRENT bytes are read at each invoke, so updating
 * it via MXNDArraySyncCopyFromCPU between steps is seen. ---- */
int MXAutogradSetIsTraining(int is_training, int* prev);
int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle* var_handles,
                            mx_uint* reqs_array, NDArrayHandle* grad_handles);
int MXAutogradComputeGradient(mx_uint num_output,
                              NDArrayHandle* output_handles);
/* Shape inference (reference signature, CSR shape args like simple_bind;
 * keys==NULL means positional). Unknown shapes come back with ndim 0;
 * *complete is 1 when every shape is fully known. Returned tables are
 * thread-local, valid until the next InferShape call on any symbol. */
int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args, const char** keys,
                       const mx_uint* arg_ind_ptr,
                       const mx_uint* arg_shape_data, mx_uint* in_shape_size,
                       const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data, mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data, mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data, int* complete);
int MXSymbolInferShapePartial(
    SymbolHandle sym, mx_uint num_args, const char** keys,
    const mx_uint* arg_ind_ptr, const mx_uint* arg_shape_data,
    mx_uint* in_shape_size, const mx_uint** in_shape_ndim,
    const mx_uint*** in_shape_data, mx_uint* out_shape_size,
    const mx_uint** out_shape_ndim, const mx_uint*** out_shape_data,
    mx_uint* aux_shape_size, const mx_uint** aux_shape_ndim,
    const mx_uint*** aux_shape_data, int* complete);
/* Per-node monitor (reference: MXExecutorSetMonitorCallback c_api.h:1087 ->
 * GraphExecutor::ExecuteMonCallback). While installed, every
 * MXExecutorForward runs the eager monitored pass and invokes `callback`
 * once per node output with a float32 host NDArrayHandle (owned by the
 * executor, valid until the next forward). NULL callback uninstalls. */
typedef void (*ExecutorMonitorCallback)(const char* name, NDArrayHandle arr,
                                        void* callback_handle);
int MXExecutorSetMonitorCallback(ExecutorHandle exec,
                                 ExecutorMonitorCallback callback,
                                 void* callback_handle);
int MXRandomSeed(int seed);
int MXNotifyShutdown(void);

/* Symbol long tail (reference c_api.h :644-:920) */
int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out);
int MXSymbolSaveToFile(SymbolHandle sym, const char* fname);
int MXSymbolCopy(SymbolHandle sym, SymbolHandle* out);
int MXSymbolPrint(SymbolHandle sym, const char** out_str);
int MXSymbolGetName(SymbolHandle sym, const char** out, int* success);
int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle* symbols,
                        SymbolHandle* out);
int MXSymbolGetInternals(SymbolHandle sym, SymbolHandle* out);
int MXSymbolGetOutput(SymbolHandle sym, mx_uint index, SymbolHandle* out);
int MXSymbolGetAttr(SymbolHandle sym, const char* key, const char** out,
                    int* success);
int MXSymbolSetAttr(SymbolHandle sym, const char* key, const char* value);
/* flat [key0, val0, key1, val1, ...] like the reference */
int MXSymbolListAttr(SymbolHandle sym, mx_uint* out_size, const char*** out);
int MXSymbolListAttrShallow(SymbolHandle sym, mx_uint* out_size,
                            const char*** out);
int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator, const char** name,
                                const char** description, mx_uint* num_args,
                                const char*** arg_names,
                                const char*** arg_type_infos,
                                const char*** arg_descriptions,
                                const char** key_var_num_args);
int MXSymbolInferType(SymbolHandle sym, mx_uint num_args, const char** keys,
                      const int* arg_type_data, mx_uint* in_type_size,
                      const int** in_type_data, mx_uint* out_type_size,
                      const int** out_type_data, mx_uint* aux_type_size,
                      const int** aux_type_data, int* complete);
int MXExecutorPrint(ExecutorHandle exec, const char** out_str);
int MXKVStoreGetType(KVStoreHandle kv, const char** out);
int MXKVStoreIsWorkerNode(int* ret);
int MXKVStoreIsServerNode(int* ret);
int MXKVStoreIsSchedulerNode(int* ret);
int MXKVStoreBarrier(KVStoreHandle kv);

/* ---- DataIter (reference: c_api.h MXListDataIters / MXDataIterCreateIter /
 * Next / BeforeFirst / GetData / GetLabel / GetDataShape / GetPadNum) ----
 * Params are strings, parsed by the iterator's schema (shapes like
 * "(1,28,28)", numbers, booleans, paths). Data crosses as float32; pull
 * pointers stay valid until the next fetch on the same handle. */
typedef void* DataIterHandle;
int MXListDataIters(mx_uint* out_size, const char*** out_array);
int MXDataIterCreate(const char* name, mx_uint num_param, const char** keys,
                     const char** vals, DataIterHandle* out);
int MXDataIterFree(DataIterHandle iter);
int MXDataIterNext(DataIterHandle iter, int* out);
int MXDataIterBeforeFirst(DataIterHandle iter);
int MXDataIterGetData(DataIterHandle iter, const float** out,
                      mx_uint* out_size);
int MXDataIterGetLabel(DataIterHandle iter, const float** out,
                       mx_uint* out_size);
int MXDataIterGetDataShape(DataIterHandle iter, const mx_uint** out_shape,
                           mx_uint* out_dim);
int MXDataIterGetPadNum(DataIterHandle iter, int* out);

/* ---- KVStore (reference: c_api.h MXKVStoreCreate/Init/Push/Pull) ----
 * Values cross the boundary as float32 buffers; aggregation runs on the
 * framework's KVStore (same compute path as the Python surface). Pull
 * pointers stay valid until the next pull on the same handle. */
int MXKVStoreCreate(const char* type, KVStoreHandle* out);
int MXKVStoreFree(KVStoreHandle kv);
int MXKVStoreGetRank(KVStoreHandle kv, int* out);
int MXKVStoreGetGroupSize(KVStoreHandle kv, int* out);
int MXKVStoreInit(KVStoreHandle kv, int key, const float* data,
                  const mx_uint* shape, mx_uint ndim);
int MXKVStorePush(KVStoreHandle kv, int key, const float* data,
                  const mx_uint* shape, mx_uint ndim);
int MXKVStorePull(KVStoreHandle kv, int key, const float** out,
                  mx_uint* out_size);

/* ---- Profiler (reference: c_api.h MXSetProfilerConfig/State/DumpProfile)
 * mode: "symbolic" | "all"; state: 0 stop, 1 run. Dump writes the
 * chrome-trace JSON configured by MXSetProfilerConfig. */
int MXSetProfilerConfig(const char* mode, const char* filename);
int MXSetProfilerState(int state);
int MXDumpProfile(void);

/* ---- Rtc (reference: c_api.h MXRtcCreate/Push/Free) ----
 * Runtime-compiled kernels: the kernel body is the framework's rtc dialect
 * (jax/jnp/lax/pallas in scope; reference used CUDA source). Buffers are
 * float32; shapes CSR-packed like simple_bind. Output pointers stay valid
 * until the next push on the same handle. */
typedef void* RtcHandle;
int MXRtcCreate(const char* name, mx_uint num_input, mx_uint num_output,
                const char** input_names, const char** output_names,
                const char* kernel, RtcHandle* out);
int MXRtcPush(RtcHandle h, mx_uint num_input, const float** input_data,
              const mx_uint* input_shape_data, const mx_uint* input_shape_idx,
              mx_uint num_output, const mx_uint* output_shape_data,
              const mx_uint* output_shape_idx, const float** out_data,
              mx_uint* out_sizes);
int MXRtcFree(RtcHandle h);

/* ---- RecordIO (reference: c_api.h MXRecordIOWriterCreate/WriteRecord/
 * Tell, MXRecordIOReaderCreate/ReadRecord/Seek) ----
 * Pure C++ (c_api_recordio.cc) — the reference wire format, byte-
 * interchanging with recordio.py, the native sharded reader, and the
 * reference itself. ReadRecord returns 0 with *out_buf=NULL at EOF; the
 * pointer stays valid until the next read on the same handle. */
typedef void* RecordIOHandle;
int MXRecordIOWriterCreate(const char* uri, RecordIOHandle* out);
int MXRecordIOWriterFree(RecordIOHandle h);
int MXRecordIOWriterWriteRecord(RecordIOHandle h, const char* buf,
                                size_t size);
int MXRecordIOWriterTell(RecordIOHandle h, size_t* pos);
int MXRecordIOReaderCreate(const char* uri, RecordIOHandle* out);
int MXRecordIOReaderFree(RecordIOHandle h);
int MXRecordIOReaderReadRecord(RecordIOHandle h, const char** out_buf,
                               size_t* out_size);
int MXRecordIOReaderSeek(RecordIOHandle h, size_t pos);

#ifdef __cplusplus
}
#endif
#endif /* MXTPU_C_TRAIN_API_H_ */
