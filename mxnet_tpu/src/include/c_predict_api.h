/* C predict API declarations (reference: include/mxnet/c_predict_api.h).
 * Implemented by libmxtpu_predict.so (src/c_predict_api.cc), which embeds the
 * Python runtime and runs forward as one cached XLA executable. */
#ifndef MXTPU_C_PREDICT_API_H_
#define MXTPU_C_PREDICT_API_H_

#ifdef __cplusplus
extern "C" {
#endif

typedef float mx_float;
typedef unsigned int mx_uint;
typedef void* PredictorHandle;
typedef void* NDListHandle;

const char* MXGetLastError();

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out);

int MXPredCreatePartialOut(const char* symbol_json_str, const void* param_bytes,
                           int param_size, int dev_type, int dev_id,
                           mx_uint num_input_nodes, const char** input_keys,
                           const mx_uint* input_shape_indptr,
                           const mx_uint* input_shape_data,
                           mx_uint num_output_nodes, const char** output_keys,
                           PredictorHandle* out);

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const mx_float* data, mx_uint size);
int MXPredForward(PredictorHandle handle);
int MXPredPartialForward(PredictorHandle handle, int step, int* step_left);
int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim);
int MXPredGetOutput(PredictorHandle handle, mx_uint index, mx_float* data,
                    mx_uint size);
int MXPredFree(PredictorHandle handle);

int MXNDListCreate(const char* nd_file_bytes, int nd_file_size,
                   NDListHandle* out, mx_uint* out_length);
int MXNDListGet(NDListHandle handle, mx_uint index, const char** out_key,
                const mx_float** out_data, const mx_uint** out_shape,
                mx_uint* out_ndim);
int MXNDListFree(NDListHandle handle);

/* ---- Python-free TRAINING over PJRT (beyond the reference: its predict
 * stack was inference-only). Loads a kind="train" .mxa artifact exported by
 * mxnet_tpu.export_train_artifact — one AOT-compiled program per step:
 * forward + backward + optimizer update, param/optimizer/aux buffers
 * carried device-resident between steps. The C client feeds data/label
 * inputs, drives the learning rate, reads loss outputs, and saves the
 * trained parameters in the reference .params format (loadable by
 * mx.model.load_checkpoint / MXNDListCreate). */
typedef void* TrainNativeHandle;

int MXTrainNativeCreateFromFile(const char* artifact_path,
                                TrainNativeHandle* out);
/* data/label inputs the client must feed (role: "data" | "label") */
int MXTrainNativeNumInputs(TrainNativeHandle h, mx_uint* out);
int MXTrainNativeInputInfo(TrainNativeHandle h, mx_uint index,
                           const char** name, const char** role,
                           const mx_uint** shape, mx_uint* ndim);
int MXTrainNativeSetInput(TrainNativeHandle h, const char* name,
                          const mx_float* data, mx_uint size);
/* one optimization step at learning rate lr (forward+backward+update);
 * the internal update counter t advances automatically */
int MXTrainNativeStep(TrainNativeHandle h, mx_float lr);
/* graph outputs of the LAST step (losses etc.; is_loss mirrors the
 * exported loss flags) */
int MXTrainNativeNumOutputs(TrainNativeHandle h, mx_uint* out);
int MXTrainNativeOutputInfo(TrainNativeHandle h, mx_uint index,
                            const char** name, int* is_loss,
                            const mx_uint** shape, mx_uint* ndim);
int MXTrainNativeGetOutput(TrainNativeHandle h, mx_uint index, mx_float* data,
                           mx_uint size);
/* write current params+auxs as a reference-format .params file
 * ("arg:"/"aux:" keys) */
int MXTrainNativeSaveParams(TrainNativeHandle h, const char* path);
int MXTrainNativeFree(TrainNativeHandle h);

#ifdef __cplusplus
}
#endif

#endif  // MXTPU_C_PREDICT_API_H_
