// Header-only C++ predict API (reference: cpp-package/include/mxnet-cpp — the
// generated C++ classes over the C API; this covers the deployment slice the
// predict clients use).
//
//   mxtpu::Predictor pred(json_str, param_blob, {{"data", {1, 3, 224, 224}}});
//   pred.SetInput("data", img.data(), img.size());
//   pred.Forward();
//   std::vector<float> out = pred.GetOutput(0);
#ifndef MXTPU_MXNET_PREDICT_HPP_
#define MXTPU_MXNET_PREDICT_HPP_

#include <map>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "c_predict_api.h"

namespace mxtpu {

inline void Check(int ret) {
  if (ret != 0) throw std::runtime_error(MXGetLastError());
}

class Predictor {
 public:
  Predictor(const std::string& symbol_json, const std::string& param_blob,
            const std::map<std::string, std::vector<mx_uint>>& input_shapes,
            int dev_type = 1, int dev_id = 0) {
    std::vector<const char*> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> data;
    for (const auto& kv : input_shapes) {
      keys.push_back(kv.first.c_str());
      for (mx_uint d : kv.second) data.push_back(d);
      indptr.push_back(static_cast<mx_uint>(data.size()));
    }
    Check(MXPredCreate(symbol_json.c_str(), param_blob.data(),
                       static_cast<int>(param_blob.size()), dev_type, dev_id,
                       static_cast<mx_uint>(keys.size()), keys.data(),
                       indptr.data(), data.data(), &handle_));
  }
  Predictor(const Predictor&) = delete;
  Predictor& operator=(const Predictor&) = delete;
  Predictor(Predictor&& o) noexcept : handle_(o.handle_) { o.handle_ = nullptr; }
  ~Predictor() {
    if (handle_) MXPredFree(handle_);
  }

  void SetInput(const std::string& key, const float* data, size_t size) {
    Check(MXPredSetInput(handle_, key.c_str(), data,
                         static_cast<mx_uint>(size)));
  }
  void Forward() { Check(MXPredForward(handle_)); }

  std::vector<mx_uint> GetOutputShape(mx_uint index) const {
    mx_uint* shape = nullptr;
    mx_uint ndim = 0;
    Check(MXPredGetOutputShape(handle_, index, &shape, &ndim));
    return std::vector<mx_uint>(shape, shape + ndim);
  }

  std::vector<float> GetOutput(mx_uint index) const {
    auto shape = GetOutputShape(index);
    mx_uint n = 1;
    for (mx_uint d : shape) n *= d;
    std::vector<float> out(n);
    Check(MXPredGetOutput(handle_, index, out.data(), n));
    return out;
  }

 private:
  PredictorHandle handle_ = nullptr;
};

// Parameter-blob reader (reference: MXNDList*).
class NDList {
 public:
  explicit NDList(const std::string& blob) {
    Check(MXNDListCreate(blob.data(), static_cast<int>(blob.size()), &handle_,
                         &size_));
  }
  NDList(const NDList&) = delete;
  NDList& operator=(const NDList&) = delete;
  NDList(NDList&& o) noexcept : handle_(o.handle_), size_(o.size_) {
    o.handle_ = nullptr;
    o.size_ = 0;
  }
  ~NDList() {
    if (handle_) MXNDListFree(handle_);
  }
  mx_uint size() const { return size_; }
  struct Entry {
    std::string key;
    const float* data;
    std::vector<mx_uint> shape;
  };
  Entry at(mx_uint i) const {
    const char* key;
    const float* data;
    const mx_uint* shape;
    mx_uint ndim;
    Check(MXNDListGet(handle_, i, &key, &data, &shape, &ndim));
    return Entry{key, data, std::vector<mx_uint>(shape, shape + ndim)};
  }

 private:
  NDListHandle handle_ = nullptr;
  mx_uint size_ = 0;
};

}  // namespace mxtpu

#endif  // MXTPU_MXNET_PREDICT_HPP_
