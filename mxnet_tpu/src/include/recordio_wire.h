// RecordIO wire-format constants shared by the native sharded reader
// (src/recordio.cc) and the RecordIO C API (src/c_api_recordio.cc); the
// Python mirror is mxnet_tpu/recordio.py. Framing (reference dmlc-core
// recordio): [u32 magic][u32 lrec][payload][pad to 4B], lrec>>29 =
// continuation flag (0 whole, 1 first, 2 last, 3 middle), low 29 bits =
// chunk length.
#ifndef MXTPU_RECORDIO_WIRE_H_
#define MXTPU_RECORDIO_WIRE_H_

#include <cstdint>
#include <cstddef>

namespace mxt_wire {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kMaxChunk = (1u << 29) - 1;

inline uint32_t cflag_of(uint32_t lrec) { return lrec >> 29; }
inline uint32_t len_of(uint32_t lrec) { return lrec & kMaxChunk; }
inline uint32_t lrec_of(uint32_t cflag, uint32_t len) {
  return (cflag << 29) | len;
}
inline size_t pad_of(size_t len) { return (4 - len % 4) % 4; }

}  // namespace mxt_wire

#endif  // MXTPU_RECORDIO_WIRE_H_
