// mxnet_cpp.hpp — header-only C++ training API over the C train API slice.
//
// The TPU-native analog of the reference's cpp-package
// (/root/reference/cpp-package/include/mxnet-cpp/: symbol.h, operator.h,
// executor.h, optimizer.h, kvstore.h — a header-only RAII layer over
// include/mxnet/c_api.h). Same user workflow: build a Symbol from operators
// in C++, SimpleBind it, feed data, Forward/Backward, optimizer-update, save
// a checkpoint that Python (and the reference) can load. The compute path
// underneath is the framework's XLA-compiled executor.
//
// Usage (see tests/test_cpp_package.py for a complete LeNet-style trainer):
//
//   namespace mx = mxnet::cpp;
//   auto data  = mx::Symbol::Variable("data");
//   auto fc1   = mx::Operator("FullyConnected").SetParam("num_hidden", 64)
//                    .SetInput("data", data).CreateSymbol("fc1");
//   auto act   = mx::Operator("Activation").SetParam("act_type", "relu")
//                    .SetInput("data", fc1).CreateSymbol();
//   ...
//   auto exec = net.SimpleBind(mx::Context::cpu(),
//                              {{"data", {32, 784}}, {"label", {32}}});
//   exec.InitXavier(7);
//   exec.SetArg("data", batch); exec.Forward(true); exec.Backward();
//   exec.MomentumUpdate(0.05f, 1e-4f, 0.9f);
//   exec.SaveParams("model-0001.params");   // loads in Python Module
#ifndef MXTPU_MXNET_CPP_HPP_
#define MXTPU_MXNET_CPP_HPP_

#include <map>
#include <memory>
#include <sstream>
#include <stdexcept>
#include <string>
#include <utility>
#include <vector>

#include "c_train_api.h"

namespace mxnet {
namespace cpp {

inline void Check(int rc, const char* what) {
  if (rc != 0) {
    throw std::runtime_error(std::string(what) + ": " +
                             MXTrainGetLastError());
  }
}

class Context {
 public:
  Context(std::string dev_type, int dev_id)
      : dev_type_(std::move(dev_type)), dev_id_(dev_id) {}
  static Context cpu(int id = 0) { return Context("cpu", id); }
  static Context tpu(int id = 0) { return Context("tpu", id); }
  static Context gpu(int id = 0) { return Context("gpu", id); }
  const std::string& dev_type() const { return dev_type_; }
  int dev_id() const { return dev_id_; }

 private:
  std::string dev_type_;
  int dev_id_;
};

class Executor;

class Symbol {
 public:
  Symbol() = default;
  explicit Symbol(SymbolHandle h)
      : h_(h, [](SymbolHandle p) {
          if (p) MXSymbolFree(p);
        }) {}

  static Symbol Variable(const std::string& name) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateVariable(name.c_str(), &h), "Variable");
    return Symbol(h);
  }
  static Symbol FromJSON(const std::string& json) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateFromJSON(json.c_str(), &h), "FromJSON");
    return Symbol(h);
  }
  std::string ToJSON() const {
    const char* out = nullptr;
    Check(MXSymbolSaveToJSON(get(), &out), "ToJSON");
    return out;
  }
  std::vector<std::string> ListArguments() const {
    return List(&MXSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return List(&MXSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return List(&MXSymbolListAuxiliaryStates);
  }

  // Defined after Executor.
  inline Executor SimpleBind(
      const Context& ctx,
      const std::map<std::string, std::vector<mx_uint>>& input_shapes,
      const std::string& grad_req = "write") const;

  SymbolHandle get() const { return h_.get(); }
  explicit operator bool() const { return static_cast<bool>(h_); }

 private:
  std::vector<std::string> List(
      int (*fn)(SymbolHandle, mx_uint*, const char***)) const {
    mx_uint n = 0;
    const char** arr = nullptr;
    Check(fn(get(), &n, &arr), "SymbolList");
    return std::vector<std::string>(arr, arr + n);
  }
  std::shared_ptr<void> h_;
};

// Builder over MXSymbolCreateFromOperator (reference: cpp-package
// operator.h Operator::SetParam/SetInput/CreateSymbol).
class Operator {
 public:
  explicit Operator(std::string op_name) : op_(std::move(op_name)) {}

  template <typename T>
  Operator& SetParam(const std::string& key, const T& value) {
    std::ostringstream ss;
    ss << std::boolalpha << value;
    keys_.push_back(key);
    vals_.push_back(ss.str());
    return *this;
  }
  Operator& SetInput(const std::string& input_name, const Symbol& sym) {
    input_keys_.push_back(input_name);
    inputs_.push_back(sym);
    return *this;
  }
  Operator& AddInput(const Symbol& sym) { return SetInput("", sym); }

  Symbol CreateSymbol(const std::string& name = "") {
    std::vector<const char*> k, v, ik;
    for (auto& s : keys_) k.push_back(s.c_str());
    for (auto& s : vals_) v.push_back(s.c_str());
    for (auto& s : input_keys_) ik.push_back(s.c_str());
    std::vector<SymbolHandle> ih;
    for (auto& s : inputs_) ih.push_back(s.get());
    SymbolHandle out = nullptr;
    Check(MXSymbolCreateFromOperator(
              op_.c_str(), name.c_str(), static_cast<mx_uint>(k.size()),
              k.data(), v.data(), static_cast<mx_uint>(ih.size()), ik.data(),
              ih.data(), &out),
          op_.c_str());
    return Symbol(out);
  }

 private:
  std::string op_;
  std::vector<std::string> keys_, vals_, input_keys_;
  std::vector<Symbol> inputs_;
};

class Executor {
 public:
  explicit Executor(ExecutorHandle h)
      : h_(h, [](ExecutorHandle p) {
          if (p) MXExecutorFree(p);
        }) {}

  void Forward(bool is_train) {
    Check(MXExecutorForward(get(), is_train ? 1 : 0), "Forward");
  }
  void Backward() { Check(MXExecutorBackward(get(), 0, nullptr), "Backward"); }
  void InitXavier(int seed) {
    Check(MXExecutorInitXavier(get(), seed), "InitXavier");
  }
  void SetArg(const std::string& name, const std::vector<float>& data) {
    Check(MXExecutorSetArg(get(), name.c_str(), data.data(),
                           static_cast<mx_uint>(data.size())),
          "SetArg");
  }
  std::vector<float> GetArg(const std::string& name) const {
    return Fetch([&](const float** p, mx_uint* n) {
      return MXExecutorGetArg(get(), name.c_str(), p, n);
    });
  }
  std::vector<float> GetGrad(const std::string& name) const {
    return Fetch([&](const float** p, mx_uint* n) {
      return MXExecutorGetGrad(get(), name.c_str(), p, n);
    });
  }
  std::vector<float> GetAux(const std::string& name) const {
    return Fetch([&](const float** p, mx_uint* n) {
      return MXExecutorGetAux(get(), name.c_str(), p, n);
    });
  }
  std::vector<float> GetOutput(mx_uint index) const {
    return Fetch([&](const float** p, mx_uint* n) {
      return MXExecutorGetOutput(get(), index, p, n);
    });
  }
  std::vector<mx_uint> OutputShape(mx_uint index) const {
    const mx_uint* shape = nullptr;
    mx_uint ndim = 0;
    Check(MXExecutorOutputShape(get(), index, &shape, &ndim), "OutputShape");
    return std::vector<mx_uint>(shape, shape + ndim);
  }
  mx_uint NumOutputs() const {
    mx_uint n = 0;
    Check(MXExecutorNumOutputs(get(), &n), "NumOutputs");
    return n;
  }
  // rescale_grad: loss gradients are batch-summed — pass 1/batch_size for
  // batch-mean training (the reference optimizer's rescale_grad knob)
  void SGDUpdate(float lr, float wd = 0.f, float rescale_grad = 1.f) {
    Check(MXExecutorSGDUpdate(get(), lr, wd, rescale_grad), "SGDUpdate");
  }
  void MomentumUpdate(float lr, float wd = 0.f, float momentum = 0.9f,
                      float rescale_grad = 1.f) {
    Check(MXExecutorMomentumUpdate(get(), lr, wd, momentum, rescale_grad),
          "MomentumUpdate");
  }
  void SaveParams(const std::string& path) const {
    Check(MXExecutorSaveParams(get(), path.c_str()), "SaveParams");
  }
  mx_uint LoadParams(const std::string& path) {
    mx_uint n = 0;
    Check(MXExecutorLoadParams(get(), path.c_str(), &n), "LoadParams");
    return n;
  }

  ExecutorHandle get() const { return h_.get(); }

 private:
  template <typename Fn>
  std::vector<float> Fetch(Fn fn) const {
    const float* p = nullptr;
    mx_uint n = 0;
    Check(fn(&p, &n), "Fetch");
    return std::vector<float>(p, p + n);
  }
  std::shared_ptr<void> h_;
};

inline Executor Symbol::SimpleBind(
    const Context& ctx,
    const std::map<std::string, std::vector<mx_uint>>& input_shapes,
    const std::string& grad_req) const {
  std::vector<const char*> keys;
  std::vector<mx_uint> shape_data, shape_idx{0};
  for (auto& kv : input_shapes) {
    keys.push_back(kv.first.c_str());
    shape_data.insert(shape_data.end(), kv.second.begin(), kv.second.end());
    shape_idx.push_back(static_cast<mx_uint>(shape_data.size()));
  }
  ExecutorHandle h = nullptr;
  Check(MXExecutorSimpleBindLite(get(), ctx.dev_type().c_str(), ctx.dev_id(),
                                 static_cast<mx_uint>(keys.size()),
                                 keys.data(), shape_data.data(),
                                 shape_idx.data(), grad_req.c_str(), &h),
        "SimpleBind");
  return Executor(h);
}

// Optimizer facade matching the reference cpp-package's
// Optimizer("sgd")->SetParam(...)->Update() workflow (optimizer.h), built on
// the executor's device-resident update rules.
class Optimizer {
 public:
  explicit Optimizer(const std::string& type) : type_(type) {
    if (type != "sgd" && type != "ccsgd") {
      throw std::runtime_error("cpp Optimizer supports sgd (got " + type +
                               "); use the Python surface for others");
    }
  }
  Optimizer& SetParam(const std::string& key, float value) {
    if (key == "lr" || key == "learning_rate") lr_ = value;
    else if (key == "wd") wd_ = value;
    else if (key == "momentum") momentum_ = value;
    else if (key == "rescale_grad") rescale_ = value;
    else throw std::runtime_error("unknown optimizer param " + key);
    return *this;
  }
  void Update(Executor& exec) {
    if (momentum_ != 0.f)
      exec.MomentumUpdate(lr_, wd_, momentum_, rescale_);
    else
      exec.SGDUpdate(lr_, wd_, rescale_);
  }

 private:
  std::string type_;
  float lr_ = 0.01f, wd_ = 0.f, momentum_ = 0.f, rescale_ = 1.f;
};

// Data iterator over the framework's IO pipeline (reference: cpp-package
// io.h MXDataIter — param-driven creation, Next/GetData/GetLabel loop).
class DataIter {
 public:
  DataIter(const std::string& name,
           const std::map<std::string, std::string>& params) {
    std::vector<const char*> k, v;
    for (auto& kv : params) {
      k.push_back(kv.first.c_str());
      v.push_back(kv.second.c_str());
    }
    DataIterHandle h = nullptr;
    Check(MXDataIterCreate(name.c_str(), static_cast<mx_uint>(k.size()),
                           k.data(), v.data(), &h),
          name.c_str());
    h_ = std::shared_ptr<void>(h, [](DataIterHandle p) {
      if (p) MXDataIterFree(p);
    });
  }
  bool Next() {
    int has = 0;
    Check(MXDataIterNext(h_.get(), &has), "DataIterNext");
    return has != 0;
  }
  void BeforeFirst() {
    Check(MXDataIterBeforeFirst(h_.get()), "BeforeFirst");
  }
  std::vector<float> GetData() {
    const float* p = nullptr;
    mx_uint n = 0;
    Check(MXDataIterGetData(h_.get(), &p, &n), "GetData");
    return std::vector<float>(p, p + n);
  }
  std::vector<float> GetLabel() {
    const float* p = nullptr;
    mx_uint n = 0;
    Check(MXDataIterGetLabel(h_.get(), &p, &n), "GetLabel");
    return std::vector<float>(p, p + n);
  }
  std::vector<mx_uint> GetDataShape() {
    const mx_uint* shape = nullptr;
    mx_uint ndim = 0;
    Check(MXDataIterGetDataShape(h_.get(), &shape, &ndim), "GetDataShape");
    return std::vector<mx_uint>(shape, shape + ndim);
  }
  int GetPadNum() {
    int pad = 0;
    Check(MXDataIterGetPadNum(h_.get(), &pad), "GetPadNum");
    return pad;
  }

 private:
  std::shared_ptr<void> h_;
};

class KVStore {
 public:
  explicit KVStore(const std::string& type = "local") {
    KVStoreHandle h = nullptr;
    Check(MXKVStoreCreate(type.c_str(), &h), "KVStoreCreate");
    h_ = std::shared_ptr<void>(h, [](KVStoreHandle p) {
      if (p) MXKVStoreFree(p);
    });
  }
  int GetRank() const {
    int r = 0;
    Check(MXKVStoreGetRank(h_.get(), &r), "GetRank");
    return r;
  }
  int GetGroupSize() const {
    int n = 0;
    Check(MXKVStoreGetGroupSize(h_.get(), &n), "GetGroupSize");
    return n;
  }
  void Init(int key, const std::vector<float>& data,
            const std::vector<mx_uint>& shape) {
    Check(MXKVStoreInit(h_.get(), key, data.data(), shape.data(),
                        static_cast<mx_uint>(shape.size())),
          "KVInit");
  }
  void Push(int key, const std::vector<float>& data,
            const std::vector<mx_uint>& shape) {
    Check(MXKVStorePush(h_.get(), key, data.data(), shape.data(),
                        static_cast<mx_uint>(shape.size())),
          "KVPush");
  }
  std::vector<float> Pull(int key) {
    const float* p = nullptr;
    mx_uint n = 0;
    Check(MXKVStorePull(h_.get(), key, &p, &n), "KVPull");
    return std::vector<float>(p, p + n);
  }

 private:
  std::shared_ptr<void> h_;
};

}  // namespace cpp
}  // namespace mxnet

#endif  // MXTPU_MXNET_CPP_HPP_
