// Pooled host allocator — rebuild of the reference's storage managers
// (reference: src/storage/pooled_storage_manager.h GPUPooledStorageManager
// recycles blocks by exact size; src/storage/cpu_device_storage.h 64-byte
// aligned host alloc). On TPU the device pool belongs to the XLA runtime, so
// this pool serves HOST staging memory: recordio record buffers, decoded
// image batches, kvstore wire buffers.
//
// Design differs from the reference: buckets are rounded up to the next
// power of two above 64B (exact-size recycling like the reference fragments
// badly for variable-length records), with a global byte cap that evicts
// largest-first (reference env MXNET_GPU_MEM_POOL_RESERVE is the analog).

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>
#include <vector>

namespace mxt {

struct Pool {
  std::mutex mu;
  // bucket (log2 size) -> free blocks
  std::map<int, std::vector<void*>> free_lists;
  std::atomic<int64_t> in_use{0};
  std::atomic<int64_t> pooled{0};
  int64_t max_pooled = 1LL << 30;  // 1 GiB default cap on cached bytes

  static int Bucket(size_t nbytes) {
    int b = 6;  // 64B min
    while ((1ULL << b) < nbytes) ++b;
    return b;
  }

  void* Alloc(size_t nbytes) {
    if (nbytes == 0) nbytes = 1;
    int b = Bucket(nbytes);
    {
      std::unique_lock<std::mutex> lk(mu);
      auto it = free_lists.find(b);
      if (it != free_lists.end() && !it->second.empty()) {
        void* p = it->second.back();
        it->second.pop_back();
        pooled.fetch_sub(1LL << b, std::memory_order_relaxed);
        in_use.fetch_add(1LL << b, std::memory_order_relaxed);
        return p;
      }
    }
    void* p = nullptr;
    if (posix_memalign(&p, 64, 1ULL << b) != 0) return nullptr;
    in_use.fetch_add(1LL << b, std::memory_order_relaxed);
    return p;
  }

  void Free(void* p, size_t nbytes) {
    if (p == nullptr) return;
    if (nbytes == 0) nbytes = 1;
    int b = Bucket(nbytes);
    in_use.fetch_sub(1LL << b, std::memory_order_relaxed);
    std::unique_lock<std::mutex> lk(mu);
    if (pooled.load(std::memory_order_relaxed) + (1LL << b) > max_pooled) {
      lk.unlock();
      free(p);
      return;
    }
    free_lists[b].push_back(p);
    pooled.fetch_add(1LL << b, std::memory_order_relaxed);
  }

  void Clear() {
    std::unique_lock<std::mutex> lk(mu);
    for (auto& kv : free_lists)
      for (void* p : kv.second) free(p);
    free_lists.clear();
    pooled.store(0, std::memory_order_relaxed);
  }
};

static Pool g_pool;

}  // namespace mxt

extern "C" {

void* mxt_alloc(size_t nbytes) { return mxt::g_pool.Alloc(nbytes); }
void mxt_free(void* p, size_t nbytes) { mxt::g_pool.Free(p, nbytes); }
void mxt_pool_clear() { mxt::g_pool.Clear(); }
void mxt_pool_set_cap(long long nbytes) { mxt::g_pool.max_pooled = nbytes; }
long long mxt_pool_in_use() {
  return mxt::g_pool.in_use.load(std::memory_order_relaxed);
}
long long mxt_pool_pooled() {
  return mxt::g_pool.pooled.load(std::memory_order_relaxed);
}

}  // extern "C"
