// Threaded dependency engine — TPU-native rebuild of the reference's async
// scheduler (reference: src/engine/threaded_engine.{h,cc} ThreadedVar /
// OprBlock wait counters, src/engine/threaded_engine_perdevice.cc worker
// pools; interface include/mxnet/engine.h:75-250).
//
// On TPU the device-side op stream is XLA's async dispatch, so this engine
// schedules HOST work: data-pipeline stages, checkpoint writes, kvstore
// server handlers, custom-python-op callbacks. Semantics match the
// reference's var model: an op runs once every const (read) var grants it
// shared access and every mutable (write) var grants it exclusive access;
// completion releases dependents in FIFO order per var.
//
// Not a translation: the reference threads a linked list of
// VersionedVarBlocks through object pools; here each Var owns a deque of
// pending grants behind a mutex (host-side throughput is bounded by Python
// callbacks, not by the scheduler), and priorities use a two-level queue
// (reference: FnProperty kCPUPrioritized, engine.h:59-70).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace mxt {

typedef void (*OpFn)(void* arg);

struct Opr;

// One scheduling grant on a var: an op waiting to read or write it.
struct Pending {
  Opr* opr;
  bool write;
};

struct Var {
  std::mutex mu;
  std::deque<Pending> queue;  // ops not yet granted, FIFO
  int running_reads = 0;      // granted, incomplete reads
  bool writing = false;       // granted, incomplete write
};

struct Opr {
  OpFn fn;
  void* arg;
  std::vector<Var*> const_vars;
  std::vector<Var*> mutable_vars;
  int priority;
  std::atomic<int> wait;  // deps not yet granted + 1 (reference: OprBlock::wait)
};

class Engine {
 public:
  explicit Engine(int num_workers) : stop_(false), outstanding_(0) {
    if (num_workers < 1) num_workers = 1;
    for (int i = 0; i < num_workers; ++i)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  ~Engine() {
    {
      std::unique_lock<std::mutex> lk(qmu_);
      stop_ = true;
    }
    qcv_.notify_all();
    for (auto& t : workers_) t.join();
    // free any vars the owner leaked
  }

  Var* NewVar() { return new Var(); }

  // Push an op. Grants are requested in order; the op dispatches when wait
  // hits zero (reference: ThreadedEngine::Push threaded_engine.cc:258-281).
  void Push(OpFn fn, void* arg, Var** cvars, int nc, Var** mvars, int nm,
            int priority) {
    Opr* op = new Opr();
    op->fn = fn;
    op->arg = arg;
    // Deduplicate (reference: Engine::DeduplicateVarHandle, engine.h:231):
    // repeated vars, and any var in both lists, count once — as a write
    // (a read grant alongside a queued write on the same var would deadlock
    // the op against itself).
    op->mutable_vars.assign(mvars, mvars + nm);
    std::sort(op->mutable_vars.begin(), op->mutable_vars.end());
    op->mutable_vars.erase(
        std::unique(op->mutable_vars.begin(), op->mutable_vars.end()),
        op->mutable_vars.end());
    op->const_vars.assign(cvars, cvars + nc);
    std::sort(op->const_vars.begin(), op->const_vars.end());
    op->const_vars.erase(
        std::unique(op->const_vars.begin(), op->const_vars.end()),
        op->const_vars.end());
    op->const_vars.erase(
        std::remove_if(op->const_vars.begin(), op->const_vars.end(),
                       [&](Var* v) {
                         return std::binary_search(op->mutable_vars.begin(),
                                                   op->mutable_vars.end(), v);
                       }),
        op->const_vars.end());
    op->priority = priority;
    op->wait.store(
        static_cast<int>(op->const_vars.size() + op->mutable_vars.size()) + 1,
        std::memory_order_relaxed);
    outstanding_.fetch_add(1, std::memory_order_relaxed);
    for (Var* v : op->const_vars) AppendRead(v, op);
    for (Var* v : op->mutable_vars) AppendWrite(v, op);
    Satisfy(op);  // the +1 sentinel
  }

  // Block until every op that reads or writes `v` at push time has finished:
  // push a no-op writer and wait on it (reference: Engine::WaitForVar
  // engine.h:172 pushes a read op; a writer also drains earlier readers,
  // which matches WaitToWrite and is strictly stronger for WaitToRead).
  void WaitForVar(Var* v) {
    Waiter w;
    Var* mv[1] = {v};
    Push(&Engine::WaitFn, &w, nullptr, 0, mv, 1, 1);
    std::unique_lock<std::mutex> lk(w.mu);
    w.cv.wait(lk, [&] { return w.done; });
  }

  void WaitAll() {
    std::unique_lock<std::mutex> lk(done_mu_);
    done_cv_.wait(lk, [&] {
      return outstanding_.load(std::memory_order_acquire) == 0;
    });
  }

  // Delete var once all its pending ops drain: push a writer that frees it.
  void DeleteVar(Var* v) {
    Var* mv[1] = {v};
    Push(&Engine::DeleteVarFn, v, nullptr, 0, mv, 1, 0);
  }

  int64_t Outstanding() const {
    return outstanding_.load(std::memory_order_acquire);
  }

 private:
  struct Waiter {
    std::mutex mu;
    std::condition_variable cv;
    bool done = false;
  };
  static void WaitFn(void* arg) {
    Waiter* w = static_cast<Waiter*>(arg);
    std::unique_lock<std::mutex> lk(w->mu);
    w->done = true;
    w->cv.notify_all();
  }
  static void DeleteVarFn(void*) {}

  void AppendRead(Var* v, Opr* op) {
    bool grant = false;
    {
      std::unique_lock<std::mutex> lk(v->mu);
      if (!v->writing && v->queue.empty()) {
        v->running_reads++;
        grant = true;
      } else {
        v->queue.push_back({op, false});
      }
    }
    if (grant) Satisfy(op);
  }

  void AppendWrite(Var* v, Opr* op) {
    bool grant = false;
    {
      std::unique_lock<std::mutex> lk(v->mu);
      if (!v->writing && v->running_reads == 0 && v->queue.empty()) {
        v->writing = true;
        grant = true;
      } else {
        v->queue.push_back({op, true});
      }
    }
    if (grant) Satisfy(op);
  }

  // A granted dependency; dispatch when the counter drains
  // (reference: OprBlock::decr_wait, threaded_engine.h:44-58).
  void Satisfy(Opr* op) {
    if (op->wait.fetch_sub(1, std::memory_order_acq_rel) == 1) Enqueue(op);
  }

  void Enqueue(Opr* op) {
    {
      std::unique_lock<std::mutex> lk(qmu_);
      if (op->priority > 0)
        prio_queue_.push_back(op);
      else
        queue_.push_back(op);
    }
    qcv_.notify_one();
  }

  // Completion walks each var's queue granting successors (reference:
  // ThreadedVar::CompleteReadDependency / CompleteWriteDependency,
  // threaded_engine.cc:83-168).
  void CompleteRead(Var* v) {
    Opr* granted = nullptr;
    {
      std::unique_lock<std::mutex> lk(v->mu);
      v->running_reads--;
      if (v->running_reads == 0 && !v->queue.empty() && v->queue.front().write) {
        granted = v->queue.front().opr;
        v->queue.pop_front();
        v->writing = true;
      }
    }
    if (granted) Satisfy(granted);
  }

  void CompleteWrite(Var* v) {
    std::vector<Opr*> granted;
    {
      std::unique_lock<std::mutex> lk(v->mu);
      v->writing = false;
      if (!v->queue.empty() && v->queue.front().write) {
        granted.push_back(v->queue.front().opr);
        v->queue.pop_front();
        v->writing = true;
      } else {
        while (!v->queue.empty() && !v->queue.front().write) {
          granted.push_back(v->queue.front().opr);
          v->queue.pop_front();
          v->running_reads++;
        }
      }
    }
    for (Opr* op : granted) Satisfy(op);
  }

  void WorkerLoop() {
    for (;;) {
      Opr* op = nullptr;
      {
        std::unique_lock<std::mutex> lk(qmu_);
        qcv_.wait(lk, [&] {
          return stop_ || !prio_queue_.empty() || !queue_.empty();
        });
        if (stop_ && prio_queue_.empty() && queue_.empty()) return;
        if (!prio_queue_.empty()) {
          op = prio_queue_.front();
          prio_queue_.pop_front();
        } else {
          op = queue_.front();
          queue_.pop_front();
        }
      }
      if (op->fn) op->fn(op->arg);
      bool delete_var = (op->fn == &Engine::DeleteVarFn);
      for (Var* v : op->const_vars) CompleteRead(v);
      for (Var* v : op->mutable_vars) {
        if (delete_var) {
          delete v;  // sole mutable var; nothing can follow a delete writer
        } else {
          CompleteWrite(v);
        }
      }
      delete op;
      if (outstanding_.fetch_sub(1, std::memory_order_acq_rel) == 1) {
        std::unique_lock<std::mutex> lk(done_mu_);
        done_cv_.notify_all();
      }
    }
  }

  std::vector<std::thread> workers_;
  std::mutex qmu_;
  std::condition_variable qcv_;
  std::deque<Opr*> queue_;
  std::deque<Opr*> prio_queue_;
  bool stop_;
  std::atomic<int64_t> outstanding_;
  std::mutex done_mu_;
  std::condition_variable done_cv_;
};

}  // namespace mxt

extern "C" {

void* mxt_engine_create(int num_workers) { return new mxt::Engine(num_workers); }
void mxt_engine_destroy(void* h) { delete static_cast<mxt::Engine*>(h); }
void* mxt_engine_new_var(void* h) {
  return static_cast<mxt::Engine*>(h)->NewVar();
}
void mxt_engine_delete_var(void* h, void* v) {
  static_cast<mxt::Engine*>(h)->DeleteVar(static_cast<mxt::Var*>(v));
}
void mxt_engine_push(void* h, mxt::OpFn fn, void* arg, void** cvars, int nc,
                     void** mvars, int nm, int priority) {
  static_cast<mxt::Engine*>(h)->Push(
      fn, arg, reinterpret_cast<mxt::Var**>(cvars), nc,
      reinterpret_cast<mxt::Var**>(mvars), nm, priority);
}
void mxt_engine_wait_for_var(void* h, void* v) {
  static_cast<mxt::Engine*>(h)->WaitForVar(static_cast<mxt::Var*>(v));
}
void mxt_engine_wait_all(void* h) { static_cast<mxt::Engine*>(h)->WaitAll(); }
long long mxt_engine_outstanding(void* h) {
  return static_cast<mxt::Engine*>(h)->Outstanding();
}

}  // extern "C"
