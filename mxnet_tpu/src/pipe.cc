// Native decode->augment->batch pipeline stage (reference:
// src/io/iter_image_recordio_2.cc ImageRecordIOParser2 — chunked InputSplit
// reading + OMP-parallel decode/augment into ordered InstVector batches,
// registered :559 — layered under iter_batchloader.h / iter_prefetcher.h).
//
// Shape here: N worker threads pull (seq, record) from the sharded RecReader
// ring (src/recordio.cc, already thread-safe), JPEG-decode (decode.cc),
// augment (augment.cc: resize-shortest-edge -> center/random crop ->
// horizontal flip), and deposit into an ordered reassembly map; one
// assembler thread drains the map in sequence order into uint8-HWC batch
// buffers and parks complete batches in a bounded output ring the python
// consumer (or any C caller) pops. Zero Python-thread involvement between
// record bytes and the assembled wire batch — the python side's only work
// per batch is one memcpy into a numpy array.
//
// Ordering/quarantine contract mirrors io_image.py's batcher: batches keep
// record order; corrupt records are skipped but still claim their sequence
// number so reassembly never stalls; past the max_bad budget the pipeline
// fails fast and the error surfaces from mxt_pipe_next after any batches
// assembled before the overflow.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include "include/pipe_api.h"

extern "C" {
void* mxt_alloc(size_t nbytes);
void mxt_free(void* p, size_t nbytes);
void* mxt_rec_reader_open(const char* path, int part_index, int num_parts,
                          int queue_size);
int mxt_rec_reader_next(void* h, char** data, size_t* len);
void mxt_rec_free(char* data, size_t len);
void mxt_rec_reader_close(void* h);
}

namespace mxt_aug {
void resize_bilinear(const uint8_t* src, int sh, int sw, int c, uint8_t* dst,
                     int dh, int dw);
void scale_down(int sw, int sh, int* w, int* h);
void resize_short_dims(int w, int h, int size, int* nw, int* nh);
}  // namespace mxt_aug

namespace mxt_pipe {

using Clock = std::chrono::steady_clock;

inline double seconds_since(Clock::time_point t0) {
  return std::chrono::duration<double>(Clock::now() - t0).count();
}

// splitmix64: deterministic per-worker seed mix of (seed, epoch, wid) — the
// native analog of io_image.py's per-worker seeded stream contract. The
// native and python streams are both deterministic per (seed, epoch, worker)
// but are NOT the same sequence (python draws from CPython's global MT).
inline uint64_t mix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

struct Item {
  uint8_t* img = nullptr;  // out_h*out_w*3, null = quarantined record
  size_t img_bytes = 0;
  std::vector<float> label;
};

struct Batch {
  uint8_t* data = nullptr;
  size_t data_bytes = 0;
  float* label = nullptr;  // batch_size * label_width, mxt_alloc'd
  size_t label_bytes = 0;
  int pad = 0;
};

class Pipe {
 public:
  explicit Pipe(const MXTPipeConfig& cfg) : cfg_(cfg) {
    img_bytes_ = static_cast<size_t>(cfg_.out_h) * cfg_.out_w * cfg_.out_c;
    batch_bytes_ = img_bytes_ * cfg_.batch_size;
    label_bytes_ = static_cast<size_t>(cfg_.batch_size) * cfg_.label_width *
                   sizeof(float);
    pending_cap_ = cfg_.batch_size * 4;
    if (pending_cap_ < 64) pending_cap_ = 64;
    if (pending_cap_ < cfg_.num_threads * 16)
      pending_cap_ = cfg_.num_threads * 16;
    prefetch_ = cfg_.prefetch < 1 ? 1 : cfg_.prefetch;
    reader_ = mxt_rec_reader_open(cfg_.path, cfg_.part_index, cfg_.num_parts,
                                  cfg_.num_threads * 8);
    if (!reader_) {
      fail("cannot open " + std::string(cfg_.path));
      eos_ = true;
      return;
    }
    active_workers_ = cfg_.num_threads;
    for (int i = 0; i < cfg_.num_threads; ++i)
      workers_.emplace_back([this, i] { WorkerLoop(i); });
    assembler_ = std::thread([this] { AssemblerLoop(); });
  }

  ~Pipe() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_data_.notify_all();
    cv_space_.notify_all();
    cv_out_.notify_all();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
    if (assembler_.joinable()) assembler_.join();
    if (reader_) mxt_rec_reader_close(reader_);
    std::lock_guard<std::mutex> lk(mu_);
    for (auto& kv : pending_) FreeItem(&kv.second);
    for (auto& b : out_q_) FreeBatch(&b);
    FreeBatch(&fill_);
  }

  // 1 batch, 0 end-of-shard, -1 error; caller owns (*data, *label) until
  // Release
  int Pop(uint8_t** data, float** label, int* pad) {
    Batch b;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_out_.wait(lk, [&] { return !out_q_.empty() || eos_ || failed_; });
      if (out_q_.empty()) return failed_ ? -1 : 0;
      b = out_q_.front();
      out_q_.pop_front();
    }
    cv_out_.notify_all();
    *data = b.data;
    *label = b.label;
    *pad = b.pad;
    batches_.fetch_add(1, std::memory_order_relaxed);
    return 1;
  }

  void Release(uint8_t* data, float* label) {
    if (data) mxt_free(data, batch_bytes_);
    if (label) mxt_free(label, label_bytes_);
  }

  // copying variant (C callers without a release discipline)
  int Next(uint8_t* data, float* label, int* pad) {
    uint8_t* d = nullptr;
    float* l = nullptr;
    int rc = Pop(&d, &l, pad);
    if (rc != 1) return rc;
    std::memcpy(data, d, batch_bytes_);
    std::memcpy(label, l, label_bytes_);
    Release(d, l);
    return 1;
  }

  const char* Error() {
    std::lock_guard<std::mutex> lk(mu_);
    return error_.c_str();
  }

  void Stats(double* out, int n) {
    double vals[6] = {
        static_cast<double>(bad_.load(std::memory_order_relaxed)),
        decode_ns_.load(std::memory_order_relaxed) * 1e-9,
        augment_ns_.load(std::memory_order_relaxed) * 1e-9,
        assemble_ns_.load(std::memory_order_relaxed) * 1e-9,
        static_cast<double>(decoded_.load(std::memory_order_relaxed)),
        static_cast<double>(batches_.load(std::memory_order_relaxed)),
    };
    for (int i = 0; i < n && i < 6; ++i) out[i] = vals[i];
  }

 private:
  void fail(const std::string& msg) {
    // caller must NOT hold mu_
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (!failed_) error_ = msg;
      failed_ = true;
    }
    cv_data_.notify_all();
    cv_space_.notify_all();
    cv_out_.notify_all();
  }

  void FreeItem(Item* it) {
    if (it->img) mxt_free(it->img, it->img_bytes);
    it->img = nullptr;
  }

  void FreeBatch(Batch* b) {
    if (b->data) mxt_free(b->data, b->data_bytes);
    if (b->label) mxt_free(b->label, b->label_bytes);
    b->data = nullptr;
    b->label = nullptr;
  }

  // false on allocation failure (fail() already called)
  bool AllocBatch(Batch* b) {
    b->data = static_cast<uint8_t*>(mxt_alloc(batch_bytes_));
    b->data_bytes = batch_bytes_;
    b->label = static_cast<float*>(mxt_alloc(label_bytes_));
    b->label_bytes = label_bytes_;
    if (b->data && b->label) {
      std::memset(b->label, 0, label_bytes_);
      return true;
    }
    FreeBatch(b);
    fail("native decode: batch buffer allocation failed");
    return false;
  }

  // Parse the recordio payload: IRHeader (u32 flag, f32 label, u64 id, u64
  // id2 — recordio.py's "<IfQQ"), flag>0 => flag float32 labels follow,
  // then the image bytes. False = malformed.
  bool ParseRecord(const char* rec, size_t len, std::vector<float>* label,
                   const uint8_t** img, size_t* img_len) {
    if (len < 24) return false;
    uint32_t flag;
    float lab0;
    std::memcpy(&flag, rec, 4);
    std::memcpy(&lab0, rec + 4, 4);
    size_t off = 24;
    label->assign(static_cast<size_t>(cfg_.label_width), 0.0f);
    if (flag > 0) {
      if (off + static_cast<size_t>(flag) * 4 > len) return false;
      size_t n = flag < static_cast<uint32_t>(cfg_.label_width)
                     ? flag
                     : static_cast<uint32_t>(cfg_.label_width);
      std::memcpy(label->data(), rec + off, n * 4);
      off += static_cast<size_t>(flag) * 4;
    } else if (cfg_.label_width > 0) {
      (*label)[0] = lab0;
    }
    *img = reinterpret_cast<const uint8_t*>(rec) + off;
    *img_len = len - off;
    return true;
  }

  // decode + augment one record into a ready out_h*out_w*3 image.
  // -1 = corrupt (quarantine), 0 = ok.
  int Process(const uint8_t* jpg, size_t jpg_len, std::mt19937_64* rng,
              uint8_t** out) {
    auto t0 = Clock::now();
    if (cfg_.resize == 0) {
      // packed-dataset fast path: a source already at (out_h, out_w) makes
      // every crop the identity — decode scanlines straight into the output
      // image, no intermediate buffer or copy
      uint8_t* direct = static_cast<uint8_t*>(mxt_alloc(img_bytes_));
      if (!direct) return -1;
      int rc = mxt_decode_jpeg_direct(jpg, jpg_len, direct, cfg_.out_h,
                                      cfg_.out_w);
      if (rc == 1) {
        decode_ns_.fetch_add(
            static_cast<int64_t>(seconds_since(t0) * 1e9),
            std::memory_order_relaxed);
        t0 = Clock::now();
        MaybeMirror(direct, rng);
        augment_ns_.fetch_add(
            static_cast<int64_t>(seconds_since(t0) * 1e9),
            std::memory_order_relaxed);
        *out = direct;
        return 0;
      }
      mxt_free(direct, img_bytes_);
      if (rc < 0) return -1;
    }
    uint8_t* raw = nullptr;
    int h = 0, w = 0;
    if (mxt_decode_jpeg(jpg, jpg_len, &raw, &h, &w) != 0) return -1;
    size_t raw_bytes = static_cast<size_t>(h) * w * 3;
    decode_ns_.fetch_add(
        static_cast<int64_t>(seconds_since(t0) * 1e9),
        std::memory_order_relaxed);

    t0 = Clock::now();
    // resize shortest edge (image.py ResizeAug)
    if (cfg_.resize > 0 && !(h == cfg_.resize && w == cfg_.resize)) {
      int nw, nh;
      mxt_aug::resize_short_dims(w, h, cfg_.resize, &nw, &nh);
      if (nw != w || nh != h) {
        size_t nbytes = static_cast<size_t>(nh) * nw * 3;
        uint8_t* resized = static_cast<uint8_t*>(mxt_alloc(nbytes));
        if (!resized) {
          mxt_free(raw, raw_bytes);
          return -1;
        }
        mxt_aug::resize_bilinear(raw, h, w, 3, resized, nh, nw);
        mxt_free(raw, raw_bytes);
        raw = resized;
        raw_bytes = nbytes;
        h = nh;
        w = nw;
      }
    }
    // crop to (out_w, out_h) via scale_down (image.py CenterCropAug /
    // RandomCropAug: crop a scaled-down rect, then resize it to target)
    int cw = cfg_.out_w, ch = cfg_.out_h;
    mxt_aug::scale_down(w, h, &cw, &ch);
    int x0, y0;
    if (cfg_.crop == 1) {
      x0 = w > cw ? static_cast<int>((*rng)() % (w - cw + 1)) : 0;
      y0 = h > ch ? static_cast<int>((*rng)() % (h - ch + 1)) : 0;
    } else {
      x0 = (w - cw) / 2;
      y0 = (h - ch) / 2;
    }
    uint8_t* out_img = static_cast<uint8_t*>(mxt_alloc(img_bytes_));
    if (!out_img) {
      mxt_free(raw, raw_bytes);
      return -1;
    }
    if (cw == cfg_.out_w && ch == cfg_.out_h) {
      for (int y = 0; y < ch; ++y)
        std::memcpy(out_img + static_cast<size_t>(y) * cw * 3,
                    raw + (static_cast<size_t>(y0 + y) * w + x0) * 3,
                    static_cast<size_t>(cw) * 3);
    } else {
      // crop rect != target: contiguous crop, then Pillow-parity resize
      std::vector<uint8_t> cropped(static_cast<size_t>(ch) * cw * 3);
      for (int y = 0; y < ch; ++y)
        std::memcpy(cropped.data() + static_cast<size_t>(y) * cw * 3,
                    raw + (static_cast<size_t>(y0 + y) * w + x0) * 3,
                    static_cast<size_t>(cw) * 3);
      mxt_aug::resize_bilinear(cropped.data(), ch, cw, 3, out_img,
                               cfg_.out_h, cfg_.out_w);
    }
    mxt_free(raw, raw_bytes);
    MaybeMirror(out_img, rng);
    augment_ns_.fetch_add(
        static_cast<int64_t>(seconds_since(t0) * 1e9),
        std::memory_order_relaxed);
    *out = out_img;
    return 0;
  }

  // horizontal flip with probability mirror_prob (image.py HorizontalFlipAug)
  void MaybeMirror(uint8_t* img, std::mt19937_64* rng) {
    if (cfg_.mirror_prob <= 0.0) return;
    double u = (*rng)() * (1.0 / 18446744073709551616.0);  // [0, 1)
    if (u >= cfg_.mirror_prob) return;
    for (int y = 0; y < cfg_.out_h; ++y) {
      uint8_t* row = img + static_cast<size_t>(y) * cfg_.out_w * 3;
      for (int xl = 0, xr = cfg_.out_w - 1; xl < xr; ++xl, --xr) {
        for (int b = 0; b < 3; ++b)
          std::swap(row[xl * 3 + b], row[xr * 3 + b]);
      }
    }
  }

  void WorkerLoop(int wid) {
    std::mt19937_64 rng(
        mix64(static_cast<uint64_t>(cfg_.seed) * 0x100000001b3ull ^
              mix64(static_cast<uint64_t>(cfg_.epoch) << 20 ^
                    static_cast<uint64_t>(wid))));
    for (;;) {
      char* rec = nullptr;
      size_t rec_len = 0;
      int64_t seq;
      {
        // one lock assigns the sequence number atomically with the pop, so
        // reassembly order == record order regardless of scheduling
        std::lock_guard<std::mutex> lk(reader_mu_);
        if (stopped()) break;
        if (!mxt_rec_reader_next(reader_, &rec, &rec_len)) break;
        seq = reader_seq_++;
      }
      Item item;
      const uint8_t* jpg = nullptr;
      size_t jpg_len = 0;
      bool ok = ParseRecord(rec, rec_len, &item.label, &jpg, &jpg_len);
      if (ok) {
        ok = Process(jpg, jpg_len, &rng, &item.img) == 0;
        if (ok) {
          item.img_bytes = img_bytes_;
          decoded_.fetch_add(1, std::memory_order_relaxed);
        }
      }
      mxt_rec_free(rec, rec_len);
      if (!ok) {
        int64_t nbad = bad_.fetch_add(1, std::memory_order_relaxed) + 1;
        if (cfg_.max_bad >= 0 && nbad > cfg_.max_bad) {
          fail("native decode: " + std::to_string(nbad) +
               " corrupt records exceed MXNET_IO_MAX_BAD_RECORDS=" +
               std::to_string(cfg_.max_bad));
          break;
        }
        // quarantined records still claim their seq (img stays null)
      }
      std::unique_lock<std::mutex> lk(mu_);
      cv_space_.wait(lk, [&] {
        // the holder of next_emit_ must always get through, or reassembly
        // deadlocks against a full pending map
        return stop_ || failed_ ||
               pending_.size() < static_cast<size_t>(pending_cap_) ||
               seq == next_emit_;
      });
      if (stop_ || failed_) {
        lk.unlock();
        FreeItem(&item);
        break;
      }
      pending_.emplace(seq, std::move(item));
      lk.unlock();
      cv_data_.notify_all();
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      --active_workers_;
    }
    cv_data_.notify_all();
  }

  void AssemblerLoop() {
    if (!AllocBatch(&fill_)) return;
    int i = 0;  // slot in the current batch
    for (;;) {
      Item item;
      bool have = false;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_data_.wait(lk, [&] {
          return stop_ || failed_ || pending_.count(next_emit_) ||
                 (active_workers_ == 0 && pending_.empty());
        });
        if (stop_ || failed_) return;
        auto it = pending_.find(next_emit_);
        if (it != pending_.end()) {
          item = std::move(it->second);
          pending_.erase(it);
          ++next_emit_;
          have = true;
        } else if (active_workers_ == 0 && pending_.empty()) {
          break;  // end of shard
        }
      }
      cv_space_.notify_all();
      if (!have || !item.img) continue;  // quarantined record: skip
      auto t0 = Clock::now();
      std::memcpy(fill_.data + static_cast<size_t>(i) * img_bytes_, item.img,
                  img_bytes_);
      std::copy(item.label.begin(), item.label.end(),
                fill_.label + static_cast<size_t>(i) * cfg_.label_width);
      FreeItem(&item);
      ++i;
      assemble_ns_.fetch_add(
          static_cast<int64_t>(seconds_since(t0) * 1e9),
          std::memory_order_relaxed);
      if (i == cfg_.batch_size) {
        if (!EmitBatch(0)) return;
        i = 0;
      }
    }
    if (i > 0) {
      // pad the final batch by wrapping the filled slots (io_image.py's
      // batcher / the reference's round_batch pad semantics)
      for (int j = i; j < cfg_.batch_size; ++j) {
        std::memcpy(fill_.data + static_cast<size_t>(j) * img_bytes_,
                    fill_.data + static_cast<size_t>(j - i) * img_bytes_,
                    img_bytes_);
        std::copy(fill_.label + static_cast<size_t>(j - i) * cfg_.label_width,
                  fill_.label +
                      static_cast<size_t>(j - i + 1) * cfg_.label_width,
                  fill_.label + static_cast<size_t>(j) * cfg_.label_width);
      }
      if (!EmitBatch(cfg_.batch_size - i)) return;
    }
    {
      std::lock_guard<std::mutex> lk(mu_);
      eos_ = true;
    }
    cv_out_.notify_all();
  }

  // park the filled batch in the bounded output ring; false = stopped
  bool EmitBatch(int pad) {
    Batch next;
    if (!AllocBatch(&next)) return false;
    fill_.pad = pad;
    std::unique_lock<std::mutex> lk(mu_);
    cv_out_.wait(lk, [&] {
      return stop_ || failed_ ||
             out_q_.size() < static_cast<size_t>(prefetch_);
    });
    if (stop_ || failed_) {
      lk.unlock();
      FreeBatch(&next);
      return false;
    }
    out_q_.push_back(fill_);
    fill_ = next;
    lk.unlock();
    cv_out_.notify_all();
    return true;
  }

  bool stopped() {
    std::lock_guard<std::mutex> lk(mu_);
    return stop_ || failed_;
  }

  MXTPipeConfig cfg_;
  size_t img_bytes_ = 0, batch_bytes_ = 0, label_bytes_ = 0;
  int pending_cap_ = 0, prefetch_ = 1;
  void* reader_ = nullptr;

  std::mutex reader_mu_;
  int64_t reader_seq_ = 0;

  std::mutex mu_;
  std::condition_variable cv_data_, cv_space_, cv_out_;
  std::map<int64_t, Item> pending_;
  int64_t next_emit_ = 0;
  int active_workers_ = 0;
  std::deque<Batch> out_q_;
  Batch fill_;
  bool stop_ = false, failed_ = false, eos_ = false;
  std::string error_;

  std::atomic<int64_t> bad_{0}, decoded_{0}, batches_{0};
  std::atomic<int64_t> decode_ns_{0}, augment_ns_{0}, assemble_ns_{0};

  std::vector<std::thread> workers_;
  std::thread assembler_;
};

}  // namespace mxt_pipe

extern "C" {

void* mxt_pipe_create(const MXTPipeConfig* cfg) {
  if (!cfg || !cfg->path || cfg->batch_size < 1 || cfg->num_threads < 1 ||
      cfg->out_c != 3 || cfg->label_width < 1)
    return nullptr;
  if (!mxt_pipe_decode_available()) return nullptr;
  return new mxt_pipe::Pipe(*cfg);
}

int mxt_pipe_next(void* h, uint8_t* data, float* label, int* pad) {
  return static_cast<mxt_pipe::Pipe*>(h)->Next(data, label, pad);
}

int mxt_pipe_pop(void* h, uint8_t** data, float** label, int* pad) {
  return static_cast<mxt_pipe::Pipe*>(h)->Pop(data, label, pad);
}

void mxt_pipe_release(void* h, uint8_t* data, float* label) {
  static_cast<mxt_pipe::Pipe*>(h)->Release(data, label);
}

const char* mxt_pipe_error(void* h) {
  return static_cast<mxt_pipe::Pipe*>(h)->Error();
}

void mxt_pipe_stats(void* h, double* out, int n) {
  static_cast<mxt_pipe::Pipe*>(h)->Stats(out, n);
}

void mxt_pipe_close(void* h) { delete static_cast<mxt_pipe::Pipe*>(h); }

}  // extern "C"
