// C predict API — the standalone deployment surface for C/C++ clients
// (reference: include/mxnet/c_predict_api.h + src/c_api/c_predict_api.cc:
// MXPredCreate/SetInput/Forward/GetOutputShape/GetOutput/Free, MXNDList*).
//
// The reference links the full libmxnet; here the predictor embeds CPython and
// delegates to mxnet_tpu.predict (whose forward is one cached XLA executable),
// so any C/C++/FFI caller gets the identical function signatures while the
// compute path stays the TPU one. Build: `make c_predict` (links libpython).
//
// Threading: every entry point takes the GIL via PyGILState_Ensure, so the
// library is safe to call from any thread after MXPredInit/first use.
#include <Python.h>
#include <dlfcn.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#define MXNET_DLL extern "C" __attribute__((visibility("default")))

typedef void* PredictorHandle;
typedef void* NDListHandle;
typedef unsigned int mx_uint;
typedef float mx_float;

// Promote libpython's symbols to global visibility before initializing the
// embedded interpreter. When this library is dlopen'd RTLD_LOCAL by an FFI
// host (perl XSLoader, ruby, node), python extension modules (numpy, jaxlib)
// loaded later by the embedded interpreter cannot resolve Py* symbols
// otherwise. No-op when the host already links libpython (python itself,
// directly-linked C clients).
void mxtpu_promote_libpython() {
  static const char* patterns[] = {
      "libpython%d.%d.so",      // -dev symlink
      "libpython%d.%d.so.1.0",  // runtime soname (no -dev installed)
      "libpython%d.%dm.so",     // pre-3.8 'm' ABI
  };
  char name[64];
  for (const char* pat : patterns) {
    std::snprintf(name, sizeof(name), pat, PY_MAJOR_VERSION,
                  PY_MINOR_VERSION);
    if (dlopen(name, RTLD_NOW | RTLD_GLOBAL)) return;
  }
  // best-effort: hosts that already link libpython don't need any of these
}

namespace {

thread_local std::string g_last_error;

struct PyEnv {
  PyEnv() {
    if (!Py_IsInitialized()) {
      mxtpu_promote_libpython();
      Py_InitializeEx(0);
      owns = true;
#if PY_VERSION_HEX < 0x03090000
      PyEval_InitThreads();
#endif
      // release the GIL acquired by Py_Initialize so workers can Ensure it
      state = PyEval_SaveThread();
    }
  }
  bool owns = false;
  PyThreadState* state = nullptr;
};

PyEnv& env() {
  static PyEnv e;
  return e;
}

struct Gil {
  Gil() {
    env();
    st = PyGILState_Ensure();
  }
  ~Gil() { PyGILState_Release(st); }
  PyGILState_STATE st;
};

void set_py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* msg = PyUnicode_AsUTF8(s);
      if (msg) g_last_error = msg;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

PyObject* predict_module() {
  static PyObject* mod = nullptr;  // borrowed forever once imported
  if (!mod) {
    mod = PyImport_ImportModule("mxnet_tpu.predict");
    if (!mod) set_py_error();
  }
  return mod;
}

struct Pred {
  PyObject* obj;  // mxnet_tpu.predict.Predictor
  // per-handle shape storage: MXPredGetOutputShape returns a pointer that
  // must stay valid until the next call on the SAME handle (the reference
  // stores out_shapes_ per predictor, c_predict_api.cc)
  std::vector<mx_uint> shape;
};

struct NDList {
  std::vector<std::string> names;
  std::vector<std::string> blobs;        // raw fp32 bytes
  std::vector<std::vector<mx_uint>> shapes;
  std::vector<mx_uint> shape_buf;        // scratch for MXNDListGet returns
};

}  // namespace

MXNET_DLL const char* MXGetLastError() { return g_last_error.c_str(); }

// shared error channel for the other translation units in this .so
// (c_api_ndarray.cc routes its failures here so c_api.h's single accessor
// reports them)
void mxtpu_set_last_error(const std::string& msg) { g_last_error = msg; }

static int CreateImpl(const char* symbol_json_str, const void* param_bytes,
                      int param_size, mx_uint num_input_nodes,
                      const char** input_keys,
                      const mx_uint* input_shape_indptr,
                      const mx_uint* input_shape_data,
                      mx_uint num_output_nodes, const char** output_keys,
                      PredictorHandle* out) {
  Gil gil;
  PyObject* mod = predict_module();
  if (!mod) return -1;
  PyObject* names = PyList_New(num_input_nodes);
  PyObject* shapes = PyList_New(num_input_nodes);
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(input_keys[i]));
    mx_uint lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(shp, j - lo, PyLong_FromUnsignedLong(input_shape_data[j]));
    PyList_SetItem(shapes, i, shp);
  }
  PyObject* outputs;
  if (num_output_nodes) {
    outputs = PyList_New(num_output_nodes);
    for (mx_uint i = 0; i < num_output_nodes; ++i)
      PyList_SetItem(outputs, i, PyUnicode_FromString(output_keys[i]));
  } else {
    outputs = Py_None;
    Py_INCREF(outputs);
  }
  PyObject* blob = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* res = PyObject_CallMethod(mod, "_c_create", "sOOOO",
                                      symbol_json_str, blob, names, shapes,
                                      outputs);
  Py_DECREF(blob);
  Py_DECREF(names);
  Py_DECREF(shapes);
  Py_DECREF(outputs);
  if (!res) {
    set_py_error();
    return -1;
  }
  *out = new Pred{res, {}};
  return 0;
}

MXNET_DLL int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                           int param_size, int dev_type, int dev_id,
                           mx_uint num_input_nodes, const char** input_keys,
                           const mx_uint* input_shape_indptr,
                           const mx_uint* input_shape_data,
                           PredictorHandle* out) {
  (void)dev_type;
  (void)dev_id;  // device selection: the runtime context decides (TPU if present)
  return CreateImpl(symbol_json_str, param_bytes, param_size, num_input_nodes,
                    input_keys, input_shape_indptr, input_shape_data, 0,
                    nullptr, out);
}

MXNET_DLL int MXPredCreatePartialOut(const char* symbol_json_str,
                                     const void* param_bytes, int param_size,
                                     int dev_type, int dev_id,
                                     mx_uint num_input_nodes,
                                     const char** input_keys,
                                     const mx_uint* input_shape_indptr,
                                     const mx_uint* input_shape_data,
                                     mx_uint num_output_nodes,
                                     const char** output_keys,
                                     PredictorHandle* out) {
  (void)dev_type;
  (void)dev_id;
  // requested internal outputs become the predictor's output group
  // (reference: MXPredCreatePartialOut; Predictor(output_names=...))
  return CreateImpl(symbol_json_str, param_bytes, param_size, num_input_nodes,
                    input_keys, input_shape_indptr, input_shape_data,
                    num_output_nodes, output_keys, out);
}

MXNET_DLL int MXPredSetInput(PredictorHandle handle, const char* key,
                             const mx_float* data, mx_uint size) {
  Gil gil;
  Pred* p = static_cast<Pred*>(handle);
  PyObject* mod = predict_module();
  // flat fp32 buffer; python reshapes to the bound input's shape
  PyObject* blob = PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(data), static_cast<Py_ssize_t>(size) * 4);
  PyObject* res = PyObject_CallMethod(mod, "_c_set_input_flat", "OsO",
                                      p->obj, key, blob);
  Py_DECREF(blob);
  if (!res) {
    set_py_error();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXPredForward(PredictorHandle handle) {
  Gil gil;
  Pred* p = static_cast<Pred*>(handle);
  PyObject* res = PyObject_CallMethod(predict_module(), "_c_forward", "O", p->obj);
  if (!res) {
    set_py_error();
    return -1;
  }
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXPredPartialForward(PredictorHandle handle, int step,
                                   int* step_left) {
  // whole-graph XLA execution has no per-node stepping; one step completes all
  if (step_left) *step_left = 0;
  if (step > 0) return 0;
  return MXPredForward(handle);
}

MXNET_DLL int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                                   mx_uint** shape_data, mx_uint* shape_ndim) {
  Gil gil;
  Pred* p = static_cast<Pred*>(handle);
  PyObject* res = PyObject_CallMethod(predict_module(), "_c_output_shape",
                                      "OI", p->obj, index);
  if (!res) {
    set_py_error();
    return -1;
  }
  p->shape.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(res); ++i)
    p->shape.push_back(
        static_cast<mx_uint>(PyLong_AsUnsignedLong(PyList_GetItem(res, i))));
  Py_DECREF(res);
  *shape_data = p->shape.data();
  *shape_ndim = static_cast<mx_uint>(p->shape.size());
  return 0;
}

MXNET_DLL int MXPredGetOutput(PredictorHandle handle, mx_uint index,
                              mx_float* data, mx_uint size) {
  Gil gil;
  Pred* p = static_cast<Pred*>(handle);
  PyObject* res = PyObject_CallMethod(predict_module(), "_c_get_output", "OI",
                                      p->obj, index);
  if (!res) {
    set_py_error();
    return -1;
  }
  char* buf = nullptr;
  Py_ssize_t len = 0;
  if (PyBytes_AsStringAndSize(res, &buf, &len) != 0 ||
      static_cast<mx_uint>(len) != size * 4) {
    g_last_error = "output size mismatch";
    Py_DECREF(res);
    return -1;
  }
  std::memcpy(data, buf, len);
  Py_DECREF(res);
  return 0;
}

MXNET_DLL int MXPredFree(PredictorHandle handle) {
  Gil gil;
  Pred* p = static_cast<Pred*>(handle);
  Py_XDECREF(p->obj);
  delete p;
  return 0;
}

MXNET_DLL int MXNDListCreate(const char* nd_file_bytes, int nd_file_size,
                             NDListHandle* out, mx_uint* out_length) {
  Gil gil;
  PyObject* blob = PyBytes_FromStringAndSize(nd_file_bytes, nd_file_size);
  PyObject* res =
      PyObject_CallMethod(predict_module(), "_c_ndlist", "O", blob);
  Py_DECREF(blob);
  if (!res) {
    set_py_error();
    return -1;
  }
  PyObject *names, *blobs, *shapes;
  if (!PyArg_ParseTuple(res, "OOO", &names, &blobs, &shapes)) {
    set_py_error();
    Py_DECREF(res);
    return -1;
  }
  NDList* list = new NDList();
  for (Py_ssize_t i = 0; i < PyList_Size(names); ++i) {
    const char* key = PyUnicode_AsUTF8(PyList_GetItem(names, i));
    list->names.push_back(key ? key : "");
    char* b;
    Py_ssize_t n;
    PyBytes_AsStringAndSize(PyList_GetItem(blobs, i), &b, &n);
    list->blobs.emplace_back(b, n);
    PyObject* shp = PyList_GetItem(shapes, i);
    std::vector<mx_uint> sv;
    for (Py_ssize_t j = 0; j < PyList_Size(shp); ++j)
      sv.push_back(
          static_cast<mx_uint>(PyLong_AsUnsignedLong(PyList_GetItem(shp, j))));
    list->shapes.push_back(std::move(sv));
  }
  Py_DECREF(res);
  *out = list;
  *out_length = static_cast<mx_uint>(list->names.size());
  return 0;
}

MXNET_DLL int MXNDListGet(NDListHandle handle, mx_uint index, const char** out_key,
                          const mx_float** out_data, const mx_uint** out_shape,
                          mx_uint* out_ndim) {
  NDList* list = static_cast<NDList*>(handle);
  if (index >= list->names.size()) {
    g_last_error = "NDList index out of range";
    return -1;
  }
  *out_key = list->names[index].c_str();
  *out_data = reinterpret_cast<const mx_float*>(list->blobs[index].data());
  *out_shape = list->shapes[index].data();
  *out_ndim = static_cast<mx_uint>(list->shapes[index].size());
  return 0;
}

MXNET_DLL int MXNDListFree(NDListHandle handle) {
  delete static_cast<NDList*>(handle);
  return 0;
}
