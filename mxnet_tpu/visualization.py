"""Network visualization: ``print_summary`` and ``plot_network``.

Same user-facing API as the reference (python/mxnet/visualization.py), built
differently: both functions render from one shared graph view
(:func:`_graph_view`) computed off the Symbol's own node objects, and
parameter counts come from the ACTUAL inferred shapes of each node's
weight-like arguments — exact for every op (grouped convolutions, no-bias
layers, custom ops with learnable inputs), where per-op arithmetic formulas
under- or over-count.
"""
from __future__ import annotations

from .symbol import Symbol, _topo_order

__all__ = ["print_summary", "plot_network"]

# variable-name suffixes that mean "learnable/auxiliary tensor, not data"
# (states and data-like inputs are NOT here: their shapes are batch-sized
# and must not count as parameters)
_WEIGHT_SUFFIXES = (
    "_weight", "_bias", "_gamma", "_beta", "_moving_mean", "_moving_var",
)


def _is_weight_name(name):
    return name.endswith(_WEIGHT_SUFFIXES)


class _NodeInfo:
    __slots__ = ("name", "op", "attrs", "preds", "out_shape", "param_count",
                 "is_output")

    def __init__(self, name, op, attrs):
        self.name = name
        self.op = op
        self.attrs = attrs
        self.preds = []        # visible predecessor names (non-weight)
        self.out_shape = None  # first-output shape minus batch, or None
        self.param_count = 0
        self.is_output = False


def _graph_view(symbol, shape=None):
    """List of _NodeInfo in topological order: compute nodes plus any
    variables that appear as graph outputs or data inputs.

    With ``shape`` (dict of input name -> shape), output shapes are inferred
    through ``get_internals`` and parameter counts are the summed sizes of
    each node's weight-like variable inputs — read from the inferred ARG
    shapes, so they are exact whatever the op's internal arithmetic is.
    """
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    shape_of_output = {}
    shape_of_arg = {}
    if shape is not None:
        internals = symbol.get_internals()
        arg_shapes, out_shapes, _ = internals.infer_shape(**shape)
        if out_shapes is None:
            raise ValueError("Input shape is incomplete")
        shape_of_output = dict(zip(internals.list_outputs(), out_shapes))
        shape_of_arg = dict(zip(internals.list_arguments(), arg_shapes or []))

    order = _topo_order(symbol._entries)
    output_ids = {id(n) for n, _ in symbol._entries}
    infos = []
    for node in order:
        # weight-like variables fold into their consumer's param count;
        # every other variable (data, labels, states) is a visible node
        if node.is_variable and not (
                id(node) in output_ids or not _is_weight_name(node.name)):
            continue
        info = _NodeInfo(node.name, node.op or "null", dict(node.attrs or {}))
        info.is_output = id(node) in output_ids
        if not node.is_variable:
            for inp, _k in node.inputs:
                if inp.is_variable:
                    if _is_weight_name(inp.name):
                        info.param_count += _size_of(
                            shape_of_arg.get(inp.name))
                    else:
                        info.preds.append(inp.name)
                else:
                    info.preds.append(inp.name)
            key = node.name + "_output"
        else:
            key = node.name
        s = shape_of_output.get(key)
        info.out_shape = tuple(s[1:]) if s else None
        infos.append(info)
    return infos


def _size_of(shape):
    if not shape:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n


# ------------------------------------------------------------------ summary
def print_summary(symbol, shape=None, line_length=120,
                  positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a layer table: name(type), output shape, #params, connections.

    ``positions`` are column right-edges, as fractions of ``line_length``
    (or absolute columns if > 1) — the reference's signature.
    """
    cols = [int(line_length * p) if p <= 1 else int(p) for p in positions]
    infos = _graph_view(symbol, shape)

    def emit(fields):
        line = []
        start = 0
        for text, edge in zip(fields, cols):
            cell = str(text)[: edge - start]
            line.append(cell + " " * (edge - start - len(cell)))
            start = edge
        print("".join(line))

    rule, double = "_" * line_length, "=" * line_length
    print(rule)
    emit(["Layer (type)", "Output Shape", "Param #", "Previous Layer"])
    print(double)
    total = 0
    for i, info in enumerate(infos):
        out = "x".join(str(d) for d in info.out_shape) if info.out_shape else ""
        first = info.preds[0] if info.preds else ""
        emit(["%s(%s)" % (info.name, info.op), out, info.param_count, first])
        for extra in info.preds[1:]:
            emit(["", "", "", extra])
        total += info.param_count
        print(double if i == len(infos) - 1 else rule)
    print("Total params: %s" % total)
    print(rule)


# ------------------------------------------------------------------ plotting
# op -> (palette color index, label function). Anything unlisted gets the
# default color with its op name as the label.
def _label_conv(a):
    k = a.get("kernel", "")
    s = a.get("stride", "") or "(1,1)"
    return "Convolution\n%s/%s, %s" % (_fmt_shape(k), _fmt_shape(s),
                                       a.get("num_filter", ""))


def _label_pool(a):
    return "Pooling\n%s, %s/%s" % (
        a.get("pool_type", "max"), _fmt_shape(a.get("kernel", "")),
        _fmt_shape(a.get("stride", "") or "(1,1)"))


def _fmt_shape(text):
    from .base import parse_shape

    try:
        dims = parse_shape(str(text))
    except Exception:  # noqa: BLE001 — attr not shape-like: show verbatim
        return str(text)
    return "x".join(str(d) for d in dims or ())


_PALETTE = ("#8dd3c7", "#fb8072", "#ffffb3", "#bebada", "#80b1d3",
            "#fdb462", "#b3de69", "#fccde5")

_STYLE = {
    "null": (0, None),
    "Convolution": (1, _label_conv),
    "Deconvolution": (1, _label_conv),
    "FullyConnected": (1, lambda a: "FullyConnected\n%s" % a.get("num_hidden", "")),
    "Activation": (2, lambda a: "Activation\n%s" % a.get("act_type", "")),
    "LeakyReLU": (2, lambda a: "LeakyReLU\n%s" % a.get("act_type", "")),
    "BatchNorm": (3, None),
    "Pooling": (4, _label_pool),
    "Concat": (5, None),
    "Flatten": (5, None),
    "Reshape": (5, None),
    "Softmax": (6, None),
    "SoftmaxOutput": (6, None),
    "SoftmaxActivation": (6, None),
}
_DEFAULT_STYLE = (7, None)


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Graphviz digraph of the network (edges drawn data-flow 'back' style,
    shape labels on edges when ``shape`` is given). Requires graphviz."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("Draw network requires graphviz library")
    # weight variables are folded away by the default view; the
    # hide_weights=False variant re-includes them (one shape inference
    # either way)
    infos = (_graph_view_all_vars(symbol, shape) if not hide_weights
             else _graph_view(symbol, shape))
    known = {i.name for i in infos}

    base_attrs = {"shape": "box", "fixedsize": "true", "width": "1.3",
                  "height": "0.8034", "style": "filled"}
    if node_attrs:
        base_attrs.update(node_attrs)
    dot = Digraph(name=title)

    shapes_by_name = {i.name: i.out_shape for i in infos}
    for info in infos:
        color_i, labeler = _STYLE.get(info.op, _DEFAULT_STYLE)
        attrs = {"shape": "box", "fixedsize": "false", "style": "filled",
                 "fillcolor": _PALETTE[color_i]}
        if info.op == "null":
            attrs["shape"] = "oval"
            label = info.name
        else:
            label = labeler(info.attrs) if labeler else info.op
        dot.node(name=info.name, label=label, **attrs)
    for info in infos:
        if info.op == "null":
            continue
        for pred in info.preds:
            if pred not in known:
                continue
            edge_attrs = {"dir": "back", "arrowtail": "open"}
            ps = shapes_by_name.get(pred)
            if shape is not None and ps:
                edge_attrs["label"] = "x".join(str(d) for d in ps)
            dot.edge(tail_name=info.name, head_name=pred, **edge_attrs)
    return dot


def _graph_view_all_vars(symbol, shape):
    """Variant of _graph_view that keeps weight variables visible (used by
    plot_network(hide_weights=False)) and routes them into preds."""
    infos = _graph_view(symbol, shape)
    by_name = {i.name: i for i in infos}
    order = _topo_order(symbol._entries)
    out = []
    for node in order:
        if node.is_variable and node.name not in by_name:
            vi = _NodeInfo(node.name, "null", dict(node.attrs or {}))
            out.append(vi)
        elif node.name in by_name:
            info = by_name[node.name]
            if not node.is_variable:
                info.preds = [inp.name for inp, _ in node.inputs]
            out.append(info)
    return out
