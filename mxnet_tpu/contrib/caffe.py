"""Caffe runtime layers (reference: plugin/caffe/caffe_op-inl.h,
caffe_loss-inl.h — CaffeOp/CaffeLoss let a network embed layers written
as caffe prototxt and run them INSIDE the framework, weights included).

The reference plugin links the actual caffe library and calls its
Forward/Backward. No caffe exists in this environment (or on TPU hosts),
so the TPU-native equivalent runs the layer through the caffe-converter's
layer mapping instead: the prototxt snippet expands AT SYMBOL-BUILD TIME
into the equivalent native subgraph, its weights become ordinary named
arguments (initialized/updated/checkpointed like any other), and backward
comes from autodiff. Semantics match the converter's (the same mapping
that is numerically validated against numpy in
tests/test_caffe_converter.py); anything the converter rejects, CaffeOp
rejects too — loudly.

    conv = mx.contrib.caffe.CaffeOp(
        data,
        prototxt='layer { type: "Convolution" '
                 'convolution_param { num_output: 8 kernel_size: 3 } }',
        name="c1")

`prototxt` may contain several layers; they chain in order (bottoms
default to the previous layer's output, like the plugin feeding blobs
through). `CaffeLoss` is CaffeOp whose final layer is a loss head.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["CaffeOp", "CaffeLoss"]


def _converter():
    try:
        from tools import caffe_converter
    except ImportError as e:
        raise MXNetError(
            "CaffeOp needs tools/caffe_converter.py (repo checkout on "
            "sys.path); it is a repo tool, not part of the installed "
            "package: %s" % (e,))
    return caffe_converter


def CaffeOp(*data, prototxt="layer{}", name=None):
    """Expand a caffe prototxt snippet into the equivalent native subgraph.

    Parameters
    ----------
    *data : Symbol
        Inputs, bound to the first layer's bottoms positionally (the
        plugin's ``num_data`` blobs).
    prototxt : str
        One or more ``layer { ... }`` blocks (deploy-style). TRAIN/TEST
        data layers are not allowed — inputs come from ``*data``.
    name : str
        Prefix for the expanded layers' parameter names (so two CaffeOps
        with the same prototxt do not collide). Defaults to the layer
        names inside the prototxt.
    """
    import mxnet_tpu as mx

    if not data:
        raise MXNetError("CaffeOp needs at least one input symbol")
    try:
        return _converter().expand_layers(mx, prototxt, list(data),
                                          name_prefix=name)
    except ValueError as e:
        raise MXNetError("CaffeOp: %s" % (e,))


def CaffeLoss(*data, prototxt="layer{}", name=None, grad_scale=1.0):
    """CaffeOp whose snippet ends in a loss head (reference
    caffe_loss-inl.h). ``grad_scale`` matches the plugin's parameter; the
    mapped loss ops take it via their own ``grad_scale`` where supported.
    """
    if grad_scale != 1.0:
        raise MXNetError(
            "CaffeLoss grad_scale: set grad_scale on the mapped loss op "
            "via the prototxt's loss_weight instead (converter mapping)")
    return CaffeOp(*data, prototxt=prototxt, name=name)
