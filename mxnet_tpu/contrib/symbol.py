"""contrib symbol namespace: expose _contrib_* ops under their short names
(reference: python/mxnet/contrib/symbol.py generated from the registry)."""
import sys

from .. import symbol as _sym
from ..ops.registry import list_ops

_mod = sys.modules[__name__]
for _name in list_ops():
    if _name.startswith("_contrib_"):
        setattr(_mod, _name[len("_contrib_"):], getattr(_sym, _name))
