"""Imperative autograd (reference: python/mxnet/contrib/autograd.py:82-159 over
src/ndarray/autograd.{h,cc} — records imperative FCompute calls into an NNVM
graph and replays it with an internal executor).

TPU design: recording happens at the NDArray dispatch layer — inside a
``train_section`` every ``imperative_invoke`` appends (op, attrs, inputs,
outputs) to a tape. ``backward``/``compute_gradient`` replays the tape as a
pure jax function of the marked variables and differentiates it with
``jax.vjp`` — the replay is jit-compiled, so gradient computation runs as one
XLA program rather than op-by-op.
"""
from __future__ import annotations

import contextlib
import functools

from ..base import MXNetError

__all__ = [
    "set_is_training", "train_section", "test_section",
    "mark_variables", "backward", "compute_gradient", "grad_and_loss", "grad",
]

_RECORDING = [False]  # thread-confined: the imperative tape records on the user's training thread only (reference semantics: autograd state is per-thread)
_TAPE = []  # thread-confined: see _RECORDING — (op_name, attrs, [input NDArray ids], [output NDArrays])
_MARKED = {}  # id(NDArray) -> (NDArray, grad NDArray, grad_req)


def is_recording():
    return _RECORDING[0]


def record_op(op_name, attrs, inputs, outputs):
    """Called by ndarray.imperative_invoke while a train_section is active."""
    if _RECORDING[0]:
        _TAPE.append((op_name, dict(attrs), list(inputs), list(outputs)))


def set_is_training(is_train):
    """(reference: contrib/autograd.py set_is_training)"""
    from .. import ndarray as nd

    prev = nd._TRAIN_MODE[0]
    nd._TRAIN_MODE[0] = bool(is_train)
    _RECORDING[0] = bool(is_train)
    return prev


@contextlib.contextmanager
def train_section():
    """(reference: contrib/autograd.py train_section with-scope)"""
    prev = set_is_training(True)
    try:
        yield
    finally:
        set_is_training(prev)


@contextlib.contextmanager
def test_section():
    """(reference: contrib/autograd.py test_section)"""
    prev = set_is_training(False)
    try:
        yield
    finally:
        set_is_training(prev)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Mark NDArrays as variables to compute gradients for
    (reference: contrib/autograd.py mark_variables → MXAutogradMarkVariables)."""
    from ..ndarray import NDArray

    if isinstance(variables, NDArray):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad, req in zip(variables, gradients, grad_reqs):
        _MARKED[id(var)] = (var, grad, req)


def _replay_and_grad(heads, head_grads):
    """Differentiate the tape w.r.t. marked variables via jax.vjp."""
    import jax
    import jax.numpy as jnp

    from .. import random as _random
    from ..ndarray import NDArray
    from ..ops.registry import OpContext, get_op

    tape = list(_TAPE)
    marked = {k: v for k, v in _MARKED.items()}
    if not marked:
        raise MXNetError("no variables marked; call mark_variables first")
    # identify which tensors feed the tape: map NDArray id -> value
    var_ids = list(marked.keys())
    var_arrays = [marked[i][0] for i in var_ids]

    # leaf values captured at replay time for non-marked inputs
    def run(var_vals):
        env = {i: v for i, v in zip(var_ids, var_vals)}
        for op_name, attrs, in_ids_vals, outputs in tape:
            op = get_op(op_name)
            args = []
            for iid, captured in in_ids_vals:
                args.append(env.get(iid, captured))
            key = None
            if op.stochastic:
                key = jax.random.PRNGKey(0)
            octx = OpContext(is_train=True, rng=key)
            n_args = len(op.arg_names(attrs))
            outs, _ = op.forward(octx, attrs, args[:n_args], args[n_args:])
            for o_nd, o_val in zip(outputs, outs):
                env[id(o_nd)] = o_val
        return [env[id(h)] for h in heads]

    var_vals = [v.data for v in var_arrays]
    outs, vjp_fn = jax.vjp(run, var_vals)
    if head_grads is None:
        seeds = [jnp.ones_like(o) for o in outs]
    else:
        seeds = [g.data for g in head_grads]
    grads = vjp_fn(seeds)[0]
    for i, g in zip(var_ids, grads):
        var, gout, req = marked[i]
        if req == "add":
            gout._set_data(gout.data + g)
        elif req != "null":
            gout._set_data(g)


def backward(outputs, out_grads=None, retain_graph=False):
    """(reference: contrib/autograd.py backward → MXAutogradBackward)"""
    from ..ndarray import NDArray

    if isinstance(outputs, NDArray):
        outputs = [outputs]
    _replay_and_grad(outputs, out_grads)
    if not retain_graph:
        _TAPE.clear()


def compute_gradient(outputs):
    """(reference: contrib/autograd.py compute_gradient)"""
    backward(outputs)


def grad_and_loss(func, argnum=None):
    """Return a function computing both gradient and loss
    (reference: contrib/autograd.py grad_and_loss)."""
    import jax

    @functools.wraps(func)
    def wrapped(*args):
        from .. import ndarray as nd
        from ..ndarray import NDArray

        variables = args
        if argnum is not None:
            argnum_ = argnum if isinstance(argnum, list) else [argnum]
            variables = [args[i] for i in argnum_]
        for x in variables:
            assert isinstance(x, NDArray), "type of autograd input should NDArray."
        grads = [nd.zeros(x.shape, dtype=x.dtype) for x in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        backward([outputs] if isinstance(outputs, NDArray) else outputs)
        return grads, outputs

    return wrapped


def grad(func, argnum=None):
    """(reference: contrib/autograd.py grad)"""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]

    return wrapped
