"""Contrib namespace (reference: python/mxnet/contrib/__init__.py — autograd,
contrib ops)."""
from . import autograd  # noqa: F401
