"""Contrib namespace (reference: python/mxnet/contrib/__init__.py — autograd,
contrib ops)."""
from . import autograd  # noqa: F401
from . import ndarray  # noqa: F401
from . import symbol  # noqa: F401
from . import caffe
