"""contrib ndarray namespace: expose _contrib_* ops under their short names
(reference: python/mxnet/contrib/ndarray.py generated from the registry)."""
import sys

from .. import ndarray as _nd
from ..ops.registry import list_ops

_mod = sys.modules[__name__]
for _name in list_ops():
    if _name.startswith("_contrib_"):
        setattr(_mod, _name[len("_contrib_"):], getattr(_nd, _name))
