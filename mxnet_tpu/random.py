"""Random state (reference: python/mxnet/random.py, src/resource.cc ResourceRandom).

The reference seeds a per-device RNG resource (`ResourceManagerImpl::ResourceRandom`,
src/resource.cc:158) consumed by sampling ops. On TPU randomness is functional:
jax threefry keys. This module owns the process-global key chain — ``mx.random.seed``
resets it; every imperative sampling call and every stochastic executor forward
splits a fresh subkey from it, which preserves the reference's "seed once,
reproducible stream" contract while staying jit-friendly (keys are explicit
operands, never hidden state inside a compiled graph).
"""
from __future__ import annotations

import threading

__all__ = ["seed", "next_key", "uniform", "normal", "randint"]

_state = threading.local()
_DEFAULT_SEED = 0


def _key():
    import jax

    if getattr(_state, "key", None) is None:
        _state.key = jax.random.PRNGKey(_DEFAULT_SEED)
    return _state.key


def seed(seed_state):
    """Seed the global random number generators
    (reference: python/mxnet/random.py:45 mx.random.seed)."""
    import jax

    if not isinstance(seed_state, int):
        raise ValueError("sd must be int")
    _state.key = jax.random.PRNGKey(seed_state)


def next_key():
    """Split and return a fresh subkey from the global chain."""
    import jax

    k = _key()
    _state.key, sub = jax.random.split(k)
    return sub


# Imperative samplers (mx.random.uniform / normal); also exposed as nd.random_*.
def uniform(low=0, high=1, shape=None, dtype=None, ctx=None, out=None):
    from . import ndarray as nd

    return nd.random_uniform(low=low, high=high, shape=shape, dtype=dtype, ctx=ctx, out=out)


def normal(loc=0, scale=1, shape=None, dtype=None, ctx=None, out=None):
    from . import ndarray as nd

    return nd.random_normal(loc=loc, scale=scale, shape=shape, dtype=dtype, ctx=ctx, out=out)


def randint(low, high, shape=None, dtype="int32", ctx=None, out=None):
    from . import ndarray as nd

    return nd.random_randint(low=low, high=high, shape=shape, dtype=dtype, ctx=ctx, out=out)
