"""Torch interop (reference: python/mxnet/torch.py + plugin/torch — TorchModule/
TorchCriterion ops bridging Torch tensors/modules into the NDArray runtime).

The reference embeds LuaJIT Torch; here the bridge targets PyTorch (present in
the environment, CPU build). Transfers stage through host numpy copies — the
device buffer is fetched, so round-trips are not free:

* ``to_torch(nd_arr)`` / ``from_torch(tensor)`` — NDArray ↔ torch.Tensor;
* ``function(torch_fn)`` — wrap any torch callable into an NDArray function
  (the analog of the generated ``mx.th.*`` functions);
* ``TorchModule`` — run a ``torch.nn.Module`` forward as an NDArray op, the
  analog of plugin/torch's TorchModule operator. Backward runs through
  torch.autograd, so a torch module can be used as a fixed feature extractor
  or fine-tuned with gradients flowing back into MXNet arrays.
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd

__all__ = ["to_torch", "from_torch", "function", "TorchModule"]


def _torch():
    try:
        import torch
    except ImportError as e:  # pragma: no cover
        raise ImportError("torch is not available in this environment") from e
    return torch


def to_torch(arr):
    """NDArray → torch.Tensor (host copy; the TPU buffer is fetched)."""
    torch = _torch()
    # copy: asnumpy() may return a read-only view of the device buffer
    return torch.from_numpy(np.array(arr.asnumpy()))


def from_torch(tensor, ctx=None):
    """torch.Tensor → NDArray on ctx (default current context)."""
    return nd.array(tensor.detach().cpu().numpy(), ctx=ctx)


def function(torch_fn):
    """Wrap a torch callable into an NDArray→NDArray function."""

    def wrapped(*args, **kwargs):
        targs = [to_torch(a) if isinstance(a, nd.NDArray) else a for a in args]
        tkwargs = {k: to_torch(v) if isinstance(v, nd.NDArray) else v
                   for k, v in kwargs.items()}
        out = torch_fn(*targs, **tkwargs)
        torch = _torch()
        if isinstance(out, (list, tuple)):
            return [from_torch(o) if isinstance(o, torch.Tensor) else o for o in out]
        return from_torch(out) if isinstance(out, torch.Tensor) else out

    wrapped.__name__ = getattr(torch_fn, "__name__", "torch_fn")
    return wrapped


class TorchModule:
    """Run a torch.nn.Module on NDArrays with optional backward.

    forward(x) -> NDArray; backward(out_grad) -> input gradient NDArray.
    Parameters stay inside the torch module; step(lr) applies a plain SGD
    update to them (enough for the plugin's fine-tuning use case).
    """

    def __init__(self, module):
        self.module = module
        self._last = None

    def forward(self, x, is_train=False):
        torch = _torch()
        tx = to_torch(x)
        if is_train:
            tx = tx.clone().requires_grad_(True)
            out = self.module(tx)
            self._last = (tx, out)
            return from_torch(out)
        self._last = None  # an eval forward invalidates any pending backward
        with torch.no_grad():
            return from_torch(self.module(tx))

    def backward(self, out_grad):
        if self._last is None:
            raise RuntimeError("backward before forward(is_train=True)")
        tx, out = self._last
        out.backward(to_torch(out_grad))
        self._last = None
        return from_torch(tx.grad)

    def step(self, lr):
        torch = _torch()
        with torch.no_grad():
            for p in self.module.parameters():
                if p.grad is not None:
                    p -= lr * p.grad
                    p.grad.zero_()
