"""Deterministic in-process fault injection.

The reference's fault-tolerance story was validated with out-of-process chaos
(kill -9 a ps-lite server, tests/nightly/dist_sync_kvstore.py relaunch runs).
That is non-deterministic and needs a cluster; this module instead threads
named *injection points* through the runtime's failure-prone seams so every
recovery path is testable in a single process, byte-for-byte reproducibly:

* ``checkpoint_write`` — inside the atomic checkpoint writer
  (``crash_after_bytes=N`` kills the write mid-stream, leaving a torn temp
  file exactly N bytes long).
* ``checkpoint_between_files`` — after the symbol json, before the params
  blob (the classic half-written two-file checkpoint).
* ``kv_push`` / ``kv_pull`` — the dist KVStore RPCs (``drop=1`` fails the
  attempt, ``delay_ms=N`` stalls it) to exercise retry/backoff.
* ``server_updater`` — the PS server's optimizer application (``raise=1``)
  to exercise the server's failure counting and threshold.
* ``nan`` — the health guard's sentinel (guard.py): poisons the next step's
  gradients with NaN (``target=loss`` flags the loss scalar instead), so
  skip/rollback/abort are testable without real divergence.
* ``stall`` — the device-feed transfer stage (io.DeviceFeedIter._stage):
  ``delay_ms=N`` sleeps it past the guard's watchdog deadline.
* ``bad_record`` — ImageRecordIter's per-record decode: makes the record
  undecodable to exercise the quarantine/budget path
  (``MXNET_IO_MAX_BAD_RECORDS``).
* ``oom`` — the executor boundary (compileobs.oom_guard): a firing rule
  synthesizes a ``RESOURCE_EXHAUSTED`` failure there, exercising the OOM
  forensics dump (top live allocations + program table) without needing a
  real device out-of-memory. Spec ``oom:`` alone fires every step;
  ``oom:after=K,times=1`` dies once at step K.
* ``kill_worker`` — the fit loop's per-batch seam (base_module.py): SIGKILLs
  this process — no exit hooks, no final flush, the closest in-process
  analog of a machine loss. The optional ``rank=N`` arg targets one worker
  of a launched cluster (every process inherits the same
  ``MXNET_FAULT_SPEC``); combine with ``after=K`` to die mid-epoch at batch
  K. Drives the elastic kill→reconfigure→rejoin cycle
  (docs/distributed.md §elasticity, tools/launch.py --elastic).
* ``kill_server`` — the PS server's update-apply seam (kvstore_server.py):
  SIGKILLs a *server* process the same way, driving the server-HA
  promote→reconfigure path (docs/distributed.md §server-HA). The optional
  ``server_id=N`` arg targets one server of a launched cluster; combine
  with ``after=K`` to die after K applied updates (mid-epoch).
* ``dispatch_error`` — the serving engine's prefill/decode dispatch seam
  (serving/engine.py): ``raise=1`` escapes the step, aborting the engine —
  the supervisor-restart trigger for the serving chaos e2e
  (docs/fault_tolerance.md §serving).
* ``kv_oom`` — the KV block allocator (serving/kv_cache.py): a firing rule
  synthesizes a classified ``KVCacheOOM`` (bumping the alloc-failure
  counters) without actually draining the pool, exercising preemption and
  admission-failure paths at any pool size.
* ``slow_step`` — the serving engine step's entry (``delay_ms=N`` stalls
  the whole step): trips request deadlines and SLO burn without faking
  clocks.

Faults are described by a spec string, either in ``MXNET_FAULT_SPEC`` (so a
whole process tree — e.g. launched PS servers — inherits them) or pushed
programmatically with :func:`inject`::

    MXNET_FAULT_SPEC="checkpoint_write:crash_after_bytes=128;kv_push:drop=1,times=2"

Grammar: ``point:arg=val[,arg=val...]`` joined by ``;``. Common args:

* ``times=N``  — fire at most N times (default: unlimited).
* ``after=N``  — let the first N hits through untouched.
* ``raise=1``  — raise :class:`InjectedFault` (an ``MXNetError``).
* ``crash=1``  — raise :class:`InjectedCrash` (a ``BaseException``: ordinary
  ``except Exception`` recovery code cannot swallow it, so it behaves like a
  real ``kill -9`` for everything except the test harness that expects it).
* ``delay_ms=N`` — sleep before returning (transient-stall simulation).
* ``drop=1`` / ``crash_after_bytes=N`` — interpreted by the call site.
"""
from __future__ import annotations

import os
import threading
import time
from contextlib import contextmanager

# telemetry is imported at module top, NOT lazily at the firing sites:
# kill_server/consume fire on a PS server's conn-handler / checkpoint-
# writer threads while the server's main thread never leaves ``import
# mxnet_tpu`` — a package-relative import there would deadlock on the
# import lock (kvstore_server.py's import-lock invariant)
from . import telemetry
from .base import MXNetError, env_str as _env_str

__all__ = ["InjectedFault", "InjectedCrash", "POINTS", "hit", "inject",
           "reset", "crash_after_bytes", "kill_worker", "kill_server"]

#: Every registered injection point (the module docstring is the prose
#: catalog; tests pin this list so a new seam cannot ship undocumented).
#: A spec naming a point outside this list arms a rule nothing consults.
POINTS = (
    "checkpoint_write",
    "checkpoint_between_files",
    "kv_push",
    "kv_pull",
    "server_updater",
    "nan",
    "stall",
    "bad_record",
    "oom",
    "kill_worker",
    "kill_server",
    # serving resilience seams (docs/fault_tolerance.md §serving)
    "dispatch_error",
    "kv_oom",
    "slow_step",
)


class InjectedFault(MXNetError):
    """A recoverable failure raised by an injection point."""


class InjectedCrash(BaseException):
    """A simulated hard crash (power loss / kill -9).

    Deliberately NOT an ``Exception``: recovery paths that catch ``Exception``
    must not be able to "handle" a crash — only the test that injected it
    catches this, the same way a supervisor observes a dead process.
    """


_lock = threading.RLock()
_rules = None  # lazily parsed from MXNET_FAULT_SPEC
_spec_stack = []  # programmatic overrides from inject()


def _parse(spec):
    rules = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        point, _, argstr = part.partition(":")
        args = {}
        for kv in argstr.split(","):
            kv = kv.strip()
            if not kv:
                continue
            k, _, v = kv.partition("=")
            args[k.strip()] = v.strip()
        rules.append({"point": point.strip(), "args": args,
                      "hits": 0, "fired": 0})
    return rules


def _active_rules():
    global _rules
    with _lock:
        if _spec_stack:
            return _spec_stack[-1]
        if _rules is None:
            _rules = _parse(_env_str("MXNET_FAULT_SPEC", ""))
        return _rules


def reset():
    """Forget parsed env rules and their counters (re-reads the env on next
    hit). Programmatic injections from :func:`inject` are unaffected."""
    global _rules
    with _lock:
        _rules = None


@contextmanager
def inject(spec):
    """Activate ``spec`` for the dynamic extent of the block (test harness
    entry point). Nested injects stack; the innermost wins wholesale."""
    rules = _parse(spec)
    with _lock:
        _spec_stack.append(rules)
    try:
        yield rules
    finally:
        with _lock:
            _spec_stack.remove(rules)


def _arm(name, require=None, match=None):
    """Shared after/times gating (caller holds ``_lock``): find ``name``'s
    rule (with arg ``require``, when given; with every ``match`` item equal
    to the rule's same-named arg when that arg is present), count the hit,
    and return the rule if it should fire — NOT yet marked fired, so the
    caller decides whether firing happens now (:func:`hit`) or when a
    stream wrapper later exhausts its budget (:func:`crash_after_bytes` →
    :func:`consume`)."""
    for r in _active_rules():
        if r["point"] != name:
            continue
        if require is not None and require not in r["args"]:
            continue
        if match is not None and any(
                k in r["args"] and r["args"][k] != str(v)
                for k, v in match.items()):
            # the rule targets a different value (e.g. rank=1 on rank 0):
            # not this caller's rule — and not a counted hit either, so the
            # target's after=/times= budget is untouched
            continue
        args = r["args"]
        r["hits"] += 1
        if r["hits"] <= int(args.get("after", 0)):
            return None
        times = args.get("times")
        if times is not None and r["fired"] >= int(times):
            return None
        return r
    return None


def hit(name):
    """Consult the active spec at injection point ``name``.

    Returns ``None`` when no rule fires. Otherwise applies ``delay_ms`` /
    ``raise`` / ``crash`` itself and returns the rule's arg dict so the call
    site can interpret point-specific args (``drop``, ``crash_after_bytes``).
    """
    with _lock:
        rule = _arm(name)
        if rule is None:
            return None
        rule["fired"] += 1
        args = rule["args"]
    # always-on counter (telemetry.py module doc): robustness tests assert
    # injected faults were actually exercised via the metrics dump
    telemetry.counter("fault.injections", point=name).inc()
    delay = args.get("delay_ms")
    if delay:
        time.sleep(int(delay) / 1000.0)
    if args.get("crash") not in (None, "0"):
        raise InjectedCrash("injected crash at %s" % name)
    if args.get("raise") not in (None, "0"):
        raise InjectedFault("injected fault at %s" % name)
    return args


def crash_after_bytes(name):
    """Byte budget for a write-stream injection point, or ``None``.

    Each call counts as one hit (one stream opened at the point), so
    ``after=N`` lets the first N streams through untouched and ``times=N``
    stops arming budgets after N crashes. Does NOT record a firing — the
    stream wrapper that enforces the budget calls :func:`consume` when the
    budget is actually exhausted.
    """
    with _lock:
        rule = _arm(name, require="crash_after_bytes")
        if rule is None:
            return None
        return int(rule["args"]["crash_after_bytes"])


def kill_worker(rank=None):
    """Injection point for elastic training tests: when a ``kill_worker``
    rule fires — and its ``rank=`` arg (if any) matches ``rank`` — SIGKILL
    this process. No exit hooks run and nothing is flushed: everything
    except the supervising launcher sees a machine loss. Called from the
    fit loop once per batch (``after=K`` dies mid-epoch at batch K)."""
    with _lock:
        rule = _arm("kill_worker",
                    match=None if rank is None else {"rank": int(rank)})
        if rule is None:
            return
        rule["fired"] += 1
    telemetry.counter("fault.injections", point="kill_worker").inc()
    import signal

    os.kill(os.getpid(), signal.SIGKILL)


def kill_server(server_id=None):
    """Injection point for server-HA tests, mirroring :func:`kill_worker`:
    when a ``kill_server`` rule fires — and its ``server_id=`` arg (if
    any) matches ``server_id`` — SIGKILL this *server* process. Called
    from the PS server's update-apply path once per applied update
    (``after=K`` dies mid-epoch after K updates), so the loss lands while
    optimizer slots and replication are in flight — the worst case the
    promote→reconfigure path must survive."""
    with _lock:
        rule = _arm("kill_server",
                    match=None if server_id is None
                    else {"server_id": int(server_id)})
        if rule is None:
            return
        rule["fired"] += 1
    telemetry.counter("fault.injections", point="kill_server").inc()
    import signal

    os.kill(os.getpid(), signal.SIGKILL)


def consume(name):
    """Record a firing for ``name`` without applying any action (used by
    stream wrappers that enforce ``crash_after_bytes`` themselves; the hit
    was already counted when :func:`crash_after_bytes` armed the budget).
    Credits the rule that CARRIES a ``crash_after_bytes`` arg — the one
    :func:`crash_after_bytes` armed — so a sibling rule on the same point
    (e.g. a ``raise=1``) doesn't absorb the firing and leave the armed
    rule's ``times=`` budget unspent, crashing forever."""
    with _lock:
        for r in _active_rules():
            if r["point"] == name and "crash_after_bytes" in r["args"]:
                r["fired"] += 1
                break
        else:
            return
    telemetry.counter("fault.injections", point=name).inc()
