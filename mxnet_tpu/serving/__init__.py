"""LLM serving engine — paged KV-cache attention + continuous batching.

The "millions of users" workload the substrate exists for (ROADMAP #1): a
standing inference engine over the Transformer-LM zoo model. Sequences share
one device's KV memory through a block-paged ragged cache (per "Ragged Paged
Attention", PAPERS.md) and a continuous-batching scheduler mixes prefill and
decode into padded shape buckets, so the decode step compiles once per
bucket (provable via compileobs) and thousands of variable-length streams
multiplex one set of weights.

Layers:

* :mod:`.kv_cache`  — the device block pool + host allocator
  (``serving.kv_blocks_*`` accounting).
* :mod:`.model`     — the functional Transformer-LM forward sharing
  ``models/transformer_lm.py`` parameter names: full-sequence prefill
  (flash attention) and the fused one-token paged decode step
  (``ops.attention.paged_attention``).
* :mod:`.scheduler` — admission queue, per-request state machine,
  FCFS continuous batching, block-exhaustion preemption.
* :mod:`.engine`    — :class:`ServingEngine`: the Python API
  (``submit``/``step``/``generate``) with per-request TTFT / latency /
  tokens-per-sec flowing through the telemetry registry.
* :mod:`.obs`       — the per-request observability plane: lifecycle
  event stream keyed by ``request_id``, phase attribution (queue_wait /
  prefill / decode / replay / compile_stall summing to end-to-end),
  SLO accounting (``MXNET_SERVING_SLO_*``), the step occupancy timeline.
* :mod:`.resilience` — failure-as-routine: classified load shedding
  (:class:`ServingOverloadError` + Retry-After hints), per-request
  deadlines/cancellation (TIMED_OUT/CANCELLED terminal states swept
  every step), and :class:`EngineSupervisor` — abort → salvage →
  backoff → rebuild warm from the compile cache → replay survivors
  bit-identically. docs/serving.md §resilience.

Front ends: ``tools/serve.py`` (HTTP/JSON standing server with live stat
columns), ``tools/bench_serving.py`` (offline BENCH headline), and
``tools/serving_report.py`` (per-request waterfalls + occupancy timeline
from telemetry JSONL). See docs/serving.md.
"""
from .engine import ServingConfig, ServingEngine
from .kv_cache import KVBlockPool, KVCacheOOM
from .obs import PHASES, RequestTrace, ServingObs
from .resilience import EngineSupervisor, ServingOverloadError, retry_after_s
from .scheduler import (CANCELLED, FAILED, FINISHED, TIMED_OUT, Request,
                        Scheduler)

__all__ = ["ServingConfig", "ServingEngine", "KVBlockPool", "KVCacheOOM",
           "Request", "Scheduler", "ServingObs", "RequestTrace", "PHASES",
           "EngineSupervisor", "ServingOverloadError", "retry_after_s",
           "FINISHED", "FAILED", "TIMED_OUT", "CANCELLED"]
