"""Continuous-batching scheduler: admission queue, per-request state
machine, FCFS prefill/decode mixing, block-exhaustion preemption.

State machine (one :class:`Request` each)::

    WAITING --admit/alloc--> PREFILL --first token--> DECODING
       ^                                                 |
       |<------------- preempt (blocks exhausted) -------|
                                                         v
                FINISHED (len/eos) / FAILED / TIMED_OUT / CANCELLED

Terminal states:

* **FINISHED** — length cap or EOS; the only state SLO accounting judges.
* **FAILED** — engine/scheduler error (pool too small, dispatch abort).
* **TIMED_OUT** — the request's deadline (``timeout_s``) expired; swept
  at admission and per step so its blocks return to the pool promptly.
* **CANCELLED** — the consumer walked away (serve.py detects the dropped
  connection; direct drivers call ``engine.cancel``); blocks freed on
  the next sweep rather than decoding to ``max_new_tokens`` for nobody.

Each engine step the scheduler produces one :class:`StepPlan`:

* **ensure** — every DECODING request gets a pool block for its next slot;
  when the pool is dry the LATEST-admitted decoding request is preempted
  (its blocks freed, its tokens-so-far requeued at the HEAD of the waiting
  queue for deterministic re-prefill) until the older ones fit. FCFS both
  ways: oldest requests never starve behind younger ones.
* **admit** — waiting requests are admitted head-first while the batch cap,
  the per-step prefill budget, and the free list allow; the queue head
  blocks admission when its prompt doesn't fit (no skip-ahead — a short
  prompt can never overtake a long one, which is the fairness contract
  tests pin down).

Preemption is recompute-style (vLLM's recompute mode): a victim's
generated-so-far tokens become its new prompt; greedy decoding makes the
replay bit-deterministic, so preemption is invisible in the output stream.
"""
import itertools
import time
from collections import deque

from .. import telemetry
from .kv_cache import KVCacheOOM

WAITING = "waiting"
PREFILL = "prefill"
DECODING = "decoding"
FINISHED = "finished"
FAILED = "failed"
TIMED_OUT = "timed_out"
CANCELLED = "cancelled"

# every state a finished() request can be in; _terminate() routes each to
# its own counter so shed/expiry accounting never inflates requests_failed
TERMINAL_STATES = (FINISHED, FAILED, TIMED_OUT, CANCELLED)
_TERMINAL_COUNTERS = {
    FAILED: "serving.requests_failed",
    TIMED_OUT: "serving.timeouts",
    CANCELLED: "serving.cancelled",
}

_rid_counter = itertools.count()


class Request:
    """One generation request and its serving-side state."""

    __slots__ = ("rid", "request_id", "prompt", "max_new_tokens", "eos_id",
                 "state", "blocks", "shared_blocks", "context_len",
                 "generated", "pending_token", "arrival_t", "admitted_t",
                 "first_token_t", "preempted_t", "finish_t", "preemptions",
                 "error", "done_event", "trace", "deadline_t", "cancelled")

    def __init__(self, prompt, max_new_tokens, eos_id=None, rid=None,
                 request_id=None, timeout_s=None):
        self.rid = rid if rid is not None else next(_rid_counter)
        # wire identity: caller-supplied (X-Request-Id header) or derived
        # from the process-local rid — threads through every lifecycle
        # event, the /stats surface, and the per-request trace lanes
        self.request_id = (str(request_id) if request_id is not None
                           else "r%d" % self.rid)
        self.prompt = [int(t) for t in prompt]
        if not self.prompt:
            raise ValueError("empty prompt (the decoder needs a seed token)")
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        self.eos_id = None if eos_id is None else int(eos_id)
        self.state = WAITING
        self.blocks = []          # pool block ids, position order
        self.shared_blocks = 0    # leading blocks mapped from the prefix
                                  # index (refcounted, copy-on-write; the
                                  # prefill write table routes them to
                                  # trash — their K/V is already cached)
        self.context_len = 0      # tokens currently cached in the pool
        self.generated = []       # tokens produced so far (output stream)
        self.pending_token = None  # last generated token, not yet cached
        self.arrival_t = time.time()
        self.admitted_t = None
        self.first_token_t = None
        self.preempted_t = None   # last preemption (obs replay clock)
        self.finish_t = None
        self.preemptions = 0
        self.error = None
        self.done_event = None    # engine attaches for blocking consumers
        self.trace = None         # obs.RequestTrace (engine submits only)
        if timeout_s is not None:
            timeout_s = float(timeout_s)
            if timeout_s <= 0:
                raise ValueError("timeout_s must be > 0")
            self.deadline_t = self.arrival_t + timeout_s
        else:
            self.deadline_t = None
        self.cancelled = False    # consumer walked away; swept next step

    def expired(self, now=None):
        if self.deadline_t is None:
            return False
        return (now if now is not None else time.time()) >= self.deadline_t

    # tokens that must be in the KV cache for the next decode step
    def replay_tokens(self):
        """Prompt + generated-but-cached tokens: re-prefilling exactly these
        reconstructs the preempted request's cache state."""
        gen_cached = self.generated[:-1] if self.pending_token is not None \
            else self.generated
        return self.prompt + gen_cached

    @property
    def num_new_tokens(self):
        return len(self.generated)

    def finished(self):
        return self.state in TERMINAL_STATES

    def __repr__(self):
        return ("Request(rid=%s, state=%s, prompt=%d, generated=%d, ctx=%d, "
                "blocks=%d)" % (self.rid, self.state, len(self.prompt),
                                len(self.generated), self.context_len,
                                len(self.blocks)))


class StepPlan:
    """One engine step's work: requests to prefill (newly admitted or
    preempt-replayed) and requests to run the fused decode over."""

    __slots__ = ("prefills", "decodes", "preempted")

    def __init__(self, prefills, decodes, preempted):
        self.prefills = prefills
        self.decodes = decodes
        self.preempted = preempted

    def empty(self):
        return not (self.prefills or self.decodes)


class Scheduler:
    """FCFS continuous-batching scheduler over one :class:`KVBlockPool`."""

    def __init__(self, pool, max_batch=32, prefills_per_step=4,
                 lookahead=1, max_positions=None):
        self.pool = pool
        self.max_batch = int(max_batch)
        self.prefills_per_step = int(prefills_per_step)
        # write slots a decoding stream consumes per engine step: 1 for
        # plain decode, spec_k + 1 for speculative decoding (the draft +
        # verify window writes positions context_len .. context_len+k)
        self.lookahead = int(lookahead)
        # position cap (cfg.max_len): write slots at/past it route to the
        # trash block in-graph, so headroom past it is never allocated
        self.max_positions = (None if max_positions is None
                              else int(max_positions))
        self.waiting = deque()
        self.running = []          # admission order (oldest first)
        self.failed = []           # _fail victims awaiting engine drain
        self.preempt_count = 0     # this scheduler only (the registry
                                   # counter is process-global)

    # ---- intake ---------------------------------------------------------
    def add(self, req):
        """Enqueue a WAITING request (engine validates capacity first)."""
        self.waiting.append(req)
        self._refresh_gauges()

    def has_work(self):
        return bool(self.waiting or self.running)

    # ---- the per-step plan ---------------------------------------------
    def schedule(self):
        """Build this step's :class:`StepPlan`; mutates request states and
        the pool free list (alloc for admissions and next-slot headroom,
        free for preemption victims)."""
        preempted = self.ensure_decode_headroom()
        prefills = self._admit(preempted)
        self._refresh_gauges()
        return StepPlan(prefills, self.decodable(), preempted)

    def decodable(self):
        """Streams the fused decode step advances this iteration. The
        engine re-reads this AFTER running prefills (fresh admissions
        become decodable mid-step) — one definition, two call points."""
        return [r for r in self.running if r.state == DECODING
                and r.pending_token is not None]

    def ensure_decode_headroom(self):
        """Every DECODING request needs its next write slot backed by a
        block. Pool dry -> preempt youngest-admitted victims (never a
        request older than the one we are ensuring).

        Called twice per engine step: inside :meth:`schedule` for streams
        already decoding, and again by the engine after prefills — a
        prompt that exactly fills its blocks writes its FIRST decode
        token at a fresh block boundary, and without the second pass that
        write would land in the trash block and the position's K/V would
        be silently lost (outputs then drift from sequential decoding)."""
        preempted = []
        for req in list(self.running):
            # a victim preempted earlier this pass is WAITING now, so the
            # state check also skips members the loop snapshot still holds
            if req.state != DECODING or req.pending_token is None:
                continue
            last_pos = req.context_len + self.lookahead - 1
            if self.max_positions is not None:
                # slots at/past the cap route to trash in-graph; backing
                # them with real blocks would waste pool for nothing
                last_pos = min(last_pos, self.max_positions - 1)
            need_idx = last_pos // self.pool.block_size
            while need_idx >= len(req.blocks):
                try:
                    req.blocks.extend(self.pool.alloc(1))
                except KVCacheOOM:
                    # evict the YOUNGEST decoding stream — possibly req
                    # itself (a younger request never steals blocks from
                    # an older one: FCFS both ways)
                    victim = self._pick_victim(ensuring=req)
                    if victim is None or (victim is req
                                          and len(self.running) == 1):
                        # alone and still dry: the pool cannot hold this
                        # request at all — fail it, never wedge the engine
                        self._fail(req, "KV pool too small for request: "
                                        "%d blocks held, next slot needs "
                                        "one more and nothing is evictable"
                                   % len(req.blocks))
                        break
                    self._preempt(victim)
                    preempted.append(victim)
                    if victim is req:
                        break
        return preempted

    def _pick_victim(self, ensuring=None):
        """Youngest decoding stream whose eviction actually reclaims
        blocks. With refcounted prefix sharing the real reclaim gain is
        the count of blocks whose refcount would drop to ZERO — a stream
        holding only shared prefix blocks frees nothing, and preempting
        it would burn a replay for zero reclaimed headroom.

        Scanning stops at the stream being ensured: FCFS both ways means
        a younger request never steals blocks from an older one, so when
        every candidate at or after ``ensuring`` frees nothing the answer
        is None (the ensured stream fails, it does not reach upstream)."""
        for req in reversed(self.running):   # youngest admission first
            if (req.state == DECODING
                    and self.pool.reclaimable(req.blocks) > 0):
                return req
            if req is ensuring:
                break
        return None

    def _preempt(self, req):
        """Recompute-style preemption: free the blocks, requeue at the
        HEAD of the waiting queue with tokens-so-far as the new replay
        prompt (greedy decode makes the replay deterministic). Freeing
        decrements refcounts: shared prefix blocks survive for their
        other holders, only sole-owner blocks return to the pool."""
        self.running.remove(req)
        if req.blocks:
            self.pool.free(req.blocks)
            req.blocks = []
        req.shared_blocks = 0
        req.context_len = 0
        req.state = WAITING
        req.preemptions += 1
        req.preempted_t = time.time()
        self.preempt_count += 1
        telemetry.counter("serving.preemptions").inc()
        self.waiting.appendleft(req)

    def _fail(self, req, msg):
        self._terminate(req, FAILED, msg)

    def _terminate(self, req, state, msg):
        """Move ``req`` to a non-FINISHED terminal state: free its blocks
        promptly (refcount-decrement — shared prefix blocks survive for
        their other holders), route it into the ``failed`` drain channel
        so the engine's public completion paths surface it, and wake any
        blocked consumer. One exit door for FAILED/TIMED_OUT/CANCELLED —
        each bumps its own counter."""
        if req in self.running:   # admission-time failures never joined
            self.running.remove(req)
        if req.blocks:
            self.pool.free(req.blocks)
            req.blocks = []
        req.shared_blocks = 0
        req.state = state
        req.error = msg
        req.finish_t = time.time()
        telemetry.counter(_TERMINAL_COUNTERS[state]).inc()
        self.failed.append(req)
        if req.done_event is not None:
            req.done_event.set()

    def sweep(self, now=None):
        """Terminate expired / cancelled requests wherever they sit —
        WAITING (queue positions open up) or PREFILL/DECODING (their KV
        blocks return to the pool at once instead of decoding to
        ``max_new_tokens`` for a consumer that is gone). Called by the
        engine at the top of every step and safe to call directly.
        Returns the requests it terminated."""
        now = time.time() if now is None else now
        swept = []
        for req in list(self.running) + list(self.waiting):
            if req.finished():
                continue
            if req.cancelled:
                state, msg = CANCELLED, "cancelled by consumer"
            elif req.expired(now):
                state, msg = TIMED_OUT, (
                    "deadline expired after %.3fs (timeout_s=%.3f)"
                    % (now - req.arrival_t, req.deadline_t - req.arrival_t))
            else:
                continue
            if req in self.waiting:
                self.waiting.remove(req)
            self._terminate(req, state, msg)
            swept.append(req)
        if swept:
            self._refresh_gauges()
        return swept

    def _admit(self, preempted=()):
        """FCFS head-first admission into PREFILL, bounded by the batch
        cap, the per-step prefill budget, and the free list. The
        admission grant covers the replay tokens PLUS the first decode
        token's write slot — without that headroom a boundary-length
        prompt prefills, loses the decode-slot race to the next
        admission, and thrashes prefill->preempt every step on a tight
        pool. The head blocks the queue when it doesn't fit: no
        skip-ahead. A head the pool could never hold even when empty is
        failed outright (wedging the queue behind it forever serves no
        one). A request preempted THIS pass sits the step out —
        re-admitting it at once would re-grab the blocks the eviction
        just reclaimed."""
        prefills = []
        while (self.waiting and len(self.running) < self.max_batch
               and len(prefills) < self.prefills_per_step):
            req = self.waiting[0]
            if req in preempted:
                break
            replay = req.replay_tokens()
            need = self.pool.blocks_for(len(replay) + 1)
            if need > self.pool.num_usable:
                self.waiting.popleft()
                self._fail(req, "KV pool too small for request: needs %d "
                                "blocks (replay + first decode slot), pool "
                                "holds %d usable"
                           % (need, self.pool.num_usable))
                continue
            # prefix sharing: map the longest indexed block-aligned prefix
            # into the table (refcounted), allocate only the tail. The
            # match can never cover the first write slot — it spans full
            # blocks of the replay only, so decode writes always land in
            # this request's private tail blocks (COW stays a safety net,
            # not a hot path).
            shared = self.pool.prefix_match(replay)
            fresh = need - len(shared)
            if fresh > self.pool.available():
                if shared:   # drop our references; other holders keep them
                    self.pool.free(shared)
                break
            self.waiting.popleft()
            try:
                fresh_blocks = self.pool.alloc(fresh)
            except KVCacheOOM as e:
                # refused despite the available() check above (a
                # fault-injected kv_oom, or a racing allocator): no
                # dispatch happened and the pool is intact, so this is
                # the request's failure, not the engine's — fail it
                # through the classified exit door and keep admitting
                if shared:   # drop our references; other holders keep them
                    self.pool.free(shared)
                self._fail(req, "admission refused: %s" % e)
                continue
            req.blocks = shared + fresh_blocks
            req.shared_blocks = len(shared)
            req.state = PREFILL
            req.admitted_t = time.time()
            self.running.append(req)
            telemetry.counter("serving.requests_admitted").inc()
            prefills.append(req)
        return prefills

    def pop_failed(self):
        """Drain requests FAILED by the scheduler itself (pool too small,
        nothing evictable). The engine routes these through the same
        public completion channels as successes — ``step()``'s return and
        ``pop_finished()`` — so a polling driver can't miss a failure."""
        out, self.failed = self.failed, []
        return out

    # ---- completion (engine calls after a step's device work) ----------
    def finish(self, req):
        """Retire a FINISHED/FAILED request and release its blocks."""
        if req in self.running:
            self.running.remove(req)
        if req.blocks:
            self.pool.free(req.blocks)
            req.blocks = []
        req.shared_blocks = 0
        self._refresh_gauges()

    def frag_slots(self):
        """Internal fragmentation: allocated-but-unused tail-block slots.
        Per-scheduler (the gauge below is process-global; engine stats()
        and the step timeline read this directly)."""
        return sum(len(r.blocks) * self.pool.block_size - r.context_len
                   for r in self.running)

    def _refresh_gauges(self):
        telemetry.gauge("serving.queue_depth").set(len(self.waiting))
        telemetry.gauge("serving.active_requests").set(len(self.running))
        telemetry.gauge("serving.kv_blocks_frag_slots").set(
            self.frag_slots())
