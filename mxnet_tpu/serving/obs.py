"""Per-request serving observability: lifecycle tracing, phase
attribution, SLO accounting, and the step-level occupancy timeline.

The engine's aggregate histograms (``serving.ttft_seconds``,
``serving.request_latency_seconds``) say *how slow* — this module says
*why*. Every submitted request carries a ``request_id`` (caller-supplied
via the ``X-Request-Id`` HTTP header, auto-assigned otherwise) and a
:class:`RequestTrace` that attributes its whole wall clock to exactly one
phase at a time::

    submitted --> queue_wait --admit--> prefill --first token--> decode
                                                                  |
                       replay <------------ preempted ------------|
                         |--readmit--> (replay continues) --replayed--> decode
                                                                  |
                                                       finished / failed

Phases (``serving.phase_seconds{engine,phase}``):

* ``queue_wait``    submit -> admission (head-of-line blocking, pool dry)
* ``prefill``       admission -> first token (fresh prompts)
* ``decode``        steady-state token generation
* ``replay``        preemption -> replay-prefill done: the wall a
                    recompute-style preemption cost the request (its
                    KV cache is rebuilt from tokens-so-far)
* ``compile_stall`` time blocked behind a cold bucket compile, measured
                    via compileobs compile-tally deltas around each
                    dispatch and *debited* from the enclosing phase

The debit keeps the invariant the report tools rely on: the five phases
sum EXACTLY to ``finish_t - arrival_t`` for every request (modulo float
rounding) — attribution closes, nothing is double-counted.

SLO accounting is always-on (rare-path counters): per-request TTFT and
TPOT are judged against ``MXNET_SERVING_SLO_TTFT_MS`` /
``MXNET_SERVING_SLO_TPOT_MS`` into ``serving.slo_good`` /
``serving.slo_total{engine,phase}``; ``serving.goodput{engine}`` gauges
the attainment over the last :data:`SLO_WINDOW` finished requests and a
``serving.slo_burn`` event fires on the transition below
:data:`BURN_THRESHOLD`.

Structured events (``MXNET_TELEMETRY_FILE`` JSONL, rendered by
``tools/serving_report.py`` and ``tools/trace_merge.py --serving-lanes``):

* ``serving.request``        one per lifecycle transition (``state`` in
  submitted/admitted/decoding/preempted/readmitted/replayed/finished/
  failed); the terminal event carries the full phase breakdown
* ``serving.step_timeline``  one per non-empty engine step: batch
  occupancy, admitted/preempted/finished counts, queue depth, KV-pool
  used/free/frag — the occupancy time series
* ``serving.slo_burn``       attainment crossed below the burn threshold

Thread model: every hook runs under the engine lock (the driver thread
owns all transitions); no locking of its own. With telemetry disabled the
per-step cost is O(changed requests): hooks fire only on lifecycle
transitions, ``telemetry.event`` is a no-op, and nothing here touches
device values (no host syncs).
"""
import time
from collections import deque

from .. import telemetry
from ..base import env_float

__all__ = ["PHASES", "SLO_WINDOW", "BURN_THRESHOLD", "RequestTrace",
           "ServingObs"]

#: Exhaustive phase set; every request's wall clock is partitioned over it.
PHASES = ("queue_wait", "prefill", "decode", "replay", "compile_stall")

#: Finished requests in the goodput sliding window.
SLO_WINDOW = 32

#: ``serving.slo_burn`` fires when windowed attainment crosses below this.
BURN_THRESHOLD = 0.9

#: Minimum finished requests before burn-rate judgment (a 1-request window
#: would fire on the first miss of the day).
_BURN_MIN_SAMPLES = 8


# thread-confined: a trace is mutated only by the thread stepping its
# request (driver thread under the engine lock); handler threads read it
# only after finish() publishes the request under that same lock
class RequestTrace:
    """One request's phase clock: exactly one open phase at any moment.

    ``to_phase`` closes the open phase at ``now`` and opens the next;
    ``add_stall`` moves compile wall out of the open phase into
    ``compile_stall`` (debited at close so the five phases still sum to
    the request's end-to-end wall). All calls happen under the engine
    lock, in timestamp order.
    """

    __slots__ = ("phases", "cur", "t0", "stall_debit", "closed", "sub")

    def __init__(self, t0):
        self.phases = dict.fromkeys(PHASES, 0.0)
        self.cur = "queue_wait"
        self.t0 = float(t0)
        self.stall_debit = 0.0
        self.closed = False
        # SUB-attribution inside the decode phase (speculative decoding's
        # draft/verify split) — informational breakdown, NOT a phase:
        # the five phases alone still sum exactly to end-to-end wall
        self.sub = {"spec_draft": 0.0, "spec_verify": 0.0}

    def _settle(self, now):
        # stall_debit <= elapsed by construction (each stall is clipped to
        # its dispatch wall, dispatches are disjoint within the phase);
        # max() guards float noise only
        self.phases[self.cur] += max(0.0, (now - self.t0) - self.stall_debit)
        self.stall_debit = 0.0

    def to_phase(self, phase, now):
        """Close the open phase at ``now`` and open ``phase``."""
        if self.closed:
            return
        self._settle(now)
        self.cur = phase
        self.t0 = now

    def add_stall(self, seconds):
        """Attribute ``seconds`` of the open phase to ``compile_stall``."""
        if self.closed or seconds <= 0.0:
            return
        self.phases["compile_stall"] += seconds
        self.stall_debit += seconds

    def close(self, now):
        """Terminal transition: settle the open phase and freeze."""
        if self.closed:
            return
        self._settle(now)
        self.closed = True

    def total(self):
        """Sum over phases — equals end-to-end wall once closed."""
        return sum(self.phases.values())


class ServingObs:
    """One engine's observability plane (engine-lock-guarded, not
    thread-safe on its own). The engine calls one hook per request
    lifecycle transition plus one per step for the timeline."""

    __slots__ = ("engine_id", "slo_ttft_s", "slo_tpot_s", "_window",
                 "_burning", "_good", "_total")

    def __init__(self, engine_id, slo_ttft_ms=None, slo_tpot_ms=None):
        self.engine_id = str(engine_id)
        if slo_ttft_ms is None:
            slo_ttft_ms = env_float("MXNET_SERVING_SLO_TTFT_MS", 1000.0)
        if slo_tpot_ms is None:
            slo_tpot_ms = env_float("MXNET_SERVING_SLO_TPOT_MS", 100.0)
        self.slo_ttft_s = float(slo_ttft_ms) / 1000.0
        self.slo_tpot_s = float(slo_tpot_ms) / 1000.0
        self._window = deque(maxlen=SLO_WINDOW)   # True per SLO-good finish
        self._burning = False
        # per-engine tallies mirrored into the labeled registry counters:
        # stats() reads these so a second engine in the process never
        # inherits the first one's numbers
        self._good = {"ttft": 0, "tpot": 0}
        self._total = {"ttft": 0, "tpot": 0}

    # ---- lifecycle hooks (engine lock held) ----------------------------
    def request_submitted(self, req):
        """Attach the trace; the queue_wait clock starts at arrival."""
        req.trace = RequestTrace(req.arrival_t)
        telemetry.event("serving.request", request_id=req.request_id,
                        engine=self.engine_id, state="submitted",
                        prompt_tokens=len(req.prompt),
                        max_new_tokens=req.max_new_tokens)

    def request_admitted(self, req):
        """Admission: fresh prompts enter ``prefill``; a preemption
        victim re-admitted for replay stays on its ``replay`` clock (the
        re-prefill is part of what the preemption cost it)."""
        tr = req.trace
        if tr is None:
            return
        if tr.cur == "replay":
            telemetry.event("serving.request", request_id=req.request_id,
                            engine=self.engine_id, state="readmitted",
                            preemptions=req.preemptions)
            return
        tr.to_phase("prefill", req.admitted_t)
        telemetry.event("serving.request", request_id=req.request_id,
                        engine=self.engine_id, state="admitted",
                        queue_wait_s=round(tr.phases["queue_wait"], 6))

    def prefill_done(self, req, stall_s, replay):
        """Prefill dispatch returned: the request is decoding. Fresh
        prompts got their first token here (TTFT closes); replays just
        finished rebuilding their cache (replay overhead closes)."""
        tr = req.trace
        if tr is None:
            return
        tr.add_stall(stall_s)
        now = time.time()
        tr.to_phase("decode", now)
        if replay:
            telemetry.event("serving.request", request_id=req.request_id,
                            engine=self.engine_id, state="replayed",
                            replay_s=round(tr.phases["replay"], 6))
            return
        ttft = (req.first_token_t or now) - req.arrival_t
        telemetry.histogram("serving.ttft_seconds",
                            engine=self.engine_id).observe(ttft)
        telemetry.event("serving.request", request_id=req.request_id,
                        engine=self.engine_id, state="decoding",
                        ttft_s=round(ttft, 6))

    def decode_stall(self, reqs, stall_s):
        """A decode dispatch compiled (cold batch bucket): every stream
        in the batch was blocked behind it for the full stall."""
        if stall_s <= 0.0:
            return
        for req in reqs:
            if req.trace is not None:
                req.trace.add_stall(stall_s)

    def spec_step(self, reqs, draft_s, verify_s, proposed, accepted):
        """One speculative decode step landed: histogram the draft/verify
        walls (stall already subtracted by the caller), count the
        proposal/acceptance tokens, and sub-attribute each stream's share
        of the step inside its decode phase (``trace.sub`` — the
        waterfall's draft/verify split; never double-counted against the
        phase sum, which only partitions over :data:`PHASES`)."""
        telemetry.histogram("serving.spec_draft_seconds").observe(draft_s)
        telemetry.histogram("serving.spec_verify_seconds").observe(verify_s)
        telemetry.counter("serving.spec_proposed_tokens").inc(proposed)
        telemetry.counter("serving.spec_accepted_tokens").inc(accepted)
        for req in reqs:
            if req.trace is not None:
                req.trace.sub["spec_draft"] += draft_s
                req.trace.sub["spec_verify"] += verify_s

    def request_preempted(self, req):
        """Blocks evicted, tokens-so-far requeued: everything until the
        replay prefill lands is overhead the preemption caused."""
        tr = req.trace
        if tr is None:
            return
        tr.to_phase("replay", req.preempted_t or time.time())
        telemetry.event("serving.request", request_id=req.request_id,
                        engine=self.engine_id, state="preempted",
                        preemptions=req.preemptions)

    def request_finished(self, req, failed=False):
        """Terminal: close the trace, observe the labeled latency/phase
        histograms, judge the SLOs (always-on counters), refresh goodput
        and the burn state, emit the terminal event with the breakdown.

        The terminal state comes from ``req.state`` (finished / failed /
        timed_out / cancelled); the legacy ``failed`` flag forces the
        failed lane for callers predating the resilience states. Only
        FINISHED requests are judged against the SLOs — a shed, expired,
        or cancelled request is not a latency sample."""
        tr = req.trace
        if tr is None or tr.closed:
            return
        now = req.finish_t if req.finish_t is not None else time.time()
        tr.close(now)
        e2e = now - req.arrival_t
        phases = {ph: round(v, 6) for ph, v in tr.phases.items()}
        for ph in PHASES:
            telemetry.histogram("serving.phase_seconds", engine=self.engine_id,
                                phase=ph).observe(tr.phases[ph])
        state = "failed" if failed else req.state
        ok = state == "finished"
        slo = {}
        if ok:
            telemetry.histogram(
                "serving.request_latency_seconds",
                engine=self.engine_id).observe(e2e)
            slo = self._judge_slo(req)
        fields = dict(request_id=req.request_id, engine=self.engine_id,
                      state=state, e2e_s=round(e2e, 6), phases=phases,
                      tokens=len(req.generated),
                      preemptions=req.preemptions, **slo)
        if tr.sub["spec_draft"] or tr.sub["spec_verify"]:
            # decode-phase sub-split for serving_report.py's waterfall;
            # NOT part of the phase-sum contract
            fields["spec_draft_s"] = round(tr.sub["spec_draft"], 6)
            fields["spec_verify_s"] = round(tr.sub["spec_verify"], 6)
        if not ok:
            fields["error"] = req.error
        telemetry.event("serving.request", **fields)

    # ---- SLO ----------------------------------------------------------
    def _judge_slo(self, req):
        """Always-on good/total counters + windowed goodput + burn edge.
        TPOT is judged only for requests that decoded (>= 2 tokens)."""
        out = {}
        ok_all = True
        ttft = (req.first_token_t or req.finish_t) - req.arrival_t
        ok = ttft <= self.slo_ttft_s
        self._bump("ttft", ok)
        out["slo_ttft_ok"] = ok
        ok_all &= ok
        n = len(req.generated)
        if n >= 2 and req.first_token_t is not None:
            tpot = (req.finish_t - req.first_token_t) / (n - 1)
            ok = tpot <= self.slo_tpot_s
            self._bump("tpot", ok)
            out["slo_tpot_ok"] = ok
            out["tpot_s"] = round(tpot, 6)
            ok_all &= ok
            telemetry.histogram("serving.tpot_seconds",
                                engine=self.engine_id).observe(tpot)
        self._window.append(bool(ok_all))
        att = sum(self._window) / len(self._window)
        telemetry.gauge("serving.goodput", engine=self.engine_id).set(att)
        if len(self._window) >= _BURN_MIN_SAMPLES:
            if att < BURN_THRESHOLD and not self._burning:
                self._burning = True
                telemetry.event("serving.slo_burn", engine=self.engine_id,
                                attainment=round(att, 4),
                                threshold=BURN_THRESHOLD,
                                window=len(self._window))
            elif att >= BURN_THRESHOLD:
                self._burning = False
        return out

    def _bump(self, phase, good):
        self._total[phase] += 1
        telemetry.counter("serving.slo_total", engine=self.engine_id,
                          phase=phase).inc()
        if good:
            self._good[phase] += 1
            telemetry.counter("serving.slo_good", engine=self.engine_id,
                              phase=phase).inc()

    # ---- step timeline ------------------------------------------------
    def step_timeline(self, step, occupancy, admitted, preempted, finished,
                      queue, running, kv_used, kv_free, kv_frag_slots):
        """One occupancy sample per non-empty engine step (disabled
        telemetry short-circuits before any field is assembled)."""
        if not telemetry.enabled():
            return
        telemetry.event("serving.step_timeline", engine=self.engine_id,
                        step=step, occupancy=occupancy, admitted=admitted,
                        preempted=preempted, finished=finished, queue=queue,
                        running=running, kv_used=kv_used, kv_free=kv_free,
                        kv_frag_slots=kv_frag_slots)

    # ---- snapshots (stats() / serve.py / bench) -----------------------
    def slo_snapshot(self):
        """This engine's SLO block for ``stats()``/bench JSON."""
        att = {ph: (self._good[ph] / self._total[ph]
                    if self._total[ph] else None)
               for ph in ("ttft", "tpot")}
        return {
            "ttft_target_ms": round(self.slo_ttft_s * 1000.0, 3),
            "tpot_target_ms": round(self.slo_tpot_s * 1000.0, 3),
            "good": dict(self._good),
            "total": dict(self._total),
            "attainment": att,
            "goodput": (sum(self._window) / len(self._window)
                        if self._window else None),
            "burning": self._burning,
        }

    def phase_snapshot(self):
        """Per-phase p50/p99/total from THIS engine's labeled histograms."""
        out = {}
        for ph in PHASES:
            h = telemetry.histogram("serving.phase_seconds",
                                    engine=self.engine_id, phase=ph)
            out[ph] = {"count": h.count,
                       "total_s": round(h.sum, 6),
                       "p50_s": h.percentile(50),
                       "p99_s": h.percentile(99)}
        return out
