"""Block-paged KV-cache pool: the serving-side replacement for the
per-executor contiguous cache.

The contiguous cached decoder (``_contrib_CachedMultiHeadAttention``) gives
every stream a private ``(max_len, heads, head_dim)`` cache per layer —
serving N streams costs N full-length caches whether a stream holds 4 tokens
or 4096. Here all streams share ONE device pool of fixed-size blocks
(``block_size`` token slots each); a per-request block table names which
pool blocks hold the request's tokens, in position order. Device memory
scales with tokens actually cached, admission is a free-list pop, and
release is O(blocks) with zero copying.

Layout (one pool per engine): ``(num_layers, num_blocks, block_size,
num_heads, head_dim)`` for K and V. Block 0 is the reserved TRASH block —
padded table entries and padded batch rows point at it, so masked lanes of
a bucketed step scatter their garbage somewhere no reader ever trusts
(readers mask by context length; the pool hands block 0 to no request).

Fragmentation accounting: fixed-size blocks make external fragmentation
impossible by construction (any free block serves any request), so "defrag"
reduces to accounting for INTERNAL fragmentation — allocated-but-unused
slots in each request's tail block — exposed as the
``serving.kv_blocks_frag_slots`` gauge (the engine refreshes it each step).
"""
import threading

import numpy as np

from .. import telemetry
from ..base import MXNetError


class KVCacheOOM(MXNetError):
    """The block pool cannot satisfy an allocation (classified so the
    scheduler can preempt / the engine can fail the request instead of
    dying inside a step)."""


class KVBlockPool:
    """Device KV block pool + thread-safe host-side free-list allocator."""

    def __init__(self, num_layers, num_blocks, block_size, num_heads,
                 head_dim, dtype=np.float32, device=None):
        if num_blocks < 2:
            raise ValueError("KVBlockPool needs >= 2 blocks (block 0 is the "
                             "reserved trash block)")
        import jax.numpy as jnp

        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = np.dtype(dtype)
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        k = jnp.zeros(shape, self.dtype)
        v = jnp.zeros(shape, self.dtype)
        if device is not None:
            import jax

            k = jax.device_put(k, device)
            v = jax.device_put(v, device)
        #: the device pages; the engine REPLACES these after every jitted
        #: prefill/decode call (the arrays are donated into the step)
        self.k_pages = k
        self.v_pages = v
        self._lock = threading.Lock()
        # LIFO free list, block 0 excluded (trash)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        telemetry.gauge("serving.kv_blocks_total").set(self.num_usable)
        self._refresh_gauges_locked()

    # ---- capacity -------------------------------------------------------
    @property
    def num_usable(self):
        """Allocatable blocks (pool size minus the trash block)."""
        return self.num_blocks - 1

    def available(self):
        with self._lock:
            return len(self._free)

    def used(self):
        with self._lock:
            return self.num_usable - len(self._free)

    def nbytes(self):
        """Device bytes the pool pins (K + V)."""
        per = (self.num_layers * self.num_blocks * self.block_size
               * self.num_heads * self.head_dim * self.dtype.itemsize)
        return 2 * per

    def blocks_for(self, num_tokens):
        """Blocks needed to hold ``num_tokens`` cache slots."""
        return -(-int(num_tokens) // self.block_size)

    # ---- alloc / free ---------------------------------------------------
    def alloc(self, n):
        """Pop ``n`` blocks off the free list; raises :class:`KVCacheOOM`
        (allocating nothing) when fewer than ``n`` are free."""
        n = int(n)
        with self._lock:
            if n > len(self._free):
                telemetry.counter("serving.kv_blocks_alloc_failures").inc()
                raise KVCacheOOM(
                    "KV block pool exhausted: want %d blocks, %d free of %d "
                    "usable (%d-token slots each)"
                    % (n, len(self._free), self.num_usable, self.block_size))
            got = [self._free.pop() for _ in range(n)]
            telemetry.counter("serving.kv_blocks_allocs").inc(n)
            self._refresh_gauges_locked()
            return got

    def free(self, blocks):
        """Return blocks to the pool. Double-free and trash-free are hard
        errors — the accounting gauges must never drift."""
        blocks = list(blocks)
        with self._lock:
            freed = set(self._free)
            for b in blocks:
                b = int(b)
                if b <= 0 or b >= self.num_blocks:
                    raise ValueError("free of invalid block id %d" % b)
                if b in freed:
                    raise ValueError("double free of block %d" % b)
                self._free.append(b)
                freed.add(b)
            telemetry.counter("serving.kv_blocks_frees").inc(len(blocks))
            self._refresh_gauges_locked()

    def _refresh_gauges_locked(self):
        telemetry.gauge("serving.kv_blocks_used").set(
            self.num_usable - len(self._free))
        telemetry.gauge("serving.kv_blocks_free").set(len(self._free))
