"""Block-paged KV-cache pool: the serving-side replacement for the
per-executor contiguous cache.

The contiguous cached decoder (``_contrib_CachedMultiHeadAttention``) gives
every stream a private ``(max_len, heads, head_dim)`` cache per layer —
serving N streams costs N full-length caches whether a stream holds 4 tokens
or 4096. Here all streams share ONE device pool of fixed-size blocks
(``block_size`` token slots each); a per-request block table names which
pool blocks hold the request's tokens, in position order. Device memory
scales with tokens actually cached, admission is a free-list pop, and
release is O(blocks) with zero copying.

Layout (one pool per engine): ``(num_layers, num_blocks, block_size,
num_heads, head_dim)`` for K and V. Block 0 is the reserved TRASH block —
padded table entries and padded batch rows point at it, so masked lanes of
a bucketed step scatter their garbage somewhere no reader ever trusts
(readers mask by context length; the pool hands block 0 to no request).

Prefix sharing (docs/serving.md §prefix-sharing): every allocated block
carries a REFCOUNT. Full prefill blocks are content-hashed into a pool-
level prefix index — the digest chains token ids through the block's
position base, so only a same-tokens same-positions prefix can ever match
(position embeddings are baked into the cached K/V). A new request maps
the longest indexed block-aligned prefix into its table via
:meth:`prefix_match` (incref), and ``free``/preempt decrements — a block
returns to the free list only when its refcount reaches zero, at which
point its index entry is dropped. Shared blocks are COPY-ON-WRITE:
:meth:`cow` hands a writer a private bit-exact copy first. The trash
block is never refcounted, never indexed, never shared.

Fragmentation accounting: fixed-size blocks make external fragmentation
impossible by construction (any free block serves any request), so "defrag"
reduces to accounting for INTERNAL fragmentation — allocated-but-unused
slots in each request's tail block — exposed as the
``serving.kv_blocks_frag_slots`` gauge (the engine refreshes it each step).
"""
import hashlib
import threading

import numpy as np

from .. import fault, telemetry
from ..analysis import witness
from ..base import MXNetError


class KVCacheOOM(MXNetError):
    """The block pool cannot satisfy an allocation (classified so the
    scheduler can preempt / the engine can fail the request instead of
    dying inside a step)."""


class KVBlockPool:
    """Device KV block pool + thread-safe host-side free-list allocator
    with block refcounts and a content-hash prefix index."""

    def __init__(self, num_layers, num_blocks, block_size, num_heads,
                 head_dim, dtype=np.float32, device=None,
                 prefix_cache=True):
        if num_blocks < 2:
            raise ValueError("KVBlockPool needs >= 2 blocks (block 0 is the "
                             "reserved trash block)")
        import jax.numpy as jnp

        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = np.dtype(dtype)
        self.prefix_cache = bool(prefix_cache)
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        k = jnp.zeros(shape, self.dtype)
        v = jnp.zeros(shape, self.dtype)
        if device is not None:
            import jax

            k = jax.device_put(k, device)
            v = jax.device_put(v, device)
        #: the device pages; the engine REPLACES these after every jitted
        #: prefill/decode call (the arrays are donated into the step)
        self.k_pages = k
        self.v_pages = v
        self._lock = threading.Lock()
        self._lock = witness.declare(
            "mxnet_tpu.serving.kv_cache.KVBlockPool._lock", self._lock)
        # LIFO free list, block 0 excluded (trash)
        self._free = list(range(self.num_blocks - 1, 0, -1))
        # block id -> refcount, allocated blocks only (never block 0)
        self._ref = {}
        # content-hash prefix index: chained digest -> block id holding
        # that full block's K/V, plus the reverse map for O(1) removal
        # when the block's refcount hits zero
        self._prefix = {}
        self._block_digest = {}
        # per-pool tallies (the registry counters with the same names are
        # process-global; stats() must read only this pool's traffic)
        self.prefix_lookups = 0
        self.prefix_hits = 0
        self.prefix_hit_blocks = 0
        self.cow_copies = 0
        telemetry.gauge("serving.kv_blocks_total").set(self.num_usable)
        # the pool may be constructed on a supervisor thread while handler
        # threads already poll the gauges of a predecessor — honor the
        # _locked suffix even on the init path
        with self._lock:
            self._refresh_gauges_locked()

    # ---- capacity -------------------------------------------------------
    @property
    def num_usable(self):
        """Allocatable blocks (pool size minus the trash block)."""
        return self.num_blocks - 1

    def available(self):
        with self._lock:
            return len(self._free)

    def used(self):
        with self._lock:
            return self.num_usable - len(self._free)

    def nbytes(self):
        """Device bytes the pool pins (K + V)."""
        per = (self.num_layers * self.num_blocks * self.block_size
               * self.num_heads * self.head_dim * self.dtype.itemsize)
        return 2 * per

    def block_nbytes(self):
        """Device bytes ONE block pins across layers (K + V) — the unit
        every shared reference saves."""
        return 2 * (self.num_layers * self.block_size * self.num_heads
                    * self.head_dim * self.dtype.itemsize)

    def blocks_for(self, num_tokens):
        """Blocks needed to hold ``num_tokens`` cache slots."""
        return -(-int(num_tokens) // self.block_size)

    # ---- alloc / free ---------------------------------------------------
    def alloc(self, n):
        """Pop ``n`` blocks off the free list (each born with refcount 1);
        raises :class:`KVCacheOOM` (allocating nothing) when fewer than
        ``n`` are free."""
        n = int(n)
        # chaos: forced allocator exhaustion, checked OUTSIDE the pool
        # lock (the injection must not perturb lock ordering) — exercises
        # every KVCacheOOM consumer: preemption, admission failure, the
        # classified alloc-failure counters (docs/fault_tolerance.md)
        if fault.hit("kv_oom") is not None:
            telemetry.counter("serving.kv_blocks_alloc_failures").inc()
            raise KVCacheOOM(
                "KV block pool exhausted (fault-injected kv_oom): want %d "
                "blocks" % n)
        with self._lock:
            if n > len(self._free):
                telemetry.counter("serving.kv_blocks_alloc_failures").inc()
                raise KVCacheOOM(
                    "KV block pool exhausted: want %d blocks, %d free of %d "
                    "usable (%d-token slots each)"
                    % (n, len(self._free), self.num_usable, self.block_size))
            got = [self._free.pop() for _ in range(n)]
            for b in got:
                self._ref[b] = 1
            telemetry.counter("serving.kv_blocks_allocs").inc(n)
            self._refresh_gauges_locked()
            self._check_invariants_locked()
            return got

    def free(self, blocks):
        """Drop one reference per listed block. A block returns to the
        free list (and its prefix-index entry is dropped) only when its
        refcount reaches ZERO — freeing a shared block reclaims nothing.
        Double-free (a block with no references) and trash-free are hard
        errors: the accounting gauges must never drift."""
        blocks = [int(b) for b in blocks]
        with self._lock:
            released = 0
            for b in blocks:
                if b <= 0 or b >= self.num_blocks:
                    raise ValueError("free of invalid block id %d" % b)
                rc = self._ref.get(b, 0)
                if rc <= 0:
                    raise ValueError("double free of block %d" % b)
                if rc == 1:
                    del self._ref[b]
                    self._drop_index_locked(b)
                    self._free.append(b)
                    released += 1
                else:
                    self._ref[b] = rc - 1
            if released:
                telemetry.counter("serving.kv_blocks_frees").inc(released)
            self._refresh_gauges_locked()
            self._check_invariants_locked()
            return released

    # ---- refcounts ------------------------------------------------------
    def refcount(self, b):
        """Current reference count of ``b`` (0 when free/never allocated)."""
        with self._lock:
            return self._ref.get(int(b), 0)

    def incref(self, blocks):
        """Add one reference per listed block (each must be allocated)."""
        with self._lock:
            for b in blocks:
                b = int(b)
                rc = self._ref.get(b, 0)
                if b <= 0 or rc <= 0:
                    raise ValueError(
                        "incref of unallocated block %d (trash and free "
                        "blocks cannot be shared)" % b)
                self._ref[b] = rc + 1
            self._refresh_gauges_locked()
            self._check_invariants_locked()

    def reclaimable(self, blocks):
        """How many of ``blocks`` would actually return to the free list
        if freed now — only those whose refcount is exactly 1. The
        scheduler's eviction-victim picker computes its reclaim gain from
        this, never from ``len(blocks)``."""
        with self._lock:
            return sum(1 for b in blocks if self._ref.get(int(b), 0) == 1)

    def cow(self, b):
        """Copy-on-write: hand the caller a PRIVATE copy of block ``b``
        before a write. Sole owner (refcount 1) -> ``b`` itself, no copy.
        Shared -> allocate a fresh block, device-copy the K/V pages
        bit-exactly, drop one reference from ``b``, return the new id.
        Raises :class:`KVCacheOOM` when the free list is dry."""
        b = int(b)
        with self._lock:
            rc = self._ref.get(b, 0)
            if b <= 0 or rc <= 0:
                raise ValueError("cow of unallocated block %d" % b)
            if rc == 1:
                return b
            if not self._free:
                telemetry.counter("serving.kv_blocks_alloc_failures").inc()
                raise KVCacheOOM(
                    "KV block pool exhausted: copy-on-write of shared "
                    "block %d needs a free block, 0 free of %d usable"
                    % (b, self.num_usable))
            nb = self._free.pop()
            self._ref[nb] = 1
            self._ref[b] = rc - 1
            # eager device-side page copy — bit-exact K/V into the private
            # block; the writer's table swaps b -> nb after this returns
            self.k_pages = self.k_pages.at[:, nb].set(self.k_pages[:, b])
            self.v_pages = self.v_pages.at[:, nb].set(self.v_pages[:, b])
            self.cow_copies += 1
            telemetry.counter("serving.prefix_cow_copies").inc()
            telemetry.counter("serving.kv_blocks_allocs").inc()
            self._refresh_gauges_locked()
            self._check_invariants_locked()
            return nb

    # ---- prefix index ---------------------------------------------------
    def _digests(self, tokens):
        """Chained content digest per FULL block of ``tokens``: digest i
        covers tokens[0 : (i+1)*block_size] plus the position base i, so
        equal digests imply equal token prefix at equal absolute positions
        — the only condition under which cached K/V (position embeddings
        baked in, attention over the whole prefix) is reusable."""
        bs = self.block_size
        out = []
        h = hashlib.sha1()
        for i in range(len(tokens) // bs):
            h.update(b"%d|" % i)
            h.update(np.asarray(  # fwlint: disable=device-escape — host token list -> bytes for hashing; no device value involved
                tokens[i * bs:(i + 1) * bs], np.int64).tobytes())
            out.append(h.digest())
        return out

    def prefix_match(self, tokens):
        """Longest indexed block-aligned prefix of ``tokens``: returns the
        matched block ids IN POSITION ORDER with one reference taken on
        each (the caller owns them exactly like ``alloc`` output — ``free``
        releases). Empty list when the index is cold or disabled."""
        if not self.prefix_cache:
            return []
        digests = self._digests(tokens)
        with self._lock:
            self.prefix_lookups += 1
            telemetry.counter("serving.prefix_lookups").inc()
            got = []
            for d in digests:
                b = self._prefix.get(d)
                if b is None:
                    break
                rc = self._ref.get(b, 0)
                assert rc > 0, (
                    "prefix index invariant violated: indexed block %d has "
                    "no references (index entries must be dropped when the "
                    "refcount hits zero)" % b)
                self._ref[b] = rc + 1
                got.append(b)
            if got:
                self.prefix_hits += 1
                self.prefix_hit_blocks += len(got)
                telemetry.counter("serving.prefix_hits").inc()
                telemetry.counter("serving.prefix_hit_blocks").inc(len(got))
            self._refresh_gauges_locked()
            self._check_invariants_locked()
            return got

    def prefix_insert(self, tokens, blocks):
        """Register a freshly prefilled request's FULL blocks under their
        chain digests. ``blocks[i]`` must hold tokens[i*bs:(i+1)*bs]'s K/V
        at position base i. First writer wins: a digest already indexed
        (e.g. the shared prefix this request itself mapped) is skipped, as
        is any block already indexed under another digest."""
        if not self.prefix_cache:
            return 0
        digests = self._digests(tokens)
        added = 0
        with self._lock:
            for d, b in zip(digests, blocks):
                b = int(b)
                if d in self._prefix or b in self._block_digest:
                    continue
                assert b > 0 and self._ref.get(b, 0) > 0, (
                    "prefix_insert of unallocated block %d" % b)
                self._prefix[d] = b
                self._block_digest[b] = d
                added += 1
            self._refresh_gauges_locked()
            self._check_invariants_locked()
        return added

    def _drop_index_locked(self, b):
        d = self._block_digest.pop(b, None)
        if d is not None:
            self._prefix.pop(d, None)

    def prefix_stats(self):
        """This pool's prefix-sharing snapshot (engine stats() / bench)."""
        with self._lock:
            shared = [rc for rc in self._ref.values() if rc > 1]
            saved_blocks = sum(rc - 1 for rc in shared)
            return {
                "enabled": self.prefix_cache,
                "lookups": self.prefix_lookups,
                "hits": self.prefix_hits,
                "hit_rate": (self.prefix_hits / self.prefix_lookups
                             if self.prefix_lookups else None),
                "hit_blocks": self.prefix_hit_blocks,
                "shared_blocks": len(shared),
                "kv_bytes_saved": saved_blocks * self.block_nbytes(),
                "cow_copies": self.cow_copies,
                "index_size": len(self._prefix),
            }

    # ---- accounting -----------------------------------------------------
    def _refresh_gauges_locked(self):
        telemetry.gauge("serving.kv_blocks_used").set(
            self.num_usable - len(self._free))
        telemetry.gauge("serving.kv_blocks_free").set(len(self._free))
        shared = [rc for rc in self._ref.values() if rc > 1]
        telemetry.gauge("serving.prefix_shared_blocks").set(len(shared))
        telemetry.gauge("serving.prefix_kv_bytes_saved").set(
            sum(rc - 1 for rc in shared) * self.block_nbytes())

    def _check_invariants_locked(self):
        # every usable block is exactly one of: free, or referenced;
        # the trash block is neither, and never indexed or shared
        assert len(self._free) + len(self._ref) == self.num_usable, (
            "KV pool accounting drift: %d free + %d referenced != %d usable"
            % (len(self._free), len(self._ref), self.num_usable))
        assert 0 not in self._ref and 0 not in self._block_digest, \
            "trash block must never be refcounted or indexed"
        assert len(self._prefix) == len(self._block_digest), \
            "prefix index maps out of sync"
