"""Serving resilience: overload classification, backoff hints, and the
supervised engine-recovery loop.

The serving engine's failure contract is deliberately blunt — any error
escaping a step aborts the engine and "after an abort the engine is
unusable" (engine.py). That is the right primitive (donated pool pages
cannot be trusted after a failed dispatch) but the wrong place to stop:
production serving treats failure as routine, the way the training tier
already does (checkpoint retries, elastic resize, parameter-server HA).
This module layers the routine-failure story on top:

* :class:`ServingOverloadError` — the classified load-shedding signal.
  ``submit()`` raises it instead of enqueueing when the admission queue
  is at ``MXNET_SERVING_MAX_QUEUE``, the engine is draining, or the
  supervisor is mid-restart. It carries a ``retry_after_s`` hint so
  serve.py can answer ``503`` with a ``Retry-After`` header and clients
  back off instead of piling onto a saturated engine.
* :func:`retry_after_s` — the hint itself, estimated from the windowed
  occupancy/latency/goodput gauges the observability layer maintains:
  roughly "how long until the present backlog has worked off".
* :class:`EngineSupervisor` — wraps an engine *factory*. When the engine
  aborts, the supervisor salvages still-live requests (the engine parks
  them via ``salvage_on_abort`` instead of failing them), waits out an
  exponential backoff, builds a replacement engine — warm, because the
  persistent compile cache keys are content-addressed and hit across
  engines — and resubmits the survivors. Their replay prefill rebuilds
  the KV state from prompt + emitted tokens, exactly like recompute
  preemption, so greedy decoding finishes them bit-identical to an
  uninterrupted run. A restart cap turns repeated aborts into a
  permanent failure that fails pending requests with the abort cause.

The supervisor is duck-typed over the engine surface it drives
(``run_loop``/``submit``/``abort``/``pop_salvaged``/``resubmit``/...)
and deliberately does NOT import the engine module — engine.py imports
this module for the error class, and the factory closes over the real
constructor at the call site (tools/serve.py, tests).

Lock order: supervisor lock is leaf-only held (never while calling into
the engine), so supervisor-lock -> engine-lock cycles cannot form.
"""
import threading
import time

from .. import telemetry
from ..analysis import witness
from ..base import MXNetError, env_float, env_int
from .scheduler import FAILED

__all__ = ["ServingOverloadError", "retry_after_s", "EngineSupervisor"]


class ServingOverloadError(MXNetError):
    """Load shed at submit: the request was REJECTED, not enqueued.

    ``reason`` classifies the shed — ``"queue_full"`` (admission queue at
    its bound), ``"draining"`` (shutdown in progress), ``"restarting"``
    (supervisor rebuilding the engine) — and ``retry_after_s`` is the
    backoff hint serve.py forwards as the ``Retry-After`` header."""

    def __init__(self, msg, reason="queue_full", retry_after_s=1.0):
        super().__init__(msg)
        self.reason = str(reason)
        self.retry_after_s = float(retry_after_s)


def retry_after_s(engine, default_s=1.0, max_s=60.0):
    """Client backoff hint: estimated seconds until the engine's current
    backlog has worked off, from the gauges the observability layer
    already maintains — backlog depth over batch slots gives the number
    of "waves" ahead of a retry, the windowed latency p50 prices a wave,
    and sub-1.0 goodput (the engine is missing its SLOs) stretches the
    hint so a struggling engine is not told "come right back". Clamped
    to [default_s, max_s]; any missing gauge degrades to ``default_s``
    (a cold engine has no latency history — and no backlog either)."""
    try:
        backlog = (len(engine.scheduler.waiting)
                   + len(engine.scheduler.running))
        slots = max(1, int(engine.config.max_batch))
        eid = str(engine.engine_id)
    except AttributeError:
        return default_s
    p50 = telemetry.histogram("serving.request_latency_seconds",
                              engine=eid).percentile(50)
    if not p50 or p50 <= 0.0:
        p50 = default_s
    waves = max(1, -(-backlog // slots))   # ceil without math import
    hint = waves * p50
    goodput = telemetry.gauge("serving.goodput", engine=eid).value
    if goodput and 0.0 < goodput < 1.0:
        hint /= max(goodput, 0.25)
    return round(min(max(hint, default_s), max_s), 3)


class EngineSupervisor:
    """Restart-supervised serving engine (one engine live at a time).

    ``factory`` is a zero-argument callable returning a fresh, ready
    engine; the supervisor owns the current instance (``.engine``) and
    re-invokes the factory after an abort. Warmth across restarts is the
    factory's job and comes for free when the engine's compile cache is
    enabled: the persistent cache keys are content-addressed (no engine
    nonce), so the replacement engine loads every bucket's serialized
    executable instead of compiling.

    Drive it exactly like an engine: ``run_loop`` on one driver thread,
    ``submit``/``cancel`` from any thread. ``run_loop`` returns only on
    a clean stop; it re-raises the abort cause once the restart budget
    (``MXNET_SERVING_MAX_RESTARTS``) is exhausted, so a driver thread's
    death stays observable (serve.py's ``/healthz``)."""

    def __init__(self, factory, max_restarts=None, backoff_s=None,
                 backoff_max_s=None):
        self.factory = factory
        self.max_restarts = int(
            max_restarts if max_restarts is not None
            else env_int("MXNET_SERVING_MAX_RESTARTS", 3))
        self.backoff_s = float(
            backoff_s if backoff_s is not None
            else env_float("MXNET_SERVING_RESTART_BACKOFF_MS", 100.0)
            / 1000.0)
        self.backoff_max_s = float(
            backoff_max_s if backoff_max_s is not None
            else env_float("MXNET_SERVING_RESTART_BACKOFF_MAX_MS", 5000.0)
            / 1000.0)
        self._lock = threading.Lock()
        self._lock = witness.declare(
            "mxnet_tpu.serving.resilience.EngineSupervisor._lock", self._lock)
        self._restarts = 0
        self._restarting = False
        self._failed_msg = None     # permanent: restart budget exhausted
        self._last_error = None
        self._draining = False
        self._engine = factory()
        self._engine.salvage_on_abort = True

    # ---- state ---------------------------------------------------------
    @property
    def engine(self):
        """The live engine (replaced across restarts — do not cache)."""
        with self._lock:
            return self._engine

    @property
    def restarts(self):
        with self._lock:
            return self._restarts

    @property
    def last_error(self):
        with self._lock:
            return self._last_error

    @property
    def failed(self):
        """Permanent-failure cause, or None while restarts remain."""
        with self._lock:
            return self._failed_msg

    @property
    def restarting(self):
        with self._lock:
            return self._restarting

    @property
    def draining(self):
        with self._lock:
            return self._draining

    # ---- engine surface ------------------------------------------------
    def submit(self, *args, **kwargs):
        """Proxy to the live engine. During a restart window new work is
        shed (``reason="restarting"``, retry hint = the backoff in
        flight) — the queue the dead engine held is being replayed, not
        accepting. After permanent failure submits raise the abort cause
        like a bare aborted engine would."""
        with self._lock:
            eng = self._engine
            failed = self._failed_msg
            restarting = self._restarting
        if failed is not None:
            raise RuntimeError(failed)
        if restarting:
            raise ServingOverloadError(
                "engine restarting after abort", reason="restarting",
                retry_after_s=max(self.backoff_s, 0.05))
        try:
            return eng.submit(*args, **kwargs)
        except RuntimeError as exc:
            # the engine aborted between our snapshot and the enqueue;
            # unless the budget is gone the restart loop will replace it,
            # so advertise a transient overload, not permanent death
            with self._lock:
                failed = self._failed_msg
            if failed is not None:
                raise RuntimeError(failed) from exc
            raise ServingOverloadError(
                str(exc), reason="restarting",
                retry_after_s=max(self.backoff_s, 0.05)) from exc

    def cancel(self, req):
        self.engine.cancel(req)

    def cancel_all(self):
        return self.engine.cancel_all()

    def has_work(self):
        with self._lock:
            if self._restarting:
                return True     # salvaged requests await the replacement
            eng = self._engine
        return eng.has_work()

    def pop_finished(self):
        return self.engine.pop_finished()

    def start_drain(self):
        """Close admission on the live engine and every future
        replacement (a restart mid-drain must not reopen the doors)."""
        with self._lock:
            self._draining = True
            eng = self._engine
        eng.start_drain()

    def stats(self):
        """The live engine's stats() plus a ``supervisor`` block."""
        out = self.engine.stats()
        with self._lock:
            out["supervisor"] = {
                "restarts": self._restarts,
                "max_restarts": self.max_restarts,
                "restarting": self._restarting,
                "failed": self._failed_msg,
                "last_error": self._last_error,
                "draining": self._draining,
            }
        return out

    # ---- the supervision loop ------------------------------------------
    def run_loop(self, stop_event=None, idle_wait_s=0.05):
        """Drive the live engine; on abort, salvage + backoff + rebuild +
        resubmit, up to ``max_restarts`` times. Returns when
        ``stop_event`` is set; re-raises the final abort cause once the
        budget is exhausted (after failing every salvaged request)."""
        while stop_event is None or not stop_event.is_set():
            with self._lock:
                eng = self._engine
            try:
                eng.run_loop(stop_event, idle_wait_s=idle_wait_s)
                if stop_event is None or stop_event.is_set():
                    return
                continue
            except Exception as exc:
                if not self._recover(eng, exc, stop_event):
                    raise

    def _recover(self, eng, exc, stop_event):
        """One abort's recovery. Returns True when a replacement engine
        is live (loop continues), False when the failure is permanent or
        shutdown interrupted the restart (caller re-raises)."""
        salvaged = eng.pop_salvaged()
        cause = eng.aborted or ("serving engine aborted: %r" % (exc,))
        with self._lock:
            self._last_error = cause
            self._restarts += 1
            n = self._restarts
            permanent = n > self.max_restarts
            if permanent:
                self._failed_msg = (
                    "serving engine permanently failed (restart budget "
                    "%d exhausted): %s" % (self.max_restarts, cause))
                msg = self._failed_msg
            else:
                self._restarting = True
        if permanent:
            telemetry.event("serving.engine_restart", engine=eng.engine_id,
                            outcome="gave_up", restarts=n - 1,
                            error=cause)
            self._fail_salvaged(eng, salvaged, msg)
            return False
        backoff = min(self.backoff_s * (2.0 ** (n - 1)), self.backoff_max_s)
        telemetry.counter("serving.restarts").inc()
        telemetry.event("serving.engine_restart", engine=eng.engine_id,
                        outcome="restarting", restart=n,
                        backoff_s=round(backoff, 3),
                        salvaged=len(salvaged), error=cause)
        interrupted = (stop_event.wait(backoff) if stop_event is not None
                       else (time.sleep(backoff) or False))
        if interrupted:
            # shutdown won the race: wake the salvaged waiters honestly
            self._fail_salvaged(eng, salvaged,
                                "shutdown during engine restart: " + cause)
            with self._lock:
                self._restarting = False
            return False
        new_eng = self.factory()
        new_eng.salvage_on_abort = True
        with self._lock:
            draining = self._draining
        if draining:
            new_eng.start_drain()
        for req in salvaged:    # original submit order: FCFS is preserved
            new_eng.resubmit(req)
        with self._lock:
            self._engine = new_eng
            self._restarting = False
        return True

    @staticmethod
    def _fail_salvaged(eng, salvaged, msg):
        """Terminal path for requests that survived the abort but not
        the supervisor: fail them with the classified cause through the
        dead engine's obs so their traces close and waiters wake."""
        now = time.time()
        for req in salvaged:
            req.state = FAILED
            req.error = msg
            req.finish_t = now
            telemetry.counter("serving.requests_failed").inc()
            eng.obs.request_finished(req, failed=True)
            if req.done_event is not None:
                req.done_event.set()
