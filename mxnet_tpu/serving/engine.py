"""ServingEngine — the standing inference engine's Python API.

One engine owns: the weights (a trained checkpoint's ``arg_params`` or
deterministic ``random_params``), one :class:`~.kv_cache.KVBlockPool`, one
:class:`~.scheduler.Scheduler`, and exactly TWO compileobs-tracked XLA
programs — ``serving.prefill`` and ``serving.decode`` — each compiled once
per padded shape bucket (prompt-length buckets for prefill, batch-size
buckets for decode) and replayed forever after: ``compileobs`` showing a
flat compile count after bucket warmup is the engine's no-recompile
acceptance gate.

Each :meth:`step` runs the scheduler's plan: admitted prompts prefill into
the shared block pool (one call per request at its length bucket), then
every decoding stream advances one token through the fused paged decode
step at the batch bucket. The ONLY device->host sync per step is the tiny
next-token vector — that read IS the product (tokens leave for clients);
everything else stays device-resident, pool pages donated call to call.

Thread model: ``submit()`` is safe from any thread (HTTP handlers);
``step()``/``run_loop()`` must run on one driver thread. Per-request
latency metrics (TTFT, end-to-end, tokens/sec) flow through the telemetry
registry — ``serving.*`` in docs/observability.md — and render live in
``tools/serve.py``'s stat columns.
"""
import itertools
import threading
import time
from collections import deque

import numpy as np

from .. import compile_cache, compileobs, fault, telemetry
from ..analysis import witness
from ..base import env_bool, env_int, env_str
from . import model as _model
from .kv_cache import KVBlockPool
from .obs import ServingObs
from .resilience import ServingOverloadError, retry_after_s
from .scheduler import (CANCELLED, DECODING, FAILED, FINISHED, TIMED_OUT,
                        WAITING, Request, Scheduler)

_SITE = "serving/engine.py"

_engine_ids = itertools.count()


class ServingConfig(_model.ModelConfig):
    """Model shape + engine knobs. Engine knobs default from the
    ``MXNET_SERVING_*`` environment (docs/env_var.md)."""

    __slots__ = ("block_size", "num_blocks", "max_batch",
                 "prefills_per_step", "kv_dtype", "prefix_cache",
                 "spec_k", "draft", "max_queue", "default_timeout_ms")

    def __init__(self, vocab_size=32000, num_layers=4, model_dim=256,
                 num_heads=4, ffn_dim=1024, max_len=128,
                 block_size=None, num_blocks=None, max_batch=None,
                 prefills_per_step=None, kv_dtype=np.float32,
                 prefix_cache=None, spec_k=None, draft=None,
                 max_queue=None, default_timeout_ms=None):
        super().__init__(vocab_size, num_layers, model_dim, num_heads,
                         ffn_dim, max_len)
        self.block_size = int(block_size if block_size is not None
                              else env_int("MXNET_SERVING_BLOCK_SIZE", 16))
        self.num_blocks = int(num_blocks if num_blocks is not None
                              else env_int("MXNET_SERVING_NUM_BLOCKS", 257))
        self.max_batch = int(max_batch if max_batch is not None
                             else env_int("MXNET_SERVING_MAX_BATCH", 32))
        self.prefills_per_step = int(
            prefills_per_step if prefills_per_step is not None
            else env_int("MXNET_SERVING_PREFILLS_PER_STEP", 4))
        self.kv_dtype = np.dtype(kv_dtype)
        # prefix sharing (docs/serving.md §prefix-sharing): content-hash
        # full prefill blocks so same-prefix admissions map cached blocks
        # (refcounted, copy-on-write) instead of re-caching them
        self.prefix_cache = bool(
            prefix_cache if prefix_cache is not None
            else env_bool("MXNET_SERVING_PREFIX_CACHE", True))
        # speculative decoding (docs/serving.md §speculative-decoding):
        # spec_k > 0 turns it on — a draft LM proposes spec_k tokens per
        # step, the target scores all spec_k+1 window positions in one
        # multi-query verify pass, greedy acceptance keeps the emitted
        # stream bit-identical to target-only decoding
        self.spec_k = int(spec_k if spec_k is not None
                          else env_int("MXNET_SERVING_SPEC_K", 0))
        if self.spec_k < 0:
            raise ValueError("spec_k must be >= 0 (0 disables speculative "
                             "decoding)")
        self.draft = str(draft if draft is not None
                         else env_str("MXNET_SERVING_DRAFT", "self"))
        # resilience knobs (docs/serving.md §resilience): a bounded
        # admission queue sheds load at submit instead of letting the
        # WAITING deque grow without limit, and a default deadline bounds
        # how long any request may live without the client asking
        self.max_queue = int(max_queue if max_queue is not None
                             else env_int("MXNET_SERVING_MAX_QUEUE", 0))
        if self.max_queue < 0:
            raise ValueError("max_queue must be >= 0 (0 = unbounded)")
        self.default_timeout_ms = int(
            default_timeout_ms if default_timeout_ms is not None
            else env_int("MXNET_SERVING_DEFAULT_TIMEOUT_MS", 0))
        if self.default_timeout_ms < 0:
            raise ValueError("default_timeout_ms must be >= 0 (0 = no "
                             "default deadline)")
        if self.max_len % self.block_size:
            raise ValueError(
                "max_len (%d) must be a multiple of block_size (%d): "
                "prefill buckets and the decode block table are sized in "
                "whole blocks" % (self.max_len, self.block_size))

    def decode_buckets(self):
        """Padded decode batch sizes: powers of two up to max_batch."""
        out = []
        b = 1
        while b < self.max_batch:
            out.append(b)
            b *= 2
        out.append(self.max_batch)
        return out

    def prefill_buckets(self):
        """Padded prompt lengths: block_size doublings up to max_len."""
        out = []
        s = self.block_size
        while s < self.max_len:
            out.append(s)
            s *= 2
        out.append(self.max_len)
        return out


def _bucket_for(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    raise ValueError("no bucket holds %d (buckets %s)" % (n, buckets))


class ServingEngine:
    """Continuous-batching inference over the Transformer-LM zoo model."""

    def __init__(self, config, arg_params=None, seed=0, device=None,
                 enable_telemetry=True):
        if enable_telemetry:
            telemetry.enable()
        self.config = cfg = config
        if arg_params is None:
            arg_params = _model.random_params(cfg, seed=seed)
        self.params = _model.as_device_params(arg_params, cfg, device=device)
        self.pool = KVBlockPool(cfg.num_layers, cfg.num_blocks,
                                cfg.block_size, cfg.num_heads,
                                cfg.model_dim // cfg.num_heads,
                                dtype=cfg.kv_dtype, device=device,
                                prefix_cache=cfg.prefix_cache)
        # speculative decoding writes spec_k+1 window slots per step, so
        # headroom lookahead covers the whole draft+verify window
        self._spec = cfg.spec_k > 0
        self.spec_k = cfg.spec_k
        self.scheduler = Scheduler(self.pool, max_batch=cfg.max_batch,
                                   prefills_per_step=cfg.prefills_per_step,
                                   lookahead=cfg.spec_k + 1,
                                   max_positions=cfg.max_len)
        self._nb_max = cfg.max_len // cfg.block_size
        self._lock = threading.RLock()
        # separate statement: lockgraph keys the lock to the ctor line
        # above; the witness proxy is identity-transparent when off
        self._lock = witness.declare(
            "mxnet_tpu.serving.engine.ServingEngine._lock", self._lock)
        self._work = threading.Condition(self._lock)
        # retired requests awaiting pop_finished(), BOUNDED: a driver
        # that consumes done_events instead (serve.py) would otherwise
        # leak one Request per call served for the life of the server.
        # A polling driver draining every step never hits the cap — a
        # step retires at most max_batch streams plus a handful of
        # admission failures; only a mass abort can shed the oldest
        # entries, and those waiters were already woken via done_event.
        self._finished = deque(maxlen=max(256, 8 * cfg.max_batch))
        self._aborted = None
        self._draining = False
        # supervisor contract (resilience.EngineSupervisor): when set,
        # abort() parks still-salvageable inflight requests in _salvaged
        # (blocks dropped, tokens-so-far kept as a replay prompt) instead
        # of failing them, so a fresh engine can resubmit() them and —
        # greedy decode — finish them bit-identical to an unfaulted run
        self.salvage_on_abort = False
        self._salvaged = []
        self._steps = 0
        # per-engine tallies: the registry counters with the same names
        # are process-global and would attribute a previous engine's
        # traffic to this one in stats()
        self._n_completed = 0
        self._n_failed = 0
        self._n_timed_out = 0
        self._n_cancelled = 0
        self._n_shed = 0
        self._token_window = []   # one timestamp per token, for tokens/sec
        self._t_started = time.time()
        self._tokens_total = 0
        # per-engine identity: labels this engine's histograms/counters in
        # the process-global registry (stats() reads ONLY its own label)
        # and salts the graph keys below
        self.engine_id = next(_engine_ids)
        self.obs = ServingObs(self.engine_id)

        # donation frees the pool's previous pages the moment the step
        # consumes them — without it every step would briefly double the
        # pool's device footprint (CPU backends ignore donation; harmless)
        import jax

        donate = {} if jax.default_backend() == "cpu" else \
            {"donate_argnums": (4, 5)}
        # the engine nonce is part of the graph identity: a second engine
        # in the same process (even one with an IDENTICAL config) holds
        # fresh function objects, so its bucket warmup compiles again —
        # under a shared graph key that warmup would diff against the
        # first engine's signatures and misreport as compile.recompile
        # (cause=placement; cause=dtype when only kv_dtype differs)
        gkey = ("serving", self.engine_id) + cfg.key() + (
            cfg.block_size, cfg.num_blocks, str(cfg.kv_dtype))

        # fresh function objects per bucket (factories, not one shared
        # closure): jax's tracing cache is keyed on the wrapped function,
        # so bucket wrappers sharing one function would share one cache
        # and each wrapper's cache-size delta would misfire on the
        # others' compiles
        def _mk_prefill():
            def _prefill(params, tokens, length, block_table,
                         k_pages, v_pages):
                return _model.prefill(params, tokens, length, block_table,
                                      k_pages, v_pages, cfg)
            return _prefill

        def _mk_decode():
            def _decode(params, tokens, positions, block_tables,
                        context_lens, k_pages, v_pages):
                return _model.decode(params, tokens, positions,
                                     block_tables, context_lens,
                                     k_pages, v_pages, cfg)
            return _decode

        if donate:
            decode_donate = {"donate_argnums": (5, 6)}
        else:
            decode_donate = {}
        # one wrapper per shape bucket: buckets are DESIGNED to each
        # compile once, so a bucket's first compile must not diff against
        # another bucket's signature under a shared graph key — that would
        # report routine warmup as compile.recompile (the counter
        # operators alarm on) with a WARNING per bucket. Per-bucket keys
        # reserve the recompile stream for a bucket compiling AGAIN.
        #
        # cache_key drops the per-engine NONCE from the graph key: the
        # persistent compile cache must hit across processes (and across
        # engines of identical config), so its identity is pure content —
        # model shape + pool geometry + bucket. aot=True: each bucket is a
        # single-signature site, the serialized-executable fast lane — a
        # warm replica's warmup() loads every bucket from disk instead of
        # compiling it (tools/serve.py --warmup, bench_serving warmup_s).
        ckey_base = cfg.key() + (cfg.block_size, cfg.num_blocks,
                                 str(cfg.kv_dtype))
        self._prefill_jits = {
            S: compileobs.jit(_mk_prefill(), "serving.prefill", site=_SITE,
                              graph_key=gkey + ("prefill", S), aot=True,
                              cache_key=("serving.prefill",) + ckey_base
                              + (S,), **donate)
            for S in cfg.prefill_buckets()}
        self._decode_jits = {
            B: compileobs.jit(_mk_decode(), "serving.decode", site=_SITE,
                              graph_key=gkey + ("decode", B), aot=True,
                              cache_key=("serving.decode",) + ckey_base
                              + (B,), **decode_donate)
            for B in cfg.decode_buckets()}
        # bucket dispatch: call sites pad to an exact bucket shape, so the
        # padded dims index the wrapper table directly
        self._prefill_fn = lambda params, toks, L, table, kp, vp: \
            self._prefill_jits[toks.shape[1]](params, toks, L, table,
                                              kp, vp)
        self._decode_fn = lambda params, toks, poss, tables, ctx, kp, vp: \
            self._decode_jits[toks.shape[0]](params, toks, poss, tables,
                                             ctx, kp, vp)

        # ---- speculative decoding: draft model + verify pass ----------
        # two more compileobs program families riding the same nonce-free
        # persistent-cache pattern: `serving.draft` (the proposal model's
        # prefill + one-token decode over its own pages) and
        # `serving.verify` (the target scoring all spec_k+1 window
        # positions in ONE multi-query paged-attention pass). Fixed k per
        # engine: the verify window is a static shape, so compile counts
        # stay flat after bucket warmup — no per-k recompiles.
        self._draft_params = None
        self._draft_kp = self._draft_vp = None
        self._spec_proposed = 0
        self._spec_accepted = 0
        self._spec_draft_s = 0.0
        self._spec_verify_s = 0.0
        if self._spec:
            dcfg = _model.draft_config(cfg, cfg.draft)
            self.draft_config = dcfg
            if dcfg.key() == cfg.key():
                # self-drafting: the draft IS the target (shared device
                # params) — proposals match the verify pass and
                # acceptance sits near 1.0 (the test harness's mode)
                self._draft_params = self.params
            else:
                self._draft_params = _model.as_device_params(
                    _model.random_params(dcfg, seed=seed), dcfg,
                    device=device)
            import jax.numpy as jnp

            dshape = (dcfg.num_layers, cfg.num_blocks, cfg.block_size,
                      dcfg.num_heads, dcfg.model_dim // dcfg.num_heads)
            dk = jnp.zeros(dshape, cfg.kv_dtype)
            dv = jnp.zeros(dshape, cfg.kv_dtype)
            if device is not None:
                dk = jax.device_put(dk, device)
                dv = jax.device_put(dv, device)
            self._draft_kp, self._draft_vp = dk, dv

            def _mk_draft_prefill():
                def _dprefill(params, tokens, length, block_table,
                              k_pages, v_pages):
                    return _model.prefill(params, tokens, length,
                                          block_table, k_pages, v_pages,
                                          dcfg)
                return _dprefill

            def _mk_draft_decode():
                def _ddecode(params, tokens, positions, block_tables,
                             context_lens, k_pages, v_pages):
                    return _model.decode(params, tokens, positions,
                                         block_tables, context_lens,
                                         k_pages, v_pages, dcfg)
                return _ddecode

            def _mk_verify():
                def _verify(params, tokens, positions, block_tables,
                            context_lens, k_pages, v_pages):
                    return _model.extend(  # fwlint: disable=trace-impure — module-level verify-step function, not a container mutation
                        params, tokens, positions, block_tables,
                        context_lens, k_pages, v_pages, cfg)
                return _verify

            dkey_base = dcfg.key() + (cfg.block_size, cfg.num_blocks,
                                      str(cfg.kv_dtype))
            self._draft_prefill_jits = {
                S: compileobs.jit(_mk_draft_prefill(), "serving.draft",
                                  site=_SITE,
                                  graph_key=gkey + ("draft.prefill", S),
                                  aot=True,
                                  cache_key=("serving.draft.prefill",)
                                  + dkey_base + (S,), **donate)
                for S in cfg.prefill_buckets()}
            self._draft_decode_jits = {
                B: compileobs.jit(_mk_draft_decode(), "serving.draft",
                                  site=_SITE,
                                  graph_key=gkey + ("draft.decode", B),
                                  aot=True,
                                  cache_key=("serving.draft.decode",)
                                  + dkey_base + (B,), **decode_donate)
                for B in cfg.decode_buckets()}
            self._verify_jits = {
                B: compileobs.jit(_mk_verify(), "serving.verify",
                                  site=_SITE,
                                  graph_key=gkey + ("verify", B, cfg.spec_k),
                                  aot=True,
                                  cache_key=("serving.verify",) + ckey_base
                                  + (B, cfg.spec_k), **decode_donate)
                for B in cfg.decode_buckets()}
            self._draft_prefill_fn = \
                lambda params, toks, L, table, kp, vp: \
                self._draft_prefill_jits[toks.shape[1]](
                    params, toks, L, table, kp, vp)
            self._draft_decode_fn = \
                lambda params, toks, poss, tables, ctx, kp, vp: \
                self._draft_decode_jits[toks.shape[0]](
                    params, toks, poss, tables, ctx, kp, vp)
            self._verify_fn = \
                lambda params, toks, poss, tables, ctx, kp, vp: \
                self._verify_jits[toks.shape[0]](
                    params, toks, poss, tables, ctx, kp, vp)

    # ------------------------------------------------------------------ API
    def submit(self, prompt, max_new_tokens, eos_id=None, request_id=None,
               timeout_s=None):
        """Enqueue a request; returns the :class:`Request` (its
        ``done_event`` is set when it finishes — block on it from serving
        threads, or drive :meth:`step` yourself). ``request_id`` is the
        wire identity threaded through every lifecycle event and trace
        lane (auto-assigned from the rid when omitted). ``timeout_s``
        sets the request's deadline (default from
        ``MXNET_SERVING_DEFAULT_TIMEOUT_MS``; None/0 = none): once it
        expires the request is swept to TIMED_OUT and its KV blocks
        return to the pool. Raises :class:`ServingOverloadError` (with a
        ``retry_after_s`` hint) when the engine is draining or the
        admission queue is at ``cfg.max_queue`` — shed, not enqueued."""
        if timeout_s is None and self.config.default_timeout_ms > 0:
            timeout_s = self.config.default_timeout_ms / 1000.0
        req = Request(prompt, max_new_tokens, eos_id=eos_id,
                      request_id=request_id, timeout_s=timeout_s)
        total = len(req.prompt) + req.max_new_tokens
        if total > self.config.max_len:
            raise ValueError(
                "request needs %d total positions > max_len %d (the "
                "position-embedding table bounds every stream)"
                % (total, self.config.max_len))
        if self.pool.blocks_for(total) > self.pool.num_usable:
            raise ValueError(
                "request needs %d KV blocks > pool capacity %d"
                % (self.pool.blocks_for(total), self.pool.num_usable))
        req.done_event = threading.Event()
        with self._work:
            # checked under the lock: an abort() racing an unlocked check
            # could drain the queues first, leaving this request enqueued
            # behind a dead driver with a done_event nobody will ever set
            if self._aborted is not None:
                raise RuntimeError(self._aborted)
            if self._draining:
                telemetry.counter("serving.shed").inc()
                self._n_shed += 1
                raise ServingOverloadError(
                    "engine is draining (admission closed)",
                    reason="draining",
                    retry_after_s=retry_after_s(self))
            if (self.config.max_queue
                    and len(self.scheduler.waiting) >= self.config.max_queue):
                telemetry.counter("serving.shed").inc()
                self._n_shed += 1
                raise ServingOverloadError(
                    "admission queue full (%d waiting >= max_queue %d)"
                    % (len(self.scheduler.waiting), self.config.max_queue),
                    reason="queue_full",
                    retry_after_s=retry_after_s(self))
            self.obs.request_submitted(req)
            self.scheduler.add(req)
            self._work.notify_all()
        return req

    def cancel(self, req):
        """Mark ``req`` for cancellation (safe from any thread — serve.py
        calls it when the client connection drops). The next step's sweep
        moves it to CANCELLED and frees its KV blocks; a WAITING request
        is dropped without ever being admitted. No-op once terminal."""
        with self._work:
            if not req.finished():
                req.cancelled = True
                self._work.notify_all()

    def cancel_all(self):
        """Cancel every non-terminal request (the drain deadline passed:
        stragglers are cut loose rather than holding the process open).
        Returns the number marked."""
        with self._work:
            n = 0
            for req in (list(self.scheduler.running)
                        + list(self.scheduler.waiting)):
                if not req.finished():
                    req.cancelled = True
                    n += 1
            if n:
                self._work.notify_all()
            return n

    def start_drain(self):
        """Close admission: new submits are shed with
        ``reason="draining"`` while inflight work keeps stepping to
        completion. ``has_work()`` going False signals the drain is done
        (serve.py's drain sequence; idempotent)."""
        with self._work:
            if not self._draining:
                self._draining = True
                telemetry.counter("serving.drains").inc()
                telemetry.event("serving.drain", engine=self.engine_id,
                                waiting=len(self.scheduler.waiting),
                                active=len(self.scheduler.running))
                self._work.notify_all()

    @property
    def draining(self):
        # under the lock: handler threads poll this against the driver's
        # locked writes — an unlocked read observes the flag torn against
        # the drain bookkeeping it summarizes (fwlint unguarded-shared-write)
        with self._lock:
            return self._draining

    @property
    def aborted(self):
        """The abort cause message, or None while the engine is live."""
        with self._lock:
            return self._aborted

    def has_work(self):
        with self._lock:
            return self.scheduler.has_work()

    def step(self):
        """One engine iteration: schedule, prefill admissions, fused decode,
        retire finished requests. Returns the requests that finished.

        A failure escaping the step (device error, XLA crash) aborts the
        engine before re-raising — the pool pages may have been donated
        into the failed dispatch and cannot be trusted, so EVERY driver
        (run_loop, :meth:`generate`, bench/step-polling loops) gets the
        same contract: pending requests fail loudly, waiters wake, later
        submits refuse."""
        try:
            with self._lock, telemetry.span("serving.step"):
                # chaos: injected per-step latency (trips deadlines/SLOs
                # without faking clocks) — docs/fault_tolerance.md
                # fwlint: disable=lock-order — the injected delay models a slow device dispatch, which blocks under the step lock by design
                fault.hit("slow_step")
                # deadline/cancellation sweep BEFORE scheduling: expired
                # or abandoned requests release their KV blocks this step
                # instead of decoding on for a consumer that is gone, and
                # _drain_failed below routes them out the public channels
                self.scheduler.sweep()
                plan = self.scheduler.schedule()
                for req in plan.preempted:
                    self.obs.request_preempted(req)
                for req in plan.prefills:
                    self.obs.request_admitted(req)
                failed = self._drain_failed()
                if plan.empty():
                    return failed
                for req in plan.prefills:
                    # fwlint: disable=lock-order — fault.hit("dispatch_error") in the callee can inject a delay; real dispatch blocks under the step lock identically
                    self._run_prefill(req)
                n_preempted = len(plan.preempted)
                if plan.prefills:
                    # a prompt that exactly filled its blocks writes its
                    # first decode token at a fresh block boundary — back
                    # that slot with a real block NOW or the write lands in
                    # trash and the position's K/V is silently lost
                    late = self.scheduler.ensure_decode_headroom()
                    for req in late:
                        self.obs.request_preempted(req)
                    n_preempted += len(late)
                    failed += self._drain_failed()
                decodes = self.scheduler.decodable()
                if decodes:
                    # copy-on-write safety net: a write slot backed by a
                    # SHARED block gets a private bit-exact copy first
                    # (structurally unreachable — prefix matches cover
                    # only full replay blocks, writes land past them —
                    # but the pool invariant must hold unconditionally)
                    self._cow_guard(decodes)
                    if self._spec:
                        # fwlint: disable=lock-order — injected dispatch fault may stall; matches real device-dispatch blocking under the step lock
                        self._run_spec_decode(decodes)
                    else:
                        # fwlint: disable=lock-order — injected dispatch fault may stall; matches real device-dispatch blocking under the step lock
                        self._run_decode(decodes)
                finished = [r for r in list(self.scheduler.running)
                            if r.finished()]
                for req in finished:
                    self.scheduler.finish(req)
                    self._retire(req)
                self._steps += 1
                self._refresh_throughput()
                self.obs.step_timeline(
                    step=self._steps, occupancy=len(decodes),
                    admitted=len(plan.prefills), preempted=n_preempted,
                    finished=len(finished) + len(failed),
                    queue=len(self.scheduler.waiting),
                    running=len(self.scheduler.running),
                    kv_used=self.pool.used(), kv_free=self.pool.available(),
                    kv_frag_slots=self.scheduler.frag_slots())
                return finished + failed
        except Exception as exc:
            self.abort(exc)
            raise

    def run_loop(self, stop_event=None, idle_wait_s=0.05):
        """Drive :meth:`step` until ``stop_event`` is set, sleeping on the
        submit condition while idle (the serve.py driver thread).

        A step failure must not strand clients blocked on their
        ``done_event`` forever behind a silently dead driver: ``step()``
        itself aborts the engine — every queued + running request is
        FAILED with the error and woken, later submits refuse — and the
        re-raise propagates here so the driver thread's death is
        observable (``Thread.is_alive()`` backs serve.py's
        ``/healthz``)."""
        while stop_event is None or not stop_event.is_set():
            with self._work:
                if not self.scheduler.has_work():
                    # idle steps never run, so decay the sliding
                    # tokens/sec window here or it freezes at its last
                    # loaded value on a quiet server
                    self._refresh_throughput()
                    self._work.wait(timeout=idle_wait_s)
                    if not self.scheduler.has_work():
                        continue
            self.step()

    def abort(self, exc):
        """Fail every queued and running request (the driver died mid-
        step, or the caller is shutting down hard). After an abort the
        engine refuses new submits — the pool pages may have been donated
        into the failed dispatch and cannot be trusted.

        Under a supervisor (``salvage_on_abort`` set), non-terminal
        requests are PARKED instead of failed: blocks dropped (the pool
        dies with the engine), tokens-so-far kept, done_event left unset
        — :meth:`pop_salvaged` hands them to the supervisor, which
        :meth:`resubmit`-s them into a fresh engine where the replay
        prefill (recompute-preemption style) rebuilds their cache and
        greedy decode finishes them bit-identical to an unfaulted run."""
        msg = "serving engine aborted: %r" % (exc,)
        with self._lock:
            self._aborted = msg
            self._drain_failed()   # scheduler failures the step never saw
            reqs = list(self.scheduler.running) + list(self.scheduler.waiting)
            self.scheduler.running.clear()
            self.scheduler.waiting.clear()
            if self.salvage_on_abort:
                now = time.time()
                for req in reqs:
                    if req.finished():
                        continue
                    was_running = req.state != WAITING
                    req.blocks = []   # pool accounting is moot post-abort
                    req.shared_blocks = 0
                    req.context_len = 0
                    req.state = WAITING
                    if was_running:
                        # the restart wall is replay overhead, same clock
                        # as recompute preemption — the 5-phase sum still
                        # partitions the request's end-to-end wall
                        req.preemptions += 1
                        req.preempted_t = now
                        telemetry.counter("serving.preemptions").inc()
                        self.obs.request_preempted(req)
                    self._salvaged.append(req)
                return
            for req in reqs:
                req.blocks = []   # pool accounting is moot post-abort
                req.state = FAILED
                req.error = msg
                req.finish_t = time.time()
                telemetry.counter("serving.requests_failed").inc()
                self.obs.request_finished(req, failed=True)
                if req.done_event is not None:
                    req.done_event.set()
            self._finished.extend(reqs)
            self._n_failed += len(reqs)

    def pop_salvaged(self):
        """Drain the requests :meth:`abort` parked for the supervisor
        (empty unless ``salvage_on_abort`` was set before the abort)."""
        with self._lock:
            out, self._salvaged = self._salvaged, []
            return out

    def resubmit(self, req):
        """Re-admit a request salvaged from a dead engine: it keeps its
        identity, done_event, trace clock, and generated-so-far tokens —
        ``replay_tokens()`` re-prefills prompt + emitted tokens exactly
        like a recompute preemption, so greedy decode continues the
        stream bit-identically. The supervisor calls this on the FRESH
        engine for every survivor, in original submit order."""
        with self._work:
            if self._aborted is not None:
                raise RuntimeError(self._aborted)
            telemetry.event("serving.request", request_id=req.request_id,
                            engine=self.engine_id, state="resubmitted",
                            generated=len(req.generated),
                            preemptions=req.preemptions)
            self.scheduler.add(req)
            self._work.notify_all()
        return req

    def warmup(self):
        """Compile every prefill length bucket and decode batch bucket in
        one pass (one throwaway dispatch each, all-trash block tables, no
        requests involved) so the first real traffic pays zero compile
        wall and the steady-state compile count is flat from step one."""
        cfg = self.config
        with self._lock:
            for S in cfg.prefill_buckets():
                toks = np.zeros((1, S), np.int32)
                table = np.zeros(S // cfg.block_size, np.int32)
                _t, _l, kp, vp = self._prefill_fn(
                    self.params, toks, np.int32(1), table,
                    self.pool.k_pages, self.pool.v_pages)
                self.pool.k_pages, self.pool.v_pages = kp, vp
            for B in cfg.decode_buckets():
                toks = np.zeros(B, np.int32)
                poss = np.zeros(B, np.int32)
                tables = np.zeros((B, self._nb_max), np.int32)
                ctx = np.ones(B, np.int32)
                _t, _l, kp, vp = self._decode_fn(
                    self.params, toks, poss, tables, ctx,
                    self.pool.k_pages, self.pool.v_pages)
                self.pool.k_pages, self.pool.v_pages = kp, vp
            if self._spec:
                # spec adds three program families — warm them too or the
                # first spec step pays draft + verify compile wall at once
                for S in cfg.prefill_buckets():
                    toks = np.zeros((1, S), np.int32)
                    table = np.zeros(S // cfg.block_size, np.int32)
                    _t, _l, dkp, dvp = self._draft_prefill_fn(
                        self._draft_params, toks, np.int32(1), table,
                        self._draft_kp, self._draft_vp)
                    self._draft_kp, self._draft_vp = dkp, dvp
                T = self.spec_k + 1
                for B in cfg.decode_buckets():
                    toks = np.zeros(B, np.int32)
                    poss = np.zeros(B, np.int32)
                    tables = np.zeros((B, self._nb_max), np.int32)
                    ctx = np.ones(B, np.int32)
                    _t, _l, dkp, dvp = self._draft_decode_fn(
                        self._draft_params, toks, poss, tables, ctx,
                        self._draft_kp, self._draft_vp)
                    self._draft_kp, self._draft_vp = dkp, dvp
                    toks2 = np.zeros((B, T), np.int32)
                    poss2 = np.zeros((B, T), np.int32)
                    ctx2 = np.ones((B, T), np.int32)
                    _t, _l, kp, vp = self._verify_fn(
                        self.params, toks2, poss2, tables, ctx2,
                        self.pool.k_pages, self.pool.v_pages)
                    self.pool.k_pages, self.pool.v_pages = kp, vp

    def generate(self, prompts, max_new_tokens, eos_id=None, timeout_s=None):
        """Convenience batch API: submit every prompt, drive steps until
        all finish, return each request's generated tokens (in input
        order). Raises if any request failed.

        ``timeout_s`` bounds each request (threaded to :meth:`submit`):
        the per-step sweep moves expired requests to TIMED_OUT, so the
        drive loop terminates instead of decoding past a blown deadline.
        An abort — this loop's own step raising, or another thread
        killing the engine — surfaces as a RuntimeError carrying the
        classified cause rather than a silent spin."""
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * len(prompts)
        reqs = [self.submit(p, n, eos_id=eos_id, timeout_s=timeout_s)
                for p, n in zip(prompts, max_new_tokens)]
        while any(not r.finished() for r in reqs):
            # an external abort() cleared the scheduler queues but this
            # loop's snapshot still holds the requests — re-stepping a
            # dead engine forever would spin without ever finishing them
            msg = self.aborted   # locked read: abort() publishes under it
            if msg is not None:
                raise RuntimeError(msg)
            self.step()
        bad = [r for r in reqs if r.state != FINISHED]
        if bad:
            raise RuntimeError("requests failed: %s"
                               % [(r.rid, r.state, r.error) for r in bad])
        return [list(r.generated) for r in reqs]

    def pop_finished(self):
        """Drain every request retired since the last call — FINISHED and
        FAILED both (check ``req.state``/``req.error``); a polling driver
        must never lose a request to a silent scheduler-side failure.
        The backlog is bounded (``max(256, 8 * max_batch)``) so drivers
        that consume ``done_event`` instead of polling don't accumulate
        one retired Request per call served — drain at least once per
        step to observe every retiree."""
        with self._lock:
            out = list(self._finished)
            self._finished.clear()
            return out

    def _drain_failed(self):
        """Requests the scheduler terminated — FAILED, and since the
        resilience layer also TIMED_OUT/CANCELLED — surface through the
        same channels as successes: appended to the ``pop_finished()``
        queue and returned from :meth:`step`. ``_terminate`` already
        stamped ``finish_t``, bumped the per-state counter and woke the
        ``done_event``; obs reads the terminal state off the request."""
        failed = self.scheduler.pop_failed()
        for req in failed:
            self.obs.request_finished(req)
            if req.state == TIMED_OUT:
                self._n_timed_out += 1
            elif req.state == CANCELLED:
                self._n_cancelled += 1
            else:
                self._n_failed += 1
        self._finished.extend(failed)
        return failed

    # ------------------------------------------------------------ internals
    def _table_row(self, req, width):
        # the admission grant includes the first decode slot's headroom
        # block, so a boundary-length replay holds one block more than its
        # prefill bucket's table width — clip; prefill never reads it
        row = np.zeros(width, np.int32)
        n = min(len(req.blocks), width)
        row[:n] = req.blocks[:n]
        return row

    def _run_prefill(self, req):
        cfg = self.config
        replay = req.replay_tokens()
        L = len(replay)
        S = _bucket_for(L, cfg.prefill_buckets())
        toks = np.zeros((1, S), np.int32)
        toks[0, :L] = replay
        table = self._table_row(req, S // cfg.block_size)
        # prefix sharing: blocks mapped from the index already hold this
        # prefix's K/V — route their WRITE entries to the trash block so
        # the scatter cannot touch a shared block (copy-on-write contract;
        # the logits are untouched, the table only steers the scatter)
        write_table = table
        if req.shared_blocks:
            write_table = table.copy()
            write_table[:min(req.shared_blocks, len(write_table))] = 0
        # compile-tally delta around the dispatch: a bump means THIS call
        # sat behind a cold prefill bucket — that wall is the request's
        # compile_stall, not honest prefill time
        jit = self._prefill_jits[S]
        c0, s0 = jit.compile_totals()
        s0 += self._draft_prefill_jits[S].compile_totals()[1] \
            if self._spec else 0.0
        # chaos: injected dispatch failure — escapes step(), which aborts
        # the engine (the supervisor's restart trigger in the chaos e2e)
        fault.hit("dispatch_error")
        t0 = time.time()
        tok, _logits, kp, vp = self._prefill_fn(
            self.params, toks, np.int32(L), write_table,
            self.pool.k_pages, self.pool.v_pages)
        self.pool.k_pages, self.pool.v_pages = kp, vp
        if self._spec:
            # the draft caches the same replay through the same write
            # table into its OWN pages (its K/V never mixes with the
            # target's); shared blocks were draft-cached by the prefix's
            # original prefill, same as the target pages
            _dt, _dl, dkp, dvp = self._draft_prefill_fn(
                self._draft_params, toks, np.int32(L), write_table,
                self._draft_kp, self._draft_vp)
            self._draft_kp, self._draft_vp = dkp, dvp
        # the per-step token egress: serving's output IS this transfer
        tok = int(np.asarray(tok)[0])  # fwlint: disable=device-escape — token egress to the client is the product, one scalar per prefill
        wall = time.time() - t0
        c1, s1 = jit.compile_totals()
        s1 += self._draft_prefill_jits[S].compile_totals()[1] \
            if self._spec else 0.0
        stall = min(s1 - s0, wall) if c1 > c0 or s1 > s0 else 0.0
        telemetry.histogram("serving.prefill_seconds").observe(wall)
        telemetry.counter("serving.prefill_tokens").inc(L)
        # register this prefix's full blocks for later admissions (first
        # writer wins; the blocks it itself mapped shared are already in)
        self.pool.prefix_insert(replay, req.blocks)
        was_replay = req.pending_token is not None
        req.context_len = L
        req.state = DECODING
        if not was_replay:
            # fresh prompt: the prefill's greedy token is the first output
            self._note_token(req, tok)
        # else: preemption replay — the pending token was already produced
        # (greedy replay recomputes the same cache; tok == pending_token)
        self.obs.prefill_done(req, stall, was_replay)

    def _run_decode(self, reqs):
        cfg = self.config
        B = _bucket_for(len(reqs), cfg.decode_buckets())
        toks = np.zeros(B, np.int32)
        poss = np.zeros(B, np.int32)
        tables = np.zeros((B, self._nb_max), np.int32)
        ctx = np.ones(B, np.int32)
        for i, req in enumerate(reqs):
            toks[i] = req.pending_token
            poss[i] = req.context_len
            tables[i] = self._table_row(req, self._nb_max)
            ctx[i] = req.context_len + 1
        # compile-tally delta: a cold decode batch bucket stalls EVERY
        # stream in the batch for the compile wall (serving/obs.py)
        jit = self._decode_jits[B]
        c0, s0 = jit.compile_totals()
        fault.hit("dispatch_error")
        t0 = time.time()
        nxt, _logits, kp, vp = self._decode_fn(
            self.params, toks, poss, tables, ctx,
            self.pool.k_pages, self.pool.v_pages)
        self.pool.k_pages, self.pool.v_pages = kp, vp
        # the fused step's single device->host sync: the next-token vector
        nxt = np.asarray(nxt)  # fwlint: disable=device-escape — token egress to clients is the product, B int32s per step
        wall = time.time() - t0
        c1, s1 = jit.compile_totals()
        if c1 > c0:
            self.obs.decode_stall(reqs, min(s1 - s0, wall))
        telemetry.histogram("serving.decode_batch").observe(len(reqs))
        for i, req in enumerate(reqs):
            req.context_len += 1
            self._note_token(req, int(nxt[i]))

    def _cow_guard(self, reqs):
        """Give every write slot this step will touch a PRIVATE block.

        Structurally unreachable with the current admission flow — a
        prefix match covers only FULL blocks of the replay (n <= L//bs)
        and every decode/spec write lands at a position >= L, i.e. in a
        later, privately-allocated block — but the pool's copy-on-write
        contract must hold unconditionally (a future scheduler change
        must fail a unit test, not corrupt a neighbour's cache)."""
        bs = self.config.block_size
        k = self.spec_k if self._spec else 0
        for req in reqs:
            first = req.context_len // bs
            last = min(req.context_len + k, self.config.max_len - 1) // bs
            for idx in range(first, min(last, len(req.blocks) - 1) + 1):
                b = req.blocks[idx]
                if self.pool.refcount(b) > 1:
                    nb = self.pool.cow(b)
                    if nb != b:
                        if self._draft_kp is not None:
                            # draft pages share the block table, so the
                            # draft copy rides the same COW decision
                            self._draft_kp = self._draft_kp.at[:, nb].set(
                                self._draft_kp[:, b])
                            self._draft_vp = self._draft_vp.at[:, nb].set(
                                self._draft_vp[:, b])
                        req.blocks[idx] = nb

    def _run_spec_decode(self, reqs):
        """Speculative decode: the draft proposes ``spec_k`` greedy
        tokens (one-token steps over its OWN pages, same block tables),
        then the target scores all ``spec_k+1`` window positions in ONE
        multi-query paged-attention pass and greedy acceptance emits the
        TARGET's tokens — the output stream is bit-identical to
        target-only decoding no matter what the draft proposed.

        The draft runs k+1 inner steps: steps 0..k-1 yield proposals,
        the last is cache-fill only — with all k proposals accepted the
        next step starts at position ctx+k+1, and the draft's attention
        there needs its K/V at ctx+k (which no proposal step wrote)."""
        cfg = self.config
        k = self.spec_k
        B = _bucket_for(len(reqs), cfg.decode_buckets())
        n = len(reqs)
        nb = self._nb_max
        base_ctx = [r.context_len for r in reqs]
        tables = np.zeros((B, nb), np.int32)
        for i, req in enumerate(reqs):
            tables[i] = self._table_row(req, nb)
        proposals = [[] for _ in range(n)]
        cur = np.zeros(B, np.int32)
        for i, req in enumerate(reqs):
            cur[i] = req.pending_token
        djit = self._draft_decode_jits[B]
        c0, s0 = djit.compile_totals()
        fault.hit("dispatch_error")
        t0 = time.time()
        for j in range(k + 1):
            toks = cur.copy()
            poss = np.zeros(B, np.int32)
            ctx = np.ones(B, np.int32)
            for i in range(n):
                poss[i] = base_ctx[i] + j
                ctx[i] = base_ctx[i] + j + 1
            dnxt, _dl, dkp, dvp = self._draft_decode_fn(
                self._draft_params, toks, poss, tables, ctx,
                self._draft_kp, self._draft_vp)
            self._draft_kp, self._draft_vp = dkp, dvp
            if j < k:
                # the proposal steers the NEXT inner step's input token —
                # an unavoidable per-draft-step sync, B int32s
                dnxt = np.asarray(dnxt)  # fwlint: disable=device-escape — draft proposals feed the next inner draft step, B int32s per step
                for i in range(n):
                    proposals[i].append(int(dnxt[i]))
                    cur[i] = dnxt[i]
        draft_wall = time.time() - t0
        c1, s1 = djit.compile_totals()
        draft_stall = min(s1 - s0, draft_wall) if c1 > c0 else 0.0
        # verify: the target scores position ctx+j for j in 0..k in one
        # extend() pass — lane j consumes [pending, d_1..d_k][j] and its
        # greedy argmax is the token the stream emits if lane j is reached
        T = k + 1
        toks2 = np.zeros((B, T), np.int32)
        poss2 = np.zeros((B, T), np.int32)
        ctx2 = np.ones((B, T), np.int32)
        for i, req in enumerate(reqs):
            toks2[i, 0] = req.pending_token
            for j in range(k):
                toks2[i, j + 1] = proposals[i][j]
            for j in range(T):
                poss2[i, j] = base_ctx[i] + j
                ctx2[i, j] = base_ctx[i] + j + 1
        vjit = self._verify_jits[B]
        c0, s0 = vjit.compile_totals()
        t0 = time.time()
        nxt2, _logits, kp, vp = self._verify_fn(
            self.params, toks2, poss2, tables, ctx2,
            self.pool.k_pages, self.pool.v_pages)
        self.pool.k_pages, self.pool.v_pages = kp, vp
        nxt2 = np.asarray(nxt2)  # fwlint: disable=device-escape — token egress to clients is the product, B×(k+1) int32s per step
        verify_wall = time.time() - t0
        c1, s1 = vjit.compile_totals()
        verify_stall = min(s1 - s0, verify_wall) if c1 > c0 else 0.0
        if draft_stall or verify_stall:
            self.obs.decode_stall(reqs, draft_stall + verify_stall)
        # greedy acceptance — emit the TARGET's token at every reached
        # lane. Lane j+1 is reached only if the draft's proposal d_{j+1}
        # MATCHED the target's lane-j output (the window's K/V past a
        # mismatch encodes the draft's wrong token, so stop there; the
        # stale writes are overwritten by the next step's lane 0).
        proposed = accepted = 0
        for i, req in enumerate(reqs):
            proposed += k
            for j in range(T):
                tok = int(nxt2[i, j])
                if tok < 0:
                    break   # overflow-poisoned lane (past max_len)
                req.context_len += 1
                self._note_token(req, tok)
                if req.state != DECODING or j >= k \
                        or proposals[i][j] != tok:
                    break
                accepted += 1
        self._spec_proposed += proposed
        self._spec_accepted += accepted
        self._spec_draft_s += draft_wall
        self._spec_verify_s += verify_wall
        telemetry.histogram("serving.decode_batch").observe(len(reqs))
        self.obs.spec_step(reqs, draft_wall - draft_stall,
                           verify_wall - verify_stall, proposed, accepted)

    def _note_token(self, req, tok):
        now = time.time()
        if req.first_token_t is None:
            req.first_token_t = now
            telemetry.histogram("serving.ttft_seconds").observe(
                now - req.arrival_t)
        req.generated.append(tok)
        req.pending_token = tok
        self._tokens_total += 1
        self._token_window.append(now)
        telemetry.counter("serving.generated_tokens").inc()
        if (len(req.generated) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id)):
            req.state = FINISHED
            req.pending_token = None

    def _retire(self, req):
        req.finish_t = time.time()
        # unlabeled aggregate kept alongside the engine-labeled observe in
        # obs.request_finished: process-wide dashboards and pre-existing
        # tests read the bare name
        telemetry.histogram("serving.request_latency_seconds").observe(
            req.finish_t - req.arrival_t)
        telemetry.counter("serving.requests_completed").inc()
        self.obs.request_finished(req)
        self._n_completed += 1
        self._finished.append(req)
        if req.done_event is not None:
            req.done_event.set()

    def _refresh_throughput(self, window_s=10.0):
        now = time.time()
        cut = now - window_s
        w = self._token_window = [t for t in self._token_window if t >= cut]
        span = now - max(cut, self._t_started)
        telemetry.gauge("serving.tokens_per_sec").set(
            len(w) / span if span > 0 else 0.0)

    # ------------------------------------------------------------ stats
    def stats(self):
        """One dashboard snapshot (serve.py columns, /stats endpoint).

        Everything here is THIS engine's: counts are per-engine tallies
        and the latency/TTFT percentiles read the ``engine=<id>``-labeled
        registry histograms, so two engines sharing a process never mix
        numbers (the bare-name histograms still aggregate process-wide
        for dashboards)."""
        with self._lock:
            self._refresh_throughput()   # a stale window must read as 0
            eid = str(self.engine_id)
            lat = telemetry.histogram("serving.request_latency_seconds",
                                      engine=eid)
            ttft = telemetry.histogram("serving.ttft_seconds", engine=eid)
            prog = {p["program"]: p for p in compileobs.program_table()
                    if p["program"].startswith("serving.")}
            return {
                "engine": self.engine_id,
                "steps": self._steps,
                "waiting": len(self.scheduler.waiting),
                "active": len(self.scheduler.running),
                "kv_blocks_total": self.pool.num_usable,
                "kv_blocks_used": self.pool.used(),
                "kv_blocks_frag_slots": self.scheduler.frag_slots(),
                "kv_pool_bytes": self.pool.nbytes(),
                "tokens_total": self._tokens_total,
                "tokens_per_sec":
                    telemetry.gauge("serving.tokens_per_sec").value,
                "latency_p50_s": lat.percentile(50),
                "latency_p99_s": lat.percentile(99),
                "ttft_p50_s": ttft.percentile(50),
                "ttft_p99_s": ttft.percentile(99),
                "preemptions": self.scheduler.preempt_count,
                "completed": self._n_completed,
                "failed": self._n_failed,
                "resilience": {
                    "draining": self._draining,
                    "aborted": self._aborted,
                    "max_queue": self.config.max_queue,
                    "default_timeout_ms": self.config.default_timeout_ms,
                    "shed": self._n_shed,
                    "timed_out": self._n_timed_out,
                    "cancelled": self._n_cancelled,
                },
                "prefix": self.pool.prefix_stats(),
                "spec": {
                    "enabled": self._spec,
                    "k": self.spec_k,
                    "draft": self.config.draft if self._spec else None,
                    "proposed_tokens": self._spec_proposed,
                    "accepted_tokens": self._spec_accepted,
                    "acceptance_rate":
                        (self._spec_accepted / self._spec_proposed)
                        if self._spec_proposed else 0.0,
                    "draft_seconds": round(self._spec_draft_s, 6),
                    "verify_seconds": round(self._spec_verify_s, 6),
                },
                "slo": self.obs.slo_snapshot(),
                "phases": self.obs.phase_snapshot(),
                "compiles": {n: {"count": p["compile_count"],
                                 "seconds": round(p["compile_seconds"], 3),
                                 "runs": p["run_count"]}
                             for n, p in prog.items()},
                "compile_cache": compile_cache.stats(),
            }
