"""ServingEngine — the standing inference engine's Python API.

One engine owns: the weights (a trained checkpoint's ``arg_params`` or
deterministic ``random_params``), one :class:`~.kv_cache.KVBlockPool`, one
:class:`~.scheduler.Scheduler`, and exactly TWO compileobs-tracked XLA
programs — ``serving.prefill`` and ``serving.decode`` — each compiled once
per padded shape bucket (prompt-length buckets for prefill, batch-size
buckets for decode) and replayed forever after: ``compileobs`` showing a
flat compile count after bucket warmup is the engine's no-recompile
acceptance gate.

Each :meth:`step` runs the scheduler's plan: admitted prompts prefill into
the shared block pool (one call per request at its length bucket), then
every decoding stream advances one token through the fused paged decode
step at the batch bucket. The ONLY device->host sync per step is the tiny
next-token vector — that read IS the product (tokens leave for clients);
everything else stays device-resident, pool pages donated call to call.

Thread model: ``submit()`` is safe from any thread (HTTP handlers);
``step()``/``run_loop()`` must run on one driver thread. Per-request
latency metrics (TTFT, end-to-end, tokens/sec) flow through the telemetry
registry — ``serving.*`` in docs/observability.md — and render live in
``tools/serve.py``'s stat columns.
"""
import itertools
import threading
import time
from collections import deque

import numpy as np

from .. import compile_cache, compileobs, telemetry
from ..base import env_int
from . import model as _model
from .kv_cache import KVBlockPool
from .obs import ServingObs
from .scheduler import DECODING, FAILED, FINISHED, Request, Scheduler

_SITE = "serving/engine.py"

_engine_ids = itertools.count()


class ServingConfig(_model.ModelConfig):
    """Model shape + engine knobs. Engine knobs default from the
    ``MXNET_SERVING_*`` environment (docs/env_var.md)."""

    __slots__ = ("block_size", "num_blocks", "max_batch",
                 "prefills_per_step", "kv_dtype")

    def __init__(self, vocab_size=32000, num_layers=4, model_dim=256,
                 num_heads=4, ffn_dim=1024, max_len=128,
                 block_size=None, num_blocks=None, max_batch=None,
                 prefills_per_step=None, kv_dtype=np.float32):
        super().__init__(vocab_size, num_layers, model_dim, num_heads,
                         ffn_dim, max_len)
        self.block_size = int(block_size if block_size is not None
                              else env_int("MXNET_SERVING_BLOCK_SIZE", 16))
        self.num_blocks = int(num_blocks if num_blocks is not None
                              else env_int("MXNET_SERVING_NUM_BLOCKS", 257))
        self.max_batch = int(max_batch if max_batch is not None
                             else env_int("MXNET_SERVING_MAX_BATCH", 32))
        self.prefills_per_step = int(
            prefills_per_step if prefills_per_step is not None
            else env_int("MXNET_SERVING_PREFILLS_PER_STEP", 4))
        self.kv_dtype = np.dtype(kv_dtype)
        if self.max_len % self.block_size:
            raise ValueError(
                "max_len (%d) must be a multiple of block_size (%d): "
                "prefill buckets and the decode block table are sized in "
                "whole blocks" % (self.max_len, self.block_size))

    def decode_buckets(self):
        """Padded decode batch sizes: powers of two up to max_batch."""
        out = []
        b = 1
        while b < self.max_batch:
            out.append(b)
            b *= 2
        out.append(self.max_batch)
        return out

    def prefill_buckets(self):
        """Padded prompt lengths: block_size doublings up to max_len."""
        out = []
        s = self.block_size
        while s < self.max_len:
            out.append(s)
            s *= 2
        out.append(self.max_len)
        return out


def _bucket_for(n, buckets):
    for b in buckets:
        if n <= b:
            return b
    raise ValueError("no bucket holds %d (buckets %s)" % (n, buckets))


class ServingEngine:
    """Continuous-batching inference over the Transformer-LM zoo model."""

    def __init__(self, config, arg_params=None, seed=0, device=None,
                 enable_telemetry=True):
        if enable_telemetry:
            telemetry.enable()
        self.config = cfg = config
        if arg_params is None:
            arg_params = _model.random_params(cfg, seed=seed)
        self.params = _model.as_device_params(arg_params, cfg, device=device)
        self.pool = KVBlockPool(cfg.num_layers, cfg.num_blocks,
                                cfg.block_size, cfg.num_heads,
                                cfg.model_dim // cfg.num_heads,
                                dtype=cfg.kv_dtype, device=device)
        self.scheduler = Scheduler(self.pool, max_batch=cfg.max_batch,
                                   prefills_per_step=cfg.prefills_per_step)
        self._nb_max = cfg.max_len // cfg.block_size
        self._lock = threading.RLock()
        self._work = threading.Condition(self._lock)
        # retired requests awaiting pop_finished(), BOUNDED: a driver
        # that consumes done_events instead (serve.py) would otherwise
        # leak one Request per call served for the life of the server.
        # A polling driver draining every step never hits the cap — a
        # step retires at most max_batch streams plus a handful of
        # admission failures; only a mass abort can shed the oldest
        # entries, and those waiters were already woken via done_event.
        self._finished = deque(maxlen=max(256, 8 * cfg.max_batch))
        self._aborted = None
        self._steps = 0
        # per-engine tallies: the registry counters with the same names
        # are process-global and would attribute a previous engine's
        # traffic to this one in stats()
        self._n_completed = 0
        self._n_failed = 0
        self._token_window = []   # one timestamp per token, for tokens/sec
        self._t_started = time.time()
        self._tokens_total = 0
        # per-engine identity: labels this engine's histograms/counters in
        # the process-global registry (stats() reads ONLY its own label)
        # and salts the graph keys below
        self.engine_id = next(_engine_ids)
        self.obs = ServingObs(self.engine_id)

        # donation frees the pool's previous pages the moment the step
        # consumes them — without it every step would briefly double the
        # pool's device footprint (CPU backends ignore donation; harmless)
        import jax

        donate = {} if jax.default_backend() == "cpu" else \
            {"donate_argnums": (4, 5)}
        # the engine nonce is part of the graph identity: a second engine
        # in the same process (even one with an IDENTICAL config) holds
        # fresh function objects, so its bucket warmup compiles again —
        # under a shared graph key that warmup would diff against the
        # first engine's signatures and misreport as compile.recompile
        # (cause=placement; cause=dtype when only kv_dtype differs)
        gkey = ("serving", self.engine_id) + cfg.key() + (
            cfg.block_size, cfg.num_blocks, str(cfg.kv_dtype))

        # fresh function objects per bucket (factories, not one shared
        # closure): jax's tracing cache is keyed on the wrapped function,
        # so bucket wrappers sharing one function would share one cache
        # and each wrapper's cache-size delta would misfire on the
        # others' compiles
        def _mk_prefill():
            def _prefill(params, tokens, length, block_table,
                         k_pages, v_pages):
                return _model.prefill(params, tokens, length, block_table,
                                      k_pages, v_pages, cfg)
            return _prefill

        def _mk_decode():
            def _decode(params, tokens, positions, block_tables,
                        context_lens, k_pages, v_pages):
                return _model.decode(params, tokens, positions,
                                     block_tables, context_lens,
                                     k_pages, v_pages, cfg)
            return _decode

        if donate:
            decode_donate = {"donate_argnums": (5, 6)}
        else:
            decode_donate = {}
        # one wrapper per shape bucket: buckets are DESIGNED to each
        # compile once, so a bucket's first compile must not diff against
        # another bucket's signature under a shared graph key — that would
        # report routine warmup as compile.recompile (the counter
        # operators alarm on) with a WARNING per bucket. Per-bucket keys
        # reserve the recompile stream for a bucket compiling AGAIN.
        #
        # cache_key drops the per-engine NONCE from the graph key: the
        # persistent compile cache must hit across processes (and across
        # engines of identical config), so its identity is pure content —
        # model shape + pool geometry + bucket. aot=True: each bucket is a
        # single-signature site, the serialized-executable fast lane — a
        # warm replica's warmup() loads every bucket from disk instead of
        # compiling it (tools/serve.py --warmup, bench_serving warmup_s).
        ckey_base = cfg.key() + (cfg.block_size, cfg.num_blocks,
                                 str(cfg.kv_dtype))
        self._prefill_jits = {
            S: compileobs.jit(_mk_prefill(), "serving.prefill", site=_SITE,
                              graph_key=gkey + ("prefill", S), aot=True,
                              cache_key=("serving.prefill",) + ckey_base
                              + (S,), **donate)
            for S in cfg.prefill_buckets()}
        self._decode_jits = {
            B: compileobs.jit(_mk_decode(), "serving.decode", site=_SITE,
                              graph_key=gkey + ("decode", B), aot=True,
                              cache_key=("serving.decode",) + ckey_base
                              + (B,), **decode_donate)
            for B in cfg.decode_buckets()}
        # bucket dispatch: call sites pad to an exact bucket shape, so the
        # padded dims index the wrapper table directly
        self._prefill_fn = lambda params, toks, L, table, kp, vp: \
            self._prefill_jits[toks.shape[1]](params, toks, L, table,
                                              kp, vp)
        self._decode_fn = lambda params, toks, poss, tables, ctx, kp, vp: \
            self._decode_jits[toks.shape[0]](params, toks, poss, tables,
                                             ctx, kp, vp)

    # ------------------------------------------------------------------ API
    def submit(self, prompt, max_new_tokens, eos_id=None, request_id=None):
        """Enqueue a request; returns the :class:`Request` (its
        ``done_event`` is set when it finishes — block on it from serving
        threads, or drive :meth:`step` yourself). ``request_id`` is the
        wire identity threaded through every lifecycle event and trace
        lane (auto-assigned from the rid when omitted)."""
        req = Request(prompt, max_new_tokens, eos_id=eos_id,
                      request_id=request_id)
        total = len(req.prompt) + req.max_new_tokens
        if total > self.config.max_len:
            raise ValueError(
                "request needs %d total positions > max_len %d (the "
                "position-embedding table bounds every stream)"
                % (total, self.config.max_len))
        if self.pool.blocks_for(total) > self.pool.num_usable:
            raise ValueError(
                "request needs %d KV blocks > pool capacity %d"
                % (self.pool.blocks_for(total), self.pool.num_usable))
        req.done_event = threading.Event()
        with self._work:
            # checked under the lock: an abort() racing an unlocked check
            # could drain the queues first, leaving this request enqueued
            # behind a dead driver with a done_event nobody will ever set
            if self._aborted is not None:
                raise RuntimeError(self._aborted)
            self.obs.request_submitted(req)
            self.scheduler.add(req)
            self._work.notify_all()
        return req

    def has_work(self):
        with self._lock:
            return self.scheduler.has_work()

    def step(self):
        """One engine iteration: schedule, prefill admissions, fused decode,
        retire finished requests. Returns the requests that finished.

        A failure escaping the step (device error, XLA crash) aborts the
        engine before re-raising — the pool pages may have been donated
        into the failed dispatch and cannot be trusted, so EVERY driver
        (run_loop, :meth:`generate`, bench/step-polling loops) gets the
        same contract: pending requests fail loudly, waiters wake, later
        submits refuse."""
        try:
            with self._lock, telemetry.span("serving.step"):
                plan = self.scheduler.schedule()
                for req in plan.preempted:
                    self.obs.request_preempted(req)
                for req in plan.prefills:
                    self.obs.request_admitted(req)
                failed = self._drain_failed()
                if plan.empty():
                    return failed
                for req in plan.prefills:
                    self._run_prefill(req)
                n_preempted = len(plan.preempted)
                if plan.prefills:
                    # a prompt that exactly filled its blocks writes its
                    # first decode token at a fresh block boundary — back
                    # that slot with a real block NOW or the write lands in
                    # trash and the position's K/V is silently lost
                    late = self.scheduler.ensure_decode_headroom()
                    for req in late:
                        self.obs.request_preempted(req)
                    n_preempted += len(late)
                    failed += self._drain_failed()
                decodes = self.scheduler.decodable()
                if decodes:
                    self._run_decode(decodes)
                finished = [r for r in list(self.scheduler.running)
                            if r.finished()]
                for req in finished:
                    self.scheduler.finish(req)
                    self._retire(req)
                self._steps += 1
                self._refresh_throughput()
                self.obs.step_timeline(
                    step=self._steps, occupancy=len(decodes),
                    admitted=len(plan.prefills), preempted=n_preempted,
                    finished=len(finished) + len(failed),
                    queue=len(self.scheduler.waiting),
                    running=len(self.scheduler.running),
                    kv_used=self.pool.used(), kv_free=self.pool.available(),
                    kv_frag_slots=self.scheduler.frag_slots())
                return finished + failed
        except Exception as exc:
            self.abort(exc)
            raise

    def run_loop(self, stop_event=None, idle_wait_s=0.05):
        """Drive :meth:`step` until ``stop_event`` is set, sleeping on the
        submit condition while idle (the serve.py driver thread).

        A step failure must not strand clients blocked on their
        ``done_event`` forever behind a silently dead driver: ``step()``
        itself aborts the engine — every queued + running request is
        FAILED with the error and woken, later submits refuse — and the
        re-raise propagates here so the driver thread's death is
        observable (``Thread.is_alive()`` backs serve.py's
        ``/healthz``)."""
        while stop_event is None or not stop_event.is_set():
            with self._work:
                if not self.scheduler.has_work():
                    # idle steps never run, so decay the sliding
                    # tokens/sec window here or it freezes at its last
                    # loaded value on a quiet server
                    self._refresh_throughput()
                    self._work.wait(timeout=idle_wait_s)
                    if not self.scheduler.has_work():
                        continue
            self.step()

    def abort(self, exc):
        """Fail every queued and running request (the driver died mid-
        step, or the caller is shutting down hard). After an abort the
        engine refuses new submits — the pool pages may have been donated
        into the failed dispatch and cannot be trusted."""
        msg = "serving engine aborted: %r" % (exc,)
        with self._lock:
            self._aborted = msg
            self._drain_failed()   # scheduler failures the step never saw
            reqs = list(self.scheduler.running) + list(self.scheduler.waiting)
            self.scheduler.running.clear()
            self.scheduler.waiting.clear()
            for req in reqs:
                req.blocks = []   # pool accounting is moot post-abort
                req.state = FAILED
                req.error = msg
                req.finish_t = time.time()
                telemetry.counter("serving.requests_failed").inc()
                self.obs.request_finished(req, failed=True)
                if req.done_event is not None:
                    req.done_event.set()
            self._finished.extend(reqs)
            self._n_failed += len(reqs)

    def warmup(self):
        """Compile every prefill length bucket and decode batch bucket in
        one pass (one throwaway dispatch each, all-trash block tables, no
        requests involved) so the first real traffic pays zero compile
        wall and the steady-state compile count is flat from step one."""
        cfg = self.config
        with self._lock:
            for S in cfg.prefill_buckets():
                toks = np.zeros((1, S), np.int32)
                table = np.zeros(S // cfg.block_size, np.int32)
                _t, _l, kp, vp = self._prefill_fn(
                    self.params, toks, np.int32(1), table,
                    self.pool.k_pages, self.pool.v_pages)
                self.pool.k_pages, self.pool.v_pages = kp, vp
            for B in cfg.decode_buckets():
                toks = np.zeros(B, np.int32)
                poss = np.zeros(B, np.int32)
                tables = np.zeros((B, self._nb_max), np.int32)
                ctx = np.ones(B, np.int32)
                _t, _l, kp, vp = self._decode_fn(
                    self.params, toks, poss, tables, ctx,
                    self.pool.k_pages, self.pool.v_pages)
                self.pool.k_pages, self.pool.v_pages = kp, vp

    def generate(self, prompts, max_new_tokens, eos_id=None):
        """Convenience batch API: submit every prompt, drive steps until
        all finish, return each request's generated tokens (in input
        order). Raises if any request failed."""
        if isinstance(max_new_tokens, int):
            max_new_tokens = [max_new_tokens] * len(prompts)
        reqs = [self.submit(p, n, eos_id=eos_id)
                for p, n in zip(prompts, max_new_tokens)]
        while any(not r.finished() for r in reqs):
            self.step()
        failed = [r for r in reqs if r.state == FAILED]
        if failed:
            raise RuntimeError("requests failed: %s"
                               % [(r.rid, r.error) for r in failed])
        return [list(r.generated) for r in reqs]

    def pop_finished(self):
        """Drain every request retired since the last call — FINISHED and
        FAILED both (check ``req.state``/``req.error``); a polling driver
        must never lose a request to a silent scheduler-side failure.
        The backlog is bounded (``max(256, 8 * max_batch)``) so drivers
        that consume ``done_event`` instead of polling don't accumulate
        one retired Request per call served — drain at least once per
        step to observe every retiree."""
        with self._lock:
            out = list(self._finished)
            self._finished.clear()
            return out

    def _drain_failed(self):
        """Scheduler-failed requests surface through the same channels as
        successes: appended to the ``pop_finished()`` queue and returned
        from :meth:`step`. ``_fail`` already stamped ``finish_t``, bumped
        ``serving.requests_failed`` and woke the ``done_event``."""
        failed = self.scheduler.pop_failed()
        for req in failed:
            self.obs.request_finished(req, failed=True)
        self._finished.extend(failed)
        self._n_failed += len(failed)
        return failed

    # ------------------------------------------------------------ internals
    def _table_row(self, req, width):
        # the admission grant includes the first decode slot's headroom
        # block, so a boundary-length replay holds one block more than its
        # prefill bucket's table width — clip; prefill never reads it
        row = np.zeros(width, np.int32)
        n = min(len(req.blocks), width)
        row[:n] = req.blocks[:n]
        return row

    def _run_prefill(self, req):
        cfg = self.config
        replay = req.replay_tokens()
        L = len(replay)
        S = _bucket_for(L, cfg.prefill_buckets())
        toks = np.zeros((1, S), np.int32)
        toks[0, :L] = replay
        table = self._table_row(req, S // cfg.block_size)
        # compile-tally delta around the dispatch: a bump means THIS call
        # sat behind a cold prefill bucket — that wall is the request's
        # compile_stall, not honest prefill time
        jit = self._prefill_jits[S]
        c0, s0 = jit.compile_totals()
        t0 = time.time()
        tok, _logits, kp, vp = self._prefill_fn(
            self.params, toks, np.int32(L), table,
            self.pool.k_pages, self.pool.v_pages)
        self.pool.k_pages, self.pool.v_pages = kp, vp
        # the per-step token egress: serving's output IS this transfer
        tok = int(np.asarray(tok)[0])  # fwlint: disable=device-escape — token egress to the client is the product, one scalar per prefill
        wall = time.time() - t0
        c1, s1 = jit.compile_totals()
        stall = min(s1 - s0, wall) if c1 > c0 else 0.0
        telemetry.histogram("serving.prefill_seconds").observe(wall)
        telemetry.counter("serving.prefill_tokens").inc(L)
        was_replay = req.pending_token is not None
        req.context_len = L
        req.state = DECODING
        if not was_replay:
            # fresh prompt: the prefill's greedy token is the first output
            self._note_token(req, tok)
        # else: preemption replay — the pending token was already produced
        # (greedy replay recomputes the same cache; tok == pending_token)
        self.obs.prefill_done(req, stall, was_replay)

    def _run_decode(self, reqs):
        cfg = self.config
        B = _bucket_for(len(reqs), cfg.decode_buckets())
        toks = np.zeros(B, np.int32)
        poss = np.zeros(B, np.int32)
        tables = np.zeros((B, self._nb_max), np.int32)
        ctx = np.ones(B, np.int32)
        for i, req in enumerate(reqs):
            toks[i] = req.pending_token
            poss[i] = req.context_len
            tables[i] = self._table_row(req, self._nb_max)
            ctx[i] = req.context_len + 1
        # compile-tally delta: a cold decode batch bucket stalls EVERY
        # stream in the batch for the compile wall (serving/obs.py)
        jit = self._decode_jits[B]
        c0, s0 = jit.compile_totals()
        t0 = time.time()
        nxt, _logits, kp, vp = self._decode_fn(
            self.params, toks, poss, tables, ctx,
            self.pool.k_pages, self.pool.v_pages)
        self.pool.k_pages, self.pool.v_pages = kp, vp
        # the fused step's single device->host sync: the next-token vector
        nxt = np.asarray(nxt)  # fwlint: disable=device-escape — token egress to clients is the product, B int32s per step
        wall = time.time() - t0
        c1, s1 = jit.compile_totals()
        if c1 > c0:
            self.obs.decode_stall(reqs, min(s1 - s0, wall))
        telemetry.histogram("serving.decode_batch").observe(len(reqs))
        for i, req in enumerate(reqs):
            req.context_len += 1
            self._note_token(req, int(nxt[i]))

    def _note_token(self, req, tok):
        now = time.time()
        if req.first_token_t is None:
            req.first_token_t = now
            telemetry.histogram("serving.ttft_seconds").observe(
                now - req.arrival_t)
        req.generated.append(tok)
        req.pending_token = tok
        self._tokens_total += 1
        self._token_window.append(now)
        telemetry.counter("serving.generated_tokens").inc()
        if (len(req.generated) >= req.max_new_tokens
                or (req.eos_id is not None and tok == req.eos_id)):
            req.state = FINISHED
            req.pending_token = None

    def _retire(self, req):
        req.finish_t = time.time()
        # unlabeled aggregate kept alongside the engine-labeled observe in
        # obs.request_finished: process-wide dashboards and pre-existing
        # tests read the bare name
        telemetry.histogram("serving.request_latency_seconds").observe(
            req.finish_t - req.arrival_t)
        telemetry.counter("serving.requests_completed").inc()
        self.obs.request_finished(req)
        self._n_completed += 1
        self._finished.append(req)
        if req.done_event is not None:
            req.done_event.set()

    def _refresh_throughput(self, window_s=10.0):
        now = time.time()
        cut = now - window_s
        w = self._token_window = [t for t in self._token_window if t >= cut]
        span = now - max(cut, self._t_started)
        telemetry.gauge("serving.tokens_per_sec").set(
            len(w) / span if span > 0 else 0.0)

    # ------------------------------------------------------------ stats
    def stats(self):
        """One dashboard snapshot (serve.py columns, /stats endpoint).

        Everything here is THIS engine's: counts are per-engine tallies
        and the latency/TTFT percentiles read the ``engine=<id>``-labeled
        registry histograms, so two engines sharing a process never mix
        numbers (the bare-name histograms still aggregate process-wide
        for dashboards)."""
        with self._lock:
            self._refresh_throughput()   # a stale window must read as 0
            eid = str(self.engine_id)
            lat = telemetry.histogram("serving.request_latency_seconds",
                                      engine=eid)
            ttft = telemetry.histogram("serving.ttft_seconds", engine=eid)
            prog = {p["program"]: p for p in compileobs.program_table()
                    if p["program"].startswith("serving.")}
            return {
                "engine": self.engine_id,
                "steps": self._steps,
                "waiting": len(self.scheduler.waiting),
                "active": len(self.scheduler.running),
                "kv_blocks_total": self.pool.num_usable,
                "kv_blocks_used": self.pool.used(),
                "kv_blocks_frag_slots": self.scheduler.frag_slots(),
                "kv_pool_bytes": self.pool.nbytes(),
                "tokens_total": self._tokens_total,
                "tokens_per_sec":
                    telemetry.gauge("serving.tokens_per_sec").value,
                "latency_p50_s": lat.percentile(50),
                "latency_p99_s": lat.percentile(99),
                "ttft_p50_s": ttft.percentile(50),
                "ttft_p99_s": ttft.percentile(99),
                "preemptions": self.scheduler.preempt_count,
                "completed": self._n_completed,
                "failed": self._n_failed,
                "slo": self.obs.slo_snapshot(),
                "phases": self.obs.phase_snapshot(),
                "compiles": {n: {"count": p["compile_count"],
                                 "seconds": round(p["compile_seconds"], 3),
                                 "runs": p["run_count"]}
                             for n, p in prog.items()},
                "compile_cache": compile_cache.stats(),
            }
