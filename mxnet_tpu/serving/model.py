"""Functional Transformer-LM forward for serving.

The serving engine cannot run the symbol executors: prefill needs the K/V
projections OUT of the graph (to scatter into the shared block pool) and
decode needs attention THROUGH per-request block tables. This module is the
functional twin of ``models/transformer_lm.py`` — same parameter names, same
primitive-for-primitive numerics (LayerNorm composed from mean/square/sqrt
with the same 1e-5 epsilon, the same fused-qkv einsums at
``fp32_precision``, ``flash_attention`` for prefill exactly as the training
block uses it) — so a trained checkpoint's ``arg_params`` drop straight in
and the paged decode reproduces the contiguous cached decoder to float
tolerance (tests_tpu/test_serving.py pins it at <1e-5 for fp32).

Both step functions are PURE (params and pages in, logits and pages out):
the engine wraps them in ``compileobs.jit`` with the pool pages donated, so
each shape bucket compiles exactly once and the pool never copies.

Padded-lane safety contract: bucketed steps carry dead lanes (padded batch
rows, padded prompt tail). Dead lanes write through the block table's
TRASH entries (block 0) and read under a context-length mask that pins
their scores to exp(-1e30)=0 — garbage can neither corrupt a live block
nor leak into a live row. An out-of-range decode position (>= max_len) is
routed to the trash block and its lane's outputs poisoned (token -1,
logits NaN): the paged path upholds the same graph-level overflow contract
as ``_contrib_CachedMultiHeadAttention``.
"""
import numpy as np

from ..ops.attention import (flash_attention, paged_attention,
                             paged_attention_multi)
from ..ops.registry import fp32_precision

#: parameter init scale matching models/transformer_lm.py's Normal(0.02)
#: pos-embed init; used by random_params for self-contained serving runs
_INIT_SCALE = 0.02


class ModelConfig:
    """Static Transformer-LM shape config (hashable: feeds compileobs
    graph keys). ``max_len`` is the training graph's ``seq_len`` — the
    position-embedding table bounds every stream's total length."""

    __slots__ = ("vocab_size", "num_layers", "model_dim", "num_heads",
                 "ffn_dim", "max_len")

    def __init__(self, vocab_size=32000, num_layers=4, model_dim=256,
                 num_heads=4, ffn_dim=1024, max_len=128):
        self.vocab_size = int(vocab_size)
        self.num_layers = int(num_layers)
        self.model_dim = int(model_dim)
        self.num_heads = int(num_heads)
        self.ffn_dim = int(ffn_dim)
        self.max_len = int(max_len)
        if self.model_dim % self.num_heads:
            raise ValueError("model_dim must divide by num_heads")

    def key(self):
        return (self.vocab_size, self.num_layers, self.model_dim,
                self.num_heads, self.ffn_dim, self.max_len)

    def _slot_names(self):
        # walk the whole MRO: on a subclass (ServingConfig) bare
        # self.__slots__ resolves to the subclass's slots only, silently
        # dropping the model-shape fields from repr/as_dict
        names = []
        for klass in reversed(type(self).__mro__):
            names.extend(getattr(klass, "__slots__", ()))
        return names

    def as_dict(self):
        return {k: getattr(self, k) for k in self._slot_names()}

    def __repr__(self):
        # %r, not %d: subclass slots hold non-int values (kv_dtype) and
        # this repr feeds the as_device_params diagnostics — it must
        # never itself raise
        return "%s(%s)" % (type(self).__name__, ", ".join(
            "%s=%r" % (k, getattr(self, k)) for k in self._slot_names()))


def param_shapes(cfg):
    """Name -> shape for every weight the serving forward consumes —
    exactly the training graph's ``arg_dict`` names (minus data/label)."""
    m, f, v = cfg.model_dim, cfg.ffn_dim, cfg.vocab_size
    shapes = {
        "embed_weight": (v, m),
        "pos_embed_weight": (1, cfg.max_len, m),
        "final_ln_gamma": (1, 1, m),
        "final_ln_beta": (1, 1, m),
        "lm_head_weight": (v, m),
        "lm_head_bias": (v,),
    }
    for i in range(cfg.num_layers):
        p = "layer%d" % i
        shapes.update({
            p + "_ln1_gamma": (1, 1, m), p + "_ln1_beta": (1, 1, m),
            p + "_ln2_gamma": (1, 1, m), p + "_ln2_beta": (1, 1, m),
            p + "_attn_in_weight": (3 * m, m),
            p + "_attn_out_weight": (m, m),
            p + "_ffn1_weight": (f, m), p + "_ffn1_bias": (f,),
            p + "_ffn2_weight": (m, f), p + "_ffn2_bias": (m,),
        })
    return shapes


def random_params(cfg, seed=0, dtype=np.float32):
    """Deterministic host-side random weights (gamma=1, beta/bias=0,
    weights ~N(0, 0.02)) — the same function call in any process yields
    byte-identical params, which is what lets the e2e test compare a
    served subprocess against an in-process sequential reference."""
    rng = np.random.RandomState(seed)
    out = {}
    for name, shape in sorted(param_shapes(cfg).items()):
        if name.endswith("_gamma"):
            out[name] = np.ones(shape, dtype)
        elif name.endswith(("_beta", "_bias")):
            out[name] = np.zeros(shape, dtype)
        else:
            out[name] = (rng.randn(*shape) * _INIT_SCALE).astype(dtype)
    return out


def as_device_params(arg_params, cfg, dtype=None, device=None):
    """Stage a params dict (numpy / NDArray / jax values) onto the device,
    validating names+shapes against the config. Extra entries (e.g. a
    checkpoint's optimizer leftovers) are ignored."""
    import jax
    import jax.numpy as jnp

    want = param_shapes(cfg)
    out = {}
    missing = []
    for name, shape in want.items():
        if name not in arg_params:
            missing.append(name)
            continue
        a = arg_params[name]
        a = a.data if hasattr(a, "data") and hasattr(a, "asnumpy") else a
        a = jnp.asarray(a, dtype=dtype)
        if tuple(a.shape) != tuple(shape):
            raise ValueError("param %s: shape %s != expected %s (config %r)"
                             % (name, tuple(a.shape), shape, cfg))
        out[name] = jax.device_put(a, device) if device is not None else a
    if missing:
        raise ValueError("params missing for serving config %r: %s"
                         % (cfg, sorted(missing)))
    return out


# ---------------------------------------------------------------------------
# functional blocks (numerics mirror models/transformer_lm.py op for op)
# ---------------------------------------------------------------------------


def draft_config(cfg, spec):
    """Resolve a draft-model selection (``MXNET_SERVING_DRAFT``) against a
    target config. ``"self"`` is the self-drafting harness — the draft IS
    the target shape (the engine then shares the target's weights, so
    greedy proposals match the verify pass and acceptance sits near 1.0);
    any other name must be a ``models/transformer_lm.py``
    ``SERVING_DRAFT_PRESETS`` entry (a tiny zoo shape). vocab_size and
    max_len always follow the target: the draft proposes tokens from the
    same vocabulary at the same absolute positions."""
    from ..models.transformer_lm import SERVING_DRAFT_PRESETS

    if spec == "self":
        return ModelConfig(cfg.vocab_size, cfg.num_layers, cfg.model_dim,
                           cfg.num_heads, cfg.ffn_dim, cfg.max_len)
    if spec not in SERVING_DRAFT_PRESETS:
        raise ValueError(
            "unknown draft model %r: expected 'self' or one of %s "
            "(models/transformer_lm.py SERVING_DRAFT_PRESETS)"
            % (spec, sorted(SERVING_DRAFT_PRESETS)))
    p = SERVING_DRAFT_PRESETS[spec]
    return ModelConfig(cfg.vocab_size, p["num_layers"], p["model_dim"],
                       p["num_heads"], p["ffn_dim"], cfg.max_len)


def _layer_norm(x, gamma, beta):
    import jax.numpy as jnp

    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + 1e-5) * gamma + beta


def _ffn(x2d, params, prefix, prec):
    import jax.numpy as jnp

    f = jnp.dot(x2d, params[prefix + "_ffn1_weight"].T, precision=prec)
    f = jnp.maximum(f + params[prefix + "_ffn1_bias"], 0)
    f = jnp.dot(f, params[prefix + "_ffn2_weight"].T, precision=prec)
    return f + params[prefix + "_ffn2_bias"]


def prefill(params, tokens, length, block_table, k_pages, v_pages, cfg):
    """Full-sequence prefill for ONE request at a padded bucket length.

    tokens:      (1, S) int32, S a bucket multiple of the pool block size
                 (prompt left-aligned, tail padded with 0s)
    length:      () int32 — true prompt length (1 <= length <= S)
    block_table: (S // block_size,) int32 — the request's allocated blocks
                 in position order; tail entries past the prompt = 0 (trash)
    k/v_pages:   the pool pages, (L, N, bs, H, D) — donated by the engine

    Returns ``(next_token (1,) int32, logits (1, V), k_pages, v_pages)``:
    every layer's K/V for positions < S scattered into the pool through the
    table, and the greedy next token sampled at position ``length - 1``.
    Attention is the training block's ``flash_attention(causal=True)`` —
    padded tail rows compute garbage but cannot reach rows < length (causal
    mask) and their cache writes land in trash-table blocks.
    """
    import jax.numpy as jnp

    _, S = tokens.shape
    m, hh = cfg.model_dim, cfg.num_heads
    hd = m // hh
    bs = k_pages.shape[2]
    prec = fp32_precision(k_pages.dtype)

    x = jnp.take(params["embed_weight"], tokens, axis=0)       # (1, S, M)
    x = x + params["pos_embed_weight"][:, :S]

    def split_heads(t):
        return t.reshape(1, S, hh, hd).transpose(0, 2, 1, 3)   # (1, H, S, hd)

    k_all, v_all = [], []
    for i in range(cfg.num_layers):
        p = "layer%d" % i
        h = _layer_norm(x, params[p + "_ln1_gamma"], params[p + "_ln1_beta"])
        qkv = jnp.einsum("bsm,nm->bsn", h, params[p + "_attn_in_weight"],
                         precision=prec)
        q, k, v = jnp.split(qkv, 3, axis=-1)                   # (1, S, M)
        k_all.append(k.reshape(S, hh, hd))
        v_all.append(v.reshape(S, hh, hd))
        attn = flash_attention(split_heads(q), split_heads(k),
                               split_heads(v), True)
        attn = attn.transpose(0, 2, 1, 3).reshape(1, S, m)
        attn = jnp.einsum("bsm,nm->bsn", attn,
                          params[p + "_attn_out_weight"], precision=prec)
        x = x + attn
        h = _layer_norm(x, params[p + "_ln2_gamma"], params[p + "_ln2_beta"])
        x = x + _ffn(h.reshape(S, m), params, p, prec).reshape(1, S, m)

    # scatter every layer's K/V through the block table (trash entries
    # absorb the padded tail)
    kw = jnp.stack(k_all).reshape(cfg.num_layers, S // bs, bs, hh, hd)
    vw = jnp.stack(v_all).reshape(cfg.num_layers, S // bs, bs, hh, hd)
    k_pages = k_pages.at[:, block_table].set(kw.astype(k_pages.dtype))
    v_pages = v_pages.at[:, block_table].set(vw.astype(v_pages.dtype))

    x = _layer_norm(x, params["final_ln_gamma"], params["final_ln_beta"])
    h_last = jnp.take(x[0], length - 1, axis=0)                # (M,)
    logits = (jnp.dot(h_last[None], params["lm_head_weight"].T,
                      precision=prec) + params["lm_head_bias"])  # (1, V)
    next_token = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return next_token, logits, k_pages, v_pages


def decode(params, tokens, positions, block_tables, context_lens,
           k_pages, v_pages, cfg):
    """The fused paged decode step: one token for every sequence in the
    padded batch, one XLA program per batch bucket.

    tokens:       (B,) int32 — each stream's pending input token
    positions:    (B,) int32 — the slot this token is written at
                  (== tokens cached so far for the stream)
    block_tables: (B, max_len // block_size) int32 — pool blocks per
                  stream in position order; unused/padded entries = 0
    context_lens: (B,) int32 — valid tokens AFTER this step's write
                  (positions + 1 for live rows; padded rows pass 1)
    k/v_pages:    pool pages (donated)

    Returns ``(next_tokens (B,), logits (B, V), k_pages, v_pages)``.
    Out-of-range positions (>= max_len) honor the overflow contract:
    the write is routed to the trash block, ``next_token`` is -1, and the
    lane's logits are NaN — the cache cannot be corrupted from the graph.
    """
    import jax.numpy as jnp

    B = tokens.shape[0]
    m, hh = cfg.model_dim, cfg.num_heads
    hd = m // hh
    bs = k_pages.shape[2]
    prec = fp32_precision(k_pages.dtype)

    in_range = positions < cfg.max_len
    safe_pos = jnp.minimum(positions, cfg.max_len - 1)
    page_ids = jnp.take_along_axis(block_tables, (safe_pos // bs)[:, None],
                                   axis=1)[:, 0]
    page_ids = jnp.where(in_range, page_ids, 0)  # overflow -> trash block
    slots = jnp.where(in_range, safe_pos % bs, 0)

    pos_tab = params["pos_embed_weight"].reshape(cfg.max_len, m)
    x = (jnp.take(params["embed_weight"], tokens, axis=0)
         + jnp.take(pos_tab, safe_pos, axis=0))                # (B, M)
    x = x[:, None, :]                                          # (B, 1, M)

    for i in range(cfg.num_layers):
        p = "layer%d" % i
        h = _layer_norm(x, params[p + "_ln1_gamma"], params[p + "_ln1_beta"])
        qkv = jnp.einsum("bsm,nm->bsn", h, params[p + "_attn_in_weight"],
                         precision=prec)
        q, k_new, v_new = jnp.split(qkv, 3, axis=-1)           # (B, 1, M)
        q = q.reshape(B, hh, hd)
        k_new = k_new.reshape(B, hh, hd)
        v_new = v_new.reshape(B, hh, hd)
        k_pages = k_pages.at[i, page_ids, slots].set(
            k_new.astype(k_pages.dtype))
        v_pages = v_pages.at[i, page_ids, slots].set(
            v_new.astype(v_pages.dtype))
        attn = paged_attention(q, k_pages[i], v_pages[i], block_tables,
                               context_lens)                   # (B, H, hd)
        attn = attn.reshape(B, 1, m)
        attn = jnp.einsum("bsm,nm->bsn", attn,
                          params[p + "_attn_out_weight"], precision=prec)
        x = x + attn
        h = _layer_norm(x, params[p + "_ln2_gamma"], params[p + "_ln2_beta"])
        x = x + _ffn(h.reshape(B, m), params, p, prec).reshape(B, 1, m)

    x = _layer_norm(x, params["final_ln_gamma"], params["final_ln_beta"])
    logits = (jnp.dot(x.reshape(B, m), params["lm_head_weight"].T,
                      precision=prec) + params["lm_head_bias"])  # (B, V)
    next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # overflow contract: poison the overflowed lanes, loudly
    next_tokens = jnp.where(in_range, next_tokens, -1)
    logits = jnp.where(in_range[:, None], logits,
                       jnp.asarray(np.nan, logits.dtype))
    return next_tokens, logits, k_pages, v_pages


def extend(params, tokens, positions, block_tables, context_lens,
           k_pages, v_pages, cfg):
    """The speculative-decoding VERIFY step: :func:`decode` generalized to
    T tokens per stream, scored in ONE multi-query paged-attention pass.

    tokens:       (B, T) int32 — lane 0 is the stream's pending token,
                  lanes 1..T-1 the draft's proposals
    positions:    (B, T) int32 — each lane's write slot (consecutive:
                  context_len + lane for live rows)
    block_tables: (B, max_len // block_size) int32 — ONE table per stream
                  (the window's lanes share the stream's blocks)
    context_lens: (B, T) int32 — valid tokens PER LANE after this step's
                  writes (positions + 1 for live lanes) — per-lane
                  masking is what makes the window causal
    k/v_pages:    pool pages (donated)

    Returns ``(next_tokens (B, T), logits (B, T, V), k_pages, v_pages)``:
    lane t's output is the target model's greedy next token given the
    stream's context plus window lanes 0..t — exactly what :func:`decode`
    would have produced had the window been fed one token at a time, so
    greedy acceptance of matching draft proposals emits a token stream
    bit-identical to target-only decoding. Out-of-range lanes
    (position >= max_len) honor the overflow contract per lane: write
    routed to the trash block, token -1, logits NaN.
    """
    import jax.numpy as jnp

    B, T = tokens.shape
    m, hh = cfg.model_dim, cfg.num_heads
    hd = m // hh
    bs = k_pages.shape[2]
    prec = fp32_precision(k_pages.dtype)

    in_range = positions < cfg.max_len                          # (B, T)
    safe_pos = jnp.minimum(positions, cfg.max_len - 1)
    page_ids = jnp.take_along_axis(block_tables, safe_pos // bs, axis=1)
    page_ids = jnp.where(in_range, page_ids, 0)  # overflow -> trash block
    slots = jnp.where(in_range, safe_pos % bs, 0)

    pos_tab = params["pos_embed_weight"].reshape(cfg.max_len, m)
    x = (jnp.take(params["embed_weight"], tokens, axis=0)
         + jnp.take(pos_tab, safe_pos, axis=0))                 # (B, T, M)

    for i in range(cfg.num_layers):
        p = "layer%d" % i
        h = _layer_norm(x, params[p + "_ln1_gamma"], params[p + "_ln1_beta"])
        qkv = jnp.einsum("btm,nm->btn", h, params[p + "_attn_in_weight"],
                         precision=prec)
        q, k_new, v_new = jnp.split(qkv, 3, axis=-1)            # (B, T, M)
        q = q.reshape(B, T, hh, hd)
        k_new = k_new.reshape(B, T, hh, hd)
        v_new = v_new.reshape(B, T, hh, hd)
        # window lanes write their K/V first (distinct slots per lane;
        # overflow lanes pile into trash), then every lane reads back
        # under its OWN context length — lane t cannot see lanes > t
        k_pages = k_pages.at[i, page_ids, slots].set(
            k_new.astype(k_pages.dtype))
        v_pages = v_pages.at[i, page_ids, slots].set(
            v_new.astype(v_pages.dtype))
        attn = paged_attention_multi(q, k_pages[i], v_pages[i],
                                     block_tables, context_lens)
        attn = attn.reshape(B, T, m)
        attn = jnp.einsum("btm,nm->btn", attn,
                          params[p + "_attn_out_weight"], precision=prec)
        x = x + attn
        h = _layer_norm(x, params[p + "_ln2_gamma"], params[p + "_ln2_beta"])
        x = x + _ffn(h.reshape(B * T, m), params, p, prec).reshape(B, T, m)

    x = _layer_norm(x, params["final_ln_gamma"], params["final_ln_beta"])
    logits = (jnp.dot(x.reshape(B * T, m), params["lm_head_weight"].T,
                      precision=prec)
              + params["lm_head_bias"]).reshape(B, T, -1)       # (B, T, V)
    next_tokens = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    # overflow contract: poison the overflowed lanes, loudly
    next_tokens = jnp.where(in_range, next_tokens, -1)
    logits = jnp.where(in_range[:, :, None], logits,
                       jnp.asarray(np.nan, logits.dtype))
    return next_tokens, logits, k_pages, v_pages
