"""Learning-rate schedules.

API parity with the reference (python/mxnet/lr_scheduler.py: LRScheduler,
FactorScheduler :28, MultiFactorScheduler :66; PolyScheduler appears in its
examples) but computed in closed form: each call maps ``num_update`` directly
to a rate instead of replaying a mutable decay loop, so schedules are safe to
evaluate from the fused SPMD step's host hook (parallel/fused_opt.py
host_step_values), from checkpoint-resumed counters, and from out-of-order
probes alike. ``self.base_lr`` always mirrors the most recent value returned,
matching the reference's observable behavior (Optimizer assigns ``base_lr``
after construction, so the pristine rate is captured lazily).

CosineScheduler is an extension (no reference counterpart): the standard
warmup+cosine decay used by modern large-batch recipes.
"""
from __future__ import annotations

import logging
import math
from bisect import bisect_left

__all__ = [
    "LRScheduler", "FactorScheduler", "MultiFactorScheduler", "PolyScheduler",
    "CosineScheduler",
]


class LRScheduler:
    """Base: ``scheduler(num_update) -> lr``."""

    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr
        self._lr0 = None  # pristine rate, captured at first call

    def _origin(self):
        if self._lr0 is None:
            self._lr0 = self.base_lr
        return self._lr0

    def __call__(self, num_update):
        raise NotImplementedError("must override this")


class _DecayBySteps(LRScheduler):
    """Shared machinery: lr = pristine * factor^(number of boundaries passed),
    with an optional floor, logging once per newly-crossed boundary."""

    def __init__(self, factor, stop_factor_lr=0.0):
        super().__init__()
        if factor > 1.0:
            raise ValueError("factor must be <= 1 so the rate never grows")
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr
        self._seen_decays = 0

    def _num_decays(self, num_update):
        raise NotImplementedError

    def __call__(self, num_update):
        decays = self._num_decays(num_update)
        lr = self._origin() * self.factor ** decays
        floored = self.stop_factor_lr and lr < self.stop_factor_lr
        if floored:
            lr = self.stop_factor_lr
        if decays > self._seen_decays:
            self._seen_decays = decays
            if floored:
                logging.info(
                    "Update[%d]: learning rate floored at %0.5e; no further decay",
                    num_update, lr,
                )
            else:
                logging.info("Update[%d]: learning rate is now %0.5e", num_update, lr)
        self.base_lr = lr
        return lr


class FactorScheduler(_DecayBySteps):
    """Multiply by ``factor`` once per ``step`` updates (reference contract:
    lr_scheduler.py:28-63, including the strict ``>`` boundary)."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8):
        if step < 1:
            raise ValueError("step must be >= 1 update")
        super().__init__(factor, stop_factor_lr)
        self.step = step

    def _num_decays(self, num_update):
        return max(0, num_update - 1) // self.step


class MultiFactorScheduler(_DecayBySteps):
    """Multiply by ``factor`` when crossing each boundary in ``step``
    (reference contract: lr_scheduler.py:66-98)."""

    def __init__(self, step, factor=1):
        if not isinstance(step, list) or not step:
            raise ValueError("step must be a non-empty list of update counts")
        if any(s < 1 for s in step) or any(
            b <= a for a, b in zip(step, step[1:])
        ):
            raise ValueError("step must be a strictly increasing list of "
                             "updates >= 1")
        super().__init__(factor)
        self.step = step

    def _num_decays(self, num_update):
        # boundaries are passed once num_update EXCEEDS them (strict >)
        return bisect_left(self.step, num_update)


class PolyScheduler(LRScheduler):
    """Polynomial decay to zero across ``max_update`` updates."""

    def __init__(self, max_update, base_lr=0.01, pwr=2):
        super().__init__(base_lr)
        if max_update < 1:
            raise ValueError("max_update must be >= 1")
        self.max_update = max_update
        self.power = pwr

    def __call__(self, num_update):
        frac = min(num_update, self.max_update) / float(self.max_update)
        self.base_lr = self._origin() * (1.0 - frac) ** self.power
        return self.base_lr


class CosineScheduler(LRScheduler):
    """Linear warmup to the base rate, then cosine decay to ``final_lr``
    across ``max_update`` updates (extension; no reference counterpart)."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0.0, warmup_steps=0):
        super().__init__(base_lr)
        if max_update < 1:
            raise ValueError("max_update must be >= 1")
        if not 0 <= warmup_steps < max_update:
            raise ValueError("need 0 <= warmup_steps < max_update")
        self.max_update = max_update
        self.final_lr = final_lr
        self.warmup_steps = warmup_steps

    def __call__(self, num_update):
        peak = self._origin()
        if num_update < self.warmup_steps:
            lr = peak * (num_update + 1) / max(1, self.warmup_steps)
        elif num_update >= self.max_update:
            lr = self.final_lr
        else:
            span = self.max_update - self.warmup_steps
            done = (num_update - self.warmup_steps) / span
            lr = self.final_lr + 0.5 * (peak - self.final_lr) * (
                1 + math.cos(math.pi * done)
            )
        self.base_lr = lr
        return lr
